#!/usr/bin/env python
"""Sampling CLI for the causal LM families — restore a checkpoint, extend
prompts.

    python generate.py --model gpt2_small --checkpoint-dir /ckpts/run1 \
        --prompt-ids 464,3290,318 --max-new-tokens 32 --temperature 0.8

Prompts are raw token ids (comma-separated; `--prompt-ids` repeatable for a
batch) — tokenization is corpus-specific and lives with the data tooling
(tools/tokenize_corpus.py), not the sampler.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="gpt2_small")
    p.add_argument("--checkpoint-dir", required=True)
    p.add_argument("--prompt-ids", action="append", required=True,
                   help="comma-separated token ids; repeat for a batch "
                        "(rows must share a length)")
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--seq-len", type=int, default=None,
                   help="model context length (defaults to prompt+new)")
    p.add_argument("--vocab-size", type=int, default=None)
    p.add_argument("--backend", default="tpu", choices=["tpu", "cpu"])
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel ways (Megatron-style kernel "
                        "sharding over the model mesh axis) — serves a "
                        "model too big for one chip; composes with "
                        "sampling, beam search, and --use-cache (the KV "
                        "caches shard over heads)")
    p.add_argument("--num-beams", type=int, default=0,
                   help="beam-search decoding with this many beams "
                        "(deterministic; overrides temperature/top-k; "
                        "composes with --use-cache for O(S)/token beams)")
    p.add_argument("--length-penalty", type=float, default=1.0,
                   help="beam scores divide by length**alpha (>1 favors "
                        "longer hypotheses); only with --num-beams")
    p.add_argument("--eos-id", type=int, default=None,
                   help="end-of-sequence token id for beam search "
                        "(finished beams freeze and pad)")
    p.add_argument("--use-cache", action="store_true",
                   help="KV-cache incremental decoding (GPT and Llama "
                        "families): O(S) per token instead of full-refeed "
                        "O(S^2); output is identical at the same seed")
    p.add_argument("--draft-model", default=None,
                   help="speculative decoding: draft-model name (same "
                        "vocabulary); emits the EXACT target greedy "
                        "continuation with fewer target forwards. "
                        "Batch-1, greedy only")
    p.add_argument("--draft-checkpoint-dir", default=None,
                   help="checkpoint for --draft-model")
    p.add_argument("--draft-len", type=int, default=4,
                   help="draft tokens proposed per verify round")
    args = p.parse_args(argv)

    import os
    if args.backend == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"

    import contextlib

    import flax.linen as nn
    import jax

    from distributeddeeplearning_tpu.parallel import sharding as shardlib
    from distributeddeeplearning_tpu.parallel.mesh import use_mesh
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.models.generate import (
        generate, generate_beam)
    from distributeddeeplearning_tpu.train import checkpoint as ckptlib
    from distributeddeeplearning_tpu.train import loop

    prompts = [[int(t) for t in row.split(",")] for row in args.prompt_ids]
    if len({len(r) for r in prompts}) != 1:
        raise SystemExit("all --prompt-ids rows must share a length")
    total = len(prompts[0]) + args.max_new_tokens

    spec = model_spec(args.model)
    if spec.objective != "causal":
        raise SystemExit(f"{args.model!r} is not a causal LM")
    # The speculative path writes up to draft_len cache slots past `total`
    # before each rewind, so both models get that much position/cache slack.
    slack = args.draft_len if args.draft_model else 0
    data_kw = dict(synthetic=True, seq_len=(args.seq_len or total) + slack)
    if args.vocab_size:
        data_kw["vocab_size"] = args.vocab_size
    if args.tp < 1:
        raise SystemExit(f"--tp {args.tp}: need a positive ways count")
    cfg = TrainConfig(model=args.model, global_batch_size=len(prompts),
                      dtype="float32", checkpoint_dir=args.checkpoint_dir,
                      backend=args.backend, data=DataConfig(**data_kw),
                      parallel=ParallelConfig(model=args.tp))

    mesh, model, _, state, _, _, _ = loop.build(cfg, total_steps=1)
    ckpt = ckptlib.Checkpointer.create(cfg)
    try:
        # Params-only partial restore: the sampler must not need to know
        # which optimizer the training run used.
        params = ckpt.restore_latest_params(state.params)
    finally:
        ckpt.close()
    if ((args.use_cache or args.draft_model) and hasattr(model, "cfg")
            and hasattr(model.cfg, "decode_cache_len")):
        # Right-size the Llama KV cache to this request: a fixed default
        # buffer would make every decode step attend over unused slots.
        import dataclasses
        model = model.clone(cfg=dataclasses.replace(
            model.cfg, decode_cache_len=total + slack))
    if params is None:
        raise SystemExit(
            f"no checkpoint in {args.checkpoint_dir!r}; refusing to sample "
            "from randomly initialized weights")

    # Under TP the model's logical-axis constraints must resolve against
    # the mesh while the generation scan traces — same rules as training;
    # the restored params already carry their NamedShardings (loop.build +
    # the partial restore place them), so GSPMD propagates the kernel
    # sharding through every decode forward.
    ctx = contextlib.ExitStack()
    if args.tp > 1:
        ctx.enter_context(use_mesh(mesh))
        ctx.enter_context(nn.logical_axis_rules(
            list(shardlib.logical_rules(cfg.parallel))))
    draft = None
    if args.draft_model:
        if args.num_beams or args.temperature > 0 or args.tp > 1:
            raise SystemExit("--draft-model (speculative) is greedy, "
                             "single-stream, untensored; drop "
                             "--num-beams/--temperature/--tp")
        if args.use_cache:
            raise SystemExit("--draft-model decodes through KV caches "
                             "already; drop --use-cache")
        if args.draft_len < 1:
            raise SystemExit(f"--draft-len {args.draft_len}: need >= 1")
        if not args.draft_checkpoint_dir:
            raise SystemExit("--draft-model needs --draft-checkpoint-dir")
        dcfg = cfg.replace(model=args.draft_model,
                           checkpoint_dir=args.draft_checkpoint_dir)
        _, draft_model, _, dstate, _, _, _ = loop.build(dcfg, total_steps=1)
        if hasattr(draft_model, "cfg") and hasattr(draft_model.cfg,
                                                   "decode_cache_len"):
            import dataclasses
            draft_model = draft_model.clone(cfg=dataclasses.replace(
                draft_model.cfg, decode_cache_len=total + slack))
        dckpt = ckptlib.Checkpointer.create(dcfg)
        try:
            draft_params = dckpt.restore_latest_params(dstate.params)
        finally:
            dckpt.close()
        if draft_params is None:
            raise SystemExit(
                f"no draft checkpoint in {args.draft_checkpoint_dir!r}")
        draft = (draft_model, draft_params)

    with ctx:
        if draft is not None:
            from distributeddeeplearning_tpu.models.generate import (
                generate_speculative)
            draft_model, draft_params = draft
            out = generate_speculative(
                model, {"params": params}, draft_model,
                {"params": draft_params}, prompts,
                max_new_tokens=args.max_new_tokens,
                draft_len=args.draft_len)
        elif args.num_beams > 0:
            out = generate_beam(model, {"params": params}, prompts,
                                max_new_tokens=args.max_new_tokens,
                                num_beams=args.num_beams,
                                length_penalty=args.length_penalty,
                                eos_id=args.eos_id,
                                use_cache=args.use_cache)
        else:
            out = generate(model, {"params": params}, prompts,
                           max_new_tokens=args.max_new_tokens,
                           temperature=args.temperature, top_k=args.top_k,
                           rng=jax.random.key(args.seed),
                           use_cache=args.use_cache)
    for row in jax.device_get(out).tolist():
        print(json.dumps({"tokens": row}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
