#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line with the metric of record.

Metric (BASELINE.json:2): ResNet50/ImageNet images/sec/chip, measured on the
headline single-chip synthetic config (config 1 scaled to a throughput-class
batch), bfloat16, after compile/warmup exclusion — the same protocol the
reference's harness used for its images/sec tables (SURVEY.md §3.4).

``vs_baseline``: BASELINE.json captured no published reference numbers
("published": {}), so the denominator is the north-star target expressed
per-chip: 8xV100 ResNet50 ImageNet aggregate on a v5e-8, i.e. one V100's
mixed-precision throughput per chip. We pin that at 1450 images/sec/chip
(NVIDIA's commonly-published V100 ResNet50 AMP figure); vs_baseline > 1.0
means beating the target.
"""

from __future__ import annotations

import argparse
import json
import sys

V100_AMP_RESNET50_IMAGES_PER_SEC = 1450.0


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup-steps", type=int, default=10)
    args = p.parse_args(argv)

    import jax

    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    n_dev = jax.device_count()
    cfg = TrainConfig(
        model=args.model,
        global_batch_size=args.batch_size * n_dev,
        dtype="bfloat16",
        log_every=10**9,  # silent; bench prints exactly one line
        parallel=ParallelConfig(data=n_dev),
        data=DataConfig(synthetic=True))

    summary = loop.run(
        cfg, total_steps=args.warmup_steps + args.steps,
        warmup_steps=args.warmup_steps,
        logger=MetricLogger(enabled=False))

    value = summary["examples_per_sec_per_chip"]
    print(json.dumps({
        "metric": f"{args.model}_imagenet_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / V100_AMP_RESNET50_IMAGES_PER_SEC, 4),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
