#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line with the metric of record.

Metric (BASELINE.json:2): ResNet50/ImageNet images/sec/chip, measured on the
headline single-chip synthetic config (config 1 scaled to a throughput-class
batch), bfloat16, after compile/warmup exclusion — the same protocol the
reference's harness used for its images/sec tables (SURVEY.md §3.4).

``vs_baseline``: BASELINE.json captured no published reference numbers
("published": {}), so the denominator is the north-star target expressed
per-chip: 8xV100 ResNet50 ImageNet aggregate on a v5e-8, i.e. one V100's
mixed-precision throughput per chip. We pin that at 1450 images/sec/chip
(NVIDIA's commonly-published V100 ResNet50 AMP figure); vs_baseline > 1.0
means beating the target.

Resilience contract (VERDICT.md round 1, Missing #1): backend init against
the remote TPU can hang or raise transient ``UNAVAILABLE``.  The measurement
therefore runs in a *child* process under a hard per-attempt timeout, with
bounded retries + backoff in the parent; whatever happens, the parent prints
exactly one parseable JSON line (a numeric record on success, an ``error``
record otherwise) and exits 0.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

V100_AMP_RESNET50_IMAGES_PER_SEC = 1450.0
RETRY_BACKOFF_SEC = (10, 30)  # sleeps between the 3 attempts


def _child(args) -> int:
    """Run the actual measurement; prints the one JSON metric line."""
    import jax

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        jax.config.update("jax_platforms", args.platform)

    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    n_dev = jax.device_count()
    cfg = TrainConfig(
        model=args.model,
        global_batch_size=args.batch_size * n_dev,
        dtype="bfloat16",
        log_every=10**9,  # silent; bench prints exactly one line
        parallel=ParallelConfig(data=n_dev),
        data=DataConfig(synthetic=True))

    summary = loop.run(
        cfg, total_steps=args.warmup_steps + args.steps,
        warmup_steps=args.warmup_steps,
        logger=MetricLogger(enabled=False))

    value = summary["examples_per_sec_per_chip"]
    print(json.dumps({
        "metric": f"{args.model}_imagenet_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / V100_AMP_RESNET50_IMAGES_PER_SEC, 4),
    }), flush=True)
    return 0


def _emit_error(args, msg: str) -> None:
    print(json.dumps({
        "metric": f"{args.model}_imagenet_images_per_sec_per_chip",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "error": msg[-800:],
    }), flush=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    # 512/chip is the measured v5e sweet spot: 2325 img/s/chip vs 1341 at
    # 256 and 1978 at 1024 (2026-07-29 sweep on the tunneled chip) — large
    # enough to amortize per-step dispatch latency, small enough to stay
    # HBM-friendly.
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup-steps", type=int, default=10)
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. cpu) for smoke runs")
    p.add_argument("--attempt-timeout", type=int, default=600,
                   help="hard wall-clock limit per measurement attempt (s)")
    p.add_argument("--attempts", type=int, default=3)
    p.add_argument("--budget", type=int, default=1200,
                   help="total wall-clock budget across all attempts (s); "
                        "guarantees the error record is printed before any "
                        "outer driver timeout can strike")
    p.add_argument("--run-child", action="store_true", help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.run_child:
        return _child(args)

    child_cmd = [sys.executable, os.path.abspath(__file__), "--run-child",
                 "--model", args.model,
                 "--batch-size", str(args.batch_size),
                 "--steps", str(args.steps),
                 "--warmup-steps", str(args.warmup_steps)]
    if args.platform:
        child_cmd += ["--platform", args.platform]

    last_err = "no attempt ran"
    deadline = time.monotonic() + args.budget
    for attempt in range(args.attempts):
        if attempt:
            time.sleep(RETRY_BACKOFF_SEC[min(attempt - 1,
                                             len(RETRY_BACKOFF_SEC) - 1)])
        remaining = deadline - time.monotonic()
        if remaining < 30:
            last_err += "; budget exhausted"
            break
        try:
            proc = subprocess.run(
                child_cmd, capture_output=True, text=True,
                timeout=min(args.attempt_timeout, remaining))
            stdout, stderr, rc = proc.stdout, proc.stderr, proc.returncode
        except subprocess.TimeoutExpired as e:
            # The child may have printed its metric line and then hung in
            # backend teardown (the classic remote-TPU failure mode) — scan
            # the captured-so-far stdout before declaring the attempt dead.
            stdout = e.stdout or b""
            if isinstance(stdout, bytes):
                stdout = stdout.decode(errors="replace")
            stderr, rc = "", f"timeout {min(args.attempt_timeout, int(remaining))}s"
        # Find the metric line: last stdout line that parses as JSON.
        for line in reversed(stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    json.loads(line)
                except ValueError:
                    continue
                print(line, flush=True)
                return 0
        tail = (stderr or stdout or "").strip()
        last_err = f"attempt {attempt + 1}: rc={rc}: {tail[-600:]}"

    _emit_error(args, last_err)
    return 0


if __name__ == "__main__":
    sys.exit(main())
