#!/usr/bin/env python
"""Benchmark harness — prints JSON metric lines; the LAST line is the result.

Metric of record (BASELINE.json:2): ResNet50/ImageNet images/sec/chip,
measured on the headline single-chip synthetic config (config 1 scaled to a
throughput-class batch), bfloat16, compile/warmup excluded — the protocol the
reference's harness used for its images/sec tables (SURVEY.md §3.4).

``vs_baseline``: BASELINE.json captured no published reference numbers
("published": {}), so the denominator is the north-star target expressed
per-chip: 8xV100 ResNet50 ImageNet aggregate on a v5e-8, i.e. one V100's
mixed-precision throughput per chip. We pin that at 1450 images/sec/chip
(NVIDIA's commonly-published V100 ResNet50 AMP figure — a literature
stand-in, NOT a measured reference value; every metric line says so in its
``baseline_denominator`` field). vs_baseline > 1.0 means beating the target.

Resilience contract (VERDICT r1 Missing #1, r2 Next #1): backend init against
the remote TPU can hang, raise transient ``UNAVAILABLE``, or die mid-run.
Three defenses, so a number lands inside ONE driver attempt window:

1. **Progressive emission.** The child compiles ONCE, then emits a valid
   metric line after a short quick window (3 warmup + 8 timed steps — seconds
   after compile) and a refined line after the full-protocol window (the 11
   steps already run count as warmup ≥ the classic 10, then 30 timed steps).
   Last parseable line wins, so the refined number supersedes the quick one
   when there is time for it.
2. **Streaming relay.** The parent relays each child metric line to stdout
   the moment it appears — an outer kill cannot erase a number that was
   already printed. If the child hangs after the quick line, that line
   stands and the harness still exits 0 with a real value.
3. **Persistent XLA compilation cache** (JAX_COMPILATION_CACHE_DIR): a retry
   after a mid-compile hang skips straight past compilation.

Whatever happens, the parent prints at least one parseable JSON line (an
``error`` record if no measurement succeeded) and exits 0.

``--suite`` measures every acceptance config (BASELINE.json:6-12) plus the
beyond-scope families in one child process (backend init amortized), one
metric line per config — used to (re)populate BASELINE.md's measured tables.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import subprocess
import sys
import threading
import time

V100_AMP_RESNET50_IMAGES_PER_SEC = 1450.0
BASELINE_DENOMINATOR_NOTE = (
    "V100 AMP ResNet50 1450 img/s — literature stand-in per chip for the "
    "8xV100-on-v5e-8 north star; BASELINE.json published={}")
RETRY_BACKOFF_SEC = (5, 15)  # sleeps between attempts
# Child->parent heartbeat marker: the parent's preflight deadline disarms on
# this substring, so the child's backend-up note and the parent's matcher
# must never drift apart.
BACKEND_UP_HEARTBEAT = "backend up:"
def _compile_cache_dir(explicit=None):
    """Shared persistent-cache resolution (perf/compile_cache.py): flag >
    $DDL_COMPILE_CACHE > repo-local default; None = disabled. Guarded
    import: the bench parent must keep running (and relaying child errors)
    even when the package itself is broken."""
    try:
        from distributeddeeplearning_tpu.perf import compile_cache
        return compile_cache.resolve_dir(explicit)
    except Exception:
        return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".cache", "jax_compile")

# --suite rows: (name, model, overrides, est_s) in VALUE-PER-MINUTE order —
# a window that dies mid-suite yields the most valuable prefix (VERDICT r4
# Weak #5). Rows are SELECTED BY NAME (--suite-rows, tools/chip_window.sh):
# names are stable under reorders/insertions, unlike the former positional
# indices where adding a row silently shifted which configs each window
# step measured (ADVICE r5). est_s is the expected on-chip wall cost of the
# row (compile with warm persistent cache + measure; round-2/3 sessions
# measured ~30-60s compile + ~60s measure per row) and gates row admission
# against the remaining --suite-budget; it is NOT a hard per-row kill (the
# row deadline handles that). Batch sizes are the measured sweet spots from
# BASELINE.md's round-2 sweeps; S=2048 rows need flash+remat to fit.
SUITE = (
    # Headline family first: its compile cache is warm from the headline
    # run, and the acceptance metric of record is this row.
    ("resnet50", "resnet50", {}, 90),
    # Fused-vs-per-leaf gradient all-reduce A/B (parallel/collectives.py):
    # same model/batch as the headline (warm cache), differing ONLY in the
    # reduction schedule — ar_fused buckets leaves at the default 4 MB,
    # ar_perleaf (bucket_mb=0) reduces leaf-by-leaf, the pre-fusion
    # behavior. Never measured on chip — the tensor-fusion win this PR
    # exists to quantify.
    ("ar_fused", "resnet50", {"allreduce_bucket_mb": 4.0}, 90),
    ("ar_perleaf", "resnet50", {"allreduce_bucket_mb": 0.0}, 90),
    # ZeRO-1 optimizer sharding vs the fused all-reduce it replaces
    # (parallel/zero.py): reduce-scatter + shard-local update + param
    # all-gather, same wire volume, opt state 1/N per chip. Paired with
    # ar_fused (same model/batch/bucket) so the throughput delta isolates
    # the schedule change; the memory win shows in the per-device
    # opt_state bytes every record now carries. Never measured on chip.
    ("zero1", "resnet50", {"allreduce_bucket_mb": 4.0,
                           "optimizer_sharding": "zero1"}, 90),
    # ZeRO-2/3 complete the ladder (same pairing discipline as zero1):
    # zero2 keeps grads reduce-scattered per bucket (never materializing
    # the full grad tree), zero3 stores params 1/N-chunked and all-gathers
    # them per bucket on demand — both with the backward/collective
    # overlapped schedule on by default. Never measured on chip.
    ("zero2", "resnet50", {"allreduce_bucket_mb": 4.0,
                           "optimizer_sharding": "zero2"}, 90),
    ("zero3", "resnet50", {"allreduce_bucket_mb": 4.0,
                           "optimizer_sharding": "zero3"}, 90),
    # Never measured on chip under the gather-head protocol (r2 protocol
    # change) — the two highest-value unknown rows.
    ("bert512_flash", "bert_base", {"batch_size": 32, "seq_len": 512,
                                    "attention_impl": "flash"}, 120),
    ("gpt2_1024", "gpt2_small", {"batch_size": 16, "seq_len": 1024}, 120),
    ("bert512", "bert_base", {"batch_size": 32, "seq_len": 512}, 120),
    ("resnet152", "resnet152", {"batch_size": 256}, 120),
    ("densenet121", "densenet121", {"batch_size": 256}, 120),
    ("vit_b16", "vit_b16", {"batch_size": 256}, 120),
    # Long-context last: largest compile, slowest steps, and its CPU-side
    # evidence (flash==dense parity) is the strongest of the set.
    ("bert2048_flash", "bert_base", {"batch_size": 32, "seq_len": 2048,
                                     "attention_impl": "flash",
                                     "remat": True}, 180),
    # Large-batch %-of-peak A/B (ISSUE 20), reached by NAME via the gated
    # DDL_LARGEBATCH=1 chip-window step: identical model at 2x the headline
    # per-chip batch, differing ONLY in precision policy. The fp32 arm
    # scores against the fp32 roof and the mixed arm (bf16 compute + fp32
    # masters + dynamic loss scaling) against the bf16 roof, so the pair
    # reads as distance-from-own-speed-of-light (arXiv 1711.04325); each
    # emits under its own _<precision> metric name.
    ("largebatch_fp32", "resnet50", {"batch_size": 1024,
                                     "precision": "fp32"}, 120),
    ("largebatch_bf16", "resnet50", {"batch_size": 1024,
                                     "precision": "mixed"}, 120),
    # Pipeline-schedule A/B (models/pipeline.py), after the value-per-minute
    # prefix — chip windows reach these by NAME via the gated DDL_PIPELINE=1
    # pipeline_ab step, never by budget order. Fill/drain GPipe vs
    # interleaved 1f1b at IDENTICAL geometry — same model, batch, seq_len,
    # stages (pp=2) and microbatches (M=4, registry); the ONLY delta is the
    # schedule (1f1b adds V=2 virtual chunks per stage). Each record
    # carries the measured pipeline_bubble_fraction from the trace-time
    # tick instants next to the analytic (P-1)/(M*V+P-1), so the pair IS
    # the bubble-kill verdict: 1f1b's measured bubble must land strictly
    # below gpipe's and within 1.5x its analytic value (docs/pipeline.md).
    ("pp_gpipe", "bert_tiny_pp4", {"batch_size": 4, "seq_len": 128,
                                   "pp": 2, "pipeline_schedule": "gpipe",
                                   "pipeline_virtual_stages": 1}, 90),
    ("pp_1f1b", "bert_tiny_pp4", {"batch_size": 4, "seq_len": 128,
                                  "pp": 2, "pipeline_schedule": "1f1b",
                                  "pipeline_virtual_stages": 2}, 90),
)


def _metric_name_unit(args) -> tuple[str, str]:
    """One source of truth for the metric identity, shared by the success
    and error paths (parent + child processes). Consults the model registry
    for the input kind; registry import touches no device backend."""
    objective = None
    try:
        from distributeddeeplearning_tpu.models import model_spec
        spec = model_spec(args.model)
        if spec.input_kind == "tokens":
            objective = spec.objective
    except Exception:
        name = args.model  # best effort when the registry import fails
        if "bert" in name:
            objective = "mlm"
        elif "gpt" in name or "llama" in name:
            objective = "causal"
    if objective:
        # The head mode is part of the measurement protocol: gN = gather
        # head over N positions (canonical BERT), no suffix = dense logits.
        # Keeps gather-mode rows from being compared against the dense-head
        # numbers recorded under the unsuffixed name.
        from distributeddeeplearning_tpu.config import (
            resolve_mlm_max_predictions)
        mp = resolve_mlm_max_predictions(
            args.mlm_max_predictions, args.seq_len, objective)
    # Per-leaf gradient all-reduce (bucket_mb=0) is the fusion A/B's
    # reference schedule, NOT the production path: give it its own metric
    # name so its (expected-slower) number can never evict the headline's
    # last-good entry under the same key.
    perleaf = ("_perleaf_ar"
               if getattr(args, "allreduce_bucket_mb", None) == 0 else "")
    # ZeRO rows likewise get their own metric name per stage: each sharded
    # schedule is a different measurement protocol and its number must not
    # evict the replicated headline's last-good entry.
    stage = getattr(args, "optimizer_sharding", None)
    if stage and stage != "none":
        perleaf += f"_{stage}"
    # Pipeline rows likewise: each (stages, schedule, virtual-stage) tuple
    # is its own measurement protocol — the gpipe and 1f1b A/B rows must
    # never evict each other's (or the non-pipelined model's) last-good
    # entries under a shared key.
    pp = getattr(args, "pp", 1) or 1
    if pp > 1:
        sched = getattr(args, "pipeline_schedule", "gpipe") or "gpipe"
        vv = getattr(args, "pipeline_virtual_stages", 1) or 1
        perleaf += f"_pp{pp}_{sched}" + (f"v{vv}" if vv > 1 else "")
    # Precision-policy A/B rows (ISSUE 20): the fp32 reference arm and the
    # mixed (bf16 compute + fp32 masters + dynamic loss scaling) arm are
    # different measurement protocols scoring against different rooflines —
    # each gets its own metric name so neither can evict the other's (or
    # the default row's) last-good entry.
    prec = getattr(args, "precision", None)
    if prec:
        perleaf += f"_{prec}"
    # Tracing adds per-step clock reads inside the timed window — protocol
    # drift by design (it's how the overhead A/B measures itself), so traced
    # numbers live under their own metric name and can never evict an
    # untraced last-good entry.
    if getattr(args, "trace_dir", None):
        perleaf += "_tele"
    if objective:
        gather = f"_g{mp}" if mp > 0 else ""
        return (f"{args.model}{perleaf}_{objective}_s{args.seq_len}{gather}"
                f"_seqs_per_sec_per_chip", "sequences/sec/chip")
    return (f"{args.model}{perleaf}_imagenet_images_per_sec_per_chip",
            "images/sec/chip")


def _protocol_suffix(args) -> str:
    """Measurement-protocol qualifiers that are not part of the metric name
    (attention kernel, remat) — without them the dense and flash suite rows
    would be indistinguishable."""
    parts = []
    if args.attention_impl:
        parts.append(args.attention_impl)
    if args.remat:
        parts.append("remat")
    if getattr(args, "fused_bn", False):
        parts.append("fusedbn")
    if getattr(args, "fused_block", False):
        parts.append("fusedblock")
    if getattr(args, "fused_conv3", False):
        parts.append("fusedconv3")
    ar_mb = getattr(args, "allreduce_bucket_mb", None)
    if ar_mb is not None:
        # Reduction schedule is protocol: default (no flag) is the fused
        # path at AllReduceConfig's default bucket size; an explicit value
        # is marked so the A/B rows stay distinguishable in the record.
        parts.append("perleaf-ar" if ar_mb == 0 else f"ar{ar_mb:g}mb")
    if getattr(args, "allreduce_dtype", None) == "bfloat16":
        parts.append("ar-bf16")
    stage = getattr(args, "optimizer_sharding", None)
    if stage and stage != "none":
        parts.append(stage)
        if stage in ("zero2", "zero3") and \
                getattr(args, "overlap_collectives", True) is False:
            parts.append("no-overlap")
    if getattr(args, "opt_state_offload", False):
        parts.append("opt-offload")
    prec = getattr(args, "precision", None)
    if prec:
        # Spell the policy out (compute/param/reduce + loss scale) so the
        # record says WHAT "mixed" meant when it was measured, not just
        # that it was.
        try:
            from distributeddeeplearning_tpu.config import PrecisionPolicy
            pol = (PrecisionPolicy.mixed() if prec == "mixed"
                   else PrecisionPolicy.fp32())
            parts.append(pol.describe())
        except Exception:
            parts.append(f"prec-{prec}")
    elif getattr(args, "dtype", None):
        parts.append(args.dtype)
    pp = getattr(args, "pp", 1) or 1
    if pp > 1:
        parts.append(f"pp{pp}-{getattr(args, 'pipeline_schedule', 'gpipe')}"
                     f"-v{getattr(args, 'pipeline_virtual_stages', 1) or 1}")
    if getattr(args, "trace_dir", None):
        parts.append("tele")
    return (" " + "+".join(parts)) if parts else ""


def _mfu_fields(args, value: float) -> dict:
    """tflops_per_sec + mfu_pct for a rate of ``value`` examples/sec/chip
    (VERDICT r4 Next #5). Model FLOPs are the analytic fwd+bwd enumeration
    (models/flops.py, 2-flops-per-MAC convention, validated against XLA
    cost analysis by tests/test_flops.py); the peak is the detected chip's
    bf16 spec number. Never raises — an unknown model or backend simply
    omits the fields, because a missing efficiency annotation must not
    cost a throughput measurement."""
    try:
        from distributeddeeplearning_tpu.config import (
            resolve_mlm_max_predictions)
        from distributeddeeplearning_tpu.models import flops as flopslib
        from distributeddeeplearning_tpu.models import model_spec
        spec = model_spec(args.model)
        mlm_pred = (resolve_mlm_max_predictions(
            args.mlm_max_predictions, args.seq_len, spec.objective)
            if spec.input_kind == "tokens" else 0)
        per_ex = flopslib.train_flops_per_example(
            args.model, seq_len=args.seq_len, mlm_positions=mlm_pred)
        if per_ex is None:
            return {}
        out = {"tflops_per_sec": round(value * per_ex / 1e12, 2)}
        import jax
        # %-of-peak scores against the roof of the arm's OWN compute dtype
        # (models/flops.py peak tables): the fp32 reference arm vs the
        # fp32 roof, the mixed/bf16 arm vs the bf16 roof — each measures
        # distance from its own speed of light (arXiv 1711.04325 axis).
        prec = getattr(args, "precision", None)
        compute = ("float32"
                   if prec == "fp32" or (prec is None and
                                         getattr(args, "dtype", None)
                                         == "float32")
                   else "bfloat16")
        peak = flopslib.peak_flops(jax.devices()[0].device_kind, compute)
        if peak:
            out["mfu_pct"] = round(100.0 * value * per_ex / peak, 1)
            out["peak_dtype"] = compute
        return out
    except Exception:
        return {}


def _emit_metric(args, value: float, protocol: str,
                 extra: dict | None = None) -> None:
    metric, unit = _metric_name_unit(args)
    # The 1450 img/s denominator is specifically the V100 ResNet50 AMP
    # figure — comparing any other model against it would be meaningless,
    # so vs_baseline is emitted only for the metric of record.
    rec = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": (round(value / V100_AMP_RESNET50_IMAGES_PER_SEC, 4)
                        if args.model == "resnet50" else None),
        "protocol": protocol + _protocol_suffix(args),
        "baseline_denominator": BASELINE_DENOMINATOR_NOTE,
    }
    rec.update(_mfu_fields(args, value))
    # Roofline %-of-peak is ALWAYS present (null when model FLOPs or the
    # chip's spec peak are unknown): the suite table's comparability
    # column must exist on every row, not only the lucky ones
    # (docs/perf_measurement.md; large-batch baselines of arXiv
    # 1711.04325 compare on this axis).
    rec["pct_of_peak"] = rec.get("mfu_pct")
    # Structured kernel-config marker (ADVICE r4 bench.py:303): consumers
    # of the last-good table can filter fused-kernel records without
    # parsing the protocol string.
    if getattr(args, "fused_block", False):
        rec["fused_block"] = True
    if getattr(args, "fused_conv3", False):
        rec["fused_conv3"] = True
    if extra:
        rec.update(extra)
    # This line is a live measurement by THIS process — the only path
    # allowed to claim ``fresh`` (cached numbers re-enter only through
    # _emit_error as stale/expired). Runs in the child, so the backend
    # identity block reflects the devices that actually answered.
    from distributeddeeplearning_tpu.observability import perf_report
    perf_report.annotate(rec, provenance="fresh")
    rec["attempt"] = int(os.environ.get("DDL_BENCH_ATTEMPT", "1") or 1)
    print(json.dumps(rec), flush=True)


def _note(msg: str) -> None:
    """Child heartbeat on stderr: reaches error records, never stdout."""
    print(f"# bench: {msg}", file=sys.stderr, flush=True)


def _child_measure(args, emit_quick: bool = True,
                   emit_final: bool = True,
                   deadline: float | None = None) -> float:
    """One config: compile once, emit quick then full-protocol lines;
    returns the full-protocol rate.

    ``emit_quick=False`` (suite mode) keeps the quick window as pure warmup
    so each config contributes exactly one metric line. ``emit_final=False``
    (batch-sweep alternates) measures without printing — the caller emits
    only if the alternate beats the primary, because the driver takes the
    LAST line and a slower alternate must never shadow a faster primary.

    ``deadline`` (time.monotonic value) is the row's wall budget: the
    timed loops stop early when it passes and the rate is computed over
    the steps actually completed (protocol records the cut), so a suite
    row that runs long yields a shorter valid measurement instead of
    eating the rows behind it. Compile+warmup is never interrupted — by
    the time the deadline can fire the expensive part is already paid. If
    the deadline passes before ANY timed step completes, TimeoutError."""
    import jax

    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.config import (
        AllReduceConfig, DataConfig, ParallelConfig, TrainConfig,
        resolve_mlm_max_predictions)
    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.observability import telemetry
    from distributeddeeplearning_tpu.train import loop

    # Configure telemetry BEFORE build: the per-bucket collective spans are
    # recorded at trace time, i.e. during the first train_step compile.
    tele = None
    if getattr(args, "trace_dir", None):
        tele = telemetry.configure(trace_dir=args.trace_dir,
                                   process_index=jax.process_index(),
                                   process_name="bench")

    # Pipeline rows: the measured bubble comes from the trace-time
    # pipeline_tick instants (models/pipeline.py), so a buffer-only
    # registry captures them without adding clock reads to the timed
    # windows — the metric keeps its untraced protocol (no _tele drift).
    # A --trace-dir run reuses its own registry instead.
    pp = getattr(args, "pp", 1) or 1
    pipe_tele = tele
    if pp > 1 and pipe_tele is None:
        pipe_tele = telemetry.configure(enabled=True,
                                        process_index=jax.process_index(),
                                        process_name="bench")

    n_dev = jax.device_count()
    spec = model_spec(args.model)
    tokens = spec.input_kind == "tokens"
    mlm_pred = resolve_mlm_max_predictions(
        args.mlm_max_predictions, args.seq_len, spec.objective)
    data = (DataConfig(synthetic=True, dataset="mlm", seq_len=args.seq_len,
                       mlm_max_predictions=mlm_pred)
            if tokens else DataConfig(synthetic=True))
    ar_kw = {}
    if getattr(args, "allreduce_bucket_mb", None) is not None:
        ar_kw["bucket_mb"] = args.allreduce_bucket_mb
    if getattr(args, "allreduce_dtype", None):
        ar_kw["dtype"] = args.allreduce_dtype
    # Precision-policy A/B arms (ISSUE 20): --precision selects an explicit
    # policy (the compute dtype follows the policy); bare --dtype covers
    # legacy-knob runs. Default stays the bf16 protocol of record.
    prec_kw = {}
    dtype = getattr(args, "dtype", None) or "bfloat16"
    prec = getattr(args, "precision", None)
    if prec:
        from distributeddeeplearning_tpu.config import PrecisionPolicy
        pol = (PrecisionPolicy.mixed() if prec == "mixed"
               else PrecisionPolicy.fp32())
        prec_kw["precision"] = pol
        dtype = pol.compute_dtype
    cfg = TrainConfig(
        model=args.model,
        global_batch_size=args.batch_size * n_dev,
        dtype=dtype,
        **prec_kw,
        log_every=10**9,  # silent; bench prints only metric lines on stdout
        attention_impl=args.attention_impl,
        remat=args.remat,
        fused_bn=args.fused_bn,
        fused_block=args.fused_block,
        fused_conv3=getattr(args, "fused_conv3", False),
        parallel=ParallelConfig(data=max(1, n_dev // pp), pipeline=pp),
        data=data,
        allreduce=AllReduceConfig(**ar_kw),
        optimizer_sharding=(getattr(args, "optimizer_sharding", None)
                            or "none"),
        overlap_collectives=getattr(args, "overlap_collectives", True),
        opt_state_offload=getattr(args, "opt_state_offload", False),
        pipeline_schedule=(getattr(args, "pipeline_schedule", None)
                           or "gpipe"),
        pipeline_virtual_stages=(getattr(args, "pipeline_virtual_stages", 1)
                                 or 1))
    if pp > 1 and n_dev % pp:
        raise ValueError(f"pipeline stages {pp} must divide the device "
                         f"count {n_dev}")

    quick_w = (args.warmup_steps if args.warmup_steps is not None
               else args.quick_warmup)
    quick_n = args.quick_steps
    total = quick_w + quick_n + args.steps
    _note(f"building {args.model} batch={cfg.global_batch_size} on "
          f"{n_dev} device(s)")
    t_row0 = time.perf_counter()
    mesh, model, batch_shd, state, train_step, sched, rng = loop.build(
        cfg, total)
    source = datalib.make_source(cfg, spec.input_kind, batch_shd,
                                 objective=spec.objective)
    t_compile = time.perf_counter()
    i = 0
    metrics = None
    compile_time_s = time_to_first_step_s = None
    for _ in range(quick_w):
        t_step0 = time.perf_counter() if i == 0 else None
        state, metrics = train_step(state, source.batch(i), rng)
        if t_step0 is not None:
            # First dispatch blocks the host for trace+compile (or the AOT
            # load); the fetch barrier closes the cold-start window.
            compile_time_s = time.perf_counter() - t_step0
            jax.device_get(metrics)
            time_to_first_step_s = time.perf_counter() - t_row0
        i += 1
    # device_get, not block_until_ready: a fetch is a true execution barrier
    # on every backend (remote-tunneled devices can report buffers "ready"
    # while programs are still in flight).
    jax.device_get(metrics)
    _note(f"compile+warmup({quick_w}) done in "
          f"{time.perf_counter() - t_compile:.1f}s; quick window starts")
    # Per-device memory annotation for every metric line this row emits:
    # peak HBM where the allocator reports it, plus params/grads/opt-state
    # resident bytes (shard-aware) and their sum — the numbers the ZeRO
    # ladder rows compare (replicated -> zero1 -> zero2 -> zero3 must fall
    # monotonically).
    mem = {}
    try:
        stats = loop._device_memory_stats(state, train_step)
        for key in ("peak_bytes_in_use", "bytes_in_use",
                    "params_bytes_per_device", "grads_bytes_per_device",
                    "opt_state_bytes_per_device",
                    "ema_params_bytes_per_device",
                    "resident_bytes_per_device"):
            if key in stats:
                mem[key] = int(stats[key])
    except Exception:
        pass  # annotation only — never costs a measurement
    # Pipeline A/B annotation: measured bubble (idle / total stage-ticks
    # over the trace-time tick instants; null on an AOT cache hit that
    # skipped tracing) next to the schedule table's analytic value — the
    # pair the gpipe-vs-1f1b acceptance check reads (docs/pipeline.md).
    pipe = {}
    if pp > 1 and pipe_tele is not None:
        bub = telemetry.pipeline_bubble_fraction(pipe_tele.snapshot())
        pipe_rec = {"stages": pp, "schedule": cfg.pipeline_schedule,
                    "virtual_stages": cfg.pipeline_virtual_stages,
                    "bubble_fraction": None if bub is None
                    else round(bub, 4)}
        try:
            from distributeddeeplearning_tpu.models import pipeline as plib
            ticks = [e for e in pipe_tele.snapshot()
                     if e.get("name") == "pipeline_tick"]
            mm = int(ticks[0]["args"]["microbatches"]) if ticks else 0
            if mm:
                pipe_rec["microbatches"] = mm
                pipe_rec["analytic_bubble_fraction"] = round(
                    plib.build_schedule(
                        cfg.pipeline_schedule, num_stages=pp,
                        num_microbatches=mm,
                        virtual_stages=cfg.pipeline_virtual_stages,
                    ).analytic_bubble_fraction(), 4)
        except Exception:
            pass  # annotation only
        pipe["pipeline"] = pipe_rec
    def timed_window(n_steps: int):
        """Dispatch up to n_steps; returns (steps_done, elapsed).

        Without a deadline: one device_get barrier at the end (steps
        pipeline freely — the round-2/3 protocol). With a deadline: steps
        are dispatched in chunks of 5 with a barrier + clock check between
        chunks — async dispatch would otherwise queue the whole window in
        milliseconds and make the deadline unenforceable. The extra
        barriers cost one tunnel round-trip per chunk (amortized over 5
        steps), the price of a row that can be cut on budget."""
        nonlocal state, metrics, i
        t0 = time.perf_counter()
        done = 0
        chunk = n_steps if deadline is None else 5
        while done < n_steps:
            for _ in range(min(chunk, n_steps - done)):
                if tele is None:
                    state, metrics = train_step(state, source.batch(i), rng)
                else:
                    # Traced protocol (metric name carries _tele): two extra
                    # monotonic reads per step split data_wait from dispatch.
                    ta = telemetry.now_s()
                    batch = source.batch(i)
                    tb = telemetry.now_s()
                    state, metrics = train_step(state, batch, rng)
                    tc = telemetry.now_s()
                    tele.record_span("data_wait", ta, tb, step=i)
                    tele.record_span("dispatch", tb, tc, step=i)
                i += 1
                done += 1
            if tele is None:
                jax.device_get(metrics)
            else:
                with tele.span("fetch_barrier", step=i - 1):
                    jax.device_get(metrics)
            if deadline is not None and time.monotonic() >= deadline:
                break
        return done, time.perf_counter() - t0

    # Cold-start annotations (docs/compile_cache.md): every record carries
    # the row's compile cost and whether the AOT executable cache served it.
    cold = {}
    # The perf/aot.py config fingerprint ties the number to the compiled
    # program it measured — two records with different fingerprints are
    # different experiments however similar the CLI looked.
    try:
        from distributeddeeplearning_tpu.perf import aot as aotlib
        cold["config_fingerprint"] = aotlib.config_fingerprint(
            cfg, total_steps=total)
    except Exception:
        pass  # annotation only
    try:
        # Policy + ramp provenance on every line (ISSUE 20): an fp32 and a
        # mixed arm (or a ramped and unramped run) must never be conflated.
        from distributeddeeplearning_tpu.config import resolve_precision
        from distributeddeeplearning_tpu.train import optim as optimlib
        cold["precision"] = resolve_precision(cfg).describe()
        cold["batch_ramp"] = optimlib.ramp_describe(cfg)
    except Exception:
        pass  # annotation only
    if compile_time_s is not None:
        cold["compile_time_s"] = round(compile_time_s, 2)
        cold["time_to_first_step_s"] = round(time_to_first_step_s, 2)
        aot = getattr(train_step, "aot", None)
        if aot is not None and aot.enabled:
            cold["aot_source"] = aot.sources.get("dp_train_step", "n/a")

    def row_extra() -> dict:
        """Per-line annotations: memory + cold-start, plus (traced rows)
        the phase breakdown aggregated from the buffered spans so far."""
        if tele is None:
            return {**mem, **cold, **pipe}
        return {**mem, **cold, **pipe,
                "phases": telemetry.phase_totals(tele.snapshot())}

    # Protocol marker: chunked barriers are measurement-protocol drift vs
    # the barrier-free round-2/3 windows (one pipeline drain per 5 steps
    # instead of one per window) — the emitted numbers must say so, or
    # they'd overwrite prior last-good entries as silently incomparable.
    mark = "" if deadline is None else " chunked"
    q_done, q_elapsed = timed_window(quick_n)
    q_rate = (cfg.global_batch_size * q_done / q_elapsed / n_dev
              if q_done else 0.0)
    if emit_quick and q_done:
        _emit_metric(args, q_rate,
                     protocol=f"quick w{quick_w}+{q_done} "
                              f"b{args.batch_size}{mark}", extra=row_extra())
    # Full-protocol window: everything so far (quick_w + quick_n >= the
    # classic 10) counts as warmup; time a fresh window of args.steps.
    if deadline is None or time.monotonic() < deadline:
        done, elapsed = timed_window(args.steps)
    else:
        done = 0
    if done:
        rate = cfg.global_batch_size * done / elapsed / n_dev
        cut = "" if done == args.steps else " cut"
        if emit_final:
            _emit_metric(args, rate,
                         protocol=f"w{quick_w + q_done}+{done} "
                                  f"b{args.batch_size}{mark}{cut}",
                         extra=row_extra())
        if tele is not None and tele.export():
            _note(f"telemetry trace written to "
                  f"{telemetry.trace_path(args.trace_dir, tele.process_index)}")
        return rate
    if q_done:
        # Deadline landed inside the quick window: the quick measurement
        # is the row's result (still post-compile, >= 1 timed step).
        if emit_final:
            _emit_metric(args, q_rate,
                         protocol=f"quick w{quick_w}+{q_done} "
                                  f"b{args.batch_size}{mark} cut",
                         extra=row_extra())
        if tele is not None and tele.export():
            _note(f"telemetry trace written to "
                  f"{telemetry.trace_path(args.trace_dir, tele.process_index)}")
        return q_rate
    raise TimeoutError(
        f"row deadline passed before any timed step (warmup {quick_w})")


def _sweep_batches(args) -> list[int]:
    """Alternate per-chip batches to try after the primary measurement."""
    if args.sweep == "none":
        return []
    if args.sweep == "auto":
        # Headline protocol only: the sweep exists to catch the session-
        # dependent 256/512 sweet-spot flip without inflating every run.
        if args.model == "resnet50" and args.batch_size == 512:
            return [256]
        return []
    return [int(b) for b in args.sweep.split(",") if int(b) != args.batch_size]


def _child(args) -> int:
    import jax

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        jax.config.update("jax_platforms", args.platform)
    try:
        from distributeddeeplearning_tpu.perf import compile_cache
        cache_dir = compile_cache.activate(
            getattr(args, "compile_cache_dir", None))
        if cache_dir:
            _note(f"compile cache at {cache_dir}")
    except Exception as e:  # cache is an optimization, never fatal
        _note(f"compilation cache disabled: {e}")

    t0 = time.perf_counter()
    _note("initializing backend")
    n_dev = jax.device_count()
    _note(f"{BACKEND_UP_HEARTBEAT} {n_dev} x {jax.devices()[0].platform} in "
          f"{time.perf_counter() - t0:.1f}s")

    if not args.suite:
        best = _child_measure(args)
        best_batch = args.batch_size
        # Batch sweep: the per-step dispatch latency of the tunneled chip
        # moves the throughput sweet spot between sessions (measured:
        # b256 1341 < b512 2325 one day, b256 2497 > b512 2366 another).
        # Measure the alternates and emit only a STRICTLY better number —
        # last parseable line wins, so a slower alternate stays silent.
        for alt in _sweep_batches(args):
            row = copy.copy(args)
            row.batch_size = alt
            try:
                rate = _child_measure(row, emit_quick=False,
                                      emit_final=False)
            except Exception as e:  # an OOM alternate must not kill the run
                _note(f"sweep b{alt} failed: {type(e).__name__}: {e}")
                continue
            _note(f"sweep b{alt}: {rate:.1f}/chip (best {best:.1f})")
            if rate > best:
                best, best_batch = rate, alt
                _emit_metric(row, rate,
                             protocol=f"w{row.quick_warmup + row.quick_steps}"
                                      f"+{row.steps} b{alt} sweep")
        # Conv-epilogue fusion alternates (round-3/5 kernel campaign):
        # measured at the winning batch, emitted ONLY if strictly faster —
        # so the driver's own headline run captures a fusion win the
        # moment there is one, and stays silent otherwise. v2
        # (fused_conv3, the 3x3 kernel) runs only if v1 succeeded — a
        # Mosaic rejection of the new kernel must cost one caught
        # exception, never the headline. Restricted to the headline
        # protocol like the batch sweep.
        if (args.model == "resnet50" and args.batch_size == 512
                and not args.fused_block and args.sweep == "auto"):
            for label, flags in (
                    ("fused-block", {"fused_block": True}),
                    ("fused-conv3", {"fused_block": True,
                                     "fused_conv3": True})):
                row = copy.copy(args)
                row.batch_size = best_batch
                for k, v in flags.items():
                    setattr(row, k, v)
                try:
                    rate = _child_measure(row, emit_quick=False,
                                          emit_final=False)
                except Exception as e:
                    _note(f"{label} alternate failed: "
                          f"{type(e).__name__}: {e}")
                    break  # v2 builds on v1; don't try it after a failure
                _note(f"{label} b{best_batch}: {rate:.1f}/chip "
                      f"(best {best:.1f})")
                if rate > best:
                    best = rate
                    _emit_metric(
                        row, rate,
                        protocol=f"w{row.quick_warmup + row.quick_steps}"
                                 f"+{row.steps} b{best_batch} sweep")
        return 0
    wanted = (set(args.suite_models.split(","))
              if args.suite_models else None)
    wanted_rows = (set(args.suite_rows.split(","))
                   if args.suite_rows else None)
    # Suite budget discipline (VERDICT r4 Weak #5): rows run in SUITE's
    # value-per-minute order against one deadline anchored at backend-up.
    # A row is ADMITTED only if 60% of its est_s fits in the remaining
    # budget (a partially-measured row still emits, so starting with most
    # of a row's budget available beats skipping it); a row that runs long
    # is CUT by its own deadline (min(est_s * 2, suite deadline)) instead
    # of eating the rows behind it. Skips are visible on stderr.
    suite_deadline = (time.monotonic() + args.suite_budget
                      if args.suite_budget > 0 else None)
    for row_name, model, overrides, est_s in SUITE:
        if wanted is not None and model not in wanted:
            continue
        if wanted_rows is not None and row_name not in wanted_rows:
            continue
        row = copy.copy(args)
        row.model = model
        row.attention_impl, row.remat, row.fused_bn = None, False, False
        row.fused_block = row.fused_conv3 = False
        row.allreduce_bucket_mb = row.allreduce_dtype = None
        row.optimizer_sharding = None
        row.overlap_collectives, row.opt_state_offload = True, False
        row.pp, row.pipeline_schedule = 1, "gpipe"
        row.pipeline_virtual_stages = 1
        row.dtype = row.precision = None
        for k, v in overrides.items():
            setattr(row, k, v)
        row_deadline = None
        if suite_deadline is not None:
            remaining = suite_deadline - time.monotonic()
            if remaining < 0.6 * est_s:
                _note(f"suite row {model} b{row.batch_size}"
                      f"{_protocol_suffix(row)} SKIPPED on budget "
                      f"(remaining {remaining:.0f}s < 0.6*est {est_s}s)")
                continue
            row_deadline = min(suite_deadline,
                               time.monotonic() + 2.0 * est_s)
        try:
            _child_measure(row, emit_quick=False, deadline=row_deadline)
        except Exception as e:  # one OOM must not sink the rest of the suite
            from distributeddeeplearning_tpu.observability import perf_report
            metric, unit = _metric_name_unit(row)
            print(json.dumps(perf_report.annotate({
                "metric": metric, "value": None, "unit": unit,
                "vs_baseline": None,
                "protocol": _protocol_suffix(row).strip() or None,
                "error": f"{type(e).__name__}: {e}"[:600],
            }, provenance="error")), flush=True)
    return 0


# Routed through observability/sidecars.py (atomic publish + envelope);
# sidecars is pure stdlib, so the jax-free parent can import it.
from distributeddeeplearning_tpu.observability import sidecars  # noqa: E402

LAST_GOOD_PATH = sidecars.path_for("last_bench")


def _record_last_good(line: str) -> None:
    """Persist the newest successful measurement per metric (parent side) —
    keyed by metric so a suite run can't evict the headline's entry."""
    try:
        rec = json.loads(line)
        metric = rec["metric"]
    except (ValueError, TypeError, KeyError):
        return  # cache is evidence, not correctness
    side = sidecars.read(LAST_GOOD_PATH) or {}
    table = side.get("metrics")
    if not isinstance(table, dict):
        table = {}  # legacy flat/single-record layouts: start over
    table[metric] = rec
    sidecars.write(LAST_GOOD_PATH, {"metrics": table})


def _emit_error(args, msg: str, attempts: list | None = None) -> None:
    from distributeddeeplearning_tpu.observability import perf_report
    metric, unit = _metric_name_unit(args)
    rec = {
        "metric": metric,
        "value": None,
        "unit": unit,
        "vs_baseline": None,
        "error": msg[-800:],
    }
    # Context for the reader, NOT a measurement: the newest number this
    # harness captured on a live chip (value above stays null — a dead
    # backend yields no result, but the record should say what the same
    # command measured when the chip last answered). The embedded prior
    # carries its OWN provenance (stale within --max-stale-age, expired
    # past it — an expired prior additionally loses vs_baseline: a
    # week-old cache must not keep scoring against the target).
    # ``stale_age_s`` is top-level so a consumer can judge freshness
    # without digging the timestamp out of the nested record.
    max_age = getattr(args, "max_stale_age",
                      perf_report.DEFAULT_MAX_STALE_AGE_S)
    try:
        side = sidecars.read(LAST_GOOD_PATH) or {}
        table = side.get("metrics")
        prior = table.get(metric) if isinstance(table, dict) else None
        if isinstance(prior, dict) and prior.get("metric") == metric:
            age = perf_report.measurement_age_s(prior.get("measured_at"))
            labeled = perf_report.stale_record(prior, age, max_age)
            rec["last_measured_on_live_chip"] = labeled
            if age is not None:
                rec["stale_age_s"] = int(age)
            if labeled["provenance"] == "expired":
                _note(f"WARNING: cached {metric} measurement is "
                      f"{'unknown age' if age is None else f'{int(age)}s old'}"
                      f" (> --max-stale-age {int(max_age)}s): demoted to "
                      f"provenance=expired, vs_baseline dropped — this "
                      f"number is history, not a current result")
    except (OSError, ValueError):
        pass
    # with_backend=False: this runs in the PARENT, which never initialized
    # jax — probing a backend here could hang on the very tunnel whose
    # death this record reports.
    perf_report.annotate(rec, provenance="error", attempts=attempts,
                         with_backend=False)
    print(json.dumps(rec), flush=True)


def _last_summary(stdout: str):
    """Last ``{"summary": ...}`` line a train.py child printed, or None.
    Under ``launch.py --max-restarts`` the crashed attempt prints no
    summary, so the last one belongs to the attempt that finished."""
    for line in reversed((stdout or "").splitlines()):
        if '"summary"' not in line:
            continue
        try:
            return json.loads(line)["summary"]
        except (ValueError, KeyError, TypeError):
            continue
    return None


def _run_chaos(args) -> int:
    """Chaos recovery benchmark (CPU, no chip needed): run the same tiny
    synthetic job twice — once clean, once killed by fault injection at
    step F under ``launch.py --max-restarts 1`` — and report the wall-clock
    overhead of surviving one fault (relaunch + backend re-init +
    re-compile + checkpoint restore + replayed steps). Deterministic on
    purpose: ``crash@F`` is attempt-scoped (robustness/faults.py), so the
    restarted attempt runs fault-free to completion.

    All runs share one fresh compile cache (perf/compile_cache.py): the
    clean run cold-compiles and populates it, so the faulted run's restart
    attempt recovers *warm* — measuring the recovery path users actually
    hit when the launcher exports the cache to every attempt. Pass
    ``--chaos-cold`` to additionally rerun the faulted job with the cache
    disabled and report the cold-recovery overhead next to the warm one."""
    import shutil
    import tempfile

    from distributeddeeplearning_tpu.observability import perf_report

    base = os.path.dirname(os.path.abspath(__file__))
    steps, fail_at, every = args.chaos_steps, args.chaos_fail_at, 2
    metric = "chaos_recovery_overhead"
    if not 0 < fail_at < steps:
        # with_backend=False here and below: the chaos harness is the
        # PARENT — it spawns launch.py children and never initializes jax.
        print(json.dumps(perf_report.annotate({
            "metric": metric, "value": None, "unit": "s per fault",
            "error": f"--chaos-fail-at must be in (0, {steps})"},
            provenance="error", with_backend=False)), flush=True)
        return 0
    root = tempfile.mkdtemp(prefix="ddl_chaos_")
    cache = os.path.join(root, "cache")
    os.makedirs(cache, exist_ok=True)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env_warm = dict(env, DDL_COMPILE_CACHE=cache,
                    JAX_COMPILATION_CACHE_DIR=cache)
    env_cold = dict(env, DDL_COMPILE_CACHE="off")
    env_cold.pop("JAX_COMPILATION_CACHE_DIR", None)

    def train_cmd(ckpt_dir: str, extra: tuple = ()) -> list[str]:
        return [sys.executable, os.path.join(base, "train.py"),
                "--backend", "cpu", "--synthetic",
                "--model", "resnet18_thin", "--image-size", "32",
                "--batch-size", "8", "--dtype", "float32",
                "--steps", str(steps), "--checkpoint-every", str(every),
                "--log-every", "1000", "--checkpoint-dir", ckpt_dir,
                *extra]

    def fail(stage: str, proc) -> int:
        tail = (proc.stderr or "")[-600:]
        print(json.dumps(perf_report.annotate({
            "metric": metric, "value": None, "unit": "s per fault",
            "error": f"{stage} run failed rc={proc.returncode}: {tail}"},
            provenance="error", with_backend=False)), flush=True)
        return 0

    def faulted_run(tag: str, run_env: dict):
        launch_cmd = [sys.executable, os.path.join(base, "launch.py"),
                      "--num-processes", "1", "--max-restarts", "1",
                      "--backoff", "0.2", "--",
                      *train_cmd(os.path.join(root, tag),
                                 ("--fault-plan", f"crash@{fail_at}"))]
        t = time.monotonic()
        proc = subprocess.run(launch_cmd, env=run_env, capture_output=True,
                              text=True, timeout=420)
        return time.monotonic() - t, proc

    try:
        t0 = time.monotonic()
        populate = subprocess.run(
            train_cmd(os.path.join(root, "populate")), env=env_warm,
            capture_output=True, text=True, timeout=420)
        w_populate = time.monotonic() - t0
        if populate.returncode != 0:
            return fail("populate", populate)

        # The warm BASELINE must itself run warm: comparing a warm faulted
        # run against the cold populate run would subtract the populate
        # run's compile time and report a (nonsensical) negative overhead.
        t0 = time.monotonic()
        clean = subprocess.run(
            train_cmd(os.path.join(root, "clean")), env=env_warm,
            capture_output=True, text=True, timeout=420)
        w_clean = time.monotonic() - t0
        if clean.returncode != 0:
            return fail("clean", clean)

        w_faulted, faulted = faulted_run("faulted", env_warm)
        if faulted.returncode != 0 or "restart 1/1" not in faulted.stderr:
            return fail("faulted", faulted)

        # Checkpoint cadence fixes the resume point analytically: the loop
        # saves at step F before the injector kills it only when F is on
        # cadence, so the restart replays F - floor(F/every)*every steps.
        resumed_from = (fail_at // every) * every
        rec = {
            "metric": metric,
            "value": round(w_faulted - w_clean, 2),
            "unit": "s per fault",
            "vs_baseline": None,
            "steps_lost": fail_at - resumed_from,
            "restarts": 1,
            "clean_s": round(w_clean, 1),
            "clean_cold_s": round(w_populate, 1),
            "faulted_s": round(w_faulted, 1),
            "cache": "warm",
            "protocol": (f"cpu resnet18_thin b8 {steps} steps, "
                         f"crash@{fail_at}, ckpt every {every}, shared "
                         f"compile cache (a populate run cold-compiles it, "
                         f"then clean baseline, faulted run, and restart "
                         f"all recover warm); overhead = relaunch + "
                         f"re-init + cached compile + restore + "
                         f"{fail_at - resumed_from} replayed step(s)"),
        }
        # The restarted attempt's own cold-start telemetry (train/loop.py
        # stamps both into the run summary the child prints on stdout).
        summary = _last_summary(faulted.stdout)
        if summary:
            for k in ("compile_time_s", "time_to_first_step_s"):
                if summary.get(k) is not None:
                    rec[f"recovery_{k}"] = summary[k]

        if getattr(args, "chaos_cold", False):
            w_cold, cold = faulted_run("faulted_cold", env_cold)
            if cold.returncode != 0 or "restart 1/1" not in cold.stderr:
                return fail("faulted_cold", cold)
            rec["faulted_cold_s"] = round(w_cold, 1)
            # Cold-vs-cold: the cache-off faulted run's attempt 0 compiles
            # cold too, so its baseline is the cold populate run.
            rec["overhead_cold_s"] = round(w_cold - w_populate, 2)
            rec["recovery_compile_saved_s"] = round(w_cold - w_faulted, 2)
            cold_summary = _last_summary(cold.stdout)
            if cold_summary:
                for k in ("compile_time_s", "time_to_first_step_s"):
                    if cold_summary.get(k) is not None:
                        rec[f"recovery_cold_{k}"] = cold_summary[k]
        perf_report.annotate(rec, provenance="fresh", with_backend=False)
        print(json.dumps(rec), flush=True)
        return 0
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _run_elastic_chaos(args) -> int:
    """Elastic soak benchmark (CPU, no chip needed): a 2-host x 2-device
    dp4 transformer job under ``launch.py --elastic`` loses host 1 to a
    ``host_lost`` fault (SIGKILL + heartbeat suppressed), auto-re-forms at
    dp2 from the last good checkpoint, then grows back to dp4 when the
    survivor announces a ``host_rejoin`` — all with the global batch fixed,
    so the trajectory matches an uninterrupted run to the last float32 ulp
    (tests/test_elastic_resume.py proves that part; this benchmark measures
    the OUTAGE). The record's value is ``reconfiguration_time_s`` — fault
    detection to first post-resume step, both ends on the shared local
    CLOCK_MONOTONIC — as stamped into the final attempt's run summary by
    train/loop.py."""
    import shutil
    import tempfile

    from distributeddeeplearning_tpu import hostmesh
    from distributeddeeplearning_tpu.observability import perf_report

    base = os.path.dirname(os.path.abspath(__file__))
    metric = "reconfiguration_time_s"
    steps, lose_at, rejoin_at = 12, 4, 8
    root = tempfile.mkdtemp(prefix="ddl_elastic_")
    cache = os.path.join(root, "cache")
    os.makedirs(cache, exist_ok=True)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update(hostmesh.virtual_host_env(2))  # 2 fake devices per host
    env.update(DDL_COMPILE_CACHE=cache, JAX_COMPILATION_CACHE_DIR=cache)

    def fail(stage: str, proc=None, detail: str = "") -> int:
        tail = detail or (getattr(proc, "stderr", "") or "")[-600:]
        rc = getattr(proc, "returncode", None)
        print(json.dumps(perf_report.annotate({
            "metric": metric, "value": None, "unit": "s",
            "error": f"{stage} failed rc={rc}: {tail}"},
            provenance="error")), flush=True)
        return 0

    cmd = [sys.executable, os.path.join(base, "launch.py"),
           "--num-processes", "2", "--elastic",
           "--max-restarts", "2", "--backoff", "0.2",
           "--heartbeat-dir", os.path.join(root, "hb"),
           # Attempt 0: host 1 dies at dp4 -> shrink to dp2. Attempt 1:
           # the survivor (original host 0) announces a rejoin -> graceful
           # stop, grow back to dp4. Attempt 2 runs fault-free to the end.
           "--child-fault-plan", f"1:host_lost@{lose_at}",
           "--child-fault-plan", f"0:host_rejoin@{rejoin_at}:a1",
           "--",
           sys.executable, os.path.join(base, "train.py"),
           "--backend", "cpu", "--synthetic", "--model", "bert_tiny",
           "--seq-len", "32", "--batch-size", "8", "--dtype", "float32",
           "--dp", "4", "--steps", str(steps),
           "--checkpoint-every", "2", "--log-every", "1000",
           "--checkpoint-dir", os.path.join(root, "ckpt")]
    try:
        t0 = time.monotonic()
        try:
            proc = subprocess.run(cmd, env=env, capture_output=True,
                                  text=True, timeout=900)
        except subprocess.TimeoutExpired as e:
            return fail("soak", detail=f"timeout after {e.timeout}s")
        wall = time.monotonic() - t0
        if proc.returncode != 0:
            return fail("soak", proc)
        if "elastic re-formation (host_lost)" not in proc.stderr:
            return fail("soak", proc, detail="no host_lost re-formation in "
                        "launcher output")
        summary = _last_summary(proc.stdout)
        if not summary or summary.get(metric) is None:
            return fail("soak", proc,
                        detail="final summary carries no "
                        f"{metric} (elastic event not delivered?)")
        event = summary.get("elastic_event") or {}
        grew = "elastic re-formation (host_rejoin)" in proc.stderr
        rec = {
            "metric": metric,
            "value": round(float(summary[metric]), 2),
            "unit": "s per re-formation",
            "vs_baseline": None,
            "trigger": event.get("trigger"),
            "degree_before": event.get("degree_before"),
            "degree_after": event.get("degree_after"),
            "reformations": proc.stderr.count("# launcher: elastic event:"),
            "grew_back": grew,
            # Rendezvous-path observability: the outage's detect -> drain ->
            # restore -> compile -> first-step split and the membership
            # epoch the final attempt resumed under (train/loop.py).
            "phases": summary.get("reconfiguration_phases"),
            "membership_epoch": event.get("epoch"),
            "final_step": summary.get("final_step"),
            "total_s": round(wall, 1),
            "protocol": (f"cpu bert_tiny b8 seq32 {steps} steps, 2 hosts x "
                         f"2 devices, host_lost@{lose_at} shrinks dp4->dp2, "
                         f"host_rejoin@{rejoin_at} grows dp2->dp4, global "
                         f"batch fixed; value = launcher fault detection -> "
                         f"first post-resume step of the last re-formation "
                         f"(shared CLOCK_MONOTONIC)"),
        }
        perf_report.annotate(rec, provenance="fresh")
        print(json.dumps(rec), flush=True)
        return 0
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _parse_record(line: str):
    """A parseable bench record (measurement or per-config error), or None."""
    if not line.startswith("{"):
        return None
    try:
        rec = json.loads(line)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) and "metric" in rec else None


def _run_attempt(child_cmd, timeout: float, *, relay_errors: bool,
                 record_good: bool = True,
                 preflight: float = 0) -> tuple[int, str, object]:
    """Run one child, RELAYING metric lines to stdout as they appear.

    Returns (num_measurements_relayed, stderr_tail, rc). The relay is the
    point: once a line is printed it survives any outer kill.
    ``relay_errors`` (suite mode) also passes through per-config error
    records so a failed row is visible, not silently absent; default mode
    keeps them back because the driver takes the LAST parseable line and an
    error record must never shadow a real measurement.

    ``preflight`` > 0 arms a fail-fast deadline on backend init: the child
    prints a ``# bench: backend up`` heartbeat the moment ``jax.devices()``
    returns (seconds on a live tunnel), but a DOWN tunnel makes that call
    hang indefinitely — so if neither the heartbeat nor a metric line has
    appeared within ``preflight`` seconds the child is killed and rc is the
    sentinel ``preflight ...`` string. This costs nothing on a live chip
    (the deadline disarms at the heartbeat, before compilation starts) and
    turns a dead-tunnel run from 3 x attempt_timeout of hangs into one
    short probe, leaving the driver's window open for a later retry."""
    # The shared cache env (DDL_COMPILE_CACHE / JAX_COMPILATION_CACHE_DIR)
    # was exported by main() before the first attempt; children inherit it.
    env = dict(os.environ)
    proc = subprocess.Popen(child_cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)
    relayed = [0, 0]  # [measurements, error records]
    err_lines: list[str] = []
    backend_up = threading.Event()

    def _pump_out():
        for line in proc.stdout:
            line = line.strip()
            rec = _parse_record(line)
            if rec is None:
                continue
            backend_up.set()  # any metric line proves the backend answered
            if rec.get("value") is not None:
                print(line, flush=True)
                relayed[0] += 1
                if record_good:  # never from forced-platform smoke runs
                    rec["measured_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
                    _record_last_good(json.dumps(rec))
            elif relay_errors:
                print(line, flush=True)
                relayed[1] += 1

    def _pump_err():
        for line in proc.stderr:
            if BACKEND_UP_HEARTBEAT in line:
                backend_up.set()
            err_lines.append(line.rstrip())
            del err_lines[:-40]

    threads = [threading.Thread(target=_pump_out, daemon=True),
               threading.Thread(target=_pump_err, daemon=True)]
    for t in threads:
        t.start()
    start = time.monotonic()
    rc: object = None
    while True:
        try:
            rc = proc.wait(timeout=1)
            break
        except subprocess.TimeoutExpired:
            pass
        elapsed = time.monotonic() - start
        if preflight and not backend_up.is_set() and elapsed >= preflight:
            proc.kill()
            proc.wait()
            rc = (f"preflight {int(preflight)}s: backend never came up "
                  f"(tunnel presumed down)")
            break
        if elapsed >= timeout:
            proc.kill()
            proc.wait()
            rc = f"timeout {int(timeout)}s"
            break
    for t in threads:
        t.join(timeout=5)
    return relayed[0] + relayed[1], "\n".join(err_lines), rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    # 512/chip is the measured v5e sweet spot: 2325 img/s/chip vs 1341 at
    # 256 and 1978 at 1024 (2026-07-29 sweep on the tunneled chip) — large
    # enough to amortize per-step dispatch latency, small enough to stay
    # HBM-friendly.
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--seq-len", type=int, default=512,
                   help="sequence length for token (BERT/GPT) models")
    p.add_argument("--mlm-max-predictions", type=int, default=-1,
                   help="gather-mode MLM head width; -1 = auto "
                        "(round(0.15*seq_len), the canonical BERT recipe), "
                        "0 = dense full-sequence logits")
    p.add_argument("--attention-impl", default=None,
                   choices=[None, "dense", "flash", "ring", "zigzag"],
                   help="attention implementation for token models")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize transformer layers in backward")
    p.add_argument("--fused-bn", action="store_true",
                   help="Pallas fused BN(+residual)+ReLU kernels (CNNs)")
    p.add_argument("--fused-block", action="store_true",
                   help="conv-epilogue fusion: 1x1 convs as Pallas "
                        "matmul+BN (resnet50/101/152)")
    p.add_argument("--fused-conv3", action="store_true",
                   help="fused_block v2: stride-1 3x3 convs as Pallas "
                        "conv+BN too (requires --fused-block)")
    p.add_argument("--allreduce-bucket-mb", type=float, default=None,
                   help="gradient tensor-fusion bucket size in MB "
                        "(parallel/collectives.py); 0 = per-leaf reduction "
                        "(the unfused A/B reference, emitted under its own "
                        "_perleaf_ar metric name); unset = config default "
                        "(fused, 4 MB)")
    p.add_argument("--allreduce-dtype", default=None,
                   choices=[None, "float32", "bfloat16"],
                   help="gradient all-reduce payload dtype (bfloat16 = "
                        "compressed wire payload, fp32 restored after)")
    p.add_argument("--optimizer-sharding", default=None,
                   choices=[None, "none", "zero1", "zero2", "zero3"],
                   help="ZeRO sharding ladder (parallel/zero.py): zero1 = "
                        "sharded optimizer state, zero2 = + grads stay "
                        "reduce-scattered per bucket, zero3 = + params "
                        "1/N-chunked, all-gathered per bucket; each stage "
                        "emitted under its own _<stage> metric name; unset "
                        "= replicated optimizer")
    p.add_argument("--no-overlap-collectives", dest="overlap_collectives",
                   action="store_false", default=True,
                   help="serialize the zero2/zero3 reduce-scatters after "
                        "backward instead of issuing them per fusion "
                        "bucket as cotangents are produced (A/B for the "
                        "overlap win; marked no-overlap in the protocol)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline stages (models/pipeline.py); must divide "
                        "the device count, remaining devices become the "
                        "data axis; the model must be a *_pp registry "
                        "variant with matching pipeline_stages")
    p.add_argument("--pipeline-schedule", default="gpipe",
                   choices=["gpipe", "1f1b"],
                   help="pipeline schedule: gpipe = fill/drain, 1f1b = "
                        "interleaved one-forward-one-backward over "
                        "--pipeline-virtual-stages chunks per stage; each "
                        "(stages, schedule, V) tuple reports under its own "
                        "metric name and records carry the measured "
                        "pipeline_bubble_fraction (docs/pipeline.md)")
    p.add_argument("--pipeline-virtual-stages", type=int, default=1,
                   help="virtual chunks per stage for --pipeline-schedule "
                        "1f1b (V>1 shrinks the bubble to "
                        "(P-1)/(M*V+P-1)); must divide layers-per-stage")
    p.add_argument("--dtype", default=None,
                   choices=[None, "float32", "bfloat16"],
                   help="compute dtype via the legacy knob (unset = the "
                        "bfloat16 protocol of record); subsumed by "
                        "--precision when that is set")
    p.add_argument("--precision", default=None,
                   choices=[None, "fp32", "mixed"],
                   help="explicit precision policy (config.PrecisionPolicy) "
                        "for the large-batch %%-of-peak A/B: 'fp32' = "
                        "everything float32 scored against the fp32 roof, "
                        "'mixed' = bf16 compute + fp32 master weights + "
                        "dynamic loss scaling scored against the bf16 roof; "
                        "each arm emits under its own _<precision> metric "
                        "name (docs/mixed_precision.md)")
    p.add_argument("--opt-state-offload", action="store_true",
                   help="place sharded optimizer-state chunks in host RAM "
                        "(pinned_host memory kind) where the backend "
                        "exposes it; no-op with a warning elsewhere")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--quick-steps", type=int, default=8,
                   help="timed steps in the progressive quick window")
    p.add_argument("--quick-warmup", type=int, default=3,
                   help="warmup steps before the quick window")
    p.add_argument("--warmup-steps", type=int, default=None,
                   help="compat alias for --quick-warmup (pre-progressive "
                        "protocol name)")
    p.add_argument("--sweep", default="auto",
                   help="alternate per-chip batch sizes to try after the "
                        "primary measurement (comma list, 'none', or "
                        "'auto' = 256 for the resnet50 b512 headline); "
                        "an alternate line is emitted only if faster")
    p.add_argument("--suite-models", default=None,
                   help="with --suite: only measure rows whose model is "
                        "in this comma list (re-run a single row)")
    p.add_argument("--suite-rows", default=None,
                   help="with --suite: only measure rows with these NAMES "
                        "(comma list, see SUITE; runs in suite order) — "
                        "unlike --suite-models this selects EXACT rows, "
                        "e.g. one of the bert_base protocol variants "
                        "(tools/chip_window.sh splits the suite across "
                        "window steps with this); names stay valid when "
                        "rows are inserted or reordered")
    p.add_argument("--suite", action="store_true",
                   help="measure every acceptance config, one line each")
    p.add_argument("--suite-budget", type=int, default=-1,
                   help="wall budget (s) for the suite rows themselves, "
                        "anchored after backend init; rows that don't fit "
                        "are skipped with a stderr note and a row that "
                        "runs long is cut at 2x its estimate. -1 = derive "
                        "from --budget minus an init margin; 0 = no "
                        "budget (measure every row to completion)")
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. cpu) for smoke runs")
    p.add_argument("--trace-dir", default=None,
                   help="write a Chrome-trace JSON (phase spans + per-bucket "
                        "collective spans) for the timed windows under this "
                        "directory, and attach a per-phase breakdown to the "
                        "metric record; traced rows report under a _tele "
                        "metric name because tracing reads the clock inside "
                        "the timed loop (protocol drift by design — it is "
                        "how the overhead A/B measures itself)")
    p.add_argument("--attempt-timeout", type=int, default=480,
                   help="hard wall-clock limit per measurement attempt (s); "
                        "the quick line lands ~1 min after backend init on "
                        "a live chip, and a hanging backend must leave the "
                        "parent time to print the error record before any "
                        "outer driver timeout")
    p.add_argument("--attempts", type=int, default=3)
    p.add_argument("--preflight-timeout", type=int, default=75,
                   help="fail-fast deadline (s) on backend init: if the "
                        "child's 'backend up' heartbeat hasn't appeared "
                        "within this window the tunnel is presumed down and "
                        "the error record is emitted immediately instead of "
                        "burning attempts x attempt_timeout on hangs; 0 "
                        "disables (live-chip init lands in seconds, so 75s "
                        "is generous)")
    p.add_argument("--budget", type=int, default=1200,
                   help="total wall-clock budget across all attempts (s); "
                        "guarantees the error record is printed before any "
                        "outer driver timeout can strike")
    p.add_argument("--max-stale-age", type=float, default=24 * 3600.0,
                   help="age cap (s) on the cached last-good measurement "
                        "embedded in error records: younger is labeled "
                        "provenance=stale (age attached), older is demoted "
                        "to provenance=expired, loses vs_baseline, and "
                        "warns loudly (default 24h)")
    p.add_argument("--chaos", action="store_true",
                   help="CPU recovery-overhead benchmark: time a clean tiny "
                        "run vs the same run crashed at --chaos-fail-at and "
                        "auto-restarted by launch.py; emits one "
                        "chaos_recovery_overhead record (no chip needed)")
    p.add_argument("--chaos-steps", type=int, default=8,
                   help="total steps of each --chaos run")
    p.add_argument("--chaos-fail-at", type=int, default=5,
                   help="step after which the faulted --chaos run crashes")
    p.add_argument("--chaos-cold", action="store_true",
                   help="--chaos: also run the faulted job with the compile "
                        "cache disabled and report the cold-cache recovery "
                        "overhead next to the warm one (roughly doubles the "
                        "chaos runtime)")
    p.add_argument("--chaos-elastic", action="store_true",
                   help="CPU elastic soak benchmark: a 2-host dp4 "
                        "transformer job loses a host (host_lost fault), "
                        "auto-re-forms at dp2, grows back to dp4 on rejoin, "
                        "and reports the measured reconfiguration_time_s "
                        "(fault detection -> first post-resume step) as one "
                        "provenance-stamped record (no chip needed)")
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent compile cache + AOT step executables "
                        "shared by parent/child/suite rows "
                        "(docs/compile_cache.md); default $DDL_COMPILE_CACHE "
                        "or <repo>/.cache/jax_compile; 'off' disables")
    p.add_argument("--run-child", action="store_true", help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.chaos:
        return _run_chaos(args)
    if args.chaos_elastic:
        return _run_elastic_chaos(args)

    if args.fused_conv3 and not args.fused_block:
        # Same up-front reject as train.py: on a scarce chip window this
        # must die at parse time, not after backend init inside the child.
        p.error("--fused-conv3 requires --fused-block")
    if args.allreduce_bucket_mb is not None and args.allreduce_bucket_mb < 0:
        p.error(f"--allreduce-bucket-mb must be >= 0 "
                f"(got {args.allreduce_bucket_mb}); 0 selects per-leaf "
                f"reduction")
    # Same up-front rejects as train.py / models/pipeline.build_schedule:
    # a malformed schedule must die at parse time, not after backend init.
    if args.pp < 1:
        p.error(f"--pp must be >= 1 (got {args.pp})")
    if args.pipeline_virtual_stages < 1:
        p.error(f"--pipeline-virtual-stages must be >= 1 "
                f"(got {args.pipeline_virtual_stages})")
    if args.pipeline_virtual_stages > 1 and args.pipeline_schedule != "1f1b":
        p.error("--pipeline-virtual-stages > 1 requires "
                "--pipeline-schedule 1f1b (gpipe has no virtual chunks)")
    try:  # fail a malformed --sweep at parse time, not after the primary
        _sweep_batches(args)
    except ValueError:
        p.error(f"--sweep {args.sweep!r}: expected a comma list of ints, "
                f"'auto', or 'none'")
    if args.suite and args.sweep not in ("auto", "none"):
        p.error("--sweep is a headline-run option; suite rows pin their "
                "measured sweet-spot batches (see SUITE)")
    if args.suite_models:
        known = {m for _n, m, _o, _e in SUITE}
        asked = {s.strip() for s in args.suite_models.split(",") if s.strip()}
        if not asked or asked - known:
            p.error(f"--suite-models: unknown model(s) "
                    f"{sorted(asked - known) or args.suite_models!r}; "
                    f"suite rows: {sorted(known)}")
        args.suite_models = ",".join(sorted(asked))
    if args.suite_rows:
        if args.suite_models:
            p.error("--suite-rows and --suite-models are mutually "
                    "exclusive (rows select exact entries)")
        row_names = [n for n, _m, _o, _e in SUITE]
        asked = [s.strip() for s in args.suite_rows.split(",") if s.strip()]
        resolved, unknown = [], []
        for s in asked:
            if s in row_names:
                resolved.append(s)
            elif s.isdigit() and int(s) < len(row_names):
                # Deprecated alias: positional indices predate named rows
                # and silently select the wrong row when the suite is
                # reordered — accept them for old drivers, but say so.
                print(f"# bench: --suite-rows index {s} is deprecated, "
                      f"resolving to row {row_names[int(s)]!r}; indices "
                      f"break when suite rows are inserted or reordered",
                      file=sys.stderr, flush=True)
                resolved.append(row_names[int(s)])
            else:
                unknown.append(s)
        if not asked or unknown:
            p.error(f"--suite-rows: unknown row name(s) "
                    f"{unknown or args.suite_rows!r}; suite rows: "
                    f"{row_names}")
        args.suite_rows = ",".join(dict.fromkeys(resolved))  # dedupe, ordered

    if args.run_child:
        return _child(args)

    child_cmd = [sys.executable, os.path.abspath(__file__), "--run-child",
                 "--model", args.model,
                 "--batch-size", str(args.batch_size),
                 "--seq-len", str(args.seq_len),
                 "--steps", str(args.steps),
                 "--quick-steps", str(args.quick_steps),
                 "--quick-warmup", str(args.warmup_steps
                                       if args.warmup_steps is not None
                                       else args.quick_warmup),
                 "--mlm-max-predictions", str(args.mlm_max_predictions)]
    child_cmd += ["--sweep", args.sweep]
    if args.platform:
        child_cmd += ["--platform", args.platform]
    if args.attention_impl:
        child_cmd += ["--attention-impl", args.attention_impl]
    if args.remat:
        child_cmd += ["--remat"]
    if args.fused_bn:
        child_cmd += ["--fused-bn"]
    if args.fused_block:
        child_cmd += ["--fused-block"]
    if args.fused_conv3:
        child_cmd += ["--fused-conv3"]
    if args.allreduce_bucket_mb is not None:
        child_cmd += ["--allreduce-bucket-mb", str(args.allreduce_bucket_mb)]
    if args.allreduce_dtype:
        child_cmd += ["--allreduce-dtype", args.allreduce_dtype]
    if args.optimizer_sharding:
        child_cmd += ["--optimizer-sharding", args.optimizer_sharding]
    if not args.overlap_collectives:
        child_cmd += ["--no-overlap-collectives"]
    if args.opt_state_offload:
        child_cmd += ["--opt-state-offload"]
    if args.dtype:
        child_cmd += ["--dtype", args.dtype]
    if args.precision:
        child_cmd += ["--precision", args.precision]
    if args.pp > 1:
        child_cmd += ["--pp", str(args.pp)]
    if args.pipeline_schedule != "gpipe":
        child_cmd += ["--pipeline-schedule", args.pipeline_schedule]
    if args.pipeline_virtual_stages != 1:
        child_cmd += ["--pipeline-virtual-stages",
                      str(args.pipeline_virtual_stages)]
    if args.trace_dir:
        child_cmd += ["--trace-dir", args.trace_dir]
    if args.compile_cache_dir is not None:
        child_cmd += ["--compile-cache-dir", args.compile_cache_dir]
    if args.suite:
        child_cmd += ["--suite"]
        if args.suite_models:
            child_cmd += ["--suite-models", args.suite_models]
        if args.suite_rows:
            child_cmd += ["--suite-rows", args.suite_rows]
        args.attempt_timeout = max(args.attempt_timeout, args.budget)

    # Export the shared cache once so every attempt's child (and anything
    # it spawns) lands on the same directory — attempt 2 of a flaky tunnel
    # then reuses attempt 1's compiled programs.
    cache_dir = _compile_cache_dir(args.compile_cache_dir)
    if cache_dir is not None:
        os.makedirs(cache_dir, exist_ok=True)
        os.environ["DDL_COMPILE_CACHE"] = cache_dir
        os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    else:
        os.environ["DDL_COMPILE_CACHE"] = "off"
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

    last_err = "no attempt ran"
    attempt_log: list = []  # retry history for the error record's schema
    deadline = time.monotonic() + args.budget
    for attempt in range(args.attempts):
        if attempt:
            time.sleep(RETRY_BACKOFF_SEC[min(attempt - 1,
                                             len(RETRY_BACKOFF_SEC) - 1)])
        remaining = deadline - time.monotonic()
        if remaining < 30:
            last_err += "; budget exhausted"
            attempt_log.append({"attempt": attempt + 1,
                                "rc": "skipped: budget exhausted"})
            break
        # Children stamp their fresh records with the attempt that produced
        # them — "landed on attempt 3 of a flaky tunnel" must be readable
        # off the record (observability/perf_report.py).
        os.environ["DDL_BENCH_ATTEMPT"] = str(attempt + 1)
        cmd = list(child_cmd)
        if args.suite:
            # The child's row budget excludes backend init (its clock
            # starts after jax.devices() returns) but must leave the
            # parent room to relay the last row before --budget ends —
            # derived from the budget REMAINING at this attempt, so a
            # retry's gating matches the time it actually has (a first
            # derivation reused verbatim would admit rows the parent's
            # deadline then kills mid-row). Floor of 60s: a derived
            # budget must never collapse to 0, which means "no gating".
            suite_budget = (args.suite_budget if args.suite_budget >= 0
                            else max(60, int(remaining) - 120))
            cmd += ["--suite-budget", str(suite_budget)]
        n_lines, err_tail, rc = _run_attempt(
            cmd, timeout=min(args.attempt_timeout, remaining),
            relay_errors=args.suite, record_good=not args.platform,
            preflight=args.preflight_timeout)
        if args.suite and n_lines and rc != 0:
            # Child died mid-suite: partial rows are already on stdout (and
            # stay valid), but flag the incompleteness on stderr. No error
            # record — it would become the last line and shadow real data.
            print(f"# bench: suite incomplete (child rc={rc}); rows above "
                  f"are valid, remaining configs unmeasured",
                  file=sys.stderr, flush=True)
            return 0
        if n_lines and (rc == 0 or not args.suite):
            # At least one real measurement is already on stdout; a child
            # that then hung or died cannot take it back.
            return 0
        last_err = f"attempt {attempt + 1}: rc={rc}: {err_tail[-600:]}"
        attempt_log.append({"attempt": attempt + 1, "rc": str(rc),
                            "relayed_lines": n_lines})
        if isinstance(rc, str) and rc.startswith("preflight"):
            # Backend init hung: further attempts would hang identically.
            # Exit NOW so the total dead-tunnel runtime is one preflight
            # window, not attempts x attempt_timeout.
            break

    _emit_error(args, last_err, attempts=attempt_log)
    return 0


if __name__ == "__main__":
    sys.exit(main())
