#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line with the metric of record.

Metric (BASELINE.json:2): ResNet50/ImageNet images/sec/chip, measured on the
headline single-chip synthetic config (config 1 scaled to a throughput-class
batch), bfloat16, after compile/warmup exclusion — the same protocol the
reference's harness used for its images/sec tables (SURVEY.md §3.4).

``vs_baseline``: BASELINE.json captured no published reference numbers
("published": {}), so the denominator is the north-star target expressed
per-chip: 8xV100 ResNet50 ImageNet aggregate on a v5e-8, i.e. one V100's
mixed-precision throughput per chip. We pin that at 1450 images/sec/chip
(NVIDIA's commonly-published V100 ResNet50 AMP figure); vs_baseline > 1.0
means beating the target.

Resilience contract (VERDICT.md round 1, Missing #1): backend init against
the remote TPU can hang or raise transient ``UNAVAILABLE``.  The measurement
therefore runs in a *child* process under a hard per-attempt timeout, with
bounded retries + backoff in the parent; whatever happens, the parent prints
exactly one parseable JSON line (a numeric record on success, an ``error``
record otherwise) and exits 0.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

V100_AMP_RESNET50_IMAGES_PER_SEC = 1450.0
RETRY_BACKOFF_SEC = (10, 30)  # sleeps between the 3 attempts


def _metric_name_unit(args) -> tuple[str, str]:
    """One source of truth for the metric identity, shared by the success
    and error paths (parent + child processes). Consults the model registry
    for the input kind; registry import touches no device backend."""
    objective = None
    try:
        from distributeddeeplearning_tpu.models import model_spec
        spec = model_spec(args.model)
        if spec.input_kind == "tokens":
            objective = spec.objective
    except Exception:
        name = args.model  # best effort when the registry import fails
        if "bert" in name:
            objective = "mlm"
        elif "gpt" in name or "llama" in name:
            objective = "causal"
    if objective:
        # The head mode is part of the measurement protocol: gN = gather
        # head over N positions (canonical BERT), no suffix = dense logits.
        # Keeps gather-mode rows from being compared against the dense-head
        # numbers recorded under the unsuffixed name.
        from distributeddeeplearning_tpu.config import (
            resolve_mlm_max_predictions)
        mp = resolve_mlm_max_predictions(
            args.mlm_max_predictions, args.seq_len, objective)
        gather = f"_g{mp}" if mp > 0 else ""
        return (f"{args.model}_{objective}_s{args.seq_len}{gather}"
                f"_seqs_per_sec_per_chip", "sequences/sec/chip")
    return (f"{args.model}_imagenet_images_per_sec_per_chip",
            "images/sec/chip")


def _child(args) -> int:
    """Run the actual measurement; prints the one JSON metric line."""
    import jax

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        jax.config.update("jax_platforms", args.platform)

    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    from distributeddeeplearning_tpu.config import resolve_mlm_max_predictions

    n_dev = jax.device_count()
    spec = model_spec(args.model)
    tokens = spec.input_kind == "tokens"
    mlm_pred = resolve_mlm_max_predictions(
        args.mlm_max_predictions, args.seq_len, spec.objective)
    data = (DataConfig(synthetic=True, dataset="mlm", seq_len=args.seq_len,
                       mlm_max_predictions=mlm_pred)
            if tokens else DataConfig(synthetic=True))
    cfg = TrainConfig(
        model=args.model,
        global_batch_size=args.batch_size * n_dev,
        dtype="bfloat16",
        log_every=10**9,  # silent; bench prints exactly one line
        attention_impl=args.attention_impl,
        remat=args.remat,
        steps_per_loop=args.steps_per_loop,
        parallel=ParallelConfig(data=n_dev),
        data=data)

    summary = loop.run(
        cfg, total_steps=args.warmup_steps + args.steps,
        warmup_steps=args.warmup_steps,
        logger=MetricLogger(enabled=False))

    value = summary["examples_per_sec_per_chip"]
    metric, unit = _metric_name_unit(args)
    # The 1450 img/s denominator is specifically the V100 ResNet50 AMP
    # figure — comparing any other model against it would be meaningless,
    # so vs_baseline is emitted only for the metric of record.
    print(json.dumps({
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": (round(value / V100_AMP_RESNET50_IMAGES_PER_SEC, 4)
                        if args.model == "resnet50" else None),
    }), flush=True)
    return 0


def _emit_error(args, msg: str) -> None:
    metric, unit = _metric_name_unit(args)
    print(json.dumps({
        "metric": metric,
        "value": None,
        "unit": unit,
        "vs_baseline": None,
        "error": msg[-800:],
    }), flush=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    # 512/chip is the measured v5e sweet spot: 2325 img/s/chip vs 1341 at
    # 256 and 1978 at 1024 (2026-07-29 sweep on the tunneled chip) — large
    # enough to amortize per-step dispatch latency, small enough to stay
    # HBM-friendly.
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--seq-len", type=int, default=512,
                   help="sequence length for token (BERT) models")
    p.add_argument("--mlm-max-predictions", type=int, default=-1,
                   help="gather-mode MLM head width; -1 = auto "
                        "(round(0.15*seq_len), the canonical BERT recipe), "
                        "0 = dense full-sequence logits")
    p.add_argument("--attention-impl", default=None,
                   choices=[None, "dense", "flash", "ring"],
                   help="attention implementation for token models")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize transformer layers in backward")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup-steps", type=int, default=10)
    # Measured 2026-07-30 on the tunneled v5e chip: per-step async dispatch
    # already pipelines (2319 img/s) and BEATS the fused lax.scan program
    # (1313 rolled / 2022 unrolled at K=5) — the queue keeps the chip fed,
    # and the fused carry costs more than the dispatches save. Default 1;
    # the knob exists for genuinely dispatch-bound setups.
    p.add_argument("--steps-per-loop", type=int, default=1,
                   help="train steps fused into one XLA program via "
                        "lax.scan (steps_per_loop); >1 helps only when "
                        "per-step dispatch is the bottleneck")
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. cpu) for smoke runs")
    p.add_argument("--attempt-timeout", type=int, default=480,
                   help="hard wall-clock limit per measurement attempt (s); "
                        "a live-chip run measures in ~240 s, and a hanging "
                        "backend must leave the parent time to print the "
                        "error record before any outer driver timeout")
    p.add_argument("--attempts", type=int, default=3)
    p.add_argument("--budget", type=int, default=1200,
                   help="total wall-clock budget across all attempts (s); "
                        "guarantees the error record is printed before any "
                        "outer driver timeout can strike")
    p.add_argument("--run-child", action="store_true", help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.run_child:
        return _child(args)

    child_cmd = [sys.executable, os.path.abspath(__file__), "--run-child",
                 "--model", args.model,
                 "--batch-size", str(args.batch_size),
                 "--seq-len", str(args.seq_len),
                 "--steps", str(args.steps),
                 "--warmup-steps", str(args.warmup_steps),
                 "--steps-per-loop", str(args.steps_per_loop),
                 "--mlm-max-predictions", str(args.mlm_max_predictions)]
    if args.platform:
        child_cmd += ["--platform", args.platform]
    if args.attention_impl:
        child_cmd += ["--attention-impl", args.attention_impl]
    if args.remat:
        child_cmd += ["--remat"]

    last_err = "no attempt ran"
    deadline = time.monotonic() + args.budget
    for attempt in range(args.attempts):
        if attempt:
            time.sleep(RETRY_BACKOFF_SEC[min(attempt - 1,
                                             len(RETRY_BACKOFF_SEC) - 1)])
        remaining = deadline - time.monotonic()
        if remaining < 30:
            last_err += "; budget exhausted"
            break
        try:
            proc = subprocess.run(
                child_cmd, capture_output=True, text=True,
                timeout=min(args.attempt_timeout, remaining))
            stdout, stderr, rc = proc.stdout, proc.stderr, proc.returncode
        except subprocess.TimeoutExpired as e:
            # The child may have printed its metric line and then hung in
            # backend teardown (the classic remote-TPU failure mode) — scan
            # the captured-so-far stdout before declaring the attempt dead;
            # keep stderr too so the hung child's traceback reaches the
            # error record.
            def _text(buf):
                return (buf.decode(errors="replace")
                        if isinstance(buf, bytes) else buf or "")
            stdout, stderr = _text(e.stdout), _text(e.stderr)
            rc = f"timeout {min(args.attempt_timeout, int(remaining))}s"
        # Find the metric line: last stdout line that parses as JSON.
        for line in reversed(stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    json.loads(line)
                except ValueError:
                    continue
                print(line, flush=True)
                return 0
        tail = (stderr or stdout or "").strip()
        last_err = f"attempt {attempt + 1}: rc={rc}: {tail[-600:]}"

    _emit_error(args, last_err)
    return 0


if __name__ == "__main__":
    sys.exit(main())
