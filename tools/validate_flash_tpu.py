#!/usr/bin/env python
"""On-hardware validation of the Pallas flash-attention kernel (VERDICT r1
#6): run the COMPILED forward+backward on the TPU at BERT-base shapes and
compare against the dense attention reference, then time both.

Prints one JSON line per check; exits nonzero on any correctness failure.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

# Runnable from anywhere without touching PYTHONPATH (which carries the
# platform plugin on axon machines).
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def dense_ref(q, k, v, mask):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (d ** -0.5)
    s = jnp.where(mask[:, None, None, :], s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _sync(out):
    # device_get is a true execution barrier; block_until_ready on a
    # remote-tunneled device can return while programs are still in flight
    # (same caveat as train/loop.py's timing window).
    jax.device_get(jax.tree_util.tree_map(lambda x: x.ravel()[0], out))


def timed(fn, *args, iters=20):
    _sync(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def main() -> int:
    from distributeddeeplearning_tpu.ops.flash_attention import flash_attention

    backend = jax.default_backend()
    if backend != "tpu":
        print(json.dumps({"error": f"need TPU, got {backend}"}))
        return 1

    B, S, H, D = 8, 512, 12, 64  # BERT-base attention shapes
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
    # Padding mask with ragged valid lengths, incl. one fully-valid row.
    lens = np.r_[S, rng.integers(S // 4, S, B - 1)]
    mask = jnp.asarray(np.arange(S)[None, :] < lens[:, None])

    flash = jax.jit(functools.partial(flash_attention, interpret=False))
    dense = jax.jit(dense_ref)

    out_f = np.asarray(flash(q, k, v, mask), np.float32)
    out_d = np.asarray(dense(q, k, v, mask), np.float32)
    valid = np.asarray(mask)[:, :, None, None]
    fwd_err = float(np.abs((out_f - out_d) * valid).max())
    ok_fwd = fwd_err < 2e-2  # bf16 inputs, f32 accumulation
    print(json.dumps({"check": "forward", "max_abs_err": fwd_err,
                      "ok": ok_fwd}), flush=True)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, mask, interpret=False)
        return (o.astype(jnp.float32) * valid ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_ref(q, k, v, mask).astype(jnp.float32) * valid ** 2).sum()

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    gerrs = {}
    ok_bwd = True
    for name, a, b in zip("dq dk dv".split(), gf, gd):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(np.abs(b).max(), 1.0)
        err = float(np.abs(a - b).max() / scale)
        gerrs[name] = err
        ok_bwd &= err < 3e-2
    print(json.dumps({"check": "backward", "rel_err": gerrs, "ok": ok_bwd}),
          flush=True)

    # Causal path (GPT): compiled kernel vs causal dense reference.
    def dense_causal(q, k, v, mask):
        d = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (d ** -0.5)
        tri = jnp.tril(jnp.ones((S, S), bool))
        keep = mask[:, None, None, :] & tri[None, None]
        s = jnp.where(keep, s, jnp.finfo(jnp.float32).min)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    flash_c = jax.jit(functools.partial(flash_attention, interpret=False,
                                        causal=True))
    out_fc = np.asarray(flash_c(q, k, v, mask), np.float32)
    out_dc = np.asarray(jax.jit(dense_causal)(q, k, v, mask), np.float32)
    causal_err = float(np.abs((out_fc - out_dc) * valid).max())
    ok_causal = causal_err < 2e-2
    print(json.dumps({"check": "causal_forward", "max_abs_err": causal_err,
                      "ok": ok_causal}), flush=True)

    # In-kernel hash dropout (round-4 semantics closure): the COMPILED
    # Mosaic lowering of the uint32 mixer must (a) exist, (b) agree with
    # the jnp-built mask (the oracle the CPU suite pins all impls to —
    # agreement here closes the chain compiled==jnp==interpret), and
    # (c) cost little (5 VPU ops per element; timing printed below).
    from distributeddeeplearning_tpu.ops.hash_dropout import dense_keep_mask
    RATE, SEED = 0.1, jnp.int32(20260731)
    flash_do = jax.jit(functools.partial(
        flash_attention, interpret=False, dropout_rate=RATE,
        dropout_seed=SEED))
    out_do = np.asarray(flash_do(q, k, v, mask), np.float32)
    km = np.asarray(dense_keep_mask(SEED, B, H, S, S, RATE))
    p_ref = jax.nn.softmax(jnp.where(
        jnp.asarray(np.asarray(mask))[:, None, None, :],
        jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (D ** -0.5),
        jnp.finfo(jnp.float32).min), axis=-1)
    p_ref = jnp.where(jnp.asarray(km), p_ref / (1 - RATE), 0.0)
    out_do_ref = np.asarray(jnp.einsum(
        "bhqk,bkhd->bqhd", p_ref, v.astype(jnp.float32)), np.float32)
    do_err = float(np.abs((out_do - out_do_ref) * valid).max())
    ok_dropout = do_err < 2e-2
    print(json.dumps({"check": "dropout_forward_compiled_vs_hash_ref",
                      "max_abs_err": do_err, "ok": ok_dropout,
                      "dropped_frac_ref": round(1.0 - float(km.mean()), 4)}),
          flush=True)

    t_flash = timed(flash, q, k, v, mask)
    t_flash_do = timed(flash_do, q, k, v, mask)
    t_dense = timed(dense, q, k, v, mask)
    grad_f = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
    grad_d = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))
    t_flash_bwd = timed(grad_f, q, k, v)
    t_dense_bwd = timed(grad_d, q, k, v)
    print(json.dumps({
        "check": "timing", "shape": [B, S, H, D],
        "fwd_ms": {"flash": round(t_flash * 1e3, 3),
                   "flash_dropout": round(t_flash_do * 1e3, 3),
                   "dense": round(t_dense * 1e3, 3)},
        "fwd_bwd_ms": {"flash": round(t_flash_bwd * 1e3, 3),
                       "dense": round(t_dense_bwd * 1e3, 3)},
    }), flush=True)
    return 0 if (ok_fwd and ok_bwd and ok_causal and ok_dropout) else 1


if __name__ == "__main__":
    sys.exit(main())
