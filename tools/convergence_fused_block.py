#!/usr/bin/env python
"""Recipe-level convergence A/B for the conv-epilogue fusion (--fused-block).

The per-step numerics tests (tests/test_fused_block.py) prove gradient
parity to rounding; this tool proves the thing a user actually cares
about: the fused path TRAINS the same — same eval-top-1 trajectory over
an epochs-scaled schedule on the learnable-synthetic task, same seeds,
same optimizer/schedule, toggling only the flag.

Runs both arms through the shard_map path (dp=1 — see the dp note) (the shard_map path, where the
off-TPU jnp twins keep CPU wall-clock sane) on resnet26_thin — the
CPU-tractable bottleneck carrier with the exact block structure of
resnet50.

  python tools/convergence_fused_block.py [--epochs 8]
      [--epoch-examples 8192] [--out /tmp/convergence_fused_block.json]

One JSON line per arm, then a summary with the per-epoch eval curves and
the final-top1 delta.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pin_cpu_mesh(n: int = 4) -> None:
    from distributeddeeplearning_tpu.hostmesh import pin_virtual_cpu_mesh

    pin_virtual_cpu_mesh(n)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--epoch-examples", type=int, default=8192)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--eval-batches", type=int, default=8)
    p.add_argument("--out", default="/tmp/convergence_fused_block.json")
    p.add_argument("--arm", default="both",
                   choices=["both", "unfused", "fused"],
                   help="run one arm only (fresh process per arm sidesteps\
 the XLA:CPU in-process collective watchdog on long oversubscribed runs)")
    args = p.parse_args(argv)

    _pin_cpu_mesh(4)

    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    steps_per_epoch = args.epoch_examples // args.batch
    total = steps_per_epoch * args.epochs

    def run_one(fused: bool):
        cfg = TrainConfig(
            model="resnet26_thin", global_batch_size=args.batch,
            dtype="float32", log_every=10**9, seed=7, fused_block=fused,
            steps_per_epoch=steps_per_epoch, eval_every_epochs=1.0,
            # dp=1: XLA:CPU in-process collectives hard-abort (40 s
            # rendezvous termination) when a concurrent compile starves
            # their threads on this one-core box — measured at dp=8 AND
            # dp=4. One shard has no rendezvous; the A/B compares the two
            # arms at equal dp, and the shard_map path (jnp twins) is
            # still the one exercised.
            parallel=ParallelConfig(data=1),
            data=DataConfig(synthetic=True, image_size=args.image_size,
                            num_classes=args.num_classes,
                            synthetic_learnable=True),
            optimizer=OptimizerConfig(
                name="sgd", learning_rate=0.1, reference_batch=256,
                momentum=0.9, schedule="warmup_cosine", warmup_epochs=1.0,
                weight_decay=1e-4, label_smoothing=0.1))
        t0 = time.time()
        summary = loop.run(cfg, total_steps=total,
                           eval_batches=args.eval_batches,
                           logger=MetricLogger(enabled=False))
        rec = {
            "arm": "fused_block" if fused else "unfused",
            "steps": total,
            "eval_curve": summary.get("evals"),
            "final_top1": summary.get("eval_top1"),
            "final_loss": summary["final_metrics"].get("loss"),
            "wall_s": round(time.time() - t0, 1),
        }
        print(json.dumps(rec), flush=True)
        return rec

    if args.arm != "both":
        run_one(args.arm == "fused")
        return 0
    a = run_one(False)
    b = run_one(True)
    delta = (None if a["final_top1"] is None or b["final_top1"] is None
             else round(b["final_top1"] - a["final_top1"], 4))
    out = {"summary": {
        "epochs": args.epochs, "epoch_examples": args.epoch_examples,
        "unfused_final_top1": a["final_top1"],
        "fused_final_top1": b["final_top1"],
        "delta_top1": delta,
        "unfused_curve": a["eval_curve"], "fused_curve": b["eval_curve"],
    }}
    print(json.dumps(out), flush=True)
    with open(args.out, "w") as f:
        json.dump({"arms": [a, b], **out}, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
