#!/usr/bin/env python
"""Where did my latency go? — attribution report over a serve trace.

    python tools/trace_report.py --serve TRACE_OR_DIR [--json] [--top N]

Reads the Chrome trace(s) a traced serve run wrote (``--trace-dir`` on
tools/bench_serve.py, ``--serve-trace-dir`` on launch.py, or an
already-merged ``trace.merged.json``) and reports the per-request
latency decomposition the engine's tracer emitted (docs/serve_tracing.md):

  * the per-request table — TTFT, total latency, and each attribution
    component (queue / admission_stall / prefill / interference /
    decode), slowest TTFT first;
  * aggregate p50/p99/mean per component, over TTFT and total latency;
  * the **critical-path table for the p99 tail**: mean component shares
    of TTFT among the requests at/above the p99, next to the same shares
    over the whole population — the component whose share GROWS in the
    tail is where the p99 went;
  * cross-process flow links — requests re-dispatched after a replica
    death, whose one flow id spans two replica pids in the merged trace.

The components are exhaustive by construction (they sum to the measured
latency within float error; the ``sum_err_s`` field in every attribution
instant is the proof), so the tables account for *all* wall-clock, not a
sampled subset. Pure stdlib + the telemetry loaders; no jax, safe to run
on a laptop against a pulled artifact.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.observability import perf_report  # noqa: E402
from distributeddeeplearning_tpu.observability import telemetry  # noqa: E402
from distributeddeeplearning_tpu.observability.metrics import (  # noqa: E402
    percentile)
from distributeddeeplearning_tpu.serve import tracing  # noqa: E402


def expand(target: str) -> list[str]:
    """A trace file, or a --trace-dir directory (its ``trace.p*.json``
    set, falling back to an already-merged ``trace.merged.json``)."""
    if not os.path.isdir(target):
        return [target]
    found = sorted(glob.glob(os.path.join(target, "trace.p*.json")))
    if not found:
        merged = os.path.join(target, "trace.merged.json")
        if os.path.exists(merged):
            found = [merged]
    return found


def serve_report(events: list[dict]) -> dict:
    """The attribution tables from one event set (see module doc)."""
    reqs = [dict(e.get("args", {}), pid=e.get("pid"))
            for e in events
            if e.get("ph") == "i" and e.get("name") == "serve:attribution"]
    with_ttft = [r for r in reqs if r.get("ttft_s") is not None]
    ttfts = [r["ttft_s"] for r in with_ttft]
    totals = [r["total_s"] for r in reqs if r.get("total_s") is not None]

    agg = {"requests": len(reqs), "with_first_token": len(with_ttft),
           "ttft_s": {"p50": percentile(ttfts, 50),
                      "p99": percentile(ttfts, 99)},
           "total_s": {"p50": percentile(totals, 50),
                       "p99": percentile(totals, 99)},
           "components": {}}
    for c in tracing.COMPONENTS:
        tvals = [r["ttft_components"].get(c, 0.0) for r in with_ttft]
        avals = [r["components"].get(c, 0.0) for r in reqs
                 if r.get("components")]
        agg["components"][c] = {
            "ttft": {"p50": percentile(tvals, 50),
                     "p99": percentile(tvals, 99),
                     "mean": (sum(tvals) / len(tvals)) if tvals else None},
            "total": {"p50": percentile(avals, 50),
                      "p99": percentile(avals, 99),
                      "mean": (sum(avals) / len(avals)) if avals else None},
        }

    # Critical path at the p99 tail: component shares of TTFT among the
    # requests at/above the p99, vs the same shares over everybody. The
    # component whose share grows in the tail is the p99's bottleneck.
    tail = {}
    p99 = agg["ttft_s"]["p99"]
    if p99 is not None:
        tail_reqs = [r for r in with_ttft if r["ttft_s"] >= p99]

        def shares(rows):
            sums = {c: sum(r["ttft_components"].get(c, 0.0) for r in rows)
                    for c in tracing.COMPONENTS}
            denom = sum(sums.values()) or 1.0
            return {c: v / denom for c, v in sums.items()}

        body_share, tail_share = shares(with_ttft), shares(tail_reqs)
        tail = {
            "threshold_ttft_s": p99,
            "requests": [r.get("trace") for r in tail_reqs],
            "shares": {c: {"all": round(body_share[c], 4),
                           "p99_tail": round(tail_share[c], 4),
                           "delta": round(tail_share[c] - body_share[c], 4)}
                       for c in tracing.COMPONENTS},
            "dominant": max(tracing.COMPONENTS, key=lambda c: tail_share[c]),
        }

    flow_pids: dict = {}
    for e in events:
        if e.get("ph") in ("s", "t", "f") and e.get("cat") == "serve":
            flow_pids.setdefault(e.get("id"), set()).add(e.get("pid"))
    cross = [{"id": fid, "pids": sorted(pids, key=str)}
             for fid, pids in sorted(flow_pids.items(), key=lambda kv:
                                     str(kv[0]))
             if len(pids) > 1]

    max_err = max((abs(r.get("sum_err_s", 0.0)) for r in reqs),
                  default=0.0)
    return {"requests": sorted(reqs, key=lambda r:
                               -(r.get("ttft_s") or r.get("total_s") or 0)),
            "aggregate": agg, "p99_critical_path": tail,
            "cross_process_flows": cross,
            "max_sum_err_s": max_err}


def print_report(rep: dict, top: int) -> None:
    agg = rep["aggregate"]
    print(f"{agg['requests']} request(s), {agg['with_first_token']} with "
          f"a first token, max attribution sum error "
          f"{rep['max_sum_err_s'] * 1e3:.4f} ms")
    t, tot = agg["ttft_s"], agg["total_s"]
    if t["p50"] is not None:
        print(f"TTFT p50 {t['p50']:.4f}s  p99 {t['p99']:.4f}s;  "
              f"total p50 {tot['p50']:.4f}s  p99 {tot['p99']:.4f}s")

    rows = rep["requests"][:top]
    if rows:
        comps = list(tracing.COMPONENTS)
        hdr = "".join(f"{c[:10]:>12}" for c in comps)
        print(f"\nslowest {len(rows)} by TTFT:")
        print(f"{'trace':>8}{'status':>10}{'ttft_s':>10}{'total_s':>10}"
              f"{hdr}  (component seconds, of total)")
        for r in rows:
            comp = r.get("components", {})
            ttft = r.get("ttft_s")
            print(f"{str(r.get('trace')):>8}{r.get('status', '?'):>10}"
                  f"{(f'{ttft:.4f}' if ttft is not None else '-'):>10}"
                  f"{r.get('total_s', 0.0):>10.4f}"
                  + "".join(f"{comp.get(c, 0.0):>12.4f}" for c in comps))

    print("\nTTFT components (p50 / p99 / mean seconds):")
    for c in tracing.COMPONENTS:
        s = agg["components"][c]["ttft"]
        if s["mean"] is None:
            continue
        print(f"  {c:<18}{s['p50']:>10.4f}{s['p99']:>10.4f}"
              f"{s['mean']:>10.4f}")

    cp = rep["p99_critical_path"]
    if cp:
        print(f"\np99 critical path (TTFT >= {cp['threshold_ttft_s']:.4f}s, "
              f"{len(cp['requests'])} request(s)):")
        print(f"  {'component':<18}{'share(all)':>12}{'share(p99)':>12}"
              f"{'delta':>8}")
        for c in tracing.COMPONENTS:
            s = cp["shares"][c]
            mark = "  <- dominant" if c == cp["dominant"] else ""
            print(f"  {c:<18}{s['all']:>12.1%}{s['p99_tail']:>12.1%}"
                  f"{s['delta']:>+8.1%}{mark}")

    if rep["cross_process_flows"]:
        print("\ncross-process requests (re-dispatched after a replica "
              "death):")
        for f in rep["cross_process_flows"]:
            print(f"  flow id {f['id']}  pids {f['pids']}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--serve", metavar="TRACE_OR_DIR", required=True,
                   help="serve trace file, or trace dir "
                        "(trace.p*.json / trace.merged.json)")
    p.add_argument("--json", action="store_true",
                   help="one machine-readable JSON object instead of "
                        "tables")
    p.add_argument("--top", type=int, default=10,
                   help="rows in the per-request table (slowest first)")
    args = p.parse_args(argv)
    paths = expand(args.serve)
    if not paths:
        p.error(f"no trace.p*.json or trace.merged.json under "
                f"{args.serve}")
    events: list[dict] = []
    load_errors: list[str] = []
    for path in paths:
        evs, err = telemetry.load_events_tolerant(path)
        events.extend(evs)
        if err:
            load_errors.append(err)
    rep = serve_report(events)
    rep["files"], rep["load_errors"] = paths, load_errors
    # with_backend=False: a trace reader must never import jax.
    if rep["aggregate"]["requests"]:
        perf_report.annotate(rep, provenance="fresh", with_backend=False)
    else:
        rep["error"] = ("; ".join(load_errors)
                        or "no serve:attribution events — was the run "
                           "traced? (bench_serve --trace-dir / launch.py "
                           "--serve-trace-dir)")
        perf_report.annotate(rep, provenance="error", with_backend=False)
    if args.json:
        print(json.dumps(rep))
    else:
        for err in load_errors:
            print(f"WARNING: {err} — tables below are incomplete")
        if rep["aggregate"]["requests"]:
            print_report(rep, args.top)
        else:
            print(rep["error"], file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
