#!/usr/bin/env python
"""Op-level device-time profile of a train step (the BASELINE.md method).

Runs a few steps of any config under ``jax.profiler.trace`` with a perfetto
JSON trace, then aggregates on-device slice durations by a coarse op family
(conv/matmul fusions, BN-ish reduce fusions, elementwise passes, Pallas
custom calls, copies, infeed). This is how "where the step goes" tables in
BASELINE.md are produced; it needs a live chip to say anything about TPU.

    python tools/profile_step.py --model resnet50 --batch-size 256 \
        [--fused-bn] [--steps 6] [--top 25]

Prints one JSON line: total device ms/step and a per-family + per-op-top-N
breakdown (ms/step, averaged over the traced steps).
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_and_trace(args, log_dir: str) -> None:
    import jax

    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig, resolve_mlm_max_predictions)
    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.train import loop

    n_dev = jax.device_count()
    spec = model_spec(args.model)
    tokens = spec.input_kind == "tokens"
    mlm = resolve_mlm_max_predictions(-1, args.seq_len, spec.objective)
    data = (DataConfig(synthetic=True, dataset="mlm", seq_len=args.seq_len,
                       mlm_max_predictions=mlm)
            if tokens else DataConfig(synthetic=True))
    cfg = TrainConfig(
        model=args.model, global_batch_size=args.batch_size * n_dev,
        dtype="bfloat16", log_every=10**9, fused_bn=args.fused_bn,
        fused_block=args.fused_block,
        fused_conv3=getattr(args, "fused_conv3", False),
        attention_impl=args.attention_impl, remat=args.remat,
        parallel=ParallelConfig(data=n_dev), data=data)
    mesh, model, batch_shd, state, train_step, sched, rng = loop.build(
        cfg, args.warmup + args.steps)
    source = datalib.make_source(cfg, spec.input_kind, batch_shd,
                                 objective=spec.objective)
    i = 0
    metrics = None
    for _ in range(args.warmup):
        state, metrics = train_step(state, source.batch(i), rng)
        i += 1
    jax.device_get(metrics)
    with jax.profiler.trace(log_dir, create_perfetto_trace=True):
        for _ in range(args.steps):
            state, metrics = train_step(state, source.batch(i), rng)
            i += 1
        jax.device_get(metrics)


FAMILIES = (
    # (family, compiled regex over slice name + HLO metadata) — first
    # match wins. conv_matmul outranks the reduce/elementwise families
    # because an XLA *fusion* slice whose metadata mentions a convolution
    # or dot is MXU work with fused epilogues, not an elementwise pass —
    # classifying those by the bare "fusion"/"convert_reduce" slice name
    # is exactly how the round-2 profile undercounted conv time
    # (BASELINE.md's MFU-correction note).
    ("pallas", re.compile(r"custom-call|pallas|tpu_custom_call")),
    ("conv_matmul", re.compile(
        r"convolution|conv_general|dot_general|dot\b|matmul|cudnn|mxu")),
    ("bn_reduce", re.compile(r"convert_reduce|reduce")),
    ("elementwise", re.compile(
        r"fusion|add|multiply|maximum|select|convert|divide|subtract|rsqrt")),
    ("copy_reshape", re.compile(r"copy|bitcast|reshape|transpose|pad|slice")),
    ("infeed_outfeed", re.compile(r"infeed|outfeed|transfer")),
)


def classify(name: str, meta: str = "") -> str:
    """Family for a trace slice. ``meta`` is the stringified event args —
    jax's perfetto traces carry the HLO long name / source expression
    there, which reveals what a generically-named fusion actually
    computes."""
    low = f"{name} {meta}".lower()
    for fam, pat in FAMILIES:
        if pat.search(low):
            return fam
    return "other"


def summarize(log_dir: str, steps: int, top: int):
    paths = glob.glob(os.path.join(
        log_dir, "**", "*perfetto_trace.json.gz"), recursive=True)
    if not paths:
        raise FileNotFoundError(f"no perfetto trace under {log_dir}")
    with gzip.open(sorted(paths)[-1], "rt") as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    # Keep complete slices from device tracks (TPU/device PIDs). Perfetto
    # process names live in metadata events; device tracks are named like
    # "/device:TPU:0" / "TPU:0" / "Device N".
    pid_names = {}
    tid_names = {}
    for ev in events:
        if ev.get("ph") != "M":
            continue
        if ev.get("name") == "process_name":
            pid_names[ev.get("pid")] = ev.get("args", {}).get("name", "")
        elif ev.get("name") == "thread_name":
            tid_names[(ev.get("pid"), ev.get("tid"))] = (
                ev.get("args", {}).get("name", ""))
    device_pids = {pid for pid, name in pid_names.items()
                   if re.search(r"tpu|device|xla:#", name, re.I)
                   and not re.search(r"python|host", name, re.I)}
    # The device process carries several stacked tracks (XLA Modules, Steps,
    # XLA Ops, TraceMe); only the "XLA Ops" line holds leaf op slices —
    # summing all lines would double-count every nesting level.
    op_keys = {key for key, name in tid_names.items()
               if key[0] in device_pids and "op" in name.lower()}
    per_op = collections.Counter()
    op_meta: dict = {}
    for ev in events:
        if ev.get("ph") != "X" or (ev.get("pid"), ev.get("tid")) not in op_keys:
            continue
        name = ev.get("name", "?")
        per_op[name] += ev.get("dur", 0)  # microseconds
        if name not in op_meta and ev.get("args"):
            op_meta[name] = " ".join(str(v) for v in ev["args"].values())
    if not per_op:  # fall back: no recognized op track
        for ev in events:
            if ev.get("ph") == "X":
                per_op[ev.get("name", "?")] += ev.get("dur", 0)
    fam = collections.Counter()
    for name, us in per_op.items():
        fam[classify(name, op_meta.get(name, ""))] += us
    total_ms = sum(per_op.values()) / 1000 / steps
    return {
        "device_ms_per_step": round(total_ms, 2),
        "by_family_ms": {k: round(v / 1000 / steps, 2)
                         for k, v in fam.most_common()},
        "top_ops_ms": {name: round(us / 1000 / steps, 2)
                       for name, us in per_op.most_common(top)},
        "device_tracks": sorted(pid_names[p] for p in device_pids),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--attention-impl", default=None)
    p.add_argument("--remat", action="store_true")
    p.add_argument("--fused-bn", action="store_true")
    p.add_argument("--fused-block", action="store_true")
    p.add_argument("--fused-conv3", action="store_true")
    p.add_argument("--warmup", type=int, default=4)
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--keep-trace", default=None,
                   help="directory to keep the raw trace in (default: tmp)")
    args = p.parse_args(argv)

    log_dir = args.keep_trace or tempfile.mkdtemp(prefix="ddl_profile_")
    t0 = time.time()
    run_and_trace(args, log_dir)
    out = summarize(log_dir, args.steps, args.top)
    out["model"] = args.model
    out["batch_per_chip"] = args.batch_size
    out["fused_bn"] = args.fused_bn
    out["fused_block"] = args.fused_block
    out["fused_conv3"] = args.fused_conv3
    # Analytic-MFU cross-check against DEVICE-BUSY time (not wall):
    # by_family_ms should roughly partition this much useful work.
    try:
        from distributeddeeplearning_tpu.config import (
            resolve_mlm_max_predictions)
        from distributeddeeplearning_tpu.models import flops as flopslib
        from distributeddeeplearning_tpu.models import model_spec
        spec = model_spec(args.model)
        mlm = (resolve_mlm_max_predictions(-1, args.seq_len,
                                           spec.objective)
               if spec.input_kind == "tokens" else 0)
        per_ex = flopslib.train_flops_per_example(
            args.model, seq_len=args.seq_len, mlm_positions=mlm)
        if per_ex and len(out.get("device_tracks", [])) == 1:
            busy_s = out["device_ms_per_step"] / 1e3
            tflops = args.batch_size * per_ex / busy_s / 1e12
            out["busy_tflops_per_sec"] = round(tflops, 2)
            import jax
            peak = flopslib.bf16_peak_flops(
                jax.devices()[0].device_kind)
            if peak:
                out["busy_mfu_pct"] = round(
                    100.0 * tflops * 1e12 / peak, 1)
    except Exception:
        pass
    out["wall_s"] = round(time.time() - t0, 1)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
