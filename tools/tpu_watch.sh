#!/bin/bash
# Poll for the axon TPU tunnel; the moment a probe succeeds, launch the
# batched measurement script (tools/chip_window.sh) and exit.
#
# Usage: tools/tpu_watch.sh [deadline_seconds]   (default 10.5h)
# The deadline exists so the poller can never contend with the driver's own
# end-of-round bench run. Probes use `timeout 45` because a down tunnel makes
# `jax.devices()` hang indefinitely rather than fail fast.
set -u
cd "$(dirname "$0")/.."
DEADLINE=${1:-37800}
START=$(date +%s)
LOG=.chip_results/watch.log
mkdir -p .chip_results
echo "[$(date +%H:%M:%S)] watcher start, deadline ${DEADLINE}s" >> "$LOG"
while :; do
  now=$(date +%s)
  if (( now - START > DEADLINE )); then
    echo "[$(date +%H:%M:%S)] deadline reached, no window" >> "$LOG"
    exit 1
  fi
  if timeout 45 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
      >> "$LOG" 2>&1; then
    # Hard stop for the window script's extended batch: 30 min past this
    # watcher's own deadline — a window opening late still lands the
    # headline+A/B prefix but can never contend with the driver's
    # end-of-round bench for the chip.
    STOP=$((START + DEADLINE + 1800))
    echo "[$(date +%H:%M:%S)] TUNNEL UP — launching chip_window.sh" \
         "(hard stop $STOP)" >> "$LOG"
    nohup bash tools/chip_window.sh .chip_results "$STOP" >> "$LOG" 2>&1 &
    exit 0
  fi
  sleep 90
done
