#!/usr/bin/env python
"""Per-phase breakdown of a telemetry Chrome-trace JSON.

    python tools/summarize_trace.py TRACE.json [TRACE2.json ...] [--json]
    python tools/summarize_trace.py TRACE_DIR [--json]

Reads trace files written by --trace-dir (train.py, bench.py, or a
launch.py-merged chaos run) and prints, per file set:

  * the per-phase span table — count, total ms, mean ms, and share of
    the summed span time (where does a step's wall clock go?);
  * the instant-event timeline — faults fired, launcher restarts,
    straggler warnings, preemptions — in monotonic-clock order;
  * counter tracks (HBM gauges, cumulative counts) as last-value + peak.

A directory argument expands to its ``trace.p*.json`` files (the
--trace-dir layout, which the serve stack's per-replica traces share);
a directory holding only an already-merged ``trace.merged.json`` (a
pulled serve artifact) falls back to that. Serve traces additionally
get a **flow** summary: flow chains (``s``/``t``/``f`` events — one per
request, docs/serve_tracing.md) grouped by id, flagging the chains that
span more than one process — a re-dispatched request after a replica
death shows up here as one id with two pids. Async request tracks
(``b``/``e``) are checked for pairing; an unmatched begin means the
request never retired. Truncated files are salvaged event-by-event and
reported, not fatal — a post-mortem's trace is exactly the one most
likely to be damaged.

``--json`` emits one machine-readable object in the
observability/perf_report.py record schema (``provenance`` is ``fresh``
when every file parsed clean, ``error`` when nothing could be read).
Pure stdlib + the telemetry module's loaders; no jax, safe anywhere.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.observability import perf_report  # noqa: E402
from distributeddeeplearning_tpu.observability import telemetry  # noqa: E402


def expand_traces(args: list[str]) -> list[str]:
    """Each argument is a trace file or a --trace-dir directory; a
    directory contributes its ``trace.p*.json`` files (sorted, so
    multi-process output is stable). An empty directory contributes
    nothing — the caller reports that, it is not an error here."""
    out: list[str] = []
    for a in args:
        if os.path.isdir(a):
            found = sorted(glob.glob(os.path.join(a, "trace.p*.json")))
            if not found:
                # A pulled serve artifact may hold only the supervisor's
                # merged file (its name deliberately dodges the
                # per-process glob so it is never double-counted).
                merged = os.path.join(a, "trace.merged.json")
                if os.path.exists(merged):
                    found = [merged]
            out.extend(found)
        else:
            out.append(a)
    return out


def summarize(paths: list[str]) -> dict:
    events: list[dict] = []
    load_errors: list[str] = []
    for p in paths:
        evs, err = telemetry.load_events_tolerant(p)
        events.extend(evs)
        if err:
            load_errors.append(err)
    phases = telemetry.phase_totals(events)
    instants = sorted((e for e in events if e.get("ph") == "i"),
                      key=lambda e: e.get("ts", 0))
    counters: dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "C":
            continue
        v = float(e.get("args", {}).get("value", 0.0))
        c = counters.setdefault(e["name"], {"last": v, "peak": v, "n": 0})
        c["last"] = v
        c["peak"] = max(c["peak"], v)
        c["n"] += 1
    pids = sorted({e.get("pid") for e in events if "pid" in e})
    return {
        "files": paths,
        "events": len(events),
        "load_errors": load_errors,
        "processes": pids,
        "phases": phases,
        "instants": [{"name": e["name"], "ts_us": e.get("ts", 0),
                      "pid": e.get("pid"), "args": e.get("args", {})}
                     for e in instants],
        "counters": counters,
        "flows": flow_summary(events),
    }


def flow_summary(events: list[dict]) -> dict:
    """Serve-trace request linkage: flow chains grouped by (cat, id) —
    the ones spanning >1 pid are re-dispatched requests whose life
    crossed a replica death — plus async b/e pairing (an unmatched begin
    is a request that never retired)."""
    chains: dict = {}
    for e in events:
        if e.get("ph") not in ("s", "t", "f"):
            continue
        c = chains.setdefault((e.get("cat", ""), e.get("id")),
                              {"name": e.get("name"), "pids": set(),
                               "phases": []})
        c["pids"].add(e.get("pid"))
        c["phases"].append(e["ph"])
    begun: dict = {}
    unmatched_ends = 0
    for e in events:
        if e.get("ph") == "b":
            begun[(e.get("cat", ""), e.get("id"), e.get("name"))] = True
        elif e.get("ph") == "e":
            k = (e.get("cat", ""), e.get("id"), e.get("name"))
            if begun.pop(k, None) is None:
                unmatched_ends += 1
    cross = sorted((key for key, c in chains.items()
                    if len(c["pids"]) > 1), key=lambda k: str(k[1]))
    return {
        "chains": len(chains),
        "cross_process": [
            {"id": key[1], "name": chains[key]["name"],
             "pids": sorted(chains[key]["pids"], key=str),
             "events": len(chains[key]["phases"])}
            for key in cross],
        "async_unclosed": sorted(str(k[1]) for k in begun),
        "async_unmatched_ends": unmatched_ends,
    }


def print_tables(s: dict) -> None:
    total_ms = sum(p["total_ms"] for p in s["phases"].values()) or 1.0
    print(f"{len(s['files'])} file(s), {s['events']} events, "
          f"processes {s['processes']}")
    for err in s.get("load_errors", ()):
        print(f"WARNING: {err} — totals below are incomplete")
    if s["phases"]:
        print(f"\n{'phase':<40}{'count':>8}{'total_ms':>12}"
              f"{'mean_ms':>10}{'share':>8}")
        for name, p in s["phases"].items():
            print(f"{name:<40}{p['count']:>8}{p['total_ms']:>12.2f}"
                  f"{p['mean_ms']:>10.3f}"
                  f"{p['total_ms'] / total_ms:>8.1%}")
    else:
        print("\nno complete spans")
    if s["instants"]:
        print("\ninstant events (monotonic order):")
        for e in s["instants"]:
            args = {k: v for k, v in e["args"].items()}
            print(f"  {e['ts_us'] / 1e6:>12.3f}s  p{e['pid']}  "
                  f"{e['name']}  {json.dumps(args) if args else ''}".rstrip())
    if s["counters"]:
        print("\ncounters (last / peak / samples):")
        for name in sorted(s["counters"]):
            c = s["counters"][name]
            print(f"  {name:<40}{c['last']:>16g}{c['peak']:>16g}"
                  f"{c['n']:>8}")
    fl = s.get("flows") or {}
    if fl.get("chains"):
        print(f"\nflow chains: {fl['chains']} "
              f"({len(fl['cross_process'])} cross-process)")
        for c in fl["cross_process"]:
            print(f"  id {c['id']}  {c['name']}  pids {c['pids']}  "
                  f"{c['events']} events  <- re-dispatched across "
                  f"processes")
    if fl.get("async_unclosed"):
        print(f"\nWARNING: {len(fl['async_unclosed'])} request track(s) "
              f"never closed (ids {fl['async_unclosed'][:8]}) — these "
              f"requests did not retire")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("traces", nargs="+",
                   help="Chrome-trace JSON file(s), or --trace-dir "
                        "directories (expanded to trace.p*.json)")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable JSON object (record "
                        "schema of observability/perf_report.py) instead "
                        "of tables")
    args = p.parse_args(argv)
    missing = [t for t in args.traces
               if not os.path.isdir(t) and not os.path.exists(t)]
    if missing:
        p.error(f"no such trace file or directory: {missing}")
    paths = expand_traces(args.traces)
    s = summarize(paths)
    if not paths:
        s["load_errors"].append(
            f"no trace.p*.json files under {args.traces} — nothing traced "
            f"yet, or the run wrote to a different --trace-dir")
    # This analysis is "fresh" only when every requested file parsed
    # clean; damage demotes nothing to stale (there is no cache here) but
    # an empty read is an error record, not a zero-phase measurement.
    # with_backend=False: a trace reader must never import jax.
    if s["events"]:
        perf_report.annotate(s, provenance="fresh", with_backend=False)
    else:
        s["error"] = "; ".join(s["load_errors"]) or "no events"
        perf_report.annotate(s, provenance="error", with_backend=False)
    if args.json:
        print(json.dumps(s))
    else:
        print_tables(s)
        for err in (s["load_errors"] if not s["events"] else ()):
            print(f"ERROR: {err}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
