#!/usr/bin/env python
"""Export a framework checkpoint to HuggingFace format (inverse of
tools/import_hf.py).

    python tools/export_hf.py --checkpoint-dir ckpt/run1 --model gpt2_small \
        --out /data/exported [--family gpt2] [--vocab-size N] [--seq-len N]

Restores the params subtree from the newest orbax checkpoint, inverts the
weight mapping (utils/hf_convert.py EXPORTERS), loads it into a
transformers model built from the matching config, and save_pretrained's
it — so anything that consumes HF checkpoints (including our own import
tool) can read a model fine-tuned here. Round-trip logit equality is
test-pinned (tests/test_hf_parity.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.utils import hf_convert

FAMILY_OF_MODEL = {"gpt2": "gpt2", "gpt": "gpt2", "bert": "bert",
                   "llama": "llama", "tinyllama": "llama"}


def _family(model_name: str, override):
    if override:
        return override
    for prefix, fam in FAMILY_OF_MODEL.items():
        if model_name.startswith(prefix):
            return fam
    raise SystemExit(f"cannot infer HF family from model {model_name!r}; "
                     f"pass --family {sorted(set(FAMILY_OF_MODEL.values()))}")


def hf_model_for(family: str, cfg):
    """transformers model matching our model config ``cfg``."""
    import transformers

    if family == "gpt2":
        return transformers.GPT2LMHeadModel(transformers.GPT2Config(
            vocab_size=cfg.vocab_size, n_positions=cfg.max_position,
            n_embd=cfg.hidden_size, n_layer=cfg.num_layers,
            n_head=cfg.num_heads, activation_function="gelu_new",
            layer_norm_epsilon=cfg.layer_norm_eps))
    if family == "bert":
        return transformers.BertForMaskedLM(transformers.BertConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            intermediate_size=cfg.intermediate_size,
            max_position_embeddings=cfg.max_position,
            type_vocab_size=cfg.type_vocab_size,
            layer_norm_eps=cfg.layer_norm_eps, hidden_act="gelu"))
    if family == "llama":
        return transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            num_key_value_heads=cfg.num_kv_heads,
            rms_norm_eps=cfg.rms_eps, rope_theta=cfg.rope_theta,
            attention_bias=False, mlp_bias=False,
            tie_word_embeddings=False))
    raise SystemExit(f"unsupported family {family!r}")


def export(model_name: str, checkpoint_dir: str, out_dir: str,
           family=None, vocab_size=None, seq_len=None) -> dict:
    import jax
    import jax.numpy as jnp
    import torch

    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    fam = _family(model_name, family)
    spec = model_spec(model_name)
    kw = {}
    if vocab_size:
        kw["vocab_size"] = vocab_size
    if seq_len:
        kw["seq_len"] = seq_len
    model = spec.build(dtype=jnp.float32, **kw)
    init = model.init({"params": jax.random.key(0)},
                      jnp.zeros((1, 8), jnp.int32), train=False)
    ckpt = Checkpointer(checkpoint_dir, every_steps=1)
    try:
        params = ckpt.restore_latest_params(init["params"])
    finally:
        ckpt.close()
    if params is None:
        raise SystemExit(f"no checkpoint in {checkpoint_dir!r}")

    import numpy as np

    np_params = jax.tree.map(lambda x: np.asarray(x, np.float32),
                             jax.device_get(params))
    sd = hf_convert.EXPORTERS[fam](np_params, model.cfg.num_layers)
    hf = hf_model_for(fam, model.cfg)
    missing, unexpected = hf.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v))
         for k, v in sd.items()}, strict=False)
    # strict=False only to tolerate non-parameter buffers (attn.bias masks,
    # position_ids); any MISSING parameter is a real mapping hole.
    missing = [m for m in missing if not m.endswith(
        (".attn.bias", ".attn.masked_bias", ".position_ids"))]
    if missing:
        raise SystemExit(f"export mapping incomplete; HF model is missing "
                         f"{missing[:8]}")
    hf.save_pretrained(out_dir)
    return {"family": fam, "out": os.path.abspath(out_dir),
            "tensors": len(sd),
            "param_count": sum(int(v.size) for v in sd.values())}


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", required=True,
                   help="framework model name (gpt2_small, bert_base, ...)")
    p.add_argument("--checkpoint-dir", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--family", default=None,
                   choices=[None, "llama", "gpt2", "bert"])
    p.add_argument("--vocab-size", type=int, default=None)
    p.add_argument("--seq-len", type=int, default=None)
    args = p.parse_args(argv)
    print(json.dumps(export(args.model, args.checkpoint_dir, args.out,
                            args.family, args.vocab_size, args.seq_len)),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
