#!/usr/bin/env python
"""One-command incident report from a run's crash-surviving evidence.

    python tools/postmortem.py [FLIGHT_DIR] [--trace-dir DIR]
                               [--heartbeat-dir DIR] [--checkpoint-dir DIR]
                               [--run RUN_ID] [--json] [--out PATH]

Assembles everything a dead run left behind into a single report:

  * the flight record (observability/flight.py) — merged across hosts,
    attempts and the launcher into one timeline, torn tails salvaged;
  * an **attributed incident chain** — the causal story, e.g.
    "host 2 lost at step 412 → re-formed 4→2 in 15.0 s → resumed from
    step 400" — derived from fault / attribution / re-formation /
    restore events;
  * the metrics snapshot the registry exported next to the record;
  * heartbeat files (who was still beating, and at what step);
  * the telemetry trace summary (tools/summarize_trace.py) when a
    --trace-dir is given;
  * the elastic sidecar and any quarantined (``corrupt.N``) checkpoints.

FLIGHT_DIR defaults to ``$DDL_FLIGHT_DIR``, else the repo-local
``.cache/flight``. The newest run in the record is reported; ``--run``
selects an older one. Pure stdlib + jax-free observability modules —
safe to run anywhere, including a host that cannot initialize a backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.observability import flight  # noqa: E402
from distributeddeeplearning_tpu.observability import health  # noqa: E402
from distributeddeeplearning_tpu.observability import sidecars  # noqa: E402

# Event kinds that appear verbatim in the timeline. "step" and
# "collective" are dense bookkeeping — they are summarized, not listed.
_TIMELINE_SKIP = ("step", "collective")


def incident_chain(events: list[dict]) -> list[str]:
    """The causal story of the run's LAST failure, as narrative fragments.

    Walks the merged timeline for the final trigger (fault injection,
    stale heartbeat, preemption, anomaly abort, or an attributed child
    exit), then follows it forward through restart / re-formation /
    restore to the step training resumed from.
    """
    # Prefer the last ROOT cause (a fault firing, a stale heartbeat, a
    # preemption, an abort) over the child_exit that merely reports its
    # consequence — the exit still contributes the attribution verdict.
    roots = [e for e in events if e.get("ev") in
             ("fault", "heartbeat_stale", "preempted", "abort",
              "serve_replica_lost")]
    exits = [e for e in events
             if e.get("ev") == "child_exit" and e.get("rc")]
    trig = roots[-1] if roots else (exits[-1] if exits else None)
    if trig is None:
        return []
    t0 = trig.get("t", 0.0)
    chain: list[str] = []
    ev = trig.get("ev")
    if ev == "fault":
        chain.append(f"host {trig.get('host')} {trig.get('kind')} "
                     f"at step {trig.get('step')}")
    elif ev == "heartbeat_stale":
        chain.append(f"child {trig.get('child')} heartbeat stale "
                     f"({trig.get('age_s')}s) — presumed hung")
    elif ev == "preempted":
        chain.append(f"host {trig.get('host')} preempted (signal "
                     f"{trig.get('signum')}) at step {trig.get('step')}")
    elif ev == "abort":
        chain.append(f"host {trig.get('host')} aborted: "
                     f"{trig.get('error')} ({trig.get('detail')})")
    elif ev == "serve_replica_lost":
        chain.append(f"serve replica {trig.get('replica')} lost at engine "
                     f"step {trig.get('step')} "
                     f"({trig.get('attribution')}, rc={trig.get('rc')}) "
                     f"with {trig.get('inflight')} request(s) in flight")
    else:
        chain.append(f"child {trig.get('child')} exited rc={trig.get('rc')}")
    # The verdict usually follows the trigger within the same poll.
    for e in events:
        if (e.get("ev") == "child_exit" and e.get("t", 0.0) >= t0
                and e.get("attribution")):
            chain.append(f"attributed as {e['attribution']} "
                         f"(child {e.get('child')}, rc={e.get('rc')})")
            break
    after = [e for e in events if e.get("t", 0.0) >= t0]
    # Serve incidents narrate recovery in requests, not checkpoints: the
    # victims re-dispatched to survivors, then replayed token-identically.
    redispatched = [e for e in after if e.get("ev") == "serve_redispatch"]
    if redispatched:
        chain.append(f"{len(redispatched)} in-flight request(s) "
                     f"re-dispatched to survivors")
    replayed = [e for e in after if e.get("ev") == "serve_replayed"]
    if replayed and all(e.get("token_identical") for e in replayed):
        chain.append(f"{len(replayed)} request(s) replayed "
                     f"token-identically")
    for e in after:
        if e.get("ev") == "reconfiguration":
            chain.append(f"re-formed {e.get('degree_before')}→"
                         f"{e.get('degree_after')} in "
                         f"{e.get('reconfiguration_time_s')} s")
            break
        if e.get("ev") == "reconfiguration_planned":
            chain.append(f"re-formation planned {e.get('degree_before')}→"
                         f"{e.get('degree_after')} "
                         f"({e.get('trigger')})")
    for e in after:
        if e.get("ev") == "restart":
            if e.get("scope") == "serve":
                chain.append(f"replica {e.get('child')} restarted warm "
                             f"(attempt {e.get('attempt')})")
            else:
                chain.append(f"restart {e.get('restart')} "
                             f"(backoff {e.get('backoff_s')} s)")
            break
    for e in after:
        if e.get("ev") == "restore":
            chain.append(f"resumed from step {e.get('step')}")
            break
    else:
        for e in after:
            if e.get("ev") == "run_start" and e is not trig:
                chain.append(f"relaunched at step {e.get('step')}")
                break
    for e in after:
        if e.get("ev") == "run_end":
            chain.append(f"run completed at step {e.get('step')}")
        elif e.get("ev") == "giving_up":
            chain.append(f"gave up after {e.get('restarts')} restart(s) "
                         f"(rc={e.get('rc')})")
        elif e.get("ev") == "serve_drained":
            chain.append("drained with leak check "
                         + ("ok" if e.get("leak_check_ok") else "FAILED"))
    return chain


def _quarantined(checkpoint_dir: str) -> list[str]:
    try:
        return sorted(d for d in os.listdir(checkpoint_dir)
                      if d.startswith("corrupt."))
    except OSError:
        return []


def _heartbeats(heartbeat_dir: str) -> list[dict]:
    out = []
    try:
        names = sorted(os.listdir(heartbeat_dir))
    except OSError:
        return out
    now = time.time()
    for name in names:
        if not name.startswith("heartbeat."):
            continue
        path = os.path.join(heartbeat_dir, name)
        entry: dict = {"file": name}
        try:
            entry["age_s"] = round(now - os.path.getmtime(path), 1)
            with open(path, encoding="utf-8") as fh:
                entry.update(json.load(fh))
        except (OSError, ValueError):
            entry["error"] = "unreadable"
        out.append(entry)
    return out


def build_report(flight_dir: str, *, trace_dir: str | None = None,
                 heartbeat_dir: str | None = None,
                 checkpoint_dir: str | None = None,
                 run: str | None = None) -> dict:
    all_events, errors = flight.read_all(flight_dir)
    run_ids = flight.runs(all_events)
    if run is None:
        run = run_ids[-1] if run_ids else None
    events = [e for e in all_events if e.get("run") == run]
    attempts = sorted({e.get("attempt", 0) for e in events})
    hosts = sorted({str(e.get("host")) for e in events})
    steps = [e for e in events if e.get("ev") == "step"]
    collectives = [e for e in events if e.get("ev") == "collective"]
    timeline = [e for e in events if e.get("ev") not in _TIMELINE_SKIP]
    # One step milestone per attempt keeps progress visible without the
    # dense per-cadence records drowning the story.
    for a in attempts:
        a_steps = [e for e in steps if e.get("attempt", 0) == a]
        if a_steps:
            timeline.append(a_steps[-1])
    timeline.sort(key=lambda e: (e.get("t", 0.0), e.get("seq", 0)))
    report: dict = {
        "flight_dir": flight_dir,
        "run": run,
        "runs_on_record": run_ids,
        "parse_errors": errors,
        "complete": not errors,
        "events": len(events),
        "hosts": hosts,
        "attempts": attempts,
        "last_step": max((e.get("step") or 0 for e in steps), default=None),
        "timeline": [{k: v for k, v in e.items() if k != "_file"}
                     for e in timeline],
        "incident": incident_chain(events),
        "anomalies": [e for e in events if e.get("ev") == "anomaly"],
        "collective_plan_events": len(collectives),
    }
    snap = sidecars.read(os.path.join(flight_dir, "metrics_snapshot.json"))
    if snap:
        report["metrics_snapshot"] = snap
    elastic = sidecars.read("last_elastic_event")
    if elastic:
        # The sidecar is global (.cache) state — fold it in only when it
        # was written during the run being reported, else it narrates a
        # re-formation from some unrelated earlier job.
        t0 = min((e.get("t") for e in events if e.get("t")), default=None)
        stamp = elastic.get("written_at", elastic.get("updated_at"))
        if t0 is None or (isinstance(stamp, (int, float)) and stamp >= t0):
            report["elastic_sidecar"] = elastic
    if heartbeat_dir is None:
        heartbeat_dir = os.environ.get(health.ENV_HEARTBEAT_DIR)
    if heartbeat_dir:
        report["heartbeats"] = _heartbeats(heartbeat_dir)
    if checkpoint_dir:
        report["quarantined_checkpoints"] = _quarantined(checkpoint_dir)
    if trace_dir:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import summarize_trace as stl
        paths = stl.expand_traces([trace_dir])
        if paths:
            s = stl.summarize(paths)
            report["trace"] = {
                "files": len(paths), "events": s["events"],
                "load_errors": s["load_errors"],
                "phases": {k: v["total_ms"]
                           for k, v in s["phases"].items()},
                "instants": [i["name"] for i in s["instants"]],
                # Request-linkage evidence for serve incidents: a flow
                # chain spanning two pids names a request that survived
                # a replica death; an unclosed async track names one
                # that never retired.
                "flows": s.get("flows", {}),
            }
    return report


def print_report(r: dict) -> None:
    print(f"incident report — run {r['run'] or '(none on record)'}")
    print(f"  flight record: {r['events']} events from "
          f"{len(r['hosts'])} writer(s) ({', '.join(r['hosts'])}), "
          f"attempts {r['attempts']}, "
          f"{'complete' if r['complete'] else 'DAMAGED'}")
    for err in r["parse_errors"]:
        print(f"  WARNING: {err}")
    if r.get("last_step") is not None:
        print(f"  last recorded step: {r['last_step']}")
    if r["incident"]:
        print("\nattributed incident:")
        print("  " + " → ".join(r["incident"]))
    else:
        print("\nno incident on record (clean run)")
    print("\ntimeline:")
    for e in r["timeline"]:
        print(f"  {flight.describe(e)}")
    if r.get("anomalies"):
        print("\nanomalies:")
        for a in r["anomalies"]:
            print(f"  step {a.get('step')}: {a.get('kind')} — "
                  f"{a.get('detail')}")
    snap = r.get("metrics_snapshot")
    if snap and snap.get("metrics"):
        print("\nmetrics at last export:")
        for name in sorted(snap["metrics"]):
            m = snap["metrics"][name]
            print(f"  {name:<32} last={m.get('last'):<12g} "
                  f"min={m.get('min'):<12g} max={m.get('max'):<12g}")
    if r.get("heartbeats"):
        print("\nheartbeats:")
        for hb in r["heartbeats"]:
            print(f"  {hb.get('file')}: step {hb.get('step')} "
                  f"(age {hb.get('age_s')}s)")
    if r.get("elastic_sidecar"):
        e = r["elastic_sidecar"]
        print(f"\nelastic sidecar: {e.get('trigger')} "
              f"{e.get('degree_before')}→{e.get('degree_after')} "
              f"({e.get('reconfiguration_time_s')} s), "
              f"resumed from step {e.get('resume_step')}")
    if r.get("quarantined_checkpoints"):
        print("\nquarantined checkpoints: "
              + ", ".join(r["quarantined_checkpoints"]))
    if r.get("trace"):
        t = r["trace"]
        print(f"\ntrace: {t['files']} file(s), {t['events']} events; "
              f"top phases: "
              + ", ".join(f"{k}={v:.1f}ms" for k, v in sorted(
                  t["phases"].items(), key=lambda kv: -kv[1])[:5]))
        fl = t.get("flows") or {}
        for c in fl.get("cross_process", ()):
            print(f"  flow id {c['id']} spans pids {c['pids']} — request "
                  f"re-dispatched across a replica death")
        if fl.get("async_unclosed"):
            print(f"  {len(fl['async_unclosed'])} request track(s) never "
                  f"closed: ids {fl['async_unclosed'][:8]}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("flight_dir", nargs="?", default=None,
                   help="flight-record directory (default: $DDL_FLIGHT_DIR, "
                        "else <repo>/.cache/flight)")
    p.add_argument("--trace-dir", default=None,
                   help="fold a telemetry trace summary into the report")
    p.add_argument("--heartbeat-dir", default=None,
                   help="heartbeat directory (default: $DDL_HEARTBEAT_DIR)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="list quarantined (corrupt.N) checkpoints from here")
    p.add_argument("--run", default=None,
                   help="report a specific run id (default: the newest)")
    p.add_argument("--json", action="store_true",
                   help="emit the report as one JSON object")
    p.add_argument("--out", default=None,
                   help="also write the report (JSON) to this path")
    args = p.parse_args(argv)
    flight_dir = args.flight_dir or flight.default_dir()
    if not os.path.isdir(flight_dir):
        print(f"no flight record at {flight_dir} — run with --flight-dir "
              f"(train.py / launch.py) to record one", file=sys.stderr)
        return 1
    report = build_report(flight_dir, trace_dir=args.trace_dir,
                          heartbeat_dir=args.heartbeat_dir,
                          checkpoint_dir=args.checkpoint_dir, run=args.run)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True, default=str)
    if args.json:
        print(json.dumps(report, sort_keys=True, default=str))
    else:
        print_report(report)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
