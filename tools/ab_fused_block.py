#!/usr/bin/env python
"""Step-time A/B: --fused-block (conv-epilogue fusion) vs the unfused path.

    python tools/ab_fused_block.py [--batches 256,512] [--steps 20]
        [--model resnet50] [--platform cpu]

One JSON line per batch size: unfused and fused img/s/chip and the
speedup. Run on a live chip (tools/chip_window.sh step 3 calls this);
--platform cpu exists for smoke-testing the harness itself.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def step_rate(model: str, batch: int, steps: int, **flags) -> float:
    import jax

    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop

    cfg = TrainConfig(model=model, global_batch_size=batch,
                      dtype="bfloat16", log_every=10**9,
                      parallel=ParallelConfig(data=1),
                      data=DataConfig(synthetic=True), **flags)
    mesh, m, shd, state, train_step, _, rng = loop.build(cfg, 64)
    src = datalib.make_source(cfg, "image", shd)
    i, metrics = 0, None
    for _ in range(5):
        state, metrics = train_step(state, src.batch(i), rng)
        i += 1
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = train_step(state, src.batch(i), rng)
        i += 1
    jax.device_get(metrics)
    return batch * steps / (time.perf_counter() - t0)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batches", default="256,512")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--platform", default=None)
    args = p.parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform

    for batch in (int(b) for b in args.batches.split(",")):
        try:
            base = step_rate(args.model, batch, args.steps)
            fused = step_rate(args.model, batch, args.steps,
                              fused_block=True)
            print(json.dumps({
                "check": "fused_block_ab", "model": args.model,
                "batch": batch, "unfused": round(base, 1),
                "fused": round(fused, 1),
                "speedup": round(fused / base, 3)}), flush=True)
        except Exception as e:  # one OOM must not sink the other batches
            print(json.dumps({
                "check": "fused_block_ab", "model": args.model,
                "batch": batch,
                "error": f"{type(e).__name__}: {e}"[:300]}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
