#!/usr/bin/env python
"""Step-time A/B: --fused-block (conv-epilogue fusion) vs the unfused path.

    python tools/ab_fused_block.py [--batches 256,512] [--steps 20]
        [--model resnet50] [--platform cpu]

One JSON line per batch size: unfused and fused img/s/chip and the
speedup. Run on a live chip (tools/chip_window.sh step 3 calls this);
--platform cpu exists for smoke-testing the harness itself.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def step_rate(model: str, batch: int, steps: int, **flags) -> float:
    import jax

    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop

    cfg = TrainConfig(model=model, global_batch_size=batch,
                      dtype="bfloat16", log_every=10**9,
                      parallel=ParallelConfig(data=1),
                      data=DataConfig(synthetic=True), **flags)
    mesh, m, shd, state, train_step, _, rng = loop.build(cfg, 64)
    src = datalib.make_source(cfg, "image", shd)
    i, metrics = 0, None
    for _ in range(5):
        state, metrics = train_step(state, src.batch(i), rng)
        i += 1
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = train_step(state, src.batch(i), rng)
        i += 1
    jax.device_get(metrics)
    return batch * steps / (time.perf_counter() - t0)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batches", default="256,512")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--conv3", action="store_true",
                   help="also measure the v2 variant (stride-1 3x3 convs "
                        "as Pallas conv+BN, --fused-conv3)")
    p.add_argument("--platform", default=None)
    args = p.parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform

    variants = [("unfused", {}), ("fused", {"fused_block": True})]
    if args.conv3:
        # v2: 3x3 convs fused too (ops/fused_conv_bn.py). A separate
        # variant, not a replacement — if Mosaic rejects the new kernel
        # on-chip, the v1 verdict still lands.
        variants.append(("fused_conv3", {"fused_block": True,
                                         "fused_conv3": True}))
    for batch in (int(b) for b in args.batches.split(",")):
        rates = {}
        for name, flags in variants:
            try:
                rates[name] = round(
                    step_rate(args.model, batch, args.steps, **flags), 1)
            except Exception as e:  # one failure must not sink the rest
                rates[name] = None
                rates[f"{name}_error"] = f"{type(e).__name__}: {e}"[:300]
        rec = {"check": "fused_block_ab", "model": args.model,
               "batch": batch, **rates}
        base = rates.get("unfused")
        if base:
            for name, _ in variants[1:]:
                if rates.get(name):
                    rec[f"speedup_{name}"] = round(rates[name] / base, 3)
        print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
