#!/usr/bin/env python
"""Characterize the overhead of each parallel style on the fake-CPU mesh.

VERDICT r2 Weak #5/#8: GPipe's one-program schedule runs every stage every
tick (fill/drain ticks included) and MoE's GShard-style dispatch
materializes (B,S,E,C) tensors — correctness is proven by tests, but
nothing bounded their cost. This tool measures it.

Method: the 8-virtual-device CPU mesh serializes device programs onto host
cores, so wall-clock per step ~ TOTAL compute issued across the mesh.
That makes it exactly the right instrument for *occupancy* overheads (the
bubble's wasted stage-ticks, the dispatch einsums, FSDP's all-gather
regather work) even though absolute numbers say nothing about chip
latency. Expectations:

- pipeline: useful-work fraction is M/(M+P-1); measured step time should
  scale ~ (M+P-1)/M at fixed global batch. Choose M >= 4·(P-1) to keep
  the bubble under ~20%.
- MoE vs dense FFN: ratio above the FLOP ratio is dispatch overhead.
- dp x fsdp / dp x tp vs pure dp: ratio above 1.0 is regather overhead.

Prints one JSON line per experiment. Run on an OTHERWISE IDLE host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pin_cpu_mesh(n: int = 8) -> None:
    from distributeddeeplearning_tpu.hostmesh import pin_virtual_cpu_mesh

    pin_virtual_cpu_mesh(n)


def time_config(model_name: str, parallel_kw: dict, *, batch: int,
                seq_len: int = 64, steps: int = 4,
                microbatches=None) -> float:
    """Seconds per train step for a config on the fake mesh."""
    import jax

    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop

    cfg = TrainConfig(
        model=model_name, global_batch_size=batch, dtype="float32",
        log_every=10**9, parallel=ParallelConfig(**parallel_kw),
        pipeline_microbatches=microbatches,
        data=DataConfig(dataset="mlm", seq_len=seq_len, vocab_size=512),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                                  schedule="linear", label_smoothing=0.0))
    mesh, model, batch_shd, state, train_step, _, rng = loop.build(cfg, steps)
    src = datalib.make_source(cfg, "tokens", batch_shd, objective="mlm")
    state, metrics = train_step(state, src.batch(0), rng)
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for i in range(1, steps + 1):
        state, metrics = train_step(state, src.batch(i), rng)
    jax.device_get(metrics)
    return (time.perf_counter() - t0) / steps


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--steps", type=int, default=4)
    args = p.parse_args(argv)
    _pin_cpu_mesh()

    # --- baseline: plain dp8 on bert_tiny --------------------------------
    base = time_config("bert_tiny", {"data": 8}, batch=args.batch,
                       seq_len=args.seq_len, steps=args.steps)
    print(json.dumps({"experiment": "dp8_baseline_s_per_step",
                      "s": round(base, 3)}), flush=True)

    # --- fsdp / tp vs dp -------------------------------------------------
    for name, par in (("dp4_fsdp2", {"data": 4, "fsdp": 2}),
                      ("dp4_tp2", {"data": 4, "model": 2}),
                      ("dp2_sp2_tp2", {"data": 2, "seq": 2, "model": 2})):
        t = time_config("bert_tiny", par, batch=args.batch,
                        seq_len=args.seq_len, steps=args.steps)
        print(json.dumps({"experiment": name, "s": round(t, 3),
                          "vs_dp8": round(t / base, 2)}), flush=True)

    # --- pipeline bubble vs microbatch count -----------------------------
    for m in (2, 4, 8, 16):
        if args.batch % m:
            continue
        t = time_config("bert_tiny_pp", {"pipeline": 2, "data": 4},
                        batch=args.batch, seq_len=args.seq_len,
                        steps=args.steps, microbatches=m)
        ticks = m + 2 - 1
        print(json.dumps({
            "experiment": f"pp2_m{m}", "s": round(t, 3),
            "vs_dp8": round(t / base, 2),
            "schedule_overhead_model": round(ticks / m, 2)}), flush=True)

    # --- MoE vs dense FFN ------------------------------------------------
    t = time_config("bert_tiny_moe", {"data": 4, "expert": 2},
                    batch=args.batch, seq_len=args.seq_len, steps=args.steps)
    print(json.dumps({"experiment": "moe_e4_dp4_ep2", "s": round(t, 3),
                      "vs_dp8": round(t / base, 2)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
