#!/usr/bin/env python
"""CPU-proxy perf gate CLI: check the current build, or recalibrate.

Default mode measures the fixed proxy workload and compares it against the
checked-in ``perf_baselines.json`` (exit 1 on violation — the same check
the tier-1 ``perf_gate``-marked test runs). ``--recalibrate`` re-measures
and rewrites the baseline; commit the resulting ``perf_baselines.json``
diff in the PR that intentionally changed performance.

    python tools/perf_gate.py                 # gate the current build
    python tools/perf_gate.py --json          # machine-readable result
    python tools/perf_gate.py --recalibrate   # rewrite perf_baselines.json
    python tools/perf_gate.py --inject-sleep 0.3   # prove the gate fires
    python tools/perf_gate.py --workload zero2_overlap   # gate the sharded
                                              # schedule (extras baseline)

Always runs on CPU (JAX_PLATFORMS=cpu is forced before jax loads): the
gate must never depend on — or touch — a chip tunnel.
"""

import argparse
import json
import os
import sys

# Force the CPU backend before any jax import: a configured TPU tunnel
# must not turn the gate into a chip job (or a 75 s connect timeout).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Multi-device CPU mesh, same forcing as tests/conftest.py: the sharded
# gate workloads (e.g. zero2_overlap, dp=2) need more than one device.
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.observability import perf_gate  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--recalibrate", action="store_true",
                   help="re-measure and rewrite the baseline file")
    p.add_argument("--baseline", default=None,
                   help=f"baseline path (default {perf_gate.BASELINE_PATH})")
    p.add_argument("--inject-sleep", type=float, default=0.0,
                   metavar="SECONDS",
                   help="plant a sleep in the data_wait phase (self-test: "
                        "the gate must fail)")
    p.add_argument("--passes", type=int, default=3,
                   help="recalibration passes; fastest wins (default 3)")
    p.add_argument("--workload", default="default",
                   choices=sorted(perf_gate.WORKLOADS),
                   help="named gate workload: 'default' is the headline "
                        "proxy (top level of perf_baselines.json); others "
                        "live under its 'extras' key (e.g. zero2_overlap "
                        "gates the overlapped ZeRO-2 schedule)")
    p.add_argument("--json", action="store_true",
                   help="emit the full result as JSON on stdout")
    args = p.parse_args(argv)

    if args.recalibrate:
        baseline = perf_gate.recalibrate(args.baseline, passes=args.passes,
                                         workload=args.workload)
        path = args.baseline or perf_gate.BASELINE_PATH
        if args.json:
            print(json.dumps(baseline, indent=2, sort_keys=True))
        else:
            print(f"wrote {path}")
            print(f"  normalized_step {baseline['normalized_step']} "
                  f"(step {baseline['step_time_ms']} ms / calib "
                  f"{baseline['calib_unit_ms']} ms)")
            print(f"  phase_share {baseline['phase_share']}")
            print(f"  tolerance {baseline['tolerance']}")
        return 0

    result = perf_gate.check(args.baseline,
                             inject_sleep_s=args.inject_sleep,
                             workload=args.workload)
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        cur = result["current"]
        print(f"perf gate [{args.workload}]: "
              f"{'PASS' if result['ok'] else 'FAIL'}")
        print(f"  normalized_step {cur['normalized_step']} vs baseline "
              f"{result['baseline_normalized_step']} "
              f"(step {cur['step_time_ms']} ms / calib "
              f"{cur['calib_unit_ms']} ms)")
        print(f"  phase_share {cur['phase_share']}")
        for v in result["violations"]:
            print(f"  VIOLATION: {v}")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
