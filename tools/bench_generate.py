#!/usr/bin/env python
"""Decode-throughput benchmark: emitted tokens/sec, KV-cache vs full-refeed.

    python tools/bench_generate.py [--model gpt2_small] [--batch 8]
        [--prompt-len 128] [--new-tokens 128] [--platform cpu]

Random weights (throughput is weight-independent), greedy decode, one
warmup generation (compile) then a timed one. Prints one JSON line per
mode; the KV-cache line is the serving number (O(S) per token), the
refeed line is the context the speedup is measured against.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2_small")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=128)
    p.add_argument("--vocab-size", type=int, default=None)
    p.add_argument("--platform", default=None)
    p.add_argument("--skip-refeed", action="store_true",
                   help="cache-only (the refeed arm is O(S^2) and slow at "
                        "long prompts)")
    p.add_argument("--speculative", action="store_true",
                   help="add a self-draft speculative arm (batch 1): the "
                        "all-accepted upper bound on spec-decode speedup")
    p.add_argument("--draft-len", type=int, default=4)
    args = p.parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.models import flops as flopslib
    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.models.generate import generate
    from distributeddeeplearning_tpu.observability import perf_report

    total = args.prompt_len + args.new_tokens
    spec = model_spec(args.model)
    kw = dict(dtype=jnp.bfloat16, seq_len=total)
    if args.vocab_size:
        kw["vocab_size"] = args.vocab_size
    model = spec.build(**kw)
    rng = np.random.default_rng(0)
    vocab = model.cfg.vocab_size
    prompt = jnp.asarray(
        rng.integers(1, vocab, (args.batch, args.prompt_len)), jnp.int32)
    variables = model.init({"params": jax.random.key(0)}, prompt[:, :8],
                           train=False)

    # Roofline context: decode sweeps positions prompt..prompt+new, so the
    # mid-decode context is the representative KV-read size for the row.
    mid_context = args.prompt_len + args.new_tokens // 2
    device_kind = getattr(jax.devices()[0], "device_kind", "")

    def timed(use_cache: bool) -> None:
        t_c = time.perf_counter()
        out = generate(model, variables, prompt,
                       max_new_tokens=args.new_tokens, use_cache=use_cache)
        jax.block_until_ready(out)
        compile_s = time.perf_counter() - t_c
        t0 = time.perf_counter()
        out = generate(model, variables, prompt,
                       max_new_tokens=args.new_tokens, use_cache=use_cache)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        value = round(args.batch * args.new_tokens / dt, 1)
        rec = {
            "metric": f"{args.model}_decode_tokens_per_sec",
            "mode": "kv_cache" if use_cache else "full_refeed",
            "value": value,
            "unit": "tokens/sec",
            "batch": args.batch, "prompt_len": args.prompt_len,
            "new_tokens": args.new_tokens,
            "wall_s": round(dt, 2), "compile_s": round(compile_s, 1),
        }
        roof = flopslib.decode_roofline(
            args.model, context_len=mid_context,
            tokens_per_sec=value / jax.device_count(),
            device_kind=device_kind, batch=args.batch)
        if roof:
            rec["decode_roofline"] = roof
        print(json.dumps(perf_report.annotate(rec, provenance="fresh")),
              flush=True)

    timed(True)
    if not args.skip_refeed:
        timed(False)
    if args.speculative:
        from distributeddeeplearning_tpu.models.generate import (
            generate_speculative)

        prompt1 = prompt[:1]

        def spec():
            return generate_speculative(
                model, variables, model, variables, prompt1,
                max_new_tokens=args.new_tokens, draft_len=args.draft_len)

        t_c = time.perf_counter()
        jax.block_until_ready(spec())
        compile_s = time.perf_counter() - t_c
        t0 = time.perf_counter()
        jax.block_until_ready(spec())
        dt = time.perf_counter() - t0
        rec = {
            "metric": f"{args.model}_decode_tokens_per_sec",
            "mode": f"speculative_selfdraft_k{args.draft_len}",
            "value": round(args.new_tokens / dt, 1),
            "unit": "tokens/sec", "batch": 1,
            "prompt_len": args.prompt_len, "new_tokens": args.new_tokens,
            "wall_s": round(dt, 2), "compile_s": round(compile_s, 1),
        }
        roof = flopslib.decode_roofline(
            args.model, context_len=mid_context,
            tokens_per_sec=rec["value"] / jax.device_count(),
            device_kind=device_kind, batch=1)
        if roof:
            rec["decode_roofline"] = roof
        print(json.dumps(perf_report.annotate(rec, provenance="fresh")),
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
