#!/usr/bin/env python
"""One-off calibration: XLA cost-analysis FLOPs per example for the
benchable models, used to pin/validate the analytic tables in
``models/flops.py`` (MFU reporting — VERDICT r4 Next #5).

Prints one JSON line per config: lowered (pre-optimization) HLO flops for
the FULL train step (fwd+bwd+optimizer), per example. XLA counts a MAC as
2 flops — the same convention as MFU peak numbers.

Run CPU-only:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/calibrate_flops.py
"""
import json
import sys

sys.path.insert(0, ".")

CONFIGS = [
    ("resnet50", {"batch": 8}),
    ("resnet152", {"batch": 4}),
    ("densenet121", {"batch": 4}),
    ("vit_b16", {"batch": 4}),
    ("bert_base", {"batch": 2, "seq_len": 512}),
    ("bert_base", {"batch": 2, "seq_len": 512, "mlm_dense": True}),
    ("gpt2_small", {"batch": 1, "seq_len": 1024}),
]


def main() -> int:
    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig, resolve_mlm_max_predictions)
    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.train import loop

    for model, o in CONFIGS:
        spec = model_spec(model)
        tokens = spec.input_kind == "tokens"
        seq_len = o.get("seq_len", 512)
        mlm_pred = (0 if o.get("mlm_dense")
                    else resolve_mlm_max_predictions(-1, seq_len,
                                                     spec.objective))
        data = (DataConfig(synthetic=True, dataset="mlm", seq_len=seq_len,
                           mlm_max_predictions=mlm_pred)
                if tokens else DataConfig(synthetic=True))
        batch = o["batch"]
        cfg = TrainConfig(model=model, global_batch_size=batch,
                          dtype="bfloat16", log_every=10**9,
                          parallel=ParallelConfig(data=1), data=data)
        mesh, _m, batch_shd, state, train_step, _s, rng = loop.build(cfg, 100)
        source = datalib.make_source(cfg, spec.input_kind, batch_shd,
                                     objective=spec.objective)
        import jax
        raw = getattr(train_step, "raw_step", None)
        step = jax.jit(raw) if raw is not None else train_step
        lowered = step.lower(state, source.batch(0), rng)
        cost = lowered.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        flops = float(cost["flops"])
        print(json.dumps({
            "model": model, "seq_len": seq_len if tokens else None,
            "mlm_pred": mlm_pred if tokens else None, "batch": batch,
            "step_flops_per_example": round(flops / batch / 1e9, 3),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
