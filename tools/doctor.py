#!/usr/bin/env python
"""Environment diagnosis: one command that answers "why doesn't it run?".

    python tools/doctor.py [--probe-timeout 45]

Checks, each printed as one JSON line (never raises, never hangs):
accelerator reachability (subprocess probe with a hard timeout — a dead
tunnel hangs forever otherwise), virtual CPU mesh, library versions,
native toolchain + in-tree loader build, data-loader auto-resolution,
XLA compile-cache state, and the last recorded benchmark measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(check: str, **kw) -> None:
    print(json.dumps({"check": check, **kw}), flush=True)


def check_accelerator(timeout: int) -> None:
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); "
             "print(d[0].platform, len(d))"],
            capture_output=True, text=True, timeout=timeout)
        out = r.stdout.strip().splitlines()
        if r.returncode == 0 and out:
            platform, n = out[-1].split()
            emit("accelerator", ok=True, platform=platform, devices=int(n),
                 init_s=round(time.time() - t0, 1))
        else:
            emit("accelerator", ok=False,
                 error=(r.stderr.strip().splitlines() or ["no output"])[-1])
    except subprocess.TimeoutExpired:
        emit("accelerator", ok=False,
             error=f"backend init exceeded {timeout}s — the TPU tunnel is "
                   f"down or hanging; CPU paths still work (JAX_PLATFORMS="
                   f"cpu)")


def check_cpu_mesh() -> None:
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
        n = int(r.stdout.strip().splitlines()[-1])
        emit("virtual_cpu_mesh", ok=n == 8, devices=n)
    except Exception as e:
        emit("virtual_cpu_mesh", ok=False, error=str(e)[:200])


def check_kernels() -> None:
    """Interpret-mode smoke of every Pallas kernel family on tiny shapes —
    an import error or interpret regression in any of them should show up
    in one doctor run, not at bench time on a scarce chip window."""
    code = """
import os
os.environ.pop('PALLAS_AXON_POOL_IPS', None)
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax, jax.numpy as jnp
from distributeddeeplearning_tpu.ops.flash_attention import flash_attention
from distributeddeeplearning_tpu.ops.fused_linear_bn import linear_stats
from distributeddeeplearning_tpu.ops.fused_conv_bn import conv3x3_stats
from distributeddeeplearning_tpu.ops.embedding import embedding_lookup
q = jax.random.normal(jax.random.key(0), (1, 16, 2, 8))
flash_attention(q, q, q)
x = jax.random.normal(jax.random.key(1), (32, 8))
linear_stats(x, jax.random.normal(jax.random.key(2), (8, 16)))
img = jax.random.normal(jax.random.key(3), (1, 8, 8, 8))
conv3x3_stats(img, jax.random.normal(jax.random.key(4), (3, 3, 8, 8)))
embedding_lookup(x, jnp.array([[0, 3]]))
print('OK')
"""
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=300)
        emit("pallas_kernels_interpret",
             ok=r.stdout.strip().endswith("OK"),
             **({} if r.returncode == 0 else
                {"error": r.stderr[-300:]}))
    except Exception as e:
        emit("pallas_kernels_interpret", ok=False, error=str(e)[:200])


def check_versions() -> None:
    import importlib.metadata as md
    vers = {}
    for pkg in ("jax", "jaxlib", "libtpu", "flax", "optax",
                "orbax-checkpoint", "grain", "tensorflow", "torch",
                "transformers"):
        try:
            vers[pkg] = md.version(pkg)
        except md.PackageNotFoundError:
            vers[pkg] = None
    emit("versions", ok=all(vers[p] for p in ("jax", "flax", "optax")),
         **{k.replace("-", "_"): v for k, v in vers.items()})


def check_native() -> None:
    tools = {t: bool(shutil.which(t)) for t in ("g++", "make", "cmake")}
    lib = os.path.join(REPO, "distributeddeeplearning_tpu", "data",
                       "_native", "libddl_loader.so")
    built = os.path.exists(lib)
    if not built and tools["make"]:  # the loader builds on demand
        try:
            r = subprocess.run(
                ["make", "-C", os.path.join(REPO, "csrc"), "lib"],
                capture_output=True, text=True, timeout=300)
            built = r.returncode == 0 and os.path.exists(lib)
        except (subprocess.TimeoutExpired, OSError):
            built = False  # report, never raise: doctor must finish
    emit("native_toolchain", ok=tools["g++"] and tools["make"] and built,
         **tools, loader_built=built)


def check_loader() -> None:
    import tempfile
    try:
        from distributeddeeplearning_tpu.config import DataConfig, TrainConfig
        from distributeddeeplearning_tpu.data import resolve_loader
        with tempfile.TemporaryDirectory() as d:
            os.makedirs(os.path.join(d, "train", "class0"))
            cfg = TrainConfig(data=DataConfig(synthetic=False, data_dir=d,
                                              loader="auto"))
            emit("data_loader", ok=True,
                 auto_resolves_to=resolve_loader(cfg, "image"))
    except Exception as e:
        emit("data_loader", ok=False, error=str(e)[:200])


def check_caches(prune_days: float = 0.0) -> None:
    """Compile-cache state via the shared policy module
    (distributeddeeplearning_tpu/perf/compile_cache.py): resolved location
    (flag > $DDL_COMPILE_CACHE > repo default), entry count / size split
    into XLA entries vs AOT step executables, and the last run's hit/miss
    counters from the stats sidecar. ``--prune N`` evicts entries older
    than N days first."""
    try:
        from distributeddeeplearning_tpu.perf import compile_cache
        cache = compile_cache.resolve_dir()
        pruned = None
        if cache and prune_days > 0:
            removed, kept = compile_cache.prune(
                cache, max_age_days=prune_days)
            pruned = {"removed": removed, "kept": kept,
                      "max_age_days": prune_days}
        info = compile_cache.summarize(cache)
        stats = compile_cache.read_stats(cache) if cache else None
        fields = {
            "compile_cache_dir": info["dir"],
            "compile_cache_entries": info["entries"],
            "compile_cache_aot_entries": info["aot_entries"],
            "compile_cache_mb": round(info["total_bytes"] / 1e6, 1),
        }
        if isinstance(stats, dict):
            fields["last_run_stats"] = {
                k: stats[k] for k in ("aot_hits", "aot_misses", "aot_saves",
                                      "aot_failures", "sources",
                                      "updated_at")
                if k in stats}
        if pruned is not None:
            fields["pruned"] = pruned
    except Exception as e:  # doctor must finish; fall back to raw listing
        cache = os.path.join(REPO, ".cache", "jax_compile")
        entries = (len(os.listdir(cache)) if os.path.isdir(cache) else 0)
        fields = {"compile_cache_dir": cache,
                  "compile_cache_entries": entries,
                  "policy_error": str(e)[:200]}
    last = None
    from distributeddeeplearning_tpu.observability import sidecars
    table = sidecars.read("last_bench")
    if isinstance(table, dict):
        last = table.get("resnet50_imagenet_images_per_sec_per_chip")
    last_fields = None
    if isinstance(last, dict):
        last_fields = {k: last.get(k) for k in ("value", "measured_at")}
        # Provenance verdict on the cached number (perf_report age rules):
        # how stale the headline the next error record would lean on
        # already is — "fresh 2h ago" and "expired, 6 days old" are
        # different situations a window planner must distinguish.
        try:
            from distributeddeeplearning_tpu.observability import perf_report
            age = perf_report.measurement_age_s(last.get("measured_at"))
            last_fields["age_s"] = None if age is None else int(age)
            last_fields["provenance_if_reused"] = perf_report.classify_age(
                age)
            if "pct_of_peak" in last:
                last_fields["pct_of_peak"] = last["pct_of_peak"]
        except Exception:
            pass
    emit("caches", ok=True, **fields, last_bench=last_fields)


def check_perf_gate() -> None:
    """CPU-proxy perf-gate state WITHOUT running the proxy (that is
    tier-1's job): baseline presence/recording info + the last recorded
    check from .cache/perf_gate_last.json — so a failing gate is
    diagnosable (which phase, how far out of band) straight from doctor
    output, no pytest rerun needed."""
    try:
        from distributeddeeplearning_tpu.observability import perf_gate
        st = perf_gate.status()
        last = st.get("last_check")
        ok = bool(st["baseline_present"]) and (last is None
                                              or bool(last.get("ok")))
        emit("perf_gate", ok=ok, **st)
    except Exception as e:
        emit("perf_gate", ok=False, error=str(e)[:200])


def check_sharding() -> None:
    """Optimizer-sharding state of the LAST run (loop.py drops
    .cache/last_run_sharding.json on process 0): active ZeRO stage,
    whether the overlapped backward/collective schedule was in effect and
    the measured overlap fraction, and opt-state offload — so "which
    sharding did that run actually use?" is answerable from doctor output
    without re-reading run logs. ok=True always: an absent sidecar just
    means no sharded run has happened yet."""
    from distributeddeeplearning_tpu.observability import sidecars
    side = sidecars.read("last_run_sharding")
    if side is not None:
        emit("optimizer_sharding", ok=True,
             **{k: side.get(k) for k in (
                 "optimizer_sharding", "overlap_collectives", "overlap",
                 "overlap_fraction", "opt_state_offload", "dp", "model")})
    else:
        emit("optimizer_sharding", ok=True, last_run=None,
             note="no sharding sidecar; written by the first train run")


def check_pipeline() -> None:
    """Pipeline-schedule state of the LAST run (the same
    .cache/last_run_sharding.json sidecar carries a ``pipeline`` block
    for pipelined configs): stage count, schedule (gpipe / 1f1b),
    virtual stages, and the measured bubble fraction — null on an AOT
    warm boot, where nothing re-traced so nothing was observed (see
    docs/pipeline.md). ok=True always: no block just means the last run
    was not pipelined."""
    from distributeddeeplearning_tpu.observability import sidecars
    side = sidecars.read("last_run_sharding")
    pipe = side.get("pipeline") if isinstance(side, dict) else None
    if isinstance(pipe, dict):
        emit("pipeline", ok=True,
             **{k: pipe.get(k) for k in (
                 "stages", "schedule", "virtual_stages",
                 "bubble_fraction")})
    else:
        emit("pipeline", ok=True, last_run=None,
             note="no pipeline block in the sharding sidecar; written by "
                  "the first pipelined (--pp > 1) train run")


def check_precision() -> None:
    """Precision policy of the LAST run (the same
    .cache/last_run_sharding.json sidecar carries ``precision`` /
    ``precision_explicit`` / ``batch_ramp``): the resolved
    compute/param/reduce-dtype triple with any dynamic loss scale
    (e.g. ``bf16/f32/bf16+dls32768``), whether it came from an explicit
    PrecisionPolicy or the legacy --dtype flag, and the batch-ramp
    schedule if one ran — so "did that run actually train mixed?" is
    answerable from doctor output (ISSUE 20). ok=True always: an absent
    sidecar just means no run has happened yet."""
    from distributeddeeplearning_tpu.observability import sidecars
    side = sidecars.read("last_run_sharding")
    if isinstance(side, dict) and side.get("precision") is not None:
        emit("precision", ok=True,
             **{k: side.get(k) for k in (
                 "precision", "precision_explicit", "batch_ramp",
                 "model")})
    else:
        emit("precision", ok=True, last_run=None,
             note="no precision field in the sharding sidecar; written "
                  "by the first train run after the PrecisionPolicy "
                  "change")


def check_elastic() -> None:
    """Last elastic re-formation (loop.py drops
    .cache/last_elastic_event.json on process 0 when a run resumes under a
    launch.py --elastic membership event): trigger (host_lost / hung /
    host_rejoin / host_join / host_drain), degree before/after, the
    membership epoch it re-formed into, the measured reconfiguration
    seconds with its detect->drain->restore->compile->first-step phase
    split, and the resume step — so "what did the last re-formation
    cost, and where did the time go?" is answerable from doctor output.
    ok=True always: an absent sidecar just means no elastic
    re-formation has happened yet."""
    from distributeddeeplearning_tpu.observability import sidecars
    side = sidecars.read("last_elastic_event")
    if side is not None:
        emit("elastic", ok=True,
             **{k: side.get(k) for k in (
                 "trigger", "degree_before", "degree_after", "epoch",
                 "reconfiguration_time_s", "phases", "resume_step")})
    else:
        emit("elastic", ok=True, last_event=None,
             note="no elastic sidecar; written when a launch.py --elastic "
                  "run re-forms")


def check_flight() -> None:
    """Last incident from the flight record (observability/flight.py):
    the most recent fault / anomaly / attributed child exit / stale
    heartbeat on record, in one human line — so "what killed the last
    run?" is answerable from doctor output before anyone opens
    tools/postmortem.py. ok=True always: an absent or incident-free
    record is a healthy state, not a failure."""
    try:
        from distributeddeeplearning_tpu.observability import flight
        directory = flight.default_dir()
        incident = flight.last_incident(directory)
        if incident is None:
            emit("flight_record", ok=True, last_incident=None,
                 flight_dir=directory,
                 note="no incident on record; record with --flight-dir "
                      "(train.py / launch.py)")
        else:
            emit("flight_record", ok=True, flight_dir=directory,
                 last_incident=flight.describe(incident),
                 run=incident.get("run"), kind=incident.get("ev"),
                 step=incident.get("step"))
    except Exception as e:
        emit("flight_record", ok=True, error=str(e)[:200])


def check_ddl_lint() -> None:
    """Static distributed-correctness state (tools/ddl_lint.py): the two
    jax-free AST passes run LIVE (they are fast), plus the recorded
    last_ddl_lint sidecar for the tracing pass's verdict and schedule
    fingerprints. ok=False only on live findings or a recorded failing
    run — an absent sidecar just means ddl_lint has not run yet."""
    try:
        from distributeddeeplearning_tpu.analysis import (donation, lints,
                                                          repo_root)
        from distributeddeeplearning_tpu.observability import sidecars
        roots = [os.path.join(repo_root(), r)
                 for r in ("distributeddeeplearning_tpu", "tools",
                           "train.py", "bench.py", "generate.py",
                           "launch.py")]
        live = lints.analyze_paths(roots) + donation.analyze_paths(roots)
        side = sidecars.read("last_ddl_lint")
        age = sidecars.age_s(side)
        recorded_ok = side.get("ok") if side else None
        emit("ddl_lint", ok=not live and recorded_ok is not False,
             live_findings=len(live),
             live_detail=[f"{f.get('file')}:{f.get('line')} {f['rule']}"
                          for f in live[:5]],
             last_run_ok=recorded_ok,
             last_run_age_s=round(age, 1) if age is not None else None,
             schedules=(side or {}).get("collective_schedules"),
             note=(None if side else "no last_ddl_lint sidecar; run "
                   "python tools/ddl_lint.py"))
    except Exception as e:
        emit("ddl_lint", ok=True, error=str(e)[:200])


def check_serve() -> None:
    """Last continuous-batching serve bench (tools/bench_serve.py drops
    the last_serve sidecar): tokens/sec/chip, speedup over the sequential
    generate() baseline, TTFT p50/p99 and AOT executable sources — so
    "what did serving last measure?" is answerable from doctor output.
    ok=True always: an absent sidecar just means the bench has not run."""
    try:
        from distributeddeeplearning_tpu.observability import sidecars
        side = sidecars.read("last_serve")
        if side is None:
            emit("serve", ok=True, last_bench=None,
                 note="no last_serve sidecar; run python tools/"
                      "bench_serve.py")
            return
        rec = side.get("record") or {}
        cont = rec.get("continuous") or {}
        chaos = rec.get("chaos") or {}
        age = sidecars.age_s(side)
        # Serve health proper: shed / deadline-miss / retry counts from
        # the last bench window (nonzero on a fault-free run means the
        # SLO config or pool sizing is wrong), plus the chaos arm's
        # recovery story when bench_serve ran with --chaos.
        extra = {}
        if chaos:
            extra = {
                "chaos_recovery_overhead_frac":
                    chaos.get("recovery_overhead_frac"),
                "chaos_redispatched": chaos.get("redispatched"),
                "chaos_restarts": chaos.get("restarts"),
                "chaos_token_identity":
                    chaos.get("token_identity_checked"),
                "chaos_leak_check_ok": chaos.get("leak_check_ok"),
            }
        # Fast-path health: prefix reuse and speculative acceptance from
        # the last bench window. A hit rate of 0 under a shared-prefix
        # trace, or acceptance far below the drafter's usual, is a fast
        # path that is configured but not paying for itself.
        if cont.get("prefix_hit_rate") is not None:
            extra["prefix_hit_rate"] = cont.get("prefix_hit_rate")
            extra["prefix_tokens_reused"] = cont.get("prefix_tokens_reused")
            extra["prefix_evictions"] = cont.get("prefix_evictions")
            extra["cow_copies"] = cont.get("cow_copies")
        if cont.get("spec_rounds"):
            extra["spec_rounds"] = cont.get("spec_rounds")
            extra["spec_acceptance_rate"] = cont.get("spec_acceptance_rate")
        if rec.get("speedup_at_slo") is not None:
            extra["speedup_at_slo"] = rec.get("speedup_at_slo")
            extra["slo_p99_ttft_s"] = rec.get("slo_p99_ttft_s")
        emit("serve", ok=True,
             tokens_per_sec_per_chip=rec.get("value"),
             speedup_vs_sequential=rec.get("speedup_vs_sequential"),
             ttft_s=cont.get("ttft_s"),
             preemptions=cont.get("preemptions"),
             sheds=cont.get("sheds"),
             deadline_misses=cont.get("deadline_misses"),
             retries=cont.get("retries"),
             model=rec.get("model"), provenance=rec.get("provenance"),
             aot_sources=(rec.get("aot") or {}).get("sources"),
             age_s=round(age, 1) if age is not None else None, **extra)
    except Exception as e:
        emit("serve", ok=True, error=str(e)[:200])


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--probe-timeout", type=int, default=45)
    p.add_argument("--prune", type=float, default=0.0, metavar="DAYS",
                   help="evict compile-cache entries older than DAYS "
                        "before reporting (0 = report only)")
    args = p.parse_args(argv)
    check_accelerator(args.probe_timeout)
    check_cpu_mesh()
    check_kernels()
    check_versions()
    check_native()
    check_loader()
    check_caches(prune_days=args.prune)
    check_perf_gate()
    check_sharding()
    check_pipeline()
    check_precision()
    check_elastic()
    check_flight()
    check_ddl_lint()
    check_serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
