#!/usr/bin/env python
"""On-hardware validation + timing of the fused BN kernels (VERDICT r2 #2).

Two stages, each printing one JSON line:

1. correctness — COMPILED fused BN(+residual)+ReLU forward and gradients at
   a real ResNet50 activation shape vs the unfused float32-stats reference;
2. step-time A/B — resnet50 synthetic batch-512 training step, fused_bn off
   vs on (the BASELINE.md profile attributes 113 ms of the 209 ms step to
   BN-statistics/dγ/dβ/dx reductions; this measures how much the fused
   kernels reclaim).

Exits nonzero on a correctness failure. Run on a live chip:
    python tools/validate_fused_bn_tpu.py [--batch-size 512] [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _sync(x):
    return jax.device_get(x)


def check_correctness() -> bool:
    from distributeddeeplearning_tpu.ops import fused_batchnorm as fbn

    eps = 1e-5
    # A mid-network ResNet50 shape: (B=64, H=W=28, C=512) -> (50176, 512).
    m, c = 64 * 28 * 28, 512
    x = jax.random.normal(jax.random.key(0), (m, c), jnp.bfloat16)
    res = jax.random.normal(jax.random.key(1), (m, c), jnp.bfloat16)
    gamma = (jax.random.normal(jax.random.key(2), (c,)) * 0.2 + 1.0)
    beta = jax.random.normal(jax.random.key(3), (c,)) * 0.1
    w = jax.random.normal(jax.random.key(4), (m, c), jnp.float32)

    def ref(x, g, b, r):
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=0)
        var = ((xf - mean) ** 2).mean(axis=0)
        y = (xf - mean) * jax.lax.rsqrt(var + eps) * g + b
        return jnp.maximum(y + r.astype(jnp.float32), 0.0)

    def loss_fused(x, g, b, r):
        y, _, _ = fbn.bn_act_res_train(x, g, b, r, True, eps)
        return jnp.sum(y.astype(jnp.float32) * w)

    def loss_ref(x, g, b, r):
        return jnp.sum(ref(x, g, b, r) * w)

    ok = True
    t0 = time.perf_counter()
    yf = _sync(jax.jit(lambda *a: fbn.bn_act_res_train(*a, True, eps)[0])(
        x, gamma, beta, res))
    yr = _sync(jax.jit(ref)(x, gamma, beta, res))
    fwd_err = float(np.max(np.abs(yf.astype(np.float32) - yr)))
    gf = _sync(jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2, 3)))(
        x, gamma, beta, res))
    gr = _sync(jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3)))(
        x, gamma, beta, res))
    errs = {}
    for a, b, name in zip(gf, gr, ("dx", "dgamma", "dbeta", "dres")):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        denom = max(float(np.max(np.abs(b32))), 1e-6)
        errs[name] = float(np.max(np.abs(a32 - b32))) / denom
        ok &= errs[name] < 3e-2  # bf16 storage tolerance
    ok &= fwd_err < 0.1  # bf16 output ULP at O(10) magnitudes
    print(json.dumps({
        "check": "fused_bn_correctness", "ok": bool(ok),
        "fwd_max_abs_err": round(fwd_err, 5),
        "grad_rel_err": {k: round(v, 5) for k, v in errs.items()},
        "wall_s": round(time.perf_counter() - t0, 1)}), flush=True)
    return ok


def bench_step(fused: bool, batch_size: int, steps: int) -> float:
    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.train import loop

    n_dev = jax.device_count()
    cfg = TrainConfig(
        model="resnet50", global_batch_size=batch_size * n_dev,
        dtype="bfloat16", log_every=10**9, fused_bn=fused,
        parallel=ParallelConfig(data=n_dev), data=DataConfig(synthetic=True))
    spec = model_spec(cfg.model)
    mesh, model, batch_shd, state, train_step, sched, rng = loop.build(cfg, 64)
    source = datalib.make_source(cfg, spec.input_kind, batch_shd)
    i = 0
    metrics = None
    for _ in range(5):
        state, metrics = train_step(state, source.batch(i), rng)
        i += 1
    _sync(metrics)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = train_step(state, source.batch(i), rng)
        i += 1
    _sync(metrics)
    dt = (time.perf_counter() - t0) / steps
    return cfg.global_batch_size / dt / n_dev


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--skip-bench", action="store_true")
    args = p.parse_args(argv)

    ok = check_correctness()
    if not args.skip_bench:
        base = bench_step(False, args.batch_size, args.steps)
        fused = bench_step(True, args.batch_size, args.steps)
        print(json.dumps({
            "check": "fused_bn_step_ab", "batch_per_chip": args.batch_size,
            "imgs_per_sec_per_chip": {"unfused": round(base, 1),
                                      "fused": round(fused, 1)},
            "speedup": round(fused / base, 3)}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
