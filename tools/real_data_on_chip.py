#!/usr/bin/env python
"""End-to-end real-pixels proof: bytes on disk -> decode -> augment -> HBM
-> train -> eval -> checkpoint resume, through EVERY image loader.

VERDICT r3 Missing #2 / Next #3: all convergence evidence was on-device
synthetic — no real image had ever flowed through the full path on the
chip. This tool drives the REAL ``train.py`` CLI (not library shortcuts)
over an on-disk JPEG imagefolder for each of the three loaders (tf.data,
in-tree C++ native, grain), with periodic eval and a mid-run resume leg,
plus a synthetic leg for the host-input-bound delta. One JSON line per leg:

    {"leg": "tf", "images_per_sec_per_chip": ..., "final_top1": ...,
     "resume_start_step": ...}

The corpus is generated once (cached): class-tinted noise JPEGs, so top-1
is *learnable from pixels* — a rising eval curve proves labels stayed
attached to their images through decode/augment/shard/batch, which pure
throughput numbers cannot.

Usage (chip window): python tools/real_data_on_chip.py
CPU smoke:           python tools/real_data_on_chip.py --backend cpu \
                        --model resnet18_thin --batch-size 16 --steps 8 \
                        --images 64 --image-size 64 --eval-batches 2
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np


def ensure_corpus(root: str, n: int, hw: int, classes: int = 16,
                  alpha: float = 0.22) -> None:
    """n JPEGs, imagefolder layout, ``classes`` classes with GRADED signal
    (VERDICT r4 Next #4 — the old 2-class tinted corpus saturated at
    top-1 = 1.0, proving labels stay attached but nothing about a recipe).

    Class k's signal is a low-amplitude combination a small CNN must
    average over many pixels to read: a hue tint at angle 2πk/C (adjacent
    classes 360/C degrees apart — deliberately confusable) plus a
    sinusoidal texture whose orientation/frequency encode k mod 4 and
    k // 4. ``alpha`` scales signal vs noise; at the default, eval top-1
    on a thin ResNet plateaus well below 1.0 while staying far above
    chance, so a recipe change (LR, schedule, SyncBN) visibly moves it.
    Idempotent: a complete corpus is reused (generation on one host core
    is the slow part; never spend chip-window time on it)."""
    marker = os.path.join(root, f".complete_{n}_{hw}_{classes}_{alpha}")
    if os.path.exists(marker):
        return
    from PIL import Image

    rng = np.random.default_rng(0)
    t0 = time.time()
    yy, xx = np.mgrid[0:hw, 0:hw].astype(np.float32) / hw
    for split, count in (("train", n), ("val", max(n // 4, 8))):
        for i in range(count):
            cls = i % classes
            d = os.path.join(root, split, f"class{cls:02d}")
            os.makedirs(d, exist_ok=True)
            noise = rng.integers(0, 256, (hw, hw, 3), np.uint8)
            hue = 2 * np.pi * cls / classes
            tint = np.array([np.cos(hue), np.cos(hue - 2 * np.pi / 3),
                             np.cos(hue + 2 * np.pi / 3)], np.float32)
            phi = np.pi * (cls % 4) / 4.0
            freq = (3, 5, 8, 12)[(cls // 4) % 4]
            tex = np.sin(2 * np.pi * freq
                         * (xx * np.cos(phi) + yy * np.sin(phi)))
            signal = (tint[None, None, :] * 60.0
                      + tex[:, :, None] * 45.0)
            arr = np.clip(noise.astype(np.float32) * (1 - alpha)
                          + (128.0 + signal) * alpha, 0, 255).astype(np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img{i}.jpg"),
                                      quality=85)
    open(marker, "w").close()
    print(f"# corpus: {n} JPEGs @ {hw}px, {classes} classes "
          f"(alpha={alpha}) in {time.time() - t0:.0f}s",
          file=sys.stderr, flush=True)


def run_leg(leg: str, cli: list[str], timeout: int,
            collect_evals: bool = False) -> dict:
    """One train.py run; returns the parsed summary plus stderr tail.
    ``collect_evals`` also gathers the periodic-eval JSONL records into a
    [(step, eval_top1), ...] trajectory (the convergence leg's product)."""
    t0 = time.time()
    try:
        proc = subprocess.run(cli, capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired as e:
        return {"leg": leg, "error": f"timeout {timeout}s",
                "stderr": (e.stderr or "")[-400:] if isinstance(
                    e.stderr, str) else None}
    summary = None
    evals = []
    for line in proc.stdout.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict):
            continue
        if "summary" in rec:
            summary = rec["summary"]
        elif "eval_top1" in rec:
            evals.append([rec.get("step"), rec["eval_top1"]])
    if summary is None:
        return {"leg": leg, "error": f"no summary (rc={proc.returncode})",
                "stderr": proc.stderr[-400:]}
    out = {"leg": leg, "summary": summary,
           "wall_s": round(time.time() - t0, 1)}
    if collect_evals:
        out["trajectory"] = evals
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir",
                   default=os.path.join(REPO, ".cache", "real_jpegs"))
    p.add_argument("--images", type=int, default=2048)
    p.add_argument("--image-size", type=int, default=224,
                   help="JPEG side length on disk (decode target is the "
                        "model's input size)")
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--eval-batches", type=int, default=4)
    p.add_argument("--backend", default="tpu", choices=["tpu", "cpu"])
    p.add_argument("--loaders", default="tf,native,grain")
    p.add_argument("--classes", type=int, default=16)
    p.add_argument("--alpha", type=float, default=0.22,
                   help="corpus signal-to-noise knob (see ensure_corpus)")
    p.add_argument("--convergence-steps", type=int, default=0,
                   help="extra CPU-scale leg: train this many steps on the "
                        "graded corpus via the tf loader with periodic "
                        "eval, emitting the full eval-top1 trajectory "
                        "(the non-saturating convergence evidence — "
                        "VERDICT r4 Next #4). 0 = off")
    p.add_argument("--convergence-lr", type=float, default=None,
                   help="LR override for the convergence leg (recipe-"
                        "sensitivity A/B: run twice with different LRs)")
    p.add_argument("--leg-timeout", type=int, default=600)
    p.add_argument("--keep-checkpoints", action="store_true")
    args = p.parse_args(argv)

    # Recipe-keyed subdirectory: different --images/--image-size/--classes/
    # --alpha runs must never share split dirs (a smoke run would otherwise
    # overwrite part of a larger corpus, and a stale completeness marker
    # from one alpha would silently reuse pixels generated at another —
    # poisoning exactly the SNR A/B the knob exists for).
    args.data_dir = os.path.join(
        args.data_dir,
        f"{args.images}x{args.image_size}x{args.classes}a{args.alpha}")
    ensure_corpus(args.data_dir, args.images, args.image_size,
                  args.classes, args.alpha)
    ckroot = tempfile.mkdtemp(prefix="realdata_ck_")
    base = [sys.executable, os.path.join(REPO, "train.py"),
            "--backend", args.backend, "--model", args.model,
            "--batch-size", str(args.batch_size),
            "--eval-batches", str(args.eval_batches),
            # Decode/train at the corpus's own resolution — without this
            # every leg silently upscales to the 224 default (a 64px
            # corpus then pays ~12x the conv FLOPs for zero information).
            "--image-size", str(args.image_size),
            "--log-every", "25"]
    if args.backend == "cpu":
        base += ["--dtype", "float32"]

    results = []
    # Synthetic first: the ceiling the host pipelines are measured against.
    results.append(run_leg("synthetic", base + [
        "--synthetic", "--steps", str(args.steps)], args.leg_timeout))
    print(json.dumps(results[-1]), flush=True)

    for loader in [s for s in args.loaders.split(",") if s]:
        ck = os.path.join(ckroot, loader)
        cli = base + ["--data-dir", args.data_dir, "--loader", loader,
                      "--checkpoint-dir", ck,
                      "--checkpoint-every", str(max(args.steps // 2, 1))]
        results.append(run_leg(loader, cli + ["--steps", str(args.steps)],
                               args.leg_timeout))
        print(json.dumps(results[-1]), flush=True)
        if "error" in results[-1]:
            continue
        # Resume leg: same checkpoint dir, extended horizon — proves the
        # stream-meta pin accepts the same loader and training continues
        # from the mid-run save (start_step > 0).
        more = run_leg(f"{loader}_resume",
                       cli + ["--steps", str(args.steps + 20)],
                       args.leg_timeout)
        if "summary" in more:
            more["resume_start_step"] = more["summary"].get("start_step")
        results.append(more)
        print(json.dumps(more), flush=True)

    if args.convergence_steps > 0:
        # Long leg with periodic eval: the product is the TRAJECTORY (does
        # top-1 keep rising? where does it plateau?) on the graded corpus
        # where 1.0 is out of reach — a recipe change moves the plateau.
        cli = base + ["--data-dir", args.data_dir, "--loader", "tf",
                      "--steps", str(args.convergence_steps),
                      "--eval-every-epochs", "0.5"]
        if args.convergence_lr is not None:
            cli += ["--lr", str(args.convergence_lr)]
        conv = run_leg("convergence_tf", cli,
                       max(args.leg_timeout * 4, 1200), collect_evals=True)
        results.append(conv)
        print(json.dumps(conv), flush=True)

    if not args.keep_checkpoints:
        shutil.rmtree(ckroot, ignore_errors=True)

    # One digest line for BASELINE.md's real-data table.
    digest = {"digest": "real_data_path", "model": args.model,
              "batch_size": args.batch_size, "backend": args.backend}
    for r in results:
        s = r.get("summary")
        if s:
            digest[r["leg"]] = {
                "images_per_sec_per_chip": round(
                    s.get("examples_per_sec_per_chip", 0.0), 1),
                "final_top1": s.get("final_metrics", {}).get("accuracy"),
                "eval_top1": s.get("eval_top1"),
                "start_step": s.get("start_step")}
        else:
            digest[r["leg"]] = {"error": r.get("error")}
    print(json.dumps(digest), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
