#!/usr/bin/env python
"""Host input-pipeline throughput: native C++ loader vs tf.data vs grain.

The reference fed GPUs from DALI/tf.data native workers; this measures our
equivalents end-to-end (JPEG decode + ResNet augmentation + batch
assembly -> host float32 NHWC) on a synthetic image-folder corpus, so the
"does the host keep the chips fed" question has a number.

Prints one JSON line per pipeline: images/sec at the given image size.
A v5e chip at 2325 img/s needs that many decoded images/sec from its host
share; multiply by local chip count for the per-host requirement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_corpus(root: str, n: int, hw: int = 400) -> None:
    """n JPEGs in an image-folder layout (2 classes), ~ImageNet-sized."""
    from PIL import Image  # ships alongside tf in this stack

    rng = np.random.default_rng(0)
    for i in range(n):
        cls = os.path.join(root, f"class{i % 2}")
        os.makedirs(cls, exist_ok=True)
        arr = rng.integers(0, 256, (hw, hw, 3), np.uint8)
        Image.fromarray(arr).save(
            os.path.join(cls, f"img{i}.jpg"), quality=85)


def bench_native(data_dir: str, batch: int, size: int, batches: int) -> float:
    from distributeddeeplearning_tpu.data import imagenet, native

    paths, labels = imagenet.folder_index(data_dir, "train")
    loader = native.NativeImageLoader(
        paths, labels, batch_size=batch, image_size=size, train=True,
        seed=0, queue_depth=4)
    it = iter(loader)
    next(it)  # warm the thread pool
    t0 = time.perf_counter()
    for _ in range(batches):
        next(it)
    dt = time.perf_counter() - t0
    loader.close()
    return batch * batches / dt


def bench_tf(data_dir: str, batch: int, size: int, batches: int) -> float:
    import tensorflow as tf

    from distributeddeeplearning_tpu.config import DataConfig, TrainConfig
    from distributeddeeplearning_tpu.data import imagenet

    cfg = TrainConfig(
        global_batch_size=batch, dtype="float32",
        data=DataConfig(data_dir=data_dir, synthetic=False, image_size=size,
                        shuffle_buffer=256, loader="tf"))
    ds = imagenet.build_dataset(cfg, train=True)
    it = ds.as_numpy_iterator()
    next(it)
    t0 = time.perf_counter()
    for _ in range(batches):
        next(it)
    return batch * batches / (time.perf_counter() - t0)


def bench_grain(data_dir: str, batch: int, size: int, batches: int) -> float:
    from distributeddeeplearning_tpu.config import DataConfig, TrainConfig
    from distributeddeeplearning_tpu.data import grain_pipeline

    cfg = TrainConfig(
        global_batch_size=batch, dtype="float32",
        data=DataConfig(data_dir=data_dir, synthetic=False, image_size=size,
                        loader="grain"))
    # Explicit process args keep jax's backend un-initialized (host-only run).
    ds = grain_pipeline.build_grain_dataset(
        cfg, train=True, process_index=0, process_count=1)
    it = iter(ds)
    next(it)
    t0 = time.perf_counter()
    for _ in range(batches):
        next(it)
    return batch * batches / (time.perf_counter() - t0)


def bench_resume(data_dir: str, batch: int, size: int, depths) -> dict:
    """Time-to-first-batch at each resume depth, per loader — the cost a
    crash-restart pays before training resumes (VERDICT r2 Weak #4).

    grain positions by index arithmetic (cost ~flat in depth); tf.data
    replays the raw record stream through skip() (pre-decode, but linear
    in depth); the native loader's deterministic schedule seeks by batch
    index (flat)."""
    from distributeddeeplearning_tpu.config import DataConfig, TrainConfig
    from distributeddeeplearning_tpu.data import grain_pipeline, imagenet
    from distributeddeeplearning_tpu.data import native

    out: dict = {}
    cfgkw = dict(data_dir=data_dir, synthetic=False, image_size=size,
                 shuffle_buffer=256)

    def tf_first(depth):
        cfg = TrainConfig(global_batch_size=batch, dtype="float32",
                          data=DataConfig(loader="tf", **cfgkw))
        t0 = time.perf_counter()
        it = imagenet.build_dataset(
            cfg, train=True, start_step=depth).as_numpy_iterator()
        next(it)
        return time.perf_counter() - t0

    def grain_first(depth):
        cfg = TrainConfig(global_batch_size=batch, dtype="float32",
                          data=DataConfig(loader="grain", **cfgkw))
        t0 = time.perf_counter()
        it = iter(grain_pipeline.build_grain_dataset(
            cfg, train=True, process_index=0, process_count=1,
            start_step=depth))
        next(it)
        return time.perf_counter() - t0

    def native_first(depth):
        # folder_index inside the window: tf/grain index the corpus inside
        # their builders, so every loader times the same cold-restart span
        # (index + construct + position + first decode).
        t0 = time.perf_counter()
        paths, labels = imagenet.folder_index(data_dir, "train")
        loader = native.NativeImageLoader(
            paths, labels, batch_size=batch, image_size=size, train=True,
            seed=0, queue_depth=2, start_batch=depth)
        next(iter(loader))
        dt = time.perf_counter() - t0
        loader.close()
        return dt

    for name, fn in (("tf_data", tf_first), ("grain", grain_first),
                     ("native_cc", native_first)):
        try:
            out[name] = {str(d): round(fn(d), 3) for d in depths}
        except Exception as e:
            out[name] = {"error": str(e)[-200:]}
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--images", type=int, default=512)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--batches", type=int, default=24)
    p.add_argument("--resume-depths", default=None,
                   help="comma-separated resume depths (in batches) to "
                        "measure time-to-first-batch per loader, e.g. "
                        "0,100,1000")
    p.add_argument("--data-dir", default=None,
                   help="existing image-folder corpus (default: generate)")
    args = p.parse_args(argv)

    if args.data_dir:
        data_dir, cleanup = args.data_dir, None
    else:
        cleanup = tempfile.TemporaryDirectory(prefix="ddl_loaderbench_")
        data_dir = os.path.join(cleanup.name, "train")
        try:
            make_corpus(data_dir, args.images)
        except Exception as e:  # keep the one-JSON-line-per-item contract
            print(json.dumps({"pipeline": "corpus_generation",
                              "error": str(e)[-300:]}), flush=True)
            cleanup.cleanup()
            return 1
        data_dir = cleanup.name

    for name, fn in [("native_cc", bench_native), ("tf_data", bench_tf),
                     ("grain", bench_grain)]:
        try:
            rate = fn(data_dir, args.batch, args.image_size, args.batches)
            print(json.dumps({
                "pipeline": name, "images_per_sec": round(rate, 1),
                "image_size": args.image_size, "batch": args.batch,
                "host_cpus": os.cpu_count()}), flush=True)
        except Exception as e:  # keep the other pipeline's number
            print(json.dumps({"pipeline": name, "error": str(e)[-300:]}),
                  flush=True)
    if args.resume_depths:
        depths = [int(d) for d in args.resume_depths.split(",")]
        print(json.dumps({
            "pipeline": "resume_time_to_first_batch_s", "batch": args.batch,
            "depths": bench_resume(data_dir, args.batch, args.image_size,
                                   depths)}), flush=True)
    if cleanup:
        cleanup.cleanup()
    return 0


if __name__ == "__main__":
    sys.exit(main())
