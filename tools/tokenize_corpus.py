#!/usr/bin/env python
"""Raw text -> packed-token ``.npy`` shards for the MLM pipeline.

Produces exactly what ``data/tokens.py`` consumes (config 4,
BASELINE.json:10): ``<split>-NNNNN.npy`` files of int32 ids with shape
``(N, seq_len)``, BERT-style packed — ``[CLS] sent sent ... [SEP]`` greedily
filled per document, padded with ``[PAD]``.

Runs fully offline from a WordPiece ``vocab.txt`` (one token per line, the
standard BERT layout: [PAD]=0, [UNK]=100, [CLS]=101, [SEP]=102, [MASK]=103);
the in-tree WordPiece implementation is greedy longest-match-first with
``##`` continuations — byte-compatible with the canonical algorithm, no
tokenizer download needed.

Usage:
  python tools/tokenize_corpus.py --input corpus/*.txt --vocab vocab.txt \
      --out-dir /data/mlm --seq-len 128 [--split train] [--shard-size 65536]

Input format: plain text; blank lines separate documents (wiki-dump style).
Each line within a document is treated as one sentence for packing.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
import unicodedata
from typing import Iterator, Optional

import numpy as np


def load_vocab(path: str) -> dict[str, int]:
    vocab: dict[str, int] = {}
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            tok = line.rstrip("\n")
            if tok:
                vocab[tok] = i
    for required in ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"):
        if required not in vocab:
            raise ValueError(f"vocab {path!r} is missing {required}")
    return vocab


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or \
            (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def basic_tokenize(text: str, *, lowercase: bool = True) -> list[str]:
    """Whitespace + punctuation split (BERT's BasicTokenizer, sans CJK
    special-casing)."""
    if lowercase:
        text = text.lower()
        text = "".join(c for c in unicodedata.normalize("NFD", text)
                       if unicodedata.category(c) != "Mn")
    out: list[str] = []
    word = []
    for ch in text:
        if ch.isspace():
            if word:
                out.append("".join(word))
                word = []
        elif _is_punct(ch):
            if word:
                out.append("".join(word))
                word = []
            out.append(ch)
        else:
            word.append(ch)
    if word:
        out.append("".join(word))
    return out


class WordPiece:
    """Greedy longest-match-first WordPiece over a loaded vocab."""

    def __init__(self, vocab: dict[str, int], *, lowercase: bool = True,
                 max_chars_per_word: int = 100):
        self.vocab = vocab
        self.lowercase = lowercase
        self.unk = vocab["[UNK]"]
        self.max_chars = max_chars_per_word

    def encode_words(self, words: list[str]) -> list[int]:
        ids: list[int] = []
        for word in words:
            if len(word) > self.max_chars:
                ids.append(self.unk)
                continue
            start, pieces, bad = 0, [], False
            while start < len(word):
                end = len(word)
                cur: Optional[int] = None
                while start < end:
                    sub = word[start:end]
                    if start > 0:
                        sub = "##" + sub
                    if sub in self.vocab:
                        cur = self.vocab[sub]
                        break
                    end -= 1
                if cur is None:
                    bad = True
                    break
                pieces.append(cur)
                start = end
            ids.extend([self.unk] if bad else pieces)
        return ids

    def encode(self, text: str) -> list[int]:
        return self.encode_words(
            basic_tokenize(text, lowercase=self.lowercase))


def documents(paths: list[str]) -> Iterator[list[str]]:
    """Yield documents (lists of non-empty lines); blank line = boundary."""
    for path in paths:
        doc: list[str] = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    if doc:
                        yield doc
                        doc = []
                else:
                    doc.append(line)
        if doc:
            yield doc


def pack_documents(docs: Iterator[list[str]], wp: WordPiece,
                   seq_len: int) -> Iterator[np.ndarray]:
    """BERT packing: [CLS] sentences... [SEP], greedy fill, pad to seq_len.

    Sequences never cross document boundaries; a sentence longer than the
    budget is hard-truncated (canonical BERT prep behavior).
    """
    pad = wp.vocab["[PAD]"]
    cls_, sep = wp.vocab["[CLS]"], wp.vocab["[SEP]"]
    budget = seq_len - 2  # room for [CLS] ... [SEP]
    for doc in docs:
        cur: list[int] = []
        for sentence in doc:
            ids = wp.encode(sentence)
            while ids:
                space = budget - len(cur)
                take, ids = ids[:space], ids[space:]
                cur.extend(take)
                if len(cur) >= budget:
                    yield np.asarray(
                        [cls_] + cur + [sep], np.int32)
                    cur = []
        if cur:
            row = [cls_] + cur + [sep]
            yield np.asarray(row + [pad] * (seq_len - len(row)), np.int32)


def write_shards(rows: Iterator[np.ndarray], out_dir: str, split: str,
                 seq_len: int, shard_size: int) -> list[str]:
    os.makedirs(out_dir, exist_ok=True)
    written: list[str] = []
    buf: list[np.ndarray] = []

    def flush():
        if not buf:
            return
        path = os.path.join(out_dir, f"{split}-{len(written):05d}.npy")
        np.save(path, np.stack(buf))
        written.append(path)
        buf.clear()

    for row in rows:
        assert row.shape == (seq_len,)
        buf.append(row)
        if len(buf) >= shard_size:
            flush()
    flush()
    return written


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    p.add_argument("--input", nargs="+", required=True,
                   help="raw-text files or globs (blank line = doc boundary)")
    p.add_argument("--vocab", required=True, help="WordPiece vocab.txt")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--split", default="train",
                   help="output prefix: train | validation")
    p.add_argument("--shard-size", type=int, default=65536,
                   help="sequences per .npy shard")
    p.add_argument("--cased", action="store_true",
                   help="disable lowercasing/accent-stripping")
    args = p.parse_args(argv)

    paths = sorted(sum((glob.glob(g) for g in args.input), []))
    if not paths:
        print(f"no input files match {args.input}", file=sys.stderr)
        return 1
    wp = WordPiece(load_vocab(args.vocab), lowercase=not args.cased)
    rows = pack_documents(documents(paths), wp, args.seq_len)
    written = write_shards(rows, args.out_dir, args.split, args.seq_len,
                           args.shard_size)
    total = sum(int(np.load(p, mmap_mode="r").shape[0]) for p in written)
    print(f"wrote {total} sequences of seq_len={args.seq_len} across "
          f"{len(written)} shard(s) to {args.out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
