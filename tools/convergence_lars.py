#!/usr/bin/env python
"""Config-5 recipe convergence proof (VERDICT r2 Next #3, BASELINE.json:2).

The metric of record includes "top-1 @ 90 epochs"; 90 real ImageNet epochs
are out of reach in this container, so this tool runs the strongest
available substitute on one host: the SAME trainer, optimizer, accumulation
and schedule machinery as the acceptance configs, on the learnable-synthetic
task (data/synthetic.py: a class-conditioned pattern under noise), at an
epochs-scaled schedule:

  A. SGD baseline      — batch 256, momentum + warmup-cosine (the classic
                         small-batch recipe, linear-scaling reference).
  B. LARS large-batch  — batch 32768 exactly as preset `resnet50_lars_32k`
                         prescribes (LARS, lr 29 @ 32k, warmup-poly, bf16-
                         style recipe but f32 here for CPU determinism),
                         via 8-way DP x 16-step gradient accumulation —
                         one optimizer update per 32768 examples.

Both runs see the SAME number of epochs (total examples); the deliverable
is final held-out top-1 parity within noise, plus each run's in-training
eval curve. Model is resnet18_thin (width-16 ResNet-18) at 32x32 so the
whole proof fits in CPU-hours; the recipe under test — LARS trust ratios,
accumulation ≡ big batch, warmup-poly over epochs — is byte-identical to
what config 5 runs at scale.

Usage:
  python tools/convergence_lars.py [--epochs 24] [--epoch-examples 32768]
      [--out /tmp/convergence.json]

Prints one JSON line per completed phase and a final summary table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pin_cpu_mesh(n: int = 8) -> None:
    from distributeddeeplearning_tpu.hostmesh import pin_virtual_cpu_mesh

    pin_virtual_cpu_mesh(n)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=24)
    p.add_argument("--epoch-examples", type=int, default=32768)
    p.add_argument("--model", default="resnet18_thin")
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--sgd-batch", type=int, default=256)
    p.add_argument("--lars-batch", type=int, default=32768)
    p.add_argument("--lars-lr", type=float, default=29.0,
                   help="preset resnet50_lars_32k peak LR; override only "
                        "to debug divergence")
    p.add_argument("--eval-batches", type=int, default=8,
                   help="final held-out eval: this many batch-256 batches, "
                        "identical set for both runs")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel shards. Default 1: on a single host "
                        "the 8-fake-device mesh serializes ~5x slower, and "
                        "dp-vs-accum equivalence is already proven by "
                        "tests (test_dp, test_accum); the 32k mechanism "
                        "under test here is accumulation")
    p.add_argument("--out", default="/tmp/convergence_lars.json")
    args = p.parse_args(argv)

    _pin_cpu_mesh()

    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    data = DataConfig(synthetic=True, image_size=args.image_size,
                      num_classes=args.num_classes, synthetic_learnable=True)
    total_examples = args.epochs * args.epoch_examples

    def run_one(tag: str, batch: int, accum: int, opt: OptimizerConfig,
                eval_every_epochs: float, eval_batches: int):
        steps_per_epoch = max(args.epoch_examples // batch, 1)
        total_steps = max(total_examples // batch, 1)
        cfg = TrainConfig(
            model=args.model, global_batch_size=batch, dtype="float32",
            grad_accum_steps=accum, log_every=10**9,
            steps_per_epoch=steps_per_epoch,
            eval_every_epochs=eval_every_epochs,
            parallel=ParallelConfig(data=args.dp), data=data, optimizer=opt)
        t0 = time.time()
        import warnings
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore",
                                    message=".*BatchNorm statistics.*")
            summary = loop.run(cfg, total_steps=total_steps,
                               eval_batches=eval_batches, return_state=True,
                               logger=MetricLogger(enabled=False))
        state = summary.pop("state")
        rec = {"phase": tag, "batch": batch, "updates": total_steps,
               "epochs": args.epochs,
               "final_train_loss": summary["final_metrics"].get("loss"),
               "evals": summary.get("evals"),
               "eval_top1_curve_final": summary.get("eval_top1"),
               "wall_s": round(time.time() - t0, 1)}
        print(json.dumps(rec), flush=True)
        return state, cfg, rec

    # --- A: SGD baseline -------------------------------------------------
    sgd_opt = OptimizerConfig(
        name="sgd", learning_rate=0.1, reference_batch=256, momentum=0.9,
        weight_decay=1e-4, warmup_epochs=1.0, schedule="warmup_cosine",
        label_smoothing=0.1)
    sgd_state, sgd_cfg, sgd_rec = run_one(
        "sgd_b256", args.sgd_batch, 1, sgd_opt,
        eval_every_epochs=2.0, eval_batches=2)

    # --- B: LARS 32k via accumulation (preset resnet50_lars_32k recipe) --
    lars_opt = OptimizerConfig(
        name="lars", learning_rate=args.lars_lr,
        reference_batch=args.lars_batch, momentum=0.9, weight_decay=1e-4,
        warmup_epochs=5.0, schedule="warmup_poly", label_smoothing=0.1)
    lars_accum = max(args.lars_batch // (args.sgd_batch * args.dp), 1)
    lars_state, lars_cfg, lars_rec = run_one(
        "lars_b32k", args.lars_batch, lars_accum, lars_opt,
        eval_every_epochs=4.0, eval_batches=1)

    # --- Final apples-to-apples eval: same batch-256 held-out set --------
    eval_cfg = sgd_cfg.replace(grad_accum_steps=1)
    mesh, model, batch_shd, _, _, _, _ = loop.build(eval_cfg, 1)
    evaluator = loop._Evaluator(eval_cfg, mesh, model, batch_shd,
                                args.eval_batches)
    finals = {}
    for tag, state in (("sgd_b256", sgd_state), ("lars_b32k", lars_state)):
        finals[tag] = evaluator(state)
        print(json.dumps({"phase": f"final_eval/{tag}",
                          "eval_top1": finals[tag]}), flush=True)

    gap = finals["sgd_b256"] - finals["lars_b32k"]
    result = {
        "model": args.model, "epochs": args.epochs,
        "epoch_examples": args.epoch_examples,
        "final_top1": finals, "top1_gap_sgd_minus_lars": round(gap, 4),
        "parity_within_2pct": abs(gap) <= 0.02,
        "sgd": sgd_rec, "lars": lars_rec,
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"phase": "RESULT", **{k: result[k] for k in (
        "final_top1", "top1_gap_sgd_minus_lars", "parity_within_2pct")}}),
        flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
