#!/usr/bin/env python
"""On-hardware validation of the fused 3x3 conv kernel (fused_block v2).

COMPILED (not interpret) bn_conv3x3_stats forward + VJP at real ResNet50
bottleneck shapes vs the unfused f32 reference, one JSON line per shape;
then a single-kernel timing line per shape. Cheap (~tens of seconds) and
deliberately scheduled BEFORE the --conv3 A/B in tools/chip_window.sh: if
Mosaic rejects the kernel (manual-DMA halo slabs, in-VMEM im2col — first
compiled here), that verdict must cost seconds, not the A/B budget.

Exits nonzero on a correctness failure.
    python tools/validate_fused_conv_tpu.py [--batch 32]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


# ResNet50 stride-1 bottleneck conv2 shapes at 224px: (H, W, Cin=Cout=f).
SHAPES = ((56, 56, 64), (28, 28, 128), (14, 14, 256), (7, 7, 512))


def check_shape(batch: int, h: int, w: int, f: int) -> bool:
    from distributeddeeplearning_tpu.ops import fused_conv_bn as fc

    ks = jax.random.split(jax.random.key(f), 6)
    x = jax.random.normal(ks[0], (batch, h, w, f), jnp.bfloat16)
    wk = (jax.random.normal(ks[1], (3, 3, f, f), jnp.float32) * 0.05)
    mu = x.astype(jnp.float32).mean(axis=(0, 1, 2))
    inv = jax.lax.rsqrt(x.astype(jnp.float32).var(axis=(0, 1, 2)) + 1e-5)
    g = jnp.abs(jax.random.normal(ks[2], (f,))) + 0.5
    b = jax.random.normal(ks[3], (f,)) * 0.1
    cot = jax.random.normal(ks[4], (3,))

    def scalar(fn):
        def run(x, mu, inv, g, b, wk):
            y, s, ss = fn(x, mu, inv, g, b, wk)
            return (cot[0] * (y.astype(jnp.float32) ** 2).mean()
                    + cot[1] * s.sum() * 1e-3 + cot[2] * (ss * 1e-3).sum())
        return run

    fused = scalar(lambda *a: fc.bn_conv3x3_stats(*a, True, True))
    ref = scalar(lambda *a: fc._twin_fwd(*a[:5], a[5], True, True))

    t0 = time.perf_counter()
    gf = jax.device_get(jax.jit(jax.grad(fused, argnums=(0, 5)))(
        x, mu, inv, g, b, wk))
    compile_s = time.perf_counter() - t0
    gr = jax.device_get(jax.jit(jax.grad(ref, argnums=(0, 5)))(
        x, mu, inv, g, b, wk))
    errs = {}
    ok = True
    for name, a_, b_ in (("dx", gf[0], gr[0]), ("dw", gf[1], gr[1])):
        import numpy as np
        err = float(np.abs(np.asarray(a_, np.float32)
                           - np.asarray(b_, np.float32)).max())
        den = float(np.abs(np.asarray(b_, np.float32)).max()) + 1e-9
        errs[name] = round(err / den, 5)
        ok = ok and err / den < 2e-2  # bf16 MXU vs XLA conv rounding
    # Forward value check too.
    yk = jax.device_get(jax.jit(
        lambda *a: fc.bn_conv3x3_stats(*a, True, True))(x, mu, inv, g, b,
                                                        wk))
    yr = jax.device_get(jax.jit(
        lambda *a: fc._twin_fwd(*a, True, True))(x, mu, inv, g, b, wk))
    import numpy as np
    yerr = float(np.abs(np.asarray(yk[0], np.float32)
                        - np.asarray(yr[0], np.float32)).max())
    errs["y_abs"] = round(yerr, 5)
    ok = ok and yerr < 0.25  # bf16 ULP at O(10) magnitudes

    # Single-op timing: fused kernel vs bn-apply + XLA conv + stats.
    fwd_fused = jax.jit(lambda *a: fc.bn_conv3x3_stats(*a, True, True))
    fwd_ref = jax.jit(lambda *a: fc._twin_fwd(*a, True, True))

    def t(fn):
        out = fn(x, mu, inv, g, b, wk)
        jax.device_get(out[1])
        t0 = time.perf_counter()
        for _ in range(10):
            out = fn(x, mu, inv, g, b, wk)
        jax.device_get(out[1])
        return (time.perf_counter() - t0) / 10

    print(json.dumps({
        "check": "fused_conv3_validate", "shape": [batch, h, w, f],
        "ok": ok, "rel_err": errs, "compile_s": round(compile_s, 1),
        "fused_ms": round(t(fwd_fused) * 1e3, 2),
        "ref_ms": round(t(fwd_ref) * 1e3, 2)}), flush=True)
    return ok


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--quick", action="store_true",
                   help="only the extreme shapes (56x56x64, 7x7x512) — "
                        "the window-budget Mosaic smoke check")
    p.add_argument("--platform", default=None)
    args = p.parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    shapes = (SHAPES[0], SHAPES[-1]) if args.quick else SHAPES
    ok = True
    for h, w, f in shapes:
        try:
            ok = check_shape(args.batch, h, w, f) and ok
        except Exception as e:
            print(json.dumps({
                "check": "fused_conv3_validate", "shape": [args.batch, h, w, f],
                "ok": False,
                "error": f"{type(e).__name__}: {e}"[:400]}), flush=True)
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
