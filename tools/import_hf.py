#!/usr/bin/env python
"""Import a local HuggingFace checkpoint into a framework checkpoint.

    python tools/import_hf.py --hf-dir /path/to/hf_model --out ckpt/imported \
        [--family llama|gpt2|bert]

Reads the HF model with transformers (torch CPU, local files only — this
environment has no network egress, which is also why imports take a
directory, not a hub name), maps the weights through
utils/hf_convert.py (the mapping tests/test_hf_parity.py proves
logit-exact), and writes an orbax step-0 checkpoint whose ``params``
subtree matches the corresponding framework model — consumable by
``generate.py --checkpoint-dir``, ``train.py --eval-only``, or as a
finetune starting point with ``--resume``.

Prints one JSON line: the family, layer/param counts, and the framework
model constructor kwargs that reproduce the architecture.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.utils import hf_convert


def model_kwargs(family: str, cfg) -> dict:
    """Framework model-constructor kwargs mirroring the HF architecture —
    what a user passes to models/{llama,gpt,bert}.py to load the import."""
    if family == "llama":
        # Options our Llama implementation does not have: reject rather
        # than import a checkpoint that would compute something different.
        for opt in ("attention_bias", "mlp_bias"):
            if getattr(cfg, opt, False):
                raise SystemExit(f"unsupported llama option {opt}=True")
        if getattr(cfg, "rope_scaling", None):
            raise SystemExit("unsupported llama option rope_scaling="
                             f"{cfg.rope_scaling!r}")
        return dict(vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
                    num_layers=cfg.num_hidden_layers,
                    num_heads=cfg.num_attention_heads,
                    num_kv_heads=cfg.num_key_value_heads,
                    intermediate_size=cfg.intermediate_size,
                    rope_theta=cfg.rope_theta, rms_eps=cfg.rms_norm_eps)
    if family == "gpt2":
        return dict(vocab_size=cfg.vocab_size, hidden_size=cfg.n_embd,
                    num_layers=cfg.n_layer, num_heads=cfg.n_head,
                    max_position=cfg.n_positions)
    if family == "bert":
        return dict(vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
                    num_layers=cfg.num_hidden_layers,
                    num_heads=cfg.num_attention_heads,
                    intermediate_size=cfg.intermediate_size,
                    max_position=cfg.max_position_embeddings,
                    type_vocab_size=cfg.type_vocab_size,
                    layer_norm_eps=cfg.layer_norm_eps)
    raise SystemExit(f"unsupported family {family!r}; "
                     f"supported: {sorted(hf_convert.CONVERTERS)}")


def load_hf(hf_dir: str, family: str):
    import transformers

    loaders = {
        "llama": transformers.LlamaForCausalLM,
        "gpt2": transformers.GPT2LMHeadModel,
        "bert": transformers.BertForMaskedLM,
    }
    model = loaders[family].from_pretrained(hf_dir, local_files_only=True)
    return model.config, model.state_dict()


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--hf-dir", required=True,
                   help="local directory with config.json + weights")
    p.add_argument("--out", required=True,
                   help="checkpoint directory to write (orbax, step 0)")
    p.add_argument("--family", default=None,
                   choices=[None, *sorted(hf_convert.CONVERTERS)],
                   help="architecture family; default: config.json "
                        "model_type")
    args = p.parse_args(argv)

    with open(os.path.join(args.hf_dir, "config.json")) as f:
        model_type = json.load(f).get("model_type", "")
    family = args.family or model_type
    if family not in hf_convert.CONVERTERS:
        raise SystemExit(f"unsupported model_type {model_type!r}; "
                         f"supported: {sorted(hf_convert.CONVERTERS)}")

    cfg, sd = load_hf(args.hf_dir, family)
    kwargs = model_kwargs(family, cfg)  # validates unsupported options
    _, layers_key = hf_convert.CONVERTERS[family]
    params = hf_convert.convert_checked(
        family, hf_convert.state_dict_to_numpy(sd), getattr(cfg, layers_key))

    import orbax.checkpoint as ocp

    mgr = ocp.CheckpointManager(os.path.abspath(args.out))
    # The {params, batch_stats, step} layout Checkpointer's partial
    # restores expect (restore_latest_params / restore_latest_for_eval).
    mgr.save(0, args=ocp.args.StandardSave(
        {"params": params, "batch_stats": None, "step": 0}))
    mgr.wait_until_finished()
    mgr.close()

    n_params = sum(int(v.size) for v in
                   __import__("jax").tree.leaves(params))
    print(json.dumps({
        "family": family, "layers": getattr(cfg, layers_key),
        "param_count": n_params, "out": os.path.abspath(args.out),
        "model_kwargs": kwargs,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
