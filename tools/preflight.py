#!/usr/bin/env python
"""Fast backend preflight with bounded retry: is the chip tunnel alive?

    python tools/preflight.py [--timeout 20] [--attempts 2] [--backoff 3]
                              [--out PATH]

Probes backend init in a SUBPROCESS (a hung ``jax.devices()`` must be
killable) with a hard per-attempt timeout and bounded backoff between
attempts. Exit 0 when the backend answered; exit 1 when it never did.
Either way, ONE perf_report-schema record lands on stdout (and in
``--out`` when given):

  * up   — ``provenance: fresh``, value = init seconds, backend identity;
  * down — ``provenance: error``, value null, full attempt history.

The point (BENCH_r02-r05): a dead tunnel used to cost 75-219 s of
bench-harness timeouts before the window learned the truth. This probe
answers in seconds and its error record is a valid bench artifact, so
``chip_window.sh`` can fail the whole window fast AND leave evidence.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.observability import perf_report  # noqa: E402

_PROBE = """
import json, jax
d = jax.devices()[0]
print(json.dumps({"platform": d.platform,
                  "device_kind": getattr(d, "device_kind", "?"),
                  "device_count": jax.device_count(),
                  "process_count": jax.process_count()}), flush=True)
"""


def probe_once(timeout: float) -> tuple[dict | None, str]:
    """One subprocess probe. Returns (backend_identity, "") on success or
    (None, reason) on failure; never raises, never hangs past timeout."""
    t0 = time.monotonic()
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE], capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, f"probe timed out after {timeout:g}s (tunnel hung)"
    except OSError as e:
        return None, f"probe failed to launch: {e}"
    if out.returncode != 0:
        tail = (out.stderr or "").strip().splitlines()[-3:]
        return None, (f"probe rc={out.returncode}: "
                      + (" | ".join(tail) or "no stderr"))
    for line in reversed((out.stdout or "").splitlines()):
        try:
            ident = json.loads(line)
            if isinstance(ident, dict) and "platform" in ident:
                ident["init_s"] = round(time.monotonic() - t0, 2)
                return ident, ""
        except ValueError:
            continue
    return None, "probe printed no identity line"


def run(timeout: float = 20.0, attempts: int = 2,
        backoff: float = 3.0) -> dict:
    """Bounded-retry probe; returns the schema record (never raises)."""
    history: list[dict] = []
    for attempt in range(1, max(attempts, 1) + 1):
        if attempt > 1:
            time.sleep(backoff)
        ident, reason = probe_once(timeout)
        if ident is not None:
            rec = {
                "metric": "backend_preflight",
                "value": ident.pop("init_s", None),
                "unit": "s_to_backend_up",
                "backend": ident,
            }
            history.append({"attempt": attempt, "rc": "up"})
            # with_backend=False: identity comes from the CHILD that
            # actually initialized; the parent must stay jax-free.
            return perf_report.annotate(rec, provenance="fresh",
                                        attempts=history,
                                        with_backend=False)
        history.append({"attempt": attempt, "rc": reason})
    rec = {
        "metric": "backend_preflight",
        "value": None,
        "unit": "s_to_backend_up",
        "error": (f"backend never came up in {attempts} attempt(s) x "
                  f"{timeout:g}s: {history[-1]['rc']}"),
    }
    return perf_report.annotate(rec, provenance="error", attempts=history,
                                with_backend=False)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--timeout", type=float, default=20.0,
                   help="per-attempt probe timeout (s); live-chip init "
                        "lands in seconds, so 20 is generous")
    p.add_argument("--attempts", type=int, default=2,
                   help="bounded retries before declaring the tunnel down")
    p.add_argument("--backoff", type=float, default=3.0,
                   help="sleep (s) between attempts")
    p.add_argument("--out", default=None,
                   help="also write the record to this path")
    args = p.parse_args(argv)
    rec = run(timeout=args.timeout, attempts=args.attempts,
              backoff=args.backoff)
    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    return 0 if rec["provenance"] == "fresh" else 1


if __name__ == "__main__":
    sys.exit(main())
