#!/usr/bin/env python
"""Continuous-batching serve bench: seeded Poisson open-loop load.

    python tools/bench_serve.py [--model gpt2_small] [--requests 16]
        [--rate 80] [--max-new 16] [--platform cpu]

At the default load (80 req/s against a batch-1 capacity of a few
requests/sec) both arms are saturated, so tokens/sec/chip measures
engine capacity, not the arrival rate. At low rates both arms are
arrival-limited and the speedup tends to 1 by construction.

One requester process submits requests at exponential inter-arrival times
(open loop: arrivals do not wait for completions — the honest serving
load model) against two arms over the SAME request trace:

- **continuous** — serve/engine.py: slots admitted/retired every step,
  paged KV cache, prefill/decode split;
- **sequential baseline** — models/generate.py ``use_cache=True``, one
  request at a time in arrival order (what the repo could do before this
  engine existed). Its TTFT is the full generation latency: the
  ``generate()`` API yields nothing until the scan finishes, which is
  precisely the serving gap the engine closes. Its inter-token latency is
  the per-call average (scan internals are not observable).

Both arms run greedy, so outputs are token-identical — the bench asserts
it request-by-request (``token_identity_checked``) before reporting any
number. ``--chaos`` adds a third, supervised arm (launch.run_serve, two
replicas): the same trace fault-free and then under ``sigkill`` +
``decode_stall`` injection, reporting p50/p99 TTFT, tokens/sec/chip and
``recovery_overhead_frac`` — after asserting the recovered streams are
token-identical to the fault-free run and the page-leak check held.
Records are provenance-stamped via observability/perf_report.py;
the summary lands in the ``last_serve`` sidecar
(observability/sidecars.py) for tools/doctor.py.

Serve fast path (docs/serving.md "Prefix reuse" / "Speculative
decoding"): ``--prefix-cache`` / ``--spec-draft-model``+``--spec-k``
turn the engine features on; ``--shared-prefix-len N`` makes the trace
realistic for them — every tenant gets its own seeded N-token "system
prompt" and each request is that shared head plus a unique tail, so the
radix cache has real reuse to find. Prefix hit rate, tokens reused, COW
copies, evictions and speculative acceptance are stamped into the
record.

``--fixed-slo S`` switches to the capacity-at-SLO protocol the fast
path is judged by: sweep offered load (``--slo-rates``), run the
configured engine AND a features-off baseline (the PR-12 engine) over
the SAME trace at each rate, assert token identity between them, and
report each arm's best tokens/sec/chip among rates whose p99 TTFT still
meets the SLO — raw throughput at blown latency does not count.
``speedup_at_slo`` is the fast/baseline ratio of those numbers.

``--trace-dir DIR`` turns on per-request tracing (docs/serve_tracing.md):
the continuous arm writes a Chrome trace to ``DIR/trace.p0.json`` and the
record gains ``continuous.ttft_attribution`` — p50/p99/mean of each TTFT
component (queue / admission_stall / prefill / interference / decode),
reported only after every request's components are verified to sum back
to its measured TTFT within 1 ms. With ``--chaos`` the supervised arm
writes per-replica traces under ``DIR/chaos/`` and the bench asserts the
re-dispatched requests' spans are flow-linked across both replica pids
in the merged trace. Read the breakdown with
``python tools/trace_report.py --serve DIR``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pct(values, q):
    if not values:
        return None
    import numpy as np
    return round(float(np.percentile(np.asarray(values, float), q)), 6)


def _latency_block(ttfts, itls):
    return {"ttft_s": {"p50": _pct(ttfts, 50), "p99": _pct(ttfts, 99)},
            "itl_s": {"p50": _pct(itls, 50), "p99": _pct(itls, 99)}}


def _ttft_attribution(requests) -> dict:
    """Aggregate the tracer's per-request TTFT decomposition (queue /
    admission_stall / prefill / interference / decode) into p50/p99/mean
    per component, after asserting each request's components sum back to
    its measured TTFT within 1 ms — an attribution that does not add up
    is not reported."""
    from distributeddeeplearning_tpu.serve import tracing

    per_comp = {c: [] for c in tracing.COMPONENTS}
    ttft_errs, total_errs = [], []
    for r in requests:
        rt = getattr(r, "trace", None)
        if rt is None or rt.ttft_comp is None or r.ttft_s is None:
            continue
        ttft_errs.append(abs(sum(rt.ttft_comp.values()) - r.ttft_s))
        if r.finished_s is not None:
            total_errs.append(abs(sum(rt.comp.values())
                                  - (r.finished_s - r.arrival_s)))
        for c in tracing.COMPONENTS:
            per_comp[c].append(rt.ttft_comp.get(c, 0.0))
    if ttft_errs and max(ttft_errs) >= 1e-3:
        raise AssertionError(
            f"TTFT attribution components sum {max(ttft_errs) * 1e3:.3f} ms "
            f"away from the measured TTFT — the exact-sum protocol is "
            f"broken; do not trust the breakdown")
    out = {c: {"p50": _pct(v, 50), "p99": _pct(v, 99),
               "mean": round(sum(v) / len(v), 6) if v else None}
           for c, v in per_comp.items()}
    out["requests"] = len(ttft_errs)
    out["max_ttft_sum_err_ms"] = (round(max(ttft_errs) * 1e3, 6)
                                  if ttft_errs else None)
    out["max_total_sum_err_ms"] = (round(max(total_errs) * 1e3, 6)
                                   if total_errs else None)
    return out


def run_continuous(engine, trace, clock):
    """Drive the engine under the arrival trace (real sleeps in the idle
    gaps — open loop, submission never waits for completions)."""
    t0 = clock()
    pending = list(trace)
    while pending or not engine.idle:
        now = clock() - t0
        while pending and pending[0]["arrival_s"] <= now:
            item = pending.pop(0)
            engine.submit(item["prompt"],
                          max_new_tokens=item["max_new_tokens"],
                          tenant=item["tenant"],
                          arrival_s=t0 + item["arrival_s"])
        if engine.idle and pending:
            time.sleep(max(0.0, pending[0]["arrival_s"] - (clock() - t0)))
            continue
        engine.step()
    done = {r.uid: r for r in engine.finished}
    end = max(r.finished_s for r in done.values())
    total_tokens = sum(len(r.tokens) for r in done.values())
    return {
        "requests": [done[uid] for uid in sorted(done)],
        "tokens": total_tokens,
        "window_s": end - (t0 + trace[0]["arrival_s"]),
        "steps": engine.steps,
        "preemptions": engine.preemptions,
    }


def run_sequential(model, variables, trace, clock):
    """FIFO batch-1 ``generate(use_cache=True)`` over the same trace —
    the strongest form of the old API: each distinct
    (prompt_len, max_new) shape is jit-wrapped and warmed before timing
    (bare ``generate`` re-traces its scan per call; charging the baseline
    for that would inflate the speedup with Python overhead instead of
    measuring batching)."""
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.models.generate import generate

    compiled = {}
    for plen in sorted({len(t["prompt"]) for t in trace}):
        for mnew in sorted({t["max_new_tokens"] for t in trace
                            if len(t["prompt"]) == plen}):
            fn = jax.jit(lambda v, ids, m=mnew: generate(
                model, v, ids, max_new_tokens=m, use_cache=True))
            jax.block_until_ready(
                fn(variables, jnp.ones((1, plen), jnp.int32)))
            compiled[(plen, mnew)] = fn

    t0 = clock()
    results = []
    total_tokens = 0
    for item in trace:
        wait = item["arrival_s"] - (clock() - t0)
        if wait > 0:
            time.sleep(wait)
        fn = compiled[(len(item["prompt"]), item["max_new_tokens"])]
        out = fn(variables, jnp.asarray([item["prompt"]], jnp.int32))
        jax.block_until_ready(out)
        done_s = clock() - t0
        toks = [int(x) for x in
                list(jax.device_get(out)[0][len(item["prompt"]):])]
        total_tokens += len(toks)
        results.append({
            "tokens": toks,
            "ttft_s": done_s - item["arrival_s"],
            "itl_s": ((done_s - item["arrival_s"]) / len(toks)
                      if toks else None),
        })
    end = clock() - t0
    return {"results": results, "tokens": total_tokens,
            "window_s": end - trace[0]["arrival_s"]}


def _run_fixed_slo(args, cfg, base, make_trace, fast_path_counters) -> int:
    """Capacity at a fixed p99 TTFT SLO: sweep offered load, run the
    configured (fast) engine and a features-off baseline over the same
    trace at each rate, keep each arm's best tokens/sec/chip among rates
    that still meet the SLO. Token identity between arms is asserted at
    every rate before anything is reported."""
    import dataclasses as dcl
    import json as jsonlib

    import jax

    from distributeddeeplearning_tpu.observability import perf_report
    from distributeddeeplearning_tpu.observability import sidecars
    from distributeddeeplearning_tpu.serve.engine import Engine

    clock = time.monotonic
    n_chips = jax.device_count()
    base_cfg = dcl.replace(cfg, prefix_cache=False, spec_draft_model=None,
                           spec_k=0)
    rates = [float(x) for x in args.slo_rates.split(",") if x]
    rec = dict(base)
    rec["mode"] = "fixed_slo"
    rec["slo_p99_ttft_s"] = args.fixed_slo
    sweep = []
    best = {"fast": None, "baseline": None}
    for rate in rates:
        trace = make_trace(rate)
        point = {"rate_rps": rate}
        arm_tokens = {}
        for arm, acfg in (("fast", cfg), ("baseline", base_cfg)):
            engine = Engine(acfg, clock=clock)
            engine.warmup()
            res = run_continuous(engine, trace, clock)
            tps = res["tokens"] / res["window_s"] / n_chips
            p99 = _pct([r.ttft_s for r in res["requests"]], 99)
            arm_tokens[arm] = [r.tokens for r in res["requests"]]
            point[arm] = {
                "tokens_per_sec_per_chip": round(tps, 1),
                "p99_ttft_s": p99,
                "meets_slo": bool(p99 <= args.fixed_slo),
                **fast_path_counters(engine),
            }
            if p99 <= args.fixed_slo and (
                    best[arm] is None
                    or tps > best[arm]["tokens_per_sec_per_chip"]):
                best[arm] = {"rate_rps": rate,
                             "tokens_per_sec_per_chip": round(tps, 1),
                             "p99_ttft_s": p99}
        if arm_tokens["fast"] != arm_tokens["baseline"]:
            mism = [i for i, (a, b) in enumerate(
                zip(arm_tokens["fast"], arm_tokens["baseline"]))
                if a != b]
            raise AssertionError(
                f"fast vs baseline token mismatch at rate {rate} for "
                f"requests {mism[:5]} — the fast path must be "
                f"token-identical; do not trust either number")
        point["token_identity_checked"] = True
        sweep.append(point)
    rec["sweep"] = sweep
    rec["fast_at_slo"] = best["fast"]
    rec["baseline_at_slo"] = best["baseline"]
    rec["token_identity_checked"] = True
    rec["value"] = (best["fast"]["tokens_per_sec_per_chip"]
                    if best["fast"] else None)
    if best["fast"] and best["baseline"]:
        rec["speedup_at_slo"] = round(
            best["fast"]["tokens_per_sec_per_chip"]
            / best["baseline"]["tokens_per_sec_per_chip"], 2)
    perf_report.annotate(rec, provenance="fresh")
    print(jsonlib.dumps(rec), flush=True)
    sidecars.write("last_serve", {"record": rec})
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2_small")
    p.add_argument("--vocab-size", type=int, default=1024,
                   help="shrunk head keeps the CPU default tractable; "
                        "weight traffic (the thing batching amortizes) "
                        "is still dominated by the 12 real layers")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--rate", type=float, default=80.0,
                   help="mean arrival rate, requests/sec (Poisson)")
    p.add_argument("--prompt-lens", default="6,10,14",
                   help="comma list; each request draws one uniformly")
    p.add_argument("--max-new", type=int, default=16)
    p.add_argument("--tenants", default="default",
                   help="comma list; requests round-robin across them")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-slots", type=int, default=8)
    p.add_argument("--page-size", type=int, default=8)
    p.add_argument("--num-pages", type=int, default=128)
    p.add_argument("--max-pages-per-slot", type=int, default=4)
    p.add_argument("--prefill-buckets", default="16,32")
    p.add_argument("--platform", default=None)
    p.add_argument("--compile-cache-dir", default=None)
    p.add_argument("--prefix-cache", action="store_true",
                   help="radix prefix cache on (serve fast path)")
    p.add_argument("--spec-draft-model", default=None,
                   help="drafter model name: speculative decoding on")
    p.add_argument("--spec-k", type=int, default=0,
                   help="drafted tokens per speculative round")
    p.add_argument("--shared-prefix-len", type=int, default=0,
                   help="per-tenant shared system-prompt length; each "
                        "request is that head + a unique tail drawn "
                        "from --prompt-lens")
    p.add_argument("--fixed-slo", type=float, default=None,
                   help="p99 TTFT SLO in seconds: sweep --slo-rates and "
                        "report capacity at the SLO, fast vs features-off "
                        "baseline")
    p.add_argument("--slo-rates", default="20,40,80,160",
                   help="offered loads (req/s) the --fixed-slo sweep "
                        "visits")
    p.add_argument("--skip-baseline", action="store_true",
                   help="continuous arm only (no speedup field)")
    p.add_argument("--trace-dir", default=None,
                   help="enable per-request tracing + TTFT attribution; "
                        "the continuous arm's Chrome trace lands at "
                        "<dir>/trace.p0.json and the record gains a "
                        "ttft_attribution block (p50/p99/mean per "
                        "component, exact-sum checked); with --chaos the "
                        "supervised arm writes a merged multi-replica "
                        "trace under <dir>/chaos/")
    p.add_argument("--chaos", action="store_true",
                   help="add a supervised chaos arm: the same trace "
                        "through launch.run_serve twice (2 replicas) — "
                        "fault-free, then with replica 0 SIGKILLed "
                        "mid-decode and replica 1 decode-stalled — and "
                        "report p50/p99 TTFT, tokens/sec/chip and the "
                        "recovery overhead vs the supervised fault-free "
                        "window, asserting recovery is token-identical "
                        "and the page-leak check holds")
    args = p.parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform

    import numpy as np

    import jax

    from distributeddeeplearning_tpu.models import flops as flopslib
    from distributeddeeplearning_tpu.observability import perf_report
    from distributeddeeplearning_tpu.observability import sidecars
    from distributeddeeplearning_tpu.observability import telemetry
    from distributeddeeplearning_tpu.serve.engine import Engine, ServeConfig

    if args.trace_dir:
        # Must precede Engine construction: the engine resolves its
        # tracer once, at build time (the zero-overhead-off contract).
        telemetry.configure(enabled=True, trace_dir=args.trace_dir,
                            process_index=0, process_name="bench-serve")

    prompt_lens = [int(x) for x in args.prompt_lens.split(",") if x]
    tenants = [t for t in args.tenants.split(",") if t]
    cfg = ServeConfig(
        model=args.model, vocab_size=args.vocab_size, dtype=args.dtype,
        max_slots=args.max_slots, page_size=args.page_size,
        num_pages=args.num_pages,
        max_pages_per_slot=args.max_pages_per_slot,
        prefill_buckets=tuple(int(x) for x in
                              args.prefill_buckets.split(",") if x),
        seed=args.seed, prefix_cache=args.prefix_cache,
        spec_draft_model=args.spec_draft_model, spec_k=args.spec_k,
        compile_cache_dir=args.compile_cache_dir)

    # Per-tenant shared system prompts, fixed across every arm and every
    # sweep rate: real multi-tenant traffic repeats the instruction head,
    # which is exactly the structure the radix prefix cache exploits.
    srng = np.random.default_rng(args.seed + 7)
    shared_heads = {
        t: [int(x) for x in
            srng.integers(1, args.vocab_size, args.shared_prefix_len)]
        for t in tenants}

    def make_trace(rate: float) -> list:
        """Seeded trace: Poisson arrivals (exponential gaps), uniform
        tail lengths, random token ids — identical request contents at
        every rate (only the arrival gaps scale), identical for every
        arm."""
        rng = np.random.default_rng(args.seed)
        gaps = rng.exponential(1.0 / rate, args.requests)
        arrivals = np.cumsum(gaps) - gaps[0]  # first request at t=0
        trace = []
        for i in range(args.requests):
            plen = int(rng.choice(prompt_lens))
            tenant = tenants[i % len(tenants)]
            trace.append({
                "arrival_s": float(arrivals[i]),
                "prompt": shared_heads[tenant] + [
                    int(x) for x in rng.integers(1, args.vocab_size, plen)],
                "max_new_tokens": args.max_new,
                "tenant": tenant,
            })
        return trace

    trace = make_trace(args.rate)

    clock = time.monotonic
    base = {
        "metric": "serve_tokens_per_sec_per_chip",
        "unit": "tokens/sec/chip",
        "model": args.model, "requests": args.requests,
        "rate_rps": args.rate, "max_new_tokens": args.max_new,
        "prompt_lens": prompt_lens, "seed": args.seed,
        "shared_prefix_len": args.shared_prefix_len,
        "tenants": len(tenants),
        "serve_config": {
            "max_slots": cfg.max_slots, "page_size": cfg.page_size,
            "num_pages": cfg.num_pages,
            "max_pages_per_slot": cfg.max_pages_per_slot,
            "prefill_buckets": list(cfg.prefill_buckets),
            "prefix_cache": cfg.prefix_cache,
            "spec_draft_model": cfg.spec_draft_model,
            "spec_k": cfg.spec_k},
    }

    def fast_path_counters(engine) -> dict:
        """Prefix-reuse and speculative-acceptance counters for the
        record — the in-record evidence the capacity claim rides on."""
        out = {}
        if engine.prefix is not None:
            admits = engine.prefix_hits + engine.prefix_misses
            out["prefix_hit_rate"] = round(
                engine.prefix_hits / admits, 4) if admits else None
            out["prefix_tokens_reused"] = engine.prefix_tokens_reused
            out["prefix_evictions"] = engine.prefix.evictions
            out["cow_copies"] = engine.cow_copies
        if engine._draft_model is not None:
            out["spec_rounds"] = engine.spec_rounds
            out["spec_acceptance_rate"] = round(
                engine.spec_accepted / engine.spec_proposed, 4) \
                if engine.spec_proposed else None
        return out

    try:
        if args.fixed_slo is not None:
            return _run_fixed_slo(args, cfg, base, make_trace,
                                  fast_path_counters)
        engine = Engine(cfg, clock=clock)
        engine.warmup()
        n_chips = jax.device_count()
        cont = run_continuous(engine, trace, clock)
        cont_tps = cont["tokens"] / cont["window_s"] / n_chips

        rec = dict(base)
        rec["value"] = round(cont_tps, 1)
        rec["continuous"] = {
            "tokens_per_sec_per_chip": round(cont_tps, 1),
            **_latency_block(
                [r.ttft_s for r in cont["requests"]],
                [s for r in cont["requests"] for s in r.itl_s]),
            "steps": cont["steps"], "preemptions": cont["preemptions"],
            "finished": len(cont["requests"]),
            # Degradation counters for tools/doctor.py serve health: a
            # fault-free bench run must show zeros here.
            "sheds": engine.sheds,
            "deadline_misses": engine.deadline_misses,
            "retries": engine.retries,
            **fast_path_counters(engine),
        }
        rec["aot"] = engine.aot_stats()
        if args.trace_dir:
            rec["continuous"]["ttft_attribution"] = _ttft_attribution(
                cont["requests"])
            rec["trace"] = telemetry.get().export()

        if not args.skip_baseline:
            seq = run_sequential(engine.model, {**engine._fresh}, trace,
                                 clock)
            seq_tps = seq["tokens"] / seq["window_s"] / n_chips
            mism = [i for i, (r, s) in
                    enumerate(zip(cont["requests"], seq["results"]))
                    if r.tokens != s["tokens"]]
            if mism:
                raise AssertionError(
                    f"continuous vs sequential token mismatch for "
                    f"requests {mism[:5]} — greedy serving must be "
                    f"token-identical; do not trust either number")
            rec["token_identity_checked"] = True
            rec["sequential_baseline"] = {
                "tokens_per_sec_per_chip": round(seq_tps, 1),
                **_latency_block(
                    [r["ttft_s"] for r in seq["results"]],
                    [r["itl_s"] for r in seq["results"]
                     if r["itl_s"] is not None]),
            }
            rec["speedup_vs_sequential"] = round(cont_tps / seq_tps, 2)

        if args.chaos:
            import tempfile

            from distributeddeeplearning_tpu import launch as launchlib

            kill_step = max(2, args.max_new // 2)
            stall_step = max(1, kill_step - 1)
            plans = {0: f"sigkill@{kill_step}",
                     1: f"decode_stall@{stall_step}:0.05s"}
            cfg_dict = dataclasses.asdict(cfg)
            reqs = [{"prompt": t["prompt"],
                     "max_new_tokens": t["max_new_tokens"],
                     "tenant": t["tenant"], "arrival_s": t["arrival_s"]}
                    for t in trace]
            # Two supervised runs over the same trace: the fault-free one
            # is the honest reference (same spawn + warm-boot cost), so
            # recovery_overhead_frac isolates what the faults cost, not
            # what process supervision costs. Both warm-boot from the AOT
            # cache the in-process arm above already populated.
            ok_run = launchlib.run_serve(
                2, reqs, cfg_dict,
                workdir=tempfile.mkdtemp(prefix="ddl-bserve-ok-"),
                heartbeat_dir=tempfile.mkdtemp(prefix="ddl-bserve-okhb-"),
                timeout_s=300.0)
            chaos_trace_dir = (os.path.join(args.trace_dir, "chaos")
                               if args.trace_dir else None)
            chaos_run = launchlib.run_serve(
                2, reqs, cfg_dict,
                workdir=tempfile.mkdtemp(prefix="ddl-bserve-chaos-"),
                heartbeat_dir=tempfile.mkdtemp(prefix="ddl-bserve-chb-"),
                child_fault_plans=plans, max_restarts=1, timeout_s=300.0,
                trace_dir=chaos_trace_dir)
            mism = [uid for uid, r in chaos_run["results"].items()
                    if r["tokens"] != cont["requests"][int(uid)].tokens]
            if mism:
                raise AssertionError(
                    f"chaos-arm tokens diverge from the fault-free run "
                    f"for requests {sorted(mism)[:5]} — recovery must be "
                    f"token-identical; do not trust these numbers")
            if not chaos_run["leak_check_ok"]:
                raise AssertionError(
                    "page-leak check failed at replica drain after the "
                    "chaos soak — the allocator lost accounting")
            ttfts = [r["ttft_s"] for r in chaos_run["results"].values()
                     if r["ttft_s"] is not None]
            chaos_tokens = sum(len(r["tokens"]) for r in
                               chaos_run["results"].values())
            rec["chaos"] = {
                "replicas": 2, "fault_plans": plans,
                "token_identity_checked": True,
                "leak_check_ok": True,
                "redispatched": chaos_run["redispatched"],
                "restarts": chaos_run["restarts"],
                "tokens_per_sec_per_chip": round(
                    chaos_tokens / chaos_run["window_s"] / n_chips, 1),
                "ttft_s": {"p50": _pct(ttfts, 50), "p99": _pct(ttfts, 99)},
                "fault_free_window_s": round(ok_run["window_s"], 3),
                "chaos_window_s": round(chaos_run["window_s"], 3),
                "recovery_overhead_frac": round(
                    chaos_run["window_s"] / ok_run["window_s"] - 1, 3),
            }
            if chaos_trace_dir and chaos_run.get("merged_trace"):
                # The chaos arm's whole point under tracing: a request
                # whose first replica was SIGKILLed must appear as ONE
                # flow chain spanning two Chrome pids in the merged
                # trace. Verify from the artifact, not from intent.
                evs, _ = telemetry.load_events_tolerant(
                    chaos_run["merged_trace"])
                flow_pids: dict = {}
                for e in evs:
                    if (e.get("ph") in ("s", "t", "f")
                            and e.get("cat") == "serve"):
                        flow_pids.setdefault(e.get("id"),
                                             set()).add(e.get("pid"))
                cross = [fid for fid, pids in flow_pids.items()
                         if len(pids) > 1]
                rec["chaos"]["merged_trace"] = chaos_run["merged_trace"]
                rec["chaos"]["flow_linked_requests"] = len(cross)
                if chaos_run["redispatched"] and not cross:
                    raise AssertionError(
                        "replica death re-dispatched "
                        f"{chaos_run['redispatched']} request(s) but "
                        "the merged trace has no flow chain spanning two "
                        "replica pids — cross-process trace linking is "
                        "broken")

        mid_context = int(np.mean(prompt_lens)) + args.max_new // 2
        roof = flopslib.decode_roofline(
            args.model, context_len=mid_context,
            tokens_per_sec=cont_tps,
            device_kind=getattr(jax.devices()[0], "device_kind", ""),
            dtype_bytes=2 if args.dtype == "bfloat16" else 4,
            batch=cfg.max_slots)
        if roof:
            rec["decode_roofline"] = roof
        perf_report.annotate(rec, provenance="fresh")
        print(json.dumps(rec), flush=True)
        sidecars.write("last_serve", {"record": rec})
        return 0
    except Exception as exc:  # noqa: BLE001 — emit an honest error record
        rec = dict(base)
        rec["value"] = None
        rec["error"] = f"{type(exc).__name__}: {exc}"
        perf_report.annotate(rec, provenance="error")
        print(json.dumps(rec), flush=True)
        return 1


if __name__ == "__main__":
    sys.exit(main())
