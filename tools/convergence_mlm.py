#!/usr/bin/env python
"""Config-4 (BERT MLM) convergence evidence on REAL tokenized text —
the token-side mirror of the image path's graded-corpus trajectory.

Generates a structured plain-text corpus whose statistics a masked-LM can
actually learn: content words come in fixed PAIRS (the second word of a
pair is deterministically implied by the first), with a noise fraction of
positions replaced by uniform words. A model that learns nothing sits at
uniform perplexity over the content vocabulary; one that learns the
bigram structure drives masked-token perplexity toward the noise floor —
so the eval trajectory is informative (falls, then plateaus above 1), and
the noise knob moves the floor the way the image corpus's alpha moves
top-1.

The corpus flows through the REAL pipeline: tools/tokenize_corpus.py
(in-tree WordPiece) -> packed .npy shards -> data/tokens.py dynamic
masking -> the standard trainer with periodic eval. One JSON line:

    {"check": "mlm_convergence", "uniform_ppl": ..., "trajectory":
     [[step, eval_loss, ppl], ...], "final_ppl": ...}

CPU-scale by default (bert_tiny, dp=1 — the XLA:CPU collective watchdog
forbids long dp>1 runs on this box):
    python tools/convergence_mlm.py [--steps 500] [--noise 0.15] [--lr X]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import numpy as np


def build_vocab(words: list[str], path: str) -> int:
    """BERT-layout vocab.txt: specials at canonical ids, real tokens >=
    1000 (data/tokens.py treats ids <= 999 as never-masked specials)."""
    rows = ["[PAD]"] + [f"[unused{i}]" for i in range(99)] + [
        "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    rows += [f"[unused{i}]" for i in range(99, 99 + (1000 - len(rows)))]
    rows += words + ["."]
    with open(path, "w") as f:
        f.write("\n".join(rows) + "\n")
    return len(rows)


def write_corpus(path: str, words: list[str], *, docs: int, noise: float,
                 seed: int) -> None:
    """Documents of pair-structured sentences: pairs (w_2i -> w_2i+1) are
    deterministic; ``noise`` of positions are uniform random words."""
    rng = np.random.default_rng(seed)
    n_pairs = len(words) // 2
    lines = []
    for _ in range(docs):
        for _ in range(rng.integers(2, 5)):  # sentences per document
            toks = []
            for _ in range(rng.integers(3, 7)):  # pairs per sentence
                p = rng.integers(n_pairs)
                toks += [words[2 * p], words[2 * p + 1]]
            # Noise: replace positions with uniform words.
            for j in range(len(toks)):
                if rng.random() < noise:
                    toks[j] = words[rng.integers(len(words))]
            lines.append(" ".join(toks) + " .")
        lines.append("")  # document break
    with open(path, "w") as f:
        f.write("\n".join(lines))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--words", type=int, default=64,
                   help="content vocabulary size (must be even: pairs)")
    p.add_argument("--docs", type=int, default=3000)
    p.add_argument("--noise", type=float, default=0.15,
                   help="fraction of positions replaced by uniform words "
                        "(the perplexity-floor knob)")
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=500)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--eval-batches", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    import tokenize_corpus as tc

    words = [f"w{i:03d}" for i in range(args.words)]
    work = tempfile.mkdtemp(prefix="mlm_conv_")
    vocab_path = os.path.join(work, "vocab.txt")
    vocab_size = build_vocab(words, vocab_path)
    for split, docs, seed in (("train", args.docs, args.seed),
                              ("validation", max(args.docs // 5, 50),
                               args.seed + 1)):
        txt = os.path.join(work, f"{split}.txt")
        write_corpus(txt, words, docs=docs, noise=args.noise, seed=seed)
        rc = tc.main(["--input", txt, "--vocab", vocab_path,
                      "--out-dir", work, "--seq-len", str(args.seq_len),
                      "--split", split])
        if rc != 0:
            print(json.dumps({"check": "mlm_convergence",
                              "error": f"tokenize rc={rc}"}))
            return 1

    n_train = sum(np.load(os.path.join(work, f)).shape[0]
                  for f in os.listdir(work) if f.startswith("train-"))
    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop

    cfg = TrainConfig(
        model="bert_tiny", global_batch_size=args.batch_size,
        dtype="float32", log_every=10**9,
        steps_per_epoch=max(n_train // args.batch_size, 1),
        eval_every_epochs=0.5,
        parallel=ParallelConfig(data=1),
        data=DataConfig(dataset="mlm", data_dir=work, synthetic=False,
                        seq_len=args.seq_len, vocab_size=vocab_size),
        optimizer=OptimizerConfig(name="adamw", learning_rate=args.lr,
                                  schedule="linear", label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=args.steps,
                       eval_batches=args.eval_batches)

    traj = [[int(s), round(v, 4), round(math.exp(v), 2)]
            for s, v in summary.get("evals", [])]
    print(json.dumps({
        "check": "mlm_convergence", "vocab_words": args.words,
        "noise": args.noise, "train_sequences": n_train,
        "steps": args.steps, "lr": args.lr,
        # A structure-blind model guesses uniformly over content words.
        "uniform_ppl": float(args.words),
        "trajectory": traj,
        "final_eval_loss": round(summary.get("eval_loss", float("nan")), 4),
        "final_ppl": round(math.exp(summary["eval_loss"]), 2)
        if "eval_loss" in summary else None,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
