#!/usr/bin/env python
"""ddl-lint: static distributed-correctness analyzer (docs/static_analysis.md).

    python tools/ddl_lint.py                # all passes over the repo
    python tools/ddl_lint.py --json         # machine-readable report
    python tools/ddl_lint.py --only lints   # one pass (collectives|donation|lints)
    python tools/ddl_lint.py --paths FILE…  # AST passes on specific files
    python tools/ddl_lint.py --hlo DUMP…    # schedule-compare HLO text dumps

Three passes (distributeddeeplearning_tpu/analysis/):

- ``collectives`` — traces the bucketed all-reduce programs
  (``parallel/collectives.py``, psum + ring) on the 8-fake-device CPU
  harness, extracts and fingerprints their collective schedules, and
  verifies: schedule identity across simulated ranks, the traced bucket
  order against the planner's promise, planner insertion-order
  determinism, and the (config fingerprint -> schedule fingerprint)
  pairing registry the AOT cache's "equal keys => equal programs"
  contract needs. Also checks the pipeline schedule tables
  (``models/pipeline.build_schedule``, gpipe + interleaved 1f1b) with
  the ``pipeline-schedule-pairing`` rule: every stage's occupancy must
  be fed by a matching collective-permute edge — the MPMD
  divergent-schedule deadlock class. ``--hlo`` instead compares
  schedules extracted from lowered-HLO dumps (e.g. from a chip window),
  including collective-permute ``source_target_pairs``.
- ``donation`` — AST taint: restored/orbax-aliased values must pass
  ``checkpoint.device_copy`` before reaching a donated step argument
  (the PR 5 / PR 9 invariant).
- ``lints`` — repo-invariant AST rules: sidecar-routed ``.cache/*.json``
  writes, fsync-before-fire chaos emitters, entered telemetry spans,
  provenance-stamped perf records, mesh-declared axis names.

Baseline (``tools/ddl_lint_baseline.json``): ``{"suppressions": [{"rule":
..., "file": ...}]}`` entries suppress matching findings (reported
separately, never failing). The checked-in baseline is EMPTY — the repo
lints clean; keep it that way.

Exit codes: 0 clean, 1 findings, 2 analyzer internal error. A successful
default run records schedule fingerprints in the ``last_ddl_lint``
sidecar so bench records can attach the schedule they measured under.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.analysis import (PASSES,  # noqa: E402
                                                  finding, repo_root,
                                                  suppression_matches)

# Shipping-code roots the AST passes lint (tests seed violations in temp
# files on purpose; see analysis.iter_py_files for the exclusions).
DEFAULT_ROOTS = ("distributeddeeplearning_tpu", "tools", "train.py",
                 "bench.py", "generate.py", "launch.py")

BASELINE_DEFAULT = os.path.join("tools", "ddl_lint_baseline.json")

LINT_SIDECAR = "last_ddl_lint"

_TRACE_AXES = ("data", "fsdp")
_TRACE_BUCKET_BYTES = 64 * 1024


def _grad_tree(shuffle=None):
    """A small many-bucket gradient tree; ``shuffle`` (a random.Random)
    perturbs dict insertion order for the determinism check."""
    import jax

    leaves = [("conv1", (3, 3, 3, 8)), ("bias1", (8,)),
              ("dense", (64, 32)), ("head", (32, 100)),
              ("scale", (32,)), ("offset", (32,))]
    if shuffle is not None:
        shuffle.shuffle(leaves)
    import jax.numpy as jnp
    return {name: jax.ShapeDtypeStruct(shape, jnp.float32)
            for name, shape in leaves}


def _allreduce_schedule(algorithm: str):
    """Trace ``parallel/collectives.all_reduce`` over the probe tree under
    shard_map on the 8-fake-device mesh; return (Schedule, BucketPlan)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributeddeeplearning_tpu import compat
    from distributeddeeplearning_tpu.analysis import (collectives as
                                                      canalysis)
    from distributeddeeplearning_tpu.config import ParallelConfig
    from distributeddeeplearning_tpu.parallel import collectives as pc
    from distributeddeeplearning_tpu.parallel.mesh import make_mesh

    structs = _grad_tree()
    plan = pc.plan_buckets(structs, _TRACE_BUCKET_BYTES)
    mesh = make_mesh(ParallelConfig(data=8), backend="cpu")
    vals = {k: jnp.zeros((8,) + tuple(s.shape), s.dtype)
            for k, s in structs.items()}

    def f(local):
        local = jax.tree_util.tree_map(lambda x: x[0], local)
        return pc.all_reduce(local, _TRACE_AXES, axis_size=8,
                             bucket_bytes=_TRACE_BUCKET_BYTES,
                             algorithm=algorithm, plan=plan)

    fn = compat.shard_map(f, mesh=mesh, in_specs=P(_TRACE_AXES),
                          out_specs=P())
    return canalysis.schedule_of(fn, vals), plan


def run_collectives_pass(*, registry_path=None, record: bool = True):
    """The dynamic (tracing) pass. Returns (findings, schedules) where
    ``schedules`` maps program name -> fingerprint. Any harness failure
    degrades to an ``analyzer-degraded`` note-finding suppressed from the
    gate — a broken *analyzer* must not read as a broken *repo* — except
    genuine verification findings, which always surface."""
    from distributeddeeplearning_tpu.analysis import collectives as ca

    findings: list[dict] = []
    schedules: dict[str, str] = {}
    try:
        from distributeddeeplearning_tpu.perf import aot

        cfg_fp = None
        try:
            from distributeddeeplearning_tpu.config import TrainConfig
            cfg_fp = aot.config_fingerprint(TrainConfig(),
                                            total_steps=None)
        except Exception:  # noqa: BLE001 — pairing check just skipped
            pass
        for algorithm in ("psum", "ring"):
            name = f"allreduce_{algorithm}"
            sched, plan = _allreduce_schedule(algorithm)
            if sched.errors:
                findings.append(finding(
                    "collectives", "analyzer-degraded",
                    f"{name}: schedule extraction degraded: "
                    f"{'; '.join(sched.errors)}"))
            schedules[name] = sched.fingerprint()
            findings.extend(ca.verify_bucket_schedule(
                sched, plan, algorithm, axis_size=8))
            # Rank-uniformity: the same program traced under each
            # simulated process index must schedule identically.
            per_rank = ca.simulate_ranks(
                lambda rank: _allreduce_schedule(algorithm)[0],
                ranks=(0, 1))
            findings.extend(ca.verify_uniform(per_rank))
            if cfg_fp is not None:
                findings.extend(ca.check_aot_pairing(
                    cfg_fp, name, sched.fingerprint(),
                    registry_path=registry_path, record=record))
        # Planner determinism under container insertion-order churn.
        import random as _random  # noqa: F401 — via plan_is_deterministic
        from distributeddeeplearning_tpu.parallel import collectives as pc
        findings.extend(ca.plan_is_deterministic(
            _grad_tree, pc.plan_buckets,
            bucket_bytes=_TRACE_BUCKET_BYTES))
        # Pipeline permute pairing: the schedule tables whose shift pairs
        # become per-stage collective-permute programs (models/pipeline)
        # must be deadlock-free at the geometries the repo ships — the
        # registry pp models' (P, M) plus the V>1 interleaved variants.
        from distributeddeeplearning_tpu.models import pipeline as plib
        for sname, pp, mm, vv in (("gpipe", 2, 4, 1), ("gpipe", 4, 8, 1),
                                  ("1f1b", 2, 4, 2), ("1f1b", 4, 8, 2)):
            label = f"pipeline_{sname}_p{pp}m{mm}v{vv}"
            table = plib.build_schedule(sname, num_stages=pp,
                                        num_microbatches=mm,
                                        virtual_stages=vv)
            findings.extend(ca.verify_pipeline_pairing(label, table))
            schedules[label] = ca.permute_schedule(table).fingerprint()
    except Exception as exc:  # noqa: BLE001 — tolerant analyzer
        findings.append(finding(
            "collectives", "analyzer-degraded",
            f"collectives pass could not run "
            f"({type(exc).__name__}: {exc}) — jax harness unavailable or "
            f"drifted; static passes still apply"))
    return findings, schedules


def run_hlo_mode(paths):
    """Compare collective schedules across lowered-HLO text dumps —
    divergence across per-rank/per-stage dumps is the SPMD hang."""
    from distributeddeeplearning_tpu.analysis import collectives as ca

    findings: list[dict] = []
    schedules: dict[str, str] = {}
    extracted = {}
    for path in paths:
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                text = fh.read()
        except OSError as exc:
            findings.append(finding(
                "collectives", "analyzer-degraded",
                f"cannot read HLO dump: {exc}", file=path))
            continue
        sched = ca.extract_from_hlo_text(text)
        extracted[os.path.basename(path)] = sched
        schedules[os.path.basename(path)] = sched.fingerprint()
        for err in sched.errors:
            findings.append(finding(
                "collectives", "analyzer-degraded",
                f"{os.path.basename(path)}: {err}", file=path))
    findings.extend(ca.verify_uniform(extracted))
    return findings, schedules


def load_baseline(path):
    if path in (None, "", "none"):
        return []
    try:
        with open(path, encoding="utf-8") as fh:
            obj = json.load(fh)
        entries = obj.get("suppressions", [])
        return [e for e in entries if isinstance(e, dict)]
    except FileNotFoundError:
        return []
    except (OSError, ValueError) as exc:
        print(f"# ddl_lint: unreadable baseline {path}: {exc}",
              file=sys.stderr)
        return []


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="static distributed-correctness analyzer")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--only", action="append", choices=PASSES, default=None,
                   help="run only this pass (repeatable)")
    p.add_argument("--baseline", default=None,
                   help=f"suppression file (default {BASELINE_DEFAULT}; "
                        f"'none' disables)")
    p.add_argument("--paths", nargs="+", default=None,
                   help="lint these files/dirs with the AST passes only")
    p.add_argument("--hlo", nargs="+", default=None, metavar="DUMP",
                   help="compare collective schedules across HLO text "
                        "dumps instead of tracing the repo's programs")
    p.add_argument("--fingerprint-registry", default=None,
                   help="override the schedule_fingerprints sidecar path "
                        "(AOT pairing check)")
    p.add_argument("--no-record", action="store_true",
                   help="do not record fingerprints or the last_ddl_lint "
                        "sidecar")
    args = p.parse_args(argv)

    only = set(args.only or PASSES)
    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = os.path.join(repo_root(), BASELINE_DEFAULT)
    suppressions = load_baseline(baseline_path)

    roots = args.paths or [os.path.join(repo_root(), r)
                           for r in DEFAULT_ROOTS]
    findings: list[dict] = []
    schedules: dict[str, str] = {}
    passes_run: list[str] = []
    try:
        if args.hlo:
            passes_run.append("collectives")
            f, schedules = run_hlo_mode(args.hlo)
            findings.extend(f)
        if "lints" in only:
            from distributeddeeplearning_tpu.analysis import lints
            passes_run.append("lints")
            findings.extend(lints.analyze_paths(roots))
        if "donation" in only:
            from distributeddeeplearning_tpu.analysis import donation
            passes_run.append("donation")
            findings.extend(donation.analyze_paths(roots))
        if "collectives" in only and not args.hlo and not args.paths:
            passes_run.append("collectives")
            f, schedules = run_collectives_pass(
                registry_path=args.fingerprint_registry,
                record=not args.no_record)
            findings.extend(f)
    except Exception as exc:  # noqa: BLE001 — exit 2: analyzer bug
        print(f"# ddl_lint: internal error: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 2

    # analyzer-degraded notes report but never gate: a broken analyzer
    # must not read as a broken repo.
    notes = [f for f in findings if f["rule"] == "analyzer-degraded"]
    hard = [f for f in findings if f["rule"] != "analyzer-degraded"]
    active = [f for f in hard
              if not any(suppression_matches(f, s) for s in suppressions)]
    suppressed = [f for f in hard if f not in active]

    ok = not active
    if not args.no_record and not args.paths and not args.hlo:
        from distributeddeeplearning_tpu.observability import sidecars
        sidecars.write(LINT_SIDECAR, {
            "ok": ok, "findings": len(active),
            "suppressed": len(suppressed), "notes": len(notes),
            "passes": sorted(set(passes_run)),
            "collective_schedules": schedules,
        })

    report = {"ok": ok, "passes": sorted(set(passes_run)),
              "findings": active, "suppressed": suppressed,
              "notes": notes, "collective_schedules": schedules,
              "baseline": (baseline_path
                           if suppressions is not None else None)}
    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for f in active:
            loc = f"{f['file']}:{f['line']}" if f.get("file") else "(repo)"
            print(f"{loc}: [{f['pass']}/{f['rule']}] {f['message']}")
        for f in suppressed:
            loc = f"{f['file']}:{f['line']}" if f.get("file") else "(repo)"
            print(f"# suppressed {loc}: [{f['pass']}/{f['rule']}]")
        for f in notes:
            print(f"# note: {f['message']}")
        for name, fp in sorted(schedules.items()):
            print(f"# schedule {name}: {fp}")
        print(f"# ddl_lint: {'OK' if ok else 'FAIL'} — "
              f"{len(active)} finding(s), {len(suppressed)} suppressed, "
              f"{len(notes)} note(s), passes: "
              f"{', '.join(sorted(set(passes_run)))}")
    return 0 if ok else 1


if __name__ == "__main__":
    # The tracing pass needs the same 8-fake-device CPU harness the tests
    # use; set up BEFORE jax is first imported.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=8").strip()
    sys.exit(main())
