#!/bin/bash
# Run every pending on-chip measurement in VALUE-PER-MINUTE order, one log
# per step.
# Usage: tools/chip_window.sh [results_dir] [hard_stop_epoch_s]
#   results_dir       default .chip_results
#   hard_stop_epoch_s absolute wall deadline (date +%s) after which no NEW
#                     step beyond the headline+A/B prefix starts — so a
#                     window opening near the watcher's deadline can never
#                     leave this script contending with the driver's own
#                     end-of-round bench run. Empty = no stop.
#
# Window economics (VERDICT r4 Weak #1): the only tunnel window ever
# observed was ~25 minutes (2026-07-31, ~03:47-04:10 UTC), so the priority
# prefix — steps 1-5 — is budgeted to fit it at P50: ~3 + ~5 + ~7 + ~3 +
# ~2 = ~20 min, from measured sessions (round-3's headline landed in one
# attempt ~3 min after the chip answered with a warm compile cache; a
# suite row costs ~60-120 s per BASELINE.md round-2/3; the A/B is two
# ~90 s measurements plus compile). The per-step `timeout`s are HANG
# GUARDS, not budgets — they only strike when a tool stalls past its own
# internal budget, and their sum (~33 min) intentionally exceeds the
# window: if every guard strikes, the tunnel died and no ordering could
# have saved the window. The real budget discipline lives inside each
# tool (bench.py --budget / --suite-budget row gating, --leg-timeout),
# which emit parseable partial output when cut.
#
# Priority rationale:
#   1. headline  — the metric of record, 4 rounds unmeasured (Missing #1);
#                  also warms the compile cache for the driver's own run.
#   2. fused-block A/B — the round-3/4 kernel-campaign verdict, the single
#                  most valuable unknown (was behind the suite in r4).
#   3. top suite rows — resnet50 + the never-measured gather-head BERT
#                  flash + gpt2 rows; bench.py now orders rows by value
#                  and cuts them on budget, so a dying window still yields
#                  the best prefix.
#   4. real-data tf leg — loader->device_put->train overlap on TPU, never
#                  measured (Weak #4).
#   5. MFU profile — where the fused-block step spends its time.
#   6+. everything else, cheapest-first within similar value.
set -u
cd "$(dirname "$0")/.."
RES="$(realpath -m "${1:-.chip_results}")"  # absolute: survives the cd above
HARD_STOP="${2:-}"
mkdir -p "$RES"
stamp() { date +%H:%M:%S; }
check_stop() {
  if [ -n "$HARD_STOP" ] && [ "$(date +%s)" -ge "$HARD_STOP" ]; then
    echo "[$(stamp)] hard stop before step $1 (driver's chip time)" \
      >> "$RES/log.txt"
    exit 0
  fi
}
# Per-step (name, rc, wall seconds) into timings.jsonl — the measured P50s
# the NEXT session's budgets should be set from (this round's are
# estimates; VERDICT r4 Weak #1 asked for measured ones).
STEP_T0=$(date +%s)
note() {
  rc=$?
  local now; now=$(date +%s)
  echo "[$(stamp)] $1 rc=$rc ${2:-}" >> "$RES/log.txt"
  echo "{\"step\": \"$1\", \"rc\": $rc, \"wall_s\": $((now - STEP_T0))}" \
    >> "$RES/timings.jsonl"
  STEP_T0=$now
}

echo "[$(stamp)] window open" >> "$RES/log.txt"

# 0. Preflight: bounded-retry backend probe (tools/preflight.py, 2 x 20 s
# + 3 s backoff ~= 45 s worst case). r04/r05 burned 75 s of bench-harness
# preflight each — and r02/r03 199-219 s of timeouts — discovering the
# tunnel was down; this answers in seconds. On a dead tunnel the probe's
# error-provenance record BECOMES the window's headline artifact (the
# driver reads bench_headline.json either way) and the window exits
# immediately rather than walking every step into the same wall.
check_stop preflight
timeout 90 python tools/preflight.py --out "$RES/preflight.json" \
  >> "$RES/log.txt" 2>&1
PREFLIGHT_RC=$?
(exit "$PREFLIGHT_RC")  # note() reads $?; restore the probe's rc for it
note preflight
if [ "$PREFLIGHT_RC" -ne 0 ]; then
  cp "$RES/preflight.json" "$RES/bench_headline.json" 2>/dev/null || true
  echo "[$(stamp)] tunnel down (preflight rc=$PREFLIGHT_RC): window aborted" \
    >> "$RES/log.txt"
  exit 0
fi

# 0b. Static distributed-correctness analyzer (gated, ask with DDL_LINT=1;
# CPU-only, ~5 s, runs BEFORE the benches on purpose): a full ddl_lint run
# records the collective-schedule fingerprints in the last_ddl_lint
# sidecar, and every bench record this window emits then carries
# collective_schedules via perf_report.annotate — the throughput numbers
# name the exact collective schedule they were measured under
# (docs/static_analysis.md). Findings do NOT abort the window: the
# artifact lands in $RES/ddl_lint.json and the rc lands in timings.jsonl
# for the driver to gate on.
if [ "${DDL_LINT:-0}" = "1" ]; then
  check_stop ddl_lint
  timeout 180 env JAX_PLATFORMS=cpu python tools/ddl_lint.py --json \
    > "$RES/ddl_lint.json" 2>> "$RES/log.txt"
  note ddl_lint
fi

# --- Priority prefix: fits a ~25-min window -------------------------------

# 1. Headline bench, quick protocol first (P50 ~3 min warm-cache; the
# progressive quick line lands ~60 s after backend-up even cold). The batch
# sweep + fused-block alternate stay ON (sweep auto): they only emit on a
# strict win and this is the one shot at catching the sweet-spot flip.
timeout 420 python bench.py --budget 400 --attempts 1 \
  > "$RES/bench_headline.json" 2>> "$RES/log.txt"
note headline

# 2. Fused-block step A/B vs unfused (the round-3/4 kernel verdict).
# P50 ~5 min: two configs x (warm compile + ~40 timed steps) at b512.
timeout 480 python tools/ab_fused_block.py --batches 512 \
  > "$RES/fused_block_ab.json" 2>> "$RES/log.txt"
note fused_block

check_stop suite_top
# 3. Highest-value suite rows under an explicit row budget, selected BY
# NAME (index selection broke silently whenever SUITE gained a row):
# resnet50 (acceptance row, cache hot from step 1), BERT-512 flash, gpt2,
# BERT-512 dense (gather-head protocol, never measured on chip).
# bench.py admits rows against the budget and cuts overruns, so this step
# degrades to the best prefix rather than overshooting. P50 ~7 min.
timeout 540 python bench.py --suite --budget 520 \
  --suite-rows resnet50,bert512_flash,gpt2_1024,bert512 \
  > "$RES/bench_suite_top.json" 2>> "$RES/log.txt"
note suite_top

check_stop real_data_tf
# 4. Real-pixels end-to-end, tf.data loader: disk JPEGs -> decode ->
# device_put -> train -> eval on the real chip — the loader/train overlap
# number (corpus pre-generated under .cache/real_jpegs; never spend window
# time on PIL). --loaders tf still runs THREE legs (synthetic baseline,
# tf, tf_resume), so the guard is 3 x leg-timeout + slack. P50 ~3 min.
timeout 520 python tools/real_data_on_chip.py --steps 100 --loaders tf \
  --leg-timeout 150 > "$RES/real_data_tf.json" 2>> "$RES/log.txt"
note real_data_tf

check_stop profile
# 5. Profile the fused-block step (where does its time go — reads on the
# A/B either way it lands). P50 ~2 min warm.
timeout 300 python tools/profile_step.py --model resnet50 --batch-size 512 \
  --fused-block --top 25 > "$RES/profile_fused_block.json" 2>> "$RES/log.txt"
note profile
echo "[$(stamp)] priority prefix done" >> "$RES/log.txt"

# --- Extended batch: runs only while the window stays open ----------------

check_stop fused_conv3
# 5b. Fused 3x3 conv kernel (fused_block v2): FIRST compiled-Mosaic smoke
# at the extreme shapes — a rejection must cost seconds here, not the A/B
# below. Then the three-way step A/B (unfused / v1 / v2).
timeout 420 python tools/validate_fused_conv_tpu.py --quick \
  > "$RES/fused_conv3_validate.json" 2>> "$RES/log.txt"
note fused_conv3_validate
check_stop fused_conv3_ab
# The 700s three-way A/B is the most expensive single step in the window;
# a hard stop landing between validate and A/B must skip it rather than
# start a run the driver's own bench would then contend with.
timeout 700 python tools/ab_fused_block.py --batches 512 --conv3 \
  > "$RES/fused_conv3_ab.json" 2>> "$RES/log.txt"
note fused_conv3_ab

check_stop suite_rest
# 6. Remaining suite rows: resnet152, densenet121, vit_b16, bert-2048
# flash+remat (exact-row selection by name — a model-name filter would
# re-admit the bert rows step 3 already measured).
timeout 900 python bench.py --suite --budget 860 \
  --suite-rows resnet152,densenet121,vit_b16,bert2048_flash \
  > "$RES/bench_suite_rest.json" 2>> "$RES/log.txt"
note suite_rest

check_stop allreduce_ab
# 6b. Fused vs per-leaf gradient all-reduce A/B (the bucketed-collective
# verdict): same model/batch as the acceptance row, only the reduction
# protocol differs. Per-leaf writes its own metric name (_perleaf_ar), so
# the fused row's last-good cache is never polluted. ~2 x 90 s + compile.
timeout 480 python bench.py --suite --budget 440 \
  --suite-rows ar_fused,ar_perleaf \
  > "$RES/bench_allreduce_ab.json" 2>> "$RES/log.txt"
note allreduce_ab

check_stop zero_ladder
# 6c. ZeRO ladder A/B (parallel/zero.py): ar_fused (replicated baseline)
# vs zero1 vs zero2 vs zero3, same model/batch/bucket throughout, so the
# four rows differ ONLY in the gradient/update/param schedule. Each stage
# emits under its own _<stage> metric name and every record carries the
# per-device params/grads/opt-state resident bytes plus their sum and
# peak HBM — the monotone memory ladder (replicated -> zero1 -> zero2 ->
# zero3) and the overlap throughput cost land in one step. zero2/zero3
# run the overlapped backward/collective schedule (the default).
# ~4 x 90 s + compile.
timeout 700 python bench.py --suite --budget 660 \
  --suite-rows ar_fused,zero1,zero2,zero3 \
  > "$RES/bench_zero_ladder.json" 2>> "$RES/log.txt"
note zero_ladder

# 6c2. Large-batch mixed-precision A/B (gated, ask with DDL_LARGEBATCH=1):
# the ISSUE 20 acceptance pair — resnet50 at 2x the acceptance batch, fp32
# recipe vs the full mixed recipe (bf16 compute/reduce, fp32 masters,
# dynamic loss scaling, LARS). The arms emit under SEPARATED metric names
# (resnet50_fp32_... / resnet50_mixed_...) with pct_of_peak scored against
# each arm's OWN dtype roof (fp32 peak = bf16 peak / 6 on v4/v5), so the
# mixed arm must land a strictly higher %-of-peak for the recipe to count
# (docs/mixed_precision.md). Gated because b1024 compiles fresh programs
# for both arms and neither is a last-good acceptance row. ~2 x 90 s +
# compile.
if [ "${DDL_LARGEBATCH:-0}" = "1" ]; then
  check_stop largebatch_ab
  timeout 480 python bench.py --suite --budget 440 \
    --suite-rows largebatch_fp32,largebatch_bf16 \
    > "$RES/bench_largebatch_ab.json" 2>> "$RES/log.txt"
  note largebatch_ab
fi

# 6d. Pipeline-schedule A/B (gated, ask with DDL_PIPELINE=1): gpipe vs
# interleaved 1f1b suite rows at IDENTICAL geometry (pp=2, M=4, V=2 — the
# only delta is the schedule). Each record carries the measured
# pipeline_bubble_fraction from the trace-time tick instants next to the
# analytic (P-1)/(M*V+P-1); the acceptance pair (1f1b strictly below
# gpipe, within 1.5x analytic) lands in bench_pipeline_ab.json
# (docs/pipeline.md). Gated because the *_pp model variants are not
# acceptance rows and both arms compile fresh programs (no warm cache
# from step 1). ~2 x 90 s + compile.
if [ "${DDL_PIPELINE:-0}" = "1" ]; then
  check_stop pipeline_ab
  timeout 480 python bench.py --suite --budget 440 \
    --suite-rows pp_gpipe,pp_1f1b \
    > "$RES/bench_pipeline_ab.json" 2>> "$RES/log.txt"
  note pipeline_ab
fi

check_stop real_data
# 7. Remaining real-data legs: native C++ loader + grain only (tf was
# step 4; re-running it would spend window time on duplicates). 5 legs
# (synthetic baseline + 2 loaders + 2 resumes) x 180s + slack.
timeout 1100 python tools/real_data_on_chip.py --steps 100 \
  --loaders native,grain --leg-timeout 180 \
  > "$RES/real_data.json" 2>> "$RES/log.txt"
note real_data

check_stop matmul_micro
# 8. Pallas matmul vs XLA dot at ResNet 1x1 shapes (kernel derisk data).
timeout 420 python - > "$RES/matmul_micro.json" 2>> "$RES/log.txt" <<'EOF'
import json, sys, time
sys.path.insert(0, ".")
import jax, jax.numpy as jnp
from distributeddeeplearning_tpu.ops.fused_linear_bn import linear_stats

def t(f, *a):
    r = jax.jit(f)
    out = r(*a)
    jax.tree.map(lambda x: x.block_until_ready(), out)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(10):
        out = r(*a)
    jax.tree.map(lambda x: x.block_until_ready(), out)
    return (time.perf_counter() - t0) / 10

# (M, K, N) of resnet50 b256 1x1 convs: layer1 c3, layer2 c3, layer3 c3.
for m, k, n in ((802816, 64, 256), (200704, 128, 512), (50176, 256, 1024),
                (200704, 512, 256)):
    x = jax.random.normal(jax.random.key(0), (m, k), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (k, n), jnp.bfloat16)
    xla = t(lambda a, b: jnp.dot(a, b, preferred_element_type=jnp.float32
                                 ).astype(jnp.bfloat16), x, w)
    pls = t(lambda a, b: linear_stats(a, b)[0], x, w)
    tf_ = 2 * m * k * n
    print(json.dumps({"mkn": [m, k, n],
                      "xla_ms": round(xla * 1e3, 2),
                      "pallas_stats_ms": round(pls * 1e3, 2),
                      "xla_tflops": round(tf_ / xla / 1e12, 1),
                      "pallas_tflops": round(tf_ / pls / 1e12, 1)}),
          flush=True)
EOF
note matmul_micro

check_stop xla_sweep
# 9. XLA-flag sweep on the headline config (quick protocol): any free wins
# from scheduler/memory knobs the default compile doesn't enable. The jax
# compilation cache keys on the flags, so cached default executables don't
# mask these runs.
for flags in \
  "--xla_tpu_enable_latency_hiding_scheduler=true" \
  "--xla_tpu_scoped_vmem_limit_kib=98304"; do
  tag=$(echo "$flags" | tr -cd 'a-z_' | tail -c 24)
  echo "[$(stamp)] xla flags: $flags" >> "$RES/log.txt"
  XLA_FLAGS="$flags" \
    timeout 420 python bench.py --steps 10 --attempts 1 --budget 400 \
    --sweep none >> "$RES/xla_flag_sweep.json" 2>> "$RES/log.txt"
  note "xla_$tag"
done

check_stop decode
# 10. Decode throughput (serving-side): GPT-2 KV-cache vs refeed.
timeout 600 python tools/bench_generate.py --model gpt2_small --batch 8 \
  --prompt-len 128 --new-tokens 128 > "$RES/decode_throughput.json" \
  2>> "$RES/log.txt"
note decode

# 10b. Continuous-batching serve bench (gated, ask with DDL_SERVE=1): the
# paged-KV engine vs sequential generate() under the same Poisson load,
# on the real chip. Gated because the sequential baseline arm deliberately
# saturates and its cost scales with --requests; the record (speedup,
# TTFT/ITL percentiles, decode roofline) lands in serve_throughput.json
# and the last_serve sidecar for doctor.py.
if [ "${DDL_SERVE:-0}" = "1" ]; then
  check_stop serve
  timeout 600 python tools/bench_serve.py --dtype bfloat16 \
    > "$RES/serve_throughput.json" 2>> "$RES/log.txt"
  note serve
fi

# 10c. Serve chaos soak (gated, OFF by default: CPU-only like the DDL_CHAOS
# step — ask with DDL_SERVE_CHAOS=1). The same Poisson trace through the
# supervised replica path fault-free and under sigkill + decode_stall,
# recording p50/p99 TTFT, tokens/sec/chip and recovery_overhead_frac with
# token-identity and the page-leak check asserted (docs/serving.md).
if [ "${DDL_SERVE_CHAOS:-0}" = "1" ]; then
  check_stop serve_chaos
  timeout 900 env JAX_PLATFORMS=cpu python tools/bench_serve.py --chaos \
    --model gpt_tiny --vocab-size 128 --requests 6 --rate 50 --max-new 8 \
    --prompt-lens 4,6 --max-slots 2 --page-size 4 --num-pages 32 \
    --max-pages-per-slot 8 --prefill-buckets 16 \
    > "$RES/serve_chaos.json" 2>> "$RES/log.txt"
  note serve_chaos
fi

# 10d. Serve fast path at a fixed SLO (gated, ask with DDL_SERVE_SPEC=1):
# radix prefix cache + speculative decoding vs the features-off engine on
# the same shared-prefix trace, capacity judged at a fixed p99 TTFT SLO
# (docs/serving.md). Gated because the sweep runs BOTH arms at every
# offered load in --slo-rates and its cost scales with rates x requests;
# the record (speedup_at_slo, per-rate hit/acceptance counters) lands in
# serve_fastpath.json and the last_serve sidecar for doctor.py.
if [ "${DDL_SERVE_SPEC:-0}" = "1" ]; then
  check_stop serve_spec
  timeout 900 python tools/bench_serve.py --dtype bfloat16 \
    --prefix-cache --spec-draft-model gpt_nano --spec-k 4 \
    --shared-prefix-len 64 --tenants a,b --requests 32 \
    --num-pages 256 --max-pages-per-slot 16 --prefill-buckets 16,128 \
    --fixed-slo 0.5 \
    > "$RES/serve_fastpath.json" 2>> "$RES/log.txt"
  note serve_spec
fi

check_stop flash
# 11. Flash-attention compiled-kernel validation (fwd/bwd err + timing).
timeout 600 python tools/validate_flash_tpu.py \
  > "$RES/flash_validate.json" 2>> "$RES/log.txt"
note flash

# 12. Chaos recovery overhead (gated, OFF by default: it runs on CPU and
# needs no chip, so it must never spend window time unless explicitly
# asked for with DDL_CHAOS=1 — e.g. a window opened purely to refresh the
# robustness numbers). Measures time-to-resume after an injected crash
# under launch.py --max-restarts (docs/fault_tolerance.md).
if [ "${DDL_CHAOS:-0}" = "1" ]; then
  check_stop chaos
  # --chaos-cold adds a second faulted run with the compile cache disabled,
  # so the record carries warm AND cold recovery overhead side by side.
  timeout 900 env JAX_PLATFORMS=cpu python bench.py --chaos --chaos-cold \
    > "$RES/chaos_recovery.json" 2>> "$RES/log.txt"
  note chaos
fi

# 12b. Elastic re-formation soak (gated, OFF by default, same reasoning as
# the chaos step: CPU-only, ask with DDL_ELASTIC=1). A 2-host dp4
# transformer job loses a host (host_lost), auto-shrinks to dp2 through
# the rendezvous reform barrier (survivors drain voluntarily at a step
# boundary — exit 75, no teardown — and re-form under a bumped membership
# epoch), grows back to dp4 on rejoin, and records the measured
# reconfiguration_time_s with its detect->drain->restore->compile->
# first-step phase split (docs/fault_tolerance.md "Rendezvous
# membership").
if [ "${DDL_ELASTIC:-0}" = "1" ]; then
  check_stop elastic
  timeout 900 env JAX_PLATFORMS=cpu python bench.py --chaos-elastic \
    > "$RES/elastic_recovery.json" 2>> "$RES/log.txt"
  note elastic
fi

# --- Gated cold-vs-warm start A/B (ask with DDL_COLDSTART=1) --------------
# Same headline config twice: once against a private EMPTY compile cache
# (true cold start: full trace + XLA compile) and once against the shared
# warm cache step 1 populated. Both records carry time_to_first_step_s /
# compile_time_s (docs/compile_cache.md), so the pair is the measured
# cold-start tax the persistent cache + AOT executables remove. The cold
# leg uses its own throwaway dir rather than DDL_COMPILE_CACHE=off so it
# also re-populates nothing shared.
if [ "${DDL_COLDSTART:-0}" = "1" ]; then
  check_stop coldstart_cold
  rm -rf "$RES/cold_cache" && mkdir -p "$RES/cold_cache"
  timeout 420 python bench.py --budget 400 --attempts 1 --sweep none \
    --compile-cache-dir "$RES/cold_cache" \
    > "$RES/bench_coldstart_cold.json" 2>> "$RES/log.txt"
  note coldstart_cold
  check_stop coldstart_warm
  timeout 420 python bench.py --budget 400 --attempts 1 --sweep none \
    > "$RES/bench_coldstart_warm.json" 2>> "$RES/log.txt"
  note coldstart_warm
fi

# --- Gated telemetry-overhead A/B (ask with DDL_TELEMETRY=1) --------------
# Same headline config traced vs untraced on the live chip: the traced run
# lands under its own _tele metric name, so the pair quantifies the cost of
# leaving --trace-dir on (docs/observability.md records the bound; a CPU
# tier-1 test bounds the disabled path's overhead). The trace itself is
# kept in $RES for tools/summarize_trace.py.
if [ "${DDL_TELEMETRY:-0}" = "1" ]; then
  check_stop telemetry_off
  timeout 420 python bench.py --budget 400 --attempts 1 --sweep none \
    > "$RES/bench_tele_off.json" 2>> "$RES/log.txt"
  note telemetry_off
  check_stop telemetry_on
  timeout 420 python bench.py --budget 400 --attempts 1 --sweep none \
    --trace-dir "$RES/trace" \
    > "$RES/bench_tele_on.json" 2>> "$RES/log.txt"
  note telemetry_on
  python tools/summarize_trace.py "$RES"/trace/trace.p*.json \
    >> "$RES/log.txt" 2>&1 || true
fi

# --- Gated flight-record rehearsal (ask with DDL_FLIGHT=1) ----------------
# CPU-only, OFF by default (same reasoning as the chaos step): a short
# launch.py run with a sigkill injected mid-attempt, recorded into a
# flight dir under $RES, then tools/postmortem.py --json over it. The
# artifact pair (flight dir + postmortem JSON) proves end to end that a
# hard kill leaves a complete, parseable record with an attributed
# incident chain — the thing docs/observability.md promises on-call.
if [ "${DDL_FLIGHT:-0}" = "1" ]; then
  check_stop flight
  rm -rf "$RES/flight" && mkdir -p "$RES/flight"
  timeout 600 env JAX_PLATFORMS=cpu \
    python launch.py --num-processes 1 --max-restarts 2 --backoff 0.2 \
    --heartbeat-timeout 120 --flight-dir "$RES/flight" -- \
    python train.py --backend cpu --model resnet18_thin --image-size 32 \
    --batch-size 8 --dp 1 --synthetic --dtype float32 --steps 6 \
    --checkpoint-dir "$RES/flight_ckpt" --checkpoint-every 2 \
    --log-every 1000000 --fault-plan "sigkill@4" >> "$RES/log.txt" 2>&1
  note flight_chaos
  timeout 120 env JAX_PLATFORMS=cpu python tools/postmortem.py \
    "$RES/flight" --checkpoint-dir "$RES/flight_ckpt" --json \
    > "$RES/postmortem.json" 2>> "$RES/log.txt"
  note flight_postmortem
fi
echo "[$(stamp)] window done" >> "$RES/log.txt"
