#!/bin/bash
# Run every pending on-chip measurement in priority order, one log per step.
# Usage: tools/chip_window.sh [results_dir]   (default .chip_results)
# Each step gets a hard timeout so one hang can't burn the whole window;
# steps append to RES so partial windows still leave evidence.
set -u
cd "$(dirname "$0")/.."
RES="$(realpath -m "${1:-.chip_results}")"  # absolute: survives the cd above
mkdir -p "$RES"
stamp() { date +%H:%M:%S; }
note() { rc=$?; echo "[$(stamp)] $1 rc=$rc" >> "$RES/log.txt"; }

echo "[$(stamp)] window open" >> "$RES/log.txt"

# 1. Headline bench (refreshes compile cache for the driver's run).
timeout 600 python bench.py > "$RES/bench_headline.json" 2>> "$RES/log.txt"
note headline

# 2. Acceptance-suite rows (all configs, one child process).
timeout 1500 python bench.py --suite --budget 1400 \
  > "$RES/bench_suite.json" 2>> "$RES/log.txt"
note suite

# 3. Fused-block step A/B vs unfused (the round-3 kernel project).
timeout 900 python tools/ab_fused_block.py --batches 256,512 \
  > "$RES/fused_block_ab.json" 2>> "$RES/log.txt"
note fused_block

# 4. Pallas matmul vs XLA dot at ResNet 1x1 shapes (kernel derisk data).
timeout 600 python - > "$RES/matmul_micro.json" 2>> "$RES/log.txt" <<'EOF'
import json, sys, time
sys.path.insert(0, ".")
import jax, jax.numpy as jnp
from distributeddeeplearning_tpu.ops.fused_linear_bn import linear_stats

def t(f, *a):
    r = jax.jit(f)
    out = r(*a)
    jax.tree.map(lambda x: x.block_until_ready(), out)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(10):
        out = r(*a)
    jax.tree.map(lambda x: x.block_until_ready(), out)
    return (time.perf_counter() - t0) / 10

# (M, K, N) of resnet50 b256 1x1 convs: layer1 c3, layer2 c3, layer3 c3.
for m, k, n in ((802816, 64, 256), (200704, 128, 512), (50176, 256, 1024),
                (200704, 512, 256)):
    x = jax.random.normal(jax.random.key(0), (m, k), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (k, n), jnp.bfloat16)
    xla = t(lambda a, b: jnp.dot(a, b, preferred_element_type=jnp.float32
                                 ).astype(jnp.bfloat16), x, w)
    pls = t(lambda a, b: linear_stats(a, b)[0], x, w)
    tf_ = 2 * m * k * n
    print(json.dumps({"mkn": [m, k, n],
                      "xla_ms": round(xla * 1e3, 2),
                      "pallas_stats_ms": round(pls * 1e3, 2),
                      "xla_tflops": round(tf_ / xla / 1e12, 1),
                      "pallas_tflops": round(tf_ / pls / 1e12, 1)}),
          flush=True)
EOF
note matmul_micro

# 5. Profile the fused-block step (where does its time go).
timeout 600 python tools/profile_step.py --model resnet50 --batch-size 256 \
  --fused-block --top 25 > "$RES/profile_fused_block.json" 2>> "$RES/log.txt"
note profile

# 6. XLA-flag sweep on the headline config (quick protocol): any free wins
# from scheduler/memory knobs the default compile doesn't enable. The jax
# compilation cache keys on the flags, so cached default executables don't
# mask these runs.
for flags in \
  "--xla_tpu_enable_latency_hiding_scheduler=true" \
  "--xla_tpu_scoped_vmem_limit_kib=98304"; do
  tag=$(echo "$flags" | tr -cd 'a-z_' | tail -c 24)
  echo "[$(stamp)] xla flags: $flags" >> "$RES/log.txt"
  XLA_FLAGS="$flags" \
    timeout 420 python bench.py --steps 10 --attempts 1 --budget 400 \
    --sweep none >> "$RES/xla_flag_sweep.json" 2>> "$RES/log.txt"
  note "xla_$tag"
done
# 7. Decode throughput (serving-side): GPT-2 KV-cache vs refeed.
timeout 600 python tools/bench_generate.py --model gpt2_small --batch 8 \
  --prompt-len 128 --new-tokens 128 > "$RES/decode_throughput.json" \
  2>> "$RES/log.txt"
note decode

# 8. Flash-attention compiled-kernel validation (fwd/bwd err + timing).
timeout 600 python tools/validate_flash_tpu.py \
  > "$RES/flash_validate.json" 2>> "$RES/log.txt"
note flash

# 9. Real-pixels end-to-end: disk JPEGs -> decode -> HBM -> train -> eval
# -> mid-run resume, through all three loaders (corpus pre-generated under
# .cache/real_jpegs — never spend window time on PIL).
# 7 legs x 180s fits the outer budget with slack for corpus checks.
timeout 1500 python tools/real_data_on_chip.py --steps 100 \
  --leg-timeout 180 > "$RES/real_data.json" 2>> "$RES/log.txt"
note real_data
echo "[$(stamp)] window done" >> "$RES/log.txt"
