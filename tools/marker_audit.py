#!/usr/bin/env python
"""Gate the tier-1 wall budget: fail if an unmarked test runs too long.

Tier-1 (``pytest -m 'not slow'``) has an 870 s budget on the 1-vCPU test
box; a single unmarked ~60 s+ test silently eats 7% of it and the budget
erodes one PR at a time. The audit closes that loop:

1. tests/conftest.py records every test's call duration each run and, when
   ``MARKER_AUDIT_JSON=<path>`` is set, dumps the records there.
2. This script reads the dump and exits 1 listing every test that exceeded
   the threshold without ``@pytest.mark.slow`` — chain it after pytest::

       MARKER_AUDIT_JSON=/tmp/durations.json pytest tests/ -m 'not slow'
       python tools/marker_audit.py /tmp/durations.json

The threshold (default 60 s) is deliberately far above any healthy tier-1
test here (slowest observed ~35 s) and far below the budget, so it only
trips on genuine misclassification, not machine jitter. Tests already
marked slow are never violations regardless of duration.
"""

from __future__ import annotations

import json
import sys

DEFAULT_THRESHOLD_S = 60.0
BUDGET_NOTE = "tier-1 budget 870s; mark tests >60s @pytest.mark.slow"


def find_violations(records, threshold_s: float = DEFAULT_THRESHOLD_S):
    """Records exceeding ``threshold_s`` without the slow marker.

    ``records``: iterables of dicts with ``nodeid``, ``duration`` (seconds,
    call phase only — setup/teardown cost is fixture-shared and not the
    test author's marker decision), ``slow`` (bool). Malformed entries are
    skipped rather than crashing the gate; sorted slowest-first.
    """
    out = []
    for rec in records:
        try:
            if rec["slow"] or float(rec["duration"]) <= threshold_s:
                continue
        except (KeyError, TypeError, ValueError):
            continue
        out.append(rec)
    return sorted(out, key=lambda r: -float(r["duration"]))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print(f"usage: marker_audit.py <durations.json> [threshold_s="
              f"{DEFAULT_THRESHOLD_S:g}]")
        return 0 if argv else 2
    threshold = float(argv[1]) if len(argv) > 1 else DEFAULT_THRESHOLD_S
    try:
        with open(argv[0]) as f:
            records = json.load(f)
    except (OSError, ValueError) as e:
        print(f"marker-audit: cannot read {argv[0]}: {e}", file=sys.stderr)
        return 2
    violations = find_violations(records, threshold)
    if not violations:
        print(f"marker-audit: OK — {len(records)} tests, none over "
              f"{threshold:g}s unmarked")
        return 0
    print(f"marker-audit: {len(violations)} test(s) over {threshold:g}s "
          f"without @pytest.mark.slow ({BUDGET_NOTE}):")
    for rec in violations:
        print(f"  {rec['duration']:7.1f}s  {rec['nodeid']}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
