#!/usr/bin/env python
"""Gate the tier-1 wall budget: fail if an unmarked test runs too long.

Tier-1 (``pytest -m 'not slow'``) has an 870 s budget on the 1-vCPU test
box; a single unmarked ~60 s+ test silently eats 7% of it and the budget
erodes one PR at a time. The audit closes that loop:

1. tests/conftest.py records every test's call duration each run and, when
   ``MARKER_AUDIT_JSON=<path>`` is set, dumps the records there.
2. This script reads the dump and exits 1 listing every test that exceeded
   the threshold without ``@pytest.mark.slow`` — chain it after pytest::

       MARKER_AUDIT_JSON=/tmp/durations.json pytest tests/ -m 'not slow'
       python tools/marker_audit.py /tmp/durations.json

The threshold (default 60 s) is deliberately far above any healthy tier-1
test here (slowest observed ~35 s) and far below the budget, so it only
trips on genuine misclassification, not machine jitter. Tests already
marked slow are never violations regardless of duration.
"""

from __future__ import annotations

import json
import os
import sys

DEFAULT_THRESHOLD_S = 60.0
BUDGET_NOTE = "tier-1 budget 870s; mark tests >60s @pytest.mark.slow"


def find_violations(records, threshold_s: float = DEFAULT_THRESHOLD_S):
    """Records exceeding ``threshold_s`` without the slow marker.

    ``records``: iterables of dicts with ``nodeid``, ``duration`` (seconds,
    call phase only — setup/teardown cost is fixture-shared and not the
    test author's marker decision), ``slow`` (bool). Malformed entries are
    skipped rather than crashing the gate; sorted slowest-first.
    """
    out = []
    for rec in records:
        try:
            if rec["slow"] or float(rec["duration"]) <= threshold_s:
                continue
        except (KeyError, TypeError, ValueError):
            continue
        out.append(rec)
    return sorted(out, key=lambda r: -float(r["duration"]))


def audit_perf_gate(records) -> list[str]:
    """Problems with the CPU-proxy perf gate's presence in this run.

    The gate (tests marked ``perf_gate``, observability/perf_gate.py)
    only protects anything while it actually executes in tier-1 — two
    silent failure modes would disarm it without failing anything:
    the marked tests disappear from the selection (renamed, deselected,
    collection error), or someone marks them ``slow`` and tier-1's
    ``-m 'not slow'`` filters the gate out. Both become loud here.
    """
    problems = []
    gate = [r for r in records if r.get("perf_gate")]
    if not gate:
        problems.append(
            "no perf_gate-marked test ran — the CPU-proxy perf gate is "
            "not protecting this run (tests/test_perf_gate.py missing, "
            "renamed, or deselected?)")
    elif not any("zero2" in (r.get("nodeid") or "") for r in gate):
        # The gate is two workloads since the ZeRO ladder landed: the
        # headline proxy AND the overlapped zero2 schedule (its extras
        # baseline in perf_baselines.json). Losing the sharded one is the
        # same silent-disarm failure mode as losing the gate entirely.
        problems.append(
            "perf_gate tests ran but none covers the zero2_overlap "
            "workload — the sharded-schedule gate "
            "(tests/test_perf_gate.py::test_perf_gate_live_zero2_overlap) "
            "is missing, renamed, or deselected")
    for rec in gate:
        if rec.get("slow"):
            problems.append(
                f"{rec.get('nodeid')} is marked BOTH perf_gate and slow — "
                f"tier-1 runs -m 'not slow', so this silently removes the "
                f"perf gate from tier-1")
    return problems


def audit_elastic(records) -> list[str]:
    """Problems with elastic-resume coverage in this run.

    The cross-degree resume path (tests marked ``elastic``) has the same
    silent-disarm failure modes as the perf gate: the marked tests vanish
    from the selection, or every one of them is also marked ``slow`` and
    tier-1's ``-m 'not slow'`` filters elastic coverage out entirely (the
    soak is legitimately slow — but a FAST variant must survive in
    tier-1; tests/test_elastic_resume.py keeps one).

    The rendezvous extension adds two coverage requirements: the
    topology-aware survivor-selection unit grid must run in EVERY
    selection (it is fast — losing it silently un-pins the deterministic
    shrink choice), and when the selection includes slow tests at all,
    the cross-axis soak (ZeRO stage + pipeline degree changing mid-run)
    must be among them."""
    problems = []
    elastic = [r for r in records if r.get("elastic")]
    if not elastic:
        problems.append(
            "no elastic-marked test ran — the cross-degree resume path is "
            "untested in this run (tests/test_elastic_resume.py missing, "
            "renamed, or deselected?)")
    elif all(r.get("slow") for r in elastic):
        problems.append(
            "every elastic-marked test is also marked slow — tier-1 runs "
            "-m 'not slow', so the cross-degree resume path is silently "
            "untested in tier-1 (keep a fast elastic variant unmarked)")
    if not any("survivor" in (r.get("nodeid") or "") for r in elastic):
        problems.append(
            "no elastic-marked survivor-selection test ran — the "
            "topology-aware shrink (hostmesh.select_survivors: "
            "deterministic, ring-contiguous) is un-pinned in this run "
            "(tests/test_rendezvous.py missing, renamed, or deselected?)")
    if (any(r.get("slow") for r in records)
            and not any("cross_axis" in (r.get("nodeid") or "")
                        for r in elastic)):
        problems.append(
            "slow tests ran but no elastic-marked cross_axis soak did — "
            "re-formation across the ZeRO-stage + pipeline-degree axes is "
            "untested in this slow run (tests/test_elastic_resume.py "
            "cross_axis soak missing, renamed, or deselected?)")
    return problems


def audit_flight(records) -> list[str]:
    """Problems with flight-recorder / post-mortem coverage in this run.

    The crash-surviving flight record (tests marked ``flight``) has the
    same silent-disarm failure modes: the marked tests vanish from the
    selection, or every one of them is also marked ``slow`` and tier-1's
    ``-m 'not slow'`` stops proving that a SIGKILL leaves a complete,
    parseable record with an attributable post-mortem."""
    problems = []
    flight = [r for r in records if r.get("flight")]
    if not flight:
        problems.append(
            "no flight-marked test ran — the crash-surviving flight "
            "record is untested in this run (tests/test_flight.py "
            "missing, renamed, or deselected?)")
    elif all(r.get("slow") for r in flight):
        problems.append(
            "every flight-marked test is also marked slow — tier-1 runs "
            "-m 'not slow', so the flight record / post-mortem path is "
            "silently untested in tier-1 (keep a fast flight variant "
            "unmarked)")
    return problems


def audit_lint(records) -> list[str]:
    """Problems with ddl-lint gate coverage in this run.

    The static-analysis gate (tests marked ``lint``) has the same
    silent-disarm failure modes: the marked tests vanish from the
    selection, every one is also marked ``slow`` and tier-1's
    ``-m 'not slow'`` filters the gate out, or the marker itself was
    dropped from pytest.ini and pytest's strict-marker path stops
    recognizing it."""
    problems = []
    lint = [r for r in records if r.get("lint")]
    if not lint:
        problems.append(
            "no lint-marked test ran — the ddl-lint static-analysis gate "
            "is untested in this run (tests/test_ddl_lint.py missing, "
            "renamed, or deselected?)")
    elif all(r.get("slow") for r in lint):
        problems.append(
            "every lint-marked test is also marked slow — tier-1 runs "
            "-m 'not slow', so the static-analysis gate is silently "
            "disarmed in tier-1 (lint tests are fast; never mark them "
            "slow)")
    ini = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "pytest.ini")
    try:
        with open(ini, encoding="utf-8") as f:
            registered = any(line.strip().startswith("lint:")
                             for line in f)
    except OSError:
        registered = False
    if not registered:
        problems.append(
            "the 'lint' marker is not registered in pytest.ini — "
            "register it under [pytest] markers or the gate tests "
            "become warnings instead of a gate")
    return problems


def audit_serve(records) -> list[str]:
    """Problems with serve-engine coverage in this run.

    The continuous-batching engine (tests marked ``serve``) has the same
    silent-disarm failure modes: the marked tests vanish from the
    selection, or every one is also marked ``slow`` and tier-1's
    ``-m 'not slow'`` stops pinning engine token-identity against
    sequential generate(). The serve_decode AND serve_prefix_prefill
    perf-gate workloads (tests/test_perf_gate.py) must also have run —
    losing either quietly un-gates the engine's per-step or
    admission-path cost — and the fast-path identity tests
    (tests/test_serve_fastpath.py: prefix cache + speculative decoding
    vs sequential generate()) must be present, or the COW/spec paths
    regress to "configured but unproven"."""
    problems = []
    serve = [r for r in records if r.get("serve")]
    if not serve:
        problems.append(
            "no serve-marked test ran — the continuous-batching engine is "
            "untested in this run (tests/test_serve.py missing, renamed, "
            "or deselected?)")
    elif all(r.get("slow") for r in serve):
        problems.append(
            "every serve-marked test is also marked slow — tier-1 runs "
            "-m 'not slow', so engine token-identity is silently unpinned "
            "in tier-1 (keep a fast serve variant unmarked)")
    if not any(r.get("perf_gate") and "serve_decode" in (r.get("nodeid")
                                                         or "")
               for r in records):
        problems.append(
            "no perf_gate test covering the serve_decode workload ran — "
            "the engine's decode-step cost is ungated "
            "(tests/test_perf_gate.py::test_perf_gate_live_serve_decode "
            "missing, renamed, or deselected?)")
    if not any(r.get("perf_gate") and "serve_prefix" in (r.get("nodeid")
                                                         or "")
               for r in records):
        problems.append(
            "no perf_gate test covering the serve_prefix_prefill workload "
            "ran — the prefix-cache admission path is ungated "
            "(tests/test_perf_gate.py::"
            "test_perf_gate_live_serve_prefix_prefill missing, renamed, "
            "or deselected?)")
    if serve and not any("fastpath" in (r.get("nodeid") or "")
                         for r in serve):
        problems.append(
            "no serve-marked fast-path test ran — prefix-cache / "
            "speculative-decoding token identity is unpinned "
            "(tests/test_serve_fastpath.py missing, renamed, or "
            "deselected?)")
    return problems


def audit_serve_chaos(records) -> list[str]:
    """Problems with serve-chaos coverage in this run.

    The fault-tolerant serving path (tests marked BOTH ``serve`` and
    ``chaos``: replica SIGKILL mid-stream through the supervised launch
    path, token-identical recovery, page-leak check) has the same
    silent-disarm failure modes: the combo-marked soak vanishes from the
    selection, or every instance is also marked ``slow`` and tier-1's
    ``-m 'not slow'`` stops proving recovery is token-identical."""
    problems = []
    soak = [r for r in records if r.get("serve") and r.get("chaos")]
    if not soak:
        problems.append(
            "no serve+chaos-marked test ran — token-identical recovery "
            "from a replica killed mid-stream is unproven in this run "
            "(tests/test_serve.py chaos soak missing, renamed, or "
            "deselected?)")
    elif all(r.get("slow") for r in soak):
        problems.append(
            "every serve+chaos-marked test is also marked slow — tier-1 "
            "runs -m 'not slow', so token-identical recovery is silently "
            "unproven in tier-1 (keep a fast serve-chaos soak unmarked)")
    return problems


def audit_pipeline(records) -> list[str]:
    """Problems with pipeline-schedule coverage in this run.

    The pipeline parity pins (tests marked ``pipeline``: 1f1b-vs-gpipe
    final-params identity, ZeRO-2 composition, cross-schedule resume)
    have the same silent-disarm failure modes: the marked tests vanish
    from the selection, or every one is also marked ``slow`` and tier-1's
    ``-m 'not slow'`` stops pinning schedule equivalence. The
    pipeline_1f1b perf-gate workload (tests/test_perf_gate.py) must also
    have run — losing it quietly un-gates the interleaved tick loop's
    step cost."""
    problems = []
    pipe = [r for r in records if r.get("pipeline")]
    if not pipe:
        problems.append(
            "no pipeline-marked test ran — the pipeline schedules are "
            "untested in this run (tests/test_pipeline.py missing, "
            "renamed, or deselected?)")
    elif all(r.get("slow") for r in pipe):
        problems.append(
            "every pipeline-marked test is also marked slow — tier-1 runs "
            "-m 'not slow', so schedule equivalence is silently unpinned "
            "in tier-1 (keep a fast pipeline variant unmarked)")
    if not any(r.get("perf_gate") and "pipeline" in (r.get("nodeid") or "")
               for r in records):
        problems.append(
            "no perf_gate test covering the pipeline_1f1b workload ran — "
            "the interleaved schedule's step cost is ungated "
            "(tests/test_perf_gate.py::test_perf_gate_live_pipeline_1f1b "
            "missing, renamed, or deselected?)")
    return problems


def audit_largebatch(records) -> list[str]:
    """Problems with large-batch / mixed-precision coverage in this run.

    The large-batch recipe (ISSUE 20: mixed-precision PrecisionPolicy,
    dynamic loss scaling, batch ramp) is gated by the largebatch_bf16
    CPU-proxy workload in tests/test_perf_gate.py — losing that test
    quietly un-gates the mixed-precision step's cost and phase mix. The
    loss-scale skip path and the ramp-boundary resume pin must also have
    run, or the recipe regresses to "configured but unproven"."""
    problems = []
    if not any(r.get("perf_gate") and "largebatch" in (r.get("nodeid")
                                                       or "")
               for r in records):
        problems.append(
            "no perf_gate test covering the largebatch_bf16 workload ran "
            "— the mixed-precision large-batch step is ungated "
            "(tests/test_perf_gate.py::"
            "test_perf_gate_live_largebatch_bf16 missing, renamed, or "
            "deselected?)")
    if not any("loss_scale" in (r.get("nodeid") or "") for r in records):
        problems.append(
            "no loss-scale test ran — the overflow->skip->halve->recover "
            "automaton is unpinned in this run "
            "(tests/test_mixed_precision.py missing, renamed, or "
            "deselected?)")
    if not any("ramp" in (r.get("nodeid") or "") for r in records):
        problems.append(
            "no batch-ramp test ran — ramp-boundary resume identity is "
            "unpinned in this run (tests/test_mixed_precision.py ramp "
            "tests missing, renamed, or deselected?)")
    return problems


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print(f"usage: marker_audit.py <durations.json> [threshold_s="
              f"{DEFAULT_THRESHOLD_S:g}] [--expect-perf-gate] "
              f"[--expect-elastic] [--expect-flight] [--expect-lint] "
              f"[--expect-serve] [--expect-serve-chaos] "
              f"[--expect-pipeline] [--expect-largebatch]")
        return 0 if argv else 2
    expect_gate = "--expect-perf-gate" in argv
    expect_elastic = "--expect-elastic" in argv
    expect_flight = "--expect-flight" in argv
    expect_lint = "--expect-lint" in argv
    expect_serve = "--expect-serve" in argv
    expect_serve_chaos = "--expect-serve-chaos" in argv
    expect_pipeline = "--expect-pipeline" in argv
    expect_largebatch = "--expect-largebatch" in argv
    argv = [a for a in argv
            if a not in ("--expect-perf-gate", "--expect-elastic",
                         "--expect-flight", "--expect-lint",
                         "--expect-serve", "--expect-serve-chaos",
                         "--expect-pipeline", "--expect-largebatch")]
    threshold = float(argv[1]) if len(argv) > 1 else DEFAULT_THRESHOLD_S
    try:
        with open(argv[0]) as f:
            records = json.load(f)
    except (OSError, ValueError) as e:
        print(f"marker-audit: cannot read {argv[0]}: {e}", file=sys.stderr)
        return 2
    violations = find_violations(records, threshold)
    # slow+perf_gate double-marking is checked on EVERY audit (it is a
    # static mistake); the presence checks (gate ran at all, both gate
    # workloads covered) are opt-in, because partial runs
    # (pytest tests/test_flops.py) legitimately lack the gate.
    gate_problems = audit_perf_gate(records)
    if not expect_gate:
        gate_problems = [p for p in gate_problems
                         if not p.startswith(("no perf_gate",
                                              "perf_gate tests ran but"))]
    # Elastic coverage is entirely opt-in (both of its problems are
    # presence checks, meaningless on partial runs).
    if expect_elastic:
        gate_problems += audit_elastic(records)
    # Flight-record coverage likewise (both problems are presence checks).
    if expect_flight:
        gate_problems += audit_flight(records)
    # ddl-lint gate coverage likewise (presence + registration checks).
    if expect_lint:
        gate_problems += audit_lint(records)
    # Serve-engine coverage likewise (presence + serve_decode gate checks).
    if expect_serve:
        gate_problems += audit_serve(records)
    # Serve-chaos soak coverage likewise (presence of the serve+chaos
    # combo-marked token-identical-recovery test).
    if expect_serve_chaos:
        gate_problems += audit_serve_chaos(records)
    # Pipeline-schedule coverage likewise (parity pins + the
    # pipeline_1f1b gate workload).
    if expect_pipeline:
        gate_problems += audit_pipeline(records)
    # Large-batch recipe coverage likewise (gate workload + loss-scale
    # + ramp pins).
    if expect_largebatch:
        gate_problems += audit_largebatch(records)
    if not violations and not gate_problems:
        print(f"marker-audit: OK — {len(records)} tests, none over "
              f"{threshold:g}s unmarked")
        return 0
    if violations:
        print(f"marker-audit: {len(violations)} test(s) over {threshold:g}s "
              f"without @pytest.mark.slow ({BUDGET_NOTE}):")
        for rec in violations:
            print(f"  {rec['duration']:7.1f}s  {rec['nodeid']}")
    for p in gate_problems:
        print(f"marker-audit: {p}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
