// Standalone sanitizer/stress driver for the native loader (ddl_loader.cc).
//
// SURVEY.md §5.2 commits any in-tree native code to ASAN/TSAN coverage; this
// driver exercises exactly the concurrency the loader's batch-slot ring and
// condition variables implement — worker pool vs. consumer, shutdown while
// blocked, finite-stream exhaustion, resume-at-start_batch — with no Python
// in the address space, so `make asan` / `make tsan` give clean signal.
//
// Exit 0 = all checks passed (and, under a sanitizer, no reports).

#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <jpeglib.h>
#include <sys/stat.h>
#include <unistd.h>

// C ABI of ddl_loader.cc (compiled into the same binary).
extern "C" {
struct DdlLoader;
DdlLoader* ddl_loader_create(const char** paths, const int32_t* labels,
                             int64_t num_samples, int32_t batch,
                             int32_t image_size, int32_t train, uint64_t seed,
                             int32_t num_threads, int32_t queue_depth,
                             int64_t start_batch, int32_t repeat,
                             const float* mean3, const float* stdev3);
int64_t ddl_loader_next(DdlLoader* L, float* images, int32_t* labels);
void ddl_loader_destroy(DdlLoader* L);
int32_t ddl_loader_abi_version();
}

namespace {

// Write a small solid-color JPEG so decode paths run for real.
void write_jpeg(const std::string& path, int h, int w, uint8_t r, uint8_t g,
                uint8_t b) {
  jpeg_compress_struct cinfo;
  jpeg_error_mgr jerr;
  cinfo.err = jpeg_std_error(&jerr);
  jpeg_create_compress(&cinfo);
  FILE* f = std::fopen(path.c_str(), "wb");
  assert(f);
  jpeg_stdio_dest(&cinfo, f);
  cinfo.image_width = (JDIMENSION)w;
  cinfo.image_height = (JDIMENSION)h;
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, 90, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  std::vector<uint8_t> row((size_t)w * 3);
  for (int x = 0; x < w; ++x) {
    row[(size_t)x * 3 + 0] = r;
    row[(size_t)x * 3 + 1] = g;
    row[(size_t)x * 3 + 2] = b;
  }
  JSAMPROW rp = row.data();
  for (int y = 0; y < h; ++y) jpeg_write_scanlines(&cinfo, &rp, 1);
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);
  std::fclose(f);
}

struct Fixture {
  std::string dir;
  std::vector<std::string> paths;
  std::vector<const char*> cpaths;
  std::vector<int32_t> labels;

  explicit Fixture(int n, int corrupt_every = 0) {
    char tmpl[] = "/tmp/ddl_loader_test_XXXXXX";
    assert(mkdtemp(tmpl));
    dir = tmpl;
    for (int i = 0; i < n; ++i) {
      std::string p = dir + "/img" + std::to_string(i) + ".jpg";
      if (corrupt_every && i % corrupt_every == 1) {
        FILE* f = std::fopen(p.c_str(), "wb");  // truncated garbage
        std::fwrite("\xff\xd8garbage", 1, 9, f);
        std::fclose(f);
      } else {
        write_jpeg(p, 40 + (i % 3) * 17, 40 + (i % 5) * 11,
                   (uint8_t)(i * 37), (uint8_t)(i * 59), (uint8_t)(i * 83));
      }
      paths.push_back(p);
      labels.push_back(i % 7);
    }
    for (auto& p : paths) cpaths.push_back(p.c_str());
  }
  ~Fixture() {
    for (auto& p : paths) unlink(p.c_str());
    rmdir(dir.c_str());
  }
};

const float kMean[3] = {0.0f, 0.0f, 0.0f};
const float kStd[3] = {1.0f, 1.0f, 1.0f};

constexpr int kSize = 32;
constexpr int kBatch = 4;

using Batch = std::pair<std::vector<float>, std::vector<int32_t>>;

Batch pull(DdlLoader* L, int64_t expect_idx) {
  std::vector<float> img((size_t)kBatch * kSize * kSize * 3);
  std::vector<int32_t> lab(kBatch);
  int64_t got = ddl_loader_next(L, img.data(), lab.data());
  if (got != expect_idx) {
    std::fprintf(stderr, "FAIL: next() returned %lld, expected %lld\n",
                 (long long)got, (long long)expect_idx);
    std::exit(1);
  }
  return {img, lab};
}

void test_determinism(Fixture& fx) {
  std::vector<Batch> a, b;
  for (int rep = 0; rep < 2; ++rep) {
    DdlLoader* L = ddl_loader_create(
        fx.cpaths.data(), fx.labels.data(), (int64_t)fx.paths.size(), kBatch,
        kSize, /*train=*/1, /*seed=*/7, /*threads=*/4, /*depth=*/2,
        /*start=*/0, /*repeat=*/1, kMean, kStd);
    assert(L);
    auto& dst = rep ? b : a;
    for (int64_t i = 0; i < 12; ++i) dst.push_back(pull(L, i));
    ddl_loader_destroy(L);
  }
  for (size_t i = 0; i < a.size(); ++i) {
    assert(a[i].second == b[i].second);
    assert(std::memcmp(a[i].first.data(), b[i].first.data(),
                       a[i].first.size() * sizeof(float)) == 0);
  }
  std::puts("ok determinism (same seed -> identical stream)");
}

void test_resume(Fixture& fx) {
  // start_batch=k on an infinite train stream resumes the exact sequence.
  DdlLoader* L0 = ddl_loader_create(
      fx.cpaths.data(), fx.labels.data(), (int64_t)fx.paths.size(), kBatch,
      kSize, 1, 7, 4, 3, /*start=*/0, /*repeat=*/1, kMean, kStd);
  std::vector<Batch> full;
  for (int64_t i = 0; i < 10; ++i) full.push_back(pull(L0, i));
  ddl_loader_destroy(L0);

  DdlLoader* L1 = ddl_loader_create(
      fx.cpaths.data(), fx.labels.data(), (int64_t)fx.paths.size(), kBatch,
      kSize, 1, 7, 4, 3, /*start=*/6, /*repeat=*/1, kMean, kStd);
  for (int64_t i = 6; i < 10; ++i) {
    Batch got = pull(L1, i);
    assert(got.second == full[(size_t)i].second);
    assert(std::memcmp(got.first.data(), full[(size_t)i].first.data(),
                       got.first.size() * sizeof(float)) == 0);
  }
  ddl_loader_destroy(L1);
  std::puts("ok resume (start_batch continues the identical stream)");
}

void test_finite_stream(Fixture& fx) {
  // Non-repeat: emits exactly batches_per_epoch batches then -1, and with
  // start_batch=k emits the remaining batches k..end of the (unshuffled
  // eval-order) epoch — the documented resume semantic.
  int64_t bpe = (int64_t)fx.paths.size() / kBatch;
  DdlLoader* L = ddl_loader_create(
      fx.cpaths.data(), fx.labels.data(), (int64_t)fx.paths.size(), kBatch,
      kSize, /*train=*/0, 7, 4, 2, /*start=*/0, /*repeat=*/0, kMean, kStd);
  std::vector<float> img((size_t)kBatch * kSize * kSize * 3);
  std::vector<int32_t> lab(kBatch);
  for (int64_t i = 0; i < bpe; ++i) assert(ddl_loader_next(L, img.data(), lab.data()) == i);
  assert(ddl_loader_next(L, img.data(), lab.data()) == -1);
  assert(ddl_loader_next(L, img.data(), lab.data()) == -1);  // idempotent
  ddl_loader_destroy(L);

  DdlLoader* L2 = ddl_loader_create(
      fx.cpaths.data(), fx.labels.data(), (int64_t)fx.paths.size(), kBatch,
      kSize, 0, 7, 4, 2, /*start=*/bpe - 1, /*repeat=*/0, kMean, kStd);
  assert(ddl_loader_next(L2, img.data(), lab.data()) == bpe - 1);
  assert(ddl_loader_next(L2, img.data(), lab.data()) == -1);
  ddl_loader_destroy(L2);
  std::puts("ok finite stream (exact batch count; start_batch tail resume)");
}

void test_corrupt_files() {
  Fixture fx(24, /*corrupt_every=*/3);
  DdlLoader* L = ddl_loader_create(
      fx.cpaths.data(), fx.labels.data(), (int64_t)fx.paths.size(), kBatch,
      kSize, 1, 3, 4, 2, 0, 1, kMean, kStd);
  std::vector<float> img((size_t)kBatch * kSize * kSize * 3);
  std::vector<int32_t> lab(kBatch);
  for (int64_t i = 0; i < 12; ++i) {
    assert(ddl_loader_next(L, img.data(), lab.data()) == i);
    for (float v : img) assert(std::isfinite(v));
  }
  ddl_loader_destroy(L);
  std::puts("ok corrupt files (gray fallback, stream stays aligned)");
}

void test_shutdown_races(Fixture& fx) {
  // Destroy at every early consumption depth, with workers mid-flight and
  // blocked on cv_space — the shutdown path TSAN cares about most.
  for (int consumed = 0; consumed < 6; ++consumed) {
    DdlLoader* L = ddl_loader_create(
        fx.cpaths.data(), fx.labels.data(), (int64_t)fx.paths.size(), kBatch,
        kSize, 1, 11, /*threads=*/8, /*depth=*/2, 0, 1, kMean, kStd);
    std::vector<float> img((size_t)kBatch * kSize * kSize * 3);
    std::vector<int32_t> lab(kBatch);
    for (int64_t i = 0; i < consumed; ++i)
      assert(ddl_loader_next(L, img.data(), lab.data()) == i);
    ddl_loader_destroy(L);
  }
  // Also: finite stream fully drained, workers already exited.
  DdlLoader* L = ddl_loader_create(
      fx.cpaths.data(), fx.labels.data(), (int64_t)fx.paths.size(), kBatch,
      kSize, 0, 11, 8, 2, 0, /*repeat=*/0, kMean, kStd);
  std::vector<float> img((size_t)kBatch * kSize * kSize * 3);
  std::vector<int32_t> lab(kBatch);
  while (ddl_loader_next(L, img.data(), lab.data()) >= 0) {}
  ddl_loader_destroy(L);
  std::puts("ok shutdown races (destroy at every drain depth)");
}

void test_stress(Fixture& fx) {
  // Oversubscribed workers vs. tiny ring: maximum contention on the
  // slot-reuse and cv_space/cv_ready paths, several epochs deep.
  DdlLoader* L = ddl_loader_create(
      fx.cpaths.data(), fx.labels.data(), (int64_t)fx.paths.size(), kBatch,
      kSize, 1, 5, /*threads=*/16, /*depth=*/2, 0, 1, kMean, kStd);
  std::vector<float> img((size_t)kBatch * kSize * kSize * 3);
  std::vector<int32_t> lab(kBatch);
  int64_t n_batches = 5 * ((int64_t)fx.paths.size() / kBatch);
  for (int64_t i = 0; i < n_batches; ++i)
    assert(ddl_loader_next(L, img.data(), lab.data()) == i);
  ddl_loader_destroy(L);
  std::puts("ok stress (16 workers, depth-2 ring, 5 epochs)");
}

}  // namespace

int main() {
  assert(ddl_loader_abi_version() == 1);
  Fixture fx(40);
  test_determinism(fx);
  test_resume(fx);
  test_finite_stream(fx);
  test_corrupt_files();
  test_shutdown_races(fx);
  test_stress(fx);
  std::puts("ALL OK");
  return 0;
}
