// Native data-loader runtime — the in-tree DALI equivalent (SURVEY.md §2 #6).
//
// The reference fed its trainers from DALI / tf.data *native* worker threads;
// this library is our same-role component for image-folder ImageNet layouts:
// a C++ thread pool that reads JPEG files, decodes them with libjpeg(-turbo),
// applies the standard ResNet50 recipe (random-resized-crop 8-100% area +
// horizontal flip for train; resize-256/center-crop-224 for eval; per-channel
// normalize), and assembles float32 NHWC batches into a bounded ring of batch
// slots so the host stays ahead of the accelerator.
//
// Determinism contract (matches data/imagenet.py's resume story): the sample
// order is a pure function of (seed, epoch) — per-epoch Fisher-Yates over the
// process's shard — so batch k is reproducible and checkpoint-resume can
// restart the stream at any batch index.
//
// Exposed as a C ABI for ctypes (data/native.py). No Python.h dependency.

#include <atomic>
#include <algorithm>
#include <memory>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <numeric>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg, error-safe via setjmp)
// ---------------------------------------------------------------------------

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decoded RGB image, 8-bit HWC.
struct Image {
  int h = 0, w = 0;
  std::vector<uint8_t> rgb;
  bool ok() const { return h > 0 && w > 0; }
};

struct Crop {
  int y, x, h, w;
};

// Fused decode-and-crop (both halves of DALI's trick, round 5):
//
// The header is parsed once; ``pick`` draws the crop from the FULL-image
// dimensions (so the crop never depends on decode scaling); then only
// the crop's region is decoded:
//
// 1. DCT scaling — decode at 1/2, 1/4, 1/8 resolution, chosen so the
//    SCALED CROP (not a worst-case crop bound) still covers ``target``
//    in both axes: knowing the crop up front lets the typical
//    20-60%-area crop take a deeper reduction than a global bound could.
//    Eval callers pass target = 2x the bilinear side to keep the
//    long-standing 2x decode-resolution margin (ADVICE r1 #3).
// 2. Region decode (libjpeg-turbo only) — jpeg_crop_scanline restricts
//    IDCT to the crop's horizontal band (widened to iMCU boundaries) and
//    jpeg_skip_scanlines skips rows above it; rows below are never read.
//    Plain IJG libjpeg (no LIBJPEG_TURBO_VERSION) falls back to a full
//    scaled-frame decode with identical pixels — just more IDCT work.
//
// Versioning note: crops were previously drawn on the DCT-scaled decoded
// dims; drawing on full header dims changes the realized deterministic
// stream versus round-4 builds for images large enough that scaling
// engaged (shorter side >= ~919px at 224 target). Within a build the
// stream remains a pure function of (seed, position).
template <typename PickCrop>
bool decode_jpeg_cropped(const uint8_t* buf, size_t len, int target,
                         const PickCrop& pick, Image* img, Crop* local) {
  jpeg_decompress_struct cinfo;
  JpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = jpeg_error_exit;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, len);
  jpeg_read_header(&cinfo, TRUE);
  const Crop crop = pick((int)cinfo.image_height, (int)cinfo.image_width);
  cinfo.out_color_space = JCS_RGB;
  cinfo.dct_method = JDCT_IFAST;
  cinfo.scale_num = 1;
  cinfo.scale_denom = 1;
  if (target > 0) {
    while (cinfo.scale_denom < 8 &&
           crop.w / (int)(cinfo.scale_denom * 2) >= target &&
           crop.h / (int)(cinfo.scale_denom * 2) >= target) {
      cinfo.scale_denom *= 2;
    }
  }
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3) {  // JCS_RGB should guarantee 3
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  const int denom = (int)cinfo.scale_denom;
  const int out_w = (int)cinfo.output_width;
  const int out_h = (int)cinfo.output_height;
  // Crop rectangle in scaled coordinates (floor start / ceil end keeps
  // the region a superset of the exact scaled crop).
  int sx = std::clamp(crop.x / denom, 0, out_w - 1);
  int sy = std::clamp(crop.y / denom, 0, out_h - 1);
  int ex = std::clamp((crop.x + crop.w + denom - 1) / denom, sx + 1, out_w);
  int ey = std::clamp((crop.y + crop.h + denom - 1) / denom, sy + 1, out_h);
#ifdef LIBJPEG_TURBO_VERSION
  JDIMENSION xoff = (JDIMENSION)sx;
  JDIMENSION xw = (JDIMENSION)(ex - sx);
  // Turbo widens the band to iMCU boundaries: xoff may move left and xw
  // may grow; the local crop x below accounts for the shift.
  jpeg_crop_scanline(&cinfo, &xoff, &xw);
  if (sy > 0 && (int)jpeg_skip_scanlines(&cinfo, (JDIMENSION)sy) != sy) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  const int row_w = (int)xw;
  const int rows = ey - sy;
  local->x = sx - (int)xoff;
  local->y = 0;
#else
  // IJG fallback: decode the full scaled frame; the crop is a plain
  // sub-rectangle of it.
  const int row_w = out_w;
  const int rows = out_h;
  local->x = sx;
  local->y = sy;
#endif
  img->w = row_w;
  img->h = rows;
  img->rgb.resize((size_t)rows * row_w * 3);
  for (int r = 0; r < rows;) {
    uint8_t* row = img->rgb.data() + (size_t)r * row_w * 3;
    JDIMENSION got = jpeg_read_scanlines(&cinfo, &row, 1);
    if (got == 0) {  // truncated stream
      jpeg_destroy_decompress(&cinfo);
      return false;
    }
    r += (int)got;
  }
  // Any rows below the crop band are never decoded; destroy aborts.
  jpeg_destroy_decompress(&cinfo);
  local->w = ex - sx;
  local->h = ey - sy;
  return img->ok();
}

// ---------------------------------------------------------------------------
// Crop + bilinear resize + normalize
// ---------------------------------------------------------------------------

// tf.image.sample_distorted_bounding_box-style random area crop.
Crop random_resized_crop(std::mt19937_64& rng, int h, int w) {
  std::uniform_real_distribution<float> area_d(0.08f, 1.0f);
  std::uniform_real_distribution<float> logr_d(std::log(3.0f / 4.0f),
                                               std::log(4.0f / 3.0f));
  for (int attempt = 0; attempt < 10; ++attempt) {
    float area = area_d(rng) * (float)h * (float)w;
    float aspect = std::exp(logr_d(rng));
    int cw = (int)std::lround(std::sqrt(area * aspect));
    int ch = (int)std::lround(std::sqrt(area / aspect));
    if (cw > 0 && ch > 0 && cw <= w && ch <= h) {
      std::uniform_int_distribution<int> yd(0, h - ch), xd(0, w - cw);
      return Crop{yd(rng), xd(rng), ch, cw};
    }
  }
  // Fallback: central crop of the shorter side (tf's use_image_if_no_bbox).
  int side = std::min(h, w);
  return Crop{(h - side) / 2, (w - side) / 2, side, side};
}

// Eval: crop fraction target/(target+32) of the shorter side, centered —
// identical protocol to data/imagenet.py::_decode_and_center_crop.
Crop center_crop(int h, int w, int target) {
  int shorter = std::min(h, w);
  int crop = (int)((float)target / (float)(target + 32) * (float)shorter);
  // >=1 guards degenerate (1-pixel-side) images from a zero-size crop,
  // which would send negative indices into resize_bilinear.
  crop = std::clamp(crop, 1, shorter);
  return Crop{(h - crop) / 2, (w - crop) / 2, crop, crop};
}

// Bilinear resize of an RGB crop region into out[target*target*3] float32,
// half-pixel centers (matches tf.image.resize v2 / torchvision).
void resize_bilinear(const Image& img, const Crop& c, int target, float* out,
                     bool hflip) {
  const float sy = (float)c.h / (float)target;
  const float sx = (float)c.w / (float)target;
  for (int oy = 0; oy < target; ++oy) {
    float fy = ((float)oy + 0.5f) * sy - 0.5f;
    int y0 = (int)std::floor(fy);
    float wy = fy - (float)y0;
    int y0c = std::clamp(y0, 0, c.h - 1) + c.y;
    int y1c = std::clamp(y0 + 1, 0, c.h - 1) + c.y;
    for (int ox = 0; ox < target; ++ox) {
      float fx = ((float)ox + 0.5f) * sx - 0.5f;
      int x0 = (int)std::floor(fx);
      float wx = fx - (float)x0;
      int x0c = std::clamp(x0, 0, c.w - 1) + c.x;
      int x1c = std::clamp(x0 + 1, 0, c.w - 1) + c.x;
      const uint8_t* p00 = &img.rgb[((size_t)y0c * img.w + x0c) * 3];
      const uint8_t* p01 = &img.rgb[((size_t)y0c * img.w + x1c) * 3];
      const uint8_t* p10 = &img.rgb[((size_t)y1c * img.w + x0c) * 3];
      const uint8_t* p11 = &img.rgb[((size_t)y1c * img.w + x1c) * 3];
      int out_x = hflip ? (target - 1 - ox) : ox;
      float* dst = out + ((size_t)oy * target + out_x) * 3;
      for (int ch = 0; ch < 3; ++ch) {
        float top = (1.0f - wx) * p00[ch] + wx * p01[ch];
        float bot = (1.0f - wx) * p10[ch] + wx * p11[ch];
        dst[ch] = (1.0f - wy) * top + wy * bot;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Loader: deterministic shuffled stream -> thread pool -> batch-slot ring
// ---------------------------------------------------------------------------

struct Sample {
  std::string path;
  int32_t label;
};

}  // namespace

extern "C" {

struct DdlLoader {
  std::vector<Sample> samples;
  int32_t batch = 0, image_size = 0;
  bool train = false, repeat = false;
  uint64_t seed = 0;
  float mean[3], stdev[3];

  // Batch-slot ring.
  struct Slot {
    std::vector<float> images;
    std::vector<int32_t> labels;
    std::atomic<int32_t> done{0};   // samples completed
    int64_t batch_idx = -1;
    bool ready = false;
  };
  std::vector<std::unique_ptr<Slot>> slots;  // Slot holds atomics (immovable)
  int64_t next_batch_to_emit = 0;      // consumer cursor (batches)
  std::atomic<int64_t> next_sample{0};  // global sample cursor (monotonic)
  int64_t total_batches = -1;           // -1 = infinite (repeat)

  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  bool stop = false;

  // Shuffled-order cache for the two most recent epochs: worker positions
  // straddle an epoch boundary while it drains, and a single-entry cache
  // would thrash a full O(n) reshuffle on every alternating lookup.
  std::mutex order_mu;
  int64_t order_epoch[2] = {-1, -1};
  std::vector<int64_t> order_cache[2];

  int64_t n() const { return (int64_t)samples.size(); }
  int64_t batches_per_epoch() const { return n() / batch; }

  // Sample index for global sequence position `pos` (deterministic).
  int64_t index_at(int64_t pos) {
    int64_t per_epoch = batches_per_epoch() * batch;  // drop remainder
    int64_t epoch = pos / per_epoch, off = pos % per_epoch;
    int slot = (int)(epoch & 1);
    std::lock_guard<std::mutex> lk(order_mu);
    if (order_epoch[slot] != epoch) {
      auto& order = order_cache[slot];
      order.resize(n());
      std::iota(order.begin(), order.end(), 0);
      if (train) {
        std::mt19937_64 rng(seed * 0x9e3779b97f4a7c15ULL + (uint64_t)epoch);
        std::shuffle(order.begin(), order.end(), rng);
      }
      order_epoch[slot] = epoch;
    }
    return order_cache[slot][off];
  }

  void fill_sample(int64_t pos, Slot& slot, int32_t slot_off) {
    const Sample& s = samples[index_at(pos)];
    float* out = slot.images.data() + (size_t)slot_off * image_size * image_size * 3;
    slot.labels[slot_off] = s.label;

    // Fused decode-and-crop: the crop is drawn from the header dims
    // inside decode_jpeg_cropped's single parse, then only its region is
    // decoded at the deepest DCT scale that keeps it >= the target in
    // both axes (no upsampling softening the augmentation distribution —
    // ADVICE r1 #3). Eval keeps its long-standing 2x decode-resolution
    // margin via the doubled target.
    Image img;
    Crop local{};
    bool hflip = false;
    {
      FILE* f = std::fopen(s.path.c_str(), "rb");
      if (f) {
        std::fseek(f, 0, SEEK_END);
        long len = std::ftell(f);
        std::fseek(f, 0, SEEK_SET);
        std::vector<uint8_t> buf((size_t)std::max(len, 0L));
        if (len > 0 && std::fread(buf.data(), 1, (size_t)len, f) == (size_t)len) {
          // Augmentation RNG keyed by (seed, pos): reproducible per
          // sample. hflip is drawn AFTER the crop, matching the old
          // draw order.
          std::mt19937_64 rng(
              seed ^ (0xda3e39cb94b95bdbULL * (uint64_t)(pos + 1)));
          auto pick = [&](int fh, int fw) {
            Crop c = train ? random_resized_crop(rng, fh, fw)
                           : center_crop(fh, fw, image_size);
            if (train) hflip = (rng() & 1) != 0;
            return c;
          };
          int target = train ? image_size : 2 * image_size;
          if (!decode_jpeg_cropped(buf.data(), buf.size(), target, pick,
                                   &img, &local)) {
            img = Image{};
          }
        }
        std::fclose(f);
      }
    }
    if (!img.ok()) {
      // Unreadable/corrupt file: deterministic gray frame (keeps the stream
      // aligned instead of shifting every later sample).
      for (size_t i = 0; i < (size_t)image_size * image_size; ++i)
        for (int ch = 0; ch < 3; ++ch)
          out[i * 3 + ch] = (128.0f - mean[ch]) / stdev[ch];
      return;
    }
    resize_bilinear(img, local, image_size, out, hflip);
    for (size_t i = 0; i < (size_t)image_size * image_size; ++i)
      for (int ch = 0; ch < 3; ++ch) {
        float& v = out[i * 3 + ch];
        v = (v - mean[ch]) / stdev[ch];
      }
  }

  void worker() {
    for (;;) {
      int64_t pos = next_sample.fetch_add(1);
      int64_t b = pos / batch;
      if (total_batches >= 0 && b >= total_batches) return;
      Slot* slot;
      {
        std::unique_lock<std::mutex> lk(mu);
        // Wait until batch b's slot is free (ring depth bound) or shutdown.
        cv_space.wait(lk, [&] {
          return stop || b < next_batch_to_emit + (int64_t)slots.size();
        });
        if (stop) return;
        slot = slots[b % slots.size()].get();
        if (slot->batch_idx != b) {
          slot->batch_idx = b;
          slot->done.store(0);
          slot->ready = false;
        }
      }
      fill_sample(pos, *slot, (int32_t)(pos % batch));
      if (slot->done.fetch_add(1) + 1 == batch) {
        std::lock_guard<std::mutex> lk(mu);
        slot->ready = true;
        cv_ready.notify_all();
      }
    }
  }

  // Returns batch index, or -1 when the (finite) stream is exhausted.
  int64_t next(float* images_out, int32_t* labels_out) {
    int64_t b = next_batch_to_emit;
    if (total_batches >= 0 && b >= total_batches) return -1;
    Slot& slot = *slots[b % slots.size()];
    {
      std::unique_lock<std::mutex> lk(mu);
      cv_ready.wait(lk, [&] {
        return stop || (slot.ready && slot.batch_idx == b);
      });
      if (stop) return -1;
    }
    std::memcpy(images_out, slot.images.data(),
                slot.images.size() * sizeof(float));
    std::memcpy(labels_out, slot.labels.data(),
                slot.labels.size() * sizeof(int32_t));
    {
      std::lock_guard<std::mutex> lk(mu);
      slot.ready = false;
      slot.batch_idx = -1;
      ++next_batch_to_emit;
      cv_space.notify_all();
    }
    return b;
  }
};

DdlLoader* ddl_loader_create(
    const char** paths, const int32_t* labels, int64_t num_samples,
    int32_t batch, int32_t image_size, int32_t train, uint64_t seed,
    int32_t num_threads, int32_t queue_depth, int64_t start_batch,
    int32_t repeat, const float* mean3, const float* stdev3) {
  if (num_samples <= 0 || batch <= 0 || image_size <= 0 ||
      num_samples < batch)
    return nullptr;
  auto* L = new DdlLoader();
  L->samples.reserve((size_t)num_samples);
  for (int64_t i = 0; i < num_samples; ++i)
    L->samples.push_back(Sample{paths[i], labels[i]});
  L->batch = batch;
  L->image_size = image_size;
  L->train = train != 0;
  L->repeat = repeat != 0;
  L->seed = seed;
  for (int c = 0; c < 3; ++c) {
    L->mean[c] = mean3 ? mean3[c] : 0.0f;
    L->stdev[c] = stdev3 ? stdev3[c] : 1.0f;
  }
  L->total_batches = L->repeat ? -1 : L->batches_per_epoch();
  L->next_batch_to_emit = start_batch;
  L->next_sample.store(start_batch * batch);

  int depth = std::max(queue_depth, 2);
  for (int i = 0; i < depth; ++i) {
    auto s = std::make_unique<DdlLoader::Slot>();
    s->images.resize((size_t)batch * image_size * image_size * 3);
    s->labels.resize((size_t)batch);
    L->slots.push_back(std::move(s));
  }
  int threads = std::max(num_threads, 1);
  for (int t = 0; t < threads; ++t)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

int64_t ddl_loader_next(DdlLoader* L, float* images, int32_t* labels) {
  return L ? L->next(images, labels) : -1;
}

void ddl_loader_destroy(DdlLoader* L) {
  if (!L) return;
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop = true;
    L->cv_space.notify_all();
    L->cv_ready.notify_all();
  }
  for (auto& t : L->workers) t.join();
  delete L;
}

int32_t ddl_loader_abi_version() { return 1; }

}  // extern "C"
