#!/usr/bin/env python
"""Training entrypoint — the reference's ``train.py`` CLI surface, TPU-native.

BASELINE.json:5 requires "the existing train.py entrypoints and benchmark
harness run unchanged from the CLI with --backend=tpu"; this is that CLI.
Pick an acceptance config by name (``--config``, see BASELINE.json:6-12) or
assemble one from flags.

Examples:
    python train.py --config resnet50_synthetic --steps 100
    python train.py --model resnet50 --batch-size 256 --dp 8 --backend tpu
    python train.py --config bert_base_mlm --steps 50 --tp 2 --sp 2
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import sys


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--config", default=None,
                   help="acceptance-config preset name (see --list-configs)")
    p.add_argument("--list-configs", action="store_true")
    p.add_argument("--backend", default="tpu", choices=["tpu", "cpu"],
                   help="device backend (BASELINE.json:5)")
    p.add_argument("--model", default=None, help="model registry name")
    p.add_argument("--batch-size", type=int, default=None,
                   help="global batch size")
    p.add_argument("--steps", type=int, default=None,
                   help="total train steps (overrides --epochs)")
    p.add_argument("--epochs", type=float, default=None)
    p.add_argument("--synthetic", action="store_true", default=None,
                   help="on-device synthetic data (config 1)")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--image-size", type=int, default=None,
                   help="decode/augment target side length for image "
                        "pipelines (default 224; small-corpus runs avoid "
                        "upscaling cost by matching their JPEG size)")
    p.add_argument("--loader", default=None,
                   choices=["auto", "tf", "native", "grain"],
                   help="input pipeline for image datasets")
    p.add_argument("--dp", type=int, default=None, help="data-parallel size")
    p.add_argument("--accum", type=int, default=None,
                   help="gradient-accumulation microbatches per optimizer "
                        "step (config 5's batch=32k on small meshes)")
    p.add_argument("--steps-per-loop", type=int, default=None,
                   help="fuse N train steps into one XLA program (lax.scan) "
                        "when data is generated on-device — amortizes "
                        "per-step host dispatch latency")
    p.add_argument("--fsdp", type=int, default=None)
    p.add_argument("--tp", type=int, default=None, help="tensor-parallel size")
    p.add_argument("--sp", type=int, default=None, help="sequence-parallel size")
    p.add_argument("--ep", type=int, default=None,
                   help="expert-parallel size (MoE models)")
    p.add_argument("--pp", type=int, default=None,
                   help="pipeline-parallel size (pipelined models)")
    p.add_argument("--attn", default=None,
                   choices=["dense", "ring", "flash", "zigzag"],
                   help="attention impl for transformer models")
    p.add_argument("--remat", action="store_true", default=None,
                   help="rematerialize transformer layers in backward "
                        "(less activation HBM, ~1/3 more FLOPs)")
    p.add_argument("--fused-bn", action="store_true", default=None,
                   help="Pallas fused BN(+residual)+ReLU kernels for CNNs "
                        "(ops/fused_batchnorm.py)")
    p.add_argument("--fused-block", action="store_true", default=None,
                   help="conv-epilogue fusion: bottleneck 1x1 convs as "
                        "Pallas matmul+BN (ops/fused_linear_bn.py; "
                        "resnet50/101/152)")
    p.add_argument("--fused-conv3", action="store_true", default=None,
                   help="fused_block v2: stride-1 3x3 convs as Pallas "
                        "conv+BN with bn1-apply prologue and bn2-stats "
                        "epilogue (ops/fused_conv_bn.py); requires "
                        "--fused-block")
    p.add_argument("--ema-decay", type=float, default=None,
                   help="exponential-moving-average of params (e.g. "
                        "0.9999); evals score the EMA weights")
    p.add_argument("--allreduce-bucket-mb", type=float, default=None,
                   help="gradient tensor-fusion bucket size in MB for the "
                        "explicit-DP path (parallel/collectives.py); one "
                        "collective per bucket instead of per parameter "
                        "leaf. 0 = per-leaf reduction (the unfused A/B "
                        "baseline); default 4")
    p.add_argument("--allreduce-dtype", default=None,
                   choices=["float32", "bfloat16"],
                   help="gradient all-reduce payload dtype: bfloat16 halves "
                        "the wire bytes and restores fp32 masters after the "
                        "reduce (documented tolerance, docs/"
                        "fused_allreduce.md)")
    p.add_argument("--allreduce-algo", default=None,
                   choices=["psum", "ring"],
                   help="per-bucket collective: one psum, or the "
                        "bandwidth-optimal psum_scatter+all_gather ring "
                        "form")
    p.add_argument("--optimizer-sharding", default=None,
                   choices=["none", "zero1", "zero2", "zero3"],
                   help="ZeRO sharding ladder for the explicit-DP path "
                        "(parallel/zero.py): zero1 = 1/N-sharded optimizer "
                        "state (reduce-scatter grads, chunk update, "
                        "all-gather updated params); zero2 = + gradients "
                        "born reduce-scattered during backward, full grad "
                        "tree never materialized; zero3 = + parameters "
                        "themselves 1/N-sharded, all-gathered on demand "
                        "per fusion bucket (FSDP unified with the bucket "
                        "planner)")
    p.add_argument("--no-overlap-collectives", dest="overlap_collectives",
                   action="store_false", default=None,
                   help="zero2/zero3: disable backward/collective overlap "
                        "(serialize every bucket's reduce-scatter after "
                        "backward) — the A/B baseline schedule; update "
                        "math is unchanged")
    p.add_argument("--opt-state-offload", action="store_true", default=None,
                   help="place the sharded optimizer-state chunks in host "
                        "RAM (pinned_host memory kind) instead of HBM; "
                        "requires runtime support (TPU), loud no-op "
                        "fallback elsewhere")
    p.add_argument("--sync-bn", action="store_true", default=None,
                   help="cross-replica BatchNorm statistics (psum over the "
                        "data axis, torch SyncBatchNorm semantics; pure-DP "
                        "CNN configs only)")
    p.add_argument("--pp-microbatches", type=int, default=None,
                   help="pipeline microbatch count for *_pp models; the "
                        "fill/drain bubble wastes (P-1)/(M*V+P-1) of each "
                        "step, so use M >= 4*(P-1) (or shrink V's "
                        "denominator with --pipeline-schedule 1f1b)")
    p.add_argument("--pipeline-schedule", default=None,
                   choices=["gpipe", "1f1b"],
                   help="pipeline schedule for *_pp models "
                        "(models/pipeline.py): gpipe = fill/drain; 1f1b = "
                        "interleaved one-forward-one-backward over "
                        "--pipeline-virtual-stages chunks per stage, "
                        "shrinking the bubble to (P-1)/(M*V+P-1) "
                        "(docs/pipeline.md)")
    p.add_argument("--pipeline-virtual-stages", type=int, default=None,
                   help="virtual chunks per stage for --pipeline-schedule "
                        "1f1b; must divide layers-per-stage, and M must be "
                        "a multiple of P when V > 1")
    p.add_argument("--seq-len", type=int, default=None,
                   help="sequence length for token models")
    p.add_argument("--mlm-max-predictions", type=int, default=None,
                   help="gather-mode MLM head: project only this many masked "
                        "positions to vocab; -1 = auto (round(0.15*seq_len), "
                        "the canonical BERT recipe); 0/unset = dense "
                        "full-sequence logits")
    p.add_argument("--optimizer", default=None, choices=["sgd", "lars", "adamw", "lamb"])
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--dtype", default=None, choices=["bfloat16", "float32"])
    p.add_argument("--precision", default=None, choices=["fp32", "mixed"],
                   help="explicit precision policy: 'mixed' = bf16 compute + "
                        "fp32 master weights + dynamic loss scaling, 'fp32' = "
                        "everything float32; subsumes --dtype "
                        "(docs/mixed_precision.md)")
    p.add_argument("--batch-ramp", default=None, metavar="SPEC",
                   help="staged global-batch ramp, e.g. '8192:600,16384:600,"
                        "32768' — 600 steps at 8192, 600 at 16384, then the "
                        "configured batch; every boundary must land on the "
                        "checkpoint cadence and the last stage must equal "
                        "--batch-size (docs/mixed_precision.md)")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--log-every", type=int, default=None)
    p.add_argument("--warmup-steps", type=int, default=2,
                   help="steps excluded from throughput timing")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--eval-batches", type=int, default=0,
                   help="periodic + final held-out eval over N batches "
                        "(top-1 for image models, loss/perplexity for "
                        "token models)")
    p.add_argument("--eval-every-epochs", type=float, default=None,
                   help="periodic-eval cadence in epochs (default 1.0; "
                        "needs --eval-batches and a sized dataset)")
    p.add_argument("--eval-only", action="store_true",
                   help="restore the newest checkpoint and run held-out "
                        "eval without training (requires --checkpoint-dir "
                        "and --eval-batches)")
    p.add_argument("--no-resume", action="store_true",
                   help="ignore existing checkpoints in --checkpoint-dir")
    p.add_argument("--profile-steps", default=None, metavar="A,B",
                   help="capture a jax.profiler trace of steps [A,B)")
    p.add_argument("--profile-dir", default=None,
                   help="trace output dir (default /tmp/ddl_tpu_profile)")
    p.add_argument("--trace-dir", default=None,
                   help="always-on phase telemetry: per-step phase spans, "
                        "per-bucket collective spans, fault/restart "
                        "instants, HBM gauges exported here as Chrome-trace "
                        "JSON (one file per process; read with "
                        "tools/summarize_trace.py or chrome://tracing)")
    p.add_argument("--trace-steps", default=None, metavar="A,B",
                   help="restrict step-tagged telemetry events to steps "
                        "[A,B) (default: the whole run)")
    p.add_argument("--flight-dir", default=None,
                   help="flight recorder: crash-surviving fsync'd JSONL "
                        "event log (steps, saves/restores, faults, "
                        "anomalies, re-formations) written here, one file "
                        "per host, plus Prometheus-text + JSON metric "
                        "exports; read with tools/postmortem.py (default: "
                        "$DDL_FLIGHT_DIR from launch.py --flight-dir, else "
                        "off)")
    p.add_argument("--no-anomaly-detection", action="store_true",
                   help="disable the online anomaly detector (loss spikes, "
                        "grad-norm drift, throughput collapse, straggler "
                        "trending on the log cadence)")
    p.add_argument("--straggler-threshold", type=float, default=None,
                   help="multi-host: warn when a host's log-cadence step "
                        "time exceeds this multiple of the cross-host mean "
                        "(default 1.5; 0 disables the per-log allgather)")
    p.add_argument("--fail-at-step", type=int, default=None,
                   help="DEPRECATED alias for --fault-plan crash@K "
                        "(fires on every restart attempt)")
    p.add_argument("--fault-plan", default=None, metavar="PLAN",
                   help="deterministic fault injection: comma-separated "
                        "kind@step[:qualifier] terms, e.g. "
                        "'sigkill@20,corrupt_latest_ckpt@20'; grammar and "
                        "kinds in docs/fault_tolerance.md")
    p.add_argument("--bad-step-guard", action="store_true",
                   help="compile the non-finite-update skip guard into the "
                        "train step (auto-enabled when --fault-plan injects "
                        "nan_grads); costs ~1 ULP of trajectory drift vs "
                        "the guard-free program, see docs/fault_tolerance.md")
    p.add_argument("--bad-step-limit", type=int, default=None,
                   help="abort after K consecutive non-finite update steps "
                        "(skipped, not applied; default 10)")
    p.add_argument("--loader-timeout", type=float, default=None,
                   help="data watchdog: seconds to wait per host batch "
                        "before retrying (0 = watchdog off, the default)")
    p.add_argument("--loader-retries", type=int, default=None,
                   help="data watchdog: retries per batch before declaring "
                        "the loader stalled (default 2)")
    p.add_argument("--checkpoint-every", type=int, default=None,
                   help="save a checkpoint every N steps")
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent compile cache + AOT step executables "
                        "(docs/compile_cache.md); default "
                        "$DDL_COMPILE_CACHE or <repo>/.cache/jax_compile; "
                        "'off' disables")
    p.add_argument("--tensorboard-dir", default=None,
                   help="mirror metrics into TF summaries at this dir")
    return p.parse_args(argv)


def build_config(args: argparse.Namespace):
    from distributeddeeplearning_tpu import config as cfglib

    cfg = cfglib.preset(args.config) if args.config else cfglib.TrainConfig()
    if args.model:
        cfg = cfg.replace(model=args.model)
    if args.batch_size:
        cfg = cfg.replace(global_batch_size=args.batch_size)
    if args.epochs:
        cfg = cfg.replace(num_epochs=args.epochs)
    if args.dtype:
        cfg = cfg.replace(dtype=args.dtype)
    if args.precision:
        pol = (cfglib.PrecisionPolicy.mixed() if args.precision == "mixed"
               else cfglib.PrecisionPolicy.fp32())
        cfg = cfg.replace(precision=pol, dtype=pol.compute_dtype)
    if args.batch_ramp:
        cfg = cfg.replace(batch_ramp=args.batch_ramp)
    if args.seed is not None:
        cfg = cfg.replace(seed=args.seed)
    if args.log_every:
        cfg = cfg.replace(log_every=args.log_every)
    if args.checkpoint_dir:
        cfg = cfg.replace(checkpoint_dir=args.checkpoint_dir)
    if args.no_resume:
        cfg = cfg.replace(resume=False)
    if args.fail_at_step is not None:
        if args.fail_at_step <= 0:
            raise SystemExit(
                f"--fail-at-step must be positive (got {args.fail_at_step})")
        cfg = cfg.replace(fail_at_step=args.fail_at_step)
    if args.fault_plan:
        from distributeddeeplearning_tpu.robustness import faults
        try:
            faults.parse_plan(args.fault_plan)  # fail fast on grammar errors
        except ValueError as e:
            raise SystemExit(f"--fault-plan: {e}")
        cfg = cfg.replace(fault_plan=args.fault_plan)
    if args.bad_step_limit is not None:
        if args.bad_step_limit <= 0:
            raise SystemExit(
                f"--bad-step-limit must be positive (got {args.bad_step_limit})")
        cfg = cfg.replace(bad_step_limit=args.bad_step_limit)
    if args.bad_step_guard:
        cfg = cfg.replace(bad_step_guard=True)
    if args.loader_timeout is not None:
        if args.loader_timeout < 0:
            raise SystemExit(
                f"--loader-timeout must be >= 0 (got {args.loader_timeout})")
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, loader_timeout_s=args.loader_timeout))
    if args.loader_retries is not None:
        if args.loader_retries < 0:
            raise SystemExit(
                f"--loader-retries must be >= 0 (got {args.loader_retries})")
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, loader_retries=args.loader_retries))
    if args.checkpoint_every is not None:
        if args.checkpoint_every <= 0:
            raise SystemExit(
                f"--checkpoint-every must be positive (got {args.checkpoint_every})")
        cfg = cfg.replace(checkpoint_every_steps=args.checkpoint_every)
    if args.compile_cache_dir is not None:
        cfg = cfg.replace(compile_cache_dir=args.compile_cache_dir)
    if args.accum is not None:
        if args.accum <= 0:
            raise SystemExit(f"--accum must be positive (got {args.accum})")
        cfg = cfg.replace(grad_accum_steps=args.accum)
    if args.steps_per_loop is not None:
        if args.steps_per_loop <= 0:
            raise SystemExit(
                f"--steps-per-loop must be positive (got {args.steps_per_loop})")
        cfg = cfg.replace(steps_per_loop=args.steps_per_loop)
    cfg = cfg.replace(backend=args.backend)
    if args.profile_steps:
        try:
            lo, hi = (int(x) for x in args.profile_steps.split(","))
        except ValueError:
            raise SystemExit(
                f"--profile-steps expects A,B (got {args.profile_steps!r})")
        if not 0 <= lo < hi:
            raise SystemExit(
                f"--profile-steps needs 0 <= A < B (got {lo},{hi})")
        cfg = cfg.replace(profile_steps=(lo, hi))
    if args.profile_dir:
        cfg = cfg.replace(profile_dir=args.profile_dir)
    if args.trace_dir:
        cfg = cfg.replace(trace_dir=args.trace_dir)
    if args.flight_dir:
        cfg = cfg.replace(flight_dir=args.flight_dir)
    if args.no_anomaly_detection:
        cfg = cfg.replace(anomaly_detection=False)
    if args.trace_steps:
        try:
            lo, hi = (int(x) for x in args.trace_steps.split(","))
        except ValueError:
            raise SystemExit(
                f"--trace-steps expects A,B (got {args.trace_steps!r})")
        if not 0 <= lo < hi:
            raise SystemExit(
                f"--trace-steps needs 0 <= A < B (got {lo},{hi})")
        cfg = cfg.replace(trace_steps=(lo, hi))
    if args.straggler_threshold is not None:
        if args.straggler_threshold < 0:
            raise SystemExit(f"--straggler-threshold must be >= 0 "
                             f"(got {args.straggler_threshold})")
        cfg = cfg.replace(straggler_threshold=args.straggler_threshold)

    par = cfg.parallel
    updates = {}
    if args.dp is not None:
        updates["data"] = args.dp
    if args.fsdp is not None:
        updates["fsdp"] = args.fsdp
    if args.tp is not None:
        updates["model"] = args.tp
    if args.sp is not None:
        updates["seq"] = args.sp
    if args.ep is not None:
        updates["expert"] = args.ep
    if args.pp is not None:
        updates["pipeline"] = args.pp
    if updates:
        cfg = cfg.replace(parallel=dataclasses.replace(par, **updates))

    if args.attn:
        cfg = cfg.replace(attention_impl=args.attn)
    if args.remat:
        cfg = cfg.replace(remat=True)
    if args.eval_every_epochs is not None:
        if args.eval_every_epochs <= 0:
            raise SystemExit(f"--eval-every-epochs must be positive "
                             f"(got {args.eval_every_epochs})")
        cfg = cfg.replace(eval_every_epochs=args.eval_every_epochs)
    if args.fused_bn:
        cfg = cfg.replace(fused_bn=True)
    if args.fused_block:
        cfg = cfg.replace(fused_block=True)
    if args.fused_conv3:
        if not (args.fused_block or cfg.fused_block):
            raise SystemExit(
                "--fused-conv3 requires --fused-block (it extends the "
                "fused bottleneck's statistics plumbing)")
        cfg = cfg.replace(fused_conv3=True)
    if args.sync_bn:
        cfg = cfg.replace(sync_bn=True)
    ar_updates = {}
    if args.allreduce_bucket_mb is not None:
        if args.allreduce_bucket_mb < 0:
            raise SystemExit(f"--allreduce-bucket-mb must be >= 0 "
                             f"(got {args.allreduce_bucket_mb}); 0 selects "
                             f"per-leaf reduction")
        ar_updates["bucket_mb"] = args.allreduce_bucket_mb
    if args.allreduce_dtype:
        ar_updates["dtype"] = args.allreduce_dtype
    if args.allreduce_algo:
        ar_updates["algorithm"] = args.allreduce_algo
    if ar_updates:
        cfg = cfg.replace(
            allreduce=dataclasses.replace(cfg.allreduce, **ar_updates))
    if args.optimizer_sharding:
        cfg = cfg.replace(optimizer_sharding=args.optimizer_sharding)
    if args.overlap_collectives is not None:
        cfg = cfg.replace(overlap_collectives=args.overlap_collectives)
    if args.opt_state_offload:
        cfg = cfg.replace(opt_state_offload=True)
    if args.ema_decay is not None:
        cfg = cfg.replace(optimizer=dataclasses.replace(
            cfg.optimizer, ema_decay=args.ema_decay))
    if args.pp_microbatches is not None:
        cfg = cfg.replace(pipeline_microbatches=args.pp_microbatches)
    if args.pipeline_schedule:
        cfg = cfg.replace(pipeline_schedule=args.pipeline_schedule)
    if args.pipeline_virtual_stages is not None:
        if args.pipeline_virtual_stages < 1:
            raise SystemExit(
                f"--pipeline-virtual-stages must be >= 1 "
                f"(got {args.pipeline_virtual_stages})")
        cfg = cfg.replace(pipeline_virtual_stages=args.pipeline_virtual_stages)
    if cfg.pipeline_virtual_stages > 1 and cfg.pipeline_schedule != "1f1b":
        raise SystemExit(
            "--pipeline-virtual-stages > 1 requires --pipeline-schedule "
            "1f1b (gpipe has no virtual chunks)")

    data_updates = {}
    if args.synthetic is not None:
        data_updates["synthetic"] = True
    if args.seq_len:
        data_updates["seq_len"] = args.seq_len
    if args.mlm_max_predictions is not None:
        from distributeddeeplearning_tpu.models import model_spec
        spec = model_spec(cfg.model)
        data_updates["mlm_max_predictions"] = \
            cfglib.resolve_mlm_max_predictions(
                args.mlm_max_predictions,
                data_updates.get("seq_len", cfg.data.seq_len),
                spec.objective)
    if args.data_dir:
        data_updates["data_dir"] = args.data_dir
        data_updates["synthetic"] = False
    if args.loader:
        data_updates["loader"] = args.loader
    if args.image_size is not None:
        if args.image_size <= 0:
            raise SystemExit(
                f"--image-size must be positive (got {args.image_size})")
        data_updates["image_size"] = args.image_size
    if data_updates:
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, **data_updates))

    opt_updates = {}
    if args.optimizer:
        opt_updates["name"] = args.optimizer
    if args.lr is not None:
        opt_updates["learning_rate"] = args.lr
    if opt_updates:
        cfg = cfg.replace(
            optimizer=dataclasses.replace(cfg.optimizer, **opt_updates))
    return cfg


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.list_configs:
        from distributeddeeplearning_tpu import config as cfglib
        print("\n".join(cfglib.PRESETS))
        return 0

    import os
    if args.backend == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"

    # Join a multi-host job if the launcher (launch.py) configured one —
    # the MPI_Init moment of the reference's stack (SURVEY.md §3.1).
    from distributeddeeplearning_tpu import launch as launchlib
    launchlib.maybe_initialize_distributed()

    cfg = build_config(args)
    from distributeddeeplearning_tpu.train import loop

    from distributeddeeplearning_tpu.models import model_spec

    total_steps = args.steps
    if args.eval_only:
        if not (args.checkpoint_dir and args.eval_batches > 0):
            raise SystemExit(
                "--eval-only needs --checkpoint-dir (the model to restore) "
                "and a positive --eval-batches (how much of the held-out "
                "split to score)")
        if args.no_resume:
            raise SystemExit(
                "--eval-only with --no-resume would score freshly "
                "initialized weights; drop --no-resume")
        if total_steps is not None or args.epochs:
            raise SystemExit(
                "--eval-only trains nothing; drop --steps/--epochs "
                "(or drop --eval-only to train then eval)")
        # Refuse an empty/typo'd directory BEFORE paying for compile + a
        # full eval of randomly initialized weights.
        from distributeddeeplearning_tpu.train import checkpoint as ckptlib
        ck = ckptlib.Checkpointer.create(cfg)
        try:
            if ck.latest_step() is None:
                raise SystemExit(
                    f"--eval-only: no checkpoint found in "
                    f"{cfg.checkpoint_dir!r}; refusing to score randomly "
                    f"initialized weights")
        finally:
            ck.close()
        # total_steps=0 with resume: the restored step lands past the
        # (empty) training range, so the loop skips straight to final eval.
        total_steps = 0
    elif total_steps is None:
        if model_spec(cfg.model).input_kind == "tokens":
            # MLM pretraining is step-based (no canonical "epoch"); require
            # an explicit step budget rather than inventing one.
            raise SystemExit(
                "token models have no epoch semantics; pass --steps")
        steps_per_epoch = loop.steps_per_epoch(cfg)
        if steps_per_epoch is None:
            raise SystemExit(
                f"dataset {cfg.data.dataset!r} has no known epoch size; "
                "pass --steps or set steps_per_epoch in the config")
        total_steps = int(cfg.num_epochs * steps_per_epoch)

    logger_cm = contextlib.nullcontext(None)
    if args.tensorboard_dir:
        from distributeddeeplearning_tpu.utils.logging import MetricLogger
        # Context manager: the TB writer / JSONL handle is released even
        # when the loop raises (preemption SystemExit, injected faults).
        logger_cm = MetricLogger(tensorboard_dir=args.tensorboard_dir)

    with logger_cm as logger:
        summary = loop.run(cfg, total_steps=total_steps,
                           warmup_steps=min(args.warmup_steps,
                                            total_steps - 1)
                           if total_steps > 1 else 0,
                           eval_batches=args.eval_batches, logger=logger,
                           restore_for_eval=args.eval_only)
    if args.eval_only and summary["start_step"] == 0:
        # Backstop for a checkpoint that vanished between the pre-check and
        # the restore: never report a random-init score as a valid summary.
        raise SystemExit(
            f"--eval-only: no checkpoint found in {cfg.checkpoint_dir!r}; "
            "refusing to score randomly initialized weights")
    import jax
    if jax.process_index() == 0:
        print(json.dumps({"summary": summary}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
