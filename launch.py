#!/usr/bin/env python
"""Pod-slice launcher CLI — replaces the reference's mpirun/Batch-AI job
submission (SURVEY.md §2 #10). See distributeddeeplearning_tpu/launch.py.

    python launch.py --num-processes 2 -- python train.py --backend cpu ...
"""

import sys

from distributeddeeplearning_tpu import launch

if __name__ == "__main__":
    sys.exit(launch.main())
