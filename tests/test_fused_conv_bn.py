"""ops/fused_conv_bn.py — the fused-block v2 3x3 conv kernel.

Kernel (interpret mode) vs jnp twin vs the classic unfused composition
(bn-apply -> lax conv -> stats reduce), forward and VJP, plus the
block/model level through ResNet(fused_conv3=True). CPU-tractable shapes;
the on-chip compiled validation is staged in tools/chip_window.sh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.resnet import (
    BottleneckBlock, ResNet)
from distributeddeeplearning_tpu.ops import fused_conv_bn as fc

jax.config.update("jax_platforms", "cpu")


def _inputs(B=2, H=8, W=6, Cin=8, Cout=16, key=0):
    ks = jax.random.split(jax.random.key(key), 6)
    x = jax.random.normal(ks[0], (B, H, W, Cin), jnp.float32)
    w = jax.random.normal(ks[1], (3, 3, Cin, Cout)) * 0.1
    mu = x.mean(axis=(0, 1, 2))
    inv = jax.lax.rsqrt(x.var(axis=(0, 1, 2)) + 1e-5)
    g = jnp.abs(jax.random.normal(ks[2], (Cin,))) + 0.5
    b = jax.random.normal(ks[3], (Cin,)) * 0.1
    return x, mu, inv, g, b, w


def _reference(x, mu, inv, g, b, w, relu, bn):
    """The unfused composition the kernel must reproduce."""
    a = x.astype(jnp.float32)
    if bn:
        a = (a - mu) * (inv * g) + b
        if relu:
            a = jnp.maximum(a, 0.0)
    a = a.astype(x.dtype)
    y = jax.lax.conv_general_dilated(
        a, w.astype(a.dtype), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32).astype(x.dtype)
    yf = y.astype(jnp.float32)
    return y, yf.sum(axis=(0, 1, 2)), (yf * yf).sum(axis=(0, 1, 2))


@pytest.mark.core
@pytest.mark.parametrize("relu,bn", [(True, True), (False, True),
                                     (False, False)])
def test_kernel_forward_matches_reference(relu, bn):
    x, mu, inv, g, b, w = _inputs()
    y_k, s_k, ss_k = fc._fwd(x, mu, inv, g, b, w, relu, bn)
    y_r, s_r, ss_r = _reference(x, mu, inv, g, b, w, relu, bn)
    np.testing.assert_allclose(y_k, y_r, rtol=1e-4, atol=3e-4)
    np.testing.assert_allclose(s_k, s_r, rtol=2e-4, atol=1e-2)
    np.testing.assert_allclose(ss_k, ss_r, rtol=2e-4, atol=1e-2)


def test_kernel_multi_row_block_and_halo():
    # W=64 forces th=8 over H=32 -> 4 row blocks per image: the top/bottom
    # halo DMAs and the boundary masking all engage.
    x, mu, inv, g, b, w = _inputs(B=2, H=32, W=64, Cin=8, Cout=16, key=7)
    assert fc._row_block(32, 64) == 8
    y_k, s_k, ss_k = fc._fwd(x, mu, inv, g, b, w, True, True)
    y_r, s_r, ss_r = _reference(x, mu, inv, g, b, w, True, True)
    np.testing.assert_allclose(y_k, y_r, rtol=1e-4, atol=3e-4)
    np.testing.assert_allclose(s_k, s_r, rtol=2e-4, atol=5e-2)
    np.testing.assert_allclose(ss_k, ss_r, rtol=2e-4, atol=5e-2)


@pytest.mark.core
def test_vjp_matches_autodiff_of_reference():
    x, mu, inv, g, b, w = _inputs()
    cot = jax.random.normal(jax.random.key(9), (3,))

    def scalar(fn):
        def run(x, mu, inv, g, b, w):
            y, s, ss = fn(x, mu, inv, g, b, w)
            return (cot[0] * (y.astype(jnp.float32) ** 2).sum()
                    + cot[1] * s.sum() + cot[2] * (ss ** 2).sum())
        return run

    fused = scalar(lambda *a: fc.bn_conv3x3_stats(*a, True, True))
    ref = scalar(lambda *a: _reference(*a, True, True))
    grads_f = jax.grad(fused, argnums=(0, 1, 2, 3, 4, 5))(x, mu, inv, g, b, w)
    grads_r = jax.grad(ref, argnums=(0, 1, 2, 3, 4, 5))(x, mu, inv, g, b, w)
    for name, gf, gr in zip("x mu inv gamma beta w".split(),
                            grads_f, grads_r):
        err = float(jnp.abs(gf - gr).max())
        den = float(jnp.abs(gr).max()) + 1e-9
        assert err / den < 5e-3, (name, err, den)


def test_conv3x3_stats_identity_prologue():
    x, mu, inv, g, b, w = _inputs()
    y_k, s_k, ss_k = fc.conv3x3_stats(x, w)
    y_r, s_r, ss_r = _reference(x, mu, inv, g, b, w, False, False)
    np.testing.assert_allclose(y_k, y_r, rtol=1e-4, atol=3e-4)
    np.testing.assert_allclose(s_k, s_r, rtol=2e-4, atol=1e-2)


def _tiny(fused_block, fused_conv3, dtype=jnp.float32):
    return ResNet([1, 1], BottleneckBlock, num_classes=10, width=16,
                  dtype=dtype, fused_block=fused_block,
                  fused_conv3=fused_conv3)


@pytest.mark.slow
def test_model_forward_and_grads_match_unfused():
    """ResNet(fused_conv3) vs the classic path, shared weights: forward,
    batch-stats updates, and parameter gradients. The [1,1] net has a
    stride-1 stage (kernel path) and a stride-2 stage (XLA fallback)."""
    model_u = _tiny(False, False)
    model_f = _tiny(True, True)
    x = jax.random.normal(jax.random.key(0), (4, 32, 32, 3))
    variables = model_u.init(jax.random.key(1), x, train=True)

    yu, su = model_u.apply(variables, x, train=True, mutable=["batch_stats"])
    yf, sf = model_f.apply(variables, x, train=True, mutable=["batch_stats"])
    np.testing.assert_allclose(yf, yu, rtol=2e-4, atol=3e-4)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(sf),
            jax.tree_util.tree_leaves_with_path(su)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=3e-4,
                                   err_msg=jax.tree_util.keystr(pa))

    def loss(model, params):
        y = model.apply({"params": params,
                         "batch_stats": variables["batch_stats"]},
                        x, train=True, mutable=["batch_stats"])[0]
        return (y.astype(jnp.float32) ** 2).mean()

    gu = jax.grad(lambda p: loss(model_u, p))(variables["params"])
    gf = jax.grad(lambda p: loss(model_f, p))(variables["params"])
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(gf),
            jax.tree_util.tree_leaves_with_path(gu)):
        den = float(jnp.abs(b).max()) + 1e-9
        err = float(jnp.abs(a - b).max())
        assert err / den < 5e-3, (jax.tree_util.keystr(pa), err, den)


@pytest.mark.usefixtures("devices8")
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.slow
def test_fused_conv3_dp_step_matches_unfused(dtype):
    """Two DP train steps over the 8-device mesh: fused_conv3 on/off give
    the same loss trajectory. This is the shard_map/check_vma jnp-twin
    path — bf16 is parametrized because the twin's conv VJP once broke
    only there (mixed-dtype conv transpose, caught by the A/B tool)."""
    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop

    losses = {}
    for fused in (False, True):
        cfg = TrainConfig(
            model="resnet26_thin", global_batch_size=32, dtype=dtype,
            log_every=10**9, fused_block=fused, fused_conv3=fused,
            parallel=ParallelConfig(data=8),
            data=DataConfig(synthetic=True, image_size=32, num_classes=10,
                            synthetic_learnable=True))
        mesh, model, batch_shd, state, train_step, _, rng = loop.build(cfg, 2)
        src = datalib.make_source(cfg, "image", batch_shd)
        out = []
        for i in range(2):
            state, metrics = train_step(state, src.batch(i), rng)
            out.append(float(metrics["loss"]))
        losses[fused] = out
    tol = 1e-5 if dtype == "float32" else 5e-2
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=tol, atol=tol)


@pytest.mark.core
def test_fused_conv3_requires_fused_block():
    x = jnp.zeros((1, 32, 32, 3))
    with pytest.raises(ValueError, match="fused_conv3"):
        _tiny(False, True).init(jax.random.key(0), x, train=True)
