"""Rendezvous membership units: topology-aware survivor selection, the
epoch/barrier protocol in observability/health.py, the geometry-aware
ElasticController, the host_join/host_drain fault kinds, the serve
AutoscalePolicy, and the elastic_reconfig storm anomaly.

Everything here is fast and jax-free (the launcher side must never import
jax); the end-to-end drain/re-form/restore behavior lives in the slow
cross-axis soak in tests/test_elastic_resume.py. The whole module carries
the elastic marker — tools/marker_audit.py --expect-elastic requires a
"survivor"-named elastic test in every tier-1 selection.
"""

import json
import os

import pytest

from distributeddeeplearning_tpu import hostmesh, launch
from distributeddeeplearning_tpu.observability import anomaly
from distributeddeeplearning_tpu.observability import flight as flightlib
from distributeddeeplearning_tpu.observability import health
from distributeddeeplearning_tpu.robustness import faults

pytestmark = pytest.mark.elastic


# ---------------------------------------------------------------------------
# Topology-aware survivor selection (hostmesh.select_survivors)
# ---------------------------------------------------------------------------

def test_survivor_selection_grid_deterministic_and_contiguous():
    """Across a grid of (ring size, live subset, target k): the choice is
    deterministic, partitions the candidates, and — whenever every host is
    still alive — lands on one unbroken ICI arc."""
    for n in (4, 8):
        full = list(range(n))
        subsets = [full] + [
            [h for h in full if h != dead] for dead in (0, n // 2, n - 1)
        ] + [[h for h in full if h % 2 == 0]]
        for alive in subsets:
            for k in range(1, len(alive) + 1):
                first = hostmesh.select_survivors(alive, k, n)
                again = hostmesh.select_survivors(list(reversed(alive)), k, n)
                assert first == again, (n, alive, k)
                survivors, rejected = first
                assert len(survivors) == k
                assert survivors == sorted(survivors)
                assert rejected == sorted(rejected)
                assert sorted(survivors + rejected) == sorted(alive)
                if alive == full:
                    assert hostmesh.is_contiguous_arc(survivors, n), \
                        (n, k, survivors)


def test_survivor_selection_pinned_cases():
    # Full ring: smallest start offset wins the tie -> the low arc.
    assert hostmesh.select_survivors([0, 1, 2, 3], 2, 4) == ([0, 1], [2, 3])
    # Host 0 gone: the contiguous pair among the survivors wins.
    assert hostmesh.select_survivors([0, 2, 3], 2, 4) == ([2, 3], [0])
    # Host 3 gone: arc {1,2} beats the bisected {0,2}.
    assert hostmesh.select_survivors([1, 2, 3], 2, 4) == ([1, 2], [3])
    # k >= live: everyone survives, nothing rejected.
    assert hostmesh.select_survivors([1, 3], 2, 4) == ([1, 3], [])
    assert hostmesh.select_survivors([1, 3], 5, 4) == ([1, 3], [])
    # k <= 0: degenerate, everyone rejected.
    assert hostmesh.select_survivors([0, 1], 0, 4) == ([], [0, 1])


def test_survivor_selection_wraps_around_the_ring():
    # The best arc crosses the 0 boundary: {3, 0} on a 4-ring.
    survivors, rejected = hostmesh.select_survivors([0, 1, 3], 2, 4)
    assert (survivors, rejected) == ([0, 1], [3])  # tie -> smallest start
    survivors, rejected = hostmesh.select_survivors([0, 3, 5], 2, 6)
    assert (survivors, rejected) == ([0, 5], [3])  # arc {5,0} wraps
    assert hostmesh.is_contiguous_arc([0, 5], 6)


# ---------------------------------------------------------------------------
# health.py: epoch namespace + reform barrier + membership markers
# ---------------------------------------------------------------------------

def test_heartbeat_path_epoch_namespace(tmp_path):
    d = str(tmp_path)
    legacy = os.path.join(d, "heartbeat.3")
    assert health.heartbeat_path(d, 3) == legacy
    assert health.heartbeat_path(d, 3, epoch=0) == legacy
    assert health.heartbeat_path(d, 3, epoch=None) == legacy
    assert health.heartbeat_path(d, 3, epoch=2) == \
        os.path.join(d, "heartbeat.e2.3")


def test_reform_barrier_roundtrip(tmp_path):
    d = str(tmp_path)
    assert health.read_reform(d) is None
    health.request_reform(d, epoch=2, trigger="host_drain", save=True)
    barrier = health.read_reform(d)
    assert barrier["epoch"] == 2 and barrier["trigger"] == "host_drain"
    assert barrier["save"] is True
    # A re-formed child must ignore the barrier that formed it (<= epoch).
    assert health.read_reform(d, newer_than_epoch=2) is None
    assert health.read_reform(d, newer_than_epoch=3) is None
    assert health.read_reform(d, newer_than_epoch=1)["epoch"] == 2
    health.clear_reform(d)
    assert health.read_reform(d) is None
    health.clear_reform(d)  # idempotent on an absent barrier


def test_join_marker_carries_its_kind(tmp_path):
    d = str(tmp_path)
    assert health.consume_join(d) is None
    health.announce_join(d)
    assert health.consume_join(d) == "host_join"
    assert health.consume_join(d) is None  # consumed exactly once
    health.announce_rejoin(d)
    assert health.consume_join(d) == "host_rejoin"
    # The legacy boolean spelling still consumes either kind.
    health.announce_join(d)
    assert health.consume_rejoin(d) is True
    assert health.consume_rejoin(d) is False


def test_drain_markers_roundtrip(tmp_path, monkeypatch):
    d = str(tmp_path)
    assert health.consume_drains(d) == []
    health.announce_drain(d, host=2)
    health.announce_drain(d, host=0)
    assert health.consume_drains(d) == [0, 2]
    assert health.consume_drains(d) == []
    # Default host identity: DDL_ELASTIC_HOST (the ORIGINAL id) wins over
    # DDL_PROCESS_ID (the slot of the current attempt).
    monkeypatch.setenv("DDL_PROCESS_ID", "1")
    monkeypatch.setenv(health.ENV_ELASTIC_HOST, "5")
    health.announce_drain(d)
    assert health.consume_drains(d) == [5]
    monkeypatch.delenv(health.ENV_ELASTIC_HOST)
    health.announce_drain(d)
    assert health.consume_drains(d) == [1]


def test_poll_drain_filters_own_epoch(tmp_path, monkeypatch):
    monkeypatch.delenv(health.ENV_HEARTBEAT_DIR, raising=False)
    assert health.poll_drain() is None  # unarmed outside a launcher
    d = str(tmp_path)
    monkeypatch.setenv(health.ENV_HEARTBEAT_DIR, d)
    assert health.poll_drain() is None  # no barrier yet
    health.request_reform(d, epoch=1, trigger="host_join", save=True)
    monkeypatch.setenv(health.ENV_ELASTIC_EPOCH, "1")
    assert health.poll_drain() is None  # the barrier that formed us
    monkeypatch.setenv(health.ENV_ELASTIC_EPOCH, "0")
    assert health.poll_drain()["trigger"] == "host_join"


def test_heartbeat_writer_and_staleness_are_epoch_scoped(tmp_path,
                                                         monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv(health.ENV_HEARTBEAT_DIR, d)
    monkeypatch.setenv("DDL_PROCESS_ID", "1")
    monkeypatch.setenv(health.ENV_ELASTIC_EPOCH, "3")
    writer = health.HeartbeatWriter.from_env()
    assert writer.path == os.path.join(d, "heartbeat.e3.1")
    writer.beat(step=7)
    old = 1_000_000.0
    os.utime(writer.path, (old, old))
    # The epoch-3 watchdog sees the stale beat; the legacy namespace and
    # other epochs see nothing — a frozen file from a previous epoch can
    # never trip the new epoch's staleness clock.
    now = old + 100.0
    assert [pid for pid, _ in
            health.check_stale(d, 2, 30.0, now=now, epoch=3)] == [1]
    assert health.check_stale(d, 2, 30.0, now=now) == []
    assert health.check_stale(d, 2, 30.0, now=now, epoch=2) == []


# ---------------------------------------------------------------------------
# ElasticController: geometry table, epoch bump, topology-aware shrink
# ---------------------------------------------------------------------------

def test_controller_geometry_rewrites_the_full_mesh_shape(tmp_path):
    hb = str(tmp_path)
    base = ["python", "train.py", "--dp", "4", "--pp", "2",
            "--optimizer-sharding", "zero2"]
    ctl = launch.ElasticController(
        2, hb, base_dp=4,
        geometry={1: {"dp": 1, "pp": 4, "sharding": "none"}})
    # Whole pod: no geometry entry for 2 hosts -> dp-only default.
    assert ctl.degree == 4
    assert ctl.command(base) == base
    # Planned leave of host 0 -> 1 live host -> the geometry row applies:
    # the re-formation crosses the pipeline AND ZeRO-stage axes.
    health.announce_drain(hb, host=0)
    assert ctl.poll_membership() == "host_drain"
    assert ctl.has_pending and ctl.pending_trigger == "host_drain"
    assert ctl.degree == 1
    cmd = ctl.command(base)
    assert cmd[cmd.index("--dp") + 1] == "1"
    assert cmd[cmd.index("--pp") + 1] == "4"
    assert cmd[cmd.index("--optimizer-sharding") + 1] == "none"


def test_controller_epoch_bump_and_child_env(tmp_path):
    hb = str(tmp_path)
    ctl = launch.ElasticController(2, hb, base_dp=4)
    health.announce_drain(hb, host=0)
    assert ctl.poll_membership() == "host_drain"
    event = ctl.take_reconfiguration()
    assert event["trigger"] == "host_drain"
    assert (event["degree_before"], event["degree_after"]) == (4, 2)
    assert event["save"] is True          # every member alive -> collective
    assert event["epoch"] == 1 and ctl.epoch == 1
    env = ctl.child_env({})
    assert list(env) == [0]               # one surviving slot
    assert env[0][health.ENV_ELASTIC_EPOCH] == "1"
    assert env[0][health.ENV_ELASTIC_HOST] == "1"  # original identity
    exported = json.loads(env[0][health.ENV_ELASTIC_EVENT])
    assert exported["epoch"] == 1 and exported["trigger"] == "host_drain"
    # The event tags exactly one attempt; the next spawn is event-free.
    assert health.ENV_ELASTIC_EVENT not in ctl.child_env({})[0]


def test_controller_drain_respects_min_hosts_floor(tmp_path, capsys):
    hb = str(tmp_path)
    ctl = launch.ElasticController(2, hb, base_dp=4, min_hosts=2)
    health.announce_drain(hb, host=1)
    assert ctl.poll_membership() is None
    assert not ctl.has_pending and ctl.live == [0, 1]
    assert "drain of host 1 ignored" in capsys.readouterr().err


def test_controller_host_lost_barrier_is_not_save_capable(tmp_path):
    hb = str(tmp_path)
    ctl = launch.ElasticController(2, hb, base_dp=4)
    # Slot 1 beat once, then its heartbeat vanished with the host.
    assert ctl.note_failure(1, -9, ever_beat=True) == "host_lost"
    event = ctl.take_reconfiguration()
    assert event["trigger"] == "host_lost"
    assert event["save"] is False  # a collective save would wedge
    assert event["epoch"] == 1


def test_controller_topology_shrink_records_survivor_selection(tmp_path):
    hb = str(tmp_path / "hb")
    os.makedirs(hb)
    flight_dir = str(tmp_path / "flight")
    try:
        flightlib.configure(flight_dir, run_id="r", host=0)
        # 4 hosts, but the geometry only knows shapes for 2 and 4: a
        # single drain forces a shrink to the largest feasible count, and
        # the survivor choice must keep the ICI ring contiguous.
        ctl = launch.ElasticController(
            4, hb, base_dp=8,
            geometry={2: {"dp": 4, "sharding": "none"}})
        health.announce_drain(hb, host=1)
        assert ctl.poll_membership() == "host_drain"
        assert ctl.live == [2, 3]  # the contiguous arc of {0, 2, 3}
        assert ctl.degree == 4     # geometry row for 2 hosts
        event = ctl.take_reconfiguration()
        assert (event["degree_before"], event["degree_after"]) == (8, 4)
        events, errors = flightlib.read_all(flight_dir)
        assert errors == []
        sel = [e for e in events if e["ev"] == "survivor_selection"]
        assert len(sel) == 1
        assert sel[0]["candidates"] == [0, 2, 3]
        assert sel[0]["chosen"] == [2, 3]
        assert sel[0]["rejected"] == [0]
        assert sel[0]["contiguous"] is True
    finally:
        flightlib.reset()


# ---------------------------------------------------------------------------
# host_join / host_drain fault kinds (robustness/faults.py)
# ---------------------------------------------------------------------------

def test_parse_plan_rendezvous_kinds():
    plan = faults.parse_plan("host_join@4,host_drain@6:a1")
    assert [(f.kind, f.step) for f in plan] == [
        ("host_join", 4), ("host_drain", 6)]
    assert plan[0].attempt == 0 and plan[1].attempt == 1
    with pytest.raises(ValueError):
        faults.parse_plan("host_join@0")


def test_injector_fires_join_and_drain_markers(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv(health.ENV_HEARTBEAT_DIR, d)
    monkeypatch.setenv(health.ENV_ELASTIC_HOST, "2")
    plan = faults.FaultPlan(faults.parse_plan("host_join@1,host_drain@2"))
    fire = faults.make_injector(plan, ckpt=None, checkpoint_dir=None)
    fire(1)
    assert health.consume_join(d) == "host_join"
    assert health.consume_drains(d) == []
    fire(2)
    assert health.consume_drains(d) == [2]  # original host identity
    assert health.consume_join(d) is None
    # Without a heartbeat dir both kinds degrade to a loud no-op.
    monkeypatch.delenv(health.ENV_HEARTBEAT_DIR)
    fire(1)
    fire(2)


# ---------------------------------------------------------------------------
# Serve autoscale policy (launch.AutoscalePolicy)
# ---------------------------------------------------------------------------

def test_autoscale_ctor_validates_band():
    with pytest.raises(ValueError):
        launch.AutoscalePolicy(0, 2)
    with pytest.raises(ValueError):
        launch.AutoscalePolicy(3, 2)


def test_autoscale_up_needs_sustained_backlog():
    p = launch.AutoscalePolicy(1, 3, up_backlog_per_replica=2.0,
                               up_sustain_polls=3)
    # A two-poll burst is absorbed; the streak resets on the quiet poll.
    assert p.decide(queue_depth=9, live_replicas=1) == 0
    assert p.decide(queue_depth=9, live_replicas=1) == 0
    assert p.decide(queue_depth=1, live_replicas=1) == 0
    assert p.decide(queue_depth=9, live_replicas=1) == 0
    assert p.decide(queue_depth=9, live_replicas=1) == 0
    assert p.decide(queue_depth=9, live_replicas=1) == 1
    # The decision zeroed the streak: the next event is a full window away.
    assert p.decide(queue_depth=9, live_replicas=2) == 0
    assert p.decide(queue_depth=9, live_replicas=2) == 0
    assert p.decide(queue_depth=9, live_replicas=2) == 1


def test_autoscale_threshold_scales_with_live_replicas():
    p = launch.AutoscalePolicy(1, 4, up_backlog_per_replica=2.0,
                               up_sustain_polls=1)
    # 5 open requests over 3 replicas is under 2.0/replica: healthy.
    assert p.decide(queue_depth=5, live_replicas=3) == 0
    assert p.decide(queue_depth=7, live_replicas=3) == 1


def test_autoscale_clamps_to_band():
    p = launch.AutoscalePolicy(1, 2, up_sustain_polls=1, down_idle_polls=2)
    assert p.decide(queue_depth=99, live_replicas=2) == 0  # at max
    assert p.decide(queue_depth=0, live_replicas=1) == 0
    assert p.decide(queue_depth=0, live_replicas=1) == 0   # at min
    # The idle streak keeps counting while clamped at min, so the drain
    # fires the moment capacity rises above the floor again.
    assert p.decide(queue_depth=0, live_replicas=2) == -1


def test_autoscale_down_needs_sustained_idle():
    p = launch.AutoscalePolicy(1, 3, down_idle_polls=3)
    assert p.decide(queue_depth=0, live_replicas=2) == 0
    assert p.decide(queue_depth=0, live_replicas=2) == 0
    assert p.decide(queue_depth=1, live_replicas=2) == 0  # traffic resets
    assert p.decide(queue_depth=0, live_replicas=2) == 0
    assert p.decide(queue_depth=0, live_replicas=2) == 0
    assert p.decide(queue_depth=0, live_replicas=2) == -1


# ---------------------------------------------------------------------------
# elastic_reconfig storm anomaly (observability/anomaly.py)
# ---------------------------------------------------------------------------

def test_elastic_storm_fires_only_on_churn():
    det = anomaly.AnomalyDetector()
    # Three planned re-formations inside the window: normal, stays quiet.
    assert det.update_elastic(0.0, epoch=1) == []
    assert det.update_elastic(100.0, epoch=2) == []
    assert det.update_elastic(200.0, epoch=3) == []
    out = det.update_elastic(300.0, epoch=4)  # 4th inside 600 s: flapping
    assert len(out) == 1 and out[0]["kind"] == "elastic_reconfig"
    assert out[0]["step"] == 4 and out[0]["value"] == 4.0
    assert "flapping" in out[0]["detail"]


def test_elastic_storm_stays_quiet_when_spaced_out():
    det = anomaly.AnomalyDetector()
    for i, t in enumerate((0.0, 700.0, 1400.0, 2100.0, 2800.0)):
        assert det.update_elastic(t, epoch=i + 1) == []
    assert det.update_elastic(None) == []  # malformed clock: ignored
