"""FusedBottleneckBlock vs the classic BottleneckBlock with shared weights.

The fused path (models/fused_block.py over ops/fused_linear_bn.py) must be
variable-compatible with the unfused ResNet — same param/batch_stats tree —
and numerically equivalent to bf16 rounding in forward, gradients, and the
running-statistics update. A small bottleneck ResNet keeps interpret-mode
kernel runtime tractable on CPU.
"""

import flax
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.resnet import (
    BottleneckBlock, ResNet)

jax.config.update("jax_platforms", "cpu")


def _tiny(fused_block, dtype=jnp.float32):
    return ResNet([1, 1], BottleneckBlock, num_classes=10, width=16,
                  dtype=dtype, fused_block=fused_block)


@pytest.fixture(scope="module")
def shared():
    model = _tiny(False)
    x = jax.random.normal(jax.random.key(0), (4, 32, 32, 3))
    variables = model.init(jax.random.key(1), x, train=True)
    return x, variables


@pytest.mark.core
def test_variable_trees_identical(shared):
    x, variables = shared
    vf = _tiny(True).init(jax.random.key(1), x, train=True)
    paths = lambda tree: {jax.tree_util.keystr(p)
                          for p, _ in jax.tree_util.tree_leaves_with_path(tree)}
    assert paths(vf) == paths(variables)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(vf),
            jax.tree_util.tree_leaves_with_path(variables)):
        assert a.shape == b.shape and a.dtype == b.dtype, pa


def test_forward_and_stats_match(shared):
    x, variables = shared
    yu, su = _tiny(False).apply(variables, x, train=True,
                                mutable=["batch_stats"])
    yf, sf = _tiny(True).apply(variables, x, train=True,
                               mutable=["batch_stats"])
    np.testing.assert_allclose(yf, yu, rtol=2e-4, atol=2e-4)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(sf),
            jax.tree_util.tree_leaves_with_path(su)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4,
                                   err_msg=jax.tree_util.keystr(pa))


@pytest.mark.slow
def test_gradients_match(shared):
    x, variables = shared
    labels = jnp.arange(4) % 10

    def loss(params, fused):
        logits, _ = _tiny(fused).apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        onehot = jax.nn.one_hot(labels, 10)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))

    gu = jax.grad(loss)(variables["params"], False)
    gf = jax.grad(loss)(variables["params"], True)
    flat_u = flax.traverse_util.flatten_dict(gu)
    flat_f = flax.traverse_util.flatten_dict(gf)
    assert flat_u.keys() == flat_f.keys()
    for k in flat_u:
        np.testing.assert_allclose(
            flat_f[k], flat_u[k], rtol=5e-3, atol=5e-4,
            err_msg="/".join(k))


def test_eval_path_matches(shared):
    x, variables = shared
    yu = _tiny(False).apply(variables, x, train=False)
    yf = _tiny(True).apply(variables, x, train=False)
    np.testing.assert_allclose(yf, yu, rtol=2e-4, atol=2e-4)


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_fused_block_dp_step_matches_unfused():
    """Two DP train steps over the 8-device mesh: fused_block on/off give
    the same loss trajectory (the shard_map/check_vma jnp-twin path)."""
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.train import loop

    losses = {}
    for fused in (False, True):
        cfg = TrainConfig(
            model="resnet26_thin", global_batch_size=32, dtype="float32",
            log_every=10**9, fused_block=fused,
            parallel=ParallelConfig(data=8),
            data=DataConfig(synthetic=True, image_size=32, num_classes=10,
                            synthetic_learnable=True))
        mesh, model, batch_shd, state, train_step, _, rng = loop.build(cfg, 2)
        src = datalib.make_source(cfg, "image", batch_shd)
        out = []
        for i in range(2):
            state, metrics = train_step(state, src.batch(i), rng)
            out.append(float(metrics["loss"]))
        losses[fused] = out
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-5, atol=1e-5)


def test_odd_spatial_size_matches_unfused():
    """Stride-2 stages on odd spatial dims: conv2 emits ceil(h/2) rows, so
    the fused path must not assume floor division (regression)."""
    x = jax.random.normal(jax.random.key(5), (2, 25, 25, 3))
    variables = _tiny(False).init(jax.random.key(1), x, train=True)
    yu, _ = _tiny(False).apply(variables, x, train=True,
                               mutable=["batch_stats"])
    yf, _ = _tiny(True).apply(variables, x, train=True,
                              mutable=["batch_stats"])
    np.testing.assert_allclose(yf, yu, rtol=2e-4, atol=2e-4)


def test_basic_block_rejects_fused_block():
    model = ResNet([1, 1], __import__(
        "distributeddeeplearning_tpu.models.resnet",
        fromlist=["BasicBlock"]).BasicBlock, num_classes=10, width=16,
        fused_block=True)
    x = jnp.zeros((2, 32, 32, 3))
    with pytest.raises(ValueError, match="bottleneck"):
        model.init(jax.random.key(0), x, train=True)
