"""Bucketed (fused) gradient all-reduce — parallel/collectives.py.

Two invariant families (docs/fused_allreduce.md):

- The bucket planner is a pure function of (path, shape, dtype): stable
  under container insertion-order churn, size-capped, and degenerate to
  per-leaf at ``bucket_bytes<=0``.
- Bucketing changes how many collectives launch, never which values are
  summed: the fused reduce must match the per-leaf psum reference on the
  8-fake-device harness — exactly at fp32 tolerance, and within the
  documented tolerance for the bf16 payload policy.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu import compat
from distributeddeeplearning_tpu.config import AllReduceConfig, ParallelConfig
from distributeddeeplearning_tpu.parallel import collectives
from distributeddeeplearning_tpu.parallel.mesh import make_mesh

AXES = ("data", "fsdp")


def leaf_specs():
    """A gradient-tree shape zoo: many small leaves plus one large one."""
    return {
        "conv1": {"kernel": (3, 3, 3, 8), "bias": (8,)},
        "bn1": {"scale": (8,), "offset": (8,)},
        "dense": {"kernel": (256, 128), "bias": (128,)},
        "head": {"kernel": (128, 1000)},
    }


def struct_tree(dtype=jnp.float32):
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s, dtype), leaf_specs(),
        is_leaf=lambda x: isinstance(x, tuple))


def value_tree(seed=0, dtype=jnp.float32):
    """Per-shard values, leading dim 8 = one distinct slice per device."""
    k = jax.random.key(seed)
    out = {}
    for mod, leaves in leaf_specs().items():
        out[mod] = {}
        for name, shape in leaves.items():
            k, sub = jax.random.split(k)
            out[mod][name] = jax.random.normal(sub, (8,) + shape, dtype)
    return out


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------


def test_plan_is_stable_under_leaf_reordering():
    """Same leaves, different dict insertion order => identical assignment
    (keyed by sorted path, the determinism contract chip runs rely on)."""
    tree = struct_tree()
    reordered = {mod: dict(reversed(list(leaves.items())))
                 for mod, leaves in reversed(list(tree.items()))}
    cap = 64 * 1024  # small enough to force several buckets
    a = collectives.plan_buckets(tree, cap)
    b = collectives.plan_buckets(reordered, cap)
    assert len(a.buckets) == len(b.buckets) > 1
    for path in a.paths:
        assert a.bucket_of(path) == b.bucket_of(path), path
    # And the payload order within buckets is identical too.
    assert tuple(tuple(a.paths[i] for i in m) for m in a.buckets) == \
        tuple(tuple(b.paths[i] for i in m) for m in b.buckets)


def test_plan_respects_size_cap_and_isolates_oversized_leaves():
    cap = 64 * 1024
    plan = collectives.plan_buckets(struct_tree(), cap)
    for members in plan.buckets:
        nbytes = sum(
            collectives._numel(plan.shapes[i]) * plan.dtypes[i].itemsize
            for i in members)
        # A bucket may exceed the cap only when a single leaf alone does.
        assert nbytes <= cap or len(members) == 1
    # The 128x1000 fp32 head (500 KB > 64 KB) must sit alone.
    head = plan.bucket_of("['head']['kernel']")
    assert len(plan.buckets[head]) == 1


def test_plan_zero_bytes_degenerates_to_per_leaf():
    plan = collectives.plan_buckets(struct_tree(), 0)
    assert len(plan.buckets) == plan.num_leaves
    assert all(len(m) == 1 for m in plan.buckets)


def test_plan_covers_every_leaf_exactly_once():
    plan = collectives.plan_buckets(struct_tree(), 32 * 1024)
    seen = sorted(i for m in plan.buckets for i in m)
    assert seen == list(range(plan.num_leaves))


# ---------------------------------------------------------------------------
# Numeric parity on 8 fake devices
# ---------------------------------------------------------------------------


def reduce_on_mesh(tree, devices8, **kw):
    """Run all_reduce under shard_map: each device holds slice [d] of every
    leaf; the reduce must return the cross-device sum, replicated."""
    mesh = make_mesh(ParallelConfig(data=8))

    def f(local):
        local = jax.tree_util.tree_map(lambda x: x[0], local)
        return collectives.all_reduce(local, AXES, axis_size=8, **kw)

    fn = compat.shard_map(f, mesh=mesh, in_specs=P(AXES), out_specs=P())
    return jax.device_get(jax.jit(fn)(tree))


def reference_sum(tree):
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float64).sum(axis=0), tree)


@pytest.mark.core
def test_fused_matches_perleaf_fp32(devices8):
    """Bucketed fp32 reduce == per-leaf reduce == direct sum, at fp32
    tolerance (the acceptance criterion for the tensor-fusion change)."""
    tree = value_tree()
    ref = reference_sum(tree)
    fused = reduce_on_mesh(tree, devices8, bucket_bytes=64 * 1024)
    perleaf = reduce_on_mesh(tree, devices8, bucket_bytes=0)
    for f, p, r in zip(jax.tree_util.tree_leaves(fused),
                       jax.tree_util.tree_leaves(perleaf),
                       jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(f, r, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(p, r, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(f, p, rtol=1e-6, atol=0)


def test_single_default_bucket_matches(devices8):
    """The whole tree fits one 4 MB default bucket — the common CNN case."""
    tree = value_tree(seed=1)
    ref = reference_sum(tree)
    out = reduce_on_mesh(tree, devices8)  # default bucket_bytes
    for o, r in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(o, r, rtol=1e-6, atol=1e-6)


def test_bf16_payload_within_documented_tolerance(devices8):
    """bf16 wire compression: result restored to fp32, within the 8-bit-
    mantissa tolerance documented in docs/fused_allreduce.md."""
    tree = value_tree(seed=2)
    ref = reference_sum(tree)
    out = reduce_on_mesh(tree, devices8, bucket_bytes=64 * 1024,
                         payload_dtype=jnp.bfloat16)
    for o, r in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        assert o.dtype == np.float32  # fp32 master restored
        # rtol covers the 8-bit-mantissa rounding of each payload; atol
        # covers cancellation — a near-zero SUM of eight O(1) bf16 terms
        # keeps the absolute error of its largest term.
        np.testing.assert_allclose(o, r, rtol=2e-2, atol=5e-2)


def test_ring_algorithm_matches_psum(devices8):
    """psum_scatter+all_gather (with odd-size padding) == plain psum."""
    tree = value_tree(seed=3)
    ref = reference_sum(tree)
    # 64 KB buckets make several payloads whose element counts are not
    # multiples of 8, exercising the pad/strip path.
    out = reduce_on_mesh(tree, devices8, bucket_bytes=64 * 1024,
                         algorithm="ring")
    for o, r in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(o, r, rtol=1e-6, atol=1e-6)


def test_all_reduce_gradients_reads_options(devices8):
    """The train-step entry point honors AllReduceConfig and rejects
    unsupported payload dtypes at trace time."""
    tree = value_tree(seed=4)
    ref = reference_sum(tree)
    mesh = make_mesh(ParallelConfig(data=8))
    opts = AllReduceConfig(bucket_mb=0.0625, dtype="float32",
                           algorithm="psum")

    def f(local):
        local = jax.tree_util.tree_map(lambda x: x[0], local)
        return collectives.all_reduce_gradients(local, AXES, axis_size=8,
                                                options=opts)

    fn = jax.jit(compat.shard_map(f, mesh=mesh, in_specs=P(AXES),
                                  out_specs=P()))
    out = jax.device_get(fn(tree))
    for o, r in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(o, r, rtol=1e-6, atol=1e-6)

    with pytest.raises(ValueError, match="not supported"):
        collectives.all_reduce_gradients(
            jax.tree_util.tree_map(lambda x: x[0], tree), AXES, axis_size=8,
            options=AllReduceConfig(dtype="float16"))


def test_plan_mismatch_raises():
    tree = struct_tree()
    plan = collectives.plan_buckets(tree, 0)
    smaller = {"conv1": tree["conv1"]}
    with pytest.raises(ValueError, match="leaves"):
        collectives.all_reduce(
            jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype),
                                   smaller),
            AXES, axis_size=1, plan=plan)


# ---------------------------------------------------------------------------
# CLI round-trip (train.py)
# ---------------------------------------------------------------------------


def test_train_cli_roundtrip_allreduce_flags():
    import train

    cfg = train.build_config(train.parse_args(
        ["--allreduce-bucket-mb", "8", "--allreduce-dtype", "bfloat16",
         "--allreduce-algo", "ring"]))
    assert cfg.allreduce.bucket_mb == 8.0
    assert cfg.allreduce.dtype == "bfloat16"
    assert cfg.allreduce.algorithm == "ring"
    assert "fused" in cfg.allreduce.describe()

    # Defaults untouched when no flag is passed.
    base = train.build_config(train.parse_args([]))
    assert base.allreduce == AllReduceConfig()
    assert base.allreduce.bucket_mb == collectives.DEFAULT_BUCKET_MB

    # 0 selects the per-leaf reference path; negatives are rejected.
    perleaf = train.build_config(train.parse_args(
        ["--allreduce-bucket-mb", "0"]))
    assert perleaf.allreduce.bucket_mb == 0.0
    assert "per-leaf" in perleaf.allreduce.describe()
    with pytest.raises(SystemExit):
        train.build_config(train.parse_args(["--allreduce-bucket-mb", "-1"]))


def test_allreduce_config_is_replace_safe():
    """bench.py builds AllReduceConfig via dataclasses.replace — keep it a
    plain frozen-compatible dataclass."""
    cfg = AllReduceConfig()
    new = dataclasses.replace(cfg, bucket_mb=0.0)
    assert new.bucket_mb == 0.0 and cfg.bucket_mb == collectives.DEFAULT_BUCKET_MB
