"""Flight recorder + metrics registry + anomaly detector + post-mortem.

Unit tier: recorder identity/durability semantics, sidecar helper,
metrics aggregation/export, anomaly detection bounds (flag an injected
spike fast, zero false positives on a clean soak), incident chains on a
synthetic record.

Acceptance tier (tier-1, ``flight``-marked): SIGKILL a child mid-run via
the chaos harness and assert the flight record survives complete and
parseable, with ``tools/postmortem.py`` producing a correctly-attributed
incident timeline.

Integration tier (slow): in-process loop runs with fault plans writing
real flight records.
"""

import io
import json
import os
import subprocess
import sys
import time
from types import SimpleNamespace

import pytest

from distributeddeeplearning_tpu.observability import anomaly, flight
from distributeddeeplearning_tpu.observability import metrics as metricslib
from distributeddeeplearning_tpu.observability import sidecars

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import postmortem  # noqa: E402


# ---------------------------------------------------------------------------
# Recorder
# ---------------------------------------------------------------------------

def test_recorder_identity_and_sequence(tmp_path):
    rec = flight.FlightRecorder(str(tmp_path), run_id="run-x", host=1,
                                attempt=2)
    rec.record("run_start", step=0, degree=4)
    rec.record("step", step=10, loss=2.5)
    rec.close()
    events, err = flight.read_file(flight.flight_path(str(tmp_path), 1))
    assert err is None
    assert [(e["ev"], e["run"], e["attempt"], e["host"], e["seq"])
            for e in events] == [("run_start", "run-x", 2, 1, 1),
                                 ("step", "run-x", 2, 1, 2)]
    assert events[1]["loss"] == 2.5
    assert events[0]["t"] > 0 and events[0]["mono"] > 0


def test_disabled_recorder_is_noop(tmp_path):
    rec = flight.FlightRecorder(None)
    assert not rec.enabled
    rec.record("anything", step=1)  # must not raise or write
    rec.close()


def test_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv(flight.ENV_FLIGHT_DIR, str(tmp_path))
    monkeypatch.setenv(flight.ENV_RUN_ID, "run-env")
    monkeypatch.setenv("DDL_PROCESS_ID", "3")
    monkeypatch.setenv("DDL_RESTART_ATTEMPT", "2")
    rec = flight.FlightRecorder.from_env()
    assert rec.enabled and rec.run_id == "run-env"
    assert rec.host == 3 and rec.attempt == 2
    assert rec.path.endswith("flight.p3.jsonl")
    rec.close()


def test_torn_tail_is_salvaged_and_reported(tmp_path):
    rec = flight.FlightRecorder(str(tmp_path), run_id="r", host=0)
    rec.record("run_start", step=0)
    rec.record("step", step=1)
    rec.close()
    path = flight.flight_path(str(tmp_path), 0)
    with open(path, "a") as fh:  # a writer killed mid-line
        fh.write('{"ev": "step", "t": 123.0, "loss')
    events, errors = flight.read_all(str(tmp_path))
    assert [e["ev"] for e in events] == ["run_start", "step"]
    assert len(errors) == 1 and "unparseable" in errors[0]


def test_rotation_bounds_the_file_and_keeps_recent_window(tmp_path):
    rec = flight.FlightRecorder(str(tmp_path), run_id="r", host=0,
                                max_bytes=400, fsync=False)
    for i in range(20):
        rec.record("step", step=i)
    rec.close()
    assert os.path.exists(rec.path + ".1")
    # the live segment re-opens lazily after a rotation; whatever exists
    # stays bounded near max_bytes
    if os.path.exists(rec.path):
        assert os.path.getsize(rec.path) < 800
    assert os.path.getsize(rec.path + ".1") < 800
    events, errors = flight.read_all(str(tmp_path))
    assert errors == []
    # the most recent window is intact even though old lines rolled off
    assert events[-1]["step"] == 19


def test_singleton_configure_and_reset(tmp_path):
    try:
        rec = flight.configure(str(tmp_path), run_id="r", host=0)
        assert flight.get() is rec
        flight.get().record("launch", num_processes=2)
        events, _ = flight.read_all(str(tmp_path))
        assert events[0]["ev"] == "launch"
    finally:
        flight.reset()
    assert not flight.get().enabled


def test_mint_run_id_is_sortable_and_distinct():
    a, b = flight.mint_run_id(1000.0), flight.mint_run_id(1000.0)
    assert a.startswith("run-") and a != b


def test_describe_is_one_human_line():
    line = flight.describe({"ev": "fault", "t": 0.0, "host": 2,
                            "attempt": 1, "kind": "sigkill", "step": 4})
    assert "[a1 h2] fault" in line
    assert "kind=sigkill" in line and "step=4" in line


def test_last_incident_is_scoped_to_the_latest_run(tmp_path):
    rec = flight.FlightRecorder(str(tmp_path), run_id="run-old", host=0)
    rec.record("fault", kind="sigkill", step=4)
    rec.close()
    assert flight.last_incident(str(tmp_path))["kind"] == "sigkill"
    time.sleep(0.01)
    rec = flight.FlightRecorder(str(tmp_path), run_id="run-new", host=0)
    rec.record("run_start", step=0)
    rec.record("run_end", step=6)
    rec.close()
    # the clean newest run has no incident; the old run's fault must not
    # leak into "what killed the LAST run?"
    assert flight.last_incident(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# Sidecar helper
# ---------------------------------------------------------------------------

def test_sidecar_roundtrip_with_envelope(tmp_path):
    path = str(tmp_path / "side.json")
    assert sidecars.write(path, {"trigger": "host_lost", "resume_step": 4}) \
        == path
    rec = sidecars.read(path)
    assert rec["trigger"] == "host_lost" and rec["resume_step"] == 4
    assert rec["schema"] == sidecars.SCHEMA_VERSION
    assert isinstance(rec["written_at"], float)
    assert sidecars.age_s(rec, now=rec["written_at"] + 7.5) == 7.5


def test_sidecar_read_tolerates_absent_and_malformed(tmp_path):
    assert sidecars.read(str(tmp_path / "missing.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    assert sidecars.read(str(bad)) is None
    notdict = tmp_path / "list.json"
    notdict.write_text("[1, 2]")
    assert sidecars.read(str(notdict)) is None
    assert sidecars.age_s(None) is None
    assert sidecars.age_s({"written_at": "yesterday"}) is None


def test_sidecar_bare_names_resolve_into_repo_cache():
    path = sidecars.path_for("last_elastic_event")
    assert path.endswith(os.path.join(".cache", "last_elastic_event.json"))
    # explicit paths pass through untouched
    assert sidecars.path_for("/x/y.json") == "/x/y.json"


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

def test_metrics_aggregate_across_hosts():
    reg = metricslib.MetricsRegistry(run_id="r")
    reg.observe("step_time_ms", 100.0, step=1, host=0)
    reg.observe("step_time_ms", 140.0, step=1, host=1)
    reg.observe("step_time_ms", float("nan"), step=1, host=2)  # dropped
    agg = reg.aggregate()
    m = agg["metrics"]["step_time_ms"]
    assert m["min"] == 100.0 and m["max"] == 140.0 and m["mean"] == 120.0
    assert m["per_host"] == {"0": 100.0, "1": 140.0}
    assert reg.hosts() == [0, 1]


def test_metrics_observe_many_skips_step_key():
    reg = metricslib.MetricsRegistry()
    reg.observe_many({"step": 5, "loss": 2.0, "note": "text"}, host=0)
    agg = reg.aggregate()["metrics"]
    assert set(agg) == {"loss"}
    assert agg["loss"]["series_tail"] == [[5, 2.0]] or \
        agg["loss"]["series_tail"] == [(5, 2.0)]


def test_metrics_prometheus_text_format(tmp_path):
    reg = metricslib.MetricsRegistry(run_id="run-p")
    reg.observe("examples/sec", 1234.5, step=2, host=0)
    text = reg.prometheus_text()
    assert "# TYPE ddl_examples_sec gauge" in text
    assert 'ddl_examples_sec{run="run-p",host="0"} 1234.5' in text
    out = reg.write_prometheus(str(tmp_path / "m.prom"))
    assert out and open(out).read() == text
    snap_path = reg.write_snapshot(str(tmp_path / "snap.json"))
    snap = json.load(open(snap_path))
    assert snap["run"] == "run-p" and "examples/sec" in snap["metrics"]


# ---------------------------------------------------------------------------
# Anomaly detector: flag fast, stay quiet on clean runs
# ---------------------------------------------------------------------------

def _clean_signal(i):
    """A deterministic healthy run: drifting loss with noise, ~5% jitter
    on throughput and grad norms, mild straggler skew."""
    wobble = 0.1 * ((i * 2654435761) % 97 / 97.0 - 0.5)
    return dict(loss=2.5 - 0.01 * i + wobble,
                grad_norm=1.0 + 0.5 * wobble,
                examples_per_sec=1000.0 * (1 + 0.5 * wobble),
                data_wait_frac=0.05,
                straggler_ratio=1.05,
                bad_step=0.0)


def test_clean_soak_produces_zero_anomalies():
    det = anomaly.AnomalyDetector()
    flagged = []
    for i in range(200):
        flagged += det.update(i, **_clean_signal(i))
    assert flagged == []


def test_loss_spike_flagged_within_five_cadences():
    det = anomaly.AnomalyDetector()
    for i in range(10):
        det.update(i, loss=2.0 + 0.01 * (i % 3))
    sig = _clean_signal(10)
    sig["loss"] = 9.0  # diverged
    cadences = 0
    flagged = []
    while not flagged and cadences < 5:
        cadences += 1
        flagged = det.update(10 + cadences, **sig)
    assert cadences <= 5 and flagged
    assert flagged[0]["kind"] == "loss_spike"
    assert flagged[0]["step"] == 10 + cadences


def test_nonfinite_loss_and_grad_flag_immediately():
    det = anomaly.AnomalyDetector()
    out = det.update(1, loss=float("nan"), grad_norm=float("inf"))
    assert sorted(a["kind"] for a in out) == ["grad_norm_nonfinite",
                                             "loss_nonfinite"]


def test_grad_norm_drift_both_directions():
    det = anomaly.AnomalyDetector()
    for i in range(6):
        det.update(i, grad_norm=1.0)
    up = det.update(6, grad_norm=50.0)
    assert [a["kind"] for a in up] == ["grad_norm_drift"]
    det2 = anomaly.AnomalyDetector()
    for i in range(6):
        det2.update(i, grad_norm=1.0)
    down = det2.update(6, grad_norm=0.001)
    assert [a["kind"] for a in down] == ["grad_norm_drift"]


def test_throughput_collapse_vs_loader_stall():
    det = anomaly.AnomalyDetector()
    for i in range(6):
        det.update(i, examples_per_sec=1000.0, data_wait_frac=0.05)
    out = det.update(6, examples_per_sec=100.0, data_wait_frac=0.1)
    assert [a["kind"] for a in out] == ["throughput_collapse"]
    det2 = anomaly.AnomalyDetector()
    for i in range(6):
        det2.update(i, examples_per_sec=1000.0, data_wait_frac=0.05)
    out = det2.update(6, examples_per_sec=100.0, data_wait_frac=0.9)
    assert [a["kind"] for a in out] == ["loader_stall"]
    assert "waiting on data" in out[0]["detail"]


def test_straggler_needs_patience_then_resets():
    det = anomaly.AnomalyDetector(straggler_patience=3)
    assert det.update(1, straggler_ratio=2.0) == []
    assert det.update(2, straggler_ratio=2.0) == []
    out = det.update(3, straggler_ratio=2.0)
    assert [a["kind"] for a in out] == ["straggler_trending"]
    # streak resets after the emit AND on a healthy interval
    assert det.update(4, straggler_ratio=2.0) == []
    assert det.update(5, straggler_ratio=1.0) == []
    assert det.update(6, straggler_ratio=2.0) == []


def test_report_fans_out_to_all_consumers(tmp_path):
    rec = flight.FlightRecorder(str(tmp_path), run_id="r", host=0)
    tele_calls, guard_feeds = [], []
    tele = SimpleNamespace(instant=lambda name, **kw:
                           tele_calls.append(name))
    tracker = SimpleNamespace(note_anomaly=lambda:
                              guard_feeds.append(1))
    out = io.StringIO()
    anomaly.report(
        [{"kind": "loss_nonfinite", "step": 7, "value": None,
          "baseline": None, "detail": "loss=nan"},
         {"kind": "bad_step", "step": 7, "value": 1.0, "baseline": 0.0,
          "detail": "guard tripped"}],
        flight_rec=rec, tele=tele, bad_tracker=tracker, stream=out)
    rec.close()
    events, _ = flight.read_all(str(tmp_path))
    assert [e["kind"] for e in events] == ["loss_nonfinite", "bad_step"]
    assert tele_calls == ["anomaly:loss_nonfinite", "anomaly:bad_step"]
    # bad_step must NOT feed the guard: push() already counted the
    # compiled flag; only the non-finite kinds count extra.
    assert len(guard_feeds) == 1
    assert "# anomaly: loss_nonfinite at step 7" in out.getvalue()


def test_injected_loader_stall_flags_through_production_injection(
        tmp_path, monkeypatch):
    """Satellite: a ``loader_stall`` fault plan, injected through the SAME
    wrapper production host-streaming loaders use (_stalling_iterator via
    the resolved plan), must surface as a flagged flight-recorder event."""
    from distributeddeeplearning_tpu.data import imagenet
    from distributeddeeplearning_tpu.robustness import faults

    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    plan = faults.resolve(SimpleNamespace(fault_plan="loader_stall@6:0.3s",
                                          fail_at_step=None))
    stalls = plan.loader_stalls()
    assert stalls == {6: 0.3}
    it = imagenet._stalling_iterator(iter([{"x": i} for i in range(10)]),
                                     stalls, 1)
    rec = flight.FlightRecorder(str(tmp_path), run_id="r", host=0)
    det = anomaly.AnomalyDetector()
    flagged = []
    for step in range(1, 9):
        t0 = time.perf_counter()
        next(it)
        wait = time.perf_counter() - t0
        interval = wait + 0.01  # 10 ms of simulated compute per step
        out = det.update(step, examples_per_sec=8.0 / interval,
                         data_wait_frac=wait / interval)
        anomaly.report(out, flight_rec=rec, stream=io.StringIO())
        flagged += out
    rec.close()
    assert [a["kind"] for a in flagged] == ["loader_stall"]
    assert flagged[0]["step"] == 6
    events, _ = flight.read_all(str(tmp_path))
    assert [e["ev"] for e in events] == ["anomaly"]
    assert events[0]["kind"] == "loader_stall" and events[0]["step"] == 6


# ---------------------------------------------------------------------------
# Post-mortem on a synthetic record
# ---------------------------------------------------------------------------

def test_postmortem_attributes_a_synthetic_elastic_incident(tmp_path):
    d = str(tmp_path)
    launcher = flight.FlightRecorder(d, run_id="run-s", host="launcher")
    h0 = flight.FlightRecorder(d, run_id="run-s", host=0)
    launcher.record("launch", num_processes=4, elastic=True)
    h0.record("run_start", step=0, degree=4)
    h0.record("step", step=400, loss=2.1)
    launcher.record("fault", kind="host_lost", step=412)
    launcher.record("child_exit", child=2, rc=1, attribution="host_lost")
    launcher.record("reconfiguration_planned", trigger="host_lost",
                    degree_before=4, degree_after=2)
    launcher.record("restart", attempt=1, restart=1, backoff_s=0.2)
    h1 = flight.FlightRecorder(d, run_id="run-s", host=0, attempt=1)
    h1.record("restore", step=400)
    h1.record("reconfiguration", step=400, trigger="host_lost",
              degree_before=4, degree_after=2,
              reconfiguration_time_s=15.0, resume_step=400)
    h1.record("run_end", step=500, bad_steps=0)
    launcher.record("job_end", rc=0)
    for r in (launcher, h0, h1):
        r.close()

    report = postmortem.build_report(d)
    assert report["complete"] and report["run"] == "run-s"
    chain = " → ".join(report["incident"])
    assert "host_lost" in chain
    assert "attributed as host_lost" in chain
    assert "re-formed 4→2 in 15.0 s" in chain
    assert "resumed from step 400" in chain
    assert "run completed at step 500" in chain
    # dense step events stay out of the timeline except as milestones
    assert any(e["ev"] == "step" for e in report["timeline"])
    assert report["last_step"] == 400


def test_postmortem_exits_cleanly_without_a_record(tmp_path):
    rc = postmortem.main([str(tmp_path / "nothing")])
    assert rc == 1


# ---------------------------------------------------------------------------
# ACCEPTANCE (tier-1): SIGKILL mid-run -> complete record + attribution
# ---------------------------------------------------------------------------

def _train_cmd(ckpt, steps, extra=()):
    return [sys.executable, "train.py", "--backend", "cpu", "--model",
            "resnet18_thin", "--image-size", "32", "--batch-size", "8",
            "--dp", "1", "--synthetic", "--dtype", "float32", "--steps",
            str(steps), "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
            "--log-every", "1000000", *extra]


def _clean_env():
    drop = ("PALLAS_AXON_POOL_IPS", "DDL_FAULT_PLAN", "DDL_RESTART_ATTEMPT",
            flight.ENV_FLIGHT_DIR, flight.ENV_RUN_ID)
    return {k: v for k, v in os.environ.items() if k not in drop}


@pytest.mark.flight
def test_sigkill_leaves_complete_record_and_attributed_postmortem(tmp_path):
    """The PR's acceptance bar: SIGKILL a child mid-run (chaos harness),
    then assert (a) the flight record parses whole — the fsync'd fault
    event written moments before the kill survived — and (b) one command
    turns it into a correctly-attributed incident timeline."""
    ckpt = str(tmp_path / "ckpt")
    fdir = str(tmp_path / "flight")
    env = _clean_env()
    proc = subprocess.run(
        [sys.executable, "launch.py", "--num-processes", "1",
         "--max-restarts", "2", "--backoff", "0.2",
         "--heartbeat-timeout", "120", "--flight-dir", fdir, "--"]
        + _train_cmd(ckpt, 6, ("--fault-plan", "sigkill@4")),
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]

    events, errors = flight.read_all(fdir)
    assert errors == [], errors  # complete + parseable despite SIGKILL
    assert len({e["run"] for e in events}) == 1  # one identity end to end
    fault = next(e for e in events if e["ev"] == "fault")
    assert fault["kind"] == "sigkill" and fault["step"] == 4
    exit_ = next(e for e in events if e["ev"] == "child_exit")
    assert exit_["rc"] == -9 and exit_["attribution"] == "crash"
    assert any(e["ev"] == "restart" for e in events)
    restore = next(e for e in events if e["ev"] == "restore")
    assert restore["step"] >= 2 and restore["attempt"] == 1
    assert next(e for e in events if e["ev"] == "run_end")["step"] == 6
    assert next(e for e in events if e["ev"] == "job_end")["rc"] == 0
    # the metrics pipeline exported its aggregate next to the record
    assert os.path.exists(os.path.join(fdir, "metrics_snapshot.json"))

    pm = subprocess.run(
        [sys.executable, "tools/postmortem.py", fdir,
         "--checkpoint-dir", ckpt, "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert pm.returncode == 0, pm.stderr[-2000:]
    report = json.loads(pm.stdout)
    assert report["complete"] is True
    chain = " → ".join(report["incident"])
    assert "sigkill" in chain and "step 4" in chain
    assert "attributed as crash" in chain
    assert "resumed from step" in chain
    assert "run completed at step 6" in chain


# ---------------------------------------------------------------------------
# Integration (slow): in-process loop runs writing real flight records
# ---------------------------------------------------------------------------

def _loop_cfg(**kw):
    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)

    base = dict(
        model="resnet18_thin", global_batch_size=16, dtype="float32",
        log_every=1,
        parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=32, num_classes=10),
        optimizer=OptimizerConfig(schedule="constant"))
    base.update(kw)
    return TrainConfig(**base)


@pytest.mark.slow
@pytest.mark.flight
def test_nan_grads_run_writes_flagged_flight_event(tmp_path):
    """Satellite: an injected ``nan_grads`` plan must leave a flagged
    anomaly event in the flight record (the compiled guard's bad_step
    flag, observed on the log cadence, reported through anomaly.report)."""
    from distributeddeeplearning_tpu.train import loop

    fdir = str(tmp_path / "flight")
    try:
        summary = loop.run(_loop_cfg(fault_plan="nan_grads@3",
                                     flight_dir=fdir), total_steps=5)
    finally:
        flight.reset()
    assert summary["bad_steps"] == 1
    events, errors = flight.read_all(fdir)
    assert errors == []
    flagged = [e for e in events if e["ev"] == "anomaly"]
    assert any(e["kind"] == "bad_step" and e["step"] == 3 for e in flagged)
    assert next(e for e in events if e["ev"] == "run_end")["step"] == 5


@pytest.mark.slow
@pytest.mark.flight
def test_fault_free_run_writes_zero_anomaly_events(tmp_path):
    """Satellite: the detector's zero-false-positive bar, end to end — a
    clean soak on the real loop (log cadence 1, detector on) must leave
    no anomaly events in the flight record."""
    from distributeddeeplearning_tpu.train import loop

    fdir = str(tmp_path / "flight")
    try:
        summary = loop.run(_loop_cfg(flight_dir=fdir), total_steps=8)
    finally:
        flight.reset()
    assert summary["final_step"] == 8
    events, errors = flight.read_all(fdir)
    assert errors == []
    assert [e for e in events if e["ev"] == "anomaly"] == []
    assert [e["ev"] for e in events if e["ev"] in
            ("run_start", "run_end")] == ["run_start", "run_end"]
