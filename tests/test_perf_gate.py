"""CPU-proxy perf regression gate (observability/perf_gate.py).

Two layers: compare() band logic pinned on synthetic measurements (every
violation class, every forgiveness rule), and the LIVE gate — the
perf_gate-marked tier-1 tests that measure the real proxy workload
against the checked-in perf_baselines.json and prove the gate flips both
ways (passes clean, fails under an injected slowdown). The live tests
are the enforcement point ISSUE 6 puts in tier-1; tools/marker_audit.py
--expect-perf-gate verifies they actually ran."""

import json

import pytest

from distributeddeeplearning_tpu.observability import perf_gate


def _base(**kw):
    b = {
        "schema_version": 1,
        "step_time_ms": 50.0,
        "calib_unit_ms": 5.0,
        "normalized_step": 10.0,
        "phase_share": {"dispatch": 0.90, "data_wait": 0.07,
                        "fetch_barrier": 0.03},
        "tolerance": {"step_hi": 3.0, "share_abs": 0.25},
    }
    b.update(kw)
    return b


def _cur(step_ms=55.0, norm=11.0, shares=None):
    return {
        "step_time_ms": step_ms,
        "normalized_step": norm,
        "phase_share": shares or {"dispatch": 0.89, "data_wait": 0.08,
                                  "fetch_barrier": 0.03},
    }


def test_compare_passes_within_band():
    assert perf_gate.compare(_base(), _cur()) == []


def test_compare_no_baseline_is_a_violation():
    v = perf_gate.compare(None, _cur())
    assert len(v) == 1 and "no baseline" in v[0]
    assert "recalibrate" in v[0]


def test_compare_flags_step_time_regression():
    v = perf_gate.compare(_base(), _cur(step_ms=400.0, norm=80.0))
    assert any("step-time regression" in s for s in v)


def test_compare_forgives_one_sided_inflation():
    """The dual-ratio rule: a loaded box can inflate RAW step time while
    the calibration unit inflates alongside (normalized stays sane), and
    a slow box inflates the normalized-free raw view — only BOTH ratios
    past the band is a regression."""
    # Raw 8x but normalized 1.2x: machine got slower, not the code.
    assert perf_gate.compare(_base(), _cur(step_ms=400.0, norm=12.0)) == []
    # Normalized 8x but raw 1.2x: calibration caught a load spike.
    assert perf_gate.compare(_base(), _cur(step_ms=60.0, norm=80.0)) == []


def test_compare_flags_phase_mix_shift():
    """data_wait exploding from 7% to 60% of the step is a pipeline
    regression even when total step time hides inside the band."""
    v = perf_gate.compare(_base(), _cur(
        shares={"dispatch": 0.37, "data_wait": 0.60, "fetch_barrier": 0.03}))
    assert len(v) == 1 and "phase-mix regression" in v[0]
    assert "data_wait" in v[0]


def test_compare_new_phase_counts_from_zero_share():
    v = perf_gate.compare(_base(), _cur(
        shares={"dispatch": 0.60, "surprise_sync": 0.40}))
    assert any("surprise_sync" in s for s in v)


def test_compare_tolerances_come_from_baseline_file():
    """Loosening/tightening the band is a reviewed perf_baselines.json
    diff, not a test-local constant."""
    tight = _base(tolerance={"step_hi": 1.05, "share_abs": 0.25})
    assert perf_gate.compare(tight, _cur(step_ms=60.0, norm=12.0))
    loose = _base(tolerance={"step_hi": 50.0, "share_abs": 0.9})
    assert perf_gate.compare(
        loose, _cur(step_ms=2000.0, norm=450.0,
                    shares={"data_wait": 0.8, "dispatch": 0.2})) == []


def test_checked_in_baseline_is_valid():
    """perf_baselines.json ships in the repo and must stay loadable and
    complete — the live gate is only as real as this file."""
    baseline = perf_gate.load_baseline()
    assert baseline is not None, (
        f"missing/corrupt {perf_gate.BASELINE_PATH}; regenerate with "
        f"`python tools/perf_gate.py --recalibrate`")
    assert baseline["normalized_step"] > 0
    assert baseline["step_time_ms"] > 0
    assert 0.99 < sum(baseline["phase_share"].values()) < 1.01
    assert set(baseline["tolerance"]) >= {"step_hi", "share_abs"}
    assert baseline["workload"]["model"] == perf_gate.WORKLOAD["model"]


# --- the live gate ----------------------------------------------------------

@pytest.fixture(scope="module")
def runner():
    """ONE compiled proxy program for all live tests: the injected-
    slowdown remeasure then costs steps, not a recompile."""
    return perf_gate.ProxyRunner()


@pytest.mark.perf_gate
def test_gate_passes_on_current_build(runner, monkeypatch, tmp_path):
    """THE tier-1 perf gate: the current build's proxy measurement must
    sit inside the checked-in band. If this fails because performance
    intentionally changed, rerun `python tools/perf_gate.py --recalibrate`
    and commit the new perf_baselines.json in the same PR."""
    monkeypatch.setattr(perf_gate, "LAST_RESULT_PATH",
                        str(tmp_path / "last.json"))
    result = perf_gate.check(runner=runner)
    assert result["ok"], "\n".join(result["violations"])
    cur = result["current"]
    assert cur["step_time_ms"] > 0 and cur["calib_unit_ms"] > 0
    # The sidecar doctor.py reads was written and round-trips.
    with open(tmp_path / "last.json") as fh:
        assert json.load(fh)["ok"] is True


@pytest.mark.perf_gate
def test_gate_fails_under_injected_slowdown(runner, monkeypatch, tmp_path):
    """The self-test proving the gate is armed: a deliberate sleep inside
    the traced data_wait phase must trip BOTH checks — step time out of
    band and the data_wait share exploding. A gate that cannot fail is
    decoration."""
    monkeypatch.setattr(perf_gate, "LAST_RESULT_PATH",
                        str(tmp_path / "last.json"))
    baseline = perf_gate.load_baseline()
    slow = runner.measure(inject_sleep_s=0.25)
    violations = perf_gate.compare(baseline, slow)
    assert any("step-time regression" in v for v in violations), violations
    assert any("phase-mix regression" in v and "data_wait" in v
               for v in violations), violations
    # And through the same entry point the gate test above uses — but a
    # deliberately-slowed pass must never overwrite the doctor sidecar.
    result = perf_gate.check(runner=runner, inject_sleep_s=0.25)
    assert not result["ok"]
    assert not (tmp_path / "last.json").exists()


def test_recalibrate_writes_usable_baseline(runner, tmp_path, monkeypatch):
    out = tmp_path / "baselines.json"
    baseline = perf_gate.recalibrate(str(out), runner=runner, passes=1)
    on_disk = perf_gate.load_baseline(str(out))
    assert on_disk["normalized_step"] == baseline["normalized_step"]
    assert on_disk["tolerance"] == perf_gate.DEFAULT_TOLERANCE
    # A build gated against its own fresh recalibration passes.
    cur = runner.measure()
    assert perf_gate.compare(on_disk, cur) == [], (on_disk, cur)


# --- the zero2_overlap extras workload --------------------------------------

def test_load_baseline_extras_routing(tmp_path):
    """Named workloads read their entry under "extras"; the default reads
    the top level; an absent entry is None (-> "no baseline" violation)."""
    path = tmp_path / "b.json"
    path.write_text(json.dumps(
        {**_base(), "extras": {"zero2_overlap": _base(normalized_step=33.0)}}))
    assert perf_gate.load_baseline(str(path))["normalized_step"] == 10.0
    extra = perf_gate.load_baseline(str(path), name="zero2_overlap")
    assert extra["normalized_step"] == 33.0
    assert perf_gate.load_baseline(str(path), name="missing") is None


def test_recalibrate_default_preserves_extras(runner, tmp_path):
    """Recalibrating the headline workload must not drop the extras block
    — otherwise every default recalibration silently disarms the
    zero2_overlap gate."""
    out = tmp_path / "b.json"
    out.write_text(json.dumps(
        {**_base(), "extras": {"zero2_overlap": _base(normalized_step=33.0)}}))
    perf_gate.recalibrate(str(out), runner=runner, passes=1)
    kept = perf_gate.load_baseline(str(out), name="zero2_overlap")
    assert kept is not None and kept["normalized_step"] == 33.0
    # And an extras recalibration into a missing file is refused loudly.
    with pytest.raises(ValueError, match="default workload first"):
        perf_gate.recalibrate(str(tmp_path / "absent.json"), runner=runner,
                              passes=1, workload="zero2_overlap")


@pytest.fixture(scope="module")
def runner_zero2():
    """ONE compiled zero2_overlap proxy (dp=2 CPU mesh, overlapped
    ZeRO-2 schedule) shared by the sharded gate tests."""
    return perf_gate.ProxyRunner(perf_gate.WORKLOADS["zero2_overlap"])


# --- the serve_decode extras workload ---------------------------------------

@pytest.fixture(scope="module")
def runner_serve():
    """ONE warmed serve engine (tiny paged-KV config) shared by the
    serve-decode gate tests."""
    return perf_gate.ServeProxyRunner()


@pytest.mark.perf_gate
@pytest.mark.serve
def test_perf_gate_live_serve_decode(runner_serve, monkeypatch, tmp_path):
    """The serve-engine gate: one continuous-batching decode step (all
    slots live) must sit inside its extras baseline band — a retrace,
    accidental pool copy, or host-loop bloat in serve/engine.py fails
    tier-1 here. Recalibrate with
    `python tools/perf_gate.py --recalibrate --workload serve_decode`."""
    monkeypatch.setattr(perf_gate, "LAST_RESULT_PATH",
                        str(tmp_path / "last.json"))
    result = perf_gate.check(runner=runner_serve, workload="serve_decode")
    assert result["ok"], "\n".join(result["violations"])
    assert result["workload_name"] == "serve_decode"
    assert result["current"]["workload"]["kind"] == "serve_decode"
    # A serve-workload check must never overwrite the headline sidecar.
    assert not (tmp_path / "last.json").exists()


@pytest.mark.perf_gate
@pytest.mark.serve
def test_serve_decode_gate_flips_on_injected_stall(runner_serve):
    """The armed-gate self-test for the serve workload: a deliberate host
    stall between decode steps must trip step time out of band AND the
    host_stall phase share."""
    baseline = perf_gate.load_baseline(name="serve_decode")
    slow = runner_serve.measure(inject_sleep_s=0.2)
    violations = perf_gate.compare(baseline, slow)
    assert any("step-time regression" in v for v in violations), violations
    assert any("phase-mix regression" in v and "host_stall" in v
               for v in violations), violations


def test_serve_decode_workload_is_registered():
    """The CLI's --workload choices come from WORKLOADS; losing the entry
    silently removes the serve gate from tools/perf_gate.py."""
    w = perf_gate.WORKLOADS["serve_decode"]
    assert w["kind"] == "serve_decode"
    assert w["max_slots"] >= 2  # a 1-slot proxy would not batch at all
    # And its baseline ships in perf_baselines.json (extras entry).
    assert perf_gate.load_baseline(name="serve_decode") is not None


@pytest.mark.perf_gate
def test_perf_gate_live_zero2_overlap(runner_zero2, monkeypatch, tmp_path):
    """The sharded-schedule gate: the overlapped ZeRO-2 proxy must sit
    inside its extras baseline band, and a sharded-workload check must
    never overwrite the headline doctor sidecar. Recalibrate with
    `python tools/perf_gate.py --recalibrate --workload zero2_overlap`."""
    monkeypatch.setattr(perf_gate, "LAST_RESULT_PATH",
                        str(tmp_path / "last.json"))
    result = perf_gate.check(runner=runner_zero2, workload="zero2_overlap")
    assert result["ok"], "\n".join(result["violations"])
    assert result["workload_name"] == "zero2_overlap"
    assert result["current"]["workload"]["optimizer_sharding"] == "zero2"
    assert not (tmp_path / "last.json").exists()


# --- the pipeline_1f1b extras workload --------------------------------------

@pytest.fixture(scope="module")
def runner_pipeline():
    """ONE compiled pipeline_1f1b proxy (bert_tiny_pp4 on a pipeline=2
    CPU sub-mesh, 1f1b schedule, V=2) shared by the pipeline gate
    tests."""
    return perf_gate.ProxyRunner(perf_gate.WORKLOADS["pipeline_1f1b"])


@pytest.mark.perf_gate
@pytest.mark.pipeline
def test_perf_gate_live_pipeline_1f1b(runner_pipeline, monkeypatch,
                                      tmp_path):
    """The interleaved-schedule gate: the steady-state 1F1B step (tick
    loop, both shift forms, per-tick chunk selection, canonical->
    interleaved param re-layout) must sit inside its extras baseline
    band — a retrace in the tick loop or a chunk gather that stopped
    being a static slice fails tier-1 here instead of waiting for chip
    time. Recalibrate with
    `python tools/perf_gate.py --recalibrate --workload pipeline_1f1b`."""
    monkeypatch.setattr(perf_gate, "LAST_RESULT_PATH",
                        str(tmp_path / "last.json"))
    result = perf_gate.check(runner=runner_pipeline,
                             workload="pipeline_1f1b")
    assert result["ok"], "\n".join(result["violations"])
    assert result["workload_name"] == "pipeline_1f1b"
    assert result["current"]["workload"]["pipeline_schedule"] == "1f1b"
    # An extras-workload check never overwrites the headline sidecar.
    assert not (tmp_path / "last.json").exists()


@pytest.mark.perf_gate
@pytest.mark.pipeline
def test_pipeline_gate_flips_on_injected_stall(runner_pipeline):
    """The armed-gate self-test for the pipeline workload: a deliberate
    stall inside the traced data_wait phase must trip step time out of
    band AND the data_wait phase share."""
    baseline = perf_gate.load_baseline(name="pipeline_1f1b")
    slow = runner_pipeline.measure(inject_sleep_s=0.25)
    violations = perf_gate.compare(baseline, slow)
    assert any("step-time regression" in v for v in violations), violations
    assert any("phase-mix regression" in v and "data_wait" in v
               for v in violations), violations


def test_pipeline_workload_is_registered():
    """Losing the WORKLOADS entry (or its extras baseline) silently
    removes the pipeline gate from tools/perf_gate.py."""
    w = perf_gate.WORKLOADS["pipeline_1f1b"]
    assert w["pipeline_schedule"] == "1f1b"
    assert w["pipeline_virtual_stages"] > 1  # V=1 would gate plain gpipe
    assert w["pp"] > 1
    assert w["batch"] % perf_gate.WORKLOADS["pipeline_1f1b"]["pp"] == 0
    assert perf_gate.load_baseline(name="pipeline_1f1b") is not None


# --- the serve_prefix_prefill extras workload -------------------------------

@pytest.fixture(scope="module")
def runner_serve_prefix():
    """ONE warmed prefix-cache engine (radix tree primed with the shared
    head) shared by the prefix-prefill gate tests."""
    return perf_gate.ServeProxyRunner(
        perf_gate.WORKLOADS["serve_prefix_prefill"])


@pytest.mark.perf_gate
@pytest.mark.serve
def test_perf_gate_live_serve_prefix_prefill(runner_serve_prefix,
                                             monkeypatch, tmp_path):
    """The fast-path admission gate: one prefix-HIT admission (tree walk
    + shared-page mapping + suffix-only block prefill + retire) must sit
    inside its extras baseline band — a regression that silently turns
    hits into cold full prefills, or bloats the radix walk, fails tier-1
    here. Recalibrate with
    `python tools/perf_gate.py --recalibrate --workload
    serve_prefix_prefill`."""
    monkeypatch.setattr(perf_gate, "LAST_RESULT_PATH",
                        str(tmp_path / "last.json"))
    result = perf_gate.check(runner=runner_serve_prefix,
                             workload="serve_prefix_prefill")
    assert result["ok"], "\n".join(result["violations"])
    assert result["workload_name"] == "serve_prefix_prefill"
    assert result["current"]["workload"]["kind"] == "serve_prefix_prefill"
    # Every timed step actually hit the tree (the runner itself raises on
    # a mis-primed pass, so a passing check IS hit-path timing), and a
    # serve-workload check never overwrites the headline sidecar.
    assert result["current"]["phase_share"].get("prefix_admit", 0) > 0.5
    assert not (tmp_path / "last.json").exists()


@pytest.mark.perf_gate
@pytest.mark.serve
def test_serve_prefix_gate_flips_on_injected_stall(runner_serve_prefix):
    """The armed-gate self-test for the prefix workload: a deliberate
    host stall between admissions must trip step time out of band AND
    the host_stall phase share."""
    baseline = perf_gate.load_baseline(name="serve_prefix_prefill")
    slow = runner_serve_prefix.measure(inject_sleep_s=0.2)
    violations = perf_gate.compare(baseline, slow)
    assert any("step-time regression" in v for v in violations), violations
    assert any("phase-mix regression" in v and "host_stall" in v
               for v in violations), violations


def test_serve_prefix_prefill_workload_is_registered():
    """Losing the WORKLOADS entry (or its extras baseline) silently
    removes the fast-path gate from tools/perf_gate.py."""
    w = perf_gate.WORKLOADS["serve_prefix_prefill"]
    assert w["kind"] == "serve_prefix_prefill"
    assert w["prefix_cache"] is True
    # The shared head must span multiple full pages or the proxy times a
    # near-empty tree walk instead of real page mapping.
    assert w["shared_prefix_len"] >= 2 * w["page_size"]
    assert perf_gate.load_baseline(name="serve_prefix_prefill") is not None


# --- the largebatch_bf16 extras workload ------------------------------------

@pytest.fixture(scope="module")
def runner_largebatch():
    """ONE compiled largebatch_bf16 proxy (2x-batch mixed-precision LARS
    step: scale/unscale, overflow reduction, skip-select, scale
    automaton) shared by the large-batch gate tests."""
    return perf_gate.ProxyRunner(perf_gate.WORKLOADS["largebatch_bf16"])


@pytest.mark.perf_gate
def test_perf_gate_live_largebatch_bf16(runner_largebatch, monkeypatch,
                                        tmp_path):
    """The large-batch mixed-precision gate (ISSUE 20): the policy-armed
    step must sit inside its extras baseline band — a retrace, added
    sync, or host stall in the mixed path fails tier-1 here instead of
    waiting for chip time. Recalibrate with
    `python tools/perf_gate.py --recalibrate --workload largebatch_bf16`."""
    monkeypatch.setattr(perf_gate, "LAST_RESULT_PATH",
                        str(tmp_path / "last.json"))
    result = perf_gate.check(runner=runner_largebatch,
                             workload="largebatch_bf16")
    assert result["ok"], "\n".join(result["violations"])
    assert result["workload_name"] == "largebatch_bf16"
    assert result["current"]["workload"]["precision"] == "mixed"
    # An extras-workload check never overwrites the headline sidecar.
    assert not (tmp_path / "last.json").exists()


@pytest.mark.perf_gate
def test_largebatch_gate_flips_on_injected_stall(runner_largebatch):
    """The armed-gate self-test for the large-batch workload: a
    deliberate stall inside the traced data_wait phase must trip step
    time out of band AND the data_wait phase share — the zero-data-wait
    headroom this PR's input pipeline exists to protect."""
    baseline = perf_gate.load_baseline(name="largebatch_bf16")
    slow = runner_largebatch.measure(inject_sleep_s=0.25)
    violations = perf_gate.compare(baseline, slow)
    assert any("step-time regression" in v for v in violations), violations
    assert any("phase-mix regression" in v and "data_wait" in v
               for v in violations), violations


def test_largebatch_workload_is_registered():
    """Losing the WORKLOADS entry (or its extras baseline) silently
    removes the large-batch gate from tools/perf_gate.py."""
    w = perf_gate.WORKLOADS["largebatch_bf16"]
    assert w["precision"] == "mixed"
    assert w["optimizer"] == "lars"
    assert w["batch"] == 2 * perf_gate.WORKLOAD["batch"]
    assert perf_gate.load_baseline(name="largebatch_bf16") is not None
