"""Checkpoint/resume + eval tests (SURVEY.md §4 "Integration", §5.3-5.4).

The strong invariant: a run interrupted at step K and resumed reproduces the
uninterrupted run exactly, because (a) orbax restores the full
params/opt-state/BN/step pytree and (b) the synthetic source is a
deterministic function of (seed, step), so the resumed run replays the same
data stream.
"""

import jax
import jax.numpy as jnp
import pytest

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.train import loop
from distributeddeeplearning_tpu.utils.logging import MetricLogger


def tiny_cfg(**kw) -> TrainConfig:
    base = dict(
        model="resnet18", global_batch_size=8, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=2),
        data=DataConfig(synthetic=True, image_size=32, num_classes=10),
        # constant LR: the warmup/decay schedules are (intentionally)
        # functions of the run's total step budget, which differs between
        # the 3-step "interrupted" run and the 6-step reference here.
        optimizer=OptimizerConfig(schedule="constant", learning_rate=0.01))
    base.update(kw)
    return TrainConfig(**base)


def params_of(summary):
    return jax.device_get(summary["state"].params)


@pytest.fixture()
def quiet():
    return MetricLogger(enabled=False)


@pytest.mark.slow
def test_resume_matches_uninterrupted(tmp_path, quiet):
    ckpt = str(tmp_path / "ckpt")
    # Uninterrupted 6-step run.
    ref = loop.run(tiny_cfg(), total_steps=6, logger=quiet, return_state=True)
    # Interrupted: 3 steps (checkpointed), then fresh process-equivalent
    # resume to 6.
    cfg = tiny_cfg(checkpoint_dir=ckpt, checkpoint_every_steps=3)
    part1 = loop.run(cfg, total_steps=3, logger=quiet)
    assert part1["final_step"] == 3 and part1["start_step"] == 0
    part2 = loop.run(cfg, total_steps=6, logger=quiet, return_state=True)
    assert part2["start_step"] == 3

    a, b = params_of(ref), params_of(part2)
    jax.tree_util.tree_map(
        lambda x, y: None if jnp.allclose(x, y, atol=1e-6) else
        pytest.fail("resumed params diverge from uninterrupted run"), a, b)
    # Optimizer state (momentum) must also round-trip.
    assert int(jax.device_get(part2["state"].step)) == 6


@pytest.mark.slow
def test_restore_is_noop_when_complete(tmp_path, quiet):
    cfg = tiny_cfg(checkpoint_dir=str(tmp_path / "ckpt"),
                   checkpoint_every_steps=100)
    loop.run(cfg, total_steps=2, logger=quiet)  # final-save at 2
    again = loop.run(cfg, total_steps=2, logger=quiet)
    assert again["start_step"] == 2  # nothing re-trained


@pytest.mark.slow
def test_no_resume_flag(tmp_path, quiet):
    cfg = tiny_cfg(checkpoint_dir=str(tmp_path / "ckpt"))
    loop.run(cfg, total_steps=2, logger=quiet)
    fresh = loop.run(cfg.replace(resume=False), total_steps=2, logger=quiet)
    assert fresh["start_step"] == 0


@pytest.mark.slow
def test_eval_top1_aggregates_across_shards(quiet):
    summary = loop.run(tiny_cfg(parallel=ParallelConfig(data=4)),
                       total_steps=2, logger=quiet, eval_batches=2)
    assert 0.0 <= summary["eval_top1"] <= 1.0


def test_stream_meta_mismatch_fails_loudly(tmp_path):
    """A resume whose loader resolution changed must not silently feed a
    different sample stream (ADVICE r1 #1)."""
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    ckpt = Checkpointer(str(tmp_path / "ckpt"), every_steps=10)
    try:
        ckpt.verify_or_record_stream_meta({"loader": "native"})
        ckpt.verify_or_record_stream_meta({"loader": "native"})  # same: ok
        with pytest.raises(RuntimeError, match="native.*tf|tf.*native"):
            ckpt.verify_or_record_stream_meta({"loader": "tf"})
    finally:
        ckpt.close()


@pytest.mark.slow
def test_preemption_sigterm_saves_and_resumes(tmp_path):
    """SIGTERM mid-run (Cloud TPU preemption / launcher fail-whole grace
    window) triggers a synchronous save at the next step boundary and a
    nonzero exit; a restart resumes from that exact step."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time

    ckpt_dir = str(tmp_path / "ckpt")
    cmd = [sys.executable, "train.py", "--backend", "cpu",
           "--model", "resnet18", "--batch-size", "8", "--dp", "8",
           "--synthetic", "--dtype", "float32", "--steps", "2000",
           "--log-every", "1", "--checkpoint-dir", ckpt_dir,
           # cadence far beyond the run: only the preemption save writes
           "--checkpoint-every", "100000"]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Merged stream: blocking on stdout while stderr's pipe fills would
    # deadlock a warning-heavy child; one pipe can't.
    proc = subprocess.Popen(cmd, cwd=repo, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 300
        steps_seen = 0
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:  # child died before producing steps
                break
            if line.startswith("{\"step\""):
                steps_seen += 1
                if steps_seen >= 2:
                    break
        assert steps_seen >= 2, "subprocess produced no steps in time"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=240)
    finally:
        proc.kill()
    assert proc.returncode != 0
    assert "preempted" in out, out[-800:]

    # Restart with a tiny budget: it must resume from the preemption save
    # (start_step >= the 2 steps we watched complete), not from scratch.
    short = list(cmd)
    short[short.index("--steps") + 1] = "3"
    r = subprocess.run(short, cwd=repo, capture_output=True, text=True,
                       timeout=240)
    assert r.returncode == 0, r.stderr[-800:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])["summary"]
    assert summary["start_step"] >= 2, summary


@pytest.mark.core
@pytest.mark.slow
def test_preemption_resume_start_step(tmp_path, quiet):
    """In-process variant: a real SIGTERM delivered mid-run must trip the
    loop's preemption handler (SystemExit + synchronous save before any
    cadence save would fire), and the restart must resume from that step."""
    import os
    import signal
    import threading

    del threading
    cfg = tiny_cfg(checkpoint_dir=str(tmp_path / "ckpt"),
                   checkpoint_every_steps=100000,  # only preemption saves
                   log_every=1)

    class _KillOnFirstLog(MetricLogger):
        """Deliver SIGTERM from inside the loop's first log callback — the
        handler is guaranteed installed by then (a timer could fire during
        the pre-loop compile, where default SIGTERM would kill the process)."""

        def __init__(self):
            super().__init__(enabled=False)
            self.sent = False

        def log(self, *a, **kw):
            if not self.sent:
                self.sent = True
                os.kill(os.getpid(), signal.SIGTERM)

    with pytest.raises(SystemExit, match="preempted"):
        loop.run(cfg, total_steps=50, logger=_KillOnFirstLog())

    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    ck = Checkpointer.create(cfg)
    try:
        saved = ck.latest_step()
    finally:
        ck.close()
    assert saved is not None and saved >= 1
    resumed = loop.run(cfg, total_steps=saved + 1, logger=quiet)
    assert resumed["start_step"] == saved
    assert resumed["final_step"] == saved + 1


@pytest.mark.slow
def test_eval_only_restores_and_scores(tmp_path, quiet):
    """--eval-only semantics: total_steps=0 + resume restores the newest
    checkpoint and jumps straight to final held-out eval, training nothing."""
    cfg = tiny_cfg(checkpoint_dir=str(tmp_path / "ckpt"))
    loop.run(cfg, total_steps=3, logger=quiet)
    summary = loop.run(cfg, total_steps=0, logger=quiet, eval_batches=2)
    assert summary["start_step"] == 3
    assert summary["final_step"] == 3
    assert 0.0 <= summary["eval_top1"] <= 1.0


@pytest.mark.core
def test_restore_unwraps_boxes_but_not_value_named_params():
    # _restore_subtree must unwrap serialized sharding boxes ({'value': leaf}
    # where the model has a leaf) while leaving a genuine parameter NAMED
    # 'value' alone (ADVICE r2 #3) — the two shapes are identical in the raw
    # checkpoint and only the target tree disambiguates them.
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    ck = Checkpointer.__new__(Checkpointer)
    arr = jnp.arange(6.0).reshape(2, 3)
    # Case 1: a submodule whose single param is named 'value' (dict in the
    # target) — must survive round-trip un-unwrapped.
    like = {"head": {"value": arr}}
    raw = {"head": {"value": arr * 0 + 7.0}}
    out = ck._restore_subtree(raw, like, "params")
    assert set(out["head"]) == {"value"}
    assert float(out["head"]["value"][0, 1]) == 7.0
    # Case 2: a serialized box (leaf in the target) — must unwrap.
    like2 = {"w": arr}
    raw2 = {"w": {"value": arr * 0 + 3.0}}
    out2 = ck._restore_subtree(raw2, like2, "params")
    assert float(out2["w"][1, 2]) == 3.0
