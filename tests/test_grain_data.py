"""grain input pipeline (data/grain_pipeline.py): shapes/labels, label-pixel
pairing, determinism, slice-based resume, per-process sharding, loader
dispatch, and end-to-end training (SURVEY.md §4 "Integration")."""

from __future__ import annotations

import os

import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu import data as datalib
from distributeddeeplearning_tpu.config import (
    DataConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data import grain_pipeline
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.parallel import sharding as shardlib

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

NUM_CLASSES = 4
IMAGES_PER_CLASS = 8
IMG = 64


@pytest.fixture(scope="module")
def folder_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("imagenet_grain")
    rng = np.random.default_rng(7)
    for split in ("train", "val"):
        for label in range(NUM_CLASSES):
            d = os.path.join(root, split, f"n{label:08d}")
            os.makedirs(d)
            for i in range(IMAGES_PER_CLASS if split == "train" else 2):
                # Class-colored so labels are recoverable from pixels.
                arr = np.full((IMG, IMG, 3), 40 + 50 * label, np.uint8)
                arr += rng.integers(0, 10, arr.shape, dtype=np.uint8)
                Image.fromarray(arr).save(
                    os.path.join(d, f"img_{i}.JPEG"), quality=95)
    return str(root)


def _cfg(data_dir, batch=8, dp=2, **data_kw):
    return TrainConfig(
        model="resnet18", global_batch_size=batch, dtype="float32",
        parallel=ParallelConfig(data=dp),
        data=DataConfig(synthetic=False, data_dir=data_dir, loader="grain",
                        image_size=32, num_classes=NUM_CLASSES, **data_kw))


def _source(cfg, **kw):
    mesh = meshlib.make_mesh(cfg.parallel)
    return grain_pipeline.make_grain_source(
        cfg, shardlib.batch_sharding(mesh), **kw)


@pytest.mark.usefixtures("devices8")
def test_batches_shapes_and_labels(folder_dir):
    cfg = _cfg(folder_dir)
    src = _source(cfg, train=True)
    for step in range(3):
        b = src.batch(step)
        assert b["image"].shape == (8, 32, 32, 3)
        assert b["image"].dtype == np.float32
        assert b["label"].shape == (8,)
        labels = np.asarray(jax.device_get(b["label"]))
        assert ((0 <= labels) & (labels < NUM_CLASSES)).all()


@pytest.mark.usefixtures("devices8")
def test_labels_match_pixels(folder_dir):
    """Class-colored images: the decoded (de-normalized) pixel level must
    identify the label — catches decode/label pairing bugs."""
    from distributeddeeplearning_tpu.data.imagenet import MEAN_RGB, STDDEV_RGB

    cfg = _cfg(folder_dir, batch=8, dp=1)
    src = _source(cfg, train=False)
    b = src.batch(0)
    images = np.asarray(jax.device_get(b["image"]))
    labels = np.asarray(jax.device_get(b["label"]))
    raw = images * np.asarray(STDDEV_RGB, np.float32) + np.asarray(
        MEAN_RGB, np.float32)
    level = raw.mean(axis=(1, 2, 3))
    decoded = np.round((level - 45) / 50).astype(int)
    np.testing.assert_array_equal(np.clip(decoded, 0, NUM_CLASSES - 1),
                                  labels)


def _labels_stream(cfg, steps, **kw):
    src = _source(cfg, train=True, **kw)
    return [np.asarray(jax.device_get(src.batch(i)["label"]))
            for i in range(kw.get("start_step", 0), steps)]


@pytest.mark.usefixtures("devices8")
def test_deterministic_and_epochs_reshuffle(folder_dir):
    cfg = _cfg(folder_dir, batch=8, dp=1)
    a = _labels_stream(cfg, steps=8)
    b = _labels_stream(cfg, steps=8)
    # Same seed -> identical record stream.
    np.testing.assert_array_equal(np.stack(a), np.stack(b))
    # Epoch 2 (steps 4..8 over 32 train records / batch 8) is a different
    # permutation of the same label multiset as epoch 1.
    e1, e2 = np.stack(a[:4]).ravel(), np.stack(a[4:]).ravel()
    assert sorted(e1.tolist()) == sorted(e2.tolist())
    assert not np.array_equal(e1, e2)


@pytest.mark.usefixtures("devices8")
def test_resume_is_exact_slice(folder_dir):
    cfg = _cfg(folder_dir, batch=8, dp=1)
    full = _labels_stream(cfg, steps=6)
    resumed = _labels_stream(cfg, steps=6, start_step=3)
    np.testing.assert_array_equal(np.stack(full[3:]), np.stack(resumed))


@pytest.mark.usefixtures("devices8")
def test_resume_replays_augmentation_draws(folder_dir):
    # Stronger than record identity: the random crop/flip draws must also
    # match the uninterrupted run — augmentation RNG is keyed by global
    # stream index, not by position within the resumed slice (ADVICE r2 #2).
    cfg = _cfg(folder_dir, batch=8, dp=1)

    def images(start):
        src = _source(cfg, train=True, start_step=start)
        return [np.asarray(jax.device_get(src.batch(i)["image"]))
                for i in range(start, 6)]

    full, resumed = images(0), images(3)
    np.testing.assert_array_equal(np.stack(full[3:]), np.stack(resumed))


def test_process_sharding_disjoint(folder_dir):
    # One eval epoch, 2 processes: 8 val records -> one batch of 4 each;
    # interleaved index sharding must cover the split exactly once.
    cfg = _cfg(folder_dir, batch=8, dp=1)
    seen = []
    for pidx in range(2):
        ds = grain_pipeline.build_grain_dataset(
            cfg, train=False, process_index=pidx, process_count=2)
        seen.append(sum((b["label"].tolist() for b in ds), []))
    assert sorted(seen[0] + seen[1]) == sorted(
        [l for l in range(NUM_CLASSES) for _ in range(2)])


@pytest.mark.usefixtures("devices8")
def test_dispatcher_routes_grain(folder_dir):
    cfg = _cfg(folder_dir)
    assert datalib.resolve_loader(cfg, "image") == "grain"
    mesh = meshlib.make_mesh(cfg.parallel)
    src = datalib.make_source(cfg, "image",
                              shardlib.batch_sharding(mesh))
    assert src.batch(0)["image"].shape == (8, 32, 32, 3)


@pytest.mark.usefixtures("devices8")
def test_train_end_to_end_grain(folder_dir):
    from distributeddeeplearning_tpu.train import loop

    # batch 8 so the 8-record val split fills exactly one eval batch
    cfg = _cfg(folder_dir, batch=8, dp=8).replace(log_every=10**9)
    summary = loop.run(cfg, total_steps=3, eval_batches=1)
    assert summary["final_step"] == 3
    assert np.isfinite(summary["final_metrics"]["loss"])
    assert 0.0 <= summary["eval_top1"] <= 1.0
