"""Shared oracle + fixtures for the attention test suites (ring, flash):
one dense softmax(QK^T)V reference so both kernels validate against the
identical ground truth."""

import jax
import jax.numpy as jnp


def dense_reference(q, k, v, kv_mask=None, causal=False):
    """softmax(QK^T/sqrt(d))V with optional key-padding mask and causal
    triangle; (B,S,H,D) io. The ONE oracle for ring/flash/zigzag suites."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, -1e30)
    if causal:
        n = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((n, n), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def random_qkv(key, b=2, s=32, h=4, d=8, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))
