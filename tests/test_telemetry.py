"""Observability contracts: telemetry ring buffer + Chrome-trace export,
heartbeat health, the launcher's hang watchdog, the straggler aggregation,
and the MetricLogger hardening that rides this PR.

The fast tests exercise the stdlib layer directly (no jax backend); the
@slow tests run the real acceptance scenarios through launch.py + train.py
subprocesses (the same harness test_launch.py uses)."""

import json
import os
import subprocess
import sys
import time

import pytest

from distributeddeeplearning_tpu.observability import health, telemetry


# --- telemetry core --------------------------------------------------------


def test_span_nesting_and_ring_bound():
    tele = telemetry.Telemetry(enabled=True, max_events=8)
    with tele.span("outer", step=1):
        with tele.span("inner", step=1):
            pass
    events = tele.snapshot()
    # Inner exits (and records) first; both carry the step arg.
    assert [e["name"] for e in events] == ["inner", "outer"]
    assert all(e["args"]["step"] == 1 for e in events)
    for k in range(100):
        tele.instant(f"i{k}")
    events = tele.snapshot()
    assert len(events) == 8  # ring bound holds
    assert events[-1]["name"] == "i99"  # ...and keeps the newest events


def test_chrome_trace_schema(tmp_path):
    tele = telemetry.Telemetry(enabled=True, trace_dir=str(tmp_path),
                               process_index=3, process_name="t")
    with tele.span("phase_a", step=0, detail="x"):
        pass
    tele.record_span("phase_b", telemetry.now_s() - 0.5, telemetry.now_s())
    tele.instant("fault:crash", step=2)
    tele.gauge("hbm/d0", 123.0, step=0)
    tele.counter("bad_steps")
    path = tele.export()
    assert path == telemetry.trace_path(str(tmp_path), 3)
    obj = json.load(open(path))  # must be VALID json, loadable in one shot
    assert obj["displayTimeUnit"] == "ms"
    events = obj["traceEvents"]
    by_name = {e["name"]: e for e in events}
    for e in events:
        assert {"name", "ph", "ts", "pid"} <= set(e), e
    for name in ("phase_a", "phase_b"):
        assert by_name[name]["ph"] == "X"
        assert by_name[name]["dur"] >= 0
    assert by_name["fault:crash"]["ph"] == "i"
    assert by_name["fault:crash"]["s"] == "p"
    assert by_name["hbm/d0"]["ph"] == "C"
    assert by_name["hbm/d0"]["args"]["value"] == 123.0
    assert by_name["process_name"]["ph"] == "M"
    assert by_name["process_name"]["args"]["name"] == "t p3"
    assert by_name["phase_b"]["dur"] == pytest.approx(500_000, rel=0.05)


def test_export_drains_and_merges(tmp_path):
    """Two exports to the same path accumulate WITHOUT duplicating: the
    restart-recovered chaos run and the launcher both fold into one file."""
    path = str(tmp_path / "trace.json")
    tele = telemetry.Telemetry(enabled=True)
    tele.instant("first")
    assert tele.export(path) == path
    assert tele.export(path) is None  # buffer drained: nothing to write
    tele.instant("second")
    tele.export(path)
    other = telemetry.Telemetry(enabled=True, process_index=7)
    other.instant("launcher:restart")
    other.export(path)
    names = [e["name"] for e in telemetry.load_events(path)]
    assert names.count("first") == 1
    assert names.count("second") == 1
    assert "launcher:restart" in names
    # one process_name meta per pid
    metas = [e for e in telemetry.load_events(path) if e["ph"] == "M"]
    assert len(metas) == 2


def test_disabled_path_is_noop():
    tele = telemetry.Telemetry(enabled=False)
    assert tele.span("x") is telemetry._NULL_SPAN  # shared, no allocation
    tele.record_span("x", 0.0, 1.0)
    tele.instant("x")
    tele.gauge("x", 1.0)
    tele.counter("x")
    assert tele.snapshot() == []
    assert tele.export("/nonexistent/should/never/be/written") is None
    # Overhead bound: the disabled hot path is one attribute check; 50k
    # calls must land far under a single training step even on a loaded
    # CI box (generous 0.5 s bound for a ~5 ms expected cost).
    t0 = time.perf_counter()
    for _ in range(50_000):
        with tele.span("step"):
            pass
    assert time.perf_counter() - t0 < 0.5


def test_trace_steps_window():
    tele = telemetry.Telemetry(enabled=True, trace_steps=(10, 20))
    with tele.span("in", step=10):
        pass
    assert tele.span("out", step=20) is telemetry._NULL_SPAN  # half-open
    tele.record_span("out", 0.0, 1.0, step=9)
    tele.gauge("out", 1.0, step=25)
    with tele.span("stepless"):  # step-less events are always kept
        pass
    names = [e["name"] for e in tele.snapshot()]
    assert names == ["in", "stepless"]


def test_phase_totals():
    events = [
        {"name": "a", "ph": "X", "ts": 0, "dur": 1000},
        {"name": "a", "ph": "X", "ts": 0, "dur": 3000},
        {"name": "b", "ph": "X", "ts": 0, "dur": 10_000},
        {"name": "skip", "ph": "i", "ts": 0},
    ]
    totals = telemetry.phase_totals(events)
    assert list(totals) == ["b", "a"]  # largest total first
    assert totals["a"] == {"count": 2, "total_ms": 4.0, "mean_ms": 2.0}
    assert totals["b"]["count"] == 1


def test_configure_singleton_roundtrip():
    try:
        tele = telemetry.configure(trace_dir="/tmp/x")
        assert tele.enabled  # enabled defaults to "destination given"
        assert telemetry.get() is tele
        assert not telemetry.configure().enabled
    finally:
        telemetry.reset()
    assert not telemetry.get().enabled


def test_summarize_trace_cli(tmp_path, capsys):
    tele = telemetry.Telemetry(enabled=True, trace_dir=str(tmp_path))
    with tele.span("dispatch", step=1):
        pass
    tele.instant("fault:crash", step=1)
    tele.gauge("hbm/d0", 42.0)
    path = tele.export()

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools import summarize_trace
    assert summarize_trace.main([path, "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert "dispatch" in rec["phases"]
    assert [e["name"] for e in rec["instants"]] == ["fault:crash"]
    assert rec["counters"]["hbm/d0"]["last"] == 42.0
    assert summarize_trace.main([path]) == 0  # table mode renders too
    out = capsys.readouterr().out
    assert "dispatch" in out and "fault:crash" in out
    with pytest.raises(SystemExit):
        summarize_trace.main([str(tmp_path / "missing.json")])


# --- heartbeat health ------------------------------------------------------


def test_heartbeat_writer_and_staleness(tmp_path, monkeypatch):
    d = str(tmp_path)
    w = health.HeartbeatWriter(d, process_id=1)
    w.beat(5)
    crumb = json.load(open(health.heartbeat_path(d, 1)))
    assert crumb["step"] == 5
    now = time.time()
    # Fresh beat: not stale. Child 0 never beat: never reported (the
    # watchdog arms per child on its first beat — no startup grace logic).
    assert health.check_stale(d, 2, timeout_s=1.0, now=now) == []
    os.utime(w.path, (now - 30, now - 30))  # fake clock via mtime
    stale = health.check_stale(d, 2, timeout_s=1.0, now=now)
    assert [pid for pid, _age in stale] == [1]
    assert stale[0][1] == pytest.approx(30, abs=1)
    w.beat(6)  # beating again un-stales
    assert health.check_stale(d, 2, timeout_s=1.0) == []


def test_heartbeat_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv(health.ENV_HEARTBEAT_DIR, raising=False)
    assert health.HeartbeatWriter.from_env() is None
    monkeypatch.setenv(health.ENV_HEARTBEAT_DIR, str(tmp_path))
    monkeypatch.setenv("DDL_PROCESS_ID", "2")
    w = health.HeartbeatWriter.from_env()
    assert w is not None and w.process_id == 2
    w.beat(0)
    assert os.path.exists(health.heartbeat_path(str(tmp_path), 2))


def test_monitor_kills_stale_heartbeat(tmp_path):
    """The hang watchdog end-to-end at unit scale: a child that sleeps
    forever but whose heartbeat has gone stale is killed by monitor() and
    attributed through the existing fail-whole path (nonzero rc)."""
    from distributeddeeplearning_tpu import launch

    d = str(tmp_path)
    specs = launch.plan_local(1, port=9481)
    child = launch.spawn(
        specs[0], [sys.executable, "-c", "import time; time.sleep(120)"])
    # The child "beat once" long ago: write its heartbeat pre-staled.
    health.HeartbeatWriter(d, 0).beat(0)
    old = time.time() - 60
    os.utime(health.heartbeat_path(d, 0), (old, old))
    t0 = time.monotonic()
    rc = launch.monitor([child], poll_interval_s=0.05, grace_s=2.0,
                        heartbeat_dir=d, heartbeat_timeout_s=0.5)
    assert rc != 0  # hung child was killed and attributed, not waited on
    assert time.monotonic() - t0 < 30
    assert child.poll() is not None


# --- MetricLogger hardening (satellite) ------------------------------------


def test_metric_logger_context_manager_and_idempotent_close(tmp_path):
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    path = str(tmp_path / "metrics.jsonl")
    with pytest.raises(RuntimeError):
        with MetricLogger(file_path=path, enabled=True) as logger:
            logger.log(1, {"loss": 1.0})
            raise RuntimeError("boom")  # close() must still run
    assert logger.file is None  # released despite the exception
    logger.close()  # double-close is a no-op, not an error
    rec = json.loads(open(path).read().strip())
    assert rec == {"step": 1, "loss": 1.0}


def test_metric_logger_nonmonotonic_step_resets_throughput(tmp_path):
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    logger = MetricLogger(stream=open(os.devnull, "w"), enabled=True)
    logger.log(10, {}, examples_per_step=8)
    r = logger.log(20, {}, examples_per_step=8)
    assert "step_time_s" in r  # monotonic: throughput accounted normally
    # Restart resumed from an earlier checkpoint: step goes BACKWARD.
    # The elapsed wall time is restore/compile downtime, not step time —
    # no garbage sample now, and none at the next log either.
    r = logger.log(5, {}, examples_per_step=8)
    assert "step_time_s" not in r
    r = logger.log(15, {}, examples_per_step=8)
    assert "step_time_s" in r  # baseline re-armed from the step-5 log
    logger.close()


# --- end-to-end acceptance (slow: real subprocess training runs) -----------


def _env():
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    return env


@pytest.mark.slow
def test_chaos_run_produces_single_merged_trace(tmp_path):
    """ISSUE acceptance: a --fault-plan chaos run under launch.py
    --max-restarts with --trace-dir yields ONE valid Chrome-trace JSON
    holding step phase spans, per-bucket collective spans, the fault
    instant, and the launcher's restart instant."""
    trace = str(tmp_path / "trace")
    ckpt = str(tmp_path / "ckpt")
    cmd = [sys.executable, "launch.py", "--num-processes", "1",
           "--max-restarts", "1", "--backoff", "0.2", "--",
           sys.executable, "train.py", "--backend", "cpu", "--model",
           "resnet18", "--batch-size", "8", "--dp", "1", "--synthetic",
           "--dtype", "float32", "--steps", "5", "--log-every", "2",
           "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
           "--fault-plan", "crash@3", "--trace-dir", trace]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                          env=_env())
    assert proc.returncode == 0, proc.stderr[-2000:]
    files = os.listdir(trace)
    assert files == ["trace.p0.json"]  # ONE merged file
    events = telemetry.load_events(os.path.join(trace, files[0]))
    names = {e["name"] for e in events}
    assert {"data_wait", "dispatch", "fetch_barrier"} <= names
    assert any(n.startswith("collective:allreduce/bucket") for n in names)
    assert "fault:crash" in names
    assert "launcher:restart" in names
    # Both attempts landed: the dispatch spans cover pre- and post-crash
    # steps (crash@3 kills after step 3; resume covers 3..5).
    steps = {e["args"].get("step") for e in events
             if e["name"] == "dispatch"}
    assert steps & {1, 2, 3} and steps & {4, 5}


# --- straggler aggregation -------------------------------------------------
# Unit-level with the allgather stubbed: this box's jax CPU backend cannot
# run multiprocess computations (the pre-existing 2-process dp=2 training
# test in test_launch.py hits the same wall), so the collective itself is
# exercised on real multi-host hardware while the skew math, warning, and
# telemetry instant are pinned here.


def _collect_with(monkeypatch, per_host, threshold=1.5):
    import numpy as np
    from jax.experimental import multihost_utils

    from distributeddeeplearning_tpu.observability import straggler

    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda x: np.concatenate([np.asarray(h, np.float64)
                                  for h in per_host]))
    mon = straggler.StragglerMonitor(threshold, len(per_host))
    return mon.collect(10, *per_host[0])


def test_straggler_skew_fields_no_straggler(monkeypatch, capsys):
    rec = _collect_with(monkeypatch, [(0.10, 0.01), (0.12, 0.02)])
    assert rec["host_count"] == 2
    assert rec["host_step_time_min"] == 0.10
    assert rec["host_step_time_max"] == 0.12
    assert rec["host_step_time_mean"] == pytest.approx(0.11)
    assert rec["host_data_wait_max"] == 0.02
    assert "straggler_host" not in rec  # 0.12 < 1.5 * 0.11
    assert "straggler" not in capsys.readouterr().err


def test_straggler_warning_and_instant(monkeypatch, capsys):
    telemetry.configure(enabled=True)
    try:
        rec = _collect_with(monkeypatch,
                            [(0.10, 0.01), (0.10, 0.01), (0.40, 0.30)])
        assert rec["straggler_host"] == 2
        err = capsys.readouterr().err
        assert "# straggler: host 2" in err
        assert "data_wait 0.3000s" in err  # names the likely cause
        inst = [e for e in telemetry.get().snapshot()
                if e["name"] == "straggler"]
        assert len(inst) == 1 and inst[0]["args"]["host"] == 2
    finally:
        telemetry.reset()


def test_make_monitor_gating():
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.observability import straggler

    # Single-process (this test env): no monitor, regardless of threshold.
    assert straggler.make_monitor(TrainConfig(model="resnet18")) is None
    mon = straggler.StragglerMonitor(1.5, 2)  # what multi-process builds
    assert mon.threshold == 1.5 and mon.num_processes == 2


# --- MetricLogger <-> telemetry single emit path (ISSUE 6 satellite) --------


def test_metric_logger_uses_caller_clock_and_mirrors_gauges():
    """One clock, one emit: the step-time window is computed from the
    ``now_s`` reading the caller already took for the straggler monitor
    (not a second internal clock that can disagree by the cost of the
    straggler allgather), and every numeric field of the record is
    mirrored into the active telemetry registry so trace and JSONL can
    never diverge."""
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    try:
        telemetry.configure(enabled=True)
        logger = MetricLogger(stream=open(os.devnull, "w"), enabled=True)
        logger.log(1, {"loss": 2.0}, examples_per_step=8, now_s=100.0)
        rec = logger.log(2, {"loss": 1.5}, examples_per_step=8,
                         now_s=100.5, lr=0.1)
        # Exactly the caller's readings: 0.5 s apart — impossible to get
        # from an internal wall clock in a microsecond-fast test.
        assert rec["step_time_s"] == 0.5
        assert rec["examples_per_sec"] == 16.0
        gauges = {}
        for e in telemetry.get().snapshot():
            if e.get("ph") == "C":
                gauges.setdefault(e["name"], []).append(
                    e["args"]["value"])
        for key in ("loss", "step_time_s", "examples_per_sec", "lr"):
            assert key in gauges, f"{key} not mirrored into telemetry"
        assert gauges["loss"] == [2.0, 1.5]
        assert gauges["examples_per_sec"][-1] == 16.0
        logger.close()
    finally:
        telemetry.reset()


def test_metric_logger_no_mirroring_when_telemetry_disabled():
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    telemetry.reset()  # the disabled singleton
    logger = MetricLogger(stream=open(os.devnull, "w"), enabled=True)
    logger.log(1, {"loss": 2.0}, now_s=1.0)
    assert telemetry.get().snapshot() == []
    logger.close()


def test_metric_logger_roofline_pct_of_peak():
    """set_roofline turns every throughput record into a roofline record:
    tflops_per_sec always, pct_of_peak when the peak is known — the
    log-cadence %-of-peak line ISSUE 6's tentpole requires."""
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    logger = MetricLogger(stream=open(os.devnull, "w"), enabled=True)
    logger.set_roofline(1e9, 1e12)  # 1 GFLOP/example, 1 TFLOP/s peak
    logger.log(1, {}, examples_per_step=100, now_s=10.0)
    rec = logger.log(2, {}, examples_per_step=100, now_s=11.0)
    assert rec["examples_per_sec"] == 100.0
    assert rec["tflops_per_sec"] == 0.1
    assert rec["pct_of_peak"] == 10.0
    # Unknown peak (CPU): tflops still reported, pct honestly absent.
    logger.set_roofline(1e9, None)
    logger.log(3, {}, examples_per_step=100, now_s=12.0)
    rec = logger.log(4, {}, examples_per_step=100, now_s=13.0)
    assert rec["tflops_per_sec"] == 0.1 and "pct_of_peak" not in rec
    logger.close()
