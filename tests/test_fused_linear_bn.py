"""Numerics of the fused matmul+BN op vs the unfused jnp composition.

The op under test is the conv-epilogue fusion (ops/fused_linear_bn.py):
prologue BN-apply + matmul + per-channel Σy/Σy² epilogue, with a custom
VJP whose backward is two matmul kernels. Off-TPU the same kernels run in
Pallas interpret mode, so these tests exercise the real kernel bodies.

Reference semantics: stats are taken over y AS STORED (bf16 in training),
and μ/inv are differentiable inputs — the reference composition below
mirrors both, so everything (including dμ/dinv cotangents) must agree.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.ops import fused_linear_bn as flb

jax.config.update("jax_platforms", "cpu")


def _ref(x, mu, inv, gamma, beta, w, relu, bn):
    a = x
    if bn:
        af = (x.astype(jnp.float32) - mu) * (inv * gamma) + beta
        if relu:
            af = jnp.maximum(af, 0.0)
        a = af.astype(x.dtype)
    y = jnp.dot(a.astype(jnp.float32), w.astype(jnp.float32)).astype(x.dtype)
    yf = y.astype(jnp.float32)
    return y, yf.sum(axis=0), (yf * yf).sum(axis=0)


def _inputs(m=24, k=16, n=8, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 6)
    x = jax.random.normal(ks[0], (m, k), dtype)
    w = (jax.random.normal(ks[1], (k, n)) * 0.3).astype(dtype)
    mu = jax.random.normal(ks[2], (k,)) * 0.2
    var = jax.random.uniform(ks[3], (k,), minval=0.25, maxval=2.0)
    inv = jax.lax.rsqrt(var)
    gamma = jax.random.normal(ks[4], (k,)) * 0.3 + 1.0
    beta = jax.random.normal(ks[5], (k,)) * 0.1
    return x, mu, inv, gamma, beta, w


@pytest.mark.core
@pytest.mark.parametrize("relu,bn", [(True, True), (False, True),
                                     (False, False)])
def test_forward_matches_reference(relu, bn):
    x, mu, inv, gamma, beta, w = _inputs()
    y, s, ss = flb.bn_linear_stats(x, mu, inv, gamma, beta, w, relu, bn)
    yr, sr, ssr = _ref(x, mu, inv, gamma, beta, w, relu, bn)
    np.testing.assert_allclose(y, yr, atol=1e-5)
    np.testing.assert_allclose(s, sr, rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(ss, ssr, rtol=1e-5, atol=1e-4)


@pytest.mark.core
@pytest.mark.parametrize("relu,bn", [(True, True), (False, True),
                                     (False, False)])
def test_gradients_match_reference(relu, bn):
    x, mu, inv, gamma, beta, w = _inputs(seed=1)
    # Weighted sums touch all three outputs so dy, ds and dss are all
    # nonzero — the ds/dss folding is the novel part of the backward.
    ky = jax.random.split(jax.random.key(7), 3)
    wy = jax.random.normal(ky[0], (24, 8))
    ws_ = jax.random.normal(ky[1], (8,))
    wss = jax.random.normal(ky[2], (8,)) * 0.01

    def loss(f):
        def inner(x, mu, inv, gamma, beta, w):
            y, s, ss = f(x, mu, inv, gamma, beta, w, relu, bn)
            return (jnp.sum(y.astype(jnp.float32) * wy)
                    + jnp.sum(s * ws_) + jnp.sum(ss * wss))
        return inner

    gf = jax.grad(loss(flb.bn_linear_stats), argnums=tuple(range(6)))(
        x, mu, inv, gamma, beta, w)
    gr = jax.grad(loss(_ref), argnums=tuple(range(6)))(
        x, mu, inv, gamma, beta, w)
    names = ("dx", "dmu", "dinv", "dgamma", "dbeta", "dw")
    for a, b, name in zip(gf, gr, names):
        if not bn and name in ("dmu", "dinv", "dgamma", "dbeta"):
            continue  # op contract: zeros for unused vector inputs
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-4, atol=2e-4, err_msg=name)


@pytest.mark.core
def test_linear_stats_wrapper():
    x, _, _, _, _, w = _inputs(seed=2)
    y, s, ss = flb.linear_stats(x, w)
    yr = jnp.dot(x, w)
    np.testing.assert_allclose(y, yr, atol=1e-5)
    np.testing.assert_allclose(s, yr.sum(axis=0), rtol=1e-5, atol=1e-4)


def test_bf16_storage_stats_match_next_layer_view():
    """Σy/Σy² must describe y as the next layer will read it (bf16)."""
    x, mu, inv, gamma, beta, w = _inputs(dtype=jnp.bfloat16, seed=3)
    y, s, ss = flb.bn_linear_stats(x, mu, inv, gamma, beta, w, True, True)
    yf = np.asarray(y, np.float32)
    np.testing.assert_allclose(s, yf.sum(axis=0), rtol=1e-3, atol=1e-2)
    np.testing.assert_allclose(ss, (yf * yf).sum(axis=0), rtol=1e-3,
                               atol=1e-2)


def test_chained_two_layers_matches_unfused():
    """The intended usage: layer2's μ/inv derive from layer1's s/ss, so
    gradients flow through the epilogue sums into BOTH layers."""
    m, k, n1, n2 = 32, 16, 8, 8
    ks = jax.random.split(jax.random.key(11), 4)
    x = jax.random.normal(ks[0], (m, k))
    w1 = (jax.random.normal(ks[1], (k, n1)) * 0.3)
    w2 = (jax.random.normal(ks[2], (n1, n2)) * 0.3)
    gamma = jnp.ones((n1,))
    beta = jnp.zeros((n1,))
    tgt = jax.random.normal(ks[3], (m, n2))
    eps = 1e-5

    def fused(params):
        w1, w2, gamma, beta = params
        zk = jnp.zeros((k,), jnp.float32)
        y1, s1, ss1 = flb.bn_linear_stats(x, zk, zk, zk, zk, w1,
                                          False, False)
        mu = s1 / m
        var = jnp.maximum(ss1 / m - mu * mu, 0.0)
        inv = jax.lax.rsqrt(var + eps)
        y2, _, _ = flb.bn_linear_stats(y1, mu, inv, gamma, beta, w2,
                                       True, True)
        return jnp.mean((y2.astype(jnp.float32) - tgt) ** 2)

    def unfused(params):
        w1, w2, gamma, beta = params
        y1 = jnp.dot(x, w1)
        mu = y1.mean(axis=0)
        var = jnp.maximum((y1 * y1).mean(axis=0) - mu * mu, 0.0)
        inv = jax.lax.rsqrt(var + eps)
        a = jnp.maximum((y1 - mu) * (inv * gamma) + beta, 0.0)
        y2 = jnp.dot(a, w2)
        return jnp.mean((y2 - tgt) ** 2)

    params = (w1, w2, gamma, beta)
    lf, gf = jax.value_and_grad(fused)(params)
    lr, gr = jax.value_and_grad(unfused)(params)
    np.testing.assert_allclose(lf, lr, rtol=1e-5)
    for a, b, name in zip(gf, gr, ("dw1", "dw2", "dgamma", "dbeta")):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5,
                                   err_msg=name)
