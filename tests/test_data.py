"""Synthetic source determinism (SURVEY.md §4) + loss-function unit tests."""

import jax
import jax.numpy as jnp
import numpy as np

from distributeddeeplearning_tpu.data.synthetic import (
    SyntheticImages, SyntheticTokens)
from distributeddeeplearning_tpu.train import losses


def test_synthetic_images_deterministic():
    a = SyntheticImages(4, 16, 10, seed=0)
    b = SyntheticImages(4, 16, 10, seed=0)
    ba, bb = a.batch(3), b.batch(3)
    np.testing.assert_array_equal(np.asarray(ba["image"], np.float32),
                                  np.asarray(bb["image"], np.float32))
    np.testing.assert_array_equal(ba["label"], bb["label"])
    b4 = a.batch(4)
    assert not np.array_equal(np.asarray(ba["image"], np.float32),
                              np.asarray(b4["image"], np.float32))


def test_synthetic_images_shapes_dtypes():
    src = SyntheticImages(8, 32, 100, seed=1)
    b = src.batch(0)
    assert b["image"].shape == (8, 32, 32, 3)
    assert b["image"].dtype == jnp.bfloat16
    assert b["label"].shape == (8,)
    assert int(b["label"].min()) >= 0 and int(b["label"].max()) < 100


def test_synthetic_tokens_masking():
    src = SyntheticTokens(4, 64, 1000, mask_prob=0.25, seed=0)
    b = src.batch(0)
    masked = b["labels"] >= 0
    # masked positions carry the [MASK] id in inputs, original id in labels
    assert bool((b["input_ids"][masked] == 103).all())
    frac = float(masked.mean())
    assert 0.1 < frac < 0.45
    unmasked = ~masked
    assert bool((b["labels"][unmasked] == -1).all())


def test_synthetic_tokens_small_vocab_in_range():
    """Regression: vocab smaller than the reserved-id offset must still
    produce in-vocab ids (out-of-range labels NaN the cross entropy)."""
    src = SyntheticTokens(4, 16, 512, seed=0)
    b = src.batch(0)
    assert int(b["labels"].max()) < 512
    assert int(b["input_ids"].max()) < 512


def test_mlm_loss_ignores_unmasked():
    logits = jax.random.normal(jax.random.key(0), (2, 8, 50))
    labels_none = jnp.full((2, 8), -1)
    # all-unmasked batch: guarded, returns 0
    assert float(losses.mlm_loss(logits, labels_none)) == 0.0
    labels = labels_none.at[0, 0].set(7)
    expected = -jax.nn.log_softmax(logits[0, 0])[7]
    np.testing.assert_allclose(float(losses.mlm_loss(logits, labels)),
                               float(expected), rtol=1e-6)


def test_label_smoothing_matches_manual():
    logits = jax.random.normal(jax.random.key(1), (4, 10))
    labels = jnp.array([1, 2, 3, 4])
    got = losses.smoothed_softmax_ce(logits, labels, smoothing=0.1)
    onehot = jax.nn.one_hot(labels, 10) * 0.9 + 0.1 / 10
    manual = (-(onehot * jax.nn.log_softmax(logits)).sum(-1)).mean()
    np.testing.assert_allclose(float(got), float(manual), rtol=1e-6)


def test_top1_accuracy():
    logits = jnp.array([[1.0, 2.0], [3.0, 0.0]])
    assert float(losses.top1_accuracy(logits, jnp.array([1, 0]))) == 1.0
    assert float(losses.top1_accuracy(logits, jnp.array([0, 0]))) == 0.5
