"""Gradient accumulation (VERDICT r1 #3): accum-N step ≡ one big-batch step,
and the batch=32k LARS preset (config 5, BASELINE.json:11) actually runs on
the 8-fake-CPU mesh."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig, preset)
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.train import optim, steps
from distributeddeeplearning_tpu.train.state import TrainState


class _TinyNet(nn.Module):
    """BN-free image classifier: accumulation equivalence is exact (up to fp
    summation order) only without cross-example normalization."""

    @nn.compact
    def __call__(self, x, train=False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(10)(x)


def _build(accum: int):
    cfg = TrainConfig(
        model="resnet18", global_batch_size=32, dtype="float32",
        grad_accum_steps=accum,
        parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=8, num_classes=10),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1,
                                  reference_batch=32, momentum=0.9,
                                  schedule="constant", warmup_epochs=0.0))
    mesh = meshlib.make_mesh(cfg.parallel)
    model = _TinyNet()
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 10, None)
    variables = model.init(
        {"params": jax.random.key(0)}, jnp.zeros((1, 8, 8, 3)), train=False)
    state = TrainState.create(params=variables["params"],
                              opt_state=tx.init(variables["params"]),
                              batch_stats=None)
    step = steps.make_dp_train_step(model, tx, mesh, cfg, "image")
    return state, step


@pytest.mark.usefixtures("devices8")
@pytest.mark.core
def test_accum_matches_big_batch():
    rng = jax.random.key(1)
    batch = {
        "image": jax.random.normal(jax.random.key(2), (32, 8, 8, 3)),
        "label": jax.random.randint(jax.random.key(3), (32,), 0, 10),
    }
    state1, step1 = _build(accum=1)
    state4, step4 = _build(accum=4)
    for _ in range(3):  # momentum makes later steps depend on earlier grads
        state1, m1 = step1(state1, batch, rng)
        state4, m4 = step4(state4, batch, rng)
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]),
                               rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        jax.device_get(state1.params), jax.device_get(state4.params))


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_lars_32k_preset_runs_on_8_devices():
    from distributeddeeplearning_tpu.train import loop

    cfg = preset("resnet50_lars_32k")
    assert cfg.global_batch_size == 32768
    assert cfg.parallel.data * cfg.grad_accum_steps * \
        (cfg.global_batch_size // cfg.parallel.data // cfg.grad_accum_steps) \
        == 32768
    # Shrink only the *image resolution* (compute), never the batch math:
    # 32768 examples still flow through one LARS update.
    cfg = cfg.replace(
        model="resnet18", dtype="float32", log_every=10**9,
        data=DataConfig(synthetic=True, image_size=8, num_classes=10))
    summary = loop.run(cfg, total_steps=1)
    assert summary["final_step"] == 1
    assert np.isfinite(summary["final_metrics"]["loss"])


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_accum_gspmd_tokens_runs():
    from distributeddeeplearning_tpu.train import loop

    cfg = TrainConfig(
        model="bert_tiny", global_batch_size=16, dtype="float32",
        grad_accum_steps=2, log_every=10**9,
        parallel=ParallelConfig(data=2, seq=2, model=2),
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=128),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                                  schedule="linear", label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=2)
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])


@pytest.mark.core
def test_accum_divisibility_validation():
    cfg = TrainConfig(global_batch_size=32, grad_accum_steps=3,
                      parallel=ParallelConfig(data=8))
    with pytest.raises(ValueError, match="grad_accum_steps"):
        _ = cfg.per_device_batch
