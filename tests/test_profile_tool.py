"""tools/profile_step.py's trace aggregation, against a synthetic perfetto
trace — the tool backs BASELINE.md's where-the-step-goes claims, so its
track selection (XLA Ops only, no double-counting of module/step slices)
and family classification are pinned here."""

import gzip
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import profile_step  # noqa: E402

sys.path.pop(0)


def _trace(tmp_path, events):
    d = tmp_path / "plugins" / "perfetto"
    d.mkdir(parents=True)
    with gzip.open(d / "x.perfetto_trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def _meta(pid, name, tid=None):
    ev = {"ph": "M", "pid": pid,
          "name": "thread_name" if tid is not None else "process_name",
          "args": {"name": name}}
    if tid is not None:
        ev["tid"] = tid
    return ev


@pytest.mark.core
def test_summarize_uses_only_the_ops_track(tmp_path):
    events = [
        _meta(1, "/device:TPU:0"),
        _meta(1, "XLA Modules", tid=1),
        _meta(1, "XLA Ops", tid=2),
        _meta(2, "python host", ),
        _meta(2, "main", tid=1),
        # Module-level slice spanning everything — must NOT be counted.
        {"ph": "X", "pid": 1, "tid": 1, "name": "jit_step_fn", "dur": 9000},
        # Leaf ops (microseconds).
        {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.1", "dur": 3000},
        {"ph": "X", "pid": 1, "tid": 2, "name": "convert_reduce_fusion.2",
         "dur": 2000},
        {"ph": "X", "pid": 1, "tid": 2, "name": "copy.5", "dur": 1000},
        {"ph": "X", "pid": 1, "tid": 2, "name": "bn_stem.7", "dur": 500},
        # Host-side slice — wrong pid, must not be counted.
        {"ph": "X", "pid": 2, "tid": 1, "name": "fusion.1", "dur": 77777},
    ]
    out = profile_step.summarize(_trace(tmp_path, events), steps=2, top=10)
    # 6.5 ms of ops over 2 steps = 3.25 ms/step; the 9 ms module slice and
    # the 77 ms host slice are excluded.
    assert out["device_ms_per_step"] == pytest.approx(3.25)
    fam = out["by_family_ms"]
    assert fam["elementwise"] == pytest.approx(1.5)   # fusion.1
    assert fam["bn_reduce"] == pytest.approx(1.0)     # convert_reduce
    assert fam["copy_reshape"] == pytest.approx(0.5)  # copy.5
    assert fam["other"] == pytest.approx(0.25)        # bn_stem (pallas name)
    assert out["top_ops_ms"]["fusion.1"] == pytest.approx(1.5)
    assert "jit_step_fn" not in out["top_ops_ms"]


def test_summarize_missing_trace_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        profile_step.summarize(str(tmp_path), steps=1, top=5)
