"""Numerics parity vs HuggingFace transformers (torch CPU), SURVEY.md §4:
with identical weights, our forward must match the canonical architecture
implementation — the strongest available substitute for reference parity
while /root/reference is empty. Models are instantiated offline from
configs (random init, no downloads); HF weights are mapped into our
pytrees and logits compared."""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributeddeeplearning_tpu.models import bert, gpt, llama  # noqa: E402


def _t(x):  # torch weight -> numpy
    return x.detach().cpu().numpy()


def test_llama_forward_matches_hf():
    """Tiny llama (GQA 4 heads / 2 KV) vs transformers.LlamaForCausalLM:
    validates RoPE convention, GQA repeat, SwiGLU, RMSNorm, untied head."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=False, mlp_bias=False, tie_word_embeddings=False,
        attention_dropout=0.0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    sd = hf.state_dict()

    def layer(i):
        p = f"model.layers.{i}."
        return {
            "attention_norm": {"scale": _t(sd[p + "input_layernorm.weight"])},
            "mlp_norm": {"scale": _t(sd[p + "post_attention_layernorm.weight"])},
            "attention": {
                "q_proj": {"kernel": _t(sd[p + "self_attn.q_proj.weight"]).T},
                "k_proj": {"kernel": _t(sd[p + "self_attn.k_proj.weight"]).T},
                "v_proj": {"kernel": _t(sd[p + "self_attn.v_proj.weight"]).T},
                "o_proj": {"kernel": _t(sd[p + "self_attn.o_proj.weight"]).T},
            },
            "gate_proj": {"kernel": _t(sd[p + "mlp.gate_proj.weight"]).T},
            "up_proj": {"kernel": _t(sd[p + "mlp.up_proj.weight"]).T},
            "down_proj": {"kernel": _t(sd[p + "mlp.down_proj.weight"]).T},
        }

    params = {
        "embed_tokens": _t(sd["model.embed_tokens.weight"]),
        "final_norm": {"scale": _t(sd["model.norm.weight"])},
        "lm_head": {"kernel": _t(sd["lm_head.weight"]).T},
        **{f"layer{i}": layer(i) for i in range(2)},
    }

    ours = llama.tiny_llama(vocab_size=256, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (2, 16))
    ours_logits = np.asarray(ours.apply(
        {"params": params}, jnp.asarray(ids, jnp.int32), train=False))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours_logits, hf_logits, rtol=2e-4, atol=2e-4)


def test_gpt2_forward_matches_hf():
    """Tiny GPT-2 vs transformers.GPT2LMHeadModel: validates pre-LN blocks,
    fused-qkv split, tanh-gelu MLP, learned positions, tied head. HF GPT-2
    uses Conv1D ([in, out] weights — no transpose)."""
    hf_cfg = transformers.GPT2Config(
        vocab_size=256, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        layer_norm_epsilon=1e-5, activation_function="gelu_new")
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    sd = hf.state_dict()

    def ln(prefix):
        return {"scale": _t(sd[prefix + ".weight"]),
                "bias": _t(sd[prefix + ".bias"])}

    def layer(i):
        p = f"transformer.h.{i}."
        qkv_w = _t(sd[p + "attn.c_attn.weight"])   # (h, 3h), Conv1D layout
        qkv_b = _t(sd[p + "attn.c_attn.bias"])
        h = qkv_w.shape[0]
        return {
            "ln1": ln(p + "ln_1"),
            "ln2": ln(p + "ln_2"),
            "attention": {
                "query": {"kernel": qkv_w[:, :h], "bias": qkv_b[:h]},
                "key": {"kernel": qkv_w[:, h:2 * h], "bias": qkv_b[h:2 * h]},
                "value": {"kernel": qkv_w[:, 2 * h:], "bias": qkv_b[2 * h:]},
                "output": {"kernel": _t(sd[p + "attn.c_proj.weight"]),
                           "bias": _t(sd[p + "attn.c_proj.bias"])},
            },
            "mlp_in": {"kernel": _t(sd[p + "mlp.c_fc.weight"]),
                       "bias": _t(sd[p + "mlp.c_fc.bias"])},
            "mlp_out": {"kernel": _t(sd[p + "mlp.c_proj.weight"]),
                        "bias": _t(sd[p + "mlp.c_proj.bias"])},
        }

    params = {
        "wte": _t(sd["transformer.wte.weight"]),
        "wpe": _t(sd["transformer.wpe.weight"]),
        "ln_f": ln("transformer.ln_f"),
        **{f"layer{i}": layer(i) for i in range(2)},
    }

    ours = gpt.tiny_gpt(vocab_size=256, dtype=jnp.float32, dropout_rate=0.0,
                        max_position=64)
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 256, (2, 16))
    ours_logits = np.asarray(ours.apply(
        {"params": params}, jnp.asarray(ids, jnp.int32), train=False))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours_logits, hf_logits, rtol=2e-4, atol=2e-4)


def test_bert_forward_matches_hf():
    """Tiny BERT MLM vs transformers.BertForMaskedLM: validates embeddings
    (word+pos+type, post-LN), post-LN encoder, and the tied MLM head."""
    hf_cfg = transformers.BertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=128, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        layer_norm_eps=1e-12, hidden_act="gelu")
    hf = transformers.BertForMaskedLM(hf_cfg).eval()
    hf.tie_weights()
    sd = hf.state_dict()

    def ln(prefix):
        return {"scale": _t(sd[prefix + ".weight"]),
                "bias": _t(sd[prefix + ".bias"])}

    def dense(prefix):
        return {"kernel": _t(sd[prefix + ".weight"]).T,
                "bias": _t(sd[prefix + ".bias"])}

    def layer(i):
        p = f"bert.encoder.layer.{i}."
        return {
            "attention": {
                "query": dense(p + "attention.self.query"),
                "key": dense(p + "attention.self.key"),
                "value": dense(p + "attention.self.value"),
                "output": dense(p + "attention.output.dense"),
            },
            "attention_ln": ln(p + "attention.output.LayerNorm"),
            "intermediate": dense(p + "intermediate.dense"),
            "mlp_output": dense(p + "output.dense"),
            "mlp_ln": ln(p + "output.LayerNorm"),
        }

    params = {
        "word_embeddings": _t(sd["bert.embeddings.word_embeddings.weight"]),
        "position_embeddings": _t(
            sd["bert.embeddings.position_embeddings.weight"]),
        "type_embeddings": _t(
            sd["bert.embeddings.token_type_embeddings.weight"]),
        "embeddings_ln": ln("bert.embeddings.LayerNorm"),
        "mlm_transform": dense("cls.predictions.transform.dense"),
        "mlm_ln": ln("cls.predictions.transform.LayerNorm"),
        "mlm_bias": _t(sd["cls.predictions.bias"]),
        **{f"layer{i}": layer(i) for i in range(2)},
    }

    ours = bert.tiny_bert_mlm(vocab_size=256, dtype=jnp.float32,
                              dropout_rate=0.0)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 256, (2, 16))
    ours_logits = np.asarray(ours.apply(
        {"params": params}, jnp.asarray(ids, jnp.int32), train=False))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours_logits, hf_logits, rtol=2e-4, atol=2e-4)
