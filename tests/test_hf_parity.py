"""Numerics parity vs HuggingFace transformers (torch CPU), SURVEY.md §4:
with identical weights, our forward must match the canonical architecture
implementation — the strongest available substitute for reference parity
while /root/reference is empty. Models are instantiated offline from
configs (random init, no downloads); HF weights are mapped into our
pytrees THROUGH the shipped converter (utils/hf_convert.py — the same
code tools/import_hf.py uses), so these tests prove the import path, not
just a test-local mapping."""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributeddeeplearning_tpu.models import bert, gpt, llama  # noqa: E402
from distributeddeeplearning_tpu.utils import hf_convert  # noqa: E402


def test_llama_forward_matches_hf():
    """Tiny llama (GQA 4 heads / 2 KV) vs transformers.LlamaForCausalLM:
    validates RoPE convention, GQA repeat, SwiGLU, RMSNorm, untied head."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=False, mlp_bias=False, tie_word_embeddings=False,
        attention_dropout=0.0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    params = hf_convert.llama_params_from_hf(
        hf_convert.state_dict_to_numpy(hf.state_dict()), 2)

    ours = llama.tiny_llama(vocab_size=256, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (2, 16))
    ours_logits = np.asarray(ours.apply(
        {"params": params}, jnp.asarray(ids, jnp.int32), train=False))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours_logits, hf_logits, rtol=2e-4, atol=2e-4)


def test_llama_tied_embeddings_head():
    """tie_word_embeddings=True checkpoints ship no lm_head tensor; the
    converter must fall back to the embedding matrix."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        tie_word_embeddings=True, attention_bias=False, mlp_bias=False)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    hf.tie_weights()
    sd = hf_convert.state_dict_to_numpy(hf.state_dict())
    # save_pretrained drops tied duplicates from the serialized checkpoint
    # (in-memory state_dicts may still alias them) — simulate the on-disk
    # form the import tool actually reads.
    sd.pop("lm_head.weight", None)
    params = hf_convert.llama_params_from_hf(sd, 1)
    np.testing.assert_array_equal(params["lm_head"]["kernel"],
                                  params["embed_tokens"].T)


def test_gpt2_forward_matches_hf():
    """Tiny GPT-2 vs transformers.GPT2LMHeadModel: validates pre-LN blocks,
    fused-qkv split, tanh-gelu MLP, learned positions, tied head. HF GPT-2
    uses Conv1D ([in, out] weights — no transpose)."""
    hf_cfg = transformers.GPT2Config(
        vocab_size=256, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        layer_norm_epsilon=1e-5, activation_function="gelu_new")
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    params = hf_convert.gpt2_params_from_hf(
        hf_convert.state_dict_to_numpy(hf.state_dict()), 2)

    ours = gpt.tiny_gpt(vocab_size=256, dtype=jnp.float32, dropout_rate=0.0,
                        max_position=64)
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 256, (2, 16))
    ours_logits = np.asarray(ours.apply(
        {"params": params}, jnp.asarray(ids, jnp.int32), train=False))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours_logits, hf_logits, rtol=2e-4, atol=2e-4)


def test_bert_forward_matches_hf():
    """Tiny BERT MLM vs transformers.BertForMaskedLM: validates embeddings
    (word+pos+type, post-LN), post-LN encoder, and the tied MLM head."""
    hf_cfg = transformers.BertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=128, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        layer_norm_eps=1e-12, hidden_act="gelu")
    hf = transformers.BertForMaskedLM(hf_cfg).eval()
    hf.tie_weights()
    params = hf_convert.bert_params_from_hf(
        hf_convert.state_dict_to_numpy(hf.state_dict()), 2)

    ours = bert.tiny_bert_mlm(vocab_size=256, dtype=jnp.float32,
                              dropout_rate=0.0)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 256, (2, 16))
    ours_logits = np.asarray(ours.apply(
        {"params": params}, jnp.asarray(ids, jnp.int32), train=False))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours_logits, hf_logits, rtol=2e-4, atol=2e-4)


def test_convert_checked_rejects_unconsumed_tensors():
    """A checkpoint with weights the mapping doesn't consume (e.g.
    attention_bias=True biases) must fail loudly, not import silently."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        attention_bias=True, mlp_bias=False, tie_word_embeddings=False)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    sd = hf_convert.state_dict_to_numpy(hf.state_dict())
    with pytest.raises(ValueError, match="does not consume"):
        hf_convert.convert_checked("llama", sd, 1)
    # The clean config imports fine through the same checked path.
    hf_cfg2 = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        attention_bias=False, mlp_bias=False, tie_word_embeddings=False)
    hf2 = transformers.LlamaForCausalLM(hf_cfg2).eval()
    params = hf_convert.convert_checked(
        "llama", hf_convert.state_dict_to_numpy(hf2.state_dict()), 1)
    assert "layer0" in params


def test_import_hf_tool_end_to_end(tmp_path):
    """save_pretrained → tools/import_hf.py → Checkpointer params restore →
    logits match HF. The full user path for bringing pretrained weights in
    (no network: the tool reads local directories only)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import import_hf
    finally:
        sys.path.pop(0)

    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=1, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    hf_dir, out_dir = str(tmp_path / "hf"), str(tmp_path / "ckpt")
    hf.save_pretrained(hf_dir)

    assert import_hf.main(["--hf-dir", hf_dir, "--out", out_dir]) == 0

    ours = gpt.GptLM(gpt.GptConfig(
        vocab_size=128, hidden_size=32, num_layers=1, num_heads=2,
        max_position=32, dropout_rate=0.0), dtype=jnp.float32)
    import jax
    init = ours.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                     train=False)
    ckpt = Checkpointer(out_dir, every_steps=1)
    try:
        params = ckpt.restore_latest_params(init["params"])
    finally:
        ckpt.close()
    assert params is not None

    rng = np.random.default_rng(3)
    ids = rng.integers(0, 128, (2, 8))
    ours_logits = np.asarray(ours.apply(
        {"params": params}, jnp.asarray(ids, jnp.int32), train=False))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours_logits, hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("family", ["gpt2", "bert", "llama"])
def test_export_inverts_import(family):
    """params -> to_hf -> from_hf is the identity (exact array equality),
    for every family — the two mappings are true inverses."""
    import numpy as np

    mk = {
        "gpt2": lambda: transformers.GPT2LMHeadModel(transformers.GPT2Config(
            vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2)),
        "bert": lambda: transformers.BertForMaskedLM(transformers.BertConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=64,
            max_position_embeddings=32, type_vocab_size=2)),
        "llama": lambda: transformers.LlamaForCausalLM(
            transformers.LlamaConfig(
                vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=2,
                num_key_value_heads=2, attention_bias=False, mlp_bias=False,
                tie_word_embeddings=False)),
    }[family]
    hf = mk()
    hf.tie_weights()
    sd = hf_convert.state_dict_to_numpy(hf.state_dict())
    convert, _ = hf_convert.CONVERTERS[family]
    params = convert(sd, 2)
    back = hf_convert.EXPORTERS[family](params, 2)
    again = convert(back, 2)
    flat_a = hf_convert._flat(params)
    flat_b = hf_convert._flat(again)
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(flat_a[k], flat_b[k], err_msg=k)


def test_export_tool_roundtrip_cli(tmp_path):
    """Full circle: train-shaped checkpoint -> export_hf -> transformers
    loads it -> import_hf brings it back -> logits identical."""
    import os
    import sys

    import jax
    import orbax.checkpoint as ocp

    tools_dir = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools_dir)
    try:
        import export_hf
        import import_hf
    finally:
        # remove by value: the tools import themselves prepend the repo
        # root, so pop(0) would evict the wrong entry
        sys.path.remove(tools_dir)

    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    # A gpt_tiny "training run" checkpoint with random params.
    spec = model_spec("gpt_tiny")
    model = spec.build(dtype=jnp.float32, vocab_size=64, seq_len=32)
    init = model.init({"params": jax.random.key(7)},
                      jnp.zeros((1, 8), jnp.int32), train=False)
    ck1 = str(tmp_path / "ck1")
    mgr = ocp.CheckpointManager(os.path.abspath(ck1))
    mgr.save(0, args=ocp.args.StandardSave(
        {"params": init["params"], "batch_stats": None, "step": 0}))
    mgr.wait_until_finished()
    mgr.close()

    hf_dir = str(tmp_path / "hf")
    out = export_hf.export("gpt_tiny", ck1, hf_dir, vocab_size=64,
                           seq_len=32)
    assert out["family"] == "gpt2"

    # transformers reads the exported model and matches our logits.
    import numpy as np
    import torch

    hf = transformers.GPT2LMHeadModel.from_pretrained(hf_dir).eval()
    ids = np.random.default_rng(5).integers(0, 64, (2, 8))
    ours = np.asarray(model.apply({"params": init["params"]},
                                  jnp.asarray(ids, jnp.int32), train=False))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # ... and import_hf closes the loop.
    ck2 = str(tmp_path / "ck2")
    assert import_hf.main(["--hf-dir", hf_dir, "--out", ck2]) == 0
    ckpt = Checkpointer(ck2, every_steps=1)
    try:
        restored = ckpt.restore_latest_params(init["params"])
    finally:
        ckpt.close()
    for (path, a), b in zip(
            jax.tree_util.tree_leaves_with_path(restored),
            jax.tree_util.tree_leaves(init["params"])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6,
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.parametrize("family", ["bert", "llama"])
def test_exported_model_logits_match(family):
    """hf_model_for's config construction for the non-GPT families: export
    our tiny model's params, load into the transformers model export_hf
    builds, compare logits (the GPT-2 case is covered end-to-end by
    test_export_tool_roundtrip_cli)."""
    import os
    import sys

    import jax

    tools_dir = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools_dir)
    try:
        import export_hf
    finally:
        sys.path.remove(tools_dir)

    if family == "bert":
        ours = bert.tiny_bert_mlm(vocab_size=64, dtype=jnp.float32,
                                  dropout_rate=0.0)
    else:
        ours = llama.tiny_llama(vocab_size=64, dtype=jnp.float32)
    from flax.core import meta

    init = ours.init({"params": jax.random.key(9)},
                     jnp.zeros((1, 8), jnp.int32), train=False)
    params = jax.tree.map(lambda x: np.asarray(x, np.float32),
                          meta.unbox(init["params"]))
    sd = hf_convert.EXPORTERS[family](params, ours.cfg.num_layers)
    hf = export_hf.hf_model_for(family, ours.cfg).eval()
    missing, _ = hf.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v))
         for k, v in sd.items()}, strict=False)
    missing = [m for m in missing if ".position_ids" not in m]
    assert not missing, missing

    ids = np.random.default_rng(6).integers(0, 64, (2, 8))
    ours_logits = np.asarray(ours.apply(
        init, jnp.asarray(ids, jnp.int32), train=False))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours_logits, hf_logits, rtol=2e-4, atol=2e-4)
