"""ResNet50 forward parity vs a canonical torch implementation (SURVEY.md
§4 "Numerics"): the reference's trainers used the torchvision ResNet50-v1.5;
torchvision itself is not in this image, so the test carries the published
architecture in plain torch.nn (Bottleneck v1.5, symmetric padding, BN
eps 1e-5) and maps identical weights into our Flax model. Eval-mode logits
must agree — validating conv padding/stride arithmetic, BN inference
semantics, pooling, and the classifier wiring across frameworks."""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
nn = torch.nn

from distributeddeeplearning_tpu import models  # noqa: E402


class _Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, filters, stride):
        super().__init__()
        cout = filters * 4
        self.conv1 = nn.Conv2d(cin, filters, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(filters)
        self.conv2 = nn.Conv2d(filters, filters, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(filters)
        self.conv3 = nn.Conv2d(filters, cout, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        y = torch.relu(self.bn1(self.conv1(x)))
        y = torch.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return torch.relu(y + idn)


class _TorchResNet50(nn.Module):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        cin = 64
        self.layers = nn.ModuleList()
        for i, blocks in enumerate([3, 4, 6, 3]):
            stage = nn.ModuleList()
            for j in range(blocks):
                stride = 2 if i > 0 and j == 0 else 1
                stage.append(_Bottleneck(cin, 64 * 2 ** i, stride))
                cin = 64 * 2 ** i * 4
            self.layers.append(stage)
        self.fc = nn.Linear(cin, num_classes)

    def forward(self, x):
        x = self.maxpool(torch.relu(self.bn1(self.conv1(x))))
        for stage in self.layers:
            for block in stage:
                x = block(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


def _conv(w):  # torch (O, I, H, W) -> flax (H, W, I, O)
    return w.detach().numpy().transpose(2, 3, 1, 0)


def _bn(mod):
    return ({"scale": mod.weight.detach().numpy(),
             "bias": mod.bias.detach().numpy()},
            {"mean": mod.running_mean.detach().numpy(),
             "var": mod.running_var.detach().numpy()})


def test_resnet50_forward_matches_torch():
    ref = _TorchResNet50()
    # Perturb BN running stats away from init (mean 0 / var 1) so the
    # inference-normalization path is actually exercised.
    g = torch.Generator().manual_seed(0)
    for m in ref.modules():
        if isinstance(m, nn.BatchNorm2d):
            m.running_mean.copy_(torch.randn(m.running_mean.shape,
                                             generator=g) * 0.1)
            m.running_var.copy_(1.0 + 0.2 * torch.rand(m.running_var.shape,
                                                       generator=g))
    ref.eval()

    params: dict = {}
    stats: dict = {}
    params["conv_stem"] = {"kernel": _conv(ref.conv1.weight)}
    params["bn_stem"], stats["bn_stem"] = _bn(ref.bn1)
    for i, stage in enumerate(ref.layers):
        for j, block in enumerate(stage):
            key = f"stage{i + 1}_block{j + 1}"
            p = {"conv1": {"kernel": _conv(block.conv1.weight)},
                 "conv2": {"kernel": _conv(block.conv2.weight)},
                 "conv3": {"kernel": _conv(block.conv3.weight)}}
            s = {}
            p["bn1"], s["bn1"] = _bn(block.bn1)
            p["bn2"], s["bn2"] = _bn(block.bn2)
            p["bn3"], s["bn3"] = _bn(block.bn3)
            if block.downsample is not None:
                p["downsample_conv"] = {
                    "kernel": _conv(block.downsample[0].weight)}
                p["downsample_bn"], s["downsample_bn"] = _bn(
                    block.downsample[1])
            params[key] = p
            stats[key] = s
    params["classifier"] = {"kernel": ref.fc.weight.detach().numpy().T,
                            "bias": ref.fc.bias.detach().numpy()}

    ours = models.get_model("resnet50", dtype=jnp.float32)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 64, 64, 3), np.float32)
    ours_logits = np.asarray(ours.apply(
        {"params": params, "batch_stats": stats},
        jnp.asarray(x), train=False))
    with torch.no_grad():
        ref_logits = ref(torch.tensor(x).permute(0, 3, 1, 2)).numpy()
    np.testing.assert_allclose(ours_logits, ref_logits, rtol=2e-4, atol=2e-4)
