"""ZeRO-2/3 sharding ladder (parallel/zero.py stages 2-3 + the overlapped
backward/collective schedule in train/steps.py).

Parity contract, same grounds as tests/test_zero1.py: ZeRO-2 is BITWISE
against zero1 for elementwise optimizers — its backward scatter runs the
IDENTICAL per-bucket ops as zero1's post-backward scatter, only earlier in
the schedule, and the update math never changes. ZeRO-3 is BITWISE against
the replicated path for SGD/AdamW on the CPU mesh (same psum chunk values,
same per-element update); LAMB is bounded-not-tight for the same
norm-summation-order reason test_zero1.py documents. The bitwise pins hold
at accum=1 (the configs here); gradient accumulation under the overlapped
schedule sums per-microbatch scatters in a different fp order (see
steps.accumulated_grads).

Memory ladder (with AdamW, N=8): replicated ~4P resident per device ->
zero1 2.25P -> zero2 1.375P -> zero3 0.5P — asserted monotonically on the
measured+modeled ``resident_bytes_per_device`` the run summaries and bench
records carry.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu import data as datalib
from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.models import model_spec
from distributeddeeplearning_tpu.observability import telemetry
from distributeddeeplearning_tpu.parallel import zero
from distributeddeeplearning_tpu.train import checkpoint as ckptlib
from distributeddeeplearning_tpu.train import loop

DATA_AXES = ("data", "fsdp")


def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


def _max_abs_diff(a, b) -> float:
    return max(float(jnp.max(jnp.abs(x - y)))
               for x, y in zip(_leaves(a), _leaves(b)))


def _cfg(opt_kw, sharding, **kw):
    base = dict(
        model="resnet18_thin", global_batch_size=16, dtype="float32",
        log_every=10**9, parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=32, num_classes=10),
        optimizer=OptimizerConfig(schedule="constant", **opt_kw),
        optimizer_sharding=sharding)
    base.update(kw)
    return TrainConfig(**base)


def _build(cfg, total_steps=4):
    spec = model_spec(cfg.model)
    mesh, model, batch_shd, state, train_step, sched, rng = loop.build(
        cfg, total_steps)
    source = datalib.make_source(cfg, spec.input_kind, batch_shd,
                                 objective=spec.objective)
    return state, train_step, source, rng


def _run(cfg, steps):
    state, train_step, source, rng = _build(cfg, steps)
    for i in range(steps):
        state, metrics = train_step(state, source.batch(i), rng)
    return state, train_step


def _full_params(state, train_step):
    """Replicated full-shape params regardless of stage (zero3 states hold
    1/N chunks; the converter gathers them)."""
    conv = getattr(train_step, "zero_converter", None)
    if conv is not None:
        state = conv.full_params_state(state)
    return jax.device_get(state.params)


# --------------------------------------------------------------------------
# Trajectory parity across the ladder.
# --------------------------------------------------------------------------

@pytest.mark.parametrize("opt_kw", [
    dict(name="sgd", learning_rate=0.1, momentum=0.9, weight_decay=1e-4),
    dict(name="adamw", learning_rate=1e-3, weight_decay=0.01),
], ids=["sgd_momentum", "adamw"])
def test_zero2_matches_zero1_bitwise(devices8, opt_kw):
    """zero2's overlapped backward scatter is the SAME per-bucket ops as
    zero1's post-backward scatter — params must agree bitwise, while the
    modeled resident grad bytes drop to 1/N (the full grad tree is never
    materialized)."""
    s1, step1 = _run(_cfg(opt_kw, "zero1"), 3)
    s2, step2 = _run(_cfg(opt_kw, "zero2"), 3)
    assert _max_abs_diff(_full_params(s1, step1),
                         _full_params(s2, step2)) == 0.0
    assert step2.zero_stage == "zero2" and step2.overlap
    assert step1.zero_stage == "zero1" and not step1.overlap
    assert step2.grad_bytes_per_device < step1.grad_bytes_per_device
    # 1/N up to per-leaf padding (each leaf pads by < N elements):
    layout = zero.build_layout(
        jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), s1.params), 8)
    assert step2.grad_bytes_per_device * 8 <= \
        step1.grad_bytes_per_device + 8 * 4 * layout.num_leaves


def test_zero2_serialized_schedule_bitwise(devices8):
    """--no-overlap-collectives is an A/B of the schedule only: the
    serialized zero2 step lands on the same params."""
    opt = dict(name="sgd", learning_rate=0.1, momentum=0.9)
    s1, step1 = _run(_cfg(opt, "zero1"), 2)
    s2, step2 = _run(_cfg(opt, "zero2", overlap_collectives=False), 2)
    assert not step2.overlap
    assert _max_abs_diff(_full_params(s1, step1),
                         _full_params(s2, step2)) == 0.0


@pytest.mark.parametrize("opt_kw", [
    dict(name="sgd", learning_rate=0.1, momentum=0.9, weight_decay=1e-4),
    dict(name="adamw", learning_rate=1e-3, weight_decay=0.01),
], ids=["sgd_momentum", "adamw"])
def test_zero3_matches_replicated_bitwise(devices8, opt_kw):
    """Full FSDP-style sharding: params live 1/N-chunked, gathered per
    bucket on demand — and the trajectory still matches the replicated
    path bitwise for elementwise optimizers (the gathered params ARE the
    replicated params; the scattered grads ARE the psum chunks)."""
    sr, step_r = _run(_cfg(opt_kw, "none"), 3)
    s3, step3 = _run(_cfg(opt_kw, "zero3"), 3)
    assert step3.zero_stage == "zero3" and step3.overlap
    assert _max_abs_diff(_full_params(sr, step_r),
                         _full_params(s3, step3)) == 0.0
    # Live zero3 param leaves really are 1/N resident per device.
    for leaf in _leaves(s3.params):
        assert leaf.addressable_shards[0].data.size == leaf.size // 8


@pytest.mark.slow
def test_zero3_lamb_bounded(devices8):
    """LAMB's trust ratio is a norm: zero3 computes it as
    sqrt(psum(partial)) whose fp summation order differs from the
    replicated full-leaf norm — bounded gap, not bitwise (same grounds and
    bound discipline as test_zero1.py's LAMB case)."""
    opt = dict(name="lamb", learning_rate=1e-3, weight_decay=0.01)
    sr, step_r = _run(_cfg(opt, "none"), 2)
    s3, step3 = _run(_cfg(opt, "zero3"), 2)
    gap = _max_abs_diff(_full_params(sr, step_r), _full_params(s3, step3))
    assert gap < 5e-3, f"zero3 LAMB diverged: {gap}"


# --------------------------------------------------------------------------
# The memory ladder: resident bytes per device fall monotonically.
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_resident_bytes_ladder_monotonic(devices8):
    """replicated -> zero1 -> zero2 -> zero3 strictly decreases the
    per-device resident footprint (params + modeled grads + opt state) —
    the acceptance ladder, on the same resident_bytes_per_device number
    run summaries and bench records report. AdamW so opt state is 2P."""
    opt = dict(name="adamw", learning_rate=1e-3, weight_decay=0.01)
    resident = {}
    for stage in ("none", "zero1", "zero2", "zero3"):
        state, train_step, _, _ = _build(_cfg(opt, stage), 2)
        stats = loop._device_memory_stats(state, train_step)
        resident[stage] = stats["resident_bytes_per_device"]
        assert stats["grads_bytes_per_device"] > 0
    assert resident["none"] > resident["zero1"] > resident["zero2"] \
        > resident["zero3"], resident
    # Coarse shape of the AdamW ladder (P params + 2P opt + grads):
    # zero1 saves the ~1.75P of opt state, zero2 the ~7/8 of grads too,
    # zero3 the ~7/8 of params as well — each step at least 20% down.
    for hi, lo in (("none", "zero1"), ("zero1", "zero2"),
                   ("zero2", "zero3")):
        assert resident[lo] < 0.8 * resident[hi], resident


def test_modeled_grad_bytes(devices8):
    """The grads component of the ladder is MODELED (grads are transient
    in a jit program): chunked = sum of chunk rows, full = sum of leaf
    bytes, chunked ~ full/N."""
    tree = {"a": jnp.zeros((33, 5)), "b": jnp.zeros((7,))}
    layout = zero.build_layout(tree, 8)
    full = zero.modeled_grad_bytes(layout, chunked=False)
    chunked = zero.modeled_grad_bytes(layout, chunked=True)
    assert full == (33 * 5 + 7) * 4
    assert chunked == sum(layout.chunk_sizes) * 4
    assert full < chunked * 8 <= full + 8 * 4 * layout.num_leaves


# --------------------------------------------------------------------------
# Overlap telemetry: the gauge reads the schedule, not wishful thinking.
# --------------------------------------------------------------------------

def test_overlap_fraction_unit():
    ev = [
        {"ph": "X", "name": "collective:zero2/reduce_scatter/bucket00",
         "args": {"overlapped": True, "cat": "trace"}},
        {"ph": "X", "name": "collective:zero1/reduce_scatter/bucket00",
         "args": {"cat": "trace"}},
        {"ph": "X", "name": "phase:dispatch"},
        {"ph": "M", "name": "collective:zero2/reduce_scatter/bucket01",
         "args": {"overlapped": True}},  # metadata, not a span
    ]
    assert telemetry.overlap_fraction(ev) == 0.5
    assert telemetry.overlap_fraction([]) == 0.0


def test_overlap_fraction_traced(devices8):
    """Tracing a zero2 step yields overlapped reduce-scatter spans
    (fraction 1.0); the zero1 schedule yields the same spans un-marked
    (fraction 0.0). Compile cache off: an AOT hit compiles nothing and
    trace-time spans never fire — the documented gauge caveat."""
    def traced_fraction(sharding):
        tele = telemetry.configure(enabled=True)
        try:
            opt = dict(name="sgd", learning_rate=0.1)
            cfg = _cfg(opt, sharding, compile_cache_dir="off")
            state, train_step, source, rng = _build(cfg, 2)
            state, _ = train_step(state, source.batch(0), rng)
            events = tele.snapshot()
            assert any("/reduce_scatter/" in e.get("name", "")
                       for e in events), "no scatter spans traced"
            return telemetry.overlap_fraction(events)
        finally:
            telemetry.reset()

    assert traced_fraction("zero2") == 1.0
    assert traced_fraction("zero1") == 0.0


# --------------------------------------------------------------------------
# Cross-stage checkpoint resume through the canonical layout.
# --------------------------------------------------------------------------

def _save_sharded(tmp_path, sharding, opt_kw, steps=2, **kw):
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    cfg = _cfg(opt_kw, sharding, **kw)
    state, train_step, source, rng = _build(cfg, steps + 2)
    for i in range(steps):
        state, _ = train_step(state, source.batch(i), rng)
    ckpt = Checkpointer(str(tmp_path / "ckpt"), every_steps=1,
                        converter=train_step.zero_converter)
    assert ckpt.maybe_save(int(state.step), state, force=True)
    ckpt.wait()
    ckpt.close()
    return cfg, state, train_step


def _restore(tmp_path, cfg):
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    state, train_step, source, rng = _build(cfg, 6)
    ck = Checkpointer(str(tmp_path / "ckpt"), every_steps=1,
                      converter=getattr(train_step, "zero_converter", None))
    restored = ck.restore_latest(state)
    ck.close()
    assert restored is not None
    return restored, train_step, source, rng


@pytest.mark.slow
def test_cross_stage_resume_from_zero3(devices8, tmp_path):
    """Save under zero3 on 8 shards (params AND opt state chunked on
    disk-side gather to canonical); restore (a) replicated dp=8,
    (b) zero2 dp=8, (c) zero3 dp=2. Params bitwise the save's full
    params everywhere; optimizer states agree in canonical form; one
    post-resume SGD step from (a) and (b) lands on identical params.

    Marked slow at ~59s (right at the 60s line): the zero2->zero3 edge
    keeps slow-tier coverage below, and the donation-safety bug class it
    guards stays pinned fast by test_zero1.py::test_cross_degree_resume."""
    opt = dict(name="sgd", learning_rate=0.1, momentum=0.9)
    cfg8, saved, step8 = _save_sharded(tmp_path, "zero3", opt)
    saved_params = _full_params(saved, step8)
    saved_canon = jax.device_get(
        step8.zero_converter.to_canonical(saved).opt_state)

    # (a) replicated, same degree.
    rest_r, step_r, source, rng_r = _restore(
        tmp_path, _cfg(opt, "none"))
    assert _max_abs_diff(jax.device_get(rest_r.params), saved_params) == 0.0
    assert _max_abs_diff(jax.device_get(rest_r.opt_state), saved_canon) == 0.0

    # (b) zero2, same degree: full params live, chunked opt state.
    rest_2, step_2, _, rng_2 = _restore(tmp_path, _cfg(opt, "zero2"))
    assert _max_abs_diff(jax.device_get(rest_2.params), saved_params) == 0.0
    assert _max_abs_diff(
        jax.device_get(step_2.zero_converter.to_canonical(
            rest_2).opt_state), saved_canon) == 0.0

    # (c) zero3 on HALF the degree: 1/2 chunks, same canonical content.
    cfg3 = _cfg(opt, "zero3", parallel=ParallelConfig(data=2),
                global_batch_size=16)
    rest_3, step_3, _, _ = _restore(tmp_path, cfg3)
    for leaf in _leaves(rest_3.params):
        assert leaf.addressable_shards[0].data.size == leaf.size // 2
    assert _max_abs_diff(_full_params(rest_3, step_3), saved_params) == 0.0
    assert _max_abs_diff(
        jax.device_get(step_3.zero_converter.to_canonical(
            rest_3).opt_state), saved_canon) == 0.0

    # Post-resume step parity (device_copy first: a warm AOT cache serves
    # donating executables, and orbax-restored buffers must not be donated
    # — tests/test_zero1.py::test_cross_degree_resume's bug class).
    rest_r = ckptlib.device_copy(rest_r)
    rest_2 = ckptlib.device_copy(rest_2)
    batch = source.batch(2)
    next_r, _ = step_r(rest_r, batch, rng_r)
    next_2, _ = step_2(rest_2, batch, rng_2)
    assert int(next_r.step) == int(next_2.step)
    assert _max_abs_diff(jax.device_get(next_r.params),
                         _full_params(next_2, step_2)) == 0.0


@pytest.mark.slow
def test_cross_stage_resume_zero2_to_zero3_adamw(devices8, tmp_path):
    """The remaining edge of the matrix: a zero2 AdamW checkpoint resumes
    under zero3 at the same degree, bitwise in canonical form, and the
    next step agrees with the zero2 continuation."""
    opt = dict(name="adamw", learning_rate=1e-3, weight_decay=0.01)
    cfg2, saved, step_s = _save_sharded(tmp_path, "zero2", opt)
    saved_params = _full_params(saved, step_s)
    saved_canon = jax.device_get(
        step_s.zero_converter.to_canonical(saved).opt_state)

    rest_3, step_3, source, rng_3 = _restore(tmp_path, _cfg(opt, "zero3"))
    assert _max_abs_diff(_full_params(rest_3, step_3), saved_params) == 0.0
    assert _max_abs_diff(
        jax.device_get(step_3.zero_converter.to_canonical(
            rest_3).opt_state), saved_canon) == 0.0

    rest_2, step_2, _, rng_2 = _restore(tmp_path, _cfg(opt, "zero2"))
    rest_2 = ckptlib.device_copy(rest_2)
    rest_3 = ckptlib.device_copy(rest_3)
    batch = source.batch(2)
    next_2, _ = step_2(rest_2, batch, rng_2)
    next_3, _ = step_3(rest_3, batch, rng_3)
    assert _max_abs_diff(_full_params(next_2, step_2),
                         _full_params(next_3, step_3)) == 0.0


# --------------------------------------------------------------------------
# Flags, guards, and the fsdp fold.
# --------------------------------------------------------------------------

def test_cli_flag_roundtrip():
    import train as train_cli

    cfg = train_cli.build_config(train_cli.parse_args(
        ["--optimizer-sharding", "zero3", "--no-overlap-collectives",
         "--opt-state-offload"]))
    assert cfg.optimizer_sharding == "zero3"
    assert cfg.overlap_collectives is False
    assert cfg.opt_state_offload is True
    # Defaults: overlap on, offload off, and zero2 parses.
    cfg = train_cli.build_config(train_cli.parse_args(
        ["--optimizer-sharding", "zero2"]))
    assert cfg.optimizer_sharding == "zero2"
    assert cfg.overlap_collectives is True
    assert cfg.opt_state_offload is False


def test_opt_state_offload_falls_back_on_cpu(devices8, capsys):
    """The CPU backend exposes no pinned_host memory kind: the offload
    request must degrade to a LOUD warning + normal device placement, not
    an error — the flag's contract on backends without host memory
    spaces (docs/zero_sharding.md caveats)."""
    opt = dict(name="sgd", learning_rate=0.1)
    cfg = _cfg(opt, "zero2", opt_state_offload=True)
    state, train_step, source, rng = _build(cfg, 2)
    err = capsys.readouterr().err
    assert "opt-state-offload" in err and "pinned_host" in err
    state, _ = train_step(state, source.batch(0), rng)  # still trains


def test_zero3_folds_fsdp_off_gspmd(devices8):
    """fsdp>1 alone forces the GSPMD path; with zero3 the bucket planner
    owns parameter sharding, so the same parallel config stays on the
    explicit-DP path (the sharding.py 'embed' rule folded into zero3) —
    and the dp axes product still drives the 1/N layout."""
    opt = dict(name="sgd", learning_rate=0.1)
    fsdp = ParallelConfig(data=4, fsdp=2)
    assert loop.uses_gspmd(_cfg(opt, "none", parallel=fsdp), "image")
    cfg = _cfg(opt, "zero3", parallel=fsdp)
    assert not loop.uses_gspmd(cfg, "image")
    state, train_step, source, rng = _build(cfg, 2)
    assert train_step.zero_stage == "zero3"
    for leaf in _leaves(state.params):
        assert leaf.addressable_shards[0].data.size == leaf.size // 8
    state, _ = train_step(state, source.batch(0), rng)


def test_sharding_sidecar_written(devices8, tmp_path, monkeypatch):
    """loop._write_sharding_sidecar: the doctor-readable record of which
    sharding the last run actually used."""
    opt = dict(name="sgd", learning_rate=0.1)
    cfg = _cfg(opt, "zero2")
    state, train_step, _, _ = _build(cfg, 2)
    path = tmp_path / "side.json"
    monkeypatch.setattr(loop, "_sharding_sidecar_path", lambda: str(path))
    loop._write_sharding_sidecar(cfg, train_step, 0.75)
    side = json.loads(path.read_text())
    assert side["optimizer_sharding"] == "zero2"
    assert side["overlap"] is True
    assert side["overlap_fraction"] == 0.75
    assert side["dp"] == 8
