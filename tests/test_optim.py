"""Optimizer/schedule unit tests (SURVEY.md §4): LARS trust-ratio math on toy
tensors, schedule shapes, linear-scaling rule, decay masking."""

import jax
import jax.numpy as jnp
import pytest
import numpy as np

from distributeddeeplearning_tpu.config import OptimizerConfig
from distributeddeeplearning_tpu.train import optim


pytestmark = pytest.mark.core

def test_linear_scaling_rule():
    cfg = OptimizerConfig(learning_rate=0.1, reference_batch=256)
    assert optim.scaled_lr(cfg, 256) == 0.1
    assert abs(optim.scaled_lr(cfg, 32768) - 12.8) < 1e-9


def test_warmup_cosine_shape():
    cfg = OptimizerConfig(schedule="warmup_cosine", warmup_epochs=5)
    sched = optim.make_schedule(cfg, 256, total_steps=1000, steps_per_epoch=10)
    assert float(sched(0)) == 0.0
    peak = optim.scaled_lr(cfg, 256)
    np.testing.assert_allclose(float(sched(50)), peak, rtol=1e-6)
    assert float(sched(999)) < peak * 0.01 + 1e-6


def test_warmup_poly_lars_schedule():
    cfg = OptimizerConfig(name="lars", schedule="warmup_poly",
                          learning_rate=29.0, reference_batch=32768,
                          warmup_epochs=5)
    sched = optim.make_schedule(cfg, 32768, total_steps=100,
                                steps_per_epoch=4)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(20)), 29.0, rtol=1e-6)
    assert float(sched(100)) <= 1e-6


def test_decay_mask_excludes_bn_and_bias():
    params = {
        "conv": {"kernel": jnp.ones((3, 3, 1, 1))},
        "bn": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
        "dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.zeros((4,))},
        "word_embeddings": jnp.ones((10, 4)),
    }
    mask = optim._decay_mask(params)
    assert mask["conv"]["kernel"] is True
    assert mask["bn"]["scale"] is False
    assert mask["bn"]["bias"] is False
    assert mask["dense"]["kernel"] is True
    assert mask["dense"]["bias"] is False
    assert mask["word_embeddings"] is True


def test_decay_mask_frozen_matches_plain():
    """FrozenDict and plain-dict params produce the SAME decisions and a
    mask with the SAME treedef as the input — optax's masking zips mask
    and update trees, so a plain mask over frozen params is a structure
    mismatch (the flax .init default before unfreezing)."""
    import flax

    plain = {
        "embed": {"embedding": jnp.ones((10, 4))},          # nn.Embed name
        "block": {"conv": {"kernel": jnp.ones((3, 3, 1, 1))},
                  "norm": {"scale": jnp.ones((4,)),
                           "bias": jnp.zeros((4,))}},
        "head": {"kernel": jnp.ones((4, 2)), "bias": jnp.zeros((2,))},
    }
    frozen = flax.core.freeze(plain)
    m_plain = optim._decay_mask(plain)
    m_frozen = optim._decay_mask(frozen)

    assert isinstance(m_frozen, flax.core.FrozenDict)
    assert (jax.tree_util.tree_structure(m_plain)
            == jax.tree_util.tree_structure(plain))
    assert (jax.tree_util.tree_structure(m_frozen)
            == jax.tree_util.tree_structure(frozen))
    # identical per-leaf decisions either way
    assert (jax.tree_util.tree_leaves(m_plain)
            == jax.tree_util.tree_leaves(m_frozen))
    # decay on kernels/embeddings, none on norm scales or any bias
    assert m_frozen["embed"]["embedding"] is True
    assert m_frozen["block"]["conv"]["kernel"] is True
    assert m_frozen["block"]["norm"]["scale"] is False
    assert m_frozen["block"]["norm"]["bias"] is False
    assert m_frozen["head"]["kernel"] is True
    assert m_frozen["head"]["bias"] is False
    # and optax accepts the frozen mask against frozen params end-to-end
    import optax
    tx = optax.add_decayed_weights(0.1, mask=optim._decay_mask)
    updates, _ = tx.update(jax.tree_util.tree_map(jnp.zeros_like, frozen),
                           tx.init(frozen), frozen)
    assert float(jnp.abs(updates["block"]["norm"]["scale"]).max()) == 0.0
    assert float(jnp.abs(updates["head"]["kernel"]).max()) > 0.0


def test_lars_trust_ratio_toy():
    """LARS update magnitude ~ lr * trust_coeff * ||w|| / ||g|| * ||g||."""
    import optax
    cfg = OptimizerConfig(name="lars", schedule="constant", learning_rate=1.0,
                          reference_batch=256, momentum=0.0,
                          weight_decay=0.0, trust_coefficient=0.01)
    tx, _ = optim.make_optimizer(cfg, 256, total_steps=10)
    params = {"dense": {"kernel": jnp.full((4, 4), 2.0)}}
    grads = {"dense": {"kernel": jnp.full((4, 4), 0.5)}}
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    u = updates["dense"]["kernel"]
    w_norm = float(jnp.linalg.norm(params["dense"]["kernel"]))
    g_norm = float(jnp.linalg.norm(grads["dense"]["kernel"]))
    expected = -1.0 * cfg.trust_coefficient * w_norm / g_norm * 0.5
    np.testing.assert_allclose(np.asarray(u), expected, rtol=1e-5)


def test_sgd_momentum_step():
    cfg = OptimizerConfig(name="sgd", schedule="constant", learning_rate=0.1,
                          reference_batch=256, momentum=0.9,
                          weight_decay=0.0)
    tx, _ = optim.make_optimizer(cfg, 256, total_steps=10)
    params = {"dense": {"kernel": jnp.ones((2,))}}
    grads = {"dense": {"kernel": jnp.ones((2,))}}
    state = tx.init(params)
    updates, state = tx.update(grads, state, params)
    np.testing.assert_allclose(np.asarray(updates["dense"]["kernel"]), -0.1,
                               rtol=1e-6)
    updates, state = tx.update(grads, state, params)
    # second step: momentum buffer = 1*0.9 + 1 = 1.9 -> update = -0.19
    np.testing.assert_allclose(np.asarray(updates["dense"]["kernel"]), -0.19,
                               rtol=1e-6)


def test_lamb_optimizer_steps():
    """LAMB builds and reduces loss on a toy quadratic."""
    import jax
    import jax.numpy as jnp
    from distributeddeeplearning_tpu.config import OptimizerConfig
    from distributeddeeplearning_tpu.train import optim

    cfg = OptimizerConfig(name="lamb", learning_rate=0.1, reference_batch=1,
                          schedule="constant", weight_decay=0.01)
    tx, _ = optim.make_optimizer(cfg, global_batch=1, total_steps=10)
    params = {"layer": {"kernel": jnp.ones((4, 4)), "bias": jnp.zeros((4,))}}
    opt_state = tx.init(params)

    def loss_fn(p):
        return (p["layer"]["kernel"] ** 2).sum() + (p["layer"]["bias"] ** 2).sum()

    first = float(loss_fn(params))
    for _ in range(5):
        grads = jax.grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax
        params = optax.apply_updates(params, updates)
    assert float(loss_fn(params)) < first
