"""Serve fast path: COW radix prefix cache + speculative decoding.

The load-bearing pin is unchanged from test_serve.py — TOKEN IDENTITY
against sequential ``generate(use_cache=True)`` — but now with the two
fast-path features on: shared prefix pages mapped by refcount instead of
re-prefilled (partial trailing page copy-on-write), and a shrunk
same-family drafter proposing k tokens per target verify. Either feature
wrong changes tokens; both right, they only change *speed*. Around the
pin: allocator refcount units (share / double-decref / write-to-shared /
multiset leak check), radix-tree units (match / insert / LRU evict /
evictable accounting), eviction under pool pressure, the AOT warm boot
of every fast-path program, the spec-acceptance anomaly kind, and the
replica-SIGKILL chaos soak with both features on.
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from distributeddeeplearning_tpu.models import generate as genlib
from distributeddeeplearning_tpu.serve import kv_cache
from distributeddeeplearning_tpu.serve.engine import (Engine, ServeConfig,
                                                      serve_fingerprint)
from distributeddeeplearning_tpu.serve.scheduler import (SloScheduler,
                                                         TenantPolicy)

pytestmark = pytest.mark.serve

VOCAB = 97


def _engine(model="gpt_tiny", **kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 32)
    kw.setdefault("max_pages_per_slot", 8)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("compile_cache_dir", "off")
    t = [0.0]

    def clock():
        t[0] += 0.001
        return t[0]

    return Engine(ServeConfig(model=model, **kw), clock=clock)


def _reference_tokens(eng, prompt, max_new):
    out = genlib.generate(eng.model, {**eng._fresh},
                          jnp.asarray([prompt], jnp.int32),
                          max_new_tokens=max_new, use_cache=True)
    return [int(x) for x in np.asarray(out)[0, len(prompt):]]


def _shared_prefix_prompts(rng, head_len=9, tails=(5, 5, 5)):
    """One shared head + distinct tails: the serving-traffic shape the
    radix cache exists for. head_len=9 with page_size=4 leaves a partial
    trailing chunk, so admission exercises the COW path too. Tails are
    equal-length (distinct content) so the reference generate() compiles
    one prompt shape, not one per request."""
    head = [int(x) for x in rng.integers(1, VOCAB, head_len)]
    return [head + [int(x) for x in rng.integers(1, VOCAB, t)]
            for t in tails]


# --- allocator refcount units -----------------------------------------------

def test_allocator_share_refuses_writes_and_double_decref():
    alloc = kv_cache.PageAllocator(4)
    (p,) = alloc.alloc(1)
    alloc.assert_writable([p])  # exclusive: in-place writes legal
    alloc.incref([p])           # second holder (tree node / shared slot)
    assert alloc.refcount(p) == 2
    with pytest.raises(RuntimeError, match="shared page"):
        alloc.assert_writable([p])
    # First decref drops to 1 (still held), second frees, third raises.
    alloc.decref([p])
    assert alloc.refcount(p) == 1 and alloc.free_pages == 3
    alloc.assert_writable([p])  # back to exclusive
    alloc.decref([p])
    assert alloc.free_pages == 4
    with pytest.raises(ValueError, match="double-decref"):
        alloc.decref([p])
    # Sharing can only extend a LIVE allocation.
    with pytest.raises(ValueError, match="not currently allocated"):
        alloc.incref([p])


def test_allocator_check_leaks_is_multiset_aware():
    """A page shared by a slot AND the tree must appear once per claim in
    the owned multiset — shared-but-live balances, a dropped claim or an
    unshared double-owner still fails loudly (exact message prefixes are
    load-bearing: the chaos sweep tests match on them)."""
    alloc = kv_cache.PageAllocator(4)
    (a, b) = alloc.alloc(2)
    alloc.incref([a])  # a: slot + tree
    alloc.check_leaks([a, a, b])        # balanced multiset
    with pytest.raises(RuntimeError, match="KV page leak"):
        alloc.check_leaks([a, b])       # one of a's two claims dropped
    with pytest.raises(RuntimeError, match="page-table corruption"):
        alloc.check_leaks([a, a, b, b])  # b double-owned without a share
    alloc.decref([a])
    alloc.check_leaks([a, b])


# --- radix tree units -------------------------------------------------------

def test_radix_match_insert_full_pages_only():
    alloc = kv_cache.PageAllocator(8)
    tree = kv_cache.RadixPrefixCache(alloc, page_size=4)
    ids = list(range(1, 11))       # 10 tokens: 2 full pages + partial
    pages = alloc.alloc(3)
    assert tree.insert(ids, pages) == 2     # the partial chunk never enters
    assert [alloc.refcount(p) for p in pages] == [2, 2, 1]
    matched, shared = tree.match(ids)
    assert matched == 8 and shared == pages[:2]
    # Diverging token in the second chunk: only the first page matches.
    fork = ids[:5] + [77] + ids[6:]
    matched, shared = tree.match(fork)
    assert matched == 4 and shared == [pages[0]]
    assert tree.match([50, 51]) == (0, [])
    # Re-inserting the same prompt creates nothing and bumps no refcount.
    assert tree.insert(ids, pages) == 0
    assert [alloc.refcount(p) for p in pages] == [2, 2, 1]


def test_radix_evict_lru_and_refcount_pinning():
    alloc = kv_cache.PageAllocator(8)
    tree = kv_cache.RadixPrefixCache(alloc, page_size=2)
    old = alloc.alloc(1)
    new = alloc.alloc(1)
    tree.insert([1, 2], old)
    tree.insert([3, 4], new)
    tree.match([3, 4])            # refresh: [3,4] is now most-recent
    alloc.decref(old + new)       # tree holds the only claims
    assert tree.evictable_pages() == 2
    assert tree.evict(1) == 1     # LRU order: [1,2] goes first
    assert tree.evictions == 1
    assert tree.match([1, 2]) == (0, [])
    assert tree.match([3, 4])[0] == 2
    # A page a live slot still maps is pinned: eviction comes up short.
    alloc.incref([tree.match([3, 4])[1][0]])
    assert tree.evictable_pages() == 0
    assert tree.evict(1) == 0
    assert tree.num_nodes() == 1


def test_radix_evict_cascades_into_parents():
    alloc = kv_cache.PageAllocator(8)
    tree = kv_cache.RadixPrefixCache(alloc, page_size=2)
    pages = alloc.alloc(2)
    tree.insert([1, 2, 3, 4], pages)   # chain: [1,2] -> [3,4]
    alloc.decref(pages)
    # The parent only becomes a leaf once its child is gone; evict(2)
    # must free both in one call.
    assert tree.evict(2) == 2
    assert tree.num_nodes() == 0 and alloc.free_pages == 8


# --- token identity: prefix cache -------------------------------------------

@pytest.mark.parametrize("model", ["gpt_tiny", "llama_tiny"])
def test_prefix_cache_token_identity_and_reuse(model):
    """Shared-head requests through a prefix-cache engine: every stream
    must equal its solo sequential run, later admissions must HIT (shared
    pages mapped, only the tail prefilled), and the partial trailing page
    must be COW'd — identity plus the counters that prove the fast path
    actually engaged."""
    eng = _engine(model, prefix_cache=True)
    rng = np.random.default_rng(3)
    prompts = _shared_prefix_prompts(rng)
    # Sequential submission so request 0 populates the tree first.
    reqs = []
    for p in prompts:
        r = eng.submit(p, max_new_tokens=5)
        reqs.append(r)
        eng.run_until_idle()
    for r in reqs:
        assert r.tokens == _reference_tokens(eng, r.prompt, 5), r.uid
    assert eng.prefix_hits == 2 and eng.prefix_misses == 1
    assert eng.prefix_tokens_reused == 16  # 2 hits x 2 full head pages
    assert eng.cow_copies == 0  # head is 9 tokens: matched 8 is page-aligned
    eng.shutdown()  # leak gate with tree pages still live


def test_prefix_cache_cow_on_partial_trailing_page():
    """A fully-cached page-aligned prompt re-submitted: the engine may
    reuse at most plen-1 tokens (the last position must re-run to emit
    the first token), which lands mid-page — that page MUST be cloned,
    not written in place, and tokens must not change."""
    eng = _engine("gpt_tiny", prefix_cache=True)
    prompt = list(range(1, 9))  # 8 tokens: exactly 2 full pages
    a = eng.submit(prompt, max_new_tokens=5)
    eng.run_until_idle()
    b = eng.submit(prompt, max_new_tokens=5)
    eng.run_until_idle()
    assert eng.cow_copies == 1 and eng.prefix_hits == 1
    ref = _reference_tokens(eng, prompt, 5)
    assert a.tokens == ref and b.tokens == ref
    eng.shutdown()


def test_prefix_cache_eviction_under_pool_pressure():
    """A pool too small to hold every retired prefix: admission must
    evict LRU tree pages instead of failing, tokens stay identical, and
    the drain leak-check passes with shared pages still in the tree."""
    eng = _engine("gpt_tiny", max_slots=1, num_pages=4,
                  prefix_cache=True, prefill_buckets=(8,))
    rng = np.random.default_rng(5)
    reqs = []
    for i in range(4):
        p = [int(x) for x in rng.integers(1, VOCAB, 6)]
        r = eng.submit(p, max_new_tokens=4)
        reqs.append((r, p))
        eng.run_until_idle()
    assert eng.prefix.evictions > 0
    for r, p in reqs:
        assert r.tokens == _reference_tokens(eng, p, 4)
    assert eng.prefix.num_nodes() > 0  # shared pages live at drain...
    eng.shutdown()                     # ...and the multiset check passes


# --- token identity: speculative decoding -----------------------------------

@pytest.mark.parametrize("model,draft", [("gpt_tiny", "gpt_nano")])
def test_spec_decode_token_identity_nano_drafter(model, draft):
    """Drafter proposals verified by the target: output must be bitwise
    the target's greedy stream no matter what the drafter proposes.
    (llama+nano spec identity is pinned by the preemption test below,
    which runs both features for both families.)"""
    eng = _engine(model, spec_draft_model=draft, spec_k=3)
    rng = np.random.default_rng(7)
    reqs = [eng.submit([int(x) for x in rng.integers(1, VOCAB, n)],
                       max_new_tokens=7) for n in (8, 8)]
    eng.run_until_idle()
    for r in reqs:
        assert r.tokens == _reference_tokens(eng, r.prompt, 7), r.uid
    assert eng.spec_rounds > 0 and eng.spec_proposed > 0
    assert 0 <= eng.spec_accepted <= eng.spec_proposed
    eng.shutdown()


def test_spec_decode_self_draft_accepts_everything():
    """Drafter == target (same seed, bitwise-equal params): every
    proposal matches the target's argmax, acceptance is exactly 1.0 —
    the upper bound that pins the accept/emit bookkeeping."""
    eng = _engine("gpt_tiny", spec_draft_model="gpt_tiny", spec_k=4)
    r = eng.submit(list(range(1, 7)), max_new_tokens=8)
    eng.run_until_idle()
    assert r.tokens == _reference_tokens(eng, r.prompt, 8)
    assert eng.spec_proposed > 0
    assert eng.spec_accepted == eng.spec_proposed
    eng.shutdown()


# --- both features + preemption ---------------------------------------------

@pytest.mark.parametrize("model,draft", [("gpt_tiny", "gpt_nano"),
                                         ("llama_tiny", "llama_nano")])
def test_fast_path_preemption_resumes_token_identical(model, draft):
    """Prefix cache AND spec decoding on, a victim preempted mid-decode:
    the resume (prefix folded, drafter re-prefilled, shared pages
    re-mapped) must finish with exactly the uninterrupted tokens."""
    # num_pages=8: rt's 5-page ask cannot fit beside bg's 4 pages, so the
    # budget-tightened bg slot must actually be preempted (the same
    # geometry as test_serve.py's prefix-off preemption pin).
    eng = _engine(model, num_pages=8, prefix_cache=True,
                  spec_draft_model=draft, spec_k=3)
    rng = np.random.default_rng(11)
    bg_prompt = [int(x) for x in rng.integers(1, VOCAB, 4)]
    bg = eng.submit(bg_prompt, max_new_tokens=12, tenant="bg")
    eng.step()
    eng.step()
    assert eng.num_live == 1 and len(bg.tokens) >= 1

    eng.scheduler.policies["bg"] = TenantPolicy("bg", max_pages=3)
    rt_prompt = [int(x) for x in rng.integers(1, VOCAB, 8)]
    rt = eng.submit(rt_prompt, max_new_tokens=12, tenant="rt")
    for _ in range(8):
        if eng.preemptions:
            break
        eng.step()
    assert eng.preemptions == 1 and bg.preemptions == 1

    del eng.scheduler.policies["bg"]
    eng.run_until_idle()
    assert rt.tokens == _reference_tokens(eng, rt_prompt, 12)
    assert bg.tokens == _reference_tokens(eng, bg_prompt, 12)
    eng.shutdown()


# --- AOT warm boot of the fast-path programs --------------------------------

def test_fast_path_aot_warm_boot_zero_retrace(tmp_path):
    """Both features on: the block-prefill, page-clone, draft, and verify
    programs all ride the serve fingerprint — a second engine must
    deserialize every one (zero retraces) and decode identically."""
    kw = dict(max_slots=2, page_size=4, num_pages=16, max_pages_per_slot=4,
              prefill_buckets=(8,), prefix_cache=True,
              spec_draft_model="gpt_nano", spec_k=3,
              compile_cache_dir=str(tmp_path))
    cold = _engine("gpt_tiny", **kw)
    stats = cold.warmup()
    assert stats["aot_misses"] == stats["aot_saves"] > 2  # > base engine
    prompt = list(range(1, 7))
    cold_req = cold.submit(prompt, max_new_tokens=5)
    cold.run_until_idle()

    warm = _engine("gpt_tiny", **kw)
    wstats = warm.warmup()
    assert wstats["aot_misses"] == 0
    assert wstats["aot_hits"] == stats["aot_misses"]
    warm_req = warm.submit(prompt, max_new_tokens=5)
    warm.run_until_idle()
    assert warm_req.tokens == cold_req.tokens


def test_fast_path_fields_extend_serve_fingerprint():
    base = ServeConfig()
    assert serve_fingerprint(base) != serve_fingerprint(
        dataclasses.replace(base, prefix_cache=True))
    assert serve_fingerprint(base) != serve_fingerprint(
        dataclasses.replace(base, spec_draft_model="gpt_nano", spec_k=3))


# --- spec-acceptance anomaly kind -------------------------------------------

def test_anomaly_spec_acceptance_collapse_fires_and_stays_quiet():
    from distributeddeeplearning_tpu.observability import anomaly
    det = anomaly.AnomalyDetector()
    # Healthy soak at ~80% acceptance: never fires.
    for s in range(1, 13):
        assert det.update_serve(s, spec_proposed=16, spec_accepted=13) == []
    # Below-volume interval stays quiet (one unlucky round is not drift).
    assert det.update_serve(13, spec_proposed=2, spec_accepted=0) == []
    out = det.update_serve(14, spec_proposed=16, spec_accepted=1)
    assert [a["kind"] for a in out] == ["spec_acceptance_collapse"]
    # A drafter that was never any good is a config problem, not an
    # anomaly: median below the floor keeps the kind silent forever.
    det2 = anomaly.AnomalyDetector()
    for s in range(1, 13):
        assert det2.update_serve(s, spec_proposed=16, spec_accepted=1) == []
    assert det2.update_serve(13, spec_proposed=16, spec_accepted=0) == []


# --- chaos soak: replica SIGKILL with both features on ----------------------

@pytest.mark.chaos
@pytest.mark.slow
def test_fast_path_chaos_soak_sigkill_token_identical(tmp_path):
    """SIGKILL a replica mid-stream with prefix cache + spec decoding on:
    re-dispatched victims must replay token-identically (the survivor's
    radix tree and drafter state are its own — correctness can't depend
    on the dead replica's cache), and every replica's drain leak-check
    must pass with shared tree pages live.

    Marked slow: ~20s of process-boot + compile on the 1-vCPU box, and
    tier-1's budget is already carried by test_serve.py's fast chaos
    soak (same supervised SIGKILL path, fast-path features off)."""
    import os

    from distributeddeeplearning_tpu import launch as launchlib
    from distributeddeeplearning_tpu.observability import flight as flightlib

    cfg = ServeConfig(model="gpt_tiny", vocab_size=VOCAB, max_slots=2,
                      page_size=4, num_pages=32, max_pages_per_slot=8,
                      prefill_buckets=(16,), prefix_cache=True,
                      spec_draft_model="gpt_nano", spec_k=3,
                      compile_cache_dir=str(tmp_path / "aot"))
    head = [(3 * j) % (VOCAB - 1) + 1 for j in range(6)]
    prompts = [head + [(7 * i + j) % (VOCAB - 1) + 1
                       for j in range(2 + i % 3)] for i in range(4)]

    ref = Engine(cfg)
    for p in prompts:
        ref.submit(p, max_new_tokens=6)
    ref.run_until_idle()
    expected = {r.uid: list(r.tokens) for r in ref.finished}
    ref.shutdown()

    requests = [{"uid": i, "prompt": prompts[i], "max_new_tokens": 6}
                for i in range(4)]
    try:
        out = launchlib.run_serve(
            2, requests, dataclasses.asdict(cfg),
            workdir=str(tmp_path / "serve"),
            heartbeat_dir=str(tmp_path / "hb"),
            max_restarts=1, child_fault_plans={0: "sigkill@3"},
            flight_dir=str(tmp_path / "flight"), timeout_s=150.0)
    finally:
        flightlib.reset()
        os.environ.pop(flightlib.ENV_FLIGHT_DIR, None)
        os.environ.pop(flightlib.ENV_RUN_ID, None)

    for uid, exp in expected.items():
        res = out["results"][uid]
        assert res["finished"] and res["failed"] is None
        assert res["tokens"] == exp, f"request {uid} diverged after replay"
    assert out["restarts"] == 1 and out["redispatched"] >= 1
    assert out["leak_check_ok"] is True
    assert out["replica_rcs"] == {0: 0, 1: 0}
