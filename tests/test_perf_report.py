"""The perf-record schema contract (observability/perf_report.py): the
provenance rules every measurement surface emits under, pinned against
synthetic records shaped like the real BENCH_r01-r05 artifacts — the
driver rounds whose stale-vs-current ambiguity motivated the schema."""

import json
import time

import pytest

from distributeddeeplearning_tpu.observability import perf_report


# --- provenance classification ---------------------------------------------

def test_classify_age_bands():
    assert perf_report.classify_age(0.0) == "stale"
    assert perf_report.classify_age(3600.0) == "stale"
    assert perf_report.classify_age(24 * 3600.0) == "stale"  # inclusive cap
    assert perf_report.classify_age(24 * 3600.0 + 1) == "expired"
    # Unknown age is indistinguishable from arbitrarily old.
    assert perf_report.classify_age(None) == "expired"
    # Cap is a parameter, not a constant.
    assert perf_report.classify_age(100.0, max_stale_age_s=50.0) == "expired"


def test_cached_record_is_never_fresh():
    """THE rule of the schema: a record rebuilt from any cache may be
    stale or expired, never fresh — whatever its age."""
    prior = {"metric": "m", "value": 2366.0, "vs_baseline": 1.63,
             "measured_at": "2026-07-31 03:52:00"}
    for age in (0.0, 1.0, 3600.0, 92824.0, None):
        rec = perf_report.stale_record(prior, age)
        assert rec["provenance"] in ("stale", "expired")
        assert rec["provenance"] != "fresh"


def test_stale_record_keeps_vs_baseline_within_cap():
    prior = {"metric": "m", "value": 2366.0, "vs_baseline": 1.63}
    rec = perf_report.stale_record(prior, 3600.0)
    assert rec["provenance"] == "stale"
    assert rec["stale_age_s"] == 3600
    assert rec["vs_baseline"] == 1.63
    assert prior.get("provenance") is None  # input not mutated


def test_expired_record_loses_vs_baseline():
    """r05 shape: stale_age_s 92824 (> 24h) — the cached number must stop
    scoring against the V100 target as if it were current."""
    prior = {"metric": "resnet50_imagenet_images_per_sec_per_chip",
             "value": 2366.0, "vs_baseline": 1.63,
             "measured_at": "2026-07-31 03:52:00"}
    rec = perf_report.stale_record(prior, 92824.0)
    assert rec["provenance"] == "expired"
    assert "vs_baseline" not in rec
    assert rec["stale_age_s"] == 92824
    assert not perf_report.validate(rec)


def test_measurement_age_parses_last_good_stamp():
    now = time.time()
    stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(now - 7200))
    age = perf_report.measurement_age_s(stamp, now=now)
    assert age == pytest.approx(7200, abs=2)
    assert perf_report.measurement_age_s(None) is None
    assert perf_report.measurement_age_s("not a date") is None
    # A clock that ran backwards must not yield a negative age.
    future = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(now + 9999))
    assert perf_report.measurement_age_s(future, now=now) == 0.0


# --- annotate + validate ----------------------------------------------------

def test_annotate_stamps_schema_and_rejects_bad_provenance():
    rec = perf_report.annotate({"value": 1.0}, provenance="fresh")
    assert rec["schema_version"] == perf_report.SCHEMA_VERSION
    assert rec["provenance"] == "fresh"
    with pytest.raises(ValueError):
        perf_report.annotate({}, provenance="cached")  # not a state


def test_annotate_attempts_and_backend_identity():
    rec = perf_report.annotate(
        {"value": 2.0}, provenance="fresh",
        attempts=[{"attempt": 1, "rc": "timeout 480s"},
                  {"attempt": 2, "rc": "up"}])
    assert [a["attempt"] for a in rec["attempts"]] == [1, 2]
    # conftest pins JAX_PLATFORMS=cpu with 8 fake devices.
    assert rec["backend"]["platform"] == "cpu"
    assert rec["backend"]["device_count"] == 8
    jaxfree = perf_report.annotate({"value": 2.0}, provenance="fresh",
                                   with_backend=False)
    assert "backend" not in jaxfree


def test_annotate_config_fingerprint_matches_aot():
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.perf import aot as aotlib
    cfg = TrainConfig(model="resnet18_thin", global_batch_size=8)
    rec = perf_report.annotate({"value": 1.0}, provenance="fresh",
                               config=cfg, total_steps=10)
    assert rec["config_fingerprint"] == aotlib.config_fingerprint(
        cfg, total_steps=10)


def test_validate_fresh_rules():
    assert not perf_report.validate({"provenance": "fresh", "value": 9.0})
    # Summaries measure through other keys; no explicit value is fine.
    assert not perf_report.validate({"provenance": "fresh",
                                     "examples_per_sec": 100.0})
    assert perf_report.validate({"provenance": "fresh", "value": None})
    assert perf_report.validate({"provenance": "fresh", "value": 9.0,
                                 "stale_age_s": 60})


def test_validate_error_and_stale_rules():
    assert not perf_report.validate(
        {"provenance": "error", "value": None, "error": "tunnel down"})
    assert perf_report.validate({"provenance": "error", "value": 5.0,
                                 "error": "x"})
    assert perf_report.validate({"provenance": "error", "value": None})
    assert perf_report.validate({"provenance": "stale", "value": 5.0})
    assert perf_report.validate({"provenance": "expired", "value": 5.0,
                                 "stale_age_s": 1e6,
                                 "vs_baseline": 1.63})
    assert perf_report.validate({"provenance": None})
    assert perf_report.validate({})


# --- roofline ---------------------------------------------------------------

def test_roofline_matches_flops_tables():
    from distributeddeeplearning_tpu.models import flops as flopslib
    per_ex = flopslib.train_flops_per_example("resnet50")
    out = perf_report.roofline(2366.0, "resnet50", device_kind="TPU v5e")
    assert out["tflops_per_sec"] == round(2366.0 * per_ex / 1e12, 2)
    peak = flopslib.bf16_peak_flops("TPU v5e")
    assert out["pct_of_peak"] == round(100.0 * 2366.0 * per_ex / peak, 1)
    assert out["bf16_peak_tflops"] == round(peak / 1e12, 0)


def test_roofline_unknowns_degrade_not_raise():
    assert perf_report.roofline(None, "resnet50") == {}
    assert perf_report.roofline(10.0, "no_such_model") == {}
    out = perf_report.roofline(10.0, "resnet50", device_kind="cpu")
    assert "tflops_per_sec" in out and "pct_of_peak" not in out


# --- r01-r05-shaped synthetic records ---------------------------------------

def _r04_style_error_record(max_age):
    """Rebuild the r04/r05 artifact shape through the schema helpers the
    way bench.py's parent does."""
    prior = {"metric": "resnet50_imagenet_images_per_sec_per_chip",
             "value": 2366.0, "unit": "images_per_sec_per_chip",
             "vs_baseline": 1.63, "protocol": "w11+30 b512",
             "measured_at": "2026-07-31 03:52:00"}
    age = 92824.0
    rec = {"metric": prior["metric"], "value": None,
           "unit": prior["unit"], "vs_baseline": None,
           "error": ("attempt 1: rc=preflight 75s: backend never came up "
                     "(tunnel presumed down)"),
           "last_measured_on_live_chip":
               perf_report.stale_record(prior, age, max_age),
           "stale_age_s": int(age)}
    return perf_report.annotate(
        rec, provenance="error",
        attempts=[{"attempt": 1, "rc": "preflight 75s"}],
        with_backend=False)


def test_r04_shape_error_record_validates_and_labels_cache():
    rec = _r04_style_error_record(max_age=24 * 3600.0)
    assert not perf_report.validate(rec)
    assert rec["provenance"] == "error"
    embedded = rec["last_measured_on_live_chip"]
    assert embedded["provenance"] == "expired"  # 92824s > 24h
    assert "vs_baseline" not in embedded
    assert not perf_report.validate(embedded)
    # Raising the cap past the age keeps the cache comparable.
    young = _r04_style_error_record(max_age=7 * 24 * 3600.0)
    assert young["last_measured_on_live_chip"]["provenance"] == "stale"
    assert young["last_measured_on_live_chip"]["vs_baseline"] == 1.63
    # The whole artifact round-trips as one JSON line (driver contract).
    assert json.loads(perf_report.dumps(rec))["provenance"] == "error"


def test_git_rev_reads_head():
    rev = perf_report.git_rev()
    # This repo IS a git checkout; the rev must resolve and look like one.
    assert rev and len(rev) == 12
    assert all(c in "0123456789abcdef" for c in rev)
    assert perf_report.git_rev("/no/such/root") is None


def test_roofline_scores_against_own_dtype_roof():
    """The large-batch A/B contract (ISSUE 20): at EQUAL throughput the
    fp32 arm scores 6x the mixed arm's pct_of_peak (its roof is 6x
    lower) — so a mixed arm only wins the %-of-peak comparison by
    actually being faster, and peak_dtype stamps which roof was used."""
    mixed = perf_report.roofline(2366.0, "resnet50", device_kind="TPU v5e",
                                 compute_dtype="bfloat16")
    fp32 = perf_report.roofline(2366.0, "resnet50", device_kind="TPU v5e",
                                compute_dtype="float32")
    assert fp32["peak_dtype"] == "float32"
    assert mixed["peak_dtype"] == "bfloat16"
    assert fp32["pct_of_peak"] == pytest.approx(
        6.0 * mixed["pct_of_peak"], rel=0.01)
    # The bf16 arm keeps the back-compat alias next to the new fields.
    assert mixed["bf16_peak_tflops"] == mixed["peak_tflops"]
