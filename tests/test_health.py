"""Unit tests for observability/health.py: the heartbeat staleness clock
and the failure-attribution evidence it feeds (launch.attribute_failure).

These are the load-bearing primitives under the hang watchdog, the
elastic membership controller, and the flight record's attribution
events — tested here directly, without spawning a launcher, by steering
file mtimes with os.utime and injecting ``now``.
"""

import json
import os

from distributeddeeplearning_tpu import launch
from distributeddeeplearning_tpu.observability import health


# --- heartbeat writer -------------------------------------------------------

def test_heartbeat_path_layout(tmp_path):
    assert health.heartbeat_path(str(tmp_path), 3) == str(
        tmp_path / "heartbeat.3")


def test_writer_beats_are_atomic_json_breadcrumbs(tmp_path):
    w = health.HeartbeatWriter(str(tmp_path), process_id=2)
    w.beat(41)
    with open(w.path) as fh:
        crumb = json.load(fh)
    assert crumb["step"] == 41
    assert crumb["pid"] == os.getpid()
    assert crumb["time"] > 0
    # no tmp litter: the write is tmp + os.replace
    assert sorted(os.listdir(tmp_path)) == ["heartbeat.2"]
    w.beat(42)
    with open(w.path) as fh:
        assert json.load(fh)["step"] == 42


def test_writer_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv(health.ENV_HEARTBEAT_DIR, raising=False)
    assert health.HeartbeatWriter.from_env() is None
    monkeypatch.setenv(health.ENV_HEARTBEAT_DIR, str(tmp_path))
    monkeypatch.setenv("DDL_PROCESS_ID", "5")
    w = health.HeartbeatWriter.from_env()
    assert w is not None and w.process_id == 5
    assert w.path == health.heartbeat_path(str(tmp_path), 5)


def test_writer_survives_unwritable_directory(tmp_path):
    w = health.HeartbeatWriter(str(tmp_path), process_id=0)
    os.chmod(tmp_path, 0o500)
    try:
        w.beat(1)  # must not raise: a broken disk never kills a step
    finally:
        os.chmod(tmp_path, 0o700)


# --- staleness clock --------------------------------------------------------

def test_check_stale_reports_only_aged_heartbeats(tmp_path):
    d = str(tmp_path)
    for pid in (0, 1):
        health.HeartbeatWriter(d, pid).beat(10)
    now = os.stat(health.heartbeat_path(d, 0)).st_mtime
    # age child 1 by 30 s against the injected clock
    os.utime(health.heartbeat_path(d, 1), (now - 30, now - 30))
    stale = health.check_stale(d, num_processes=2, timeout_s=20.0, now=now)
    assert [pid for pid, _ in stale] == [1]
    assert stale[0][1] >= 30.0
    # tighten the timeout below both ages: both report, fresh one first
    stale = health.check_stale(d, num_processes=2, timeout_s=-1.0, now=now)
    assert [pid for pid, _ in stale] == [0, 1]


def test_check_stale_never_judges_a_child_that_never_beat(tmp_path):
    d = str(tmp_path)
    health.HeartbeatWriter(d, 0).beat(1)
    now = os.stat(health.heartbeat_path(d, 0)).st_mtime + 1e6
    # child 1 and 2 have no file: startup/compile grace needs no special
    # case because the watchdog only arms per child on its first beat.
    stale = health.check_stale(d, num_processes=3, timeout_s=10.0, now=now)
    assert [pid for pid, _ in stale] == [0]


# --- rejoin marker + elastic event ------------------------------------------

def test_rejoin_marker_consumed_exactly_once(tmp_path):
    d = str(tmp_path)
    assert not health.consume_rejoin(d)
    health.announce_rejoin(d)
    assert os.path.exists(health.rejoin_path(d))
    assert health.consume_rejoin(d)
    assert not health.consume_rejoin(d)  # one announcement, one re-formation


def test_read_elastic_event(monkeypatch):
    monkeypatch.delenv(health.ENV_ELASTIC_EVENT, raising=False)
    assert health.read_elastic_event() is None
    monkeypatch.setenv(health.ENV_ELASTIC_EVENT, "{not json")
    assert health.read_elastic_event() is None
    monkeypatch.setenv(health.ENV_ELASTIC_EVENT, "[1, 2]")
    assert health.read_elastic_event() is None  # must be an object
    event = {"trigger": "host_lost", "degree_before": 4, "degree_after": 2,
             "detect_t": 12.5}
    monkeypatch.setenv(health.ENV_ELASTIC_EVENT, json.dumps(event))
    assert health.read_elastic_event() == event


# --- failure attribution from the evidence ----------------------------------

def test_attribution_hung_wins_over_everything(tmp_path):
    assert launch.attribute_failure(str(tmp_path), 0, hung=True,
                                    ever_beat=True) == "hung"


def test_attribution_host_lost_needs_beat_then_vanished_file(tmp_path):
    d = str(tmp_path)
    w = health.HeartbeatWriter(d, 0)
    w.beat(7)
    # heartbeat intact -> transient crash, host is fine
    assert launch.attribute_failure(d, 0, ever_beat=True) == "crash"
    os.remove(w.path)
    # beat once, file gone with the process -> the host took its
    # filesystem presence with it
    assert launch.attribute_failure(d, 0, ever_beat=True) == "host_lost"
    # never armed: a missing file is startup death, not host loss
    assert launch.attribute_failure(d, 0, ever_beat=False) == "crash"


def test_attribution_without_heartbeat_dir_is_crash():
    assert launch.attribute_failure(None, 0, ever_beat=True) == "crash"
