"""Periodic in-training eval (VERDICT r1 #4; SURVEY.md §3.5): eval fires at
epoch boundaries per ``eval_every_epochs``, the summary tracks ``best_top1``,
and the metric is logged through MetricLogger."""

import io
import json

import pytest

from distributeddeeplearning_tpu.config import (
    DataConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.train import loop
from distributeddeeplearning_tpu.utils.logging import MetricLogger

# Every test here compiles multi-device programs — minutes on
# the 1-vCPU CPU harness, so the whole file runs in the slow
# tier (tier-1 keeps its sub-15-min budget).
pytestmark = pytest.mark.slow


def _cfg(**kw):
    base = dict(
        model="resnet18", global_batch_size=16, dtype="float32",
        log_every=10**9, steps_per_epoch=4, eval_every_epochs=1.0,
        parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=16, num_classes=10))
    base.update(kw)
    return TrainConfig(**base)


@pytest.mark.usefixtures("devices8")
def test_eval_fires_at_epoch_boundaries():
    stream = io.StringIO()
    logger = MetricLogger(stream=stream, enabled=True)
    summary = loop.run(_cfg(), total_steps=10, eval_batches=1, logger=logger)
    # steps_per_epoch=4, eval_every_epochs=1 → evals at 4, 8, final@10.
    assert [s for s, _ in summary["evals"]] == [4, 8, 10]
    assert summary["best_top1"] == max(t for _, t in summary["evals"])
    assert summary["eval_top1"] == summary["evals"][-1][1]
    logged = [json.loads(l) for l in stream.getvalue().splitlines()]
    eval_steps = [r["step"] for r in logged if "eval_top1" in r]
    assert eval_steps == [4, 8]  # the final eval lands in the summary only


@pytest.mark.usefixtures("devices8")
def test_eval_every_epochs_zero_means_final_only():
    summary = loop.run(_cfg(eval_every_epochs=0.0), total_steps=10,
                       eval_batches=1)
    assert [s for s, _ in summary["evals"]] == [10]
    assert "best_top1" in summary


@pytest.mark.usefixtures("devices8")
def test_multi_epoch_cadence():
    summary = loop.run(_cfg(eval_every_epochs=2.0), total_steps=9,
                       eval_batches=1)
    assert [s for s, _ in summary["evals"]] == [8, 9]


def test_learnable_synthetic_reaches_high_top1():
    """End-to-end accuracy path: with a class signal embedded in synthetic
    images, train -> periodic eval -> best_top1 actually climbs (the full
    SURVEY §3.5 loop, no dataset needed)."""
    import numpy as np

    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="resnet18", global_batch_size=32, dtype="float32",
        log_every=10**9, steps_per_epoch=10, eval_every_epochs=1.0,
        parallel=ParallelConfig(data=4),
        data=DataConfig(synthetic=True, synthetic_learnable=True,
                        image_size=32, num_classes=4),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05,
                                  reference_batch=32, schedule="constant",
                                  label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=30, eval_batches=2,
                       logger=MetricLogger(enabled=False))
    assert summary["best_top1"] > 0.6, summary  # chance = 0.25
    assert len(summary["evals"]) >= 3  # periodic evals fired


def test_token_eval_perplexity():
    """Token models get held-out eval too: periodic eval_loss fires, the
    summary carries best_loss + eval_ppl, and on random synthetic tokens
    the per-token loss sits near ln(vocab)."""
    import math

    import numpy as np

    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="bert_tiny", global_batch_size=8, dtype="float32",
        log_every=10**9, steps_per_epoch=3, eval_every_epochs=1.0,
        parallel=ParallelConfig(data=2, model=2, seq=2),
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=512),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                                  schedule="linear", label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=7, eval_batches=2,
                       logger=MetricLogger(enabled=False))
    assert len(summary["evals"]) >= 3       # steps 3, 6 + final
    assert np.isfinite(summary["eval_loss"])
    assert summary["best_loss"] <= summary["evals"][0][1] + 1e-6
    assert abs(summary["eval_loss"] - math.log(512)) < 1.5
    assert summary["eval_ppl"] > 1.0


def test_token_eval_causal(devices8):
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    import numpy as np

    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="gpt_tiny", global_batch_size=8, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=8),
        data=DataConfig(dataset="causal", seq_len=32, vocab_size=512))
    summary = loop.run(cfg, total_steps=2, eval_batches=2,
                       logger=MetricLogger(enabled=False))
    assert np.isfinite(summary["eval_loss"])
    assert summary["eval_ppl"] > 1.0
