"""GPT causal-LM family (models/gpt.py) + causal flash attention.

Checks: (a) GPT-2 124M/355M parameter parity, (b) the autoregressive
property (logits at t never depend on tokens > t), (c) causal flash kernel
== causal dense attention incl. gradients, (d) a dp x tp sharded causal
train step runs and optimizes via the standard loop.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.models import gpt, model_spec


def _count(model, seq=8):
    variables = model.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)},
        jnp.zeros((1, seq), jnp.int32), train=False)
    import flax

    return sum(x.size for x in jax.tree_util.tree_leaves(
        flax.linen.meta.unbox(variables["params"])))


def test_gpt2_param_parity():
    assert _count(gpt.gpt2_small(dtype=jnp.float32)) == 124_439_808


@pytest.mark.slow
def test_gpt2_medium_param_parity():
    assert _count(gpt.gpt2_medium(dtype=jnp.float32)) == 354_823_168


def test_autoregressive_property():
    """Perturbing token t+k (k>0) must not change logits at positions <= t."""
    model = gpt.tiny_gpt(vocab_size=128)
    ids = jax.random.randint(jax.random.key(0), (1, 16), 1, 128)
    variables = model.init(
        {"params": jax.random.key(1), "dropout": jax.random.key(2)},
        ids, train=False)
    base = model.apply(variables, ids, train=False)
    perturbed = ids.at[0, 10].set((ids[0, 10] + 7) % 127 + 1)
    out = model.apply(variables, perturbed, train=False)
    np.testing.assert_array_equal(np.asarray(base[0, :10]),
                                  np.asarray(out[0, :10]))
    assert np.abs(np.asarray(base[0, 10:]) - np.asarray(out[0, 10:])).max() > 0


@pytest.mark.slow
def test_causal_flash_matches_dense():
    """Same params, flash vs dense attention impl: same logits and grads."""
    ids = jax.random.randint(jax.random.key(0), (2, 32), 1, 128)
    dense = gpt.tiny_gpt(vocab_size=128, dropout_rate=0.0)
    flash = gpt.tiny_gpt(vocab_size=128, dropout_rate=0.0,
                         attention_impl="flash")
    variables = dense.init(
        {"params": jax.random.key(1), "dropout": jax.random.key(2)},
        ids, train=False)
    out_d = dense.apply(variables, ids, train=False)
    out_f = flash.apply(variables, ids, train=False)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_f),
                               rtol=2e-4, atol=2e-4)

    def loss(m, v):
        return (m.apply(v, ids, train=False) ** 2).mean()

    g_d = jax.grad(lambda v: loss(dense, v))(variables)
    g_f = jax.grad(lambda v: loss(flash, v))(variables)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4), g_d, g_f)


@pytest.mark.slow
def test_causal_step_trains_dp_tp(devices8):
    from distributeddeeplearning_tpu.data.synthetic import (
        SyntheticCausalTokens)
    from distributeddeeplearning_tpu.train import optim, steps

    cfg = TrainConfig(
        model="gpt_tiny", global_batch_size=8, dtype="float32",
        parallel=ParallelConfig(data=4, model=2),
        data=DataConfig(dataset="causal", seq_len=32, vocab_size=1024),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  reference_batch=8,
                                  schedule="linear", label_smoothing=0.0))
    from distributeddeeplearning_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(cfg.parallel)
    model = model_spec("gpt_tiny").build(vocab_size=1024, dtype=jnp.float32)
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 100)
    src = SyntheticCausalTokens(8, 32, 1024, seed=7)
    state, shardings = steps.init_sharded_state(
        model, tx, mesh, cfg, src.batch(0), jax.random.key(0), "tokens")
    step = steps.make_gspmd_train_step(model, tx, mesh, cfg, shardings,
                                       "tokens", "causal")
    rng = jax.random.key(42)
    fixed = src.batch(0)
    first = last = None
    for _ in range(8):
        state, metrics = step(state, fixed, rng)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first, (first, last)


@pytest.mark.slow
def test_gpt_runs_via_loop(devices8):
    """The CLI path: loop.run on gpt_tiny with synthetic causal data."""
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="gpt_tiny", global_batch_size=8, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=8),
        data=DataConfig(dataset="causal", seq_len=32, vocab_size=512))
    summary = loop.run(cfg, total_steps=2, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])


@pytest.mark.slow
def test_gpt_pipeline_trains(devices8):
    """GPT over pp x dp x tp: the GPipe schedule serves decoder blocks too."""
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="gpt_tiny_pp", global_batch_size=8, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(pipeline=2, data=2, model=2),
        data=DataConfig(dataset="causal", seq_len=32, vocab_size=512),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  reference_batch=8,
                                  schedule="linear", label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=3, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 3
    assert np.isfinite(summary["final_metrics"]["loss"])
