"""Attention-probability dropout parity across impls (VERDICT r3 #6).

The counter-based hash mask (ops/hash_dropout.py) is keyed on GLOBAL
coordinates, so every impl — dense, flash (in-kernel, backward regenerates),
ring, zigzag — must realize the IDENTICAL mask for the same seed, at any
sharding. That makes these exact-equality tests, not statistical ones: the
reference is dense softmax with the same hash mask materialized, and
forward AND gradients must match to float tolerance.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.config import ParallelConfig
from distributeddeeplearning_tpu.ops import flash_attention
from distributeddeeplearning_tpu.ops.hash_dropout import dense_keep_mask
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.parallel import ring_attention as ring
from tests.attention_refs import random_qkv

RATE = 0.35
SEED = jnp.int32(12345)


def dropped_dense_reference(q, k, v, kv_mask=None, *, causal=False,
                            rate=RATE, seed=SEED):
    """softmax -> hash-mask dropout -> V; the one oracle every impl must
    equal exactly (same mask by construction, not by chance)."""
    b, s, h, d = q.shape
    scale = d ** -0.5
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if kv_mask is not None:
        sc = jnp.where(kv_mask[:, None, None, :], sc, -1e30)
    if causal:
        sc = jnp.where(jnp.tril(jnp.ones((s, s), bool))[None, None],
                       sc, -1e30)
    p = jax.nn.softmax(sc.astype(jnp.float32), axis=-1)
    km = dense_keep_mask(seed, b, h, s, s, rate)
    p = jnp.where(km, p / (1.0 - rate), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def test_mask_statistics_and_determinism():
    km = dense_keep_mask(SEED, 4, 4, 64, 64, RATE)
    frac_dropped = 1.0 - float(km.mean())
    assert abs(frac_dropped - RATE) < 0.01
    km2 = dense_keep_mask(SEED, 4, 4, 64, 64, RATE)
    np.testing.assert_array_equal(np.asarray(km), np.asarray(km2))
    # Different seeds decorrelate.
    km3 = dense_keep_mask(jnp.int32(999), 4, 4, 64, 64, RATE)
    assert 0.3 < float((km != km3).mean()) < 0.6


@pytest.mark.core
@pytest.mark.parametrize("causal", [False, True])
def test_flash_dropout_matches_reference_fwd_and_grad(causal):
    q, k, v = random_qkv(jax.random.key(0), s=64, h=2, d=16)
    mask = np.ones((2, 64), bool)
    mask[0, -7:] = False
    mask = jnp.asarray(mask)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, mask, block_q=32, block_k=32,
                               causal=causal, dropout_rate=RATE,
                               dropout_seed=SEED)

    def f_ref(q, k, v):
        return dropped_dense_reference(q, k, v, mask, causal=causal)

    np.testing.assert_allclose(np.asarray(f_flash(q, k, v)),
                               np.asarray(f_ref(q, k, v)),
                               rtol=1e-5, atol=1e-5)
    gf = jax.grad(lambda *a: (f_flash(*a) ** 2).sum(), argnums=(0, 1, 2))(
        q, k, v)
    gr = jax.grad(lambda *a: (f_ref(*a) ** 2).sum(), argnums=(0, 1, 2))(
        q, k, v)
    for a, b, name in zip(gf, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_flash_dropout_block_size_invariant():
    """The realized mask is a pure function of global coordinates — kernel
    tiling must not change training semantics."""
    q, k, v = random_qkv(jax.random.key(1), s=64, h=2, d=16)
    outs = [flash_attention(q, k, v, block_q=bq, block_k=bk,
                            dropout_rate=RATE, dropout_seed=SEED)
            for bq, bk in ((16, 16), (32, 64), (64, 32))]
    for o in outs[1:]:
        np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(o),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.core
def test_ring_dropout_matches_reference(devices8):
    """Ring over 4 seq shards with dropout == dense-with-same-mask, fwd and
    grads — the mask follows global positions through the ring schedule."""
    q, k, v = random_qkv(jax.random.key(2), s=32, h=4, d=8)
    mask = jnp.asarray(np.ones((2, 32), bool))
    mesh = meshlib.make_mesh(ParallelConfig(seq=4))

    def f_ring(q, k, v):
        return ring.ring_attention_sharded(
            q, k, v, mask, causal=True, dropout_rate=RATE,
            dropout_seed=SEED)

    def f_ref(q, k, v):
        return dropped_dense_reference(q, k, v, mask, causal=True)

    with meshlib.use_mesh(mesh):
        out = jax.jit(f_ring)(q, k, v)
        gz = jax.jit(jax.grad(
            lambda *a: (f_ring(*a) ** 2).sum(), argnums=(0, 1, 2)))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(f_ref(q, k, v)),
                               rtol=1e-5, atol=1e-5)
    gr = jax.grad(lambda *a: (f_ref(*a) ** 2).sum(), argnums=(0, 1, 2))(
        q, k, v)
    for a, b, name in zip(gz, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_zigzag_dropout_matches_reference(devices8):
    """Zigzag layout keys the hash by NATURAL positions: permute in,
    attend with dropout, unpermute out == dense-with-same-mask."""
    q, k, v = random_qkv(jax.random.key(3), s=32, h=4, d=8)
    mask = jnp.asarray(np.ones((2, 32), bool))
    perm, inv = ring.zigzag_indices(32, 4)
    mesh = meshlib.make_mesh(ParallelConfig(seq=4))
    with meshlib.use_mesh(mesh):
        out_z = jax.jit(lambda a, b, c: ring.zigzag_ring_attention_sharded(
            a[:, perm], b[:, perm], c[:, perm], mask[:, perm],
            dropout_rate=RATE, dropout_seed=SEED))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_z)[:, inv],
        np.asarray(dropped_dense_reference(q, k, v, mask, causal=True)),
        rtol=1e-5, atol=1e-5)


def test_flash_dropout_sharding_invariant(devices8):
    """dp x tp sharding must not change the realized mask: the sharded
    flash call (shard offsets into global coordinates) equals the
    unsharded one exactly."""
    q, k, v = random_qkv(jax.random.key(4), s=32, h=4, d=8)
    unsharded = flash_attention(q, k, v, block_q=32, block_k=32,
                                dropout_rate=RATE, dropout_seed=SEED)
    from distributeddeeplearning_tpu.ops.flash_attention import (
        flash_attention_sharded)

    mesh = meshlib.make_mesh(ParallelConfig(data=2, model=2))
    with meshlib.use_mesh(mesh):
        sharded = jax.jit(lambda a, b, c: flash_attention_sharded(
            a, b, c, None, block_q=32, block_k=32,
            dropout_rate=RATE, dropout_seed=SEED))(q, k, v)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(unsharded),
                               rtol=1e-5, atol=1e-5)


def test_dispatch_requires_rng():
    from distributeddeeplearning_tpu.ops.attention import (
        multihead_attention)

    q, k, v = random_qkv(jax.random.key(5), s=16, h=2, d=8)
    with pytest.raises(ValueError, match="dropout_rng"):
        multihead_attention(q, k, v, None, impl="dense", causal=False,
                            dtype=jnp.float32, dropout_rate=0.1,
                            deterministic=False)


def test_dispatch_impl_parity_same_rng():
    """Through the model-facing dispatch: dense and flash with the SAME rng
    key produce identical outputs under dropout — the cross-impl semantics
    the r3 UserWarning could only apologize for."""
    from distributeddeeplearning_tpu.ops.attention import (
        multihead_attention)

    q, k, v = random_qkv(jax.random.key(6), s=64, h=2, d=16)
    rng = jax.random.key(7)
    outs = [multihead_attention(q, k, v, None, impl=impl, causal=False,
                                dtype=jnp.float32, dropout_rate=RATE,
                                dropout_rng=rng, deterministic=False)
            for impl in ("dense", "flash")]
    np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs[1]),
                               rtol=1e-5, atol=1e-5)
    # And deterministic=True ignores dropout entirely (exact no-drop path).
    a = multihead_attention(q, k, v, None, impl="flash", causal=False,
                            dtype=jnp.float32, dropout_rate=RATE,
                            deterministic=True)
    b = multihead_attention(q, k, v, None, impl="dense", causal=False,
                            dtype=jnp.float32, deterministic=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)
