"""Tensor-parallel inference: sharded generation equals single-device.

The serving story for models too big for one chip: params restore onto a
`model`-axis mesh (Megatron column/row kernel sharding, the training
rules), and the generation scan runs under GSPMD with collectives over
ICI. Token-for-token equality with the unsharded run is the invariant —
the sharded matmuls reduce in a different order, but greedy decisions on
random (tie-free) weights must not move.
"""

import contextlib

import flax.linen as nn
import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu import data as datalib  # noqa: F401
from distributeddeeplearning_tpu.config import (
    DataConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.models.generate import (
    generate, generate_beam)
from distributeddeeplearning_tpu.parallel import sharding as shardlib
from distributeddeeplearning_tpu.parallel.mesh import use_mesh
from distributeddeeplearning_tpu.train import loop

# Every test here compiles multi-device programs — minutes on
# the 1-vCPU CPU harness, so the whole file runs in the slow
# tier (tier-1 keeps its sub-15-min budget).
pytestmark = pytest.mark.slow


def _build(tp: int):
    cfg = TrainConfig(
        model="gpt_tiny", global_batch_size=2, dtype="float32",
        log_every=10**9, parallel=ParallelConfig(model=tp),
        data=DataConfig(synthetic=True, dataset="causal", seq_len=24,
                        vocab_size=96))
    mesh, model, _, state, _, _, _ = loop.build(cfg, 1)
    return cfg, mesh, model, state.params


@pytest.mark.usefixtures("devices8")
@pytest.mark.parametrize("beams", [0, 3])
def test_tp_generation_matches_single_device(beams):
    cfg, mesh, model, sharded_params = _build(tp=2)
    # The same weights, gathered to plain single-device jax arrays
    # (device_get yields numpy, which a traced index op cannot consume).
    host_params = jax.tree.map(jax.numpy.asarray,
                               jax.device_get(sharded_params))

    prompt = np.array([[5, 6, 7, 8], [9, 10, 11, 12]], np.int32)

    def run(params, ctx):
        with ctx:
            if beams:
                return np.asarray(generate_beam(
                    model, {"params": params}, prompt, max_new_tokens=6,
                    num_beams=beams))
            return np.asarray(generate(
                model, {"params": params}, prompt, max_new_tokens=6))

    tp_ctx = contextlib.ExitStack()
    tp_ctx.enter_context(use_mesh(mesh))
    tp_ctx.enter_context(nn.logical_axis_rules(
        list(shardlib.logical_rules(cfg.parallel))))
    out_tp = run(sharded_params, tp_ctx)
    out_ref = run(host_params, contextlib.ExitStack())
    np.testing.assert_array_equal(out_tp, out_ref)


@pytest.mark.usefixtures("devices8")
def test_tp_params_are_actually_sharded():
    """The invariant above is vacuous if nothing was sharded — assert at
    least the MLP kernels really live on 2 devices."""
    _, mesh, _, params = _build(tp=2)
    k = params["layer0"]["mlp_in"]["kernel"]
    k = getattr(k, "value", k)  # unbox LogicallyPartitioned
    assert len(k.sharding.device_set) == 2, k.sharding


@pytest.mark.usefixtures("devices8")
def test_tp_generation_llama_gqa():
    """TP over a GQA model: kv heads split across the model axis too."""
    cfg = TrainConfig(
        model="llama_tiny", global_batch_size=2, dtype="float32",
        log_every=10**9, parallel=ParallelConfig(model=2),
        data=DataConfig(synthetic=True, dataset="causal", seq_len=24,
                        vocab_size=96))
    mesh, model, _, state, _, _, _ = loop.build(cfg, 1)
    host = jax.tree.map(jax.numpy.asarray, jax.device_get(state.params))
    prompt = np.array([[3, 4, 5, 6]], np.int32)
    ctx = contextlib.ExitStack()
    ctx.enter_context(use_mesh(mesh))
    ctx.enter_context(nn.logical_axis_rules(
        list(shardlib.logical_rules(cfg.parallel))))
    with ctx:
        out_tp = np.asarray(generate(model, {"params": state.params},
                                     prompt, max_new_tokens=5))
    out_ref = np.asarray(generate(model, {"params": host}, prompt,
                                  max_new_tokens=5))
    np.testing.assert_array_equal(out_tp, out_ref)


@pytest.mark.usefixtures("devices8")
@pytest.mark.parametrize("model_name", ["gpt_tiny", "llama_tiny"])
def test_tp_kv_cache_decode_matches(model_name):
    """TP composes with KV-cache incremental decoding: the caches shard
    over heads (GQA: kv-head width per shard) and the emitted tokens match
    the single-device cached run exactly."""
    cfg = TrainConfig(
        model=model_name, global_batch_size=2, dtype="float32",
        log_every=10**9, parallel=ParallelConfig(model=2),
        data=DataConfig(synthetic=True, dataset="causal", seq_len=24,
                        vocab_size=96))
    mesh, model, _, state, _, _, _ = loop.build(cfg, 1)
    host = jax.tree.map(jax.numpy.asarray, jax.device_get(state.params))
    prompt = np.array([[5, 6, 7, 8], [9, 10, 11, 12]], np.int32)
    ctx = contextlib.ExitStack()
    ctx.enter_context(use_mesh(mesh))
    ctx.enter_context(nn.logical_axis_rules(
        list(shardlib.logical_rules(cfg.parallel))))
    with ctx:
        out_tp = np.asarray(generate(model, {"params": state.params},
                                     prompt, max_new_tokens=6,
                                     use_cache=True))
    out_ref = np.asarray(generate(model, {"params": host}, prompt,
                                  max_new_tokens=6, use_cache=True))
    np.testing.assert_array_equal(out_tp, out_ref)
