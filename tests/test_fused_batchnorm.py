"""Fused BN(+residual)+ReLU kernels (ops/fused_batchnorm.py) vs the classic
flax composition — forward, gradients, running-stat updates, and the
end-to-end resnet fused_bn flag. Kernels run in Pallas interpret mode here
(CPU); tools/validate_flash_tpu.py-style on-chip validation covers compiled
behavior (tools/validate_fused_bn_tpu.py)."""

import flax.linen as nn
import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.ops import fused_batchnorm as fbn

# Every test here compiles multi-device programs — minutes on
# the 1-vCPU CPU harness, so the whole file runs in the slow
# tier (tier-1 keeps its sub-15-min budget).
pytestmark = pytest.mark.slow

EPS = 1e-5


def _ref_bn_act(x2d, gamma, beta, residual=None, relu=True):
    """The unfused composition: batch-stats BN -> +residual -> relu, f32."""
    mean = x2d.mean(axis=0)
    var = ((x2d - mean) ** 2).mean(axis=0)
    y = (x2d - mean) * jax.lax.rsqrt(var + EPS) * gamma + beta
    if residual is not None:
        y = y + residual
    return jnp.maximum(y, 0.0) if relu else y


@pytest.mark.core
def test_stats_kernel_matches_jnp():
    x = jax.random.normal(jax.random.key(0), (192, 96), jnp.float32)
    mean, var = fbn.bn_stats(x)
    np.testing.assert_allclose(mean, x.mean(0), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(var, x.var(0), rtol=1e-5, atol=1e-5)


@pytest.mark.core
@pytest.mark.parametrize("relu", [True, False])
def test_forward_matches_reference(relu):
    k = jax.random.key(1)
    x = jax.random.normal(k, (64, 32), jnp.float32)
    gamma = jax.random.normal(jax.random.key(2), (32,)) * 0.2 + 1.0
    beta = jax.random.normal(jax.random.key(3), (32,)) * 0.1
    y, mean, var = fbn.bn_act_train(x, gamma, beta, relu, EPS)
    np.testing.assert_allclose(y, _ref_bn_act(x, gamma, beta, relu=relu),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(mean, x.mean(0), rtol=1e-5, atol=1e-5)


@pytest.mark.core
def test_gradients_match_reference():
    k = jax.random.key(4)
    x = jax.random.normal(k, (48, 24), jnp.float32)
    gamma = jax.random.normal(jax.random.key(5), (24,)) * 0.3 + 1.0
    beta = jax.random.normal(jax.random.key(6), (24,)) * 0.1
    w = jax.random.normal(jax.random.key(7), (48, 24))

    def loss_fused(x, g, b):
        y, _, _ = fbn.bn_act_train(x, g, b, True, EPS)
        return jnp.sum(y * w)

    def loss_ref(x, g, b):
        return jnp.sum(_ref_bn_act(x, g, b) * w)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b_, name in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(a, b_, rtol=2e-4, atol=2e-4,
                                   err_msg=name)


@pytest.mark.core
def test_residual_variant_gradients():
    x = jax.random.normal(jax.random.key(8), (32, 16), jnp.float32)
    res = jax.random.normal(jax.random.key(9), (32, 16), jnp.float32)
    gamma = jnp.ones((16,)) * 1.3
    beta = jnp.zeros((16,)) + 0.05
    w = jax.random.normal(jax.random.key(10), (32, 16))

    def loss_fused(x, g, b, r):
        y, _, _ = fbn.bn_act_res_train(x, g, b, r, True, EPS)
        return jnp.sum(y * w)

    def loss_ref(x, g, b, r):
        return jnp.sum(_ref_bn_act(x, g, b, residual=r) * w)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, gamma, beta, res)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, gamma, beta, res)
    for a, b_, name in zip(gf, gr, ("dx", "dgamma", "dbeta", "dres")):
        np.testing.assert_allclose(a, b_, rtol=2e-4, atol=2e-4,
                                   err_msg=name)


def test_module_matches_flax_batchnorm():
    """Same input -> same output, same running-stat update as nn.BatchNorm
    followed by relu; identical variable tree."""
    x = jax.random.normal(jax.random.key(11), (4, 8, 8, 16), jnp.float32)

    fused = fbn.FusedBatchNormAct(dtype=jnp.float32)
    vf = fused.init(jax.random.key(0), x)

    class Classic(nn.Module):
        @nn.compact
        def __call__(self, x):
            y = nn.BatchNorm(use_running_average=False, momentum=0.9,
                             epsilon=EPS, dtype=jnp.float32,
                             param_dtype=jnp.float32, name="bn")(x)
            return nn.relu(y)

    classic = Classic()
    vc = classic.init(jax.random.key(0), x)
    yf, mf = fused.apply(vf, x, mutable=["batch_stats"])
    yc, mc = classic.apply(vc, x, mutable=["batch_stats"])
    np.testing.assert_allclose(yf, yc, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(mf["batch_stats"]["mean"],
                               mc["batch_stats"]["bn"]["mean"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(mf["batch_stats"]["var"],
                               mc["batch_stats"]["bn"]["var"],
                               rtol=1e-5, atol=1e-6)
    # Inference mode consumes the updated stats identically.
    vf2 = {"params": vf["params"], "batch_stats": mf["batch_stats"]}
    vc2 = {"params": vc["params"], "batch_stats": mc["batch_stats"]}
    yf2 = fbn.FusedBatchNormAct(
        use_running_average=True, dtype=jnp.float32).apply(vf2, x)
    yc2 = nn.relu(nn.BatchNorm(use_running_average=True, momentum=0.9,
                               epsilon=EPS, dtype=jnp.float32,
                               name="bn").apply(
        {"params": vc2["params"]["bn"],
         "batch_stats": vc2["batch_stats"]["bn"]}, x))
    np.testing.assert_allclose(yf2, yc2, rtol=1e-5, atol=1e-5)


def test_resnet_fused_flag_preserves_numerics_and_tree():
    """resnet18_thin with fused_bn=True: identical variable tree, matching
    logits and end-to-end gradients vs the unfused model."""
    from distributeddeeplearning_tpu.models import resnet

    x = jax.random.normal(jax.random.key(12), (8, 32, 32, 3), jnp.float32)
    labels = jnp.arange(8) % 10
    models = {
        flag: resnet.resnet18_thin(num_classes=10, dtype=jnp.float32,
                                   fused_bn=flag)
        for flag in (False, True)
    }
    variables = {flag: m.init({"params": jax.random.key(0)}, x, train=False)
                 for flag, m in models.items()}
    assert (jax.tree_util.tree_structure(variables[False])
            == jax.tree_util.tree_structure(variables[True]))
    for a, b in zip(jax.tree_util.tree_leaves(variables[False]),
                    jax.tree_util.tree_leaves(variables[True])):
        np.testing.assert_allclose(a, b, rtol=0, atol=0)

    def loss_fn(flag, params):
        v = {"params": params, "batch_stats": variables[flag]["batch_stats"]}
        logits, _ = models[flag].apply(v, x, train=True,
                                       mutable=["batch_stats"])
        onehot = jax.nn.one_hot(labels, 10)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))

    losses, grads = {}, {}
    for flag in (False, True):
        losses[flag], grads[flag] = jax.value_and_grad(
            lambda p, f=flag: loss_fn(f, p))(variables[flag]["params"])
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-4, atol=1e-4)
    flat_f, _ = jax.flatten_util.ravel_pytree(grads[True])
    flat_r, _ = jax.flatten_util.ravel_pytree(grads[False])
    np.testing.assert_allclose(flat_f, flat_r, rtol=5e-3, atol=5e-4)


def test_bfloat16_path_runs():
    x = jax.random.normal(jax.random.key(13), (4, 8, 8, 32), jnp.bfloat16)
    m = fbn.FusedBatchNormAct(dtype=jnp.bfloat16)
    v = m.init(jax.random.key(0), x)
    y, _ = m.apply(v, x, mutable=["batch_stats"])
    assert y.dtype == jnp.bfloat16 and y.shape == x.shape


@pytest.mark.usefixtures("devices8")
def test_fused_dp_step_matches_unfused():
    """Two DP train steps over the 8-device mesh: fused_bn on/off produce
    the same loss trajectory (the shard_map/check_vma integration path)."""
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.train import loop

    losses = {}
    for fused in (False, True):
        cfg = TrainConfig(
            model="resnet18_thin", global_batch_size=32, dtype="float32",
            log_every=10**9, fused_bn=fused,
            parallel=ParallelConfig(data=8),
            data=DataConfig(synthetic=True, image_size=32, num_classes=10,
                            synthetic_learnable=True))
        mesh, model, batch_shd, state, train_step, _, rng = loop.build(cfg, 2)
        src = datalib.make_source(cfg, "image", batch_shd)
        out = []
        for i in range(2):
            state, metrics = train_step(state, src.batch(i), rng)
            out.append(float(metrics["loss"]))
        losses[fused] = out
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-5, atol=1e-5)
