"""Persistent compile cache + AOT step executables (docs/compile_cache.md).

Three layers under test:

- policy (perf/compile_cache.py): flag > env > default resolution, off
  switch, stats sidecar, age-based prune;
- fingerprint (perf/aot.py): equal configs -> equal keys, volatile host
  knobs never perturb the key, program-shaping fields and jax upgrades
  always do, and attempt-scoped faults expire out of the hash;
- warm restart: a second attempt through ``launch.run_with_restarts``
  loads the serialized executable and performs ZERO retraces of the train
  step (probed via ``steps.TRACE_COUNTS``), end-to-end through
  ``loop.run`` with the summary/logger cold-start fields.
"""

from __future__ import annotations

import io
import json
import os
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import pytest

from distributeddeeplearning_tpu import launch
from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.perf import aot, compile_cache
from distributeddeeplearning_tpu.robustness import faults


def _cfg(**kw):
    base = dict(
        model="resnet18_thin", global_batch_size=16, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=8, num_classes=10),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1,
                                  reference_batch=16, momentum=0.9,
                                  schedule="constant", warmup_epochs=0.0))
    base.update(kw)
    return TrainConfig(**base)


# ---------------------------------------------------------------------------
# Cache-dir policy
# ---------------------------------------------------------------------------

@pytest.mark.core
def test_resolve_dir_precedence(monkeypatch, tmp_path):
    monkeypatch.delenv(compile_cache.ENV_CACHE, raising=False)
    assert compile_cache.resolve_dir() == compile_cache.default_dir()
    monkeypatch.setenv(compile_cache.ENV_CACHE, str(tmp_path / "env"))
    assert compile_cache.resolve_dir() == str(tmp_path / "env")
    # explicit flag beats env
    assert compile_cache.resolve_dir(str(tmp_path / "flag")) == \
        str(tmp_path / "flag")
    # any off-spelling disables, at either level
    for off in ("off", "none", "0", "disabled", "OFF"):
        assert compile_cache.resolve_dir(off) is None
    monkeypatch.setenv(compile_cache.ENV_CACHE, "off")
    assert compile_cache.resolve_dir() is None


@pytest.mark.core
def test_export_env_roundtrip(monkeypatch, tmp_path):
    monkeypatch.delenv(compile_cache.ENV_CACHE, raising=False)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    compile_cache.export_env(str(tmp_path))
    assert os.environ[compile_cache.ENV_CACHE] == str(tmp_path)
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == str(tmp_path)
    compile_cache.export_env(None)  # disable propagates to children too
    assert os.environ[compile_cache.ENV_CACHE] == "off"
    assert "JAX_COMPILATION_CACHE_DIR" not in os.environ
    assert compile_cache.resolve_dir() is None


@pytest.mark.core
def test_stats_sidecar_and_prune(tmp_path):
    cache = str(tmp_path)
    compile_cache.write_stats(cache, {"aot_hits": 3, "aot_misses": 1})
    stats = compile_cache.read_stats(cache)
    assert stats["aot_hits"] == 3 and "updated_at" in stats

    old = tmp_path / "stale.bin"
    new = tmp_path / "aot" / "fresh.aotx"
    new.parent.mkdir()
    old.write_bytes(b"x" * 10)
    new.write_bytes(b"y" * 20)
    past = time.time() - 40 * 86400
    os.utime(old, (past, past))
    removed, kept = compile_cache.prune(cache, max_age_days=30.0)
    assert (removed, kept) == (1, 1)
    assert not old.exists() and new.exists()
    # the stats sidecar is bookkeeping, never a prunable entry
    assert compile_cache.read_stats(cache)["aot_hits"] == 3
    info = compile_cache.summarize(cache)
    assert info["entries"] == 0 and info["aot_entries"] == 1
    assert info["total_bytes"] == 20


# ---------------------------------------------------------------------------
# Config fingerprint stability
# ---------------------------------------------------------------------------

@pytest.mark.core
def test_equal_configs_equal_keys(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    a = aot.config_fingerprint(_cfg(), total_steps=10)
    b = aot.config_fingerprint(_cfg(), total_steps=10)
    assert a == b


@pytest.mark.core
def test_volatile_fields_do_not_change_key(monkeypatch, tmp_path):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    base = aot.config_fingerprint(_cfg(), total_steps=10)
    for kw in (dict(trace_dir=str(tmp_path / "tr")),
               dict(checkpoint_dir=str(tmp_path / "ck"),
                    checkpoint_every_steps=2),
               dict(log_every=1),
               dict(straggler_threshold=9.9),
               dict(compile_cache_dir=str(tmp_path / "cc")),
               # host-side process faults (crash/sigterm) never reach the
               # compiled program — only nan_grads does (tested below)
               dict(fault_plan="crash@3,sigterm@5")):
        assert aot.config_fingerprint(_cfg(**kw), total_steps=10) == base, kw
    # host data-pipeline knobs leave batch shapes alone
    wide = _cfg(data=DataConfig(synthetic=True, image_size=8, num_classes=10,
                                prefetch_depth=7))
    assert aot.config_fingerprint(wide, total_steps=10) == base


@pytest.mark.core
def test_program_shaping_fields_change_key(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    base = aot.config_fingerprint(_cfg(), total_steps=10)
    assert aot.config_fingerprint(_cfg(model="resnet18"),
                                  total_steps=10) != base
    assert aot.config_fingerprint(_cfg(global_batch_size=32),
                                  total_steps=10) != base
    assert aot.config_fingerprint(_cfg(dtype="bfloat16"),
                                  total_steps=10) != base
    # the LR schedule bakes the horizon into the update computation
    assert aot.config_fingerprint(_cfg(), total_steps=20) != base


@pytest.mark.core
def test_nan_grad_plan_shapes_program_but_expires_per_attempt(monkeypatch):
    """nan_grads compiles injection ops + the bad-step guard into the step,
    so it must change the key — but only on the attempt it fires on. The
    default scope is attempt 0, so the restart attempt's fingerprint equals
    a clean run's and reuses its executable (the warm-restart fast path)."""
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    clean = aot.config_fingerprint(_cfg(), total_steps=10)
    faulted = _cfg(fault_plan="nan_grads@3")
    assert aot.config_fingerprint(faulted, total_steps=10) != clean
    monkeypatch.setenv(faults.ENV_ATTEMPT, "1")  # fault expired
    assert aot.config_fingerprint(faulted, total_steps=10) == clean


@pytest.mark.core
def test_jax_version_changes_key(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    base = aot.config_fingerprint(_cfg(), total_steps=10)
    monkeypatch.setattr(jax, "__version__", "99.0.0")
    assert aot.config_fingerprint(_cfg(), total_steps=10) != base


# ---------------------------------------------------------------------------
# Warm restart: zero retraces through run_with_restarts
# ---------------------------------------------------------------------------

class _TinyNet(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(10)(nn.relu(nn.Dense(16)(x)))


@pytest.mark.usefixtures("devices8")
@pytest.mark.core
def test_restart_attempt_hits_aot_cache_zero_retraces(tmp_path, monkeypatch):
    """Attempt 0 cold-compiles the DP train step and serializes it; the
    restarted attempt (same config, fresh jit function) must load that
    executable without tracing at all — the TRACE_COUNTS probe increments
    only while jax runs the step's Python body, i.e. per (re)trace."""
    from distributeddeeplearning_tpu.parallel import mesh as meshlib
    from distributeddeeplearning_tpu.train import optim, steps
    from distributeddeeplearning_tpu.train.state import TrainState

    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    cfg = _cfg()
    cache = str(tmp_path / "cache")
    batch = {
        "image": jax.random.normal(jax.random.key(2), (16, 8, 8, 3)),
        "label": jax.random.randint(jax.random.key(3), (16,), 0, 10),
    }
    rng = jax.random.key(1)
    traces, sources = [], []

    def run_once():
        # Fresh build per attempt, exactly like a relaunched process: new
        # jit function, new cache handle — only the disk entry is shared.
        cache_handle = aot.StepExecutableCache.for_config(
            cfg, total_steps=4, cache_dir=cache)
        mesh = meshlib.make_mesh(cfg.parallel)
        model = _TinyNet()
        tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size,
                                     4, None)
        variables = model.init({"params": jax.random.key(0)},
                               jnp.zeros((1, 8, 8, 3)), train=False)
        state = TrainState.create(params=variables["params"],
                                  opt_state=tx.init(variables["params"]),
                                  batch_stats=None)
        step = steps.make_dp_train_step(model, tx, mesh, cfg,
                                        aot=cache_handle)
        before = steps.TRACE_COUNTS["dp_train_step"]
        _, metrics = step(state, batch, rng)
        jax.device_get(metrics)  # execution barrier
        traces.append(steps.TRACE_COUNTS["dp_train_step"] - before)
        sources.append(cache_handle.sources["dp_train_step"])
        cache_handle.flush_stats()
        return 1 if len(traces) == 1 else 0  # attempt 0 "crashes"

    rc = launch.run_with_restarts(run_once, 1, sleep=lambda s: None)
    assert rc == 0
    assert traces == [1, 0]  # cold trace once, warm restart retraces NEVER
    assert sources == ["compiled", "aot_hit"]
    # the stats sidecar (last writer = the warm attempt) records the hit
    stats = compile_cache.read_stats(cache)
    assert stats["aot_hits"] == 1 and stats["aot_saves"] == 0


@pytest.mark.usefixtures("devices8")
def test_loop_warm_start_summary_and_zero_retrace(tmp_path, monkeypatch):
    """End-to-end through loop.run: run 1 cold-compiles (summary +
    MetricLogger carry compile_time_s / time_to_first_step_s, the AOT
    entry is saved, the eval step warm-compiles on a thread); run 2 of the
    identical config loads the executable — zero retraces of the train
    step and sources=aot_hit in the summary."""
    from distributeddeeplearning_tpu.train import loop, steps
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cache = str(tmp_path / "cache")
    # Set through monkeypatch so loop.run's export_env mutations of these
    # keys are rolled back at teardown.
    monkeypatch.setenv(compile_cache.ENV_CACHE, cache)
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", cache)
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    cfg = _cfg(log_every=1, compile_cache_dir=cache)
    try:
        stream = io.StringIO()
        s1 = loop.run(cfg, total_steps=2, eval_batches=1,
                      logger=MetricLogger(stream=stream, enabled=True))
        assert s1["compile_time_s"] > 0
        assert s1["time_to_first_step_s"] >= s1["compile_time_s"]
        cc = s1["compile_cache"]
        assert cc["sources"]["dp_train_step"] == "compiled"
        assert cc["aot_saves"] >= 1
        first = json.loads(stream.getvalue().splitlines()[0])
        assert first["compile_time_s"] > 0
        assert first["time_to_first_step_s"] > 0

        before = steps.TRACE_COUNTS["dp_train_step"]
        s2 = loop.run(cfg, total_steps=2, eval_batches=1,
                      logger=MetricLogger(enabled=False))
        assert steps.TRACE_COUNTS["dp_train_step"] == before  # ZERO retraces
        assert s2["compile_cache"]["sources"]["dp_train_step"] == "aot_hit"
        assert s2["compile_cache"]["aot_hits"] >= 1
        assert s2["compile_time_s"] < s1["compile_time_s"]
        # both runs trained the same program: identical final loss
        assert s1["final_metrics"]["loss"] == s2["final_metrics"]["loss"]
    finally:
        # loop.run pointed the process-global jax persistent cache at the
        # tmp dir; re-point it at the repo default for the rest of the suite.
        jax.config.update("jax_compilation_cache_dir",
                          compile_cache.default_dir())


@pytest.mark.usefixtures("devices8")
def test_warm_resume_with_checkpointing_is_donation_safe(tmp_path, monkeypatch):
    """The warm-RESTART path with checkpointing live — the one combination
    that corrupted the heap before loop.run learned to device-copy restored
    state: orbax-restored arrays can alias host memory the restore machinery
    owns (zero-copy device_put on CPU), and a directly-called deserialized
    executable donates its inputs unconditionally, where jit would refuse.
    Attempt 0 cold-compiles, saves every step, and crashes mid-run; the
    resumed attempt restores the checkpoint, loads the serialized executable
    (zero retraces), checkpoint-saves while donating, and must land on the
    EXACT final loss of an uninterrupted run. A regression here tends to die
    of SIGSEGV/SIGABRT rather than assert — that is the bug."""
    from distributeddeeplearning_tpu.train import loop, steps
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cache = str(tmp_path / "cache")
    monkeypatch.setenv(compile_cache.ENV_CACHE, cache)
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", cache)
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    kw = dict(compile_cache_dir=cache, checkpoint_every_steps=1)
    try:
        # Uninterrupted reference: cold-compiles and populates the cache.
        ref = loop.run(_cfg(checkpoint_dir=str(tmp_path / "ck_ref"), **kw),
                       total_steps=4, eval_batches=0,
                       logger=MetricLogger(enabled=False))
        assert ref["compile_cache"]["sources"]["dp_train_step"] == "compiled"

        # Attempt 0: warm, saves at 1 and 2, then the injected crash.
        faulted = _cfg(checkpoint_dir=str(tmp_path / "ck"),
                       fault_plan="crash@2", **kw)
        with pytest.raises(SystemExit):
            loop.run(faulted, total_steps=4, eval_batches=0,
                     logger=MetricLogger(enabled=False))

        # The restart: crash@2 is attempt-0-scoped, so the fingerprint
        # matches the clean one and the serialized executable is reused on
        # the restored state — restore, AOT-hit donating dispatches, and
        # async saves all interleaved.
        monkeypatch.setenv(faults.ENV_ATTEMPT, "1")
        before = steps.TRACE_COUNTS["dp_train_step"]
        s = loop.run(faulted, total_steps=4, eval_batches=0,
                     logger=MetricLogger(enabled=False))
        assert steps.TRACE_COUNTS["dp_train_step"] == before
        assert s["compile_cache"]["sources"]["dp_train_step"] == "aot_hit"
        assert s["start_step"] == 2 and s["final_step"] == 4
        # Recovery is bitwise: kill + restore + warm executable fully erased.
        assert s["final_metrics"]["loss"] == ref["final_metrics"]["loss"]
    finally:
        jax.config.update("jax_compilation_cache_dir",
                          compile_cache.default_dir())


# ---------------------------------------------------------------------------
# Donation backstop (the runtime form of analysis/donation.py's invariant)
# ---------------------------------------------------------------------------

def test_donation_signature_parses_alias_header():
    class Fake:
        def as_text(self):
            return ("HloModule m, input_output_alias={ {0}: (0, {}, "
                    "may-alias) }\n\nENTRY %main () -> f32[] {\n}\n")

    assert aot.donation_signature(Fake()) == "{{0}:(0,{},may-alias)}"

    class NoAlias:
        def as_text(self):
            return "HloModule m\n"

    assert aot.donation_signature(NoAlias()) is None

    class Broken:
        def as_text(self):
            raise RuntimeError("boom")

    assert aot.donation_signature(Broken()) is None


@pytest.mark.usefixtures("devices8")
def test_aot_load_rejects_drifted_donation_set(tmp_path, monkeypatch):
    """A cached executable whose input_output_alias no longer matches the
    one recorded at save time could donate buffers the caller still
    aliases (the PR 5 bug class, through the cache): the entry must be
    deleted and recompiled cold, never dispatched. CPU executables carry
    no alias header, so the signature probe is patched to simulate the
    TPU donation sets."""
    cache = str(tmp_path / "cache")
    handle = aot.StepExecutableCache.for_config(_cfg(), total_steps=4,
                                                cache_dir=cache)
    args = (jnp.ones((4,)), jnp.ones((4,)))
    compiled = jax.jit(lambda x, y: x + y).lower(*args).compile()
    key = handle.key("step", args)

    monkeypatch.setattr(aot, "donation_signature", lambda _: "{{0}:(0,{})}")
    assert handle.save("step", key, compiled)

    # Unchanged donation set: a hit.
    warm = aot.StepExecutableCache.for_config(_cfg(), total_steps=4,
                                              cache_dir=cache)
    assert warm.load("step", key) is not None
    assert warm.hits == 1 and warm.failures == 0

    # Drifted donation set: deleted + cold fallback.
    monkeypatch.setattr(aot, "donation_signature", lambda _: "{{1}:(0,{})}")
    drifted = aot.StepExecutableCache.for_config(_cfg(), total_steps=4,
                                                 cache_dir=cache)
    assert drifted.load("step", key) is None
    assert drifted.failures == 1 and drifted.hits == 0
    assert not os.path.exists(os.path.join(
        cache, compile_cache.AOT_SUBDIR, f"{key}.aotx"))

    # Payloads with no recorded signature (pre-backstop entries, or a
    # backend whose text lacks the header) are tolerated: absence of
    # evidence is not a mismatch.
    monkeypatch.setattr(aot, "donation_signature", lambda _: None)
    assert handle.save("step", key, compiled)
    monkeypatch.setattr(aot, "donation_signature", lambda _: "{{0}:(0,{})}")
    legacy = aot.StepExecutableCache.for_config(_cfg(), total_steps=4,
                                                cache_dir=cache)
    assert legacy.load("step", key) is not None
    assert legacy.failures == 0
