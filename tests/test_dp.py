"""Data-parallel correctness (SURVEY.md §4): the central invariant is that
an N-shard psum-averaged gradient step equals the single-device step on the
concatenated batch — the property Horovod's allreduce guarantees and our
shard_map+pmean path must reproduce exactly."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.config import (
    DataConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.models import resnet
from distributeddeeplearning_tpu.parallel.mesh import make_mesh
from distributeddeeplearning_tpu.train import optim, steps
from distributeddeeplearning_tpu.train.state import TrainState


def tiny_model():
    return resnet.ResNet([1, 1], resnet.BasicBlock, num_classes=10,
                         dtype=jnp.float32)


class _NoBNNet(nn.Module):
    """BN-free convnet: the N-shard == 1-device gradient invariant is exact
    only without batch-local statistics (BN stays shard-local by design,
    matching per-GPU BN under Horovod)."""

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        x = nn.Conv(8, (3, 3), dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(10, dtype=jnp.float32)(x)


def make_state(model, tx, rng):
    variables = model.init({"params": rng}, jnp.zeros((1, 32, 32, 3)),
                           train=False)
    params = variables["params"]
    return TrainState.create(params=params, opt_state=tx.init(params),
                             batch_stats=variables.get("batch_stats"))


def cfg_for(dp: int) -> TrainConfig:
    return TrainConfig(model="resnet18", global_batch_size=16,
                       dtype="float32", parallel=ParallelConfig(data=dp),
                       data=DataConfig(image_size=32, num_classes=10))


@pytest.fixture(scope="module")
def batch():
    k = jax.random.key(42)
    k1, k2 = jax.random.split(k)
    return {"image": jax.random.normal(k1, (16, 32, 32, 3)),
            "label": jax.random.randint(k2, (16,), 0, 10)}


def grads_via(dp: int, batch, devices8):
    """Run ONE train step at dp shards with momentum-less SGD so the applied
    update is exactly -lr * averaged gradient; return the updated params."""
    import optax
    model = _NoBNNet()
    cfg = cfg_for(dp)
    tx = optax.sgd(0.1)  # no momentum/wd: update == -lr*grad
    rng = jax.random.key(0)
    variables = model.init({"params": rng}, jnp.zeros((1, 32, 32, 3)),
                           train=False)
    params = variables["params"]
    state = TrainState.create(params=params, opt_state=tx.init(params))
    mesh = make_mesh(cfg.parallel)
    step = steps.make_dp_train_step(model, tx, mesh, cfg, "image")
    new_state, metrics = step(state, batch, rng)
    return jax.device_get(new_state.params), metrics


@pytest.mark.core
def test_dp8_matches_single_device(batch, devices8):
    """psum-averaged dp=8 step == single-device step on the full batch."""
    p1, m1 = grads_via(1, batch, devices8)
    p8, m8 = grads_via(8, batch, devices8)
    flat1 = jax.tree_util.tree_leaves(p1)
    flat8 = jax.tree_util.tree_leaves(p8)
    for a, b in zip(flat1, flat8):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    # Loss metric: mean of shard means == global mean for equal shards.
    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                               rtol=1e-5)


def test_dp_loss_decreases(devices8):
    from distributeddeeplearning_tpu.data.synthetic import SyntheticImages
    model = tiny_model()
    cfg = cfg_for(8)
    tx, _ = optim.make_optimizer(cfg.optimizer, 16, 100)
    rng = jax.random.key(0)
    state = make_state(model, tx, rng)
    mesh = make_mesh(cfg.parallel)
    step = steps.make_dp_train_step(model, tx, mesh, cfg, "image")
    src = SyntheticImages(16, 32, 10, seed=0)
    fixed = src.batch(0)  # overfit one batch => loss must fall
    first = last = None
    for i in range(10):
        state, metrics = step(state, fixed, rng)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert last < first, (first, last)
    assert int(state.step) == 10


@pytest.mark.core
def test_params_stay_replicated(batch, devices8):
    """After a dp step, params on every device must be identical (the
    Horovod broadcast+allreduce invariant)."""
    model = tiny_model()
    cfg = cfg_for(8)
    import optax
    tx = optax.sgd(0.1, momentum=0.9)
    rng = jax.random.key(0)
    state = make_state(model, tx, rng)
    mesh = make_mesh(cfg.parallel)
    step = steps.make_dp_train_step(model, tx, mesh, cfg, "image")
    new_state, _ = step(state, batch, rng)
    leaf = jax.tree_util.tree_leaves(new_state.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


@pytest.mark.core
def test_eval_psum_aggregation(devices8):
    model = tiny_model()
    cfg = cfg_for(8)
    rng = jax.random.key(0)
    variables = model.init({"params": rng}, jnp.zeros((1, 32, 32, 3)),
                           train=False)
    state = TrainState.create(params=variables["params"], opt_state=(),
                              batch_stats=variables.get("batch_stats"))
    mesh = make_mesh(cfg.parallel)
    ev = steps.make_dp_eval_step(model, mesh, cfg)
    k1, k2 = jax.random.split(jax.random.key(1))
    batch = {"image": jax.random.normal(k1, (16, 32, 32, 3)),
             "label": jax.random.randint(k2, (16,), 0, 10)}
    out = ev(state, batch)
    assert int(out["total"]) == 16
    assert 0 <= int(out["correct"]) <= 16
