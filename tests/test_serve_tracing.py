"""Per-request serve tracing + TTFT attribution (ISSUE 19).

The load-bearing pins, in order:

* **Exact-sum attribution** — every finished request's component
  decomposition (queue / admission_stall / prefill / interference /
  decode) sums to its measured total latency, and the TTFT snapshot sums
  to its measured TTFT, to float precision. The protocol (one moving
  mark per request, every interval charged to exactly one component)
  makes "the components don't add up" a structural impossibility, and
  these tests keep it that way.
* **Zero overhead off** — with tracing disabled the engine holds no
  tracer, requests carry no trace state, and a decode step allocates
  NOTHING in tracing.py/telemetry.py (tracemalloc-pinned), so serving
  throughput is untouched.
* **Crash-safe multi-writer traces** — concurrent exports to one path
  lose nothing (flock-serialized read-modify-write), a SIGKILL-truncated
  replica trace is salvaged by the merge, and a request re-dispatched
  across replica processes appears as ONE flow id spanning both pids in
  the merged trace.

Around the pins: the span-name registry schema (every emitted ``serve:*``
name is registered; ddl-lint enforces the same at the AST level),
scheduler skip-reason classification, the attribution-fed anomaly kinds,
metrics percentile summaries, and the tools/trace_report.py CLI.
"""

import json
import os
import sys
import threading
import tracemalloc

import pytest

from distributeddeeplearning_tpu.observability import (anomaly, metrics,
                                                       telemetry)
from distributeddeeplearning_tpu.serve import tracing
from distributeddeeplearning_tpu.serve.engine import Engine, ServeConfig
from distributeddeeplearning_tpu.serve.scheduler import (SloScheduler,
                                                         TenantPolicy)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from tools import summarize_trace  # noqa: E402
from tools import trace_report  # noqa: E402

pytestmark = pytest.mark.serve

VOCAB = 97


def _engine(model="gpt_tiny", **kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 32)
    kw.setdefault("max_pages_per_slot", 8)
    kw.setdefault("prefill_buckets", (8,))
    kw.setdefault("compile_cache_dir", "off")
    t = [0.0]

    def clock():
        t[0] += 0.001  # strictly increasing: every read a distinct time
        return t[0]

    return Engine(ServeConfig(model=model, **kw), clock=clock)


def _drain(eng):
    while not eng.idle:
        eng.step()


@pytest.fixture
def traced():
    """Enabled telemetry singleton (no trace dir — events inspected via
    snapshot); engines built inside resolve a live tracer."""
    tele = telemetry.configure(enabled=True)
    metrics.reset()
    yield tele
    telemetry.reset()
    metrics.reset()


class _TracedRun:
    """One traced max_slots=1 engine run, shared (read-only) by every
    test that only inspects its artifacts — the engine compile is the
    expensive part, so it is paid once for the module."""

    def __init__(self, trace_dir):
        self.trace_dir = trace_dir
        telemetry.configure(enabled=True, trace_dir=trace_dir)
        metrics.reset()
        try:
            eng = _engine(max_slots=1)
            eng.warmup()
            for i in range(4):
                eng.submit([(7 * i + j) % VOCAB + 1 for j in range(6)],
                           max_new_tokens=4)
            _drain(eng)
            self.finished = list(eng.finished)
            self.events = telemetry.get().snapshot()
            telemetry.get().export()  # export drains the buffer: snapshot first
        finally:
            telemetry.reset()
            metrics.reset()


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
    return _TracedRun(str(tmp_path_factory.mktemp("traced_run")))


# --- exact-sum attribution --------------------------------------------------

def test_attribution_sums_exactly_under_queueing(traced_run):
    """max_slots=1 forces real queueing/interference; every component
    decomposition still sums to the measured latency to float precision
    — far inside the 1 ms acceptance bound."""
    finished = traced_run.finished
    assert len(finished) == 4
    for req in finished:
        rt = req.trace
        assert rt is not None and rt.done
        assert set(rt.comp) == set(tracing.COMPONENTS)
        assert all(v >= 0.0 for v in rt.comp.values())
        total = req.finished_s - req.arrival_s
        assert sum(rt.comp.values()) == pytest.approx(total, abs=1e-9)
        assert sum(rt.ttft_comp.values()) == pytest.approx(req.ttft_s,
                                                           abs=1e-9)
    # With one slot, the later arrivals waited: their non-service time
    # is attributed, not lost.
    waited = [r for r in finished
              if r.trace.comp["queue"] + r.trace.comp["interference"] > 0]
    assert len(waited) >= 2

    atts = [e for e in traced_run.events
            if e.get("ph") == "i" and e["name"] == "serve:attribution"]
    assert len(atts) == 4
    for e in atts:
        assert set(e["args"]["components"]) == set(tracing.COMPONENTS)
        assert abs(e["args"]["sum_err_s"]) < 1e-9
        assert abs(e["args"]["ttft_sum_err_s"]) < 1e-9


def test_every_emitted_serve_name_is_registered(traced_run):
    emitted = {e["name"] for e in traced_run.events
               if str(e.get("name", "")).startswith("serve:")}
    assert emitted, "a traced run must emit serve spans"
    assert emitted <= set(tracing.REGISTERED_PHASES)
    for must in ("serve:submit", "serve:scheduler_plan", "serve:page_alloc",
                 "serve:prefill", "serve:decode", "serve:decode_tick",
                 "serve:attribution", "serve:request",
                 "serve:request_flow"):
        assert must in emitted, f"core span {must} missing from a run"


def test_components_schema_is_exhaustive():
    """The component set is the closed vocabulary every consumer (bench
    record, trace_report tables, docs) keys on."""
    assert tracing.COMPONENTS == ("queue", "admission_stall", "prefill",
                                  "interference", "decode")
    rt = tracing.RequestTrace(1, 0.0)
    assert set(rt.comp) == set(tracing.COMPONENTS)
    for reason in tracing.STALL_REASONS:
        assert tracing.component_for_reason(reason) == "admission_stall"
    for reason in ("priority", "no_slot", "no_pages", "backoff",
                   "tenant_cap", "anything-else"):
        assert tracing.component_for_reason(reason) in tracing.COMPONENTS


def test_resumed_submit_continues_the_flow(traced):
    """A re-dispatched victim (supervisor retry after replica loss)
    CONTINUES its flow under the supervisor's global id — phase "t", not
    a fresh "s" — and the finish closes the same id."""
    eng = _engine()
    eng.warmup()
    eng.submit([3, 1, 4, 1, 5, 9], max_new_tokens=3, trace_id=424242,
               resumed=True)
    _drain(eng)
    flows = [e for e in traced.snapshot()
             if e["name"] == "serve:request_flow"]
    assert [e["ph"] for e in flows] == ["t", "f"]
    assert all(e["id"] == 424242 for e in flows)


# --- disabled path: a TRUE no-op -------------------------------------------

def test_disabled_tracing_is_zero_allocation():
    """Tracing off: no tracer object, no per-request trace state, and a
    decode step allocates zero objects in tracing.py/telemetry.py — the
    'tracing off leaves serve throughput unchanged' acceptance pin."""
    telemetry.reset()  # the disabled singleton
    eng = _engine()
    eng.warmup()
    assert eng._tracer is None and eng.tracer is None
    req = eng.submit([2, 7, 1, 8, 2, 8], max_new_tokens=6)
    assert req.trace is None
    eng.step()  # admission + prefill before the pinned window

    filters = [tracemalloc.Filter(True, "*serve/tracing.py"),
               tracemalloc.Filter(True, "*observability/telemetry.py")]
    tracemalloc.start()
    try:
        before = tracemalloc.take_snapshot().filter_traces(filters)
        for _ in range(3):
            eng.step()  # pure decode ticks
        after = tracemalloc.take_snapshot().filter_traces(filters)
    finally:
        tracemalloc.stop()
    diff = [d for d in after.compare_to(before, "filename")
            if d.size_diff > 0 or d.count_diff > 0]
    assert diff == [], (
        f"tracing-disabled decode allocated in the tracing stack: {diff}")
    assert telemetry.get().snapshot() == []


# --- scheduler skip reasons -------------------------------------------------

def _req(uid, tenant="default", arrival=0.0, total=8, not_before=0.0):
    class R:
        pass
    r = R()
    r.uid, r.tenant, r.arrival_s, r.total_tokens = uid, tenant, arrival, total
    r.not_before_s = not_before
    return r


def test_plan_reasons_classify_every_skipped_request():
    sched = SloScheduler()
    # No free slots and nothing preemptible: everyone skipped as no_slot.
    plan = sched.plan(now=1.0, waiting=[_req(0), _req(1)], live=[],
                      free_slots=0, free_pages=100, page_size=4)
    assert plan.reasons == {0: "no_slot", 1: "no_slot"}
    # Slots free but pages exhausted: no_pages — an admission stall, not
    # scheduler interference (the attribution layer splits on this).
    plan = sched.plan(now=1.0, waiting=[_req(0), _req(1)], live=[],
                      free_slots=2, free_pages=0, page_size=4)
    assert plan.reasons == {0: "no_pages", 1: "no_pages"}
    assert tracing.component_for_reason("no_pages") == "admission_stall"
    assert tracing.component_for_reason("no_slot") == "interference"
    # A backoff hold is named even when capacity exists.
    plan = sched.plan(now=1.0, waiting=[_req(5, not_before=9.0)], live=[],
                      free_slots=2, free_pages=100, page_size=4)
    assert plan.reasons == {5: "backoff"} and not plan.admit
    # Admitted requests carry no reason.
    plan = sched.plan(now=1.0, waiting=[_req(7)], live=[],
                      free_slots=2, free_pages=100, page_size=4)
    assert [r.uid for r in plan.admit] == [7] and plan.reasons == {}


# --- concurrent export, truncation salvage, cross-process flows -------------

def test_concurrent_exports_to_one_path_lose_nothing(tmp_path):
    """N registries flushing to the same trace file concurrently (the
    supervisor + a dying replica's final export): the flock-serialized
    read-modify-write keeps every event exactly once."""
    path = str(tmp_path / "trace.p0.json")
    errs = []

    def writer(i):
        try:
            tele = telemetry.Telemetry(enabled=True)
            for j in range(25):
                tele.instant(f"w{i}.e{j}", writer=i)
                if j % 10 == 9:
                    tele.export(path)
            tele.export(path)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errs == []
    events = telemetry.load_events(path)
    names = [e["name"] for e in events if e["ph"] == "i"]
    assert sorted(names) == sorted(f"w{i}.e{j}"
                                   for i in range(4) for j in range(25))


def test_truncated_replica_trace_salvaged_by_merge(tmp_path):
    t0 = telemetry.Telemetry(enabled=True, trace_dir=str(tmp_path),
                             process_index=0, process_name="replica-0")
    for i in range(3):
        t0.instant(f"ok{i}")
    t0.export()
    t1 = telemetry.Telemetry(enabled=True, trace_dir=str(tmp_path),
                             process_index=1, process_name="replica-1")
    for i in range(3):
        t1.instant(f"cut{i}")
    p1 = t1.export()
    text = open(p1).read()
    with open(p1, "w") as fh:  # SIGKILL mid-copy: cut inside the 3rd event
        fh.write(text[:text.rindex('"cut2"') + 3])
    merged, errors = telemetry.merge_trace_dir(str(tmp_path))
    assert merged and errors and "truncated" in errors[0]
    names = {e["name"] for e in telemetry.load_events(merged)}
    assert {"ok0", "ok1", "ok2", "cut0", "cut1"} <= names
    assert "cut2" not in names  # the lost tail is reported, not invented
    # Directory mode never double-counts the merged file...
    assert merged not in summarize_trace.expand_traces([str(tmp_path)])
    # ...but a dir holding ONLY the merged artifact falls back to it.
    only = tmp_path / "pulled"
    only.mkdir()
    os.rename(merged, only / "trace.merged.json")
    assert summarize_trace.expand_traces([str(only)]) == [
        str(only / "trace.merged.json")]


def test_cross_process_flow_links_in_merged_trace(tmp_path):
    """A request whose first life was on replica 0 and whose re-dispatch
    landed on replica 1: one flow id, two pids, reported by both
    summarize_trace and trace_report."""
    t0 = telemetry.Telemetry(enabled=True, trace_dir=str(tmp_path),
                             process_index=0, process_name="replica-0")
    t0.record_span("serve:prefill", 1.0, 1.2, request=0, trace=77)
    t0.flow("serve:request_flow", 77, "s", ts_s=1.1, request=0)
    t0.export()
    t1 = telemetry.Telemetry(enabled=True, trace_dir=str(tmp_path),
                             process_index=1, process_name="replica-1")
    t1.record_span("serve:prefill", 2.0, 2.3, request=0, trace=77,
                   resumed=True)
    t1.flow("serve:request_flow", 77, "t", ts_s=2.15, request=0)
    t1.flow("serve:request_flow", 77, "f", ts_s=2.5, request=0)
    t1.export()
    merged, errors = telemetry.merge_trace_dir(str(tmp_path))
    assert merged and not errors
    events = telemetry.load_events(merged)

    fl = summarize_trace.flow_summary(events)
    assert fl["chains"] == 1
    assert fl["cross_process"] == [
        {"id": 77, "name": "serve:request_flow", "pids": [0, 1],
         "events": 3}]

    rep = trace_report.serve_report(events)
    assert rep["cross_process_flows"] == [{"id": 77, "pids": [0, 1]}]


def test_async_track_pairing_flags_unretired_requests():
    t = telemetry.Telemetry(enabled=True)
    t.async_begin("serve:request", 1, ts_s=0.0)
    t.async_end("serve:request", 1, ts_s=1.0)
    t.async_begin("serve:request", 2, ts_s=0.5)  # never retires
    fl = summarize_trace.flow_summary(t.snapshot())
    assert fl["async_unclosed"] == ["2"]
    assert fl["async_unmatched_ends"] == 0


# --- attribution-fed anomaly kinds -----------------------------------------

def test_serve_attribution_anomaly_kinds_fire_and_stay_quiet():
    det = anomaly.AnomalyDetector()
    for step in range(6):  # a healthy baseline: no flags, ever
        assert det.update_serve(step, queue_wait_s=0.010 + step * 1e-4,
                                alloc_stall_s=0.002,
                                decode_tick_s=0.004) == []
    flags = det.update_serve(10, queue_wait_s=1.0, alloc_stall_s=0.8,
                             decode_tick_s=0.5)
    kinds = {f["kind"] for f in flags}
    assert kinds == {"queue_wait_regression", "allocation_stall",
                     "decode_stall"}
    # An untraced engine supplies None: those detectors stay silent.
    det2 = anomaly.AnomalyDetector()
    for step in range(8):
        assert det2.update_serve(step) == []


# --- metrics percentiles ----------------------------------------------------

def test_percentile_linear_interpolation():
    assert metrics.percentile([], 50) is None
    assert metrics.percentile([5.0], 99) == 5.0
    assert metrics.percentile([1, 2, 3, 4], 50) == 2.5
    assert metrics.percentile([4, 1, 3, 2], 50) == 2.5  # order-free
    assert metrics.percentile(range(1, 101), 99) == pytest.approx(99.01)
    assert metrics.percentile([1, 2, float("nan"), 3, 4], 50) == 2.5


def test_registry_percentiles_in_aggregate_and_prometheus():
    reg = metrics.MetricsRegistry(run_id="r1")
    for i in range(1, 101):
        reg.observe("serve_ttft_s", i / 100.0, step=i)
    m = reg.aggregate()["metrics"]["serve_ttft_s"]
    assert m["percentiles"]["p50"] == pytest.approx(0.505)
    assert m["percentiles"]["p90"] == pytest.approx(0.901)
    assert m["percentiles"]["p99"] == pytest.approx(0.9901)
    text = reg.prometheus_text()
    assert '# TYPE ddl_serve_ttft_s_p99 gauge' in text
    assert 'ddl_serve_ttft_s_p99{run="r1"} 0.9901' in text
    # A single sample gets no quantile lines (they would all be the
    # sample itself — noise, not signal).
    reg2 = metrics.MetricsRegistry(run_id="r2")
    reg2.observe("x", 1.0)
    assert "_p99" not in reg2.prometheus_text()


# --- straggler warnings on the shared warn path -----------------------------

def test_straggler_warn_path_emits_ratio_gauge_and_data_wait(monkeypatch,
                                                            capsys):
    import numpy as np
    from jax.experimental import multihost_utils

    from distributeddeeplearning_tpu.observability import straggler

    per_host = [(0.10, 0.01), (0.10, 0.01), (0.40, 0.30)]
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda x: np.concatenate([np.asarray(h, np.float64)
                                  for h in per_host]))
    telemetry.configure(enabled=True)
    metrics.reset()
    try:
        mon = straggler.StragglerMonitor(1.5, len(per_host))
        rec = mon.collect(10, *per_host[0])
        assert rec["straggler_host"] == 2
        inst = [e for e in telemetry.get().snapshot()
                if e["name"] == "straggler"]
        assert len(inst) == 1
        assert inst[0]["args"]["data_wait_s"] == pytest.approx(0.30)
        ratio = metrics.get().aggregate()["metrics"][
            "straggler_step_time_ratio"]
        assert ratio["last"] == pytest.approx(0.40 / 0.20)
        assert "# straggler: host 2" in capsys.readouterr().err
    finally:
        telemetry.reset()
        metrics.reset()


# --- trace_report CLI -------------------------------------------------------

def test_trace_report_serve_cli(traced_run, capsys):
    assert trace_report.main(
        ["--serve", traced_run.trace_dir, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["aggregate"]["requests"] == 4
    assert rep["max_sum_err_s"] < 1e-6
    cp = rep["p99_critical_path"]
    assert cp["dominant"] in tracing.COMPONENTS
    shares = cp["shares"]
    assert set(shares) == set(tracing.COMPONENTS)
    for scope in ("all", "p99_tail"):
        assert sum(shares[c][scope] for c in tracing.COMPONENTS) == \
            pytest.approx(1.0, abs=0.01)
    # Human mode renders the same report without error.
    assert trace_report.main(
        ["--serve", traced_run.trace_dir, "--top", "2"]) == 0
    out = capsys.readouterr().out
    assert "p99 critical path" in out and "dominant" in out
