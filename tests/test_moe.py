"""MoE expert parallelism (models/moe.py; wires ParallelConfig.expert).

Checks: (a) top-1 routing matches a per-token dense reference when capacity
is ample, (b) expert kernels actually shard over the ``expert`` mesh axis,
(c) an MoE train step runs under dp x ep x tp and optimizes, with the
load-balance aux loss surfaced in metrics.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokens
from distributeddeeplearning_tpu.models import bert
from distributeddeeplearning_tpu.models.moe import MoeMlp
from distributeddeeplearning_tpu.parallel.mesh import make_mesh
from distributeddeeplearning_tpu.train import optim, steps


def test_top1_routing_matches_dense_reference():
    """With capacity >= S no token drops: out[t] = gate[t] * MLP_{e(t)}(x[t])."""
    b, s, h, f, e = 2, 16, 8, 16, 4
    layer = MoeMlp(hidden_size=h, intermediate_size=f, num_experts=e,
                   capacity_factor=float(e), dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (b, s, h), jnp.float32)
    variables = layer.init({"params": jax.random.key(1)}, x,
                           deterministic=True)
    out = layer.apply(variables, x, deterministic=True)

    import flax.linen as nn
    params = nn.meta.unbox(variables["params"])
    wr, wi, wo = params["router"]["kernel"], params["wi"], params["wo"]
    probs = jax.nn.softmax(x @ wr, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    ref = np.zeros((b, s, h), np.float32)
    for bi in range(b):
        for si in range(s):
            ei = int(idx[bi, si])
            gate = float(probs[bi, si, ei])
            hmid = jax.nn.gelu(x[bi, si] @ wi[ei], approximate=False)
            ref[bi, si] = gate * np.asarray(hmid @ wo[ei])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_capacity_drops_tokens():
    """capacity_factor ~ 0 forces drops: only the first token routed to each
    expert (per row) contributes; later ones produce zero output."""
    b, s, h, f, e = 1, 8, 4, 8, 2
    layer = MoeMlp(hidden_size=h, intermediate_size=f, num_experts=e,
                   capacity_factor=1e-6, dtype=jnp.float32)  # cap -> 1
    x = jax.random.normal(jax.random.key(0), (b, s, h), jnp.float32)
    variables = layer.init({"params": jax.random.key(1)}, x,
                           deterministic=True)
    out = layer.apply(variables, x, deterministic=True)
    import flax.linen as nn
    wr = nn.meta.unbox(variables["params"])["router"]["kernel"]
    idx = np.asarray(jnp.argmax(jax.nn.softmax(x @ wr, -1), -1))[0]
    seen = set()
    for si in range(s):
        if idx[si] in seen:  # over capacity -> dropped -> zero output
            np.testing.assert_allclose(np.asarray(out[0, si]), 0.0,
                                       atol=1e-6)
        seen.add(idx[si])


def _moe_cfg(parallel):
    return TrainConfig(
        model="bert_tiny_moe", global_batch_size=8, dtype="float32",
        parallel=parallel,
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=1024),
        # reference_batch=8 pins the linear-scaling rule to identity so the
        # 8-example test batch actually trains at 1e-3 (not 1e-3 * 8/256,
        # where dropout noise swamps the learning signal).
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  reference_batch=8,
                                  schedule="linear", label_smoothing=0.0))


def _build(parallel):
    from distributeddeeplearning_tpu.models import model_spec

    cfg = _moe_cfg(parallel)
    mesh = make_mesh(cfg.parallel)
    model = model_spec("bert_tiny_moe").build(vocab_size=1024,
                                              dtype=jnp.float32)
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 100)
    src = SyntheticTokens(8, 32, 1024, seed=7)
    state, shardings = steps.init_sharded_state(
        model, tx, mesh, cfg, src.batch(0), jax.random.key(0), "tokens")
    step = steps.make_gspmd_train_step(model, tx, mesh, cfg, shardings,
                                       "tokens")
    return src, state, step


def test_expert_kernels_shard(devices8):
    _, state, _ = _build(ParallelConfig(data=2, expert=2, model=2))
    wi = state.params["layer1"]["moe_mlp"]["wi"].value
    assert wi.sharding.spec == P("expert", None, "model"), wi.sharding
    wo = state.params["layer1"]["moe_mlp"]["wo"].value
    assert wo.sharding.spec == P("expert", "model", None), wo.sharding
    # Layer 0 stays dense (moe_every=2): no moe params there.
    assert "moe_mlp" not in state.params["layer0"]


def test_moe_step_trains_ep(devices8):
    src, state, step = _build(ParallelConfig(data=2, expert=2, model=2))
    rng = jax.random.key(42)
    fixed = src.batch(0)
    first = last = None
    for _ in range(8):
        state, metrics = step(state, fixed, rng)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
        assert np.isfinite(float(metrics["moe_aux"]))
        # Load-balance loss is >= 1 by Cauchy-Schwarz (equality = uniform).
        assert float(metrics["moe_aux"]) >= 0.99
    assert last < first, (first, last)


def test_moe_matches_unsharded(devices8):
    """ep-sharded forward == single-device forward (collectives exact)."""
    model = bert.tiny_bert_mlm(vocab_size=1024, num_experts=4)
    ids = jax.random.randint(jax.random.key(3), (4, 32), 0, 1024)
    variables = model.init({"params": jax.random.key(0),
                            "dropout": jax.random.key(1)}, ids, train=False)
    ref = model.apply(variables, ids, train=False)

    import flax.linen as nn
    from distributeddeeplearning_tpu.parallel import sharding as shardlib
    from distributeddeeplearning_tpu.parallel.mesh import use_mesh

    cfg = _moe_cfg(ParallelConfig(data=2, expert=4))
    mesh = make_mesh(cfg.parallel)
    with use_mesh(mesh), nn.logical_axis_rules(
            list(shardlib.logical_rules(cfg.parallel))):
        sharded = jax.jit(
            lambda v, x: model.apply(v, x, train=False))(variables, ids)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(sharded),
                               rtol=1e-4, atol=1e-4)
