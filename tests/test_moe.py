"""MoE expert parallelism (models/moe.py; wires ParallelConfig.expert).

Checks: (a) top-1 routing matches a per-token dense reference when capacity
is ample, (b) expert kernels actually shard over the ``expert`` mesh axis,
(c) an MoE train step runs under dp x ep x tp and optimizes, with the
load-balance aux loss surfaced in metrics.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokens
from distributeddeeplearning_tpu.models import bert
from distributeddeeplearning_tpu.models.moe import MoeMlp
from distributeddeeplearning_tpu.parallel.mesh import make_mesh
from distributeddeeplearning_tpu.train import optim, steps
import pytest


def test_top1_routing_matches_dense_reference():
    """With capacity >= S no token drops: out[t] = gate[t] * MLP_{e(t)}(x[t])."""
    b, s, h, f, e = 2, 16, 8, 16, 4
    layer = MoeMlp(hidden_size=h, intermediate_size=f, num_experts=e,
                   capacity_factor=float(e), dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (b, s, h), jnp.float32)
    variables = layer.init({"params": jax.random.key(1)}, x,
                           deterministic=True)
    out = layer.apply(variables, x, deterministic=True)

    import flax.linen as nn
    params = nn.meta.unbox(variables["params"])
    wr, wi, wo = params["router"]["kernel"], params["wi"], params["wo"]
    probs = jax.nn.softmax(x @ wr, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    ref = np.zeros((b, s, h), np.float32)
    for bi in range(b):
        for si in range(s):
            ei = int(idx[bi, si])
            gate = float(probs[bi, si, ei])
            hmid = jax.nn.gelu(x[bi, si] @ wi[ei], approximate=False)
            ref[bi, si] = gate * np.asarray(hmid @ wo[ei])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_capacity_drops_tokens():
    """capacity_factor ~ 0 forces drops: only the first token routed to each
    expert (per row) contributes; later ones produce zero output."""
    b, s, h, f, e = 1, 8, 4, 8, 2
    layer = MoeMlp(hidden_size=h, intermediate_size=f, num_experts=e,
                   capacity_factor=1e-6, dtype=jnp.float32)  # cap -> 1
    x = jax.random.normal(jax.random.key(0), (b, s, h), jnp.float32)
    variables = layer.init({"params": jax.random.key(1)}, x,
                           deterministic=True)
    out = layer.apply(variables, x, deterministic=True)
    import flax.linen as nn
    wr = nn.meta.unbox(variables["params"])["router"]["kernel"]
    idx = np.asarray(jnp.argmax(jax.nn.softmax(x @ wr, -1), -1))[0]
    seen = set()
    for si in range(s):
        if idx[si] in seen:  # over capacity -> dropped -> zero output
            np.testing.assert_allclose(np.asarray(out[0, si]), 0.0,
                                       atol=1e-6)
        seen.add(idx[si])


def _moe_cfg(parallel):
    return TrainConfig(
        model="bert_tiny_moe", global_batch_size=8, dtype="float32",
        parallel=parallel,
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=1024),
        # reference_batch=8 pins the linear-scaling rule to identity so the
        # 8-example test batch actually trains at 1e-3 (not 1e-3 * 8/256,
        # where dropout noise swamps the learning signal).
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  reference_batch=8,
                                  schedule="linear", label_smoothing=0.0))


def _build(parallel):
    from distributeddeeplearning_tpu.models import model_spec

    cfg = _moe_cfg(parallel)
    mesh = make_mesh(cfg.parallel)
    model = model_spec("bert_tiny_moe").build(vocab_size=1024,
                                              dtype=jnp.float32)
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 100)
    src = SyntheticTokens(8, 32, 1024, seed=7)
    state, shardings = steps.init_sharded_state(
        model, tx, mesh, cfg, src.batch(0), jax.random.key(0), "tokens")
    step = steps.make_gspmd_train_step(model, tx, mesh, cfg, shardings,
                                       "tokens")
    return src, state, step


def test_expert_kernels_shard(devices8):
    _, state, _ = _build(ParallelConfig(data=2, expert=2, model=2))
    wi = state.params["layer1"]["moe_mlp"]["wi"].value
    assert wi.sharding.spec == P("expert", None, "model"), wi.sharding
    wo = state.params["layer1"]["moe_mlp"]["wo"].value
    assert wo.sharding.spec == P("expert", "model", None), wo.sharding
    # Layer 0 stays dense (moe_every=2): no moe params there.
    assert "moe_mlp" not in state.params["layer0"]


@pytest.mark.slow
def test_moe_step_trains_ep(devices8):
    src, state, step = _build(ParallelConfig(data=2, expert=2, model=2))
    rng = jax.random.key(42)
    fixed = src.batch(0)
    first = last = None
    for _ in range(8):
        state, metrics = step(state, fixed, rng)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
        assert np.isfinite(float(metrics["moe_aux"]))
        # Load-balance loss is >= 1 by Cauchy-Schwarz (equality = uniform).
        assert float(metrics["moe_aux"]) >= 0.99
    assert last < first, (first, last)


def test_moe_matches_unsharded(devices8):
    """ep-sharded forward == single-device forward (collectives exact)."""
    model = bert.tiny_bert_mlm(vocab_size=1024, num_experts=4)
    ids = jax.random.randint(jax.random.key(3), (4, 32), 0, 1024)
    variables = model.init({"params": jax.random.key(0),
                            "dropout": jax.random.key(1)}, ids, train=False)
    ref = model.apply(variables, ids, train=False)

    import flax.linen as nn
    from distributeddeeplearning_tpu.parallel import sharding as shardlib
    from distributeddeeplearning_tpu.parallel.mesh import use_mesh

    cfg = _moe_cfg(ParallelConfig(data=2, expert=4))
    mesh = make_mesh(cfg.parallel)
    with use_mesh(mesh), nn.logical_axis_rules(
            list(shardlib.logical_rules(cfg.parallel))):
        sharded = jax.jit(
            lambda v, x: model.apply(v, x, train=False))(variables, ids)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(sharded),
                               rtol=1e-4, atol=1e-4)


def test_top2_routing_matches_dense_reference():
    """GShard top-2 with ample capacity: out[t] = g1·MLP_e1(x[t]) +
    g2·MLP_e2(x[t]) with gates renormalized over the chosen pair."""
    import flax.linen as nn

    b, s, h, f, e = 2, 16, 8, 16, 4
    layer = MoeMlp(hidden_size=h, intermediate_size=f, num_experts=e,
                   capacity_factor=2.0 * e, router_top_k=2,
                   dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(2), (b, s, h), jnp.float32)
    variables = layer.init({"params": jax.random.key(3)}, x,
                           deterministic=True)
    out = layer.apply(variables, x, deterministic=True)

    params = nn.meta.unbox(variables["params"])
    wr, wi, wo = params["router"]["kernel"], params["wi"], params["wo"]
    probs = np.asarray(jax.nn.softmax(x @ wr, axis=-1))
    ref = np.zeros((b, s, h), np.float32)
    for bi in range(b):
        for si in range(s):
            order = np.argsort(-probs[bi, si])
            e1, e2 = int(order[0]), int(order[1])
            g1, g2 = probs[bi, si, e1], probs[bi, si, e2]
            g1, g2 = g1 / (g1 + g2), g2 / (g1 + g2)
            for ek, gk in ((e1, g1), (e2, g2)):
                hmid = np.asarray(jax.nn.gelu(
                    x[bi, si] @ wi[ek], approximate=False))
                ref[bi, si] += gk * (hmid @ wo[ek])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_top2_capacity_priority():
    """The GShard priority rule, against a numpy queue simulation: ALL
    first choices take capacity slots before any second choice, in token
    order; overflowing assignments drop while surviving ones keep their
    renormalized-pair gates."""
    import flax.linen as nn

    b, s, h, f, e = 2, 12, 8, 16, 2
    # Tight capacity (factor 0.5, k=2 -> cap = s/e): with e=2 experts the
    # popular expert overflows, exercising drops in both passes.
    layer = MoeMlp(hidden_size=h, intermediate_size=f, num_experts=e,
                   capacity_factor=0.5, router_top_k=2, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(4), (b, s, h), jnp.float32)
    variables = layer.init({"params": jax.random.key(5)}, x,
                           deterministic=True)
    out = layer.apply(variables, x, deterministic=True)

    params = nn.meta.unbox(variables["params"])
    wr, wi, wo = params["router"]["kernel"], params["wi"], params["wo"]
    probs = np.asarray(jax.nn.softmax(x @ wr, axis=-1))
    cap = max(int(s / e * 0.5 * 2), 1)
    ref = np.zeros((b, s, h), np.float32)
    for bi in range(b):
        count = [0] * e
        e1 = probs[bi].argmax(axis=-1)
        masked = probs[bi].copy()
        masked[np.arange(s), e1] = -1
        e2 = masked.argmax(axis=-1)
        kept = []
        for si in range(s):          # pass 1: all first choices
            if count[e1[si]] < cap:
                count[e1[si]] += 1
                kept.append((si, int(e1[si]), 0))
        for si in range(s):          # pass 2: second choices
            if count[e2[si]] < cap:
                count[e2[si]] += 1
                kept.append((si, int(e2[si]), 1))
        for si, ek, which in kept:
            g1 = probs[bi, si, e1[si]]
            g2 = probs[bi, si, e2[si]]
            gk = (g1 if which == 0 else g2) / (g1 + g2)
            hmid = np.asarray(jax.nn.gelu(
                x[bi, si] @ wi[ek], approximate=False))
            ref[bi, si] += gk * (hmid @ wo[ek])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_top2_trains_via_loop(devices8):
    """bert_tiny with top-2 MoE trains one step under dp x ep."""
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="bert_tiny_moe2", global_batch_size=8, dtype="float32",
        log_every=10**9, parallel=ParallelConfig(data=4, expert=2),
        data=DataConfig(dataset="mlm", seq_len=16, vocab_size=512),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                                  schedule="linear", label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=1, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 1
    assert np.isfinite(summary["final_metrics"]["loss"])
