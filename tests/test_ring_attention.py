"""Ring attention correctness (SURVEY.md §5.7 — the long-context subsystem).

The invariant that matters: blockwise ring attention over a sharded ``seq``
axis is *exact* attention — identical (to f32 tolerance) to the dense
softmax(QK^T)V computed on one device, for any padding mask. Runs on the
8-fake-CPU-device mesh like all distributed tests (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.parallel import ring_attention as ring
from tests.attention_refs import dense_reference, random_qkv


@pytest.mark.core
@pytest.mark.parametrize("seq_shards", [1, 2, 4, 8])
def test_ring_matches_dense(seq_shards):
    q, k, v = random_qkv(jax.random.key(0))
    mask = jnp.ones(q.shape[:2], jnp.bool_)
    mesh = meshlib.make_mesh(ParallelConfig(seq=seq_shards))
    with meshlib.use_mesh(mesh):
        out = jax.jit(lambda *a: ring.ring_attention_sharded(*a))(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_reference(q, k, v, mask)),
        rtol=1e-5, atol=1e-5)


@pytest.mark.core
def test_ring_respects_padding_mask():
    """Padding keys must not leak attention, wherever their shard lives."""
    q, k, v = random_qkv(jax.random.key(1))
    b, s = q.shape[:2]
    # Pad out the tail 10 positions (crosses the last shard boundary) plus a
    # hole mid-sequence.
    mask = np.ones((b, s), bool)
    mask[:, -10:] = False
    mask[0, 5] = False
    mask = jnp.asarray(mask)
    mesh = meshlib.make_mesh(ParallelConfig(seq=4))
    with meshlib.use_mesh(mesh):
        out = jax.jit(lambda *a: ring.ring_attention_sharded(*a))(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_reference(q, k, v, mask)),
        rtol=1e-5, atol=1e-5)


def test_ring_composes_with_head_sharding():
    """seq x model sharding together: heads split over `model`, ring over
    `seq` — the layout the longctx preset uses."""
    q, k, v = random_qkv(jax.random.key(2), h=4)
    mask = jnp.ones(q.shape[:2], jnp.bool_)
    mesh = meshlib.make_mesh(ParallelConfig(data=2, seq=2, model=2))
    with meshlib.use_mesh(mesh):
        out = jax.jit(lambda *a: ring.ring_attention_sharded(*a))(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_reference(q, k, v, mask)),
        rtol=1e-5, atol=1e-5)


@pytest.mark.core
def test_ring_grads_match_dense():
    """Autodiff through the ppermute ring == autodiff through dense attn."""
    q, k, v = random_qkv(jax.random.key(3), s=16)
    mask = jnp.ones(q.shape[:2], jnp.bool_)
    mesh = meshlib.make_mesh(ParallelConfig(seq=4))

    def ring_loss(q, k, v):
        return ring.ring_attention_sharded(q, k, v, mask).sum()

    def dense_loss(q, k, v):
        return dense_reference(q, k, v, mask).sum()

    with meshlib.use_mesh(mesh):
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_bert_ring_end_to_end():
    """Tiny BERT trains one step with ring attention on a dp x sp x tp mesh
    through the real GSPMD train path (the longctx preset's shape)."""
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="bert_tiny", global_batch_size=8, dtype="float32",
        log_every=10**9, attention_impl="ring",
        parallel=ParallelConfig(data=2, seq=2, model=2),
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=512),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                                  schedule="constant", label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=2, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])


def test_bert_ring_matches_dense_forward():
    """Full-model check: BertMLM logits with ring == dense attention impl
    (dropout off via train=False), single device."""
    from distributeddeeplearning_tpu.models import bert

    ids = jax.random.randint(jax.random.key(4), (2, 24), 0, 256)
    mask = jnp.ones((2, 24), jnp.int32).at[:, -4:].set(0)
    mesh = meshlib.make_mesh(ParallelConfig())  # all axes size 1

    dense = bert.tiny_bert_mlm(vocab_size=256)
    ringm = bert.tiny_bert_mlm(vocab_size=256, attention_impl="ring")
    variables = dense.init({"params": jax.random.key(0), "dropout": jax.random.key(0)},
                           ids, train=False)
    out_d = dense.apply(variables, ids, attention_mask=mask, train=False)
    with meshlib.use_mesh(mesh):
        out_r = jax.jit(lambda v, i, m: ringm.apply(
            v, i, attention_mask=m, train=False))(variables, ids, mask)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.core
@pytest.mark.parametrize("seq_shards", [1, 2, 4])
def test_causal_ring_matches_causal_dense(seq_shards):
    """Causal ring == causal dense attention, incl. a padding mask and
    gradients — the long-context GPT path (models/gpt.py attention 'ring')."""
    q, k, v = random_qkv(jax.random.key(2))
    b, s = q.shape[:2]
    mask = np.ones((b, s), bool)
    mask[0, -6:] = False  # padded tail crossing a shard boundary
    mask = jnp.asarray(mask)

    def dense_causal(q, k, v, mask):
        d = q.shape[-1]
        sc = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * (d ** -0.5)
        tri = jnp.tril(jnp.ones((s, s), bool))
        keep = tri[None, None] & mask[:, None, None, :]
        sc = jnp.where(keep, sc, -1e30)
        p = jax.nn.softmax(sc, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    ref = dense_causal(q, k, v, mask)
    mesh = meshlib.make_mesh(ParallelConfig(seq=seq_shards))
    with meshlib.use_mesh(mesh):
        out = jax.jit(lambda *a: ring.ring_attention_sharded(
            *a, causal=True))(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    with meshlib.use_mesh(mesh):
        g_ring = jax.jit(jax.grad(
            lambda q, k, v: (ring.ring_attention_sharded(
                q, k, v, mask, causal=True).astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (dense_causal(q, k, v, mask).astype(jnp.float32)
                         ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, bb in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=2e-4, atol=2e-4)


def test_gpt_ring_runs_via_loop(devices8):
    """Long-context causal config: GPT over dp x sp via the standard loop."""
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="gpt_tiny", global_batch_size=4, dtype="float32",
        log_every=10**9, attention_impl="ring",
        parallel=ParallelConfig(data=2, seq=4),
        data=DataConfig(dataset="causal", seq_len=64, vocab_size=512))
    summary = loop.run(cfg, total_steps=2, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])
