"""Ring attention correctness (SURVEY.md §5.7 — the long-context subsystem).

The invariant that matters: blockwise ring attention over a sharded ``seq``
axis is *exact* attention — identical (to f32 tolerance) to the dense
softmax(QK^T)V computed on one device, for any padding mask. Runs on the
8-fake-CPU-device mesh like all distributed tests (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.parallel import ring_attention as ring
from tests.attention_refs import dense_reference, random_qkv


@pytest.mark.core
@pytest.mark.parametrize("seq_shards", [1, 2, 4, 8])
def test_ring_matches_dense(seq_shards):
    q, k, v = random_qkv(jax.random.key(0))
    mask = jnp.ones(q.shape[:2], jnp.bool_)
    mesh = meshlib.make_mesh(ParallelConfig(seq=seq_shards))
    with meshlib.use_mesh(mesh):
        out = jax.jit(lambda *a: ring.ring_attention_sharded(*a))(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_reference(q, k, v, mask)),
        rtol=1e-5, atol=1e-5)


@pytest.mark.core
def test_ring_respects_padding_mask():
    """Padding keys must not leak attention, wherever their shard lives."""
    q, k, v = random_qkv(jax.random.key(1))
    b, s = q.shape[:2]
    # Pad out the tail 10 positions (crosses the last shard boundary) plus a
    # hole mid-sequence.
    mask = np.ones((b, s), bool)
    mask[:, -10:] = False
    mask[0, 5] = False
    mask = jnp.asarray(mask)
    mesh = meshlib.make_mesh(ParallelConfig(seq=4))
    with meshlib.use_mesh(mesh):
        out = jax.jit(lambda *a: ring.ring_attention_sharded(*a))(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_reference(q, k, v, mask)),
        rtol=1e-5, atol=1e-5)


def test_ring_composes_with_head_sharding():
    """seq x model sharding together: heads split over `model`, ring over
    `seq` — the layout the longctx preset uses."""
    q, k, v = random_qkv(jax.random.key(2), h=4)
    mask = jnp.ones(q.shape[:2], jnp.bool_)
    mesh = meshlib.make_mesh(ParallelConfig(data=2, seq=2, model=2))
    with meshlib.use_mesh(mesh):
        out = jax.jit(lambda *a: ring.ring_attention_sharded(*a))(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_reference(q, k, v, mask)),
        rtol=1e-5, atol=1e-5)


@pytest.mark.core
def test_ring_grads_match_dense():
    """Autodiff through the ppermute ring == autodiff through dense attn."""
    q, k, v = random_qkv(jax.random.key(3), s=16)
    mask = jnp.ones(q.shape[:2], jnp.bool_)
    mesh = meshlib.make_mesh(ParallelConfig(seq=4))

    def ring_loss(q, k, v):
        return ring.ring_attention_sharded(q, k, v, mask).sum()

    def dense_loss(q, k, v):
        return dense_reference(q, k, v, mask).sum()

    with meshlib.use_mesh(mesh):
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_bert_ring_end_to_end():
    """Tiny BERT trains one step with ring attention on a dp x sp x tp mesh
    through the real GSPMD train path (the longctx preset's shape)."""
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="bert_tiny", global_batch_size=8, dtype="float32",
        log_every=10**9, attention_impl="ring",
        parallel=ParallelConfig(data=2, seq=2, model=2),
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=512),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                                  schedule="constant", label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=2, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])


def test_bert_ring_matches_dense_forward():
    """Full-model check: BertMLM logits with ring == dense attention impl
    (dropout off via train=False), single device."""
    from distributeddeeplearning_tpu.models import bert

    ids = jax.random.randint(jax.random.key(4), (2, 24), 0, 256)
    mask = jnp.ones((2, 24), jnp.int32).at[:, -4:].set(0)
    mesh = meshlib.make_mesh(ParallelConfig())  # all axes size 1

    dense = bert.tiny_bert_mlm(vocab_size=256)
    ringm = bert.tiny_bert_mlm(vocab_size=256, attention_impl="ring")
    variables = dense.init({"params": jax.random.key(0), "dropout": jax.random.key(0)},
                           ids, train=False)
    out_d = dense.apply(variables, ids, attention_mask=mask, train=False)
    with meshlib.use_mesh(mesh):
        out_r = jax.jit(lambda v, i, m: ringm.apply(
            v, i, attention_mask=m, train=False))(variables, ids, mask)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.core
@pytest.mark.parametrize("seq_shards", [1, 2, 4])
def test_causal_ring_matches_causal_dense(seq_shards):
    """Causal ring == causal dense attention, incl. a padding mask and
    gradients — the long-context GPT path (models/gpt.py attention 'ring')."""
    q, k, v = random_qkv(jax.random.key(2))
    b, s = q.shape[:2]
    mask = np.ones((b, s), bool)
    mask[0, -6:] = False  # padded tail crossing a shard boundary
    mask = jnp.asarray(mask)

    def dense_causal(q, k, v, mask):
        return dense_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                               v.astype(jnp.float32), mask,
                               causal=True).astype(q.dtype)

    ref = dense_causal(q, k, v, mask)
    mesh = meshlib.make_mesh(ParallelConfig(seq=seq_shards))
    with meshlib.use_mesh(mesh):
        out = jax.jit(lambda *a: ring.ring_attention_sharded(
            *a, causal=True))(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    with meshlib.use_mesh(mesh):
        g_ring = jax.jit(jax.grad(
            lambda q, k, v: (ring.ring_attention_sharded(
                q, k, v, mask, causal=True).astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (dense_causal(q, k, v, mask).astype(jnp.float32)
                         ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, bb in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt_ring_runs_via_loop(devices8):
    """Long-context causal config: GPT over dp x sp via the standard loop."""
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="gpt_tiny", global_batch_size=4, dtype="float32",
        log_every=10**9, attention_impl="ring",
        parallel=ParallelConfig(data=2, seq=4),
        data=DataConfig(dataset="causal", seq_len=64, vocab_size=512))
    summary = loop.run(cfg, total_steps=2, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])


@pytest.mark.core
@pytest.mark.parametrize("seq_shards", [2, 4])
def test_zigzag_matches_causal_dense(seq_shards):
    """Zigzag-sharded causal ring == causal dense in natural order (permute
    in, compute over the ring, unpermute out) — forward AND gradients (the
    training-path invariant, same as the plain causal ring's test)."""
    q, k, v = random_qkv(jax.random.key(5))
    b, s = q.shape[:2]
    mask = np.ones((b, s), bool)
    mask[0, -6:] = False
    mask = jnp.asarray(mask)
    perm, inv = ring.zigzag_indices(s, seq_shards)
    w = jax.random.normal(jax.random.key(6), q.shape)

    mesh = meshlib.make_mesh(ParallelConfig(seq=seq_shards))

    def loss_zig(q, k, v):
        out = ring.zigzag_ring_attention_sharded(
            q[:, perm], k[:, perm], v[:, perm], mask[:, perm])
        return jnp.sum(out[:, inv] * w)

    def loss_ref(q, k, v):
        return jnp.sum(dense_reference(q, k, v, mask, causal=True) * w)

    with meshlib.use_mesh(mesh):
        out_z = jax.jit(lambda *a: ring.zigzag_ring_attention_sharded(*a))(
            q[:, perm], k[:, perm], v[:, perm], mask[:, perm])
        np.testing.assert_allclose(
            np.asarray(out_z)[:, inv],
            np.asarray(dense_reference(q, k, v, mask, causal=True)),
            rtol=1e-5, atol=1e-5)
        gz = jax.jit(jax.grad(loss_zig, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b_, name in zip(gz, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


@pytest.mark.core
def test_zigzag_schedule_is_balanced():
    """The zigzag schedule's point: per-device causal work is equal by
    construction (2 chunk-pairs per arrival + 1 extra on the local step),
    while contiguous causal sharding loads the last shard with 4n
    chunk-pair-equivalents — the lockstep ring's critical path."""
    for n in (2, 4, 8):
        totals = []
        for i in range(n):
            per_step = [len(ring._zigzag_pairs(i, (i - r) % n, n))
                        for r in range(n)]
            assert max(per_step) <= 3 and min(per_step) >= 2
            totals.append(sum(per_step))
        assert len(set(totals)) == 1, totals          # perfectly balanced
        assert totals[0] == 2 * n + 1                 # vs 4n contiguous max
        # the provably-dead pair never fires
        for i in range(n):
            for r in range(n):
                assert (i, 2 * n - 1 - ((i - r) % n)) not in [
                    p for p in ring._zigzag_pairs(i, (i - r) % n, n)
                    if p[0] == i and p[1] >= n]


def test_zigzag_indices_roundtrip():
    perm, inv = ring.zigzag_indices(32, 4)
    x = np.arange(32)
    np.testing.assert_array_equal(x[perm][inv], x)
    # shard 0 of 4 owns chunks 0 and 7 of 8
    np.testing.assert_array_equal(perm[:8], list(range(0, 4)) + list(range(28, 32)))


@pytest.mark.slow
def test_gpt_zigzag_runs_via_loop(devices8):
    """--attn zigzag end-to-end: GPT over dp x sp via the standard loop,
    whole transformer in zigzag layout (models/gpt.py permutes in/out)."""
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="gpt_tiny", global_batch_size=4, dtype="float32",
        log_every=10**9, attention_impl="zigzag",
        parallel=ParallelConfig(data=2, seq=4),
        data=DataConfig(dataset="causal", seq_len=64, vocab_size=512))
    summary = loop.run(cfg, total_steps=2, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])


@pytest.mark.core
@pytest.mark.slow
def test_gpt_zigzag_logits_match_dense(devices8):
    """The zigzag GPT forward equals the dense-attention forward in natural
    order — the permute/position/unpermute plumbing is numerics-exact."""
    from distributeddeeplearning_tpu.models import gpt

    ids = jax.random.randint(jax.random.key(0), (2, 32), 0, 500)
    outs = {}
    for impl, seq in (("dense", 1), ("zigzag", 4)):
        model = gpt.tiny_gpt(vocab_size=512, dtype=jnp.float32, seq_len=32,
                             attention_impl=impl)
        mesh = meshlib.make_mesh(ParallelConfig(seq=seq))
        with meshlib.use_mesh(mesh):
            variables = jax.jit(lambda: model.init(
                {"params": jax.random.key(1), "dropout": jax.random.key(2)},
                ids, train=False))()
            outs[impl] = jax.jit(lambda v: model.apply(v, ids, train=False))(
                variables)
    np.testing.assert_allclose(np.asarray(outs["zigzag"]),
                               np.asarray(outs["dense"]),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.core
@pytest.mark.slow
def test_llama_zigzag_logits_match_dense(devices8):
    """Llama's zigzag forward equals its dense forward in natural order —
    specifically pinning RoPE: in permuted layout the rotation must follow
    the token (positions = perm), not the slot, or phases encode wrong
    distances (GQA geometry included via tiny_llama's 4q/2kv heads)."""
    from distributeddeeplearning_tpu.models import llama

    ids = jax.random.randint(jax.random.key(0), (2, 32), 0, 900)
    outs = {}
    for impl, seq in (("dense", 1), ("zigzag", 4)):
        model = llama.tiny_llama(attention_impl=impl)
        mesh = meshlib.make_mesh(ParallelConfig(seq=seq))
        with meshlib.use_mesh(mesh):
            variables = jax.jit(lambda: model.init(
                {"params": jax.random.key(1)}, ids, train=False))()
            outs[impl] = jax.jit(lambda v: model.apply(v, ids, train=False))(
                variables)
    np.testing.assert_allclose(np.asarray(outs["zigzag"]),
                               np.asarray(outs["dense"]),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_llama_zigzag_runs_via_loop(devices8):
    """--model llama --attn zigzag end-to-end over dp x sp, including the
    remat path threading positions through nn.remat."""
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="llama_tiny", global_batch_size=4, dtype="float32",
        log_every=10**9, attention_impl="zigzag", remat=True,
        parallel=ParallelConfig(data=2, seq=4),
        data=DataConfig(dataset="causal", seq_len=64, vocab_size=1024))
    summary = loop.run(cfg, total_steps=2, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])
