"""Elastic resume: a checkpoint saved under one mesh restores under another.

The reference-era failure mode this kills: Horovod/NCCL jobs pin their world
size at launch — losing a node means restarting at the same N or not at all.
Here the checkpoint is a sharded pytree with mesh-agnostic global shapes
(orbax), and the data stream is a deterministic function of (seed, step), so
a run can resume on a different device count — or a different parallelism
strategy entirely — and continue training. The soak at the bottom closes the
loop end-to-end: ``launch.py --elastic`` re-forms a live job through a host
loss AND a host rejoin with no operator input.

Trajectory-exactness caveat, asserted accordingly: transformer models
(LayerNorm — no cross-sample statistics) continue the SAME trajectory on any
mesh at fixed global batch, and the tests demand exact parity. BatchNorm
models intentionally use per-shard statistics (like per-GPU BN under
Horovod, see train/steps.py), so their trajectory depends on the per-shard
batch; the CNN test asserts a clean resume and healthy training, not
bitwise parity.

Markers: everything here carries ``elastic`` (tools/marker_audit.py
--expect-elastic verifies the path is covered); the multi-device compiles
are minutes on the 1-vCPU harness so most tests are also ``slow`` — but the
tiny fast variant MUST stay unmarked so tier-1 exercises cross-degree
resume on every run.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.train import loop
from distributeddeeplearning_tpu.utils.logging import MetricLogger

pytestmark = pytest.mark.elastic


def _cfg(model="bert_tiny", dp=8, fsdp=1, **kw) -> TrainConfig:
    data = (DataConfig(synthetic=True, image_size=32, num_classes=10)
            if model.startswith("resnet")
            else DataConfig(synthetic=True, dataset="mlm", seq_len=32,
                            mlm_max_predictions=5))
    base = dict(
        model=model, global_batch_size=8, dtype="float32", log_every=10**9,
        parallel=ParallelConfig(data=dp, fsdp=fsdp), data=data,
        optimizer=OptimizerConfig(schedule="constant", learning_rate=0.01))
    base.update(kw)
    return TrainConfig(**base)


def _quiet():
    return MetricLogger(enabled=False)


def _params(summary):
    return jax.device_get(summary["state"].params)


def _assert_trees_close(a, b, atol=1e-6):
    flat_a = jax.tree_util.tree_leaves_with_path(a)
    flat_b = jax.tree_util.tree_leaves(b)
    for (path, x), y in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), atol=atol,
            err_msg=jax.tree_util.keystr(path))


# --- fast tier-1 variant (NOT slow — audited by --expect-elastic) ----------

@pytest.mark.core
def test_fast_cross_degree_resume_tiny(tmp_path, capfd):
    """The cross-degree resume path in tier-1: a tiny transformer saved on
    a 2-device dp=2 mesh resumes at dp=1 and lands exactly on the
    uninterrupted trajectory (fixed global batch, LayerNorm model). Also
    pins the elastic stream-meta contract: ``mesh_degree`` is rewritten to
    the live degree (informational), while ``global_batch_size`` is
    enforced — resuming with a different batch is a different optimization
    problem and must fail loudly."""
    ckpt = str(tmp_path / "ckpt")
    tiny = dict(global_batch_size=4,
                data=DataConfig(synthetic=True, dataset="mlm", seq_len=16,
                                vocab_size=512, mlm_max_predictions=3))
    ref = loop.run(_cfg(dp=2, **tiny), total_steps=2, logger=_quiet(),
                   return_state=True)
    loop.run(_cfg(dp=2, checkpoint_dir=ckpt, checkpoint_every_steps=1,
                  **tiny),
             total_steps=1, logger=_quiet())
    meta = json.loads((tmp_path / "ckpt" / "stream_meta.json").read_text())
    assert meta["mesh_degree"] == 2
    assert meta["global_batch_size"] == 4

    part2 = loop.run(_cfg(dp=1, checkpoint_dir=ckpt,
                          checkpoint_every_steps=1, **tiny),
                     total_steps=2, logger=_quiet(), return_state=True)
    assert part2["start_step"] == 1
    # Trajectory-exact across the degree change. Not literally bitwise:
    # a different sharding reduces the gradient in a different order, which
    # moves the last float32 ulp (~1e-13 observed); same-degree resume IS
    # bitwise (test_faults.py::test_chaos_soak_bitwise_identical_recovery).
    _assert_trees_close(_params(part2), _params(ref))
    # The degree change was announced, and the sidecar now records the
    # live degree (rewritten, not clash-checked).
    assert "elastic: resumed a degree-2 checkpoint" in capfd.readouterr().err
    meta = json.loads((tmp_path / "ckpt" / "stream_meta.json").read_text())
    assert meta["mesh_degree"] == 1

    # The enforced half of the contract: same degree games are fine, a
    # CHANGED global batch is rejected before any compile.
    with pytest.raises(RuntimeError, match="global_batch_size"):
        loop.run(_cfg(dp=1, checkpoint_dir=ckpt, checkpoint_every_steps=1,
                      **dict(tiny, global_batch_size=8)),
                 total_steps=3, logger=_quiet())


# --- full-size cross-degree matrix (slow) ----------------------------------

@pytest.mark.slow
@pytest.mark.usefixtures("devices8")
def test_dp8_checkpoint_resumes_on_dp4_exactly(tmp_path):
    """Save at dp=8, resume at dp=4: same trajectory as uninterrupted dp=8
    (global batch fixed; LayerNorm model, so the allreduce-mean gradient is
    mesh-invariant)."""
    ckpt = str(tmp_path / "ckpt")
    ref = loop.run(_cfg(dp=8), total_steps=6, logger=_quiet(),
                   return_state=True)
    loop.run(_cfg(dp=8, checkpoint_dir=ckpt, checkpoint_every_steps=3),
             total_steps=3, logger=_quiet())
    part2 = loop.run(_cfg(dp=4, checkpoint_dir=ckpt,
                          checkpoint_every_steps=3),
                     total_steps=6, logger=_quiet(), return_state=True)
    assert part2["start_step"] == 3
    _assert_trees_close(_params(part2), _params(ref))


@pytest.mark.slow
@pytest.mark.usefixtures("devices8")
def test_dp_checkpoint_resumes_as_fsdp(tmp_path):
    """Save under pure DP, resume under dp=2 x fsdp=2: orbax reshards the
    params onto the new layout; the trajectory continues unchanged."""
    ckpt = str(tmp_path / "ckpt")
    ref = loop.run(_cfg(dp=4), total_steps=4, logger=_quiet(),
                   return_state=True)
    loop.run(_cfg(dp=4, checkpoint_dir=ckpt, checkpoint_every_steps=2),
             total_steps=2, logger=_quiet())
    part2 = loop.run(_cfg(dp=2, fsdp=2, checkpoint_dir=ckpt,
                          checkpoint_every_steps=2),
                     total_steps=4, logger=_quiet(), return_state=True)
    assert part2["start_step"] == 2
    _assert_trees_close(_params(part2), _params(ref), atol=5e-6)


@pytest.mark.slow
@pytest.mark.usefixtures("devices8")
def test_grown_mesh_resume_cnn(tmp_path):
    """Save a BN model at dp=2, resume at dp=8 (scale UP after repair).
    Per-shard BN makes the trajectory legitimately mesh-dependent, so this
    asserts a clean resume and healthy training, not parity. Batch 16
    keeps 2 samples/shard at dp=8 — single-sample BN with a 1x1 final
    feature map degenerates to constant features (classic BN pathology,
    not a sharding bug)."""
    ckpt = str(tmp_path / "ckpt")
    loop.run(_cfg(model="resnet18", dp=2, global_batch_size=16,
                  checkpoint_dir=ckpt, checkpoint_every_steps=2),
             total_steps=2, logger=_quiet())
    part2 = loop.run(_cfg(model="resnet18", dp=8, global_batch_size=16,
                          checkpoint_dir=ckpt, checkpoint_every_steps=2),
                     total_steps=4, logger=_quiet(), return_state=True)
    assert part2["start_step"] == 2
    assert int(jax.device_get(part2["state"].step)) == 4
    assert jnp.isfinite(part2["final_metrics"]["loss"])


# --- the elastic soak (slow): shrink 4->2, grow 2->4, trajectory-exact -----

@pytest.mark.slow
def test_elastic_soak_shrink_grow_trajectory_exact(tmp_path):
    """The capstone: a live 2-host x 2-device dp=4 transformer job under
    ``launch.py --elastic`` loses host 1 (``host_lost@4``: heartbeat
    suppressed + SIGKILL), is attributed as host loss — NOT a transient
    crash — and auto-re-forms at dp=2 with no backoff and no restart-budget
    charge; the survivor later announces a ``host_rejoin`` and the job
    re-forms back at dp=4; the final step-12 params land exactly on an
    uninterrupted fixed-degree dp=4 run of the same workload (to the last
    float32 ulp — the dp=2 segment reduces the fixed global batch in a
    different order; same-degree resume is pinned bitwise in
    test_faults.py), and the final summary carries the measured
    reconfiguration_time_s."""
    steps = 12
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "DDL_FAULT_PLAN",
                        "DDL_RESTART_ATTEMPT", "DDL_ELASTIC_EVENT")}
    # 2 fake devices per process: dp=4 spans the two "hosts".
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"

    def train_cmd(ckpt: str) -> list:
        return [sys.executable, "train.py", "--backend", "cpu", "--model",
                "bert_tiny", "--batch-size", "8", "--dp", "4",
                "--synthetic", "--seq-len", "16", "--dtype", "float32",
                "--steps", str(steps), "--checkpoint-dir", ckpt,
                "--checkpoint-every", "2", "--log-every", "1000000"]

    ref_ckpt = str(tmp_path / "ref")
    ref = subprocess.run(
        [sys.executable, "launch.py", "--num-processes", "2",
         "--port", "9418", "--"] + train_cmd(ref_ckpt),
        capture_output=True, text=True, timeout=900, env=env)
    assert ref.returncode == 0, ref.stderr[-2000:]

    soak_ckpt = str(tmp_path / "soak")
    proc = subprocess.run(
        [sys.executable, "launch.py", "--num-processes", "2", "--elastic",
         "--port", "9418", "--max-restarts", "2", "--backoff", "0.2",
         "--heartbeat-dir", str(tmp_path / "hb"),
         "--child-fault-plan", "1:host_lost@4",
         "--child-fault-plan", "0:host_rejoin@8:a1",
         "--"] + train_cmd(soak_ckpt),
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stderr[-3000:]

    # Shrink: the death was attributed from the heartbeat evidence and
    # re-formed as a PLANNED reconfiguration (no backoff, budget intact).
    assert "[attributed: host_lost]" in proc.stderr
    assert "elastic re-formation (host_lost): degree 4 -> 2" in proc.stderr
    assert "restart 1/" not in proc.stderr  # never charged the budget
    # Grow: the survivor's rejoin announcement stopped the job gracefully
    # and re-formed back at full degree.
    assert "host rejoin announced" in proc.stderr
    assert "elastic re-formation (host_rejoin): degree 2 -> 4" in proc.stderr
    assert "final degree 4 (2/2 hosts)" in proc.stderr

    # The final attempt's summary measures the outage and names its cause.
    lines = [ln for ln in proc.stdout.splitlines() if "summary" in ln]
    assert lines, proc.stderr[-2000:]
    summary = json.loads(lines[-1])["summary"]
    assert summary["final_step"] == steps
    assert summary["elastic_event"]["trigger"] == "host_rejoin"
    assert summary["reconfiguration_time_s"] > 0

    # The final params vs the uninterrupted fixed-degree run: the shrink,
    # the grow, and both resumes erased nothing and changed nothing beyond
    # last-ulp reduction-order noise (fixed global batch, canonical
    # checkpoint layout).
    import orbax.checkpoint as ocp

    def params_at(directory, step):
        # Restore as host numpy: the checkpoints were written by 2-process
        # children whose device ids don't exist in this process, so a
        # shardings-as-saved restore would refuse to load them.
        ckptr = ocp.PyTreeCheckpointer()
        step_dir = os.path.join(directory, str(step), "default")
        meta = ckptr.metadata(step_dir)
        restore_args = jax.tree_util.tree_map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta)
        return ckptr.restore(step_dir, restore_args=restore_args)["params"]

    _assert_trees_close(params_at(ref_ckpt, steps),
                        params_at(soak_ckpt, steps))


# --- the cross-axis soak (slow): dp/pp/ZeRO all change mid-run --------------

@pytest.mark.slow
def test_cross_axis_soak_drain_and_join_reform_mesh(tmp_path):
    """Rendezvous membership end-to-end across ALL THREE axes: a 2-host x
    4-device job running ``bert_tiny_pp44`` (4 stages) at dp=4, pp=2,
    zero2 takes a planned ``host_drain`` (host 1 announces a leave after
    step 4), every member saves collectively at the reform barrier and
    exits voluntarily (rc 75 — no teardown of surviving children), and the
    job re-forms on host 0 as dp=1, pp=4, sharding=none via
    ``--elastic-geometry`` — the DP width shrinks while the ZeRO stage and
    the pipeline degree both change, restoring through the canonical
    checkpoint layout. A ``host_join`` after step 8 re-forms back to the
    full mesh the same way. Final step-12 params land within the
    multi-axis ULP band of an uninterrupted full-mesh run, and the final
    summary carries the detect→drain→restore→compile→first-step phase
    breakdown under the 15 s PR 9 baseline.

    The alternate geometry's program is pre-compiled into the shared AOT
    cache first — the operational pattern the geometry table exists for
    (fallback shapes are known up front, so the fleet pre-warms them;
    schedule-keyed fingerprints make the re-formed compile a cache load).
    """
    steps = 12
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "DDL_FAULT_PLAN",
                        "DDL_RESTART_ATTEMPT", "DDL_ELASTIC_EVENT",
                        "DDL_ELASTIC_EPOCH", "DDL_ELASTIC_HOST")}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["DDL_COMPILE_CACHE"] = str(tmp_path / "aot")  # shared AOT cache

    def train_cmd(ckpt: str, *, dp: int, pp: int, sharding: str) -> list:
        cmd = [sys.executable, "train.py", "--backend", "cpu", "--model",
               "bert_tiny_pp44", "--batch-size", "8", "--dp", str(dp),
               "--pp", str(pp), "--optimizer-sharding", sharding,
               "--synthetic", "--seq-len", "16", "--dtype", "float32",
               "--steps", str(steps), "--log-every", "1000000"]
        if ckpt:
            cmd += ["--checkpoint-dir", ckpt, "--checkpoint-every", "2"]
        return cmd

    # Pre-warm the shrunken geometry's AOT entry (checkpoint knobs are
    # fingerprint-volatile, so this single-process run shares the re-formed
    # attempt's executable key exactly).
    warm = subprocess.run(train_cmd("", dp=1, pp=4, sharding="none"),
                          capture_output=True, text=True, timeout=900,
                          env=env)
    assert warm.returncode == 0, warm.stderr[-2000:]

    ref_ckpt = str(tmp_path / "ref")
    ref = subprocess.run(
        [sys.executable, "launch.py", "--num-processes", "2",
         "--port", "9419", "--"]
        + train_cmd(ref_ckpt, dp=4, pp=2, sharding="zero2"),
        capture_output=True, text=True, timeout=900, env=env)
    assert ref.returncode == 0, ref.stderr[-2000:]

    soak_ckpt = str(tmp_path / "soak")
    proc = subprocess.run(
        [sys.executable, "launch.py", "--num-processes", "2", "--elastic",
         "--port", "9419", "--max-restarts", "2", "--backoff", "0.2",
         "--heartbeat-dir", str(tmp_path / "hb"),
         "--elastic-geometry", "1:dp=1,pp=4,sharding=none",
         "--child-fault-plan", "1:host_drain@4",
         "--child-fault-plan", "0:host_join@8:a1",
         "--"] + train_cmd(soak_ckpt, dp=4, pp=2, sharding="zero2"),
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stderr[-3000:]

    err = proc.stderr
    # Shrink: a PLANNED leave — barrier raised, collective save, every
    # child exits rc 75 on its own; nothing was terminated.
    assert "host drain announced" in err
    assert "drain complete — 2/2 child(ren) exited at the barrier" in err
    assert "after a collective save" in err
    assert "elastic re-formation (host_drain): degree 4 -> 1" in err
    assert "no backoff, budget untouched" in err
    assert "restart 1/" not in err           # budget never charged
    assert "escalating to terminate" not in err
    assert "fail-whole" not in err           # no teardown path, ever
    # Grow: the join announcement drains 1/1 and re-forms the full mesh.
    assert "host rejoin announced (host_join)" in err
    assert "drain complete — 1/1 child(ren) exited at the barrier" in err
    assert "elastic re-formation (host_join): degree 1 -> 4" in err
    assert "final degree 4 (2/2 hosts)" in err
    # Both re-formed attempts announce the cross-axis resume.
    assert ("cross-axis resume — optimizer sharding zero2 -> none, "
            "pipeline 2 -> 4" in err)
    assert ("cross-axis resume — optimizer sharding none -> zero2, "
            "pipeline 4 -> 2" in err)

    # The final attempt's summary: epoch 2, and the measured phase
    # breakdown below the PR 9 whole-event baseline (the grown mesh's
    # program is an AOT cache load, not a recompile).
    lines = [ln for ln in proc.stdout.splitlines() if "summary" in ln]
    assert lines, err[-2000:]
    summary = json.loads(lines[-1])["summary"]
    assert summary["final_step"] == steps
    assert summary["elastic_event"]["trigger"] == "host_join"
    assert summary["elastic_event"]["epoch"] == 2
    phases = summary["reconfiguration_phases"]
    assert set(phases) >= {"total_s", "drain_s", "restore_s", "compile_s",
                           "first_step_s", "spawn_s"}
    assert 0 < summary["reconfiguration_time_s"] < 15.0
    assert phases["total_s"] == summary["reconfiguration_time_s"]

    import orbax.checkpoint as ocp

    def params_at(directory, step):
        ckptr = ocp.PyTreeCheckpointer()
        step_dir = os.path.join(directory, str(step), "default")
        meta = ckptr.metadata(step_dir)
        restore_args = jax.tree_util.tree_map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta)
        return ckptr.restore(step_dir, restore_args=restore_args)["params"]

    # The dp=1/pp=4/none segment reduces and reshards in a different
    # order, so parity is the multi-axis ULP band, not bitwise: the GSPMD
    # partitioner reassociates reductions differently per geometry and SGD
    # integrates the noise linearly (measured 7.5e-9 over a 6-step
    # cross-geometry segment). This band is only this tight because two
    # geometry-dependences were hunted down to it: sharding-dependent
    # threefry bits (package __init__ pins partitionable threefry) and the
    # contiguous microbatch reshape the SPMD partitioner miscompiled under
    # a sharded batch dim (models/pipeline.py strided split;
    # tests/test_pipeline.py::test_pipeline_forward_mesh_invariant). A
    # regression in either reappears here as ~1e-3-per-step drift.
    _assert_trees_close(params_at(ref_ckpt, steps),
                        params_at(soak_ckpt, steps), atol=1e-5)
