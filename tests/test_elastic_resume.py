"""Elastic resume: a checkpoint saved under one mesh restores under another.

The reference-era failure mode this kills: Horovod/NCCL jobs pin their world
size at launch — losing a node means restarting at the same N or not at all.
Here the checkpoint is a sharded pytree with mesh-agnostic global shapes
(orbax), and the data stream is a deterministic function of (seed, step), so
a run can resume on a different device count — or a different parallelism
strategy entirely — and continue training.

Trajectory-exactness caveat, asserted accordingly: transformer models
(LayerNorm — no cross-sample statistics) continue the SAME trajectory on any
mesh at fixed global batch, and the tests demand exact parity. BatchNorm
models intentionally use per-shard statistics (like per-GPU BN under
Horovod, see train/steps.py), so their trajectory depends on the per-shard
batch; the CNN test asserts a clean resume and healthy training, not
bitwise parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.train import loop
from distributeddeeplearning_tpu.utils.logging import MetricLogger

# Every test here compiles multi-device programs — minutes on
# the 1-vCPU CPU harness, so the whole file runs in the slow
# tier (tier-1 keeps its sub-15-min budget).
pytestmark = pytest.mark.slow


def _cfg(model="bert_tiny", dp=8, fsdp=1, **kw) -> TrainConfig:
    data = (DataConfig(synthetic=True, image_size=32, num_classes=10)
            if model.startswith("resnet")
            else DataConfig(synthetic=True, dataset="mlm", seq_len=32,
                            mlm_max_predictions=5))
    base = dict(
        model=model, global_batch_size=8, dtype="float32", log_every=10**9,
        parallel=ParallelConfig(data=dp, fsdp=fsdp), data=data,
        optimizer=OptimizerConfig(schedule="constant", learning_rate=0.01))
    base.update(kw)
    return TrainConfig(**base)


def _quiet():
    return MetricLogger(enabled=False)


def _params(summary):
    return jax.device_get(summary["state"].params)


def _assert_trees_close(a, b, atol=1e-6):
    flat_a = jax.tree_util.tree_leaves_with_path(a)
    flat_b = jax.tree_util.tree_leaves(b)
    for (path, x), y in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), atol=atol,
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.usefixtures("devices8")
def test_dp8_checkpoint_resumes_on_dp4_exactly(tmp_path):
    """Save at dp=8, resume at dp=4: same trajectory as uninterrupted dp=8
    (global batch fixed; LayerNorm model, so the allreduce-mean gradient is
    mesh-invariant)."""
    ckpt = str(tmp_path / "ckpt")
    ref = loop.run(_cfg(dp=8), total_steps=6, logger=_quiet(),
                   return_state=True)
    loop.run(_cfg(dp=8, checkpoint_dir=ckpt, checkpoint_every_steps=3),
             total_steps=3, logger=_quiet())
    part2 = loop.run(_cfg(dp=4, checkpoint_dir=ckpt,
                          checkpoint_every_steps=3),
                     total_steps=6, logger=_quiet(), return_state=True)
    assert part2["start_step"] == 3
    _assert_trees_close(_params(part2), _params(ref))


@pytest.mark.usefixtures("devices8")
def test_dp_checkpoint_resumes_as_fsdp(tmp_path):
    """Save under pure DP, resume under dp=2 x fsdp=2: orbax reshards the
    params onto the new layout; the trajectory continues unchanged."""
    ckpt = str(tmp_path / "ckpt")
    ref = loop.run(_cfg(dp=4), total_steps=4, logger=_quiet(),
                   return_state=True)
    loop.run(_cfg(dp=4, checkpoint_dir=ckpt, checkpoint_every_steps=2),
             total_steps=2, logger=_quiet())
    part2 = loop.run(_cfg(dp=2, fsdp=2, checkpoint_dir=ckpt,
                          checkpoint_every_steps=2),
                     total_steps=4, logger=_quiet(), return_state=True)
    assert part2["start_step"] == 2
    _assert_trees_close(_params(part2), _params(ref), atol=5e-6)


@pytest.mark.usefixtures("devices8")
def test_grown_mesh_resume_cnn(tmp_path):
    """Save a BN model at dp=2, resume at dp=8 (scale UP after repair).
    Per-shard BN makes the trajectory legitimately mesh-dependent, so this
    asserts a clean resume and healthy training, not parity. Batch 16
    keeps 2 samples/shard at dp=8 — single-sample BN with a 1x1 final
    feature map degenerates to constant features (classic BN pathology,
    not a sharding bug)."""
    ckpt = str(tmp_path / "ckpt")
    loop.run(_cfg(model="resnet18", dp=2, global_batch_size=16,
                  checkpoint_dir=ckpt, checkpoint_every_steps=2),
             total_steps=2, logger=_quiet())
    part2 = loop.run(_cfg(model="resnet18", dp=8, global_batch_size=16,
                          checkpoint_dir=ckpt, checkpoint_every_steps=2),
                     total_steps=4, logger=_quiet(), return_state=True)
    assert part2["start_step"] == 2
    assert int(jax.device_get(part2["state"].step)) == 4
    assert jnp.isfinite(part2["final_metrics"]["loss"])
