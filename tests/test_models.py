"""Model-zoo unit tests: parameter counts vs canonical values and forward
shapes (SURVEY.md §4 "Unit"). Counts are checked against the torchvision /
HuggingFace canonical totals, substituting for reference parity while
/root/reference is empty."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu import models


def count_params(tree) -> int:
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(tree))


def abstract_init(name: str):
    spec = models.model_spec(name)
    if spec.input_kind == "tokens":
        model = spec.build(dtype=jnp.float32)
        return jax.eval_shape(
            lambda r: model.init({"params": r, "dropout": r},
                                 jnp.zeros((1, 16), jnp.int32), train=False),
            jax.random.key(0))
    model = spec.build(dtype=jnp.float32)
    return jax.eval_shape(
        lambda r: model.init({"params": r},
                             jnp.zeros((1, 224, 224, 3), jnp.float32),
                             train=False),
        jax.random.key(0))


@pytest.mark.parametrize("name", [
    "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "densenet121", "densenet169", "bert_base", "bert_large",
    "vit_b16", "vit_l16",
])
def test_param_counts(name):
    spec = models.model_spec(name)
    variables = abstract_init(name)
    got = count_params(variables["params"])
    assert got == spec.param_count, (
        f"{name}: {got:,} params, expected {spec.param_count:,}")


def test_resnet50_forward_shape_and_finite():
    model = models.get_model("resnet50", dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 64, 64, 3))
    variables = model.init({"params": jax.random.key(1)}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 1000)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_densenet_forward_shape():
    model = models.get_model("densenet121", dtype=jnp.float32)
    x = jnp.zeros((1, 64, 64, 3))
    variables = model.init({"params": jax.random.key(0)}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 1000)


def test_bert_tiny_forward_shape():
    model = models.get_model("bert_tiny", dtype=jnp.float32)
    ids = jnp.ones((2, 16), jnp.int32)
    variables = model.init({"params": jax.random.key(0), "dropout": jax.random.key(1)},
                           ids, train=False)
    logits = model.apply(variables, ids, train=False)
    assert logits.shape == (2, 16, 1024)
    assert bool(jnp.isfinite(logits).all())


def test_vit_tiny_forward_and_train_smoke():
    """Forward shape + a DP train step: exercises the dropout-rng plumbing
    the image loss fn threads through for transformer image models."""
    model = models.get_model("vit_tiny", dtype=jnp.float32, num_classes=10,
                             dropout_rate=0.1)
    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    variables = model.init({"params": jax.random.key(1)}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert bool(jnp.isfinite(logits).all())
    # train=True requires the dropout rng (dropout_rate > 0).
    out = model.apply(variables, x, train=True,
                      rngs={"dropout": jax.random.key(2)})
    assert bool(jnp.isfinite(out).all())


@pytest.mark.usefixtures("devices8")
def test_vit_trains_in_loop():
    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop

    cfg = TrainConfig(
        model="vit_tiny", global_batch_size=16, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=16, num_classes=10),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  schedule="constant", warmup_epochs=0.0,
                                  label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=2)
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])


def test_bn_stats_update():
    model = models.get_model("resnet18", dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (4, 32, 32, 3))
    variables = model.init({"params": jax.random.key(1)}, x, train=False)
    _, mutated = model.apply(variables, x, train=True,
                             mutable=["batch_stats"])
    old = jax.tree_util.tree_leaves(variables["batch_stats"])
    new = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(a, b) for a, b in zip(old, new))


def test_remat_forward_matches_exact():
    """Rematerialization is numerically exact: same params, same logits."""
    import jax
    import numpy as np
    from distributeddeeplearning_tpu.models import bert

    ids = jax.random.randint(jax.random.key(3), (2, 16), 0, 256)
    plain = bert.tiny_bert_mlm(vocab_size=256)
    variables = plain.init({"params": jax.random.key(0),
                            "dropout": jax.random.key(1)}, ids, train=False)
    remat = bert.tiny_bert_mlm(vocab_size=256, remat=True)
    out_p = plain.apply(variables, ids, train=False)
    out_r = remat.apply(variables, ids, train=False)
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_r))

    # And gradients flow through the remat boundary identically.
    def loss(m, v):
        return m.apply(v, ids, train=False).sum()

    g_p = jax.grad(lambda v: loss(plain, v))(variables)
    g_r = jax.grad(lambda v: loss(remat, v))(variables)
    # 1e-5: remat legitimately reorders the recomputed forward's fp ops
    # (measured max abs gap ~4e-6 on CPU); bitwise is only promised for
    # the forward above.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5), g_p, g_r)
