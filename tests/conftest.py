"""Test env: 8 fake CPU devices, no TPU (SURVEY.md §4 "Distributed-without-
a-cluster").

The image's axon sitecustomize imports jax at interpreter start and pins
``jax_platforms`` via jax.config, so env vars alone are too late here; we
must override through jax.config. XLA_FLAGS still works because no backend
client exists until first use.
"""

import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # for any subprocesses we spawn
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs
