"""Test env: 8 fake CPU devices, no TPU (SURVEY.md §4 "Distributed-without-
a-cluster").

The image's axon sitecustomize imports jax at interpreter start and pins
``jax_platforms`` via jax.config, so env vars alone are too late here; we
must override through jax.config. XLA_FLAGS still works because no backend
client exists until first use.
"""

import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # for any subprocesses we spawn
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs


# --- slow-marker audit (tools/marker_audit.py) -----------------------------
# The tier-1 budget (870 s, ROADMAP) only holds if every long test carries
# @pytest.mark.slow. Each run records (nodeid, call duration, slow?) and
# prints offenders in the terminal summary; MARKER_AUDIT_JSON=<path> dumps
# the records for tools/marker_audit.py to gate on in CI.

_audit_records = []


def pytest_runtest_logreport(report):
    if report.when != "call":
        return
    _audit_records.append({
        "nodeid": report.nodeid,
        "duration": report.duration,
        "slow": "slow" in report.keywords,
        # perf_gate rides along so tools/marker_audit.py can verify the
        # CPU-proxy gate actually ran in this tier-1 pass (a gate that
        # silently fell out of the selection is no gate).
        "perf_gate": "perf_gate" in report.keywords,
        # elastic likewise: tools/marker_audit.py --expect-elastic verifies
        # a fast cross-degree resume test survived in tier-1.
        "elastic": "elastic" in report.keywords,
        # flight likewise: tools/marker_audit.py --expect-flight verifies
        # the crash-surviving flight record is exercised in tier-1.
        "flight": "flight" in report.keywords,
        # lint likewise: tools/marker_audit.py --expect-lint verifies the
        # ddl-lint static-analysis gate actually ran in this tier-1 pass.
        "lint": "lint" in report.keywords,
        # serve likewise: tools/marker_audit.py --expect-serve verifies the
        # engine token-identity pin survived in tier-1.
        "serve": "serve" in report.keywords,
        # chaos likewise: --expect-serve-chaos verifies a serve+chaos soak
        # (replica killed mid-stream, token-identical recovery) survived.
        "chaos": "chaos" in report.keywords,
        # pipeline likewise: --expect-pipeline verifies the schedule
        # parity pins and the pipeline_1f1b perf-gate workload survived.
        "pipeline": "pipeline" in report.keywords,
    })


def pytest_terminal_summary(terminalreporter):
    import json
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.marker_audit import BUDGET_NOTE, find_violations

    out = os.environ.get("MARKER_AUDIT_JSON")
    if out:
        with open(out, "w") as f:
            json.dump(_audit_records, f)
    for rec in find_violations(_audit_records):
        terminalreporter.write_line(
            f"MARKER-AUDIT: {rec['nodeid']} took {rec['duration']:.1f}s "
            f"without @pytest.mark.slow ({BUDGET_NOTE})", yellow=True)
