"""models/flops.py — the analytic FLOPs behind bench.py's MFU fields.

Ground truth: XLA lowered-HLO cost analysis of the FULL train step
(fwd+bwd+optimizer) per example, measured on CPU by
tools/calibrate_flops.py (2026-07-31). The analytic train number is 3 x
forward (no optimizer, no remat recompute — the standard model-FLOPs MFU
convention), so it should land slightly UNDER the step truth for CNNs
(optimizer+BN extras) and within ~11% for gpt2 (XLA charges the lm-head
closer to 2x than 3x; see the pinned value)."""

import pytest

from distributeddeeplearning_tpu.models import flops as flopslib

# (model, seq_len, mlm_positions, step_flops_per_example GFLOP, rel_tol)
CALIBRATED = [
    ("resnet50", None, 0, 23.777, 0.05),
    ("resnet152", None, 0, 66.677, 0.05),
    ("densenet121", None, 0, 16.865, 0.05),
    ("vit_b16", None, 0, 106.178, 0.05),
    ("bert_base", 512, 77, 305.097, 0.05),
    ("bert_base", 512, 0, 367.972, 0.05),
    ("gpt2_small", 1024, 0, 790.642, 0.12),
]


@pytest.mark.core
@pytest.mark.parametrize("model,seq,mlm,truth,tol", CALIBRATED)
def test_analytic_matches_xla_cost_analysis(model, seq, mlm, truth, tol):
    got = flopslib.train_flops_per_example(model, seq_len=seq,
                                           mlm_positions=mlm)
    assert got is not None
    assert abs(got / 1e9 - truth) / truth < tol, (got / 1e9, truth)


@pytest.mark.core
def test_train_is_three_times_forward():
    fwd = flopslib.fwd_flops_per_example("resnet50")
    assert flopslib.train_flops_per_example("resnet50") == 3.0 * fwd


@pytest.mark.core
def test_unknown_or_underspecified_model_returns_none():
    assert flopslib.train_flops_per_example("bert_tiny") is None
    # Token models need a seq_len to be meaningful.
    assert flopslib.train_flops_per_example("gpt2_small") is None


@pytest.mark.core
def test_gather_head_is_cheaper_than_dense():
    g = flopslib.train_flops_per_example("bert_base", seq_len=512,
                                         mlm_positions=77)
    d = flopslib.train_flops_per_example("bert_base", seq_len=512,
                                         mlm_positions=0)
    assert g < d


@pytest.mark.core
def test_bf16_peak_table():
    assert flopslib.bf16_peak_flops("TPU v5 lite") == 197e12
    assert flopslib.bf16_peak_flops("TPU v5p") == 459e12
    assert flopslib.bf16_peak_flops("TPU v4") == 275e12
    assert flopslib.bf16_peak_flops("TPU v6e") == 918e12
    assert flopslib.bf16_peak_flops("cpu") is None


@pytest.mark.core
def test_dtype_aware_peak():
    """peak_flops scores each precision arm against its OWN roof: fp32
    peak is the bf16 peak / 6 (the MXU rate ratio on v4/v5), unknown
    chips stay None, unknown dtypes die loudly (ISSUE 20)."""
    assert flopslib.peak_flops("TPU v4", "bfloat16") == 275e12
    assert flopslib.peak_flops("TPU v4", "float32") == 275e12 / 6.0
    assert flopslib.peak_flops("TPU v5p", "f32") == 459e12 / 6.0
    assert flopslib.peak_flops("cpu", "float32") is None
    with pytest.raises(ValueError, match="unknown compute dtype"):
        flopslib.peak_flops("TPU v4", "int8")
