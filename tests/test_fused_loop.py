"""Fused multi-step train loop (config.steps_per_loop): K steps + on-device
batch generation compiled into one XLA program (lax.scan) must match K
per-step applications from the same state, and every host-side cadence
(logging, eval, checkpoint, fault injection) must fire at the same steps.

Equivalence is asserted from a SHARED starting state over one block with a
BatchNorm-free model: the two paths are the same math but different XLA
programs, so fp reassociation (~1e-6/step) is expected — and BN+ReLU
training on random data amplifies it chaotically, which would swamp any
end-to-end trajectory comparison (observed empirically: 8e-7 param diff
grows to 1e-2 within one ResNet step)."""

import flax.linen as nn
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data import synthetic
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.parallel import sharding as shardlib
from distributeddeeplearning_tpu.train import loop, optim, steps
from distributeddeeplearning_tpu.train.state import TrainState

# Every test here compiles multi-device programs — minutes on
# the 1-vCPU CPU harness, so the whole file runs in the slow
# tier (tier-1 keeps its sub-15-min budget).
pytestmark = pytest.mark.slow


class _TinyNet(nn.Module):
    """BN-free classifier: no cross-example normalization, so the only
    fused-vs-per-step difference is benign fp reassociation."""

    @nn.compact
    def __call__(self, x, train=False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(10)(x)


def _cnn_cfg(**kw):
    base = dict(
        model="resnet18", global_batch_size=16, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=16, num_classes=10),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.01,
                                  reference_batch=16, schedule="constant",
                                  warmup_epochs=0.0))
    base.update(kw)
    return TrainConfig(**base)


def _assert_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(x, y, rtol=rtol, atol=atol),
        jax.device_get(a), jax.device_get(b))


@pytest.mark.usefixtures("devices8")
def test_fused_block_matches_per_step_dp():
    cfg = _cnn_cfg(global_batch_size=32,
                   data=DataConfig(synthetic=True, image_size=8,
                                   num_classes=10))
    mesh = meshlib.make_mesh(cfg.parallel)
    batch_shd = shardlib.batch_sharding(mesh)
    model = _TinyNet()
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 10,
                                 None)
    variables = model.init({"params": jax.random.key(0)},
                           jnp.zeros((1, 8, 8, 3)), train=False)

    def fresh_state():
        params = jax.tree_util.tree_map(jnp.array, variables["params"])
        return TrainState.create(params=params, opt_state=tx.init(params),
                                 batch_stats=None)

    src = synthetic.make_source(cfg, "image", sharding=batch_shd)
    step = steps.make_dp_train_step(model, tx, mesh, cfg, "image")
    fused = steps.make_fused_train_loop(step, src, batch_shd, mesh)
    assert fused is not None
    rng = jax.random.key(1)

    s_ref = fresh_state()
    for i in range(4):
        s_ref, m_ref = step(s_ref, src.batch(i), rng)
    s_fused, m_fused = fused(fresh_state(), rng, 0, 4)

    assert int(s_fused.step) == 4
    _assert_close(s_ref.params, s_fused.params)
    np.testing.assert_allclose(float(m_ref["loss"]), float(m_fused["loss"]),
                               rtol=1e-5)
    # A second block reuses the n=4 executable at a different start offset.
    s_ref2, _ = step(s_ref, src.batch(4), rng)
    for i in range(5, 8):
        s_ref2, _ = step(s_ref2, src.batch(i), rng)
    s_fused2, _ = fused(s_fused, rng, 4, 4)
    _assert_close(s_ref2.params, s_fused2.params)


@pytest.mark.usefixtures("devices8")
def test_fused_matches_per_step_gspmd():
    # LayerNorm (continuous) instead of BN: the loop.run trajectories stay
    # comparable over a few steps under AdamW's small lr.
    def run(spl):
        cfg = TrainConfig(
            model="bert_tiny", global_batch_size=8, dtype="float32",
            log_every=10**9, steps_per_loop=spl,
            parallel=ParallelConfig(data=2, seq=2, model=2),
            data=DataConfig(dataset="mlm", seq_len=32, vocab_size=128),
            optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                                      schedule="linear", label_smoothing=0.0))
        summary = loop.run(cfg, total_steps=5, return_state=True)
        assert summary["final_step"] == 5
        return summary

    s1, s3 = run(1), run(3)
    _assert_close(s1["state"].params, s3["state"].params,
                  rtol=1e-4, atol=1e-6)


@pytest.mark.usefixtures("devices8")
def test_fused_respects_eval_cadence():
    # steps_per_epoch=3, eval every epoch -> evals at step 3 and final at 6,
    # even though steps_per_loop=4 would otherwise stride past step 3.
    cfg = _cnn_cfg(steps_per_loop=4, steps_per_epoch=3, eval_every_epochs=1.0)
    summary = loop.run(cfg, total_steps=6, eval_batches=1)
    assert [step for step, _ in summary["evals"]] == [3, 6]


@pytest.mark.usefixtures("devices8")
def test_fused_respects_fail_at_step():
    cfg = _cnn_cfg(steps_per_loop=4, fail_at_step=3)
    with pytest.raises(SystemExit, match="after step 3"):
        loop.run(cfg, total_steps=6)


@pytest.mark.usefixtures("devices8")
def test_fused_checkpoint_resume(tmp_path):
    # Crash at step 3 under fused blocks, resume, finish; the resumed run
    # must restart from the step-3 checkpoint and complete.
    cfg = _cnn_cfg(steps_per_loop=2, checkpoint_dir=str(tmp_path),
                   checkpoint_every_steps=3, fail_at_step=3)
    with pytest.raises(SystemExit):
        loop.run(cfg, total_steps=6)
    resumed = loop.run(cfg.replace(fail_at_step=None), total_steps=6)
    assert resumed["start_step"] == 3
    assert resumed["final_step"] == 6
    assert np.isfinite(resumed["final_metrics"]["loss"])


@pytest.mark.usefixtures("devices8")
def test_fused_throughput_fields():
    summary = loop.run(_cnn_cfg(steps_per_loop=3), total_steps=7,
                       warmup_steps=1)
    assert summary["examples_per_sec"] > 0
    assert summary["steps_per_sec"] > 0
