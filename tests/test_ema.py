"""EMA shadow parameters (optimizer.ema_decay): update math, eval routing,
checkpoint roundtrip."""

import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu import data as datalib
from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.train import loop
from distributeddeeplearning_tpu.utils.logging import MetricLogger


def _cfg(model="resnet18_thin", ema=0.5, **kw):
    data = (DataConfig(synthetic=True, image_size=32, num_classes=10,
                       synthetic_learnable=True)
            if model.startswith("resnet")
            else DataConfig(synthetic=True, dataset="mlm", seq_len=16,
                            mlm_max_predictions=3))
    base = dict(model=model, global_batch_size=8, dtype="float32",
                log_every=10**9, parallel=ParallelConfig(data=2), data=data,
                optimizer=OptimizerConfig(schedule="constant",
                                          learning_rate=0.05,
                                          ema_decay=ema))
    base.update(kw)
    return TrainConfig(**base)


@pytest.mark.core
@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_ema_matches_manual_recursion():
    cfg = _cfg()
    mesh, model, shd, state, step, _, rng = loop.build(cfg, 3)
    src = datalib.make_source(cfg, "image", shd)
    manual = jax.device_get(state.params)
    for i in range(3):
        state, _ = step(state, src.batch(i), rng)
        p = jax.device_get(state.params)
        manual = jax.tree.map(lambda e, q: 0.5 * e + 0.5 * q, manual, p)
    got = jax.device_get(state.ema_params)
    for (path, a), b in zip(jax.tree_util.tree_leaves_with_path(got),
                            jax.tree_util.tree_leaves(manual)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(path))


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_ema_gspmd_path_and_off_by_default():
    cfg = _cfg(model="bert_tiny", ema=0.9)
    mesh, model, shd, state, step, _, rng = loop.build(cfg, 2)
    assert state.ema_params is not None
    src = datalib.make_source(cfg, "tokens", shd, objective="mlm")
    state, _ = step(state, src.batch(0), rng)
    # EMA moved toward the new params but is not equal to them.
    p = jax.tree_util.tree_leaves(jax.device_get(state.params))
    e = jax.tree_util.tree_leaves(jax.device_get(state.ema_params))
    assert any(np.abs(a - b).max() > 0 for a, b in zip(p, e))

    off = loop.build(_cfg(model="bert_tiny", ema=0.0), 1)[3]
    assert off.ema_params is None


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_eval_scores_ema_weights(tmp_path):
    """decay=0.999 over 20 steps keeps the EMA ~98% at init: trained
    params improve but the eval (which must score the EMA) stays near
    init-level — proving evals route through the shadow weights.
    (decay=1.0 exactly is rejected at build time as a footgun.)"""
    frozen = loop.run(_cfg(ema=0.999, global_batch_size=16), total_steps=20,
                      eval_batches=4, logger=MetricLogger(enabled=False),
                      return_state=True)
    live = loop.run(_cfg(ema=0.0, global_batch_size=16), total_steps=20,
                    eval_batches=4, logger=MetricLogger(enabled=False),
                    return_state=True)
    # The learnable-synthetic task is quickly learnable: live eval beats
    # the frozen-at-init EMA eval.
    assert live["eval_top1"] > frozen["eval_top1"] + 0.2


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_ema_checkpoint_roundtrip(tmp_path):
    ck = str(tmp_path / "ck")
    loop.run(_cfg(checkpoint_dir=ck, checkpoint_every_steps=2),
             total_steps=2, logger=MetricLogger(enabled=False))
    resumed = loop.run(_cfg(checkpoint_dir=ck, checkpoint_every_steps=2),
                       total_steps=4, logger=MetricLogger(enabled=False),
                       return_state=True)
    assert resumed["start_step"] == 2
    assert resumed["state"].ema_params is not None


def test_ema_decay_one_rejected():
    with pytest.raises(ValueError, match="ema_decay"):
        loop.build(_cfg(ema=1.0), 1)


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_eval_only_restores_checkpointed_ema(tmp_path):
    """The reviewer scenario: restore_latest_for_eval must surface the
    CHECKPOINT's EMA (trained shadow weights), never a fresh-init EMA from
    the flag, and must clear a flag-created EMA when the checkpoint has
    none."""
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    ck = str(tmp_path / "ck")
    trained = loop.run(_cfg(checkpoint_dir=ck, checkpoint_every_steps=2),
                       total_steps=2, logger=MetricLogger(enabled=False),
                       return_state=True)
    want = jax.device_get(trained["state"].ema_params)

    # Fresh build (random init) + for-eval restore.
    cfg = _cfg(checkpoint_dir=ck)
    _, _, _, state, _, _, _ = loop.build(cfg, 1)
    ckpt = Checkpointer.create(cfg)
    try:
        restored = ckpt.restore_latest_for_eval(state)
    finally:
        ckpt.close()
    got = jax.device_get(restored.ema_params)
    for (path, a), b in zip(jax.tree_util.tree_leaves_with_path(got),
                            jax.tree_util.tree_leaves(want)):
        np.testing.assert_array_equal(a, b,
                                      err_msg=jax.tree_util.keystr(path))

    # Checkpoint WITHOUT ema + flag on: the flag's fresh-init EMA must be
    # cleared so the eval scores the trained params.
    ck2 = str(tmp_path / "ck2")
    loop.run(_cfg(ema=0.0, checkpoint_dir=ck2, checkpoint_every_steps=2),
             total_steps=2, logger=MetricLogger(enabled=False))
    cfg2 = _cfg(ema=0.5, checkpoint_dir=ck2)
    _, _, _, state2, _, _, _ = loop.build(cfg2, 1)
    ckpt2 = Checkpointer.create(cfg2)
    try:
        restored2 = ckpt2.restore_latest_for_eval(state2)
    finally:
        ckpt2.close()
    assert restored2.ema_params is None


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_training_resume_across_ema_flag_change(tmp_path):
    """restore_latest (the TRAINING resume path) across an --ema-decay
    flip, which previously died in an opaque orbax structure-mismatch
    error (ADVICE r3 #2)."""
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    # Pre-EMA checkpoint, resume WITH the flag: EMA seeded from the
    # restored params, exactly like a fresh run seeds it from init.
    ck = str(tmp_path / "ck")
    loop.run(_cfg(ema=0.0, checkpoint_dir=ck, checkpoint_every_steps=2),
             total_steps=2, logger=MetricLogger(enabled=False))
    cfg = _cfg(ema=0.5, checkpoint_dir=ck)
    state = loop.build(cfg, 1)[3]
    ckpt = Checkpointer.create(cfg)
    try:
        with pytest.warns(UserWarning, match="predates --ema-decay"):
            restored = ckpt.restore_latest(state)
    finally:
        ckpt.close()
    assert int(restored.step) == 2
    for (path, a), b in zip(
            jax.tree_util.tree_leaves_with_path(
                jax.device_get(restored.ema_params)),
            jax.tree_util.tree_leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(a, b,
                                      err_msg=jax.tree_util.keystr(path))

    # EMA checkpoint, resume WITHOUT the flag: loud actionable reject —
    # silently dropping a trained shadow contradicts the dead-knob policy.
    ck2 = str(tmp_path / "ck2")
    loop.run(_cfg(ema=0.5, checkpoint_dir=ck2, checkpoint_every_steps=2),
             total_steps=2, logger=MetricLogger(enabled=False))
    cfg2 = _cfg(ema=0.0, checkpoint_dir=ck2)
    state2 = loop.build(cfg2, 1)[3]
    ckpt2 = Checkpointer.create(cfg2)
    try:
        with pytest.raises(ValueError, match="--ema-decay"):
            ckpt2.restore_latest(state2)
    finally:
        ckpt2.close()
