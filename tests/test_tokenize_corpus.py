"""tools/tokenize_corpus.py: raw text -> packed shards -> config 4 runs
end-to-end from a raw-text fixture (VERDICT r1 #8)."""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import tokenize_corpus as tc  # noqa: E402

from distributeddeeplearning_tpu.config import (  # noqa: E402
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)

WORDS = ["the", "quick", "brown", "fox", "jumps", "over", "lazy", "dog",
         "pack", "my", "box", "with", "five", "dozen", "liquor", "jugs"]
SUBWORDS = ["##s", "##ing", "##ed"]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    """BERT-layout vocab: specials at canonical ids, real tokens >= 1000
    (data/tokens.py treats ids <= 999 as never-masked specials)."""
    rows = ["[PAD]"] + [f"[unused{i}]" for i in range(99)] + [
        "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    rows += [f"[unused{i}]" for i in range(99, 99 + (1000 - len(rows)))]
    assert len(rows) == 1000
    rows += WORDS + SUBWORDS + [".", ","]
    path = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    path.write_text("\n".join(rows) + "\n")
    return str(path)


@pytest.fixture(scope="module")
def corpus_dir(tmp_path_factory):
    rng = np.random.default_rng(0)
    d = tmp_path_factory.mktemp("corpus")
    for f in range(2):
        lines = []
        for _ in range(40):  # documents
            for _ in range(rng.integers(2, 6)):  # sentences
                n = rng.integers(4, 12)
                lines.append(" ".join(rng.choice(WORDS, n)) + " .")
            lines.append("")
        (d / f"part{f}.txt").write_text("\n".join(lines))
    return str(d)


def test_wordpiece_matches_reference_algorithm(vocab_file):
    wp = tc.WordPiece(tc.load_vocab(vocab_file))
    # "jumps" is not in vocab whole, but "jump"+"##s" isn't either (no
    # "jump") — whole word IS in vocab here. Exercise continuation on
    # "foxes" -> fox + ##e? no "##e" -> [UNK]; "dogs" -> dog + ##s.
    ids = wp.encode("The dogs jumps .")
    v = tc.load_vocab(vocab_file)
    assert ids == [v["the"], v["dog"], v["##s"], v["jumps"], v["."]]
    assert wp.encode("zzz")[0] == v["[UNK]"]


def test_shards_shape_and_layout(vocab_file, corpus_dir, tmp_path):
    rc = tc.main(["--input", f"{corpus_dir}/*.txt", "--vocab", vocab_file,
                  "--out-dir", str(tmp_path), "--seq-len", "64",
                  "--shard-size", "128"])
    assert rc == 0
    shards = sorted(tmp_path.glob("train-*.npy"))
    assert shards
    arr = np.load(shards[0])
    v = tc.load_vocab(vocab_file)
    assert arr.dtype == np.int32 and arr.shape[1] == 64
    assert (arr[:, 0] == v["[CLS]"]).all()
    # Every row terminates with [SEP] then only padding.
    for row in arr[:32]:
        sep_pos = np.flatnonzero(row == v["[SEP]"])
        assert len(sep_pos) == 1
        assert (row[sep_pos[0] + 1:] == v["[PAD]"]).all()


def test_config4_runs_from_raw_text(vocab_file, corpus_dir, tmp_path,
                                    devices8):
    """The full acceptance path: raw text -> shards -> MLM training on the
    8-device mesh via the standard loop."""
    from distributeddeeplearning_tpu.train import loop

    rc = tc.main(["--input", f"{corpus_dir}/*.txt", "--vocab", vocab_file,
                  "--out-dir", str(tmp_path), "--seq-len", "32"])
    assert rc == 0
    vocab_size = len(tc.load_vocab(vocab_file))
    cfg = TrainConfig(
        model="bert_tiny", global_batch_size=8, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=8),
        data=DataConfig(dataset="mlm", data_dir=str(tmp_path),
                        synthetic=False, seq_len=32, vocab_size=vocab_size),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  schedule="linear", label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=4)
    assert summary["final_step"] == 4
    assert np.isfinite(summary["final_metrics"]["loss"])


@pytest.mark.slow
def test_mlm_convergence_tool_loss_falls(tmp_path):
    """tools/convergence_mlm.py smoke: the pair-structured corpus drives
    masked-LM eval loss DOWN through the real text->shards->training
    pipeline (the full-scale trajectories live in BASELINE.md)."""
    import json
    import subprocess

    import os as _os
    env = {k: v for k, v in _os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    proc = subprocess.run(
        [sys.executable, "tools/convergence_mlm.py", "--docs", "300",
         "--steps", "40", "--eval-batches", "2"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=str(Path(__file__).resolve().parent.parent))
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = [json.loads(l) for l in proc.stdout.splitlines()
           if "mlm_convergence" in l][-1]
    traj = rec["trajectory"]
    assert len(traj) >= 5
    # Eval loss at the end well below the start (falling, not noise).
    assert traj[-1][1] < traj[0][1] - 0.1, traj
