"""ddl-lint: tier-1 gate + seeded-violation corpus (docs/static_analysis.md).

Two halves, both @pytest.mark.lint (audited by marker_audit --expect-lint):

- The gate: ``tools/ddl_lint.py`` must exit 0 on the clean repo — zero
  false positives is part of the analyzer's contract, so a new rule that
  fires on shipping code either found a real bug (fix the code) or is
  wrong (fix the rule). Never baseline your way past this test.
- The corpus: every rule must fire on its seeded violation and stay
  silent on the sanitized variant. A lint that cannot catch the bug it
  was built for (the PR 5 donation-after-restore crash, the PR 9
  snapshot-before-save corruption, a mismatched replica_groups deadlock)
  is decoration.

Plus tolerant-reader coverage: truncated HLO dumps, unknown custom-call
targets, and garbage inputs must degrade to ``errors`` entries, never
exceptions — a broken analyzer must not read as a broken repo.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from distributeddeeplearning_tpu.analysis import collectives as ca
from distributeddeeplearning_tpu.analysis import donation, lints

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_CLI = os.path.join(REPO, "tools", "ddl_lint.py")

MESH_AXES = {"data", "fsdp"}


def _rules(findings):
    return [f["rule"] for f in findings]


def _run_cli(*args, timeout=420):
    return subprocess.run(
        [sys.executable, LINT_CLI, *args], capture_output=True,
        text=True, cwd=REPO, timeout=timeout)


# ---------------------------------------------------------------------------
# The tier-1 gate: clean repo => exit 0, zero findings
# ---------------------------------------------------------------------------

def test_clean_repo_gate(tmp_path):
    """The acceptance gate: all three passes over the shipping repo come
    back empty. Runs the real CLI (fresh interpreter, same entry CI and
    chip_window.sh use); the fingerprint registry is pointed at a tmp
    file so ambient .cache state can neither mask nor seed a failure."""
    reg = str(tmp_path / "registry.json")
    proc = _run_cli("--json", "--no-record", "--fingerprint-registry", reg)
    assert proc.returncode == 0, f"\n{proc.stdout}\n{proc.stderr}"
    report = json.loads(proc.stdout)
    assert report["ok"] is True
    assert report["findings"] == []
    assert set(report["passes"]) == {"collectives", "donation", "lints"}
    # Both all-reduce programs traced and fingerprinted (bench provenance
    # and the AOT pairing registry consume these).
    for name in ("allreduce_psum", "allreduce_ring"):
        fp = report["collective_schedules"][name]
        assert len(fp) == 16
        int(fp, 16)  # hex
    # psum and ring are different programs; identical fingerprints would
    # mean the fingerprint is not actually a function of the schedule.
    assert (report["collective_schedules"]["allreduce_psum"]
            != report["collective_schedules"]["allreduce_ring"])


def test_checked_in_baseline_is_empty():
    """The repo lints clean, so the committed baseline must stay empty —
    a suppression sneaking in here would un-gate a real finding."""
    with open(os.path.join(REPO, "tools", "ddl_lint_baseline.json"),
              encoding="utf-8") as fh:
        baseline = json.load(fh)
    assert baseline.get("suppressions") == []


# ---------------------------------------------------------------------------
# Seeded corpus: donation pass (the PR 5 / PR 9 bug classes)
# ---------------------------------------------------------------------------

_PR5_REPRO = textwrap.dedent("""
    def run(ckpt, state, batch, rng):
        restored = ckpt.restore_latest(state)
        if restored is not None:
            state = restored
        state, metrics = train_step(state, batch, rng)
        return state, metrics
""")

_PR5_FIXED = textwrap.dedent("""
    def run(ckpt, state, batch, rng):
        restored = ckpt.restore_latest(state)
        if restored is not None:
            state = device_copy(restored)
        state, metrics = train_step(state, batch, rng)
        return state, metrics
""")


def test_donation_hazard_pr5_repro():
    """The exact PR 5 shape: orbax-restored arrays reach the donated
    train_step argument with no device_copy — donated-buffer reuse."""
    findings = donation.analyze_source(_PR5_REPRO, "seed_pr5.py")
    assert "donation-hazard" in _rules(findings), findings
    (f,) = [f for f in findings if f["rule"] == "donation-hazard"]
    assert "train_step" in f["message"]
    assert f["line"] == _PR5_REPRO[:_PR5_REPRO.index("train_step(")
                                   ].count("\n") + 1


def test_donation_hazard_sanitized_by_device_copy():
    assert donation.analyze_source(_PR5_FIXED, "fixed.py") == []


def test_donation_hazard_module_local_donor():
    """A jit with donate_argnums assigned in the module under analysis is
    a donating callee even though it is not in DONATING_CALLEES."""
    src = textwrap.dedent("""
        import jax
        step = jax.jit(_step, donate_argnums=(0,))

        def run(ckpt, state, batch):
            state = ckpt.restore_latest(state)
            return step(state, batch)
    """)
    assert "donation-hazard" in _rules(
        donation.analyze_source(src, "local_donor.py"))


def test_donation_taint_survives_branch_union():
    """Taint from ONE branch of an if/else must survive the join — the
    PR 5 bug only bit when a checkpoint actually existed."""
    src = textwrap.dedent("""
        def run(ckpt, state, batch, rng):
            if resume:
                state = ckpt.restore_latest(state)
            else:
                state = init_state()
            return train_step(state, batch, rng)
    """)
    assert "donation-hazard" in _rules(
        donation.analyze_source(src, "branchy.py"))


def test_snapshot_before_save_pr9_repro():
    """The PR 9 shape: live (donatable) state handed to an async orbax
    StandardSave with no device_copy snapshot."""
    src = textwrap.dedent("""
        def save_ckpt(mngr, state, step):
            mngr.save(step, args=StandardSave(state))
    """)
    findings = donation.analyze_source(src, "seed_pr9.py")
    assert _rules(findings) == ["snapshot-before-save"], findings


def test_snapshot_before_save_fixed_by_snapshot():
    src = textwrap.dedent("""
        def save_ckpt(mngr, state, step):
            snap = device_copy(state)
            mngr.save(step, args=StandardSave(snap))
    """)
    assert donation.analyze_source(src, "fixed_pr9.py") == []


def test_snapshot_before_save_blocking_save_exempt():
    """A save the function itself blocks on cannot race a later donation
    (tools/import_hf.py's one-shot conversion save)."""
    src = textwrap.dedent("""
        def convert(mngr, state):
            mngr.save(0, args=StandardSave(state))
            mngr.wait_until_finished()
    """)
    assert donation.analyze_source(src, "import_like.py") == []


# ---------------------------------------------------------------------------
# Seeded corpus: repo-invariant lints
# ---------------------------------------------------------------------------

def test_lint_sidecar_direct_write():
    src = textwrap.dedent("""
        import json, os

        def dump(repo, payload):
            path = os.path.join(repo, ".cache", "last_foo.json")
            with open(path, "w") as fh:
                json.dump(payload, fh)
    """)
    findings = lints.analyze_source(src, "direct.py", mesh_axes=MESH_AXES)
    assert "sidecar-direct-write" in _rules(findings), findings


def test_lint_sidecar_routed_write_clean():
    src = textwrap.dedent("""
        from distributeddeeplearning_tpu.observability import sidecars

        def dump(payload):
            sidecars.write("last_foo", payload)
    """)
    assert lints.analyze_source(src, "routed.py", mesh_axes=MESH_AXES) == []


def test_lint_fsync_before_fire():
    src = textwrap.dedent("""
        import os, signal

        def fire(sig):
            os.kill(os.getpid(), sig)
    """)
    findings = lints.analyze_source(src, "fire.py", mesh_axes=MESH_AXES)
    assert "fsync-before-fire" in _rules(findings), findings


def test_lint_fsync_before_fire_recorded_clean():
    """faults.py's actual shape: a flight record made durable before the
    self-kill is fine regardless of statement nesting order."""
    src = textwrap.dedent("""
        import os, signal

        def fire(rec, sig):
            rec.record("fault_fired", signal=sig)
            os.kill(os.getpid(), sig)
    """)
    assert lints.analyze_source(src, "fire_ok.py",
                                mesh_axes=MESH_AXES) == []


def test_lint_unpaired_span():
    src = textwrap.dedent("""
        def step(tele):
            tele.span("backward")
            run_backward()
    """)
    findings = lints.analyze_source(src, "span.py", mesh_axes=MESH_AXES)
    assert "unpaired-telemetry-span" in _rules(findings), findings


def test_lint_entered_span_clean():
    src = textwrap.dedent("""
        def step(tele):
            with tele.span("backward"):
                run_backward()
    """)
    assert lints.analyze_source(src, "span_ok.py",
                                mesh_axes=MESH_AXES) == []


def test_lint_perf_record_provenance():
    src = textwrap.dedent("""
        import json

        def emit():
            rec = {"metric": "step_time", "value": 1.0}
            print(json.dumps(rec))
    """)
    findings = lints.analyze_source(src, "perf.py", mesh_axes=MESH_AXES)
    assert "perf-record-provenance" in _rules(findings), findings


def test_lint_perf_record_annotated_clean():
    src = textwrap.dedent("""
        import json

        def emit():
            rec = {"metric": "step_time", "value": 1.0}
            print(json.dumps(perf_report.annotate(rec,
                                                  provenance="fresh")))
    """)
    assert lints.analyze_source(src, "perf_ok.py",
                                mesh_axes=MESH_AXES) == []


def test_lint_axis_name_typo():
    src = textwrap.dedent("""
        import jax

        def g(x):
            return jax.lax.psum(x, "dataa")
    """)
    findings = lints.analyze_source(src, "axes.py", mesh_axes=MESH_AXES)
    assert "axis-name-consistency" in _rules(findings), findings
    assert "dataa" in findings[0]["message"]


def test_lint_axis_names_declared_clean():
    src = textwrap.dedent("""
        import jax

        AXES = ("data", "fsdp")

        def g(x):
            a = jax.lax.psum(x, ("data", "fsdp"))
            return jax.lax.pmean(a, axis_name="data") + jax.lax.psum(
                a, AXES)
    """)
    assert lints.analyze_source(src, "axes_ok.py",
                                mesh_axes=MESH_AXES) == []


# ---------------------------------------------------------------------------
# Seeded corpus: collective-schedule pass
# ---------------------------------------------------------------------------

_HLO_RANK0 = textwrap.dedent("""\
    HloModule step

    ENTRY %main (p0: f32[2]) -> f32[16] {
      %p0 = f32[2]{0} parameter(0)
      %ag = f32[16]{0} all-gather(f32[2]{0} %p0), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
      %ar = f32[16]{0} all-reduce(f32[16]{0} %ag), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add
      ROOT %out = f32[16]{0} copy(f32[16]{0} %ar)
    }
""")

# Same program shape, but rank 1's all-reduce was lowered with split
# replica groups — the classic mismatched-replica_groups deadlock.
_HLO_RANK1 = _HLO_RANK0.replace(
    "all-reduce(f32[16]{0} %ag), replica_groups={{0,1,2,3,4,5,6,7}}",
    "all-reduce(f32[16]{0} %ag), replica_groups={{0,1,2,3},{4,5,6,7}}")


def test_hlo_mismatched_replica_groups_divergence():
    schedules = {"rank0": ca.extract_from_hlo_text(_HLO_RANK0),
                 "rank1": ca.extract_from_hlo_text(_HLO_RANK1)}
    assert schedules["rank0"].errors == ()
    findings = ca.verify_uniform(schedules)
    assert _rules(findings) == ["schedule-divergence"], findings
    # Op 0 (the all-gather) agrees; the finding must park on op 1.
    assert "at op 1" in findings[0]["message"]


def test_hlo_cli_mode_gates_on_divergence(tmp_path):
    """Acceptance: seeded mismatched replica_groups through the real CLI
    exits nonzero; identical dumps exit zero."""
    a = tmp_path / "rank0.hlo.txt"
    b = tmp_path / "rank1.hlo.txt"
    a.write_text(_HLO_RANK0)
    b.write_text(_HLO_RANK1)
    proc = _run_cli("--json", "--hlo", str(a), str(b),
                    "--only", "collectives")
    assert proc.returncode == 1, proc.stdout
    report = json.loads(proc.stdout)
    assert [f["rule"] for f in report["findings"]] == [
        "schedule-divergence"]

    b.write_text(_HLO_RANK0)
    proc = _run_cli("--json", "--hlo", str(a), str(b),
                    "--only", "collectives")
    assert proc.returncode == 0, proc.stdout


def test_jaxpr_extraction_fingerprints_collectives(devices8):
    """schedule_of sees through shard_map's sub-jaxpr and the fingerprint
    is a function of the actual op sequence."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributeddeeplearning_tpu import compat
    from distributeddeeplearning_tpu.config import ParallelConfig
    from distributeddeeplearning_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(ParallelConfig(data=8), backend="cpu")

    def one(x):
        return jax.lax.psum(x, ("data", "fsdp"))

    def two(x):
        return jax.lax.psum(jax.lax.psum(x, "data"), ("data", "fsdp"))

    def trace(f):
        fn = compat.shard_map(f, mesh=mesh, in_specs=P(("data", "fsdp")),
                              out_specs=P())
        return ca.schedule_of(fn, jnp.ones((8, 2)))

    one_s, two_s = trace(one), trace(two)
    assert [op.kind for op in one_s.ops] == ["psum"], one_s.describe()
    assert one_s.ops[0].axes == ("data", "fsdp")
    assert one_s.errors == ()
    assert [op.kind for op in two_s.ops] == ["psum", "psum"]
    assert one_s.fingerprint() != two_s.fingerprint()


def test_aot_pairing_divergence(tmp_path):
    reg = str(tmp_path / "registry.json")
    assert ca.check_aot_pairing("cfg1", "prog", "aaaa",
                                registry_path=reg) == []
    # Same pair again: silent.
    assert ca.check_aot_pairing("cfg1", "prog", "aaaa",
                                registry_path=reg) == []
    # Same config fingerprint, different schedule: the AOT contract break.
    findings = ca.check_aot_pairing("cfg1", "prog", "bbbb",
                                    registry_path=reg)
    assert _rules(findings) == ["aot-schedule-pairing"], findings
    # A different config is a new pair, not a divergence.
    assert ca.check_aot_pairing("cfg2", "prog", "bbbb",
                                registry_path=reg) == []


# ---------------------------------------------------------------------------
# Tolerant readers: degrade, never crash
# ---------------------------------------------------------------------------

def test_truncated_hlo_degrades():
    # Tear the dump mid-replica_groups on the all-gather line: the op is
    # kept (without groups), the tear is reported, nothing raises.
    idx = _HLO_RANK0.index("replica_groups={{0,1,2,3")
    torn = _HLO_RANK0[:idx + len("replica_groups={{0,1,2")]
    sched = ca.extract_from_hlo_text(torn)
    assert any("truncated" in e for e in sched.errors), sched.errors
    assert any("mid-brace" in e for e in sched.errors), sched.errors
    assert [op.kind for op in sched.ops] == ["all-gather"]
    assert sched.ops[0].groups is None
    sched.fingerprint()  # partial schedule still fingerprints


def test_unknown_custom_call_tolerated():
    text = ('  %cc = f32[8]{0} custom-call(f32[8]{0} %x), '
            'custom_call_target="mosaic_pallas_mystery_kernel"\n')
    sched = ca.extract_from_hlo_text(text)
    assert len(sched.ops) == 1
    assert sched.ops[0].kind == "custom-call"
    assert "tolerated" in (sched.ops[0].note or "")
    assert sched.errors == ()


def test_known_custom_call_collective_kept():
    text = ('  %cc = f32[8]{0} custom-call(f32[8]{0} %x), '
            'custom_call_target="xla.gpu.AllReduceKernel"\n')
    sched = ca.extract_from_hlo_text(text)
    assert sched.ops[0].kind.startswith("custom-call:")


def test_garbage_inputs_never_raise():
    for junk in (None, 42, object(), "not a jaxpr"):
        sched = ca.extract_from_jaxpr(junk)
        assert isinstance(sched, ca.Schedule)
    sched = ca.extract_from_hlo_text(b"bytes not text")
    assert sched.ops == () and sched.errors
    assert donation.analyze_source("def broken(:", "bad.py")[0][
        "rule"] == "unparseable"
    assert lints.analyze_source("def broken(:", "bad.py")[0][
        "rule"] == "unparseable"


def test_async_hlo_pairs_count_once():
    text = textwrap.dedent("""\
        %s = f32[8]{0} all-reduce-start(f32[8]{0} %x), replica_groups={{0,1}}
        %d = f32[8]{0} all-reduce-done(f32[8]{0} %s)
    """)
    sched = ca.extract_from_hlo_text(text)
    assert [op.kind for op in sched.ops] == ["all-reduce"]


# ---------------------------------------------------------------------------
# Baseline suppression workflow
# ---------------------------------------------------------------------------

def test_baseline_suppresses_via_cli(tmp_path):
    seeded = tmp_path / "seeded_violation.py"
    seeded.write_text(_PR5_REPRO)

    proc = _run_cli("--json", "--paths", str(seeded), "--baseline", "none")
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    assert any(f["rule"] == "donation-hazard"
               for f in report["findings"])

    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"suppressions": [
        {"rule": "donation-hazard", "file": "seeded_violation.py"}]}))
    proc = _run_cli("--json", "--paths", str(seeded),
                    "--baseline", str(baseline))
    assert proc.returncode == 0, proc.stdout
    report = json.loads(proc.stdout)
    assert report["findings"] == []
    assert any(f["rule"] == "donation-hazard"
               for f in report["suppressed"])


# ---------------------------------------------------------------------------
# Bench provenance: records name the schedule they measured under
# ---------------------------------------------------------------------------

def test_annotate_attaches_schedule_fingerprints(monkeypatch, tmp_path):
    import time as _time

    from distributeddeeplearning_tpu.observability import (perf_report,
                                                           sidecars)

    monkeypatch.setattr(sidecars, "cache_dir", lambda: str(tmp_path))
    sidecars.write("last_ddl_lint", {
        "ok": True, "collective_schedules": {"allreduce_psum": "abcd"}})

    rec = perf_report.annotate({"metric": "m", "value": 1.0},
                               provenance="fresh", with_backend=False)
    assert rec["collective_schedules"] == {"allreduce_psum": "abcd"}

    # Error records measured nothing; no schedule to name.
    err = perf_report.annotate({"metric": "m", "value": None, "error": "x"},
                               provenance="error", with_backend=False)
    assert "collective_schedules" not in err

    # A stale lint run describes some other build: not attached.
    sidecars.write("last_ddl_lint", {
        "ok": True, "collective_schedules": {"allreduce_psum": "abcd"},
        "written_at": _time.time()
        - 2 * perf_report.LINT_SCHEDULES_MAX_AGE_S})
    old = perf_report.annotate({"metric": "m", "value": 1.0},
                               provenance="fresh", with_backend=False)
    assert "collective_schedules" not in old


def test_lint_cow_before_write():
    """The serve fast path's COW audit invariant: a function dispatching
    a KV page copy with no prior flight record leaves shared-page bugs
    unattributable."""
    src = textwrap.dedent("""
        def admit(self, src_page, dst_page):
            self._run_page_copy(src_page, dst_page)
    """)
    findings = lints.analyze_source(src, "cow.py", mesh_axes=MESH_AXES)
    assert "cow-before-write" in _rules(findings), findings


# ---------------------------------------------------------------------------
# Seeded corpus: pipeline-schedule-pairing (MPMD permute deadlock class)
# ---------------------------------------------------------------------------

def _pipe_table(name="1f1b", p=2, m=4, v=2):
    from distributeddeeplearning_tpu.models import pipeline as plib
    return plib.build_schedule(name, num_stages=p, num_microbatches=m,
                               virtual_stages=v)


def test_pipeline_pairing_clean_corpus():
    """Every schedule geometry the repo ships — registry pp models' (P, M)
    under gpipe plus the interleaved variants — verifies pairing-clean.
    A finding here is a real deadlock in the shipped schedule table."""
    for name, p, m, v in (("gpipe", 2, 4, 1), ("gpipe", 4, 8, 1),
                          ("gpipe", 2, 6, 1), ("1f1b", 2, 4, 1),
                          ("1f1b", 2, 4, 2), ("1f1b", 4, 8, 2),
                          ("1f1b", 2, 8, 4)):
        table = _pipe_table(name, p, m, v)
        assert ca.verify_pipeline_pairing(f"{name}_p{p}m{m}v{v}",
                                          table) == []


def test_pipeline_pairing_fires_on_wrap_inject_collision():
    """Seeded violation: an inject flag forced onto a wrap-receive tick.
    Stage 0's program would take the ring wrap and a fresh microbatch in
    the same shift — the colliding-writes half of the deadlock class —
    and the conservation check sees a phantom injection."""
    import dataclasses

    table = _pipe_table()
    ticks = list(table.ticks)
    for i, tk in enumerate(ticks):
        if tk.occupancy[0] is not None and tk.occupancy[0][1] > 0:
            ticks[i] = dataclasses.replace(tk, inject_mb=99)
            break
    bad = dataclasses.replace(table, ticks=tuple(ticks))
    findings = ca.verify_pipeline_pairing("seeded", bad)
    assert findings and set(_rules(findings)) == {
        "pipeline-schedule-pairing"}
    assert any("waits on a send" in f["message"] for f in findings)


def test_pipeline_pairing_fires_on_divergent_stage_view():
    """Seeded violation: one tick's occupancy permuted across stages — as
    if stage programs were generated from different tables. The dataflow
    check names the tick where the per-stage schedules disagree."""
    import dataclasses

    table = _pipe_table()
    ticks = list(table.ticks)
    tk = ticks[3]
    ticks[3] = dataclasses.replace(tk, occupancy=tuple(
        reversed(tk.occupancy)))
    bad = dataclasses.replace(table, ticks=tuple(ticks))
    findings = ca.verify_pipeline_pairing("seeded", bad)
    assert any("per-stage schedules disagree" in f["message"]
               for f in findings), findings
    assert set(_rules(findings)) == {"pipeline-schedule-pairing"}


def test_permute_schedule_fingerprints_differ_by_geometry():
    """The rendered permute schedule is a function of (schedule, P, M, V):
    gpipe (no wrap traffic) and 1f1b at the same geometry must not
    collide, nor must different V."""
    fps = {(n, p, m, v): ca.permute_schedule(
               _pipe_table(n, p, m, v)).fingerprint()
           for n, p, m, v in (("gpipe", 2, 4, 1), ("1f1b", 2, 4, 2),
                              ("1f1b", 2, 8, 2))}
    assert len(set(fps.values())) == len(fps)
    ops = ca.permute_schedule(_pipe_table("1f1b", 2, 4, 2)).ops
    assert all(op.kind == "ppermute" and op.axes == ("pipeline",)
               for op in ops)


def test_hlo_source_target_pairs_extracted():
    """collective-permute pairs come out of an HLO dump and participate
    in the fingerprint — two stage programs lowered with different pair
    lists must diverge."""
    a = ('  %cp = f32[8]{0} collective-permute(f32[8]{0} %x), '
         'source_target_pairs={{0,1},{1,0}}\n')
    b = ('  %cp = f32[8]{0} collective-permute(f32[8]{0} %x), '
         'source_target_pairs={{0,1}}\n')
    sa, sb = ca.extract_from_hlo_text(a), ca.extract_from_hlo_text(b)
    assert sa.ops[0].kind == "collective-permute"
    assert sa.ops[0].pairs == ((0, 1), (1, 0))
    assert sb.ops[0].pairs == ((0, 1),)
    assert sa.fingerprint() != sb.fingerprint()
    findings = ca.verify_uniform({"stage0": sa, "stage1": sb})
    assert _rules(findings) == ["schedule-divergence"]


def test_jaxpr_ppermute_pairs_extracted(devices8):
    """jaxpr extraction captures the `perm` pairs of a ppermute — the
    shift pattern the pipeline's activation ring compiles down to."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributeddeeplearning_tpu import compat
    from distributeddeeplearning_tpu.config import ParallelConfig
    from distributeddeeplearning_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(ParallelConfig(data=8), backend="cpu")
    perm = [(k, (k + 1) % 8) for k in range(8)]

    def f(x):
        return jax.lax.ppermute(x, "data", perm)

    fn = compat.shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P())
    sched = ca.schedule_of(fn, jnp.ones((8, 2)))
    assert [op.kind for op in sched.ops] == ["ppermute"], sched.describe()
    assert sched.ops[0].pairs == tuple(perm)


def test_lint_cow_recorded_clean():
    """engine.py's actual shape: the serve_cow_copy record precedes the
    copy dispatch."""
    src = textwrap.dedent("""
        def admit(self, rec, src_page, dst_page):
            rec.record("serve_cow_copy", src=src_page, dst=dst_page)
            self._run_page_copy(src_page, dst_page)
    """)
    assert lints.analyze_source(src, "cow_ok.py",
                                mesh_axes=MESH_AXES) == []


# ---------------------------------------------------------------------------
# Seeded corpus: master-weight-cast (ISSUE 20)
# ---------------------------------------------------------------------------

def test_lint_master_weight_cast_astype_fires():
    src = textwrap.dedent("""
        import jax.numpy as jnp

        def shrink(state):
            return state.opt_state.astype(jnp.bfloat16)
    """)
    findings = lints.analyze_source(src, "cast.py", mesh_axes=MESH_AXES)
    assert "master-weight-cast" in _rules(findings), findings


def test_lint_master_weight_cast_constructor_fires():
    """A dtype=-carrying array constructor retypes its argument just as
    silently as astype."""
    src = textwrap.dedent("""
        import jax.numpy as jnp

        def shrink(master_params):
            return jnp.asarray(master_params, dtype="float16")
    """)
    findings = lints.analyze_source(src, "ctor.py", mesh_axes=MESH_AXES)
    assert "master-weight-cast" in _rules(findings), findings


def test_lint_master_weight_cast_fp32_and_params_clean():
    """fp32 casts of masters, and sub-fp32 casts of NON-master values
    (activations, gathered params on the wire), are both fine."""
    src = textwrap.dedent("""
        import jax.numpy as jnp

        def keep(state, chunks):
            a = state.opt_state.astype(jnp.float32)
            b = chunks.astype(jnp.bfloat16)
            return a, b
    """)
    assert lints.analyze_source(src, "clean.py",
                                mesh_axes=MESH_AXES) == []


def test_lint_master_weight_cast_sanctioned_helper_clean():
    """parallel/zero.py's gather helpers legitimately cast to the wire
    dtype; their bodies are exempt by name."""
    src = textwrap.dedent("""
        import jax.numpy as jnp

        def _gather_members(opt_state_chunks, out_dtype):
            return opt_state_chunks.astype(jnp.bfloat16)
    """)
    assert lints.analyze_source(src, "sanctioned.py",
                                mesh_axes=MESH_AXES) == []


def test_lint_master_weight_cast_repo_clean():
    """The rule must hold on the real precision-policy code: steps.py and
    zero.py cast activations/gathered params, never masters."""
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in ("distributeddeeplearning_tpu/train/steps.py",
                "distributeddeeplearning_tpu/parallel/zero.py",
                "distributeddeeplearning_tpu/train/optim.py"):
        with open(os.path.join(root, rel)) as fh:
            findings = [f for f in lints.analyze_source(
                fh.read(), rel, mesh_axes=MESH_AXES)
                if f["rule"] == "master-weight-cast"]
        assert findings == [], (rel, findings)
