"""Autoregressive generation (models/generate.py): greedy generation must
equal manual step-by-step argmax with exact-length forwards (pad handling),
sampling must be deterministic in the seed, and the CLI must restore a
checkpoint end-to-end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.models import gpt, llama
from distributeddeeplearning_tpu.models.generate import generate


def _tiny(family):
    if family == "gpt":
        model = gpt.tiny_gpt(vocab_size=97, dropout_rate=0.0)
    else:
        model = llama.tiny_llama(vocab_size=97)
    ids = jnp.ones((2, 4), jnp.int32)
    variables = model.init({"params": jax.random.key(0)}, ids, train=False)
    return model, variables


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_greedy_matches_manual_rollout(family):
    """The padded fixed-shape scan must produce exactly what running the
    model on the exact-length (unpadded) prefix produces each step."""
    model, variables = _tiny(family)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 97, (2, 4)).astype(np.int32)

    out = generate(model, variables, prompt, max_new_tokens=3)
    assert out.shape == (2, 7)
    np.testing.assert_array_equal(np.asarray(out[:, :4]), prompt)

    seq = jnp.asarray(prompt)
    for _ in range(3):
        logits = model.apply(variables, seq, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_sampling_deterministic_and_topk():
    model, variables = _tiny("gpt")
    prompt = np.ones((1, 3), np.int32)
    a = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=0.8, top_k=10, rng=jax.random.key(7))
    b = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=0.8, top_k=10, rng=jax.random.key(7))
    c = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=0.8, top_k=10, rng=jax.random.key(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert ((np.asarray(a)[:, 3:] >= 0) & (np.asarray(a)[:, 3:] < 97)).all()


def test_generate_cli_roundtrip(tmp_path):
    """Train a tiny causal LM briefly with checkpointing, then sample from
    the saved checkpoint through the CLI."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ck = str(tmp_path / "ckpt")
    r1 = subprocess.run(
        [sys.executable, "train.py", "--backend", "cpu", "--model",
         "gpt_tiny", "--batch-size", "4", "--dp", "1", "--synthetic",
         "--dtype", "float32", "--steps", "2", "--seq-len", "16",
         "--log-every", "10", "--checkpoint-dir", ck,
         "--optimizer", "adamw", "--lr", "1e-3"],
        cwd=repo, capture_output=True, text=True, timeout=420)
    assert r1.returncode == 0, r1.stderr[-800:]
    r2 = subprocess.run(
        [sys.executable, "generate.py", "--backend", "cpu", "--model",
         "gpt_tiny", "--checkpoint-dir", ck, "--prompt-ids", "5,6,7",
         "--prompt-ids", "8,9,10", "--max-new-tokens", "4"],
        cwd=repo, capture_output=True, text=True, timeout=420)
    assert r2.returncode == 0, r2.stderr[-800:]
    rows = [json.loads(line) for line in r2.stdout.strip().splitlines()]
    assert len(rows) == 2
    assert rows[0]["tokens"][:3] == [5, 6, 7]
    assert len(rows[0]["tokens"]) == 7


def test_cached_decode_matches_full_refeed():
    """KV-cache incremental decoding (decode=True, O(S)/token) produces the
    IDENTICAL greedy continuation as the full-refeed path."""
    from distributeddeeplearning_tpu.models import generate as genlib
    from distributeddeeplearning_tpu.models import gpt

    model = gpt.tiny_gpt(vocab_size=128, dtype=jnp.float32, seq_len=32)
    prompt = jnp.asarray([[5, 17, 9], [2, 4, 6]], jnp.int32)
    variables = model.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)},
        jnp.zeros((2, 8), jnp.int32), train=False)

    full = genlib.generate(model, variables, prompt, max_new_tokens=6)
    cached = genlib.generate(model, variables, prompt, max_new_tokens=6,
                             use_cache=True)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))


def test_cached_decode_sampled_parity_and_guards():
    """temperature>0 sampling is path-identical at the same seed (the RNG
    advances once per emitted token on both paths); over-length and
    non-decode models are rejected loudly."""
    from distributeddeeplearning_tpu.models import generate as genlib
    from distributeddeeplearning_tpu.models import gpt

    model = gpt.tiny_gpt(vocab_size=128, dtype=jnp.float32, seq_len=32)
    prompt = jnp.asarray([[5, 17, 9]], jnp.int32)
    variables = model.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)},
        jnp.zeros((1, 8), jnp.int32), train=False)
    kw = dict(max_new_tokens=5, temperature=0.8, top_k=20,
              rng=jax.random.key(7))
    full = genlib.generate(model, variables, prompt, **kw)
    cached = genlib.generate(model, variables, prompt, use_cache=True, **kw)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))

    with pytest.raises(ValueError, match="max_position"):
        genlib.generate(model, variables, prompt, max_new_tokens=1000,
                        use_cache=True)
    # BERT has no decode mode -> loud reject.
    from distributeddeeplearning_tpu.models import bert
    bm = bert.tiny_bert_mlm(vocab_size=128, dtype=jnp.float32)
    bv = bm.init({"params": jax.random.key(0), "dropout": jax.random.key(1)},
                 jnp.zeros((1, 8), jnp.int32), train=False)
    with pytest.raises(ValueError, match="decode"):
        genlib.generate(bm, bv, prompt, max_new_tokens=2, use_cache=True)


def test_llama_cached_decode_matches_full_refeed():
    """Llama (GQA 4/2, RoPE at absolute decode index, kv-head-width cache):
    cached greedy continuation == full refeed."""
    from distributeddeeplearning_tpu.models import generate as genlib
    from distributeddeeplearning_tpu.models import llama

    model = llama.tiny_llama(vocab_size=128, dtype=jnp.float32)
    prompt = jnp.asarray([[5, 17, 9], [2, 4, 6]], jnp.int32)
    variables = model.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)},
        jnp.zeros((2, 8), jnp.int32), train=False)
    full = genlib.generate(model, variables, prompt, max_new_tokens=6)
    cached = genlib.generate(model, variables, prompt, max_new_tokens=6,
                             use_cache=True)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))


def test_beam1_equals_greedy():
    """Beam search with num_beams=1 is exactly greedy decoding."""
    from distributeddeeplearning_tpu.models.generate import generate_beam

    model, variables = _tiny("gpt")
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, 97, (2, 4)).astype(np.int32)
    greedy = generate(model, variables, prompt, max_new_tokens=4)
    beam = generate_beam(model, variables, prompt, max_new_tokens=4,
                         num_beams=1)
    np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))


def test_beam_matches_exhaustive_search():
    """num_beams = vocab_size is exhaustive: the returned hypothesis must
    be the true argmax-probability continuation. A tiny vocab keeps the
    K*V candidate space exact."""
    import itertools

    from distributeddeeplearning_tpu.models.generate import generate_beam

    model = gpt.tiny_gpt(vocab_size=7, dropout_rate=0.0)
    ids = jnp.ones((1, 3), jnp.int32)
    variables = model.init({"params": jax.random.key(2)}, ids, train=False)
    prompt = np.array([[1, 2, 3]], np.int32)

    out = generate_beam(model, variables, prompt, max_new_tokens=2,
                        num_beams=7)

    def seq_logprob(cont):
        seq = jnp.asarray(np.concatenate([prompt[0], cont])[None, :])
        logits = model.apply(variables, seq, train=False)
        lp = jax.nn.log_softmax(logits[0])
        return float(lp[2, cont[0]] + lp[3, cont[1]])

    best = max(itertools.product(range(7), repeat=2), key=seq_logprob)
    np.testing.assert_array_equal(np.asarray(out[0, 3:]), np.asarray(best))


def test_beam_improves_or_matches_greedy_logprob():
    """The beam-4 hypothesis never scores below the greedy rollout (beam
    search explores a superset of greedy's single path)."""
    from distributeddeeplearning_tpu.models.generate import generate_beam

    model, variables = _tiny("gpt")
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, 97, (2, 4)).astype(np.int32)
    n = 5

    def score(full):
        logits = model.apply(variables, jnp.asarray(full), train=False)
        lp = jax.nn.log_softmax(logits)
        tot = []
        for b in range(full.shape[0]):
            s = sum(float(lp[b, 4 + t - 1, full[b, 4 + t]])
                    for t in range(n))
            tot.append(s)
        return np.array(tot)

    greedy = np.asarray(generate(model, variables, prompt, max_new_tokens=n))
    beam = np.asarray(generate_beam(model, variables, prompt,
                                    max_new_tokens=n, num_beams=4))
    assert (score(beam) >= score(greedy) - 1e-4).all()


def test_beam_eos_freezes_and_pads():
    """Once a beam emits eos_id it extends only with pad at frozen score."""
    from distributeddeeplearning_tpu.models.generate import generate_beam

    model, variables = _tiny("gpt")
    rng = np.random.default_rng(4)
    prompt = rng.integers(1, 97, (1, 4)).astype(np.int32)
    # Pick an eos id a surviving beam actually emits: the first generated
    # token of the no-eos winner. length_penalty=0 ranks by raw summed
    # log-prob, so the 1-token finished beam (least negative sum) must win
    # the final ranking — guaranteeing the returned hypothesis exercises
    # the freeze-and-pad path.
    free = np.asarray(generate_beam(model, variables, prompt,
                                    max_new_tokens=6, num_beams=3))
    eos = int(free[0, 4])
    out = np.asarray(generate_beam(model, variables, prompt,
                                   max_new_tokens=6, num_beams=3,
                                   eos_id=eos, pad_id=0,
                                   length_penalty=0.0))
    gen = out[0, 4:]
    assert (gen == eos).any(), "eos was never emitted; test setup broken"
    after = gen[np.argmax(gen == eos) + 1:]
    assert (after == 0).all()


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_cached_beam_matches_refeed_beam(family):
    """KV-cache beam search emits exactly what the full-refeed beam emits
    (per-beam cache reorder is the only new machinery)."""
    from distributeddeeplearning_tpu.models.generate import generate_beam

    model, variables = _tiny(family)
    rng = np.random.default_rng(8)
    prompt = rng.integers(1, 97, (2, 4)).astype(np.int32)
    ref = np.asarray(generate_beam(model, variables, prompt,
                                   max_new_tokens=5, num_beams=3))
    cached = np.asarray(generate_beam(model, variables, prompt,
                                      max_new_tokens=5, num_beams=3,
                                      use_cache=True))
    np.testing.assert_array_equal(cached, ref)


def test_cached_beam_eos_matches_refeed():
    from distributeddeeplearning_tpu.models.generate import generate_beam

    model, variables = _tiny("gpt")
    rng = np.random.default_rng(9)
    prompt = rng.integers(1, 97, (1, 4)).astype(np.int32)
    free = np.asarray(generate_beam(model, variables, prompt,
                                    max_new_tokens=6, num_beams=3))
    eos = int(free[0, 4])
    kw = dict(max_new_tokens=6, num_beams=3, eos_id=eos, pad_id=0,
              length_penalty=0.0)
    ref = np.asarray(generate_beam(model, variables, prompt, **kw))
    cached = np.asarray(generate_beam(model, variables, prompt,
                                      use_cache=True, **kw))
    np.testing.assert_array_equal(cached, ref)


def test_cached_beam_overflow_guard():
    """Cached beam search must raise (not silently clamp) when
    prompt+max_new_tokens exceeds the cache length — parity with the
    sampling path's guard."""
    from distributeddeeplearning_tpu.models.generate import generate_beam

    model, variables = _tiny("gpt")  # max_position defaults to 128
    prompt = np.ones((1, 4), np.int32)
    with pytest.raises(ValueError, match="max_position|decode_cache_len"):
        generate_beam(model, variables, prompt, max_new_tokens=1000,
                      num_beams=2, use_cache=True)


def test_cached_beam_zero_new_tokens_returns_prompt():
    """max_new_tokens=0 must return the prompt untouched in BOTH beam
    paths — the cached path's trailing out-of-scan select must not fire."""
    from distributeddeeplearning_tpu.models.generate import generate_beam

    model, variables = _tiny("gpt")
    prompt = np.ones((2, 4), np.int32) * 3
    for use_cache in (False, True):
        out = generate_beam(model, variables, prompt, max_new_tokens=0,
                            num_beams=2, use_cache=use_cache)
        np.testing.assert_array_equal(np.asarray(out), prompt,
                                      err_msg=f"use_cache={use_cache}")


def test_beam_cache_map_rejects_unknown_leaf():
    """Cache leaves are classified by NAME; a leaf beam search was never
    taught must be rejected, not silently guessed from its leading-dim
    size (which mis-expands whenever the size coincides with the batch)."""
    from distributeddeeplearning_tpu.models.generate import (
        _map_batched_cache)

    cache = {"layer0": {"cached_key": jnp.zeros((2, 4, 2, 8)),
                        "cache_index": jnp.zeros((), jnp.int32),
                        "mystery_state": jnp.zeros((2,))}}
    with pytest.raises(ValueError, match="mystery_state"):
        _map_batched_cache(cache, lambda x: x)
    # And the known layout maps only the batched leaves.
    out = _map_batched_cache(
        {"layer0": {"cached_key": jnp.zeros((2, 3)),
                    "cached_value": jnp.ones((2, 3)),
                    "cache_index": jnp.zeros((), jnp.int32)}},
        lambda x: jnp.repeat(x, 2, axis=0))
    assert out["layer0"]["cached_key"].shape == (4, 3)
    assert out["layer0"]["cache_index"].shape == ()


def test_speculative_matches_target_greedy():
    """Speculative decoding's whole contract: EXACTLY the target model's
    greedy continuation, regardless of what the draft proposes."""
    from distributeddeeplearning_tpu.models.generate import (
        generate_speculative)

    target = gpt.tiny_gpt(vocab_size=97, dropout_rate=0.0)
    draft = gpt.GptLM(gpt.GptConfig(
        vocab_size=97, hidden_size=32, num_layers=1, num_heads=2,
        max_position=128, dropout_rate=0.0), dtype=jnp.float32)
    ids = jnp.ones((1, 4), jnp.int32)
    tv = target.init({"params": jax.random.key(0)}, ids, train=False)
    dv = draft.init({"params": jax.random.key(1)}, ids, train=False)

    rng = np.random.default_rng(11)
    prompt = rng.integers(1, 97, (1, 5)).astype(np.int32)
    ref = np.asarray(generate(target, tv, prompt, max_new_tokens=9))
    for draft_len in (1, 3, 4):
        out = np.asarray(generate_speculative(
            target, tv, draft, dv, prompt, max_new_tokens=9,
            draft_len=draft_len))
        np.testing.assert_array_equal(out, ref, err_msg=f"K={draft_len}")


def test_speculative_self_draft_accepts_everything():
    """Draft == target: every proposal accepted; output still exact."""
    from distributeddeeplearning_tpu.models.generate import (
        generate_speculative)

    model, variables = _tiny("gpt")
    prompt = np.asarray([[3, 5, 7, 9]], np.int32)
    ref = np.asarray(generate(model, variables, prompt, max_new_tokens=8))
    out = np.asarray(generate_speculative(
        model, variables, model, variables, prompt, max_new_tokens=8,
        draft_len=4))
    np.testing.assert_array_equal(out, ref)


def test_speculative_llama_and_guards():
    from distributeddeeplearning_tpu.models.generate import (
        generate_speculative)

    target = llama.tiny_llama(vocab_size=97)
    draft = llama.LlamaLM(llama.LlamaConfig(
        vocab_size=97, hidden_size=32, num_layers=1, num_heads=2,
        num_kv_heads=2, intermediate_size=64, decode_cache_len=64),
        dtype=jnp.float32)
    ids = jnp.ones((1, 4), jnp.int32)
    tv = target.init({"params": jax.random.key(2)}, ids, train=False)
    dv = draft.init({"params": jax.random.key(3)}, ids, train=False)
    prompt = np.asarray([[4, 8, 15, 16]], np.int32)
    ref = np.asarray(generate(target, tv, prompt, max_new_tokens=7))
    out = np.asarray(generate_speculative(
        target, tv, draft, dv, prompt, max_new_tokens=7, draft_len=3))
    np.testing.assert_array_equal(out, ref)

    with pytest.raises(ValueError, match="batch-1"):
        generate_speculative(target, tv, draft, dv,
                             np.ones((2, 4), np.int32), max_new_tokens=2)
    with pytest.raises(ValueError, match=">= 2"):
        generate_speculative(target, tv, draft, dv,
                             np.ones((1, 1), np.int32), max_new_tokens=2)
