"""Launcher tests (SURVEY.md §2 #9-#10, §5.3): host planning, fail-whole
monitoring, multi-process rendezvous, and fault-injection → resume.

Real pod-slice runs are manual/benchmark-time (SURVEY.md §4); here the
process-management layer is tested with local subprocesses, exactly how the
launcher simulates a multi-host job on one machine.
"""

import os
import subprocess
import sys

import pytest

from distributeddeeplearning_tpu import launch


@pytest.mark.core
def test_plan_local():
    specs = launch.plan_local(4, port=9100)
    assert [s.process_id for s in specs] == [0, 1, 2, 3]
    assert all(s.num_processes == 4 for s in specs)
    assert all(s.coordinator == "127.0.0.1:9100" for s in specs)
    env = specs[2].env()
    assert env[launch.ENV_PROCESS_ID] == "2"
    assert env[launch.ENV_NUM_PROCESSES] == "4"


@pytest.mark.core
def test_plan_from_hostfile(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text("# slice hosts\nworker0\nworker1\n\nworker2\n")
    specs = launch.plan_from_hostfile(str(hf), port=9200)
    assert len(specs) == 3
    assert specs[0].coordinator == "worker0:9200"  # first host coordinates
    assert specs[2].process_id == 2
    empty = tmp_path / "empty"
    empty.write_text("# comments only\n")
    with pytest.raises(ValueError):
        launch.plan_from_hostfile(str(empty))


def _spawn_py(code: str) -> subprocess.Popen:
    return subprocess.Popen([sys.executable, "-c", code])


@pytest.mark.core
def test_monitor_all_succeed():
    children = [_spawn_py("import sys; sys.exit(0)") for _ in range(3)]
    assert launch.monitor(children) == 0


@pytest.mark.core
def test_monitor_fail_whole():
    """First nonzero exit kills the survivors (mpirun semantics)."""
    slow = _spawn_py("import time; time.sleep(60)")
    bad = _spawn_py("import sys; sys.exit(3)")
    rc = launch.monitor([slow, bad], poll_interval_s=0.05, grace_s=5.0)
    assert rc == 3
    assert slow.poll() is not None  # terminated, not left running


@pytest.mark.slow
def test_two_process_rendezvous():
    """launch.run_local really wires jax.distributed: both processes must see
    num_processes=2 and the global device count."""
    code = (
        "import os\n"
        "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from distributeddeeplearning_tpu import launch\n"
        "pid = launch.maybe_initialize_distributed()\n"
        "import jax\n"
        "assert pid == jax.process_index(), (pid, jax.process_index())\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "assert jax.device_count() == 2 * jax.local_device_count()\n"
    )
    specs = launch.plan_local(2, port=9310)
    # XLA_FLAGS="" overrides the suite's 8-fake-device flag: 1 local CPU
    # device per process.
    children = [launch.spawn(s, [sys.executable, "-c", code],
                             extra_env={"XLA_FLAGS": ""}) for s in specs]
    assert launch.monitor(children, poll_interval_s=0.1) == 0


@pytest.mark.slow
def test_fault_injection_then_resume(tmp_path):
    """End-to-end §5.3 story: a run killed at step 3 exits nonzero through
    the launcher; the relaunch resumes from the step-2 checkpoint."""
    ckpt = str(tmp_path / "ckpt")
    base = [sys.executable, "train.py", "--backend", "cpu", "--model",
            "resnet18", "--batch-size", "8", "--dp", "1", "--synthetic",
            "--dtype", "float32", "--steps", "5", "--checkpoint-dir", ckpt,
            "--checkpoint-every", "2", "--log-every", "1000000"]
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}

    crash = subprocess.run(
        [sys.executable, "launch.py", "--num-processes", "1", "--"]
        + base + ["--fail-at-step", "3"],
        capture_output=True, text=True, timeout=600, env=env)
    assert crash.returncode != 0
    assert "fault injection" in crash.stderr

    resume = subprocess.run(base, capture_output=True, text=True,
                            timeout=600, env=env)
    assert resume.returncode == 0, resume.stderr[-2000:]
    import json
    summary = json.loads(resume.stdout.strip().splitlines()[-1])["summary"]
    assert summary["start_step"] == 2  # resumed from the step-2 checkpoint
    assert summary["final_step"] == 5


@pytest.mark.slow
def test_multihost_checkpoint_save_restore_elastic(tmp_path):
    """SURVEY §5.4 under a REAL 2-process jax.distributed job (VERDICT r3
    Next #7 — the one checkpoint path that was only single-process-tested):

    1. two processes train and SAVE (every process writes its own orbax
       shards; the stream-meta agreement runs its collective fingerprint
       compare at process_count=2);
    2. the same 2-process topology RESUMES from that checkpoint;
    3. a single process resumes the 2-process checkpoint (process-count
       change — the elastic-restore claim, now proven against shards
       written by a genuinely multi-process save).

    Steps stay tiny: the XLA:CPU in-process collective watchdog aborts
    long dp>1 runs on this box (documented in conftest notes).
    """
    import json

    ckpt = str(tmp_path / "ckpt")

    def train_cmd(steps: int, dp: int) -> list:
        return [sys.executable, "train.py", "--backend", "cpu", "--model",
                "resnet18", "--batch-size", "8", "--dp", str(dp),
                "--synthetic", "--dtype", "float32", "--steps", str(steps),
                "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
                "--log-every", "1000000"]

    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["XLA_FLAGS"] = ""  # 1 CPU device per process -> dp=2 spans procs
    env["JAX_PLATFORMS"] = "cpu"

    def run2(steps: int):
        return subprocess.run(
            [sys.executable, "launch.py", "--num-processes", "2", "--"]
            + train_cmd(steps, dp=2),
            capture_output=True, text=True, timeout=900, env=env)

    def summary_of(proc):
        lines = [ln for ln in proc.stdout.splitlines() if "summary" in ln]
        assert lines, (proc.returncode, proc.stderr[-2000:])
        return json.loads(lines[-1])["summary"]

    first = run2(4)
    assert first.returncode == 0, first.stderr[-2000:]
    s1 = summary_of(first)
    assert s1["start_step"] == 0 and s1["final_step"] == 4

    second = run2(6)
    assert second.returncode == 0, second.stderr[-2000:]
    s2 = summary_of(second)
    assert s2["start_step"] == 4, s2  # resumed the multi-process save
    assert s2["final_step"] == 6

    # Elastic: one process, one device, restores the 2-process shards.
    solo = subprocess.run(train_cmd(8, dp=1), capture_output=True,
                          text=True, timeout=600, env=env)
    assert solo.returncode == 0, solo.stderr[-2000:]
    s3 = summary_of(solo)
    assert s3["start_step"] == 6, s3
    assert s3["final_step"] == 8


@pytest.mark.slow
def test_multihost_gspmd_axis_spans_processes(tmp_path):
    """GSPMD under a REAL 2-process job with a NON-data axis crossing the
    process boundary (VERDICT r4 Next #6 — the round-4 multi-host proof
    covered only shard_map-DP).

    Each process hosts 2 fake CPU devices (4 global); the mesh is
    fsdp=2 x tp=2 in MESH_AXES order, so the fsdp axis (ZeRO-3 parameter
    all-gather / gradient reduce-scatter) spans the two processes while tp
    stays process-local — the DCN-major layout parallel/mesh.py produces
    on a real pod. One jitted GSPMD program per process, XLA collectives
    over the boundary, loss finite, then a checkpoint save -> 2-process
    resume roundtrip. Steps stay tiny (XLA:CPU collective watchdog)."""
    import json

    ckpt = str(tmp_path / "ckpt")

    def train_cmd(steps: int) -> list:
        return [sys.executable, "train.py", "--backend", "cpu", "--model",
                "bert_tiny", "--batch-size", "4", "--fsdp", "2", "--tp",
                "2", "--synthetic", "--seq-len", "16", "--dtype",
                "float32", "--steps", str(steps), "--checkpoint-dir",
                ckpt, "--checkpoint-every", "2", "--log-every", "1000000"]

    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    # 2 fake devices per process: the 4-device mesh spans the processes.
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"

    def run2(steps: int):
        return subprocess.run(
            [sys.executable, "launch.py", "--num-processes", "2",
             "--port", "9411", "--"] + train_cmd(steps),
            capture_output=True, text=True, timeout=900, env=env)

    def summary_of(proc):
        lines = [ln for ln in proc.stdout.splitlines() if "summary" in ln]
        assert lines, (proc.returncode, proc.stderr[-2000:])
        return json.loads(lines[-1])["summary"]

    first = run2(2)
    assert first.returncode == 0, first.stderr[-2000:]
    s1 = summary_of(first)
    assert s1["final_step"] == 2
    import math
    assert math.isfinite(s1["final_metrics"]["loss"])

    second = run2(4)
    assert second.returncode == 0, second.stderr[-2000:]
    s2 = summary_of(second)
    assert s2["start_step"] == 2, s2  # resumed the multi-process save
    assert s2["final_step"] == 4


@pytest.mark.slow
def test_multihost_eval_uses_upfront_batch_agreement(tmp_path):
    """Multi-process eval over a REAL finite imagefolder split: the
    processes must agree on the global eval batch count via the upfront
    ``batches_hint`` collective (ADVICE r4) — and when the split holds
    fewer batches than requested, eval scores what exists on every
    process instead of deadlocking the collective eval step."""
    import json

    import numpy as np
    from PIL import Image

    rng = np.random.default_rng(0)
    for split, count in (("train", 64), ("val", 24)):
        for i in range(count):
            cls = i % 2
            d = tmp_path / "data" / split / f"class{cls}"
            d.mkdir(parents=True, exist_ok=True)
            arr = rng.integers(0, 256, (32, 32, 3)).astype(np.uint8)
            arr[:, :, 0] = 200 if cls == 0 else 30
            Image.fromarray(arr).save(d / f"img{i}.jpg")

    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["XLA_FLAGS"] = ""  # 1 CPU device per process -> dp=2 spans procs
    env["JAX_PLATFORMS"] = "cpu"
    # global batch 8 -> per-process val shard 12 images = 3 full local
    # batches of 4; ask for 5 eval batches so the hint must clamp to 3.
    cmd = [sys.executable, "train.py", "--backend", "cpu", "--model",
           "resnet18_thin", "--batch-size", "8", "--dp", "2",
           "--data-dir", str(tmp_path / "data"), "--loader", "tf",
           "--dtype", "float32", "--steps", "4", "--eval-batches", "5",
           "--image-size", "32", "--log-every", "1000000"]
    proc = subprocess.run(
        [sys.executable, "launch.py", "--num-processes", "2",
         "--port", "9412", "--"] + cmd,
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if "summary" in ln]
    assert lines, proc.stderr[-2000:]
    summary = json.loads(lines[-1])["summary"]
    assert summary["final_step"] == 4
    # The final eval ran over the 3 available batches (clamped from 5).
    assert summary["eval_top1"] is not None
    assert "holds 3 of the 5 requested" in proc.stderr


@pytest.mark.slow
def test_max_restarts_auto_resumes(tmp_path):
    """--max-restarts closes the §5.3 loop in-launcher: the injected crash
    triggers an automatic relaunch that resumes from the checkpoint and
    finishes with rc 0 — no external wrapper needed."""
    import json

    ckpt = str(tmp_path / "ckpt")
    # --fail-at-step 3 fires on the first attempt only: the relaunch resumes
    # at step 2, and on reaching step 3 again the fault re-fires... so use a
    # fail step the resumed run skips: fail at 3, checkpoint at 2 means the
    # second attempt starts at 2 and would fail at 3 again. Instead inject
    # via a flag file the child consumes once.
    flag = tmp_path / "fail_once"
    flag.write_text("1")
    runner = tmp_path / "runner.py"
    runner.write_text(f"""
import os, subprocess, sys
cmd = [sys.executable, "train.py", "--backend", "cpu", "--model", "resnet18",
       "--batch-size", "8", "--dp", "1", "--synthetic", "--dtype", "float32",
       "--steps", "5", "--checkpoint-dir", {ckpt!r},
       "--checkpoint-every", "2", "--log-every", "1000000"]
if os.path.exists({str(flag)!r}):
    os.unlink({str(flag)!r})
    cmd += ["--fail-at-step", "3"]
sys.exit(subprocess.call(cmd))
""")
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    proc = subprocess.run(
        [sys.executable, "launch.py", "--num-processes", "1",
         "--max-restarts", "2", "--", sys.executable, str(runner)],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "restart 1/2" in proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])["summary"]
    assert summary["start_step"] == 2 and summary["final_step"] == 5
