"""Trace-analysis edge cases (ISSUE 6 satellite): the damaged, partial,
and merged traces a post-mortem actually hands to telemetry.phase_totals
/ tools/summarize_trace.py — empty trace dir, truncated JSON, events
merged across restart attempts, instants-only traces."""

import json
import os
import sys

import pytest

from distributeddeeplearning_tpu.observability import telemetry

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from tools import summarize_trace  # noqa: E402


def _span(name, ts, dur, **args):
    return {"name": name, "ph": "X", "ts": ts, "dur": dur, "pid": 0,
            "tid": 1, "args": args}


def _write(path, events):
    with open(path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    return str(path)


# --- load_events_tolerant ---------------------------------------------------

def test_tolerant_load_clean_file_has_no_error(tmp_path):
    p = _write(tmp_path / "t.json", [_span("dispatch", 0, 100)])
    events, err = telemetry.load_events_tolerant(p)
    assert err is None and len(events) == 1


def test_tolerant_load_salvages_truncated_object_form(tmp_path):
    p = _write(tmp_path / "t.json", [_span("dispatch", 0, 100),
                                     _span("data_wait", 100, 50),
                                     _span("dispatch", 200, 100)])
    text = open(p).read()
    cut = text.rindex('{"name"')  # kill the 3rd event mid-object
    with open(p, "w") as fh:
        fh.write(text[:cut + 20])
    events, err = telemetry.load_events_tolerant(p)
    assert [e["name"] for e in events] == ["dispatch", "data_wait"]
    assert err and "truncated" in err and "2" in err


def test_tolerant_load_salvages_bare_array_form(tmp_path):
    p = str(tmp_path / "bare.json")
    full = json.dumps([_span("a", 0, 10), _span("b", 10, 10)])
    with open(p, "w") as fh:
        fh.write(full[:full.rindex('"name": "b"') + 4])  # cut inside b
    events, err = telemetry.load_events_tolerant(p)
    assert [e["name"] for e in events] == ["a"]
    assert err and "truncated" in err


def test_tolerant_load_garbage_and_missing(tmp_path):
    p = str(tmp_path / "garbage.json")
    with open(p, "w") as fh:
        fh.write("this is not a trace")
    events, err = telemetry.load_events_tolerant(p)
    assert events == [] and "unparseable" in err
    events, err = telemetry.load_events_tolerant(str(tmp_path / "nope"))
    assert events == [] and err


# --- phase_totals edge cases ------------------------------------------------

def test_phase_totals_empty_and_zero_duration():
    assert telemetry.phase_totals([]) == {}
    totals = telemetry.phase_totals([
        _span("x", 0, 0),  # zero-duration span still counts
        {"name": "i1", "ph": "i", "ts": 5},  # instants never do
        {"name": "c1", "ph": "C", "ts": 5, "args": {"value": 1.0}},
    ])
    assert totals == {"x": {"count": 1, "total_ms": 0.0, "mean_ms": 0.0}}


# --- summarize_trace CLI ----------------------------------------------------

def test_empty_trace_dir_is_an_error_record_not_a_crash(tmp_path, capsys):
    d = tmp_path / "empty_traces"
    d.mkdir()
    assert summarize_trace.main([str(d), "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["provenance"] == "error"
    assert rec["events"] == 0 and rec["phases"] == {}
    assert any("no trace" in e for e in rec["load_errors"])
    # Table mode reports the same truth on stderr without crashing.
    assert summarize_trace.main([str(d)]) == 0
    assert "no trace" in capsys.readouterr().err


def test_truncated_trace_summarizes_salvaged_prefix(tmp_path, capsys):
    p = _write(tmp_path / "t.json", [_span("dispatch", 0, 1000),
                                     _span("dispatch", 1000, 1000),
                                     _span("data_wait", 2000, 500)])
    text = open(p).read()
    with open(p, "w") as fh:
        fh.write(text[:text.rindex('{"name"') + 10])
    assert summarize_trace.main([p, "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    # Salvage kept the 2 complete dispatch spans; the cut data_wait is
    # gone — and the record SAYS so instead of posing as complete.
    assert rec["provenance"] == "fresh"
    assert rec["phases"]["dispatch"]["count"] == 2
    assert "data_wait" not in rec["phases"]
    assert rec["load_errors"] and "truncated" in rec["load_errors"][0]
    assert summarize_trace.main([p]) == 0  # table mode
    assert "incomplete" in capsys.readouterr().out


def test_events_merged_across_restart_attempts(tmp_path, capsys):
    """A chaos run's attempts export into ONE file (telemetry.export
    merges); the summary must aggregate across attempts, not just the
    last one."""
    path = str(tmp_path / "trace.p0.json")
    att0 = telemetry.Telemetry(enabled=True)
    with att0.span("dispatch", step=1):
        pass
    att0.instant("fault:crash", step=1)
    assert att0.export(path) == path
    att1 = telemetry.Telemetry(enabled=True)  # the restarted attempt
    with att1.span("dispatch", step=1):
        pass
    with att1.span("restore", step=1):
        pass
    assert att1.export(path) == path
    assert summarize_trace.main([path, "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["phases"]["dispatch"]["count"] == 2  # both attempts
    assert rec["phases"]["restore"]["count"] == 1
    assert [e["name"] for e in rec["instants"]] == ["fault:crash"]


def test_instants_only_trace(tmp_path, capsys):
    """A run that died before any span completed still yields a valid
    summary: timeline present, no phases — not a crash, not a lie."""
    tele = telemetry.Telemetry(enabled=True)
    tele.instant("fault:sigkill", step=3)
    tele.instant("restart_scheduled", attempt=1)
    path = str(tmp_path / "trace.p0.json")
    tele.export(path)
    assert summarize_trace.main([path, "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["provenance"] == "fresh" and rec["phases"] == {}
    assert len(rec["instants"]) == 2
    assert summarize_trace.main([path]) == 0
    assert "no complete spans" in capsys.readouterr().out


def test_directory_expands_to_per_process_traces(tmp_path, capsys):
    d = tmp_path / "traces"
    d.mkdir()
    for pid in (0, 1):
        t = telemetry.Telemetry(enabled=True, process_index=pid)
        with t.span("dispatch", step=1):
            pass
        t.export(str(d / f"trace.p{pid}.json"))
    (d / "unrelated.txt").write_text("not a trace")
    assert summarize_trace.main([str(d), "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["processes"] == [0, 1]
    assert rec["phases"]["dispatch"]["count"] == 2
    assert len(rec["files"]) == 2  # unrelated.txt was never touched


def test_missing_path_still_exits_loudly(tmp_path):
    with pytest.raises(SystemExit):
        summarize_trace.main([str(tmp_path / "missing.json")])
