"""Gather-mode MLM head (config.data.mlm_max_predictions): projecting only
the masked positions to vocab must equal gathering the dense logits (the
head is per-position), the pipelines must emit consistent fixed-width
batches, and training/eval must run end-to-end under GSPMD sharding."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data import synthetic, tokens
from distributeddeeplearning_tpu.models import bert


def test_gather_head_equals_gathered_dense_logits():
    model = bert.tiny_bert_mlm(vocab_size=256)
    ids = jax.random.randint(jax.random.key(0), (2, 16), 0, 256)
    variables = model.init({"params": jax.random.key(1),
                            "dropout": jax.random.key(2)}, ids, train=False)
    pos = jnp.array([[1, 4, 7], [0, 5, 15]], jnp.int32)
    dense = model.apply(variables, ids, train=False)
    gathered = model.apply(variables, ids, masked_positions=pos, train=False)
    assert gathered.shape == (2, 3, 256)
    np.testing.assert_allclose(
        np.asarray(jnp.take_along_axis(dense, pos[:, :, None], axis=1)),
        np.asarray(gathered), rtol=1e-6, atol=1e-6)


def test_synthetic_gathered_batches():
    src = synthetic.SyntheticTokens(4, seq_len=32, vocab_size=512, seed=0,
                                    max_predictions=5)
    b = src.batch(3)
    assert b["masked_positions"].shape == (4, 5)
    assert b["masked_labels"].shape == (4, 5)
    pos = np.asarray(b["masked_positions"])
    ids = np.asarray(b["input_ids"])
    labels = np.asarray(b["masked_labels"])
    # positions sorted + distinct per row; [MASK] written at each; labels
    # are the original (pre-mask) ids, so they differ from the MASK token.
    for r in range(4):
        assert (np.diff(pos[r]) > 0).all()
        assert (ids[r, pos[r]] == synthetic.MASK_TOKEN_ID).all()
    assert (labels >= 0).all()
    # deterministic in (seed, step)
    b2 = synthetic.SyntheticTokens(4, seq_len=32, vocab_size=512, seed=0,
                                   max_predictions=5).batch(3)
    np.testing.assert_array_equal(np.asarray(b["input_ids"]),
                                  np.asarray(b2["input_ids"]))


def test_tokens_gather_mask_batch():
    rng = np.random.default_rng(0)
    ids = rng.integers(1000, 2000, (3, 64)).astype(np.int32)
    ids[:, 0] = tokens.CLS_ID
    ids[:, -1] = tokens.SEP_ID
    ids[0, 50:] = tokens.PAD_ID
    out = tokens.gather_mask_batch(ids, max_pred=10, mask_prob=0.15,
                                   vocab_size=2000,
                                   rng=np.random.default_rng(1))
    pos, labels = out["masked_positions"], out["masked_labels"]
    assert pos.shape == labels.shape == (3, 10)
    for r in range(3):
        taken = labels[r] >= 0
        # ~15% of maskable tokens, never special/PAD positions
        assert 1 <= taken.sum() <= 10
        sel = pos[r][taken]
        assert (ids[r, sel] > tokens.UNUSED_MAX).all()
        np.testing.assert_array_equal(labels[r][taken], ids[r, sel])
    # 80/10/10: most selected positions now carry [MASK]
    sel_all = [(r, p) for r in range(3)
               for p, ok in zip(pos[r], labels[r] >= 0) if ok]
    masked = sum(out["input_ids"][r, p] == synthetic.MASK_TOKEN_ID
                 for r, p in sel_all)
    assert masked >= len(sel_all) // 2


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_gather_mlm_trains_and_evals_gspmd():
    from distributeddeeplearning_tpu.train import loop

    cfg = TrainConfig(
        model="bert_tiny", global_batch_size=8, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=4, model=2),
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=512,
                        mlm_max_predictions=5),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  schedule="constant", warmup_epochs=0.0,
                                  label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=3, eval_batches=2)
    assert summary["final_step"] == 3
    assert np.isfinite(summary["final_metrics"]["loss"])
    assert np.isfinite(summary["eval_loss"])


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_gather_loss_tracks_dense_loss():
    """Same model/params: the gathered loss at step 0 must be ~ln(vocab),
    like the dense loss — a smoke check that labels/positions pair up."""
    from distributeddeeplearning_tpu.train import loop

    def run(max_pred):
        cfg = TrainConfig(
            model="bert_tiny", global_batch_size=8, dtype="float32",
            log_every=10**9,
            parallel=ParallelConfig(data=8),
            data=DataConfig(dataset="mlm", seq_len=32, vocab_size=512,
                            mlm_max_predictions=max_pred),
            optimizer=OptimizerConfig(name="adamw", learning_rate=0.0,
                                      schedule="constant", warmup_epochs=0.0,
                                      label_smoothing=0.0))
        return loop.run(cfg, total_steps=1)["final_metrics"]["loss"]

    dense, gathered = run(0), run(5)
    assert abs(dense - np.log(512)) < 0.5
    assert abs(gathered - np.log(512)) < 0.5
