"""Real-data pipeline tests: tiny generated ImageNet (TFRecord + folder
layouts), sharding, augmentation invariants, end-to-end training integration
(SURVEY.md §4 "Integration")."""

from __future__ import annotations

import os

import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu import data as datalib
from distributeddeeplearning_tpu.config import (
    DataConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data import imagenet
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.parallel import sharding as shardlib

tf = pytest.importorskip("tensorflow")

NUM_CLASSES = 4
IMAGES_PER_CLASS = 8
IMG = 64


def _jpeg_bytes(rng: np.random.Generator, label: int) -> bytes:
    # Class-colored images so labels are recoverable from pixels.
    arr = np.full((IMG, IMG, 3), 40 + 50 * label, np.uint8)
    arr += rng.integers(0, 10, arr.shape, dtype=np.uint8)
    return tf.io.encode_jpeg(arr).numpy()


@pytest.fixture(scope="module")
def tfrecord_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("imagenet_tfr")
    rng = np.random.default_rng(0)
    for shard in range(2):
        for split, n_img in (("train", IMAGES_PER_CLASS), ("validation", 2)):
            path = os.path.join(root, f"{split}-{shard:05d}-of-00002")
            with tf.io.TFRecordWriter(path) as w:
                for label in range(NUM_CLASSES):
                    for _ in range(n_img):
                        ex = tf.train.Example(features=tf.train.Features(feature={
                            "image/encoded": tf.train.Feature(
                                bytes_list=tf.train.BytesList(
                                    value=[_jpeg_bytes(rng, label)])),
                            # canonical TFRecords are 1-based
                            "image/class/label": tf.train.Feature(
                                int64_list=tf.train.Int64List(value=[label + 1])),
                        }))
                        w.write(ex.SerializeToString())
    return str(root)


@pytest.fixture(scope="module")
def folder_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("imagenet_folder")
    rng = np.random.default_rng(1)
    for split in ("train", "val"):
        for label in range(NUM_CLASSES):
            d = os.path.join(root, split, f"n{label:08d}")
            os.makedirs(d)
            for i in range(IMAGES_PER_CLASS if split == "train" else 2):
                with open(os.path.join(d, f"img_{i}.JPEG"), "wb") as f:
                    f.write(_jpeg_bytes(rng, label))
    return str(root)


def _cfg(data_dir, batch=8, dp=2):
    return TrainConfig(
        model="resnet18", global_batch_size=batch, dtype="float32",
        parallel=ParallelConfig(data=dp),
        data=DataConfig(synthetic=False, data_dir=data_dir, image_size=32,
                        num_classes=NUM_CLASSES, shuffle_buffer=64))


def test_detect_layout(tfrecord_dir, folder_dir, tmp_path):
    assert imagenet.detect_layout(tfrecord_dir) == "tfrecord"
    assert imagenet.detect_layout(folder_dir) == "folder"
    with pytest.raises(FileNotFoundError):
        imagenet.detect_layout(str(tmp_path))


@pytest.mark.parametrize("layout", ["tfrecord", "folder"])
def test_batches_shapes_and_labels(layout, tfrecord_dir, folder_dir):
    cfg = _cfg(tfrecord_dir if layout == "tfrecord" else folder_dir)
    mesh = meshlib.make_mesh(cfg.parallel)
    shd = shardlib.batch_sharding(mesh)
    src = imagenet.make_imagenet_source(cfg, shd, train=True)
    for step in range(3):
        b = src.batch(step)
        assert b["image"].shape == (8, 32, 32, 3)
        assert b["image"].dtype == np.float32
        assert b["label"].shape == (8,)
        labels = np.asarray(jax.device_get(b["label"]))
        assert ((0 <= labels) & (labels < NUM_CLASSES)).all()
        # global array is sharded over the data axis, not replicated
        assert b["image"].sharding.is_equivalent_to(shd, 4) or (
            b["image"].sharding.spec == shd.spec)


def test_labels_match_pixels(tfrecord_dir):
    """Class-colored images: decoded pixel level must identify the label —
    catches any decode/label pairing bug in the interleave."""
    cfg = _cfg(tfrecord_dir, batch=16, dp=1)
    mesh = meshlib.make_mesh(cfg.parallel)
    src = imagenet.make_imagenet_source(
        cfg, shardlib.batch_sharding(mesh), train=False)
    b = src.batch(0)
    images = np.asarray(jax.device_get(b["image"]))
    labels = np.asarray(jax.device_get(b["label"]))
    # Undo normalization to recover the class color plateau.
    raw = images * np.array(imagenet.STDDEV_RGB) + np.array(imagenet.MEAN_RGB)
    inferred = np.clip(np.round((raw.mean((1, 2, 3)) - 45) / 50), 0,
                       NUM_CLASSES - 1).astype(np.int32)
    assert (inferred == labels).all()


def test_process_sharding_disjoint(tfrecord_dir):
    """Two simulated processes must read disjoint validation examples."""
    cfg = _cfg(tfrecord_dir, batch=8, dp=1)
    seen = []
    for proc in range(2):
        ds = imagenet.build_dataset(cfg, train=False, process_index=proc,
                                    process_count=2)
        batch = next(iter(ds.as_numpy_iterator()))
        seen.append(batch["image"].sum(axis=(1, 2, 3)))
    # Image checksums from different shards shouldn't collide en masse.
    overlap = np.intersect1d(np.round(seen[0], 2), np.round(seen[1], 2))
    assert overlap.size < 4


def test_stream_source_enforces_order(tfrecord_dir):
    cfg = _cfg(tfrecord_dir)
    mesh = meshlib.make_mesh(cfg.parallel)
    src = imagenet.make_imagenet_source(
        cfg, shardlib.batch_sharding(mesh), train=True)
    src.batch(0)
    with pytest.raises(ValueError, match="out of order"):
        src.batch(5)


@pytest.mark.slow
def test_train_end_to_end_real_data(tfrecord_dir):
    """Integration: loss decreases training on the (trivially separable)
    class-colored dataset through the full loop + real pipeline."""
    from distributeddeeplearning_tpu.train import loop

    cfg = _cfg(tfrecord_dir, batch=16, dp=2).replace(
        log_every=10**9)
    summary = loop.run(cfg, total_steps=8, eval_batches=1)
    assert summary["final_step"] == 8
    assert np.isfinite(summary["final_metrics"]["loss"])
    assert 0.0 <= summary["eval_top1"] <= 1.0


@pytest.mark.slow
def test_eval_survives_short_validation_split(tfrecord_dir):
    """A val split smaller than eval_batches x batch must score the
    batches that exist (with a warning), not crash mid-training with a
    StopIteration — found driving tools/real_data_on_chip.py."""
    from distributeddeeplearning_tpu.train import loop

    cfg = _cfg(tfrecord_dir, batch=16, dp=2).replace(log_every=10**9)
    # validation split holds 2 imgs x 4 classes x 2 shards = 16 = ONE batch.
    with pytest.warns(UserWarning, match="exhausted after 1 of 5"):
        summary = loop.run(cfg, total_steps=2, eval_batches=5)
    assert 0.0 <= summary["eval_top1"] <= 1.0


def test_dispatcher_routes(tfrecord_dir):
    cfg = _cfg(tfrecord_dir)
    mesh = meshlib.make_mesh(cfg.parallel)
    shd = shardlib.batch_sharding(mesh)
    src = datalib.make_source(cfg, "image", shd)
    assert isinstance(src, imagenet.StreamSource)
    syn = datalib.make_source(cfg.replace(
        data=DataConfig(synthetic=True)), "image", shd)
    assert isinstance(syn, datalib.SyntheticImages)
