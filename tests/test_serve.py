"""Continuous-batching serve engine (distributeddeeplearning_tpu/serve/).

The load-bearing pin is TOKEN-IDENTITY: the engine's greedy output must
equal sequential ``generate(use_cache=True)`` request-by-request — with
slots retiring and admitting mid-stream, for both model families, and
across a preemption/resume cycle. If that holds, the paged cache, the
prefill packing, the per-row positions, and the masked paged attention
are all simultaneously correct (any one of them wrong changes tokens).
Around the pin: numeric paged-vs-dense attention equivalence, allocator
and scheduler policy units, per-request capacity errors, the AOT
zero-retrace warm boot, and a bench_serve smoke through the
provenance-validated record schema.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.models import generate as genlib
from distributeddeeplearning_tpu.models import model_spec
from distributeddeeplearning_tpu.serve import kv_cache
from distributeddeeplearning_tpu.serve.engine import (Engine, ServeConfig,
                                                      serve_fingerprint)
from distributeddeeplearning_tpu.serve.scheduler import (Plan, SloScheduler,
                                                         TenantPolicy)

pytestmark = pytest.mark.serve

VOCAB = 97


def _engine(model="gpt_tiny", **kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 32)
    kw.setdefault("max_pages_per_slot", 8)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("compile_cache_dir", "off")
    t = [0.0]

    def clock():
        t[0] += 0.001  # strictly increasing: every emit gets a distinct time
        return t[0]

    return Engine(ServeConfig(model=model, **kw), clock=clock)


def _reference_tokens(eng, prompt, max_new):
    out = genlib.generate(eng.model, {**eng._fresh},
                          jnp.asarray([prompt], jnp.int32),
                          max_new_tokens=max_new, use_cache=True)
    return [int(x) for x in np.asarray(out)[0, len(prompt):]]


# --- kv_cache units ---------------------------------------------------------

def test_pages_needed_is_ceil_division():
    assert kv_cache.pages_needed(1, 4) == 1
    assert kv_cache.pages_needed(4, 4) == 1
    assert kv_cache.pages_needed(5, 4) == 2
    assert kv_cache.pages_needed(17, 4) == 5


def test_allocator_all_or_nothing_reuse_and_double_free():
    alloc = kv_cache.PageAllocator(4)
    a = alloc.alloc(3)
    assert len(a) == 3 and alloc.free_pages == 1
    # All-or-nothing: a 2-page ask against 1 free page takes NOTHING.
    assert alloc.alloc(2) is None
    assert alloc.free_pages == 1
    alloc.free(a)
    assert alloc.free_pages == 4
    # Freed pages are immediately reusable...
    b = alloc.alloc(4)
    assert sorted(b) == sorted(range(4))
    # ...and a page can never sit on two tables at once.
    alloc.free([b[0]])
    with pytest.raises(ValueError, match="double-free"):
        alloc.free([b[0]])


def test_paged_attention_matches_dense_reference():
    """Paged gather+mask attention == plain softmax attention over each
    slot's logical [0, length] context, per (grouped) head — the numeric
    core the token-identity pins rest on."""
    rng = np.random.default_rng(0)
    slots, page_size, pages_per_slot, num_pages = 3, 4, 2, 8
    kvh, heads, d = 2, 4, 8
    rep = heads // kvh
    lengths = np.array([3, 5, 0], np.int32)
    live = np.array([True, True, False])
    table = np.array([[2, 5], [1, 6], [0, 0]], np.int32)

    pool_k = rng.standard_normal((num_pages, page_size, kvh, d)).astype(
        np.float32)
    pool_v = rng.standard_normal((num_pages, page_size, kvh, d)).astype(
        np.float32)
    q = rng.standard_normal((slots, 1, heads, d)).astype(np.float32)
    k_new = rng.standard_normal((slots, 1, kvh, d)).astype(np.float32)
    v_new = rng.standard_normal((slots, 1, kvh, d)).astype(np.float32)

    out, pk, pv = kv_cache.paged_attention_step(
        jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
        jnp.asarray(pool_k), jnp.asarray(pool_v),
        kv_cache.PagedState(jnp.asarray(table), jnp.asarray(lengths),
                            jnp.asarray(live)))
    out, pk, pv = np.asarray(out), np.asarray(pk), np.asarray(pv)

    # Live slots' k_new landed at position lengths[i]; the dead slot's
    # write was dropped (pool unchanged everywhere it didn't own).
    for i in range(slots):
        if not live[i]:
            continue
        page = table[i, lengths[i] // page_size]
        np.testing.assert_array_equal(
            pk[page, lengths[i] % page_size], k_new[i, 0])
    np.testing.assert_array_equal(pv[3], pool_v[3])  # page 3: never owned

    for i in range(slots):
        if not live[i]:
            continue
        # Logical context rows 0..lengths[i], gathered in page order.
        rows_k = [pk[table[i, t // page_size], t % page_size]
                  for t in range(lengths[i] + 1)]
        rows_v = [pv[table[i, t // page_size], t % page_size]
                  for t in range(lengths[i] + 1)]
        K, V = np.stack(rows_k), np.stack(rows_v)  # (len+1, kvh, d)
        for h in range(heads):
            g = h // rep
            s = (q[i, 0, h] @ K[:, g].T) * d ** -0.5
            p = np.exp(s - s.max())
            p /= p.sum()
            np.testing.assert_allclose(out[i, 0, h * d:(h + 1) * d],
                                       p @ V[:, g], rtol=1e-5, atol=1e-5)


def test_beam_path_rejects_paged_pool_leaves():
    cache = {"layer_0": {"attn": {"pages_k": jnp.zeros((4, 2, 1, 8))}}}
    with pytest.raises(ValueError, match="beam context"):
        genlib._map_batched_cache(cache, lambda x: x)


# --- scheduler policy units -------------------------------------------------

def _req(uid, tenant="default", arrival=0.0, total=8):
    class R:
        pass
    r = R()
    r.uid, r.tenant, r.arrival_s, r.total_tokens = uid, tenant, arrival, total
    return r


def _slot(slot, tenant, num_pages, seq):
    from distributeddeeplearning_tpu.serve.engine import _SlotView
    return _SlotView(slot=slot, tenant=tenant, num_pages=num_pages,
                     admitted_seq=seq)


def test_scheduler_orders_by_deadline_slack_then_fifo():
    sched = SloScheduler([TenantPolicy("rt", ttft_slo_s=0.1),
                          TenantPolicy("batch", ttft_slo_s=10.0)])
    # batch arrived FIRST but has 10 s of slack; rt is nearly overdue.
    plan = sched.plan(now=1.0,
                      waiting=[_req(0, "batch", arrival=0.0),
                               _req(1, "rt", arrival=0.95)],
                      live=[], free_slots=2, free_pages=100, page_size=4)
    assert [r.uid for r in plan.admit] == [1, 0]
    # Same tenant class: FIFO by arrival.
    plan = sched.plan(now=1.0,
                      waiting=[_req(3, "rt", arrival=0.6),
                               _req(2, "rt", arrival=0.5)],
                      live=[], free_slots=2, free_pages=100, page_size=4)
    assert [r.uid for r in plan.admit] == [2, 3]


def test_scheduler_admission_respects_pages_and_tenant_budget():
    sched = SloScheduler([TenantPolicy("capped", max_pages=3)])
    # 2 free pages cannot cover a 3-page request: nothing admitted.
    plan = sched.plan(now=0.0, waiting=[_req(0, total=12)], live=[],
                      free_slots=1, free_pages=2, page_size=4)
    assert plan.empty
    # Tenant budget counts LIVE pages: capped holds 2, another 2-page
    # request would exceed max_pages=3 and is skipped — but an uncapped
    # tenant behind it still admits (the capped one holds its queue spot,
    # not the whole engine).
    plan = sched.plan(now=0.0,
                      waiting=[_req(0, "capped", arrival=0.0, total=8),
                               _req(1, "other", arrival=1.0, total=8)],
                      live=[_slot(0, "capped", 2, seq=1)],
                      free_slots=1, free_pages=10, page_size=4)
    assert [r.uid for r in plan.admit] == [1]
    assert not plan.preempt


def test_scheduler_preempts_newest_overbudget_slot_only():
    sched = SloScheduler([TenantPolicy("bg", max_pages=2)])
    live = [_slot(0, "bg", 3, seq=1), _slot(1, "bg", 3, seq=2)]
    # bg holds 6 pages against a budget of 2; a starved request (needs 2,
    # 0 free) evicts exactly ONE bg slot — the newest (seq=2), minimizing
    # wasted decode work.
    plan = sched.plan(now=0.0, waiting=[_req(0, "rt", total=8)], live=live,
                      free_slots=0, free_pages=0, page_size=4)
    assert plan.preempt == (1,)
    assert [r.uid for r in plan.admit] == [0]
    # Within-budget work is never evicted.
    sched2 = SloScheduler()
    plan = sched2.plan(now=0.0, waiting=[_req(0, total=8)],
                       live=[_slot(0, "default", 3, seq=1)],
                       free_slots=1, free_pages=0, page_size=4)
    assert plan.empty


# --- the token-identity pins ------------------------------------------------

@pytest.mark.parametrize("model", ["gpt_tiny", "llama_tiny"])
def test_engine_token_identity_with_midstream_retire_admit(model):
    """Five requests through two slots: slots retire and re-admit while
    others are mid-decode, and every request's greedy tokens must equal a
    sequential generate(use_cache=True) run of that request alone."""
    eng = _engine(model)
    rng = np.random.default_rng(0)
    lens = [(5, 6), (7, 4), (3, 8), (6, 5), (8, 3)]
    reqs = [eng.submit([int(x) for x in rng.integers(1, VOCAB, p)],
                       max_new_tokens=m) for p, m in lens]
    eng.run_until_idle()
    assert eng.idle and len(eng.finished) == len(reqs)
    for r in reqs:
        assert r.tokens == _reference_tokens(eng, r.prompt,
                                             r.max_new_tokens), r.uid
        assert r.ttft_s is not None and r.finished_s is not None
        assert len(r.tokens) == r.max_new_tokens
    # Every page came back to the free list.
    assert eng.allocator.free_pages == eng.config.num_pages


def test_engine_preemption_resumes_token_identical():
    """Tighten a tenant's page budget mid-run (the operational reconfig
    path), submit a starved higher-urgency request, and the over-budget
    victim must be preempted, re-queued, and finish with EXACTLY the
    tokens of an uninterrupted sequential run."""
    eng = _engine("gpt_tiny", max_slots=2, page_size=4, num_pages=8,
                  max_pages_per_slot=8, prefill_buckets=(8, 16))
    rng = np.random.default_rng(1)
    bg_prompt = [int(x) for x in rng.integers(1, VOCAB, 4)]
    bg = eng.submit(bg_prompt, max_new_tokens=12, tenant="bg")  # 4 pages
    eng.step()
    eng.step()
    assert eng.num_live == 1 and len(bg.tokens) >= 2

    eng.scheduler.policies["bg"] = TenantPolicy("bg", max_pages=3)
    rt_prompt = [int(x) for x in rng.integers(1, VOCAB, 8)]
    rt = eng.submit(rt_prompt, max_new_tokens=12, tenant="rt")  # 5 pages
    eng.step()  # rt needs 5 of 4 free pages -> bg (4 held > 3) evicted
    assert eng.preemptions == 1 and bg.preemptions == 1
    assert bg in list(eng.waiting)

    del eng.scheduler.policies["bg"]  # restore so bg can re-admit
    eng.run_until_idle()
    assert rt.tokens == _reference_tokens(eng, rt_prompt, 12)
    assert bg.tokens == _reference_tokens(eng, bg_prompt, 12)
    assert eng.allocator.free_pages == eng.config.num_pages


def test_engine_aot_warm_boot_zero_retrace(tmp_path):
    """Second engine with the same fingerprint deserializes every program
    (prefill per bucket + decode) instead of retracing — and still
    decodes token-identically."""
    kw = dict(max_slots=2, page_size=4, num_pages=16, max_pages_per_slot=4,
              prefill_buckets=(8,), compile_cache_dir=str(tmp_path))
    cold = _engine("gpt_tiny", **kw)
    stats = cold.warmup()
    assert stats["aot_misses"] == 2 and stats["aot_saves"] == 2
    prompt = list(range(1, 6))
    cold_req = cold.submit(prompt, max_new_tokens=4)
    cold.run_until_idle()

    warm = _engine("gpt_tiny", **kw)
    stats = warm.warmup()
    assert stats["aot_hits"] == 2 and stats["aot_misses"] == 0
    warm_req = warm.submit(prompt, max_new_tokens=4)
    warm.run_until_idle()
    assert warm_req.tokens == cold_req.tokens


def test_serve_fingerprint_tracks_program_shape_not_cache_dir():
    a = ServeConfig(compile_cache_dir=None)
    b = ServeConfig(compile_cache_dir="/somewhere/else")
    c = ServeConfig(page_size=a.page_size * 2)
    assert serve_fingerprint(a) == serve_fingerprint(b)
    assert serve_fingerprint(a) != serve_fingerprint(c)


# --- capacity errors --------------------------------------------------------

def test_require_decode_names_offending_request():
    model = model_spec("gpt_tiny").build(vocab_size=VOCAB)  # max_position 128
    with pytest.raises(ValueError, match=r"request 1 .*over by 72"):
        genlib._require_decode(model, 200, request_totals=[100, 200, 120])


def test_submit_rejects_oversized_requests():
    eng = _engine("gpt_tiny", max_slots=1, page_size=4, num_pages=16,
                  max_pages_per_slot=4, prefill_buckets=(8,))
    with pytest.raises(ValueError, match="slot holds at most 16"):
        eng.submit(list(range(1, 9)), max_new_tokens=9)
    with pytest.raises(ValueError, match="largest prefill bucket"):
        eng.submit(list(range(1, 11)), max_new_tokens=2)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit([], max_new_tokens=2)


def test_engine_rejects_capacity_exceeding_config():
    with pytest.raises(ValueError, match="decode bound"):
        # gpt_tiny's max_position is 128; 64-token pages x 4 = 256 > 128.
        Engine(ServeConfig(model="gpt_tiny", vocab_size=VOCAB, max_slots=1,
                           page_size=64, num_pages=8, max_pages_per_slot=4,
                           prefill_buckets=(16,), compile_cache_dir="off"))


# --- bench record smoke -----------------------------------------------------

def test_bench_serve_emits_valid_provenance_record(tmp_path, monkeypatch,
                                                   capsys):
    from distributeddeeplearning_tpu.observability import perf_report
    from tools import bench_serve

    written = {}
    from distributeddeeplearning_tpu.observability import sidecars
    monkeypatch.setattr(sidecars, "write",
                        lambda name, payload: written.update(
                            {name: payload}) or str(tmp_path / "s.json"))
    rc = bench_serve.main([
        "--model", "gpt_tiny", "--vocab-size", str(VOCAB),
        "--requests", "3", "--rate", "1000", "--max-new", "3",
        "--prompt-lens", "4,6", "--max-slots", "2", "--page-size", "4",
        "--num-pages", "16", "--max-pages-per-slot", "4",
        "--prefill-buckets", "8", "--compile-cache-dir", "off"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert perf_report.validate(rec) == []
    assert rec["provenance"] == "fresh"
    assert rec["token_identity_checked"] is True
    assert rec["continuous"]["finished"] == 3
    assert rec["sequential_baseline"]["tokens_per_sec_per_chip"] > 0
    assert "speedup_vs_sequential" in rec
    assert "last_serve" in written


# --- serve chaos: grammar, integrity sweeps, injected stalls ----------------

@pytest.fixture(scope="module")
def chaos_aot(tmp_path_factory):
    """One AOT executable cache shared by every chaos-arm engine in this
    module: identical ServeConfig -> identical fingerprint -> the first
    test pays the compile, the rest warm-boot (tier-1 stays cheap)."""
    return str(tmp_path_factory.mktemp("serve-chaos-aot"))


def _chaos_engine(cache_dir, **engine_kw):
    t = [0.0]

    def clock():
        t[0] += 0.001
        return t[0]

    cfg = ServeConfig(model="gpt_tiny", vocab_size=VOCAB, max_slots=2,
                      page_size=4, num_pages=32, max_pages_per_slot=8,
                      prefill_buckets=(8, 16), compile_cache_dir=cache_dir)
    return Engine(cfg, clock=clock, **engine_kw)


def test_resolve_serve_filters_kinds_and_attempt_scope(monkeypatch):
    from distributeddeeplearning_tpu.robustness import faults
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    plan = faults.resolve_serve(
        "page_leak@2,decode_stall@4:0.25s,nan_grads@3,sigkill@5:a1")
    # Training-only kinds never reach the serve injector...
    assert all(f.kind in faults.SERVE_KINDS for f in plan.faults)
    assert plan.serve_stalls() == {4: 0.25}
    assert [f.kind for f in plan.serve_faults_at(2)] == ["page_leak"]
    # ...and attempt-scoped faults resolve per incarnation: sigkill@5:a1
    # is invisible on attempt 0, live on attempt 1 (a restarted replica
    # must not be re-killed by the fault that killed its predecessor).
    assert not plan.serve_faults_at(5)
    monkeypatch.setenv(faults.ENV_ATTEMPT, "1")
    replan = faults.resolve_serve("sigkill@5:a1")
    assert [f.kind for f in replan.serve_faults_at(5)] == ["sigkill"]
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.parse_plan("page_fault@2")


def test_allocator_release_is_idempotent_and_leak_check_is_loud():
    alloc = kv_cache.PageAllocator(8)
    held = alloc.alloc(3)
    assert alloc.release(held) == 3
    # Victim retirement may race engine cleanup: the second release of the
    # same pages frees nothing and never raises.
    assert alloc.release(held) == 0
    assert alloc.free_pages == 8

    owned = alloc.alloc(2)
    alloc.check_leaks(owned)  # balanced: every held page owned exactly once
    leaked = alloc.alloc(1)   # dropped on the floor, no table owns it
    with pytest.raises(RuntimeError, match="KV page leak"):
        alloc.check_leaks(owned)
    alloc.release(leaked)
    alloc.check_leaks(owned)
    with pytest.raises(RuntimeError, match="page-table corruption"):
        alloc.check_leaks(owned + owned)  # one page on two slots' tables


@pytest.mark.chaos
def test_page_leak_fault_trips_next_step_integrity_sweep(chaos_aot):
    eng = _chaos_engine(chaos_aot, fault_plan="page_leak@1")
    eng.submit([1, 2, 3, 4], max_new_tokens=3)
    eng.step()  # boundary injector leaks one page AFTER this step
    with pytest.raises(RuntimeError, match="KV page leak"):
        eng.step()  # the sweep fires before anything dispatches


@pytest.mark.chaos
def test_corrupt_page_table_fault_trips_next_step_integrity_sweep(chaos_aot):
    eng = _chaos_engine(chaos_aot, fault_plan="corrupt_page_table@1")
    eng.submit([1, 2, 3, 4], max_new_tokens=3)
    eng.step()
    with pytest.raises(RuntimeError, match="page-table corruption"):
        eng.step()


@pytest.mark.chaos
def test_decode_stall_fault_injects_sleep_once(chaos_aot):
    stalls = []
    eng = _chaos_engine(chaos_aot, fault_plan="decode_stall@1:0.25s",
                        stall=stalls.append)
    eng.step()
    assert stalls == [0.25]
    eng.step()
    assert stalls == [0.25]  # step-scoped: fires exactly once


# --- deadlines, bounded retry, brownout -------------------------------------

def test_ttft_deadline_expires_waiting_request(chaos_aot):
    sched = SloScheduler([TenantPolicy("rt", ttft_deadline_s=0.0)])
    eng = _chaos_engine(chaos_aot, scheduler=sched)
    req = eng.submit([1, 2, 3, 4], max_new_tokens=3, tenant="rt")
    eng.step()  # already past the (zero) first-token budget: never admits
    assert req.failed == "deadline"
    assert eng.deadline_misses == 1 and eng.failed == [req]
    assert eng.num_live == 0 and not eng.waiting


def test_total_deadline_cancels_live_slot_and_returns_pages(chaos_aot):
    sched = SloScheduler([TenantPolicy("rt", total_deadline_s=0.004)])
    eng = _chaos_engine(chaos_aot, scheduler=sched)
    req = eng.submit([1, 2, 3, 4], max_new_tokens=16, tenant="rt")
    for _ in range(16):
        if req.failed is not None:
            break
        eng.step()
    assert req.failed == "deadline"
    assert len(req.tokens) >= 1  # it WAS streaming when the budget blew
    assert eng.deadline_misses == 1
    assert eng.num_live == 0 and eng.allocator.pages_in_use == 0


def test_retry_backoff_schedule_and_admission_hold():
    sched = SloScheduler(max_retries=2, retry_backoff_s=0.5)
    assert sched.retry_delay_s(0) == 0.0
    assert sched.retry_delay_s(1) == 0.5
    assert sched.retry_delay_s(2) == 1.0
    assert sched.retry_delay_s(3) == 2.0
    # A backing-off victim holds its queue place but is not admitted.
    r = _req(0)
    r.not_before_s = 5.0
    plan = sched.plan(now=1.0, waiting=[r], live=[], free_slots=2,
                      free_pages=100, page_size=4)
    assert plan.empty
    plan = sched.plan(now=6.0, waiting=[r], live=[], free_slots=2,
                      free_pages=100, page_size=4)
    assert [q.uid for q in plan.admit] == [0]


def test_preemption_retry_budget_exhaustion_fails_request(chaos_aot):
    sched = SloScheduler([TenantPolicy("bg", max_pages=8)], max_retries=0)
    eng = _chaos_engine(chaos_aot, scheduler=sched)
    bg0 = eng.submit([1, 2, 3, 4], max_new_tokens=12, tenant="bg")
    bg1 = eng.submit([5, 6, 7, 8], max_new_tokens=12, tenant="bg")
    eng.step()  # both bg requests admitted: engine full
    assert eng.num_live == 2
    # The bg tenant's budget collapses; a starved rt arrival evicts the
    # newest bg slot, and with max_retries=0 the victim is not re-queued —
    # it fails loudly instead of thrashing admission forever.
    sched.policies["bg"] = TenantPolicy("bg", max_pages=0)
    eng.submit([9, 10, 11, 12], max_new_tokens=3, tenant="rt")
    for _ in range(6):
        if bg0.failed or bg1.failed:
            break
        eng.step()
    assert [bg0.failed, bg1.failed].count("retries_exhausted") == 1
    assert eng.retries == 1


def test_brownout_plan_shed_orders_most_overdue_first_and_caps():
    from distributeddeeplearning_tpu.serve.scheduler import (
        BrownoutController)
    sched = SloScheduler([TenantPolicy("rt", ttft_slo_s=0.1)])
    ctrl = BrownoutController(queue_pressure=3, max_shed_per_step=2)
    waiting = [_req(0, "rt", arrival=0.9), _req(1, "rt", arrival=0.2),
               _req(2, "rt", arrival=0.5)]
    # Everything is overdue, but with no pressure NOTHING is shed.
    assert ctrl.plan_shed(now=2.0, waiting=waiting[:2], scheduler=sched,
                          free_pages=10, num_pages=10) == []
    # Pressured: most-overdue first, capped at max_shed_per_step.
    shed = ctrl.plan_shed(now=2.0, waiting=waiting, scheduler=sched,
                          free_pages=10, num_pages=10)
    assert [r.uid for r in shed] == [1, 2]
    # Page pressure alone also arms it; positive slack is never shed.
    ctrl2 = BrownoutController(page_pressure=0.5, queue_pressure=99,
                               shed_slack_s=0.0)
    fresh = _req(3, "rt", arrival=1.99)
    shed = ctrl2.plan_shed(now=2.0, waiting=[waiting[1], fresh],
                           scheduler=sched, free_pages=4, num_pages=10)
    assert [r.uid for r in shed] == [1]


def test_engine_brownout_sheds_on_queue_pressure(chaos_aot):
    from distributeddeeplearning_tpu.serve.scheduler import (
        BrownoutController)
    sched = SloScheduler([TenantPolicy("rt", ttft_slo_s=0.0)])
    eng = _chaos_engine(chaos_aot, scheduler=sched,
                        brownout=BrownoutController(queue_pressure=2,
                                                    max_shed_per_step=2))
    a = eng.submit([1, 2, 3, 4], max_new_tokens=3, tenant="rt")
    b = eng.submit([5, 6, 7, 8], max_new_tokens=3, tenant="rt")
    eng.step()  # depth 2 >= queue_pressure, both already past their SLO
    assert a.failed == "shed" and b.failed == "shed"
    assert eng.sheds == 2 and eng.num_live == 0


def test_anomaly_update_serve_kinds():
    from distributeddeeplearning_tpu.observability import anomaly
    det = anomaly.AnomalyDetector()
    # A healthy engine never trips: steady queue, zero sheds, on-time work.
    for s in range(1, 7):
        assert det.update_serve(s, queue_depth=2, sheds=0,
                                deadline_misses=0, finished=3) == []
    kinds = [a["kind"] for a in det.update_serve(
        7, queue_depth=40, sheds=3, deadline_misses=2, finished=2)]
    assert kinds == ["queue_blowup", "shed_storm", "deadline_miss_rate"]
    # Below-volume misses stay quiet (1 of 100 is not a miss-rate storm).
    assert det.update_serve(8, deadline_misses=1, finished=99) == []


# --- the serve chaos soak: SIGKILL a replica mid-stream ---------------------

@pytest.mark.chaos
def test_serve_chaos_soak_sigkill_replica_token_identical(tmp_path):
    """SIGKILL replica 0 at engine step 3 through the supervised launch
    path: its in-flight requests are re-dispatched with their received
    prefix folded, every completion is token-identical to an uninterrupted
    run, the replacement replica warm-boots from the shared AOT cache, no
    page leaks survive the drain, the flight recorder tells the whole
    story end to end — and the merged Chrome trace links each
    re-dispatched request's spans across BOTH replica processes under
    one flow id (docs/serve_tracing.md)."""
    import dataclasses
    import os

    from distributeddeeplearning_tpu import launch as launchlib
    from distributeddeeplearning_tpu.observability import flight as flightlib
    from tools import postmortem

    cfg = ServeConfig(model="gpt_tiny", vocab_size=VOCAB, max_slots=2,
                      page_size=4, num_pages=32, max_pages_per_slot=8,
                      prefill_buckets=(16,),
                      compile_cache_dir=str(tmp_path / "aot"))
    prompts = [[(7 * i + j) % (VOCAB - 1) + 1 for j in range(4 + i % 3)]
               for i in range(4)]

    # Fault-free reference through one in-process engine. This also
    # compiles into the shared AOT cache, so both replicas (and the warm
    # restart) boot with zero retraces — the soak stays tier-1 cheap.
    ref = Engine(cfg)
    for p in prompts:
        ref.submit(p, max_new_tokens=6)
    ref.run_until_idle()
    expected = {r.uid: list(r.tokens) for r in ref.finished}
    ref.shutdown()
    assert len(expected) == 4

    requests = [{"uid": i, "prompt": prompts[i], "max_new_tokens": 6}
                for i in range(4)]
    flight_dir = str(tmp_path / "flight")
    try:
        out = launchlib.run_serve(
            2, requests, dataclasses.asdict(cfg),
            workdir=str(tmp_path / "serve"),
            heartbeat_dir=str(tmp_path / "hb"),
            max_restarts=1, child_fault_plans={0: "sigkill@3"},
            flight_dir=flight_dir, timeout_s=150.0,
            trace_dir=str(tmp_path / "trace"))
    finally:
        # run_serve exports the flight env for its children; scrub it so
        # later tests see a pristine recorder.
        flightlib.reset()
        os.environ.pop(flightlib.ENV_FLIGHT_DIR, None)
        os.environ.pop(flightlib.ENV_RUN_ID, None)

    # Token identity across the kill: every stream equals the fault-free
    # reference, including the re-dispatched victims.
    for uid, exp in expected.items():
        res = out["results"][uid]
        assert res["finished"] and res["failed"] is None
        assert res["tokens"] == exp, f"request {uid} diverged after replay"
    assert out["restarts"] == 1
    assert out["redispatched"] >= 1
    assert any(out["results"][u]["retries"] for u in expected)
    assert out["leak_check_ok"] is True
    assert out["replica_rcs"] == {0: 0, 1: 0}

    # The incident chain reads end-to-end: lost -> re-dispatched ->
    # token-identical replay -> warm restart -> clean drain.
    chain = " | ".join(postmortem.build_report(flight_dir)["incident"])
    assert "serve replica 0 lost" in chain
    assert "re-dispatched to survivors" in chain
    assert "replayed token-identically" in chain
    assert "restarted warm" in chain
    assert "drained with leak check ok" in chain

    # The kill-replica acceptance pin for the tracing layer: the merged
    # Chrome trace must link a re-dispatched request's spans across both
    # replica processes — one flow id, two pids — and every emitted
    # serve span name must come from the registered schema.
    from distributeddeeplearning_tpu.observability import telemetry
    from distributeddeeplearning_tpu.serve import tracing

    assert out["merged_trace"] and os.path.exists(out["merged_trace"])
    events = telemetry.load_events(out["merged_trace"])
    emitted = {e["name"] for e in events
               if str(e.get("name", "")).startswith("serve:")}
    assert emitted <= set(tracing.REGISTERED_PHASES)
    assert "serve:replica_lost" in emitted  # the supervisor's own track
    flow_pids: dict = {}
    for e in events:
        if e.get("ph") in ("s", "t", "f") and e.get("cat") == "serve":
            flow_pids.setdefault(e["id"], set()).add(e["pid"])
    cross = {fid for fid, pids in flow_pids.items() if len(pids) > 1}
    assert cross, "no flow chain spans both replica pids after the kill"
    # The cross-process flows ARE the re-dispatched victims: each also
    # left a final attribution instant on its second replica.
    att_ids = {e["args"]["trace"] for e in events
               if e.get("name") == "serve:attribution"}
    assert cross <= att_ids


@pytest.mark.slow
@pytest.mark.chaos
def test_bench_serve_chaos_arm_record(tmp_path, monkeypatch, capsys):
    from distributeddeeplearning_tpu.observability import perf_report
    from distributeddeeplearning_tpu.observability import sidecars
    from tools import bench_serve

    monkeypatch.setattr(sidecars, "write",
                        lambda name, payload: str(tmp_path / "s.json"))
    rc = bench_serve.main([
        "--chaos", "--model", "gpt_tiny", "--vocab-size", str(VOCAB),
        "--requests", "4", "--rate", "1000", "--max-new", "6",
        "--prompt-lens", "4,6", "--max-slots", "2", "--page-size", "4",
        "--num-pages", "32", "--max-pages-per-slot", "8",
        "--prefill-buckets", "16",
        "--compile-cache-dir", str(tmp_path / "aot")])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert perf_report.validate(rec) == []
    ch = rec["chaos"]
    assert ch["token_identity_checked"] is True
    assert ch["leak_check_ok"] is True
    assert ch["restarts"] >= 1 and ch["redispatched"] >= 1
    assert ch["tokens_per_sec_per_chip"] > 0
    assert isinstance(ch["recovery_overhead_frac"], float)
