"""fsdp axis: params must actually shard over ``fsdp`` (embed-dim ZeRO-3
style) and training numerics must match the pure-DP run (VERDICT r1 #5 —
"prove fsdp or drop it").

The ``embed -> fsdp`` rule (parallel/sharding.py) shards every kernel's
embedding dimension across the fsdp axis; XLA then all-gathers params where
a full operand is needed and reduce-scatters gradients — the compiler-emitted
equivalent of FSDP's explicit gather/scatter machinery.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokens
from distributeddeeplearning_tpu.models import bert
from distributeddeeplearning_tpu.parallel.mesh import make_mesh
from distributeddeeplearning_tpu.train import optim, steps

VOCAB, SEQ, BATCH = 1024, 32, 8


def build(parallel: ParallelConfig):
    cfg = TrainConfig(
        model="bert_tiny", global_batch_size=BATCH, dtype="float32",
        parallel=parallel,
        data=DataConfig(dataset="mlm", seq_len=SEQ, vocab_size=VOCAB),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  schedule="linear", label_smoothing=0.0))
    mesh = make_mesh(cfg.parallel)
    model = bert.tiny_bert_mlm(vocab_size=VOCAB)
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 100)
    src = SyntheticTokens(BATCH, SEQ, VOCAB, seed=7)
    state, shardings = steps.init_sharded_state(
        model, tx, mesh, cfg, src.batch(0), jax.random.key(0), "tokens")
    step = steps.make_gspmd_train_step(model, tx, mesh, cfg, shardings,
                                       "tokens")
    return src, state, step


@pytest.mark.core
def test_fsdp_params_actually_shard(devices8):
    _, state, _ = build(ParallelConfig(data=2, fsdp=2, model=2))
    qk = state.params["layer0"]["attention"]["query"]["kernel"].value
    # ("embed", "heads") logical axes -> embed over fsdp, heads over model.
    assert qk.sharding.spec == P("fsdp", "model"), qk.sharding
    emb = state.params["word_embeddings"].value
    # ("vocab", "embed") -> vocab-parallel over model, embed over fsdp.
    assert emb.sharding.spec == P("model", "fsdp"), emb.sharding
    mlp_out = state.params["layer0"]["mlp_output"]["kernel"].value
    assert mlp_out.sharding.spec == P("model", "fsdp"), mlp_out.sharding
    # The optimizer state mirrors the param layout (sharded moments).
    mu_qk = state.opt_state[0].mu["layer0"]["attention"]["query"]["kernel"]
    mu_qk = getattr(mu_qk, "value", mu_qk)
    assert mu_qk.sharding.spec == P("fsdp", "model"), mu_qk.sharding


@pytest.mark.core
@pytest.mark.slow
def test_fsdp_matches_dp_numerics(devices8):
    """3 training steps under fsdp=2 == pure dp=8, same seed/batches."""
    losses = {}
    for name, parallel in [("dp", ParallelConfig(data=8)),
                           ("fsdp", ParallelConfig(data=4, fsdp=2))]:
        src, state, step = build(parallel)
        rng = jax.random.key(42)
        out = []
        for i in range(3):
            state, metrics = step(state, src.batch(i), rng)
            out.append(float(metrics["loss"]))
        losses[name] = out
    np.testing.assert_allclose(losses["dp"], losses["fsdp"],
                               rtol=2e-4, atol=2e-5)
