"""ZeRO-1 optimizer-state sharding (parallel/zero.py + the explicit-DP
train step): layout round-trips, collective equivalence, replicated-vs-
sharded trajectory parity, 1/N residency, and cross-degree checkpoint
resume through the gather-on-save canonical format.

Parity tolerances: elementwise optimizers (SGD-momentum, AdamW) are
BITWISE against the replicated path — reduce-scatter hands each shard the
same psum chunk values the all-reduce produced, and every per-element
update is identical math. Norm-based transforms (LAMB's trust ratio,
global-norm clipping) compute ``sqrt(psum(partial sums))``, whose fp
summation ORDER differs from the replicated full-leaf norm by ~1e-7 rel;
one step stays ~1e-6 while longer runs amplify that seed chaotically
through the network (a replicated-vs-replicated control with a 1e-7
perturbation of the clip threshold diverges identically: 6e-8 -> 6e-5 in
two steps), so multi-step LAMB asserts a bounded, not tight, gap."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu import data as datalib
from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.models import model_spec
from distributeddeeplearning_tpu.parallel import zero
from distributeddeeplearning_tpu.train import loop

DATA_AXES = ("data", "fsdp")


def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


def _max_abs_diff(a, b) -> float:
    return max(float(jnp.max(jnp.abs(x - y)))
               for x, y in zip(_leaves(a), _leaves(b)))


# --------------------------------------------------------------------------
# Layout: pure host-side math, no devices.
# --------------------------------------------------------------------------

def _demo_tree():
    k = jax.random.key(0)
    ks = jax.random.split(k, 4)
    return {
        "a": {"kernel": jax.random.normal(ks[0], (3, 3, 7, 5))},  # 315 = 8*39+3
        "b": {"bias": jax.random.normal(ks[1], (13,))},
        "c": {"kernel": jax.random.normal(ks[2], (17, 9))},       # 153
        "d": {"scale": jax.random.normal(ks[3], (16,))},          # exact /8
    }


def test_layout_chunk_sizes_and_padding():
    tree = _demo_tree()
    layout = zero.build_layout(tree, 8)
    flat, _ = jax.tree_util.tree_flatten(tree)
    assert layout.num_leaves == len(flat)
    for i, shape in enumerate(layout.plan.shapes):
        numel = int(np.prod(shape)) if shape else 1
        assert layout.chunk_sizes[i] == -(-numel // 8)
        assert layout.padded_size(i) >= numel
        assert layout.padded_size(i) % 8 == 0
    assert "1/8 per shard" in layout.describe()


def test_to_chunked_roundtrip_exact():
    tree = _demo_tree()
    layout = zero.build_layout(tree, 8)
    chunked = zero.to_chunked(tree, layout)
    # every chunked leaf is flat, padded to a multiple of 8, zero-padded
    for leaf, shape, c in zip(_leaves(chunked), layout.plan.shapes,
                              layout.chunk_sizes):
        numel = int(np.prod(shape)) if shape else 1
        assert leaf.shape == (8 * c,)
        assert float(jnp.abs(leaf[numel:]).max()) == 0.0 if numel < 8 * c \
            else True
    back = zero.from_chunked(chunked, layout)
    assert jax.tree_util.tree_structure(back) == \
        jax.tree_util.tree_structure(tree)
    assert _max_abs_diff(back, tree) == 0.0


def test_chunked_struct_matches_real_chunking():
    tree = _demo_tree()
    layout = zero.build_layout(tree, 8)
    struct = zero.chunked_struct(tree, layout)
    real = zero.to_chunked(tree, layout)
    for s, r in zip(_leaves(struct), _leaves(real)):
        assert s.shape == r.shape and s.dtype == r.dtype


def test_layout_from_options_validates_dtype():
    struct = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), _demo_tree())
    from distributeddeeplearning_tpu.config import AllReduceConfig
    layout, payload = zero.layout_from_options(
        struct, 8, options=AllReduceConfig(bucket_mb=0.001))
    assert payload is None  # float32 payload = no cast
    assert len(layout.plan.buckets) > 1  # tiny bucket forces multiple
    _, bf16 = zero.layout_from_options(
        struct, 8, options=AllReduceConfig(dtype="bfloat16"))
    assert bf16 == jnp.bfloat16


# --------------------------------------------------------------------------
# Collectives on the 8-device mesh.
# --------------------------------------------------------------------------

def _mesh8(devices8):
    from jax.sharding import Mesh
    return Mesh(np.array(devices8).reshape(8, 1), DATA_AXES)


def test_reduce_scatter_equals_allreduce_chunks(devices8):
    """reduce_scatter's shard-k chunk == chunk k of the psum'd padded leaf,
    and all_gather_chunks reassembles exactly the psum tree."""
    from jax.sharding import PartitionSpec as P
    from distributeddeeplearning_tpu import compat

    mesh = _mesh8(devices8)
    tree = _demo_tree()
    # per-shard distinct grads: leaf stacked over a leading device axis
    stacked = jax.tree_util.tree_map(
        lambda x: jax.random.normal(jax.random.key(7), (8,) + x.shape,
                                    x.dtype), tree)
    struct = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), stacked)
    layout = zero.build_layout(struct, 8)

    def f(x):
        local = jax.tree_util.tree_map(lambda a: a[0], x)
        chunks = zero.reduce_scatter(local, layout, DATA_AXES)
        summed = jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, DATA_AXES), local)
        return chunks, zero.all_gather_chunks(chunks, layout, DATA_AXES), \
            summed

    mapped = compat.shard_map(
        f, mesh=mesh, in_specs=(P(DATA_AXES),),
        out_specs=(P(DATA_AXES), P(), P()))
    chunks, gathered, summed = jax.jit(mapped)(stacked)

    # the concatenated global chunk array IS the padded psum'd flat leaf
    expected = zero.to_chunked(summed, layout)
    np.testing.assert_allclose(
        np.concatenate([np.ravel(c) for c in _leaves(chunks)]),
        np.concatenate([np.ravel(e) for e in _leaves(expected)]),
        rtol=1e-6, atol=1e-5)
    # and the gather reassembles the psum tree in original shapes
    assert _max_abs_diff(gathered, summed) < 1e-4  # fp order only


def test_local_chunks_then_gather_is_identity(devices8):
    from jax.sharding import PartitionSpec as P
    from distributeddeeplearning_tpu import compat

    mesh = _mesh8(devices8)
    tree = _demo_tree()
    layout = zero.build_layout(tree, 8)

    def f(x):
        return zero.all_gather_chunks(
            zero.local_chunks(x, layout, DATA_AXES), layout, DATA_AXES)

    mapped = compat.shard_map(f, mesh=mesh, in_specs=(P(),), out_specs=P())
    out = jax.jit(mapped)(tree)
    assert _max_abs_diff(out, tree) == 0.0


# --------------------------------------------------------------------------
# End-to-end trajectory parity on the explicit-DP path.
# --------------------------------------------------------------------------

def _cfg(opt_kw, sharding, **kw):
    base = dict(
        model="resnet18_thin", global_batch_size=16, dtype="float32",
        log_every=10**9, parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=32, num_classes=10),
        optimizer=OptimizerConfig(schedule="constant", **opt_kw),
        optimizer_sharding=sharding)
    base.update(kw)
    return TrainConfig(**base)


def _build(cfg, total_steps=4):
    spec = model_spec(cfg.model)
    mesh, model, batch_shd, state, train_step, sched, rng = loop.build(
        cfg, total_steps)
    source = datalib.make_source(cfg, spec.input_kind, batch_shd,
                                 objective=spec.objective)
    return state, train_step, source, rng


def _run(cfg, steps):
    state, train_step, source, rng = _build(cfg, steps)
    for i in range(steps):
        state, metrics = train_step(state, source.batch(i), rng)
    return state, metrics


def _sharded_opt_leaves(state):
    """(sharded, replicated) opt-state array leaves, by per-device shard."""
    sharded, replicated = [], []
    for leaf in _leaves(state.opt_state):
        if not hasattr(leaf, "addressable_shards"):
            continue
        local = leaf.addressable_shards[0].data.size
        (sharded if local < leaf.size else replicated).append(leaf)
    return sharded, replicated


@pytest.mark.parametrize("opt_kw", [
    dict(name="sgd", learning_rate=0.1, momentum=0.9, weight_decay=1e-4),
    dict(name="adamw", learning_rate=1e-3, weight_decay=0.01),
], ids=["sgd_momentum", "adamw"])
def test_zero1_matches_replicated_bitwise(devices8, opt_kw):
    sa, _ = _run(_cfg(opt_kw, "none"), 3)
    sb, _ = _run(_cfg(opt_kw, "zero1"), 3)
    assert _max_abs_diff(jax.device_get(sa.params),
                         jax.device_get(sb.params)) == 0.0
    sharded, _ = _sharded_opt_leaves(sb)
    assert sharded, "no opt-state leaf is sharded under zero1"
    for leaf in sharded:
        assert leaf.addressable_shards[0].data.size == leaf.size // 8


def test_zero1_matches_replicated_lamb(devices8):
    """LAMB: norm fp order bounds one step at ~1e-6; 3 steps stay bounded
    (chaotic growth of the 1-ulp seed, see module docstring)."""
    cfg_r = _cfg(dict(name="lamb", learning_rate=1e-3, weight_decay=0.01),
                 "none")
    cfg_z = _cfg(dict(name="lamb", learning_rate=1e-3, weight_decay=0.01),
                 "zero1")
    sa, step_r, source, rng_r = _build(cfg_r, 3)
    sb, step_z, _, rng_z = _build(cfg_z, 3)
    for i in range(3):
        sa, _ = step_r(sa, source.batch(i), rng_r)
        sb, _ = step_z(sb, source.batch(i), rng_z)
        if i == 0:
            assert _max_abs_diff(jax.device_get(sa.params),
                                 jax.device_get(sb.params)) < 2e-6
    sa3, sb3 = sa, sb
    assert _max_abs_diff(jax.device_get(sa3.params),
                         jax.device_get(sb3.params)) < 5e-3
    sharded, _ = _sharded_opt_leaves(sb3)
    # Adam carries mu and nu per param leaf: both must live sharded.
    n_params = len(_leaves(sb3.params))
    assert len(sharded) == 2 * n_params
    for leaf in sharded:
        assert leaf.addressable_shards[0].data.size == leaf.size // 8


def test_zero1_rejected_on_gspmd_path(devices8):
    cfg = _cfg(dict(name="sgd", learning_rate=0.1), "zero1",
               parallel=ParallelConfig(data=4, model=2))
    with pytest.raises(ValueError, match="zero1"):
        loop.build(cfg, 2)
    with pytest.raises(ValueError, match="optimizer_sharding"):
        loop.build(_cfg(dict(name="sgd", learning_rate=0.1), "zero9"), 2)


def test_cli_flag_roundtrip():
    import train as train_cli

    cfg = train_cli.build_config(train_cli.parse_args(
        ["--optimizer-sharding", "zero1"]))
    assert cfg.optimizer_sharding == "zero1"
    assert train_cli.build_config(
        train_cli.parse_args([])).optimizer_sharding == "none"


# --------------------------------------------------------------------------
# Checkpoint: gather-on-save canonical layout, cross-degree resume.
# --------------------------------------------------------------------------

def _save_zero1_dp8(tmp_path, steps=2):
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    cfg = _cfg(dict(name="sgd", learning_rate=0.1, momentum=0.9), "zero1")
    state, train_step, source, rng = _build(cfg, steps + 2)
    for i in range(steps):
        state, _ = train_step(state, source.batch(i), rng)
    ckpt = Checkpointer(str(tmp_path / "ckpt"), every_steps=1,
                        converter=train_step.zero_converter)
    assert ckpt.maybe_save(int(state.step), state, force=True)
    ckpt.wait()
    ckpt.close()
    return cfg, state, train_step


def test_cross_degree_resume(devices8, tmp_path):
    """Save under zero1 on 8 shards; restore (a) replicated on dp=8 and
    (b) zero1 on dp=2. Params must be BITWISE the save's params; the
    restored optimizer states must agree in canonical form; and one
    post-resume SGD step from either restore lands on identical params."""
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    cfg8, saved, step8 = _save_zero1_dp8(tmp_path)
    saved_params = jax.device_get(saved.params)
    saved_canon = jax.device_get(
        step8.zero_converter.to_canonical(saved).opt_state)

    # (a) replicated restore, same degree
    cfg_r = _cfg(dict(name="sgd", learning_rate=0.1, momentum=0.9), "none")
    state_r, step_r, source, rng = _build(cfg_r, 6)
    ck_r = Checkpointer(str(tmp_path / "ckpt"), every_steps=1)
    restored_r = ck_r.restore_latest(state_r)
    ck_r.close()
    assert restored_r is not None
    assert _max_abs_diff(jax.device_get(restored_r.params),
                         saved_params) == 0.0
    assert _max_abs_diff(jax.device_get(restored_r.opt_state),
                         saved_canon) == 0.0

    # (b) zero1 restore on a DIFFERENT degree (dp=2 -> 1/2 chunks)
    cfg2 = _cfg(dict(name="sgd", learning_rate=0.1, momentum=0.9), "zero1",
                parallel=ParallelConfig(data=2), global_batch_size=16)
    state_2, step_2, _, rng2 = _build(cfg2, 6)
    ck_2 = Checkpointer(str(tmp_path / "ckpt"), every_steps=1,
                        converter=step_2.zero_converter)
    restored_2 = ck_2.restore_latest(state_2)
    ck_2.close()
    assert restored_2 is not None
    assert _max_abs_diff(jax.device_get(restored_2.params),
                         saved_params) == 0.0
    # opt state re-sharded 1/2: canonical form matches the save exactly
    assert _max_abs_diff(
        jax.device_get(step_2.zero_converter.to_canonical(
            restored_2).opt_state), saved_canon) == 0.0
    sharded, _ = _sharded_opt_leaves(restored_2)
    assert sharded
    for leaf in sharded:
        assert leaf.addressable_shards[0].data.size == leaf.size // 2

    # one post-resume step at dp=2 from each restore: identical params
    # (SGD is elementwise, so replicated and zero1 continuations agree
    # bitwise given identical restored state and batches)
    cfg_r2 = _cfg(dict(name="sgd", learning_rate=0.1, momentum=0.9), "none",
                  parallel=ParallelConfig(data=2))
    state_r2, step_r2, source2, rng_r2 = _build(cfg_r2, 6)
    ck = Checkpointer(str(tmp_path / "ckpt"), every_steps=1)
    restored_r2 = ck.restore_latest(state_r2)
    ck.close()
    # device_copy before stepping: a warm AOT cache serves deserialized
    # executables that donate their inputs unconditionally, and a donating
    # dispatch on orbax-restored buffers both corrupts the arrays this
    # test still reads AND invalidates the restored state itself
    # (train/checkpoint.py device_copy docstring).
    from distributeddeeplearning_tpu.train import checkpoint as ckptlib
    restored_r2 = ckptlib.device_copy(restored_r2)
    restored_2 = ckptlib.device_copy(restored_2)
    batch = source2.batch(2)
    next_r, _ = step_r2(restored_r2, batch, rng_r2)
    next_2, _ = step_2(restored_2, batch, rng2)
    assert int(next_r.step) == int(next_2.step)
    assert _max_abs_diff(jax.device_get(next_r.params),
                         jax.device_get(next_2.params)) == 0.0
