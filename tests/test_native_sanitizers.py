"""Sanitizer builds of the native loader (SURVEY.md §5.2).

The C++ stress driver (csrc/loader_test.cc) exercises the batch-slot ring's
concurrency — worker pool vs. consumer, shutdown while blocked, finite-stream
exhaustion, start_batch resume — with no Python in the process. Here we run
it plain and under ThreadSanitizer; `make asan` is available for manual runs
(ASan's interceptors make it the slowest of the three).
"""

import shutil
import subprocess
from pathlib import Path

import pytest

CSRC = Path(__file__).resolve().parent.parent / "csrc"


def _make(target: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["make", target], cwd=CSRC, capture_output=True, text=True,
        timeout=600)


def _sanitizer_supported(flag: str) -> bool:
    """Probe whether g++ can link the sanitizer runtime on this machine."""
    probe = subprocess.run(
        ["g++", "-x", "c++", "-", f"-fsanitize={flag}", "-o", "/dev/null"],
        input="int main(){return 0;}", capture_output=True, text=True,
        timeout=120)
    return probe.returncode == 0


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_loader_stress_driver():
    proc = _make("test")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ALL OK" in proc.stdout


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_loader_tsan():
    if not _sanitizer_supported("thread"):
        pytest.skip("tsan runtime not available")
    proc = _make("tsan")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ALL OK" in proc.stdout
    assert "WARNING: ThreadSanitizer" not in proc.stdout + proc.stderr
