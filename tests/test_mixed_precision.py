"""Mixed precision + batch ramp (ISSUE 20): PrecisionPolicy resolution,
the dynamic loss-scale automaton, ramp spec validation, ramp-boundary
resume identity, and the mixed-vs-fp32 parity band across the ZeRO ladder.

Parity tolerances: bf16 compute quantizes every activation/gradient to 8
mantissa bits, so mixed-vs-fp32 trajectories diverge from step 1 — the
band is deliberately LOOSE (same loss neighborhood, still learning), not
tight. Mixed-vs-mixed across sharding stages is the tight comparison: the
fp32 masters make the update math identical, and only the bf16 wire
reduction order differs (reduce-scatter chunks vs fused all-reduce), so
sharded and replicated mixed runs must land within a narrow band of each
other.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu import data as datalib
from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, PrecisionPolicy,
    TrainConfig, resolve_precision)
from distributeddeeplearning_tpu.models import model_spec
from distributeddeeplearning_tpu.train import loop, optim


def _cfg(**kw):
    base = dict(
        model="resnet18_thin", global_batch_size=16, dtype="float32",
        log_every=10**9, parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=32, num_classes=10),
        optimizer=OptimizerConfig(schedule="constant"))
    base.update(kw)
    return TrainConfig(**base)


def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


def _max_abs_diff(a, b) -> float:
    return max(float(jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)
                                     - jnp.asarray(y, jnp.float32))))
               for x, y in zip(_leaves(a), _leaves(b)))


# --------------------------------------------------------------------------
# Policy resolution + ramp parsing: pure host-side, no devices.
# --------------------------------------------------------------------------

def test_precision_policy_describe():
    assert PrecisionPolicy.mixed().describe() == "bf16/f32/bf16+dls32768"
    assert PrecisionPolicy.fp32().describe() == "f32/f32/f32"


def test_resolve_precision_derives_legacy_policy():
    """No explicit policy: the legacy --dtype knob maps onto an unscaled
    policy (fp32 masters either way), so every consumer sees ONE shape."""
    pol = resolve_precision(_cfg(dtype="bfloat16"))
    assert (pol.compute_dtype, pol.param_dtype) == ("bfloat16", "float32")
    assert pol.loss_scale == 0.0
    pol32 = resolve_precision(_cfg(dtype="float32"))
    assert pol32.compute_dtype == "float32"


def test_resolve_precision_rejects_sub_fp32_masters():
    """param_dtype below fp32 is the silent-precision-loss bug class the
    master-weight-cast lint exists for — refused at config time."""
    bad = PrecisionPolicy(param_dtype="bfloat16")
    with pytest.raises(ValueError, match="param_dtype"):
        resolve_precision(_cfg(precision=bad))


def test_parse_batch_ramp_good_spec():
    stages = optim.parse_batch_ramp("8:2,16:2,32", final_batch=32,
                                    checkpoint_every=2)
    assert [(s.batch, s.start_step, s.end_step) for s in stages] == [
        (8, 0, 2), (16, 2, 4), (32, 4, None)]


def test_parse_batch_ramp_degenerate_is_none():
    assert optim.parse_batch_ramp(None, final_batch=32,
                                  checkpoint_every=0) is None
    assert optim.parse_batch_ramp("32", final_batch=32,
                                  checkpoint_every=0) is None


@pytest.mark.parametrize("spec,final,every,msg", [
    ("8:2,16", 32, 0, "!= global_batch_size"),
    ("8:3,32", 32, 2, "checkpoint_every"),
    ("32:2,16", 16, 0, "non-decreasing"),
    ("8:2,16:2", 16, 0, "last stage must not"),
    ("8,16", 16, 0, "only the last stage may omit"),
], ids=["final-mismatch", "off-cadence", "shrinking", "counted-last",
        "uncounted-middle"])
def test_parse_batch_ramp_rejects(spec, final, every, msg):
    with pytest.raises(ValueError, match=msg):
        optim.parse_batch_ramp(spec, final_batch=final,
                               checkpoint_every=every)


def test_effective_prefetch_depth_headroom():
    """The floor is config.data.prefetch_depth; an explicit policy doubles
    it; early ramp stages provision for the FINAL batch; depth<=0 opts
    out entirely (ISSUE 20 zero-data-wait headroom)."""
    assert datalib.effective_prefetch_depth(_cfg()) == 2
    assert datalib.effective_prefetch_depth(
        _cfg(precision=PrecisionPolicy.mixed())) == 4
    # Early ramp stage: batch 8 of a final 32 -> ceil(32/8) = 4x.
    early = _cfg(global_batch_size=8, batch_ramp="8:2,16:2,32")
    assert datalib.effective_prefetch_depth(early) == 8
    final = _cfg(global_batch_size=32, batch_ramp="8:2,16:2,32")
    assert datalib.effective_prefetch_depth(final) == 2
    off = _cfg(data=DataConfig(synthetic=True, image_size=32,
                               num_classes=10, prefetch_depth=0))
    assert datalib.effective_prefetch_depth(off) == 0


# --------------------------------------------------------------------------
# Dynamic loss-scale automaton (compiled; 8 fake CPU devices).
# --------------------------------------------------------------------------

def _build(cfg, total_steps=4):
    spec = model_spec(cfg.model)
    mesh, model, batch_shd, state, train_step, sched, rng = loop.build(
        cfg, total_steps)
    source = datalib.make_source(cfg, spec.input_kind, batch_shd,
                                 objective=spec.objective)
    return state, train_step, source, rng


def _snap(state):
    # state buffers are DONATED into the next step, and on the CPU backend
    # np.asarray can alias the device buffer — an explicit copy keeps the
    # snapshot from being rewritten in place when the buffer is reused.
    return jax.tree_util.tree_map(lambda x: np.asarray(x).copy(),
                                  (state.params, state.opt_state))


def test_loss_scale_overflow_skips_halves_recovers(devices8):
    """The automaton end to end: a poisoned backward (nan_grads@2) under
    an armed scale must (a) apply NOTHING — params/opt_state bitwise
    unchanged; (b) report loss_scale_skip=1 with bad_step=0 — a backoff
    is a controlled event, never an anomaly; (c) halve the scale; then
    (d) the next step trains normally at the halved scale."""
    cfg = _cfg(precision=PrecisionPolicy.mixed(), fault_plan="nan_grads@2")
    state, train_step, source, rng = _build(cfg)

    state1, m1 = train_step(state, source.batch(0), rng)
    assert float(m1["loss_scale"]) == 32768.0
    assert float(m1["loss_scale_skip"]) == 0.0
    p1, o1 = _snap(state1)

    state2, m2 = train_step(state1, source.batch(1), rng)  # poisoned
    assert float(m2["loss_scale_skip"]) == 1.0
    assert float(m2["bad_step"]) == 0.0  # NOT an anomaly
    assert float(m2["loss_scale"]) == 16384.0  # halved for the NEXT step
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), y),
        state2.params, p1)
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), y),
        state2.opt_state, o1)

    state3, m3 = train_step(state2, source.batch(2), rng)  # recovers
    assert float(m3["loss_scale_skip"]) == 0.0
    assert float(m3["loss_scale"]) == 16384.0
    assert np.isfinite(float(m3["loss"]))
    assert any(not np.array_equal(a, b)
               for a, b in zip(_leaves(_snap(state3)[0]), _leaves(p1)))


def test_loss_scale_grows_after_good_interval(devices8):
    """growth_interval consecutive good steps double the scale (capped at
    loss_scale_max) — the recovery half of the automaton."""
    pol = PrecisionPolicy(loss_scale=256.0, loss_scale_growth_interval=2,
                          loss_scale_max=1024.0)
    state, train_step, source, rng = _build(_cfg(precision=pol))
    scales = []
    for i in range(5):
        state, m = train_step(state, source.batch(i), rng)
        scales.append(float(m["loss_scale"]))
    # Doubles every 2 good steps, saturating at the cap.
    assert scales == [256.0, 512.0, 512.0, 1024.0, 1024.0]


def test_fp32_policy_has_no_scale_state(devices8):
    """The fp32 arm's TrainState carries loss_scale=None — the pytree is
    IDENTICAL to a pre-policy checkpoint, so old checkpoints restore."""
    state, train_step, source, rng = _build(
        _cfg(precision=PrecisionPolicy.fp32()))
    assert state.loss_scale is None
    _, m = train_step(state, source.batch(0), rng)
    assert "loss_scale" not in m


# --------------------------------------------------------------------------
# Ramp-boundary resume identity + the mixed parity band.
# --------------------------------------------------------------------------

def test_ramp_boundary_resume_bitwise(tmp_path, devices8):
    """A stage transition IS an ordinary checkpoint resume: the ramp run
    chained through save/restore must land bitwise on the in-process
    ramp (state carried across segments without serialization). This is
    the property that lets elastic re-formation and cross-degree resume
    compose with the ramp unchanged."""
    ramp = dict(global_batch_size=32, batch_ramp="16:2,32")
    in_proc = loop.run(_cfg(**ramp), total_steps=4)
    via_ckpt = loop.run(
        _cfg(**ramp, checkpoint_dir=str(tmp_path / "ckpt"),
             checkpoint_every_steps=2), total_steps=4)
    assert (in_proc["final_metrics"]["loss"]
            == via_ckpt["final_metrics"]["loss"])
    for s in (in_proc, via_ckpt):
        assert s["batch_ramp"]["spec"] == "16:2,32"
        assert [st["batch"] for st in s["batch_ramp"]["stages"]] == [16, 32]
        assert s["final_step"] == 4


def test_ramp_is_trajectory_neutral_at_equal_batch(tmp_path, devices8):
    """A ramp whose stages all run the FINAL batch ("32:2,32") must land
    bitwise on the plain unramped run: the segment/boundary machinery
    (per-stage rebuild, save/restore chaining, per-stage LR scaling at
    scale 1) adds nothing to the trajectory — only the batch schedule
    does."""
    plain = loop.run(_cfg(global_batch_size=32), total_steps=4)
    ramped = loop.run(
        _cfg(global_batch_size=32, batch_ramp="32:2,32",
             checkpoint_dir=str(tmp_path / "ckpt"),
             checkpoint_every_steps=2), total_steps=4)
    assert (plain["final_metrics"]["loss"]
            == ramped["final_metrics"]["loss"])
    assert ramped["final_step"] == plain["final_step"] == 4


def test_ramp_summary_stamps_input_pipeline(devices8):
    """data_wait_frac + the effective (deepened) prefetch depth are
    stamped unconditionally — the zero-data-wait claim is measured, not
    asserted (ISSUE 20 satellite: the metric used to vanish whenever a
    step was fast)."""
    summary = loop.run(_cfg(precision=PrecisionPolicy.mixed()),
                       total_steps=3)
    pipe = summary["input_pipeline"]
    assert pipe["prefetch_depth"] == 4  # 2x floor under an explicit policy
    assert 0.0 <= pipe["data_wait_frac"] <= 1.0
    assert pipe["data_wait_s"] >= 0.0


@pytest.mark.parametrize("sharding", ["zero2", "zero3"])
def test_mixed_zero_ladder_parity_band(devices8, sharding):
    """Mixed-vs-mixed across the ZeRO ladder is the TIGHT comparison
    (identical fp32 master update math; only the bf16 wire reduction
    order differs), and mixed-vs-fp32 the LOOSE one (bf16 quantization
    compounds per step but must stay in the same loss neighborhood)."""
    steps = 3
    mixed = dict(precision=PrecisionPolicy.mixed(), dtype="bfloat16")
    s_rep, m_rep, _ = _run(_cfg(**mixed), steps)
    s_shd, m_shd, step_shd = _run(
        _cfg(**mixed, optimizer_sharding=sharding), steps)
    # Params: the bf16 wire-order seed (~1 ulp) amplifies chaotically
    # through BN like the LAMB case in tests/test_zero1.py — bounded, not
    # tight (measured ~5e-2 after 3 steps); the LOSS stays tight.
    assert _max_abs_diff(jax.device_get(s_rep.params),
                         _full_params(s_shd, step_shd)) < 2e-1
    assert abs(float(m_rep["loss"]) - float(m_shd["loss"])) < 5e-2
    # fp32 reference: same data, same seed, full-precision compute.
    _, m_fp32, _ = _run(_cfg(precision=PrecisionPolicy.fp32()), steps)
    for m in (m_rep, m_shd):
        assert np.isfinite(float(m["loss"]))
        assert abs(float(m["loss"]) - float(m_fp32["loss"])) < 0.5


def _full_params(state, train_step):
    """Replicated full-shape params regardless of stage (zero3 states hold
    1/N chunks; the converter gathers them)."""
    conv = getattr(train_step, "zero_converter", None)
    if conv is not None:
        state = conv.full_params_state(state)
    return jax.device_get(state.params)


def _run(cfg, steps):
    state, train_step, source, rng = _build(cfg, steps)
    metrics = None
    for i in range(steps):
        state, metrics = train_step(state, source.batch(i), rng)
    return state, metrics, train_step
