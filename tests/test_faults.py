"""Chaos harness (robustness/faults.py + hardened recovery, SURVEY.md §5.3).

Fast tier: the fault-plan grammar, the fail_at_step shim, checkpoint
corruption mechanics, the loader watchdog, and the launcher's backoff /
restart-budget / attribution logic — all unit-level, no XLA compiles.

Slow tier: the compiled bad-step guard (NaN grads skip the update), the
consecutive-bad-step abort, corrupt-checkpoint quarantine + fallback, the
forced preemption save on an already-saved step, and the capstone chaos
soak — kill + corrupted checkpoint + NaN step through ``run_with_restarts``
ending BITWISE-identical to a fault-free run.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from distributeddeeplearning_tpu import launch
from distributeddeeplearning_tpu.observability import health
from distributeddeeplearning_tpu.robustness import faults


# ---------------------------------------------------------------------------
# Plan grammar + resolution
# ---------------------------------------------------------------------------

@pytest.mark.core
def test_parse_plan_grammar():
    plan = faults.parse_plan(
        "sigkill@6, corrupt_latest_ckpt@6,nan_grads@5,"
        "loader_stall@4:2.5s,crash@3:always,sigterm@7:a1")
    kinds = [(f.kind, f.step) for f in plan]
    assert kinds == [("sigkill", 6), ("corrupt_latest_ckpt", 6),
                     ("nan_grads", 5), ("loader_stall", 4),
                     ("crash", 3), ("sigterm", 7)]
    assert plan[3].seconds == 2.5
    assert plan[4].attempt == faults.ALWAYS
    assert plan[5].attempt == 1
    assert plan[0].attempt == 0  # default: first attempt only


@pytest.mark.core
@pytest.mark.parametrize("bad", [
    "explode@3",          # unknown kind
    "sigkill",            # no @step
    "sigkill@x",          # non-integer step
    "sigkill@0",          # non-positive step
    "sigkill@3:b2",       # unknown qualifier
    "loader_stall@3:-1s",  # negative stall
])
def test_parse_plan_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_plan(bad)


class _Cfg:
    """Duck-typed config stub for resolve()."""

    def __init__(self, fault_plan=None, fail_at_step=None):
        self.fault_plan = fault_plan
        self.fail_at_step = fail_at_step


@pytest.mark.core
def test_resolve_merges_and_scopes_by_attempt(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    plan = faults.resolve(_Cfg(fault_plan="nan_grads@5,sigterm@7:a1",
                               fail_at_step=3))
    kinds = {(f.kind, f.step) for f in plan.faults}
    # attempt-0 process: the a1 sigterm is filtered out; the fail_at_step
    # shim (crash@3:always) is in.
    assert kinds == {("nan_grads", 5), ("crash", 3)}
    assert plan.nan_grad_steps() == (4,)  # state.step space: N-1

    monkeypatch.setenv(faults.ENV_ATTEMPT, "1")
    plan1 = faults.resolve(_Cfg(fault_plan="nan_grads@5,sigterm@7:a1",
                                fail_at_step=3))
    kinds1 = {(f.kind, f.step) for f in plan1.faults}
    assert kinds1 == {("sigterm", 7), ("crash", 3)}  # shim is ALWAYS

    # Per-child env plan (launcher --child-fault-plan) merges in too.
    monkeypatch.setenv(faults.ENV_ATTEMPT, "0")
    monkeypatch.setenv(faults.ENV_PLAN, "sigkill@9")
    planv = faults.resolve(_Cfg())
    assert [(f.kind, f.step) for f in planv.faults] == [("sigkill", 9)]


@pytest.mark.core
def test_plan_validate(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    plan = faults.resolve(_Cfg(fault_plan="sigkill@20"))
    with pytest.raises(ValueError, match="would never fire"):
        plan.validate(10)
    plan.validate(20)
    plan2 = faults.resolve(_Cfg(fault_plan="corrupt_latest_ckpt@2"))
    with pytest.raises(ValueError, match="checkpoint_dir"):
        plan2.validate(10, checkpoint_dir=None)
    plan2.validate(10, checkpoint_dir="/tmp/x")


@pytest.mark.core
def test_corrupt_latest_checkpoint(tmp_path):
    # Fake orbax layout: steps 2 and 4, commit markers + payload files.
    for step in (2, 4):
        d = tmp_path / str(step) / "default"
        d.mkdir(parents=True)
        (d / "array.bin").write_bytes(b"A" * 64)
        (tmp_path / str(step) / "_CHECKPOINT_METADATA").write_bytes(b"meta")
    hit = faults.corrupt_latest_checkpoint(str(tmp_path))
    assert hit == 4
    assert (tmp_path / "4" / "default" / "array.bin").read_bytes() == \
        b"\x00DDL_FAULT_CORRUPTED\x00"
    # Commit marker intact: the step still LOOKS restorable (that's the
    # point — restore must discover the damage, not the step listing).
    assert (tmp_path / "4" / "_CHECKPOINT_METADATA").read_bytes() == b"meta"
    # Older step untouched.
    assert (tmp_path / "2" / "default" / "array.bin").read_bytes() == b"A" * 64
    assert faults.corrupt_latest_checkpoint(str(tmp_path / "nope")) is None


# ---------------------------------------------------------------------------
# Loader watchdog (StreamSource)
# ---------------------------------------------------------------------------

def _sharding1():
    import jax

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
    return jax.sharding.NamedSharding(mesh,
                                      jax.sharding.PartitionSpec("data"))


def test_watchdog_delivers_then_catches_stall(capsys):
    from distributeddeeplearning_tpu.data.imagenet import StreamSource

    def it():
        yield {"x": np.ones((2, 3), np.float32)}
        time.sleep(60)  # a wedged pipeline

    src = StreamSource(it(), _sharding1(), lookahead=False,
                       timeout_s=0.2, max_retries=1)
    b0 = src.batch(0)
    assert np.asarray(b0["x"]).shape == (2, 3)
    with pytest.raises(RuntimeError, match="data loader stalled"):
        src.batch(1)
    err = capsys.readouterr().err
    assert "data watchdog" in err  # per-timeout warning before the raise


def test_watchdog_propagates_producer_error_and_exhaustion():
    from distributeddeeplearning_tpu.data.imagenet import StreamSource

    def boom():
        yield {"x": np.zeros((1, 2), np.float32)}
        raise ValueError("decode failed")

    src = StreamSource(boom(), _sharding1(), lookahead=False,
                       timeout_s=5.0, max_retries=0)
    src.batch(0)
    with pytest.raises(ValueError, match="decode failed"):
        src.batch(1)

    def finite():
        yield {"x": np.zeros((1, 2), np.float32)}

    src2 = StreamSource(finite(), _sharding1(), lookahead=False,
                        timeout_s=5.0, max_retries=0)
    src2.batch(0)
    with pytest.raises(StopIteration):
        src2.batch(1)


def test_loader_stall_injection_delays_target_batch():
    from distributeddeeplearning_tpu.data.imagenet import StreamSource

    def it():
        while True:
            yield {"x": np.zeros((1, 2), np.float32)}

    src = StreamSource(it(), _sharding1(), lookahead=False,
                       stall_steps={1: 0.3})
    t0 = time.monotonic()
    src.batch(0)
    fast = time.monotonic() - t0
    t1 = time.monotonic()
    src.batch(1)  # the stalled one
    stalled = time.monotonic() - t1
    assert stalled >= 0.3 > fast


@pytest.mark.core
def test_stream_guard_kwargs_default_empty(monkeypatch):
    """No watchdog config + no plan => StreamSource gets ZERO extra kwargs
    (the hot path carries no fault machinery)."""
    from distributeddeeplearning_tpu.config import TrainConfig

    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)

    assert faults.stream_guard_kwargs(TrainConfig()) == {}
    cfg = TrainConfig(fault_plan="loader_stall@3:0.1s")
    kw = faults.stream_guard_kwargs(cfg, train=True)
    assert kw == {"stall_steps": {3: 0.1}}
    # Eval sources never get train-stream stall injection.
    assert faults.stream_guard_kwargs(cfg, train=False) == {}


# ---------------------------------------------------------------------------
# Launcher hardening
# ---------------------------------------------------------------------------

@pytest.mark.core
def test_backoff_delay_deterministic_and_capped():
    a = launch._backoff_delay(1, 3.0, 60.0)
    assert a == launch._backoff_delay(1, 3.0, 60.0)  # deterministic
    b = launch._backoff_delay(2, 3.0, 60.0)
    assert 3.0 <= a <= 3.75 and b > a
    assert launch._backoff_delay(10, 3.0, 60.0) == 60.0  # capped


@pytest.mark.core
def test_run_with_restarts_exports_attempt_and_backs_off(monkeypatch):
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    sleeps, attempts = [], []

    def run_once():
        attempts.append(os.environ[faults.ENV_ATTEMPT])
        return 1 if len(attempts) < 3 else 0

    rc = launch.run_with_restarts(run_once, 5, backoff_s=1.0,
                                  backoff_cap_s=10.0, sleep=sleeps.append)
    assert rc == 0
    assert attempts == ["0", "1", "2"]
    assert sleeps == [launch._backoff_delay(1, 1.0, 10.0),
                      launch._backoff_delay(2, 1.0, 10.0)]
    assert faults.ENV_ATTEMPT not in os.environ  # restored on exit


@pytest.mark.core
@pytest.mark.parametrize("stop_rc", [130, 143, -15])
def test_run_with_restarts_operator_stop_never_retries(stop_rc, capsys):
    calls = []

    def run_once():
        calls.append(1)
        return stop_rc

    rc = launch.run_with_restarts(run_once, 5, sleep=lambda s: None)
    assert rc == stop_rc
    assert len(calls) == 1
    assert "operator stop" in capsys.readouterr().err


@pytest.mark.core
def test_restart_budget_refills_on_progress_and_stops_crash_loops(capsys):
    # Progressing job: budget 1, but every failure lands AFTER a new
    # checkpoint step — the budget refills and the job eventually finishes.
    state = {"calls": 0}

    def run_once():
        state["calls"] += 1
        return 1 if state["calls"] < 6 else 0

    rc = launch.run_with_restarts(run_once, 1,
                                  progress_fn=lambda: state["calls"],
                                  sleep=lambda s: None)
    assert rc == 0 and state["calls"] == 6
    assert "restart budget refilled" in capsys.readouterr().err

    # Crash loop: no progress ever — budget 1 allows exactly one restart.
    loops = []

    def crash_loop():
        loops.append(1)
        return 1

    rc = launch.run_with_restarts(crash_loop, 1, progress_fn=lambda: None,
                                  sleep=lambda s: None)
    assert rc == 1 and len(loops) == 2
    assert "crash loop, giving up" in capsys.readouterr().err


def _spawn_py(code: str) -> subprocess.Popen:
    return subprocess.Popen([sys.executable, "-c", code])


@pytest.mark.core
def test_monitor_attributes_failed_child(capsys):
    slow = _spawn_py("import time; time.sleep(60)")
    bad = _spawn_py("import sys; sys.exit(7)")
    rc = launch.monitor([slow, bad], poll_interval_s=0.05, grace_s=5.0)
    assert rc == 7
    err = capsys.readouterr().err
    assert "child 1 exited rc=7" in err
    assert "terminating 1 surviving" in err


@pytest.mark.core
def test_monitor_attributes_signal_death(capsys):
    victim = _spawn_py("import os, signal; os.kill(os.getpid(), "
                       "signal.SIGKILL)")
    rc = launch.monitor([victim], poll_interval_s=0.05, grace_s=5.0)
    assert rc == -9
    assert "child 0 exited rc=-9 (killed by signal 9)" in \
        capsys.readouterr().err


@pytest.mark.core
def test_checkpoint_dir_from_command():
    f = launch._checkpoint_dir_from_command
    assert f(["train.py", "--checkpoint-dir", "/tmp/c"]) == "/tmp/c"
    assert f(["train.py", "--checkpoint-dir=/tmp/c"]) == "/tmp/c"
    assert f(["train.py", "--steps", "5"]) is None


@pytest.mark.core
def test_latest_ckpt_step(tmp_path):
    assert launch._latest_ckpt_step(str(tmp_path)) is None
    (tmp_path / "2").mkdir()
    (tmp_path / "10").mkdir()
    (tmp_path / "corrupt.12").mkdir()  # quarantined: not progress
    (tmp_path / "stream_meta.json").write_text("{}")
    assert launch._latest_ckpt_step(str(tmp_path)) == 10
    assert launch._latest_ckpt_step(str(tmp_path / "missing")) is None


@pytest.mark.core
def test_bench_chaos_rejects_bad_fail_step(capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench

    rc = bench.main(["--chaos", "--chaos-steps", "8",
                     "--chaos-fail-at", "8"])
    assert rc == 0  # harness contract: parseable record + rc 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "chaos_recovery_overhead"
    assert rec["value"] is None and "chaos-fail-at" in rec["error"]


# ---------------------------------------------------------------------------
# Elastic membership (launch.py --elastic)
# ---------------------------------------------------------------------------

@pytest.mark.core
def test_parse_plan_elastic_kinds():
    plan = faults.parse_plan("host_lost@4,host_rejoin@8:a1,host_lost@2:always")
    assert [(f.kind, f.step) for f in plan] == [
        ("host_lost", 4), ("host_rejoin", 8), ("host_lost", 2)]
    assert plan[0].attempt == 0          # default: first attempt only
    assert plan[1].attempt == 1          # fires on the SHRUNKEN attempt
    assert plan[2].attempt == faults.ALWAYS
    with pytest.raises(ValueError):
        faults.parse_plan("host_lost@0")
    # Neither kind needs a checkpoint dir to validate.
    faults.FaultPlan(tuple(plan)).validate(10, checkpoint_dir=None)


@pytest.mark.core
def test_attribute_failure_partition(tmp_path):
    hb = str(tmp_path)
    # Watchdog verdict dominates: the process was killed WHILE alive.
    assert launch.attribute_failure(hb, 0, hung=True, ever_beat=True) == \
        "hung"
    # Beat once, file gone with the process: the host took its filesystem
    # presence with it.
    assert launch.attribute_failure(hb, 0, ever_beat=True) == "host_lost"
    # Beat once, file still there: transient crash, host is fine.
    (tmp_path / "heartbeat.1").write_text("{}")
    assert launch.attribute_failure(hb, 1, ever_beat=True) == "crash"
    # Never armed / never beat: no evidence, default to crash.
    assert launch.attribute_failure(hb, 2, ever_beat=False) == "crash"
    assert launch.attribute_failure(None, 0, ever_beat=True) == "crash"


@pytest.mark.core
def test_with_flag_value():
    f = launch._with_flag_value
    assert f(["train.py", "--dp", "4", "--steps", "8"], "--dp", "2") == \
        ["train.py", "--dp", "2", "--steps", "8"]
    assert f(["train.py", "--dp=4"], "--dp", "2") == ["train.py", "--dp=2"]
    assert f(["train.py", "--steps", "8"], "--dp", "2") == \
        ["train.py", "--steps", "8", "--dp", "2"]


@pytest.mark.core
def test_elastic_controller_shrink_remaps_slots(tmp_path):
    hb = str(tmp_path / "hb")
    os.makedirs(hb)
    with pytest.raises(ValueError, match="divide evenly"):
        launch.ElasticController(3, hb, base_dp=8)
    ctl = launch.ElasticController(4, hb, base_dp=8)
    assert (ctl.num_processes, ctl.degree) == (4, 8)
    base = {0: {"X": "h0"}, 2: {"X": "h2"}}
    env0 = ctl.child_env(base)
    assert env0[2]["X"] == "h2" and health.ENV_ELASTIC_EVENT not in env0[0]

    # Host 2 dies taking its heartbeat with it (slot 2 == host 2 here).
    assert ctl.note_failure(2, -9, ever_beat=True) == "host_lost"
    assert ctl.live == [0, 1, 3] and ctl.degree == 6
    event = ctl.take_reconfiguration()
    assert (event["trigger"], event["degree_before"],
            event["degree_after"]) == ("host_lost", 8, 6)
    assert ctl.take_reconfiguration() is None  # consumed

    # Re-formed attempt: --dp rewritten, fault plans follow the ORIGINAL
    # host id (host 2's env died with it; host 3 now sits in slot 2), and
    # every slot carries the membership event — exactly once.
    assert ctl.command(["train.py", "--dp", "8"]) == ["train.py", "--dp", "6"]
    env1 = ctl.child_env(base)
    assert set(env1) == {0, 1, 2}
    assert env1[0]["X"] == "h0" and "X" not in env1[2]
    for slot in env1:
        evt = json.loads(env1[slot][health.ENV_ELASTIC_EVENT])
        assert evt["trigger"] == "host_lost" and "detect_t" in evt
    env2 = ctl.child_env(base)
    assert health.ENV_ELASTIC_EVENT not in env2[0]


@pytest.mark.core
def test_elastic_controller_rejoin_grows_back(tmp_path):
    hb = str(tmp_path / "hb")
    os.makedirs(hb)
    ctl = launch.ElasticController(2, hb, base_dp=4)
    # Rejoin marker with nobody missing: consumed, ignored.
    health.announce_rejoin(hb)
    assert ctl.poll_rejoin() is False
    assert ctl.poll_rejoin() is False  # marker actually consumed

    ctl.note_failure(1, -9, ever_beat=True)
    assert ctl.degree == 2
    assert ctl.take_reconfiguration()["trigger"] == "host_lost"
    health.announce_rejoin(hb)
    assert ctl.poll_rejoin() is True
    assert ctl.live == [0, 1] and ctl.degree == 4
    event = ctl.take_reconfiguration()
    assert (event["trigger"], event["degree_before"],
            event["degree_after"]) == ("host_rejoin", 2, 4)
    assert ctl.events and len(ctl.events) == 2


@pytest.mark.core
def test_elastic_controller_min_hosts_gives_up(tmp_path, capsys):
    hb = str(tmp_path / "hb")
    os.makedirs(hb)
    ctl = launch.ElasticController(2, hb, base_dp=4, min_hosts=2)
    ctl.note_failure(0, -9, ever_beat=True)
    assert ctl.take_reconfiguration() is None
    assert "cannot re-form, giving up" in capsys.readouterr().err


@pytest.mark.core
def test_run_with_restarts_reconfiguration_skips_backoff(capsys):
    """The satellite contract, pinned on the delay schedule: a planned
    re-formation relaunches with NO backoff sleep and NO restart-budget
    charge, while an ordinary crash in the same job still backs off."""
    class _Stub:
        def __init__(self):
            self.queue = [
                {"trigger": "host_lost", "degree_before": 4,
                 "degree_after": 2}, None, None]

        def take_reconfiguration(self):
            return self.queue.pop(0)

    sleeps, calls = [], []

    def run_once():
        calls.append(1)
        # attempt 0: host loss; attempt 1: plain crash; attempt 2: done.
        return 1 if len(calls) < 3 else 0

    rc = launch.run_with_restarts(run_once, 1, backoff_s=1.0,
                                  backoff_cap_s=10.0, sleep=sleeps.append,
                                  elastic=_Stub())
    assert rc == 0 and len(calls) == 3
    # Exactly ONE backoff (the crash); the re-formation slept zero. And the
    # budget of 1 survived because the re-formation never charged it.
    assert sleeps == [launch._backoff_delay(1, 1.0, 10.0)]
    err = capsys.readouterr().err
    assert "elastic re-formation (host_lost): degree 4 -> 2" in err
    assert "no backoff, budget untouched" in err


@pytest.mark.core
def test_run_with_restarts_ctrl_c_beats_reconfiguration():
    """^C stops the job even with a re-formation pending — the operator
    always outranks the controller."""
    class _Stub:
        def take_reconfiguration(self):  # pragma: no cover - must not run
            raise AssertionError("consulted elastic controller on rc=130")

    rc = launch.run_with_restarts(lambda: 130, 5, sleep=lambda s: None,
                                  elastic=_Stub())
    assert rc == 130


# ---------------------------------------------------------------------------
# Compiled bad-step guard + recovery (slow tier: XLA compiles, subprocesses)
# ---------------------------------------------------------------------------

def _cfg(**kw):
    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)

    base = dict(
        model="resnet18_thin", global_batch_size=16, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=8),
        data=DataConfig(synthetic=True, image_size=32, num_classes=10),
        optimizer=OptimizerConfig(schedule="constant"))
    base.update(kw)
    return TrainConfig(**base)


def _assert_trees_equal(a, b):
    import jax

    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


@pytest.mark.slow
@pytest.mark.parametrize("sharding", ["none", "zero1"])
def test_nan_step_skips_update_exactly(sharding):
    """nan_grads@2 poisons the update 1->2: the step must apply NOTHING
    (params/opt_state bitwise unchanged), flag bad_step=1, and keep the
    step counter advancing. zero1 exercises the cross-shard psum of the
    bad flag (shard-local grad chunks must agree on skipping)."""
    import jax

    from distributeddeeplearning_tpu import data as datalib
    from distributeddeeplearning_tpu.models import model_spec
    from distributeddeeplearning_tpu.train import loop

    cfg = _cfg(fault_plan="nan_grads@2", optimizer_sharding=sharding)
    spec = model_spec(cfg.model)
    mesh, model, batch_shd, state0, train_step, sched, rng = loop.build(
        cfg, 3)
    source = datalib.make_source(cfg, spec.input_kind, batch_shd,
                                 objective=spec.objective)

    def snap(state):  # state buffers are DONATED into the next step
        return jax.tree_util.tree_map(np.asarray,
                                      (state.params, state.opt_state))

    p0, _ = snap(state0)
    state1, m1 = train_step(state0, source.batch(0), rng)
    assert float(m1["bad_step"]) == 0.0
    p1, o1 = snap(state1)
    assert not np.array_equal(jax.tree_util.tree_leaves(p1)[0],
                              jax.tree_util.tree_leaves(p0)[0])
    step1 = int(state1.step)
    state2, m2 = train_step(state1, source.batch(1), rng)  # poisoned update
    assert float(m2["bad_step"]) == 1.0
    _assert_trees_equal(state2.params, p1)
    _assert_trees_equal(state2.opt_state, o1)
    assert int(state2.step) == step1 + 1  # counter still advances
    state3, m3 = train_step(state2, source.batch(2), rng)  # recovers
    assert float(m3["bad_step"]) == 0.0
    assert np.isfinite(float(m3["loss"]))


@pytest.mark.slow
def test_consecutive_bad_steps_abort():
    from distributeddeeplearning_tpu.train import loop

    cfg = _cfg(fault_plan="nan_grads@2,nan_grads@3", bad_step_limit=2)
    with pytest.raises(RuntimeError, match="consecutive non-finite"):
        loop.run(cfg, total_steps=6)


@pytest.mark.slow
def test_bad_steps_counted_in_summary():
    from distributeddeeplearning_tpu.train import loop

    summary = loop.run(_cfg(fault_plan="nan_grads@3"), total_steps=5)
    assert summary["bad_steps"] == 1
    assert np.isfinite(summary["final_metrics"]["loss"])


@pytest.mark.slow
def test_corrupt_checkpoint_quarantined_then_fallback(tmp_path):
    """Restore hits a damaged latest step: quarantine (rename to
    corrupt.<step>), fall back to the previous good step, resume there."""
    from distributeddeeplearning_tpu.train import loop

    ckpt = str(tmp_path / "ckpt")
    cfg = _cfg(checkpoint_dir=ckpt, checkpoint_every_steps=2)
    s1 = loop.run(cfg, total_steps=4)
    assert s1["final_step"] == 4
    assert faults.corrupt_latest_checkpoint(ckpt) == 4

    with pytest.warns(UserWarning, match="quarantin"):
        s2 = loop.run(cfg, total_steps=6)
    assert s2["start_step"] == 2, s2  # fell back past the damaged step 4
    assert s2["final_step"] == 6
    assert (tmp_path / "ckpt" / "corrupt.4").exists()


def _train_cmd(ckpt: str, steps: int, extra=()):
    return [sys.executable, "train.py", "--backend", "cpu", "--model",
            "resnet18_thin", "--image-size", "32", "--batch-size", "8",
            "--dp", "1", "--synthetic", "--dtype", "float32", "--steps",
            str(steps), "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
            "--log-every", "1000000", *extra]


def _clean_env():
    return {k: v for k, v in os.environ.items()
            if k not in ("PALLAS_AXON_POOL_IPS", faults.ENV_PLAN,
                         faults.ENV_ATTEMPT)}


def _summary_of(proc):
    lines = [ln for ln in proc.stdout.splitlines() if "summary" in ln]
    assert lines, (proc.returncode, proc.stderr[-2000:])
    return json.loads(lines[-1])["summary"]


@pytest.mark.slow
def test_sigterm_on_cadence_step_saves_and_resumes(tmp_path):
    """sigterm@4 lands right after the CADENCE save of step 4 already
    launched: the preemption path's forced save must short-circuit on the
    already-saved step (no duplicate-save crash), exit reporting a usable
    checkpoint, and the resume must land on exactly step 4."""
    ckpt = str(tmp_path / "ckpt")
    env = _clean_env()
    crash = subprocess.run(
        _train_cmd(ckpt, 8, ("--fault-plan", "sigterm@4")),
        capture_output=True, text=True, timeout=600, env=env)
    assert crash.returncode != 0
    assert "fault injection: SIGTERM" in crash.stderr
    assert "preempted (signal 15): checkpoint saved at step 4" in crash.stderr

    resume = subprocess.run(_train_cmd(ckpt, 8), capture_output=True,
                            text=True, timeout=600, env=env)
    assert resume.returncode == 0, resume.stderr[-2000:]
    s = _summary_of(resume)
    assert s["start_step"] == 4 and s["final_step"] == 8


@pytest.mark.slow
def test_chaos_soak_bitwise_identical_recovery(tmp_path):
    """The capstone: NaN step + corrupted checkpoint + SIGKILL in ONE run
    under run_with_restarts. Attempt 0 skips poisoned step 5, saves a
    diverged step-6 checkpoint, has it corrupted, dies by SIGKILL; the
    restart quarantines corrupt step 6, falls back to the clean step-4
    save, and replays 5..10 fault-free (attempt scoping) — so the final
    step-10 params must be BITWISE identical to a never-faulted run's."""
    ref_ckpt = str(tmp_path / "ref")
    chaos_ckpt = str(tmp_path / "chaos")
    env = _clean_env()

    ref = subprocess.run(_train_cmd(ref_ckpt, 10), capture_output=True,
                         text=True, timeout=600, env=env)
    assert ref.returncode == 0, ref.stderr[-2000:]

    plan = "nan_grads@5,corrupt_latest_ckpt@6,sigkill@6"
    proc = subprocess.run(
        [sys.executable, "launch.py", "--num-processes", "1",
         "--max-restarts", "2", "--backoff", "0.2", "--"]
        + _train_cmd(chaos_ckpt, 10, ("--fault-plan", plan)),
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    # Attempt 0's faults all fired and were attributed...
    assert "fault injection: corrupted checkpoint step 6" in proc.stderr
    assert "child 0 exited rc=-9 (killed by signal 9)" in proc.stderr
    assert "restart 1/2" in proc.stderr
    # ...and the restart quarantined the damaged step and fell back.
    assert (tmp_path / "chaos" / "corrupt.6").exists()
    s = _summary_of(proc)
    assert s["start_step"] == 4, s  # clean step-4 save, not corrupt 6
    assert s["final_step"] == 10

    # Bitwise identity of the final step-10 params: recovery fully erased
    # the kill, the corruption, AND the NaN step (its divergence lived only
    # in the quarantined checkpoint).
    import orbax.checkpoint as ocp

    def params_at(directory, step):
        with ocp.CheckpointManager(directory) as mgr:
            tree = mgr.restore(step, args=ocp.args.StandardRestore())
        return tree["params"]

    _assert_trees_equal(params_at(ref_ckpt, 10), params_at(chaos_ckpt, 10))
